"""Hierarchical multi-slice collectives: ICI x DCN (ISSUE 10).

Everything in ``comm/`` below this module assumes a single ICI slice.
This module is the multi-slice layer — the TPU rendering of the
reference's ``CommScope`` intra/inter-node split (``DistributedAttrDefs
.td:45``; SURVEY.md section 7 "Inter-slice (DCN)"): collectives on a 2D
``(outer x inner)`` mesh run the existing Pallas ring kernels WITHIN each
slice (ICI — device-initiated remote DMA) and XLA collectives ACROSS
slices (DCN — remote DMA is ICI-only, so cross-slice traffic must ride
XLA's wire), composed so the slow wire carries the minimum payload:

- **AllGather**   = intra-slice ring, then inter-slice broadcast of the
  slice blocks (``lax.all_gather`` over the outer axis).
- **ReduceScatter** = intra-slice ring reduce, then inter-slice reduce of
  the 1/n_in partials (``psum_scatter`` over the outer axis).
- **AllReduce**   = RS ∘ AG: intra RS ring -> inter-slice reduce of the
  1/n_in partial -> intra AG ring.  The DCN hop carries **1/n_in of the
  payload per chip** — the bound ``bench.py hier`` claims-gates.
- **EP all-to-all** = a two-phase scheduled exchange: the DCN phase
  (tokens bound for other slices, ``lax.all_to_all`` over the outer
  axis) launches FIRST so the slow wire saturates early, then the
  intra-slice Pallas push kernel runs with a topology-derived
  farthest-first chunk emission order pipelining underneath — the FAST
  chunk-schedule shape (arXiv:2505.09764), with the congestion argument
  of the lightweight-NoC-collective line (arXiv:2603.26438): keep the
  bottleneck wire busy, order the fast wire's chunks longest-path-first.

The schedule's topology model is the measured ``tools.calibrate
.LinkCalibration`` (per-wire-class bandwidth/latency + persisted slice
topology); cold start falls back to the documented chip-table numbers,
so behavior without a calibration run is deterministic.

DCN payloads compose with the PR-9 ``wire_dtype`` codecs
(``lang.quant``): ``wire_dtype="auto"`` quantizes the INTER-SLICE hop
(and only it — the ICI level keeps the model dtype) exactly when
``tools.calibrate.codec_pays("dcn")`` says the halved payload beats the
codec cost, which with cold-start numbers reproduces the measured
BENCH-r04 policy (codec pays on DCN, not on the ICI torus).

Record-mode protocol models: the DCN hop is an XLA collective in
production, but its ordering/credit contract — every slice block landed
before phase 2 consumes it — is part of the two-level protocol.
``dcn_broadcast_model`` / ``dcn_reduce_model`` express that contract in
the ``lang.primitives`` vocabulary so the static verifier, the fault
matrix (including the dropped-inter-slice-credit class), and the
watchdog's pending-wait diagnosis cover the composition at the
{2x2, 2x4, 4x2} slice layouts (``analysis.registry._hier_cases``).
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core import compilation
from ..lang import primitives as dl

# ---------------------------------------------------------------------------
# topology + schedule policy


def slice_axes(mesh: Mesh) -> tuple[str, str] | None:
    """(inner_axis, outer_axis) of a hierarchical mesh: the outermost
    DCN-class axis (size > 1) and the innermost ICI-class axis; None when
    the mesh has no multi-slice axis (single-slice — use the flat
    entries)."""
    from ..core import mesh as mesh_lib

    outer = None
    for name in mesh.axis_names:
        if mesh.shape[name] > 1 and mesh_lib.wire_class(mesh, name) == "dcn":
            outer = name
            break
    if outer is None:
        return None
    for name in reversed(mesh.axis_names):
        if name != outer and mesh_lib.wire_class(mesh, name) == "ici":
            return name, outer
    return None


def ici_schedule(n: int) -> tuple[int, ...]:
    """Intra-slice peer-offset emission order: farthest-first on the ring
    (longest-path chunks launch first and pipeline under near-neighbor
    traffic), self (offset 0 — no wire) last.  Deterministic, identical
    on every rank (each rank applies it to its own rotation), so ranks
    never diverge on the schedule."""
    if n <= 1:
        return (0,) * n
    offs = sorted(range(1, n), key=lambda o: (-min(o, n - o), -o))
    return (*offs, 0)


def chunk_schedule(n_out: int, n_in: int, cal=None) -> tuple[tuple[int, int], ...]:
    """Global chunk-group emission order on an (n_out x n_in) topology:
    ``(slice_offset, inner_offset)`` pairs, every group on the SLOWER
    wire class before any on the faster one (the FAST rule: saturate the
    bottleneck wire first), farthest-first within each class, the
    self-group (0, 0) last.  The wire ordering comes from the calibrated
    ``LinkCalibration`` when one exists; the cold-start chip table says
    DCN << ICI, so cold behavior is DCN-first."""
    from ..tools import calibrate, perf_model

    if cal is None:
        cal = calibrate.load_calibration()
    ici_bw = (cal.ici_gbps if cal is not None and cal.ici_gbps
              else perf_model.chip_spec().ici_gbps)
    dcn_bw = (cal.dcn_gbps if cal is not None and cal.dcn_gbps
              else perf_model.DCN_GBPS_PER_CHIP)
    dcn = [(o, i) for o in range(1, n_out) for i in range(n_in)]
    dcn.sort(key=lambda t: (-min(t[0], n_out - t[0]),
                            -min(t[1], n_in - t[1] if t[1] else 0), -t[0],
                            -t[1]))
    ici = [(0, off) for off in ici_schedule(n_in) if off != 0]
    first, second = (dcn, ici) if dcn_bw <= ici_bw else (ici, dcn)
    return (*first, *second, (0, 0))


def resolve_dcn_wire(wire_dtype: str, h: int) -> str:
    """The DCN-hop payload dtype: explicit dtypes pass through; ``auto``
    resolves by the measured codec economics on the slow wire
    (``tools.calibrate.codec_pays`` at the row width the hop actually
    ships) — the PR-9 policy, applied to the one hop where it pays."""
    if wire_dtype != "auto":
        return wire_dtype
    from ..tools import calibrate

    return "fp8" if calibrate.codec_pays("dcn", int(h)) else "bf16"


# ---------------------------------------------------------------------------
# record-mode protocol models of the DCN hop (see module docstring)


def dcn_broadcast_model(n_out: int, n_in: int, src_ref, zones_ref, send_sem,
                        recv_sems) -> None:
    """Protocol model of the inter-slice broadcast (production:
    ``lax.all_gather``/``lax.all_to_all`` over the outer axis): rank
    (o, i) pushes its slice block to the same-i rank of every other
    slice — landing in the per-SOURCE-slice zone ``zones[o]``, so no two
    slices' blocks can overlap — then consumes one arrival credit per
    source slice and drains its sends.  The credit consumption is the
    contract phase 2 relies on (a dropped inter-slice credit is the
    seeded-bad fixture and a fault-matrix class)."""
    o = dl.rank("dcn")
    i = dl.rank("tp")
    for off in range(1, n_out):
        dst_o = (o + off) % n_out
        dl.remote_copy(src_ref, zones_ref.at[o], send_sem, recv_sems.at[o],
                       dst_o * n_in + i)
    for off in range(1, n_out):
        src_o = (o + n_out - off) % n_out
        dl.wait_recv(zones_ref.at[src_o], recv_sems.at[src_o])
    for _ in range(n_out - 1):
        dl.wait_send(src_ref, send_sem)


def dcn_reduce_model(n_out: int, n_in: int, part_ref, zones_ref, out_ref,
                     send_sem, recv_sems, out_dtype, m: int, r: int) -> None:
    """Protocol model of the inter-slice reduction (production:
    ``lax.psum`` / ``psum_scatter`` over the outer axis): the broadcast
    exchange of 1/n_in partials, then the local n_out-way sum — the same
    one-shot exchange shape the quantized DCN-AR option ships for real."""
    from ..ops import blocks

    dcn_broadcast_model(n_out, n_in, part_ref, zones_ref, send_sem,
                        recv_sems)
    reduce = blocks.make_sum_pipeline(n_out, m, r, min(m, 256), min(r, 512),
                                      out_dtype)
    o = dl.rank("dcn")
    ins = [zones_ref.at[src_o] for src_o in range(n_out) if src_o != o]
    reduce(part_ref, *ins, out_ref)


# ---------------------------------------------------------------------------
# byte accounting (per chip) — shared by the obs counters, the watchdog
# pricing (tools.perf_model), and `bench.py hier`


def _packed_bytes(rows: int, r: int, dtype, wire: str) -> int:
    from ..lang import quant

    if wire == "bf16":
        return rows * r * int(jnp.dtype(dtype).itemsize)
    return rows * quant.packed_width(r, wire)


def hier_ag_wire_bytes(m_local: int, r: int, dtype, n_in: int, n_out: int,
                       dcn_wire: str = "bf16") -> tuple[int, int]:
    """(ici_bytes, dcn_bytes) one hierarchical AllGather moves per chip:
    the inner ring forwards (n_in-1) shards; the outer broadcast lands
    (n_out-1) slice blocks of n_in shards each."""
    ib = int(jnp.dtype(dtype).itemsize)
    ici = (n_in - 1) * m_local * r * ib
    dcn = (n_out - 1) * _packed_bytes(n_in * m_local, r, dtype, dcn_wire)
    return ici, dcn


def hier_rs_wire_bytes(m_partial: int, r: int, dtype, n_in: int,
                       n_out: int) -> tuple[int, int]:
    """(ici_bytes, dcn_bytes) per chip for the hierarchical RS: inner
    ring reduce of the m_partial rows (n_in-1 chunk hops), then
    ``psum_scatter`` of the (m_partial/n_in)-row partial across slices
    ((n_out-1)/n_out of it on the wire)."""
    ib = int(jnp.dtype(dtype).itemsize)
    chunk = m_partial // n_in
    ici = (n_in - 1) * chunk * r * ib
    dcn = (n_out - 1) * chunk * r * ib // n_out
    return ici, dcn


def hier_ar_wire_bytes(m: int, r: int, dtype, n_in: int, n_out: int,
                       dcn_wire: str = "bf16") -> tuple[int, int]:
    """(ici_bytes, dcn_bytes) per chip for the hierarchical AllReduce
    (RS ∘ AG): the two inner rings move 2(n_in-1)/n_in of the partial;
    the DCN hop reduces only the (m/n_in)-row partial — ring ``psum`` =
    2(n_out-1)/n_out of it, quantized one-shot = (n_out-1) packed
    copies.  At n_out=2 both forms sit exactly at the RS∘AG bound of
    1/n_in of the payload per chip."""
    ib = int(jnp.dtype(dtype).itemsize)
    partial = m * r * ib
    ici = 2 * (n_in - 1) * partial // n_in
    part_rows = m // n_in
    if dcn_wire == "bf16":
        dcn = 2 * (n_out - 1) * part_rows * r * ib // n_out
    else:
        dcn = (n_out - 1) * _packed_bytes(part_rows, r, dtype, dcn_wire)
    return ici, dcn


def hier_a2a_wire_bytes(t: int, h: int, dtype, n_in: int, n_out: int,
                        dcn_wire: str = "bf16") -> tuple[int, int]:
    """(ici_bytes, dcn_bytes) per chip for the scheduled EP A2A.  The
    DCN phase ships FIXED zero-padded t-row blocks (static shapes are
    the XLA collective's contract), one per foreign slice — so
    (n_out-1) full blocks cross the slow wire regardless of routing;
    the ICI phase redistributes up to the n_out·t merged rows within
    the slice."""
    ici = n_out * t * h * int(jnp.dtype(dtype).itemsize)
    dcn = (n_out - 1) * _packed_bytes(t, h, dtype, dcn_wire)
    return ici, dcn


# ---------------------------------------------------------------------------
# shared entry plumbing


def _validate_2d(mesh: Mesh, inner_axis: str, outer_axis: str):
    n_in = mesh.shape[inner_axis]
    n_out = mesh.shape[outer_axis]
    return n_in, n_out


def _wrap(op: str, core, *, mesh, n_in: int, n_out: int, payload: int,
          ici_bytes: int, dcn_bytes: int, method: str, chunks: int,
          fallback, eager: bool):
    """The uniform observe/survive wrapper of the hierarchical entries:
    watchdog deadline priced per wire class per level (the two-level
    ``tools.perf_model`` terms), retry->XLA-fallback->breaker ladder, and
    obs accounting that splits the wire bytes by class (``comm_wire_bytes``
    carries the total; ``comm_dcn_bytes`` the slow-wire share the bench
    claims-gate reads)."""
    from .. import obs, resilience

    n = n_in * n_out
    if eager and resilience.enabled():
        core = resilience.guarded(
            op, core, family="hierarchical", ranks=n,
            payload_bytes=payload, fallback=fallback,
            topology=(n_out, n_in),
        )
    if eager and (obs.enabled() or obs.flight.enabled()):
        inner_core = core

        def counted():
            if obs.enabled():
                obs.counter("comm_dcn_bytes", op=op, method=method).inc(
                    dcn_bytes)
            return inner_core()

        return lambda: obs.comm_call(
            op, counted, payload_bytes=payload,
            wire_bytes=ici_bytes + dcn_bytes, chunks=chunks,
            method=method, ranks=n,
        )
    return core


# ---------------------------------------------------------------------------
# AllGather


@functools.lru_cache(maxsize=None)
def _build_hier_ag(mesh: Mesh, inner_axis: str, outer_axis: str, method,
                   shard_shape: tuple[int, ...], dtype: jnp.dtype,
                   dcn_wire: str):
    from .allgather import _build_ag_call

    n_in = mesh.shape[inner_axis]
    n_out = mesh.shape[outer_axis]
    call = _build_ag_call(mesh, inner_axis, method, shard_shape, dtype)
    m_in = n_in * shard_shape[0]

    def local(x_loc):
        inner_g = call(x_loc)                            # ICI Pallas ring
        if dcn_wire == "bf16":
            outer_g = jax.lax.all_gather(inner_g, outer_axis)  # DCN via XLA
        else:
            # quantize ONLY the inter-slice payload (codec_pays("dcn")):
            # pack rows at the producer slice, u8 message on the DCN,
            # dequantize on arrival — the ICI level stays model-dtype
            from ..lang import quant

            packed = quant.pack_rows(inner_g, dcn_wire)
            gathered = jax.lax.all_gather(packed, outer_axis)
            outer_g = quant.unpack_rows(
                gathered.reshape(n_out * m_in, -1), shard_shape[-1],
                dcn_wire, dtype,
            )
        return outer_g.reshape(n_out * m_in, *shard_shape[1:])

    ndim = len(shard_shape)
    return compilation.jit_shard_map(
        local, mesh,
        in_specs=P((outer_axis, inner_axis), *([None] * (ndim - 1))),
        out_specs=P(*([None] * ndim)),
    )


def hierarchical_all_gather(
    x: jax.Array,
    mesh: Mesh,
    inner_axis: str,
    outer_axis: str,
    *,
    method=None,
    wire_dtype: str = "bf16",
) -> jax.Array:
    """Two-level AllGather over an (outer x inner) mesh — the reference's
    2D inter-node AG (``allgather.py:442-601``: intra-node copy-engine
    ring + cross-node staging).

    The ``inner_axis`` (ICI) level is the Pallas ring/push kernel of
    ``comm.allgather``; the ``outer_axis`` (DCN) level is
    ``lax.all_gather`` (remote DMA is device-initiated over ICI only —
    SURVEY.md section 7).  Rows come back in GLOBAL rank order
    (outer-major), matching a flat AG over a combined axis.

    ``wire_dtype``: "bf16" ships as-is; "int8"/"fp8" quantize the DCN
    payload (packed u8 message, ``lang.quant``); "auto" quantizes when
    ``codec_pays("dcn")`` (the measured policy).  The ICI level always
    ships the model dtype — the codec does not pay on the fast wire.

    ``x``: (n_out * n_in * M, R) sharded over both axes on dim 0.
    """
    from .allgather import AllGatherMethod, all_gather, resolve_method
    from ..tune.autotuner import is_tracer

    if method is None:
        method = AllGatherMethod.AUTO
    n_in, n_out = _validate_2d(mesh, inner_axis, outer_axis)
    if n_out == 1:
        # numerically pinned to the flat single-level collective on a
        # 1-slice mesh (the ISSUE-10 equivalence anchor)
        return all_gather(x, mesh, inner_axis, method=method)
    m_total = x.shape[0]
    if m_total % (n_in * n_out):
        raise ValueError(
            f"dim0 {m_total} not divisible by "
            f"{outer_axis}*{inner_axis} = {n_out * n_in}"
        )
    m_local = m_total // (n_in * n_out)
    shard_shape = (m_local, *x.shape[1:])
    method = resolve_method(method, shard_shape, x.dtype, n_in)
    if x.ndim == 2:
        dcn_wire = resolve_dcn_wire(wire_dtype, x.shape[-1])
    elif wire_dtype in ("bf16", "auto"):
        # "auto" resolves to the only honorable choice; an EXPLICIT
        # quantized request on a non-row-shaped payload must fail loudly
        # rather than silently ship full-width bytes
        dcn_wire = "bf16"
    else:
        raise ValueError(
            f"wire_dtype={wire_dtype!r} quantizes H-wide rows; a "
            f"{x.ndim}-D payload has no row codec — reshape to (rows, H) "
            f"or pass wire_dtype='bf16'"
        )
    compilation.verify_protocol("hierarchical", n_in * n_out)
    fn = _build_hier_ag(mesh, inner_axis, outer_axis, method, shard_shape,
                        jnp.dtype(x.dtype), dcn_wire)
    eager = not is_tracer(x)
    shard_bytes = math.prod(shard_shape) * jnp.dtype(x.dtype).itemsize
    ici, dcn = hier_ag_wire_bytes(m_local, x.shape[-1] if x.ndim == 2 else 1,
                                  x.dtype, n_in, n_out, dcn_wire) \
        if x.ndim == 2 else (
            (n_in - 1) * shard_bytes, (n_out - 1) * n_in * shard_bytes)

    def fallback():
        ndim = x.ndim
        return compilation.jit_shard_map(
            lambda v: jax.lax.all_gather(
                v, (outer_axis, inner_axis), tiled=True),
            mesh,
            in_specs=P((outer_axis, inner_axis), *([None] * (ndim - 1))),
            out_specs=P(*([None] * ndim)),
        )(x)

    core = _wrap(
        "hier_all_gather", lambda: fn(x), mesh=mesh, n_in=n_in, n_out=n_out,
        payload=shard_bytes, ici_bytes=ici, dcn_bytes=dcn,
        method=f"{method.value}+dcn_{dcn_wire}",
        chunks=(n_in - 1) + (n_out - 1), fallback=fallback, eager=eager,
    )
    return core()


# ---------------------------------------------------------------------------
# ReduceScatter


@functools.lru_cache(maxsize=None)
def _build_hier_rs(mesh: Mesh, inner_axis: str, outer_axis: str,
                   m_partial: int, r_dim: int, dtype: jnp.dtype, cfg):
    from .reduce_scatter import _build_rs_call

    n_in = mesh.shape[inner_axis]
    n_out = mesh.shape[outer_axis]
    blk = m_partial // (n_in * n_out)
    call = _build_rs_call(mesh, inner_axis, m_partial // n_in, r_dim, dtype,
                          cfg)

    def local(x_loc):
        # Row blocks arrive in flat (outer-major global rank) order; the
        # inner scatter picks by inner rank first, so transpose the block
        # grid to inner-major — then chunk i / sub-block o is exactly
        # global block o*n_in + i.
        xp = (x_loc.reshape(n_out, n_in, blk, r_dim)
              .transpose(1, 0, 2, 3).reshape(m_partial, r_dim))
        part = call(xp)                               # ICI Pallas ring
        return jax.lax.psum_scatter(                  # DCN via XLA
            part, outer_axis, scatter_dimension=0, tiled=True
        )

    return compilation.jit_shard_map(
        local, mesh,
        in_specs=P((outer_axis, inner_axis), None),
        out_specs=P((outer_axis, inner_axis), None),
    )


def hierarchical_reduce_scatter(
    x: jax.Array,
    mesh: Mesh,
    inner_axis: str,
    outer_axis: str,
    *,
    config=None,
) -> jax.Array:
    """Two-level ReduceScatter over an (outer x inner) mesh — the
    reference's 2D intra+inter hierarchy (``reduce_scatter.py:688-882``,
    ``ReduceScatter2DContext:46``): the inner ring of
    ``comm.reduce_scatter`` per slice, ``psum_scatter`` across slices.
    Semantics match a flat :func:`comm.reduce_scatter` over the combined
    outer-major axis: golden ``x.reshape(N, M, R).sum(0)`` scattered in
    global rank order.
    """
    from .reduce_scatter import ReduceScatterConfig, reduce_scatter
    from ..tune.autotuner import is_tracer

    n_in, n_out = _validate_2d(mesh, inner_axis, outer_axis)
    if n_out == 1:
        return reduce_scatter(x, mesh, inner_axis, config=config)
    n = n_in * n_out
    m_stack = x.shape[0]
    if m_stack % n:
        raise ValueError(f"dim0 {m_stack} not divisible by N={n}")
    m_partial = m_stack // n
    if m_partial % n:
        raise ValueError(f"partial rows {m_partial} not divisible by N={n}")
    cfg = (config or ReduceScatterConfig()).clip(m_partial // n_in,
                                                 x.shape[1])
    compilation.verify_protocol("hierarchical", n)
    fn = _build_hier_rs(mesh, inner_axis, outer_axis, m_partial, x.shape[1],
                        jnp.dtype(x.dtype), cfg)
    eager = not is_tracer(x)
    payload = m_partial * x.shape[1] * jnp.dtype(x.dtype).itemsize
    ici, dcn = hier_rs_wire_bytes(m_partial, x.shape[1], x.dtype, n_in,
                                  n_out)

    def fallback():
        return compilation.jit_shard_map(
            lambda v: jax.lax.psum_scatter(
                v, (outer_axis, inner_axis), scatter_dimension=0,
                tiled=True),
            mesh,
            in_specs=P((outer_axis, inner_axis), None),
            out_specs=P((outer_axis, inner_axis), None),
        )(x)

    core = _wrap(
        "hier_reduce_scatter", lambda: fn(x), mesh=mesh, n_in=n_in,
        n_out=n_out, payload=payload, ici_bytes=ici, dcn_bytes=dcn,
        method="ring+dcn_scatter", chunks=(n_in - 1) + (n_out - 1),
        fallback=fallback, eager=eager,
    )
    return core()


# ---------------------------------------------------------------------------
# AllReduce


@functools.lru_cache(maxsize=None)
def _build_hier_ar(mesh: Mesh, inner_axis: str, outer_axis: str, m: int,
                   r_dim: int, dtype: jnp.dtype, cfg, dcn_wire: str):
    from .allgather import AllGatherMethod, _build_ag_call, resolve_method
    from .reduce_scatter import ReduceScatterConfig, _build_rs_call

    n_in = mesh.shape[inner_axis]
    n_out = mesh.shape[outer_axis]
    m_loc = m // n_in
    rs_cfg = ReduceScatterConfig(bm=cfg.bm, bn=cfg.bn).clip(m_loc, r_dim)
    rs_call = _build_rs_call(mesh, inner_axis, m_loc, r_dim, dtype, rs_cfg)
    ag_method = resolve_method(
        AllGatherMethod.AUTO, (m_loc, r_dim), dtype, n_in
    )
    ag_call = _build_ag_call(mesh, inner_axis, ag_method, (m_loc, r_dim),
                             dtype)

    def local(x_loc):
        part = rs_call(x_loc)                 # ICI ring ReduceScatter
        if dcn_wire == "bf16":
            part = jax.lax.psum(part, outer_axis)      # DCN via XLA
        else:
            # quantized one-shot DCN reduce: pack the 1/n_in partial,
            # gather the n_out packed copies, dequantize + f32-sum
            # locally (the comm.quantized exchange shape, on the hop
            # where the codec pays)
            from ..lang import quant

            packed = quant.pack_rows(part, dcn_wire)
            gathered = jax.lax.all_gather(packed, outer_axis)  # (n_out,...)
            unpacked = quant.unpack_rows(gathered, r_dim, dcn_wire,
                                         jnp.float32)
            part = unpacked.sum(axis=0).astype(dtype)
        return ag_call(part)                  # ICI ring AllGather

    return compilation.jit_shard_map(
        local, mesh,
        in_specs=P((outer_axis, inner_axis), None),
        out_specs=P(None, None),
    )


def dcn_ar_wire(wire_dtype: str, r_dim: int, n_out: int) -> str:
    """The AllReduce DCN hop's payload dtype: the quantized one-shot
    exchange ships (n_out-1) packed copies where ``psum``'s ring ships
    2(n_out-1)/n_out bf16 — the codec wins only while
    ``packed < 2*bf16/n_out``, i.e. on few-slice topologies (n_out <= 3
    at the ~0.51x packing ratio).  ``auto`` applies that arithmetic on
    top of :func:`resolve_dcn_wire`'s codec economics."""
    wire = resolve_dcn_wire(wire_dtype, r_dim)
    if wire == "bf16":
        return wire
    from ..lang import quant

    if (n_out - 1) * quant.packed_width(r_dim, wire) \
            >= 2 * (n_out - 1) * 2 * r_dim // n_out:
        return "bf16"
    return wire


def hierarchical_all_reduce(
    x: jax.Array,
    mesh: Mesh,
    inner_axis: str,
    outer_axis: str,
    *,
    config=None,
    wire_dtype: str = "bf16",
) -> jax.Array:
    """Two-level AllReduce over an (outer x inner) mesh: RS ring on ICI,
    reduce across slices on DCN, AG ring on ICI — RS ∘ AG composed so the
    DCN hop carries **1/n_in of the payload per chip** (the ring-tree
    shape of the reference's hierarchical AR, ``allreduce.py:224``).

    ``x``: global ``(N*M, R)`` over both axes (outer-major), each
    device's (M, R) shard its partial addend; returns (M, R) replicated.
    Golden: ``x.reshape(N, M, R).sum(0)``.

    ``wire_dtype``: the DCN hop's payload — "auto" takes the quantized
    one-shot exchange when the codec pays on the slow wire AND the
    few-slice byte arithmetic favors it (:func:`dcn_ar_wire`); the ICI
    rings always carry the model dtype.
    """
    from .allreduce import AllReduceConfig, all_reduce
    from ..tune.autotuner import is_tracer

    n_in, n_out = _validate_2d(mesh, inner_axis, outer_axis)
    if n_out == 1:
        return all_reduce(x, mesh, inner_axis, config=config)
    n = n_in * n_out
    m_stack = x.shape[0]
    if m_stack % n:
        raise ValueError(f"dim0 {m_stack} not divisible by N={n}")
    m = m_stack // n
    if m % n_in:
        raise ValueError(
            f"partial rows {m} not divisible by {inner_axis}={n_in}"
        )
    cfg = (config or AllReduceConfig()).clip(m // n_in, x.shape[1])
    dcn_wire = dcn_ar_wire(wire_dtype, x.shape[1], n_out)
    compilation.verify_protocol("hierarchical", n)
    fn = _build_hier_ar(mesh, inner_axis, outer_axis, m, x.shape[1],
                        jnp.dtype(x.dtype), cfg, dcn_wire)
    eager = not is_tracer(x)
    payload = m * x.shape[1] * jnp.dtype(x.dtype).itemsize
    ici, dcn = hier_ar_wire_bytes(m, x.shape[1], x.dtype, n_in, n_out,
                                  dcn_wire)

    def fallback():
        def local(v):
            return jax.lax.psum(
                v.reshape(n_in, m, x.shape[1]).sum(0),
                (outer_axis, inner_axis))

        return compilation.jit_shard_map(
            local, mesh,
            in_specs=P((outer_axis, inner_axis), None),
            out_specs=P(None, None),
        )(x)

    core = _wrap(
        "hier_all_reduce", lambda: fn(x), mesh=mesh, n_in=n_in, n_out=n_out,
        payload=payload, ici_bytes=ici, dcn_bytes=dcn,
        method=f"rs_ag+dcn_{dcn_wire}",
        chunks=2 * (n_in - 1) + (n_out - 1), fallback=fallback, eager=eager,
    )
    return core()


# ---------------------------------------------------------------------------
# scheduled EP all-to-all (two-phase, DCN first)


def _cdiv(a, b):
    return (a + b - 1) // b


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


def per_slice_meta(splits_loc, n_out: int, e_slice: int):
    """(rows to each destination slice, row offset of each slice's block)
    from one rank's expert-sorted splits — destination-slice blocks are
    contiguous because rows are sorted by (globally slice-major) expert
    id.  Pure index math, unit-tested headlessly."""
    per_slice = splits_loc.reshape(n_out, e_slice).sum(axis=1)
    offs = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32),
         jnp.cumsum(per_slice)[:-1].astype(jnp.int32)]
    )
    return per_slice.astype(jnp.int32), offs.astype(jnp.int32)


def merge_order(group_splits, t_rows: int):
    """Stable merge permutation over ``g`` groups of ``t_rows`` rows,
    each group sorted by the same ``e`` expert ids with per-group counts
    ``group_splits[g, e]`` and padding at its tail: ``flat[order]`` is
    globally expert-sorted (stable across groups) with every padding row
    at the global tail.  Pure index math, unit-tested headlessly."""
    g, e = group_splits.shape
    j = jnp.arange(t_rows)
    cum = jnp.cumsum(group_splits, axis=1)
    eid = jax.vmap(lambda c: jnp.searchsorted(c, j, side="right"))(cum)
    eid = jnp.minimum(eid, e)            # padding rows -> sentinel e
    return jnp.argsort(eid.reshape(g * t_rows), stable=True).astype(
        jnp.int32)


@functools.lru_cache(maxsize=None)
def _build_sched_dispatch(mesh: Mesh, inner_axis: str, outer_axis: str,
                          t: int, h: int, epr: int, chunk: int, z: int,
                          dtype: jnp.dtype, schedule: tuple[int, ...],
                          dcn_wire: str):
    from .all_to_all import _make_push_call
    from ..lang.primitives import Team

    n_in = mesh.shape[inner_axis]
    n_out = mesh.shape[outer_axis]
    e_slice = n_in * epr
    team = Team.of(mesh, inner_axis)
    call = _make_push_call(team, chunk, z, h, n_in, "sched_ep_dispatch",
                           dtype, schedule)
    t_in = n_out * t                       # merged row count (incl padding)
    t_in_pad = _round_up(t_in, chunk) + chunk

    def local(x_loc, splits_loc):
        # ---- phase 1 (DCN, launched first): slice-grouped token blocks
        # to the same-i partner of every slice ----
        per_slice, s_offs = per_slice_meta(splits_loc, n_out, e_slice)
        j = jnp.arange(t)
        gidx = jnp.minimum(s_offs[:, None] + j[None, :], t - 1)
        blocks = jnp.take(x_loc, gidx.reshape(-1), axis=0) \
            .reshape(n_out, t, h)
        mask = j[None, :] < per_slice[:, None]
        blocks = jnp.where(mask[..., None], blocks, 0)
        if dcn_wire != "bf16":
            from ..lang import quant

            wire_blocks = quant.pack_rows(blocks, dcn_wire)
        else:
            wire_blocks = blocks
        moved = jax.lax.all_to_all(wire_blocks, outer_axis, 0, 0)
        if dcn_wire != "bf16":
            from ..lang import quant

            moved = quant.unpack_rows(moved, h, dcn_wire, dtype)
        # per-partner splits of MY slice's experts (tiny int exchange)
        recv_sl = jax.lax.all_to_all(
            splits_loc.reshape(n_out, e_slice), outer_axis, 0, 0)
        # ---- merge the n_out groups into one expert-sorted run ----
        order = merge_order(recv_sl, t)
        merged = jnp.take(moved.reshape(t_in, h), order, axis=0)
        merged = jnp.pad(merged, ((0, t_in_pad - t_in), (0, 0)))
        merged_splits = recv_sl.sum(axis=0).astype(jnp.int32)
        # ---- phase 2 (ICI, scheduled): intra-slice push kernel ----
        per_peer = merged_splits.reshape(n_in, epr).sum(axis=1) \
            .astype(jnp.int32)
        offs = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             jnp.cumsum(per_peer)[:-1].astype(jnp.int32)])
        expected = jax.lax.all_to_all(per_peer, inner_axis, 0, 0)
        recv_splits = jax.lax.all_to_all(
            merged_splits.reshape(n_in, epr), inner_axis, 0, 0)
        recv = call(per_peer, offs.astype(jnp.int32),
                    expected.astype(jnp.int32), merged)
        return recv, recv_splits.astype(jnp.int32)

    return compilation.jit_shard_map(
        local, mesh,
        in_specs=(P((outer_axis, inner_axis), None),
                  P((outer_axis, inner_axis))),
        out_specs=(P((outer_axis, inner_axis), None, None),
                   P((outer_axis, inner_axis), None)),
    )


@functools.lru_cache(maxsize=None)
def _build_sched_combine(mesh: Mesh, inner_axis: str, outer_axis: str,
                         t: int, h: int, epr: int, chunk: int, z: int,
                         dtype: jnp.dtype, schedule: tuple[int, ...],
                         dcn_wire: str):
    from .all_to_all import _make_push_call
    from ..lang.primitives import Team

    n_in = mesh.shape[inner_axis]
    n_out = mesh.shape[outer_axis]
    e_slice = n_in * epr
    team = Team.of(mesh, inner_axis)
    call = _make_push_call(team, chunk, z, h, n_in, "sched_ep_combine",
                           dtype, schedule)
    t_in = n_out * t

    def local(y_loc, splits_loc):
        # recompute dispatch's metadata deterministically from the same
        # splits (the flat combine's contract, two-level form)
        per_slice, s_offs = per_slice_meta(splits_loc, n_out, e_slice)
        recv_sl = jax.lax.all_to_all(
            splits_loc.reshape(n_out, e_slice), outer_axis, 0, 0)
        order = merge_order(recv_sl, t)
        merged_splits = recv_sl.sum(axis=0).astype(jnp.int32)
        per_peer = merged_splits.reshape(n_in, epr).sum(axis=1) \
            .astype(jnp.int32)
        offs = jnp.concatenate(
            [jnp.zeros((1,), jnp.int32),
             jnp.cumsum(per_peer)[:-1].astype(jnp.int32)])
        expected = jax.lax.all_to_all(per_peer, inner_axis, 0, 0)
        # ---- ICI return hop: zones back to their inner sources ----
        zone_offs = (jnp.arange(n_in, dtype=jnp.int32) * z)
        back = call(expected.astype(jnp.int32), zone_offs, per_peer,
                    y_loc.reshape(n_in * z, h))
        # exact repack to the merged-sorted order (flat combine's gather)
        ridx = jnp.arange(t_in)
        cum = jnp.cumsum(per_peer)
        p_of = jnp.clip(jnp.searchsorted(cum, ridx, side="right"), 0,
                        n_in - 1)
        within = ridx - jnp.take(offs, p_of)
        merged_back = jnp.take(back.reshape(n_in * z, h),
                               p_of * z + within, axis=0)
        # un-merge to the phase-1 (group, row) layout
        inv = jnp.argsort(order)
        flat = jnp.take(merged_back, inv, axis=0).reshape(n_out, t, h)
        # ---- DCN return hop ----
        if dcn_wire != "bf16":
            from ..lang import quant

            flat = quant.pack_rows(flat, dcn_wire)
        ret = jax.lax.all_to_all(flat, outer_axis, 0, 0)
        if dcn_wire != "bf16":
            from ..lang import quant

            ret = quant.unpack_rows(ret, h, dcn_wire, dtype)
        # un-group back to the original expert-sorted order
        tt = jnp.arange(t)
        cum_s = jnp.cumsum(per_slice)
        s_of = jnp.clip(jnp.searchsorted(cum_s, tt, side="right"), 0,
                        n_out - 1)
        within_s = tt - jnp.take(s_offs, s_of)
        return jnp.take(ret.reshape(t_in, h), s_of * t + within_s, axis=0)

    return compilation.jit_shard_map(
        local, mesh,
        in_specs=(P((outer_axis, inner_axis), None, None),
                  P((outer_axis, inner_axis))),
        out_specs=P((outer_axis, inner_axis), None),
    )


def _sched_geometry(t: int, n_out: int, chunk: int) -> tuple[int, int]:
    """(chunk, zone rows) of the inner scheduled push: worst case every
    merged row (n_out slices' worth) lands on one inner peer."""
    chunk = min(chunk, _round_up(max(t, 1), 8))
    z = _round_up(n_out * t, chunk) + chunk
    return chunk, z


def scheduled_ep_dispatch(
    x: jax.Array,
    splits: jax.Array,
    mesh: Mesh,
    inner_axis: str,
    outer_axis: str,
    *,
    config=None,
    wire_dtype: str = "auto",
):
    """Topology-scheduled two-level EP dispatch over an (outer x inner)
    mesh (ISSUE 10 tentpole).  Phase 1 (launched FIRST — program order
    puts the slow wire's traffic in flight before any ICI work): rows
    grouped by destination SLICE ride ``lax.all_to_all`` over the DCN
    axis between same-inner-rank partners, quantized per
    :func:`resolve_dcn_wire`.  Phase 2: the arriving groups are merged
    back into expert order (``merge_order``) and the intra-slice Pallas
    push kernel redistributes them with the farthest-first
    :func:`ici_schedule` emission order, pipelining under the DCN phase.

    Layout contract (global, outer-major rank order g = o*n_in + i):
    ``x`` (n*T, H) expert-sorted per rank; ``splits`` (n*E,) with E
    divisible by n.  Returns ``(recv, recv_splits)``: rank g's slab of
    ``recv`` is its n_in ICI landing zones (rows of its slice's experts
    by MERGED inner source), ``recv_splits`` (n*n_in, epr) the per-inner-
    source per-owned-expert counts.  :func:`scheduled_ep_combine`
    inverts it exactly (same splits).
    """
    from .. import obs, resilience
    from ..tune.autotuner import is_tracer
    from .all_to_all import AllToAllConfig, ep_dispatch

    n_in, n_out = _validate_2d(mesh, inner_axis, outer_axis)
    if n_out == 1:
        return ep_dispatch(x, splits, mesh, inner_axis, config=config,
                           wire_dtype="bf16" if wire_dtype == "auto"
                           else wire_dtype)
    n = n_in * n_out
    tn, h = x.shape
    if tn % n:
        raise ValueError(f"token dim {tn} not divisible by n={n}")
    t = tn // n
    e_tot = splits.shape[0] // n
    if splits.shape[0] % n or e_tot % n:
        raise ValueError(
            f"splits {splits.shape} must be (n*E,) with E divisible by n"
        )
    epr = e_tot // n
    cfg = config or AllToAllConfig()
    chunk, z = _sched_geometry(t, n_out, cfg.chunk)
    schedule = cfg.schedule or ici_schedule(n_in)
    dcn_wire = resolve_dcn_wire(wire_dtype, h)
    compilation.verify_protocol("hierarchical", n)
    fn = _build_sched_dispatch(mesh, inner_axis, outer_axis, t, h, epr,
                               chunk, z, jnp.dtype(x.dtype), schedule,
                               dcn_wire)
    eager = not (is_tracer(x) or is_tracer(splits))
    payload = t * h * jnp.dtype(x.dtype).itemsize
    ici, dcn = hier_a2a_wire_bytes(t, h, x.dtype, n_in, n_out, dcn_wire)
    core = lambda: fn(x, splits.astype(jnp.int32))  # noqa: E731
    if eager and resilience.enabled():
        core = resilience.guarded(
            "sched_ep_dispatch", core, family="hierarchical", ranks=n,
            payload_bytes=payload, topology=(n_out, n_in),
        )
    if eager and (obs.enabled() or obs.flight.enabled()):
        def counted(inner_core=core):
            if obs.enabled():
                obs.counter("comm_dcn_bytes", op="sched_ep_dispatch",
                            method=f"sched+dcn_{dcn_wire}").inc(dcn)
            return inner_core()

        return obs.comm_call(
            "sched_ep_dispatch", counted, payload_bytes=payload,
            wire_bytes=ici + dcn, chunks=_cdiv(max(n_out * t, 1), chunk),
            method=f"sched+dcn_{dcn_wire}", ranks=n,
        )
    return core()


def scheduled_ep_combine(
    y: jax.Array,
    splits: jax.Array,
    mesh: Mesh,
    inner_axis: str,
    outer_axis: str,
    *,
    token_dim: int,
    config=None,
    wire_dtype: str = "auto",
) -> jax.Array:
    """Inverse of :func:`scheduled_ep_dispatch`: ICI return hop (same
    scheduled push kernel, roles reversed), un-merge via the inverse
    merge permutation, DCN return hop, un-group — restoring the original
    expert-sorted row order on every source rank.  ``y`` is the zone
    layout dispatch produced (rows processed in place); ``splits`` the
    SAME global splits; ``token_dim`` = T."""
    from .. import obs, resilience
    from ..tune.autotuner import is_tracer
    from .all_to_all import AllToAllConfig, ep_combine

    n_in, n_out = _validate_2d(mesh, inner_axis, outer_axis)
    if n_out == 1:
        return ep_combine(y, splits, mesh, inner_axis, token_dim=token_dim,
                          config=config,
                          wire_dtype="bf16" if wire_dtype == "auto"
                          else wire_dtype)
    n = n_in * n_out
    h = y.shape[-1]
    t = token_dim
    e_tot = splits.shape[0] // n
    epr = e_tot // n
    cfg = config or AllToAllConfig()
    chunk, z = _sched_geometry(t, n_out, cfg.chunk)
    if y.shape[0] != n * n_in or y.shape[1] != z:
        raise ValueError(
            f"zone layout {y.shape} does not match dispatch geometry "
            f"({n * n_in}, {z}, {h})"
        )
    schedule = cfg.schedule or ici_schedule(n_in)
    dcn_wire = resolve_dcn_wire(wire_dtype, h)
    compilation.verify_protocol("hierarchical", n)
    fn = _build_sched_combine(mesh, inner_axis, outer_axis, t, h, epr,
                              chunk, z, jnp.dtype(y.dtype), schedule,
                              dcn_wire)
    eager = not (is_tracer(y) or is_tracer(splits))
    payload = t * h * jnp.dtype(y.dtype).itemsize
    ici, dcn = hier_a2a_wire_bytes(t, h, y.dtype, n_in, n_out, dcn_wire)
    core = lambda: fn(y, splits.astype(jnp.int32))  # noqa: E731
    if eager and resilience.enabled():
        core = resilience.guarded(
            "sched_ep_combine", core, family="hierarchical", ranks=n,
            payload_bytes=payload, topology=(n_out, n_in),
        )
    if eager and (obs.enabled() or obs.flight.enabled()):
        def counted(inner_core=core):
            if obs.enabled():
                obs.counter("comm_dcn_bytes", op="sched_ep_combine",
                            method=f"sched+dcn_{dcn_wire}").inc(dcn)
            return inner_core()

        return obs.comm_call(
            "sched_ep_combine", counted, payload_bytes=payload,
            wire_bytes=ici + dcn, chunks=_cdiv(max(n_out * t, 1), chunk),
            method=f"sched+dcn_{dcn_wire}", ranks=n,
        )
    return core()
