"""Collectives as fused Pallas TPU kernels (reference: the kernel library's
communication half — allgather/reduce_scatter/allreduce/all-to-all files in
``python/triton_dist/kernels/nvidia/``).  Single-slice (ICI) kernels live in
their per-family modules; the multi-slice (ICI x DCN) layer — two-level
AG/RS/AR and the topology-scheduled EP all-to-all — is ``hierarchical``."""

from .all_to_all import AllToAllConfig, ep_combine, ep_dispatch
from .allgather import (
    AllGatherMethod,
    all_gather,
    choose_method,
)
from .allreduce import (
    AllReduceConfig,
    AllReduceMethod,
    all_reduce,
)
from .hierarchical import (
    chunk_schedule,
    hierarchical_all_gather,
    hierarchical_all_reduce,
    hierarchical_reduce_scatter,
    ici_schedule,
    scheduled_ep_combine,
    scheduled_ep_dispatch,
    slice_axes,
)
from .quantized import (
    quantized_all_gather,
    quantized_all_reduce,
    quantized_ep_combine,
    quantized_ep_dispatch,
    quantized_reduce_scatter,
)
from .reduce_scatter import (
    ReduceScatterConfig,
    reduce_scatter,
)
