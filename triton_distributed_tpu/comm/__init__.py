"""Collectives as fused Pallas TPU kernels (reference: the kernel library's
communication half — allgather/reduce_scatter/allreduce/all-to-all files in
``python/triton_dist/kernels/nvidia/``)."""

from .all_to_all import AllToAllConfig, ep_combine, ep_dispatch
from .allgather import (
    AllGatherMethod,
    all_gather,
    choose_method,
    hierarchical_all_gather,
)
from .allreduce import (
    AllReduceConfig,
    AllReduceMethod,
    all_reduce,
    hierarchical_all_reduce,
)
from .quantized import (
    quantized_all_gather,
    quantized_all_reduce,
    quantized_ep_combine,
    quantized_ep_dispatch,
    quantized_reduce_scatter,
)
from .reduce_scatter import (
    ReduceScatterConfig,
    hierarchical_reduce_scatter,
    reduce_scatter,
)
