"""AllReduce collectives as Pallas TPU kernels.

TPU-native re-design of the reference's AllReduce family
(``python/triton_dist/kernels/allreduce.py:28`` method enum;
``python/triton_dist/kernels/nvidia/allreduce.py`` — one-shot push ``:365``,
two-shot push ``:477``, double-tree ``:224``, multimem variants ``:557-693``,
size-based auto-selection ``get_auto_allreduce_method:1042-1078``):

- **ONE_SHOT** — every rank pushes its full partial into a per-source slot on
  every peer, then reduces all n slots locally in one f32 pass.  (n-1) wire
  copies of the full payload but a single hop: latency-optimal for small
  tensors (the reference's headline small-M case, BASELINE.md 1.37x at
  M=128).
- **TWO_SHOT** — ReduceScatter ring followed by AllGather ring *in one
  kernel*: each chunk crosses the wire 2(n-1)/n times — bandwidth-optimal.
  No barrier is needed between the phases: phase 1 only writes out-chunk
  ``me`` and every phase-2 write is gated by its own per-chunk DMA
  semaphore.  The reference's DoubleTree / TwoShot_Multimem play this role
  on NVLink; on the ICI torus the ring IS the optimal embedding, and
  multimem (NVLS in-switch reduction) has no TPU equivalent.
- The LL (flag-in-data) protocol variants collapse into DMA completion
  semaphores, as everywhere in this framework (SURVEY.md section 7).

Semantics (functional): input global ``(n*M, R)`` over ``axis`` — each
device's shard is its (M, R) partial addend; output global ``(M, R)``
replicated: every device holds the element-wise sum of all n partials.
Golden: ``x.reshape(n, M, R).sum(0)``.
"""

from __future__ import annotations

import dataclasses
import enum
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..core import compilation
from ..core.mesh import TP_AXIS
from ..core.utils import clip_block
from ..lang import primitives as dl
from ..lang.primitives import Team
from ..ops import blocks
from . import ring
from .ring import chunk as _chunk


class AllReduceMethod(enum.Enum):
    """TPU translation of the reference enum (``kernels/allreduce.py:28``):
    the TMA/multimem/LL axes collapse (no TPU analogue); what remains is the
    algorithmic choice the auto-selector makes by size."""

    AUTO = "auto"
    ONE_SHOT = "one_shot"   # full-mesh push + local n-way sum (latency)
    TWO_SHOT = "two_shot"   # fused RS ring + AG ring (bandwidth)


# One-shot moves (n-1)*bytes over each link but in a single hop; two-shot
# moves ~2*bytes per link in 2(n-1) latency-chained steps.  Crossover sits
# where wire time starts to dominate hop latency — same reasoning as the
# reference's nbytes switch (``allreduce.py:1042-1078``).  The value comes
# from ``tools.calibrate`` (~2x the measured bandwidth-delay product) when
# the live topology has been calibrated; 512 KiB cold default otherwise.


def choose_method(nbytes_per_rank: int, num_ranks: int) -> AllReduceMethod:
    from ..tools import calibrate

    if num_ranks <= 2 or nbytes_per_rank <= calibrate.one_shot_bytes_threshold():
        return AllReduceMethod.ONE_SHOT
    return AllReduceMethod.TWO_SHOT


@dataclasses.dataclass(frozen=True)
class AllReduceConfig:
    bm: int = 256   # reduction-pipeline tile rows
    bn: int = 512   # reduction-pipeline tile cols

    def clip(self, m: int, r: int) -> "AllReduceConfig":
        return AllReduceConfig(
            bm=clip_block(self.bm, m), bn=clip_block(self.bn, r)
        )


def _ar_one_shot_kernel(
    team: Team,
    m: int,
    r_dim: int,
    cfg: AllReduceConfig,
    out_dtype,
    x_ref,       # (m, r) local partial addend                  [ANY]
    out_ref,     # (m, r) full reduced result                   [ANY]
    slots,       # (n, m, r) one landing slot per source rank   [HBM scratch]
    local_sem,   # own-slot local DMA
    send_sem,    # outgoing pushes (n-1 of identical shape)
    recv_sems,   # (n,) per-source arrival
):
    """Reference ``allreduce_one_shot_push_intra_node_kernel``
    (``allreduce.py:365``): symmetric-buffer scatter of the full payload,
    then each rank reduces everything locally.  The reference reduces inside
    the same kernel with vectorized loads over the symmetric region; here the
    n slots are summed by one f32 emit_pipeline pass."""
    me, n = team.rank(), team.size
    # own partial into its slot (async local DMA; overlaps the barrier and
    # the remote pushes — the pushes read x_ref, not the slot, so the wire
    # never waits on this copy; the slot exists so the n-way reduction can
    # use static slot indices)
    local = dl.local_copy(x_ref, slots.at[me], local_sem)
    dl.collective_prologue(team)
    # push to every peer's slot[me] (static loop; ICI routes concurrently)
    for off in range(1, n):
        dst = jax.lax.rem(me + off, n)
        dl.remote_copy(
            x_ref, slots.at[me], send_sem, recv_sems.at[me],
            team.device_id(dst),
        )
    local.wait()
    for off in range(1, n):
        src = jax.lax.rem(me + n - off, n)
        dl.wait_recv(slots.at[src], recv_sems.at[src])
    reduce = blocks.make_sum_pipeline(n, m, r_dim, cfg.bm, cfg.bn, out_dtype)
    reduce(*[slots.at[i] for i in range(n)], out_ref)
    for _ in range(n - 1):  # drain sends off the critical path
        dl.wait_send(x_ref, send_sem)


def _ar_two_shot_kernel(
    team: Team,
    m_chunk: int,
    r_dim: int,
    cfg: AllReduceConfig,
    out_dtype,
    x_ref,        # (n*m_chunk, r) local partial addend         [ANY]
    out_ref,      # (n*m_chunk, r) full reduced result          [ANY]
    recv_buf,     # (2, m_chunk, r) incoming RS partials        [HBM scratch]
    send_buf,     # (2, m_chunk, r) outgoing RS accumulated     [HBM scratch]
    rs_send_sems,  # (2,) per-parity RS send completion
    rs_recv_sems,  # (2,) per-parity RS arrival
    ack_sems,      # (2,) RS consumption credits (REGULAR)
    ag_send_sem,   # AG phase sends
    ag_recv_sems,  # (n,) AG per-chunk arrival
):
    """Fused two-shot (reference ``allreduce_two_shot_push_intra_node_kernel``
    ``allreduce.py:477``): phase 1 is the ring ReduceScatter of
    ``comm/reduce_scatter.py`` with its final accumulation landing in
    out-chunk ``me``; phase 2 is the unidirectional AG ring of
    ``comm/allgather.py`` forwarding reduced chunks to their final offsets.
    Phases need no separating barrier — phase-1 writes only chunk ``me`` and
    every phase-2 consume is gated by its per-chunk DMA semaphore."""
    me, n = team.rank(), team.size
    left, right = team.neighbor_ranks()
    left_id, right_id = team.device_id(left), team.device_id(right)

    add = blocks.make_add_pipeline(m_chunk, r_dim, cfg.bm, cfg.bn)
    tosum = blocks.make_sum_pipeline(2, m_chunk, r_dim, cfg.bm, cfg.bn,
                                     out_dtype)

    def x_chunk(c):
        return _chunk(x_ref, c, m_chunk)

    dl.collective_prologue(team, neighbors_only=True)

    # ---- phase 1: ring ReduceScatter (comm/reduce_scatter.py flow) ----
    j0 = jax.lax.rem(me + n - 1, n)
    dl.remote_copy(x_chunk(j0), recv_buf.at[0], rs_send_sems.at[0],
                   rs_recv_sems.at[0], right_id)

    for s in range(1, n):
        j = jax.lax.rem(me + n - s - 1, n)   # chunk being accumulated here
        slot_in = (s - 1) % 2
        dl.wait_recv(recv_buf.at[slot_in], rs_recv_sems.at[slot_in])
        last = s == n - 1
        if last:
            # j == me here: the fully reduced chunk lands in its final
            # output offset (possibly with a dtype cast)
            tosum(recv_buf.at[slot_in], x_chunk(j), _chunk(out_ref, me, m_chunk))
        else:
            slot_out = s % 2
            if s >= 2:
                dl.wait_send(send_buf.at[slot_out], rs_send_sems.at[slot_out])
                dl.wait(ack_sems.at[slot_out], 1)
            add(recv_buf.at[slot_in], x_chunk(j), send_buf.at[slot_out])
            dl.remote_copy(send_buf.at[slot_out], recv_buf.at[slot_out],
                           rs_send_sems.at[slot_out],
                           rs_recv_sems.at[slot_out], right_id)
        dl.notify(ack_sems.at[slot_in], left_id)

    # ---- phase 2: ring AllGather of reduced chunks ----
    ring.ag_ring_phase(team, out_ref, m_chunk, ag_send_sem, ag_recv_sems,
                       right_id)

    # ---- drains (RS send accounting identical to comm/reduce_scatter.py) ----
    dl.wait_send(send_buf.at[0], rs_send_sems.at[0])
    if n > 2:
        dl.wait_send(send_buf.at[1], rs_send_sems.at[1])
    ring.rs_ack_drain(ack_sems, n)
    ring.ag_ring_drain(team, out_ref, m_chunk, ag_send_sem)


@functools.lru_cache(maxsize=None)
def _build_all_reduce(
    mesh: Mesh,
    axis: str,
    method: AllReduceMethod,
    m: int,
    r_dim: int,
    dtype: jnp.dtype,
    out_dtype: jnp.dtype,
    cfg: AllReduceConfig,
):
    team = Team.of(mesh, axis)
    n = team.size
    compilation.verify_protocol("allreduce", n)
    if method == AllReduceMethod.ONE_SHOT:
        kernel = functools.partial(_ar_one_shot_kernel, team, m, r_dim, cfg,
                                   out_dtype)
        scratch_shapes = [
            pltpu.HBM((n, m, r_dim), dtype),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((n,)),
        ]
    else:
        m_chunk = m // n
        kernel = functools.partial(_ar_two_shot_kernel, team, m_chunk, r_dim,
                                   cfg, out_dtype)
        scratch_shapes = [
            pltpu.HBM((2, m_chunk, r_dim), dtype),
            pltpu.HBM((2, m_chunk, r_dim), dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((n,)),
        ]
    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m, r_dim), out_dtype),
        in_specs=[pl.BlockSpec(memory_space=pl.ANY)],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=scratch_shapes,
        compiler_params=compilation.compiler_params(
            collective=True,
            collective_id=compilation.collective_id("allreduce"),
        ),
        interpret=compilation.interpret_mode(),
    )
    return compilation.jit_shard_map(
        call, mesh, in_specs=P(axis, None), out_specs=P(None, None)
    )


def hierarchical_all_reduce(
    x: jax.Array,
    mesh: Mesh,
    inner_axis: str,
    outer_axis: str,
    *,
    config: AllReduceConfig | None = None,
    wire_dtype: str = "bf16",
) -> jax.Array:
    """Two-level AllReduce (ICI RS ring -> DCN reduce of the 1/n_in
    partial -> ICI AG ring).  Canonical implementation:
    ``comm.hierarchical`` (ISSUE 10); this name stays importable here
    for the historic call sites."""
    from .hierarchical import hierarchical_all_reduce as _hier

    return _hier(x, mesh, inner_axis, outer_axis, config=config,
                 wire_dtype=wire_dtype)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _all_reduce_core(mesh, axis, method, out_dtype, cfg, x):
    n = mesh.shape[axis]
    fn = _build_all_reduce(
        mesh, axis, method, x.shape[0] // n, x.shape[1],
        jnp.dtype(x.dtype), out_dtype, cfg,
    )
    return fn(x)


def _ar_fwd(mesh, axis, method, out_dtype, cfg, x):
    return _all_reduce_core(mesh, axis, method, out_dtype, cfg, x), jnp.zeros((0,), x.dtype)


def _ar_bwd(mesh, axis, method, out_dtype, cfg, wit, dout):
    # global semantics: out = x.reshape(n, M, R).sum(0) (replicated) ->
    # the adjoint tiles the cotangent over the stacked partials
    n = mesh.shape[axis]
    return (jnp.tile(dout, (n, 1)).astype(wit.dtype),)


_all_reduce_core.defvjp(_ar_fwd, _ar_bwd)


def all_reduce(
    x: jax.Array,
    mesh: Mesh,
    axis: str = TP_AXIS,
    *,
    method: AllReduceMethod = AllReduceMethod.AUTO,
    config: AllReduceConfig | None = None,
    out_dtype=None,
    wire_dtype: str = "bf16",
) -> jax.Array:
    """Sum-AllReduce over ``axis`` (reference host entry ``all_reduce``,
    ``kernels/nvidia/allreduce.py:1054-1078``).

    ``x``: global ``(n*M, R)``, device r's shard = its (M, R) partial addend.
    Returns global ``(M, R)`` replicated on every device: the element-wise
    sum.  Golden: ``x.reshape(n, M, R).sum(0)``.

    Accumulation precision: ONE_SHOT sums all n partials in f32 in one pass;
    TWO_SHOT accumulates the n-1 ring steps in the wire (input) dtype with
    only the final combine in f32 — the standard ring-AR bandwidth/precision
    trade (NCCL rings and the reference's two-shot behave the same; carrying
    f32 partials would double the wire bytes for bf16).  Under AUTO, results
    for bf16 inputs therefore differ slightly across the size threshold.

    ``wire_dtype``: "bf16" (these kernels), "int8"/"fp8" (the quantized
    two-hop exchange — ``comm.quantized.quantized_all_reduce``; its
    error-feedback option lives on that entry), or "auto"
    (tuner-resolved per shape/ranks/wire class).

    ``axis`` may be a 2-tuple ``(outer, inner)`` on a 2D multi-slice
    mesh: routes to ``comm.hierarchical`` (RS ∘ AG, the DCN hop carrying
    1/n_in of the payload).
    """
    if isinstance(axis, (tuple, list)):
        from . import hierarchical

        outer_axis, inner_axis = axis
        return hierarchical.hierarchical_all_reduce(
            x, mesh, inner_axis, outer_axis, config=config,
            wire_dtype=wire_dtype)
    n = mesh.shape[axis]
    m_stack = x.shape[0]
    if m_stack % n:
        raise ValueError(f"dim0 {m_stack} not divisible by {axis}={n}")
    m = m_stack // n
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(x.dtype)
    if n == 1:
        return x.astype(out_dtype)
    if wire_dtype != "bf16":
        from ..tune.autotuner import is_tracer as _q_is_tracer
        from . import quantized as _q

        if wire_dtype == "auto":
            wire_dtype = _q.resolve_wire_dtype(
                "ar_wire", (tuple(x.shape), str(x.dtype)), mesh, axis,
                lambda wd: (lambda: all_reduce(x, mesh, axis,
                                               method=method, config=config,
                                               out_dtype=out_dtype,
                                               wire_dtype=wd)),
                tracing=_q_is_tracer(x),
            )
        if wire_dtype != "bf16":
            return _q.quantized_all_reduce(
                x, mesh, axis, wire_dtype=wire_dtype, out_dtype=out_dtype)

    if method == AllReduceMethod.AUTO:
        nbytes = int(jnp.dtype(x.dtype).itemsize) * m * x.shape[1]
        default = choose_method(nbytes, n)
        if m % n:
            # two-shot chunks rows n ways; not a viable candidate
            method = AllReduceMethod.ONE_SHOT
        else:
            # size threshold is only the default; the contextual tuner
            # resolves the one-shot/two-shot choice per shape class when
            # it may measure (VERDICT weak #7); wire class in the key
            # (ISSUE 10) so winners cannot leak across topologies
            from ..core import mesh as mesh_lib, platform
            from ..tune.autotuner import is_tracer, resolve_config

            cands = [AllReduceMethod.ONE_SHOT, AllReduceMethod.TWO_SHOT]
            # the A/B thunks PIN the default tiles: with config=None
            # each method candidate would recursively trigger its own
            # ar_cfg tile sweep below — tiles are tuned only for the
            # method that wins
            probe_cfg = config if config is not None else AllReduceConfig()
            method = resolve_config(
                "ar_method",
                (m, x.shape[1], str(x.dtype), n,
                 mesh_lib.wire_class(mesh, axis), platform.device_kind()),
                cands, default,
                lambda mth: (lambda: all_reduce(x, mesh, axis, method=mth,
                                                config=probe_cfg,
                                                out_dtype=out_dtype)),
                tracing=is_tracer(x),
            )
    if method == AllReduceMethod.TWO_SHOT and m % n:
        # two-shot chunks rows n ways; fall back rather than pad
        method = AllReduceMethod.ONE_SHOT

    from .. import obs, resilience
    from ..tune.autotuner import is_tracer

    rows = m // n if method == AllReduceMethod.TWO_SHOT else m
    if config is None:
        # the reduction-pipeline tiles ride the same contextual tuner as
        # the GEMM ops (VERDICT r5 next #5): a cached winner when one
        # exists (jit'd layers pick up what an eager/tuned run learned),
        # measured when transparent tuning may run, and the
        # interpret-pinned default otherwise (interpret-mode timings are
        # simulation artifacts — resolve_config already refuses them)
        from ..core import mesh as mesh_lib, platform
        from ..tune.autotuner import (
            collective_tile_candidates, resolve_config,
        )

        config = resolve_config(
            "ar_cfg",
            (m, x.shape[1], str(x.dtype), n, method.value,
             mesh_lib.wire_class(mesh, axis), platform.device_kind()),
            collective_tile_candidates(AllReduceConfig, rows, x.shape[1]),
            AllReduceConfig().clip(rows, x.shape[1]),
            lambda c: (lambda: all_reduce(x, mesh, axis, method=method,
                                          config=c, out_dtype=out_dtype)),
            tracing=is_tracer(x),
        )
    cfg = config.clip(rows, x.shape[1])
    partial = m * x.shape[1] * jnp.dtype(x.dtype).itemsize
    core = lambda: _all_reduce_core(mesh, axis, method, out_dtype,  # noqa: E731
                                    cfg, x)
    eager = not is_tracer(x)  # eager calls only (see all_gather)
    if eager and resilience.integrity.enabled():
        # consumer-side re-reduction check (TDT_INTEGRITY=1; see
        # reduce_scatter — detected-but-unattributable)
        core = resilience.integrity.checked(
            "all_reduce", core, ranks=n,
            verify=lambda out: resilience.integrity.verify_reduce(
                "all_reduce", x, out, n))
    if eager and resilience.enabled():
        core = resilience.guarded(
            "all_reduce", core, family="allreduce", ranks=n,
            payload_bytes=partial,
            fallback=lambda: resilience.fallbacks.xla_all_reduce(
                x, mesh, axis, out_dtype),
        )
    if eager and (obs.enabled() or obs.flight.enabled()):
        if method == AllReduceMethod.TWO_SHOT:
            # RS ring + AG ring, each n-1 hops of 1/n of the partial
            wire, chunks = 2 * (n - 1) * partial // n, 2 * (n - 1)
        else:
            # every rank receives n-1 whole partials
            wire, chunks = (n - 1) * partial, n - 1
        return obs.comm_call(
            "all_reduce", core,
            payload_bytes=partial, wire_bytes=wire, chunks=chunks,
            method=method.value, ranks=n,
        )
    return core()
