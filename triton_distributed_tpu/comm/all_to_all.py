"""EP (expert-parallel) All-to-All: MoE token dispatch and combine.

Reference: ``python/triton_dist/kernels/nvidia/low_latency_all_to_all.py``
— single-kernel A2A with per-peer ``putmem_nbi_block`` of exact byte
counts, split counts pushed alongside, parity double-buffered signal sets
(``all_to_all_kernel:36-120``); and ``ep_a2a.py:37-150`` (dispatch via
gathered splits + recv offsets, ``:244-310``).

TPU re-design — the parts land on different machinery:

- **splits / offsets** (a few ints per peer) ride ``lax.all_to_all``
  outside the kernel: latency-bound metadata is XLA-collective territory,
  and its arrival ORDERS the data kernel (the kernel consumes the
  exchanged counts, so no flag protocol is needed);
- **token payloads** (the bandwidth) move in a Pallas kernel as a traced
  NUMBER of fixed-shape row chunks per peer (dynamic ``fori_loop`` trip
  over static-size DMAs) — TPU descriptors need static shapes, so
  "variable length" becomes "variable chunk count", the moral equivalent
  of the reference's byte-exact ``putmem`` at chunk granularity;
- the parity double-buffer + signal-SET protocol collapses into DMA
  completion semaphores and the entry barrier (counting semantics,
  SURVEY.md section 7): every invocation's waits consume exactly that
  invocation's chunk arrivals, so repeated calls need no call_count.

Layouts (E experts total, epr = E/n per rank, rank r owns experts
[r*epr, (r+1)*epr)):

- dispatch in:  x (T, H) tokens SORTED by expert id; splits (E,) row
  counts per expert (reference keeps the same sorted+splits convention).
- dispatch out: recv (n, Z, H) landing zones by source rank (zone p holds
  the rows rank p sent me, padded to the chunk multiple) + recv_splits
  (n, epr): per-source per-owned-expert counts.
- combine in:   y (n, Z, H) processed tokens still in zone layout.
- combine out:  (T, H) rows back in the original sorted-by-expert order.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..core import compilation
from ..core.mesh import EP_AXIS
from ..lang import primitives as dl
from ..lang.primitives import Team


@dataclasses.dataclass(frozen=True)
class AllToAllConfig:
    chunk: int = 128   # rows per DMA descriptor (the static payload shape)
    # static peer-offset emission order (a permutation of range(n)); None
    # = the default stagger (offset p at step p).  The hierarchical
    # scheduled A2A (comm.hierarchical) passes the topology-derived
    # farthest-first order so long-path chunks launch before short-path
    # ones (the FAST chunk-schedule shape, arXiv:2505.09764).
    schedule: tuple[int, ...] | None = None


def _cdiv(a, b):
    return (a + b - 1) // b


def _a2a_push_kernel(
    team: Team,
    chunk: int,
    z: int,            # zone rows (chunk multiple)
    h: int,
    counts_ref,   # (n,) int32 rows to SEND to each peer          [SMEM]
    offs_ref,     # (n,) int32 row offset of each peer's rows in x [SMEM]
    expected_ref,  # (n,) int32 rows each peer sends ME            [SMEM]
    x_ref,        # source rows                                    [ANY]
    out_ref,      # (n, z, h) landing zones by source rank         [ANY]
    send_sem,
    recv_sems,    # (n,) per-source arrival
    *,
    schedule: tuple[int, ...] | None = None,
):
    """Push ``counts[p]`` rows (as ceil/chunk fixed-shape DMAs) to every
    peer ``p``'s zone ``me`` and wait for ``expected[p]`` rows from each —
    the shared body of dispatch and combine (combine swaps the count
    roles).  Zones are per-SOURCE, so the chunk round-up of one sender can
    never spill into another sender's rows — the reason both directions
    land in zones and exact packing is a local gather afterwards.

    ``schedule``: static peer-offset emission order (see
    ``AllToAllConfig.schedule``); waits are unordered by emission, so any
    permutation preserves the protocol (the registry's
    ``all_to_all/scheduled`` case proves it per rank count)."""
    me, n = team.rank(), team.size

    dl.collective_prologue(team)

    offsets = schedule if schedule is not None else tuple(range(n))
    total_sent = jnp.int32(0)
    for p in offsets:
        # stagger destinations so the ring isn't hot-spotted; a schedule
        # reorders the offsets, keeping the per-rank rotation
        dst = jax.lax.rem(me + jnp.int32(p), jnp.int32(n))
        cnt = counts_ref[dst]
        nch = _cdiv(cnt, chunk)

        def body(c, _, dst=dst):
            src = x_ref.at[pl.ds(offs_ref[dst] + c * chunk, chunk)]
            dst_ref = out_ref.at[me, pl.ds(c * chunk, chunk)]
            dl.remote_copy(src, dst_ref, send_sem, recv_sems.at[me],
                           team.device_id(dst))
            return 0

        jax.lax.fori_loop(0, nch, body, 0)
        total_sent += nch

    # wait for every peer's rows (chunk-count arrivals per source)
    for p in range(n):
        nch = _cdiv(expected_ref[p], chunk)

        def wait_body(c, _, p=p):
            dl.wait_recv(out_ref.at[p, pl.ds(0, chunk)], recv_sems.at[p])
            return 0

        jax.lax.fori_loop(0, nch, wait_body, 0)

    # drain sends off the critical path
    def drain(c, _):
        dl.wait_send(x_ref.at[pl.ds(0, chunk)], send_sem)
        return 0

    jax.lax.fori_loop(0, total_sent, drain, 0)


def _make_push_call(team: Team, chunk: int, z: int, h: int, n: int,
                    family: str, dtype: jnp.dtype,
                    schedule: tuple[int, ...] | None = None):
    compilation.verify_protocol(family, n)   # aliases to all_to_all
    from ..obs import costs

    kernel = functools.partial(_a2a_push_kernel, team, chunk, z, h,
                               schedule=schedule)
    return pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((n, z, h), dtype),
        # A2A moves up to n zones of z rows each through this device
        cost_estimate=costs.pallas_cost(
            costs.all_to_all(n * z, h, n, dtype)),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((n,)),
        ],
        compiler_params=compilation.compiler_params(
            collective=True,
            collective_id=compilation.collective_id(family),
        ),
        interpret=compilation.interpret_mode(),
    )


def _per_peer_meta(splits_loc, n: int, epr: int):
    """(counts to each peer, row offset of each peer's rows) from my
    expert-sorted splits."""
    per_peer = splits_loc.reshape(n, epr).sum(axis=1)
    offs = jnp.concatenate(
        [jnp.zeros((1,), jnp.int32), jnp.cumsum(per_peer)[:-1]]
    ).astype(jnp.int32)
    return per_peer.astype(jnp.int32), offs


@functools.lru_cache(maxsize=None)
def _build_dispatch(mesh: Mesh, axis: str, t_pad: int, h: int, epr: int,
                    chunk: int, z: int, dtype: jnp.dtype,
                    schedule: tuple[int, ...] | None = None):
    team = Team.of(mesh, axis)
    n = team.size
    call = _make_push_call(team, chunk, z, h, n, "ep_dispatch", dtype,
                           schedule)

    def local_fn(x_loc, splits_loc):
        per_peer, offs = _per_peer_meta(splits_loc, n, epr)
        # tiny metadata exchange; also ORDERS the data kernel after it
        expected = jax.lax.all_to_all(per_peer, axis, 0, 0)        # (n,)
        recv_splits = jax.lax.all_to_all(
            splits_loc.reshape(n, epr), axis, 0, 0
        )                                                          # (n, epr)
        recv = call(per_peer, offs, expected.astype(jnp.int32), x_loc)
        return recv, recv_splits.astype(jnp.int32)

    return compilation.jit_shard_map(
        local_fn, mesh,
        in_specs=(P(axis, None), P(axis)),
        out_specs=(P(axis, None, None), P(axis, None)),
    )


@functools.lru_cache(maxsize=None)
def _build_combine(mesh: Mesh, axis: str, h: int, epr: int,
                   chunk: int, z: int, t: int, dtype: jnp.dtype,
                   schedule: tuple[int, ...] | None = None):
    team = Team.of(mesh, axis)
    n = team.size
    call = _make_push_call(team, chunk, z, h, n, "ep_combine", dtype,
                           schedule)

    def local_fn(y_loc, splits_loc):
        # roles reversed: I send zone p's rows (expected[p] of them) back
        # to p, landing in p's RETURN zone for me; p repacks locally.
        per_peer, offs = _per_peer_meta(splits_loc, n, epr)
        expected = jax.lax.all_to_all(per_peer, axis, 0, 0)
        zone_offs = (jnp.arange(n, dtype=jnp.int32) * z)
        back = call(expected.astype(jnp.int32), zone_offs, per_peer,
                    y_loc.reshape(n * z, h))
        # exact repack (local gather): sorted row r came back in zone p at
        # position r - offs[p], where p is r's destination peer
        ridx = jnp.arange(t)
        cum = jnp.cumsum(per_peer)
        p_of = jnp.searchsorted(cum, ridx, side="right")
        p_of = jnp.clip(p_of, 0, n - 1)
        within = ridx - jnp.take(offs, p_of)
        return jnp.take(back.reshape(n * z, h), p_of * z + within, axis=0)

    return compilation.jit_shard_map(
        local_fn, mesh,
        in_specs=(P(axis, None, None), P(axis)),
        out_specs=P(axis, None),
    )


def _round_up(x: int, m: int) -> int:
    return (x + m - 1) // m * m


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _ep_dispatch_diff(mesh, axis, cfg, x, splits):
    return _ep_dispatch_run(mesh, axis, cfg, x, splits)


def _ep_dispatch_fwd(mesh, axis, cfg, x, splits):
    out = _ep_dispatch_diff(mesh, axis, cfg, x, splits)
    return out, (splits, x.shape[0] // mesh.shape[axis],
                 jnp.zeros((0,), x.dtype))


def ep_dispatch_adjoint(d_recv, splits, mesh, axis, *, token_dim,
                        config=None):
    """Pull a cotangent on dispatch's ``recv`` zones back onto ``x``: the
    combine, with PADDING TOKEN rows masked to zero (combine's repack
    clips rows beyond each rank's real token count onto the last peer's
    zone tail, gathering chunk-rounded DMA spillover; a padding row never
    left its rank in the forward).  Exposed for straight-through
    estimators over quantized payloads (``layers.moe`` fp8 wire)."""
    cfg = config or AllToAllConfig()
    dx = _ep_combine_diff(mesh, axis, cfg, token_dim, d_recv, splits)
    n = mesh.shape[axis]
    if n > 1:
        totals = splits.reshape(n, -1).sum(-1)            # real rows/rank
        rows = jnp.arange(token_dim, dtype=totals.dtype)
        keep = (rows[None, :] < totals[:, None]).reshape(n * token_dim)
        dx = jnp.where(keep[:, None], dx, 0).astype(dx.dtype)
    return dx


def ep_combine_adjoint(dback, splits, mesh, axis, *, config=None):
    """Pull a cotangent on combine's token output back onto the zone
    layout: the dispatch, with PADDING ZONE rows masked to zero (see
    :func:`ep_dispatch_adjoint`; dispatch's chunk-rounded DMAs drag
    neighboring rows into zone tails)."""
    cfg = config or AllToAllConfig()
    dy, _ = _ep_dispatch_diff(mesh, axis, cfg, dback, splits)
    n = mesh.shape[axis]
    if n > 1:
        epr = splits.shape[0] // (n * n)
        sent = splits.reshape(n, n, epr).sum(-1)          # [src, dst]
        valid = sent.T.reshape(n * n)                     # [dst*n + src]
        rows = jnp.arange(dy.shape[1], dtype=valid.dtype)
        dy = jnp.where(
            rows[None, :, None] < valid[:, None, None], dy, 0
        ).astype(dy.dtype)
    return dy


def _ep_dispatch_bwd(mesh, axis, cfg, res, cots):
    # dispatch is a selection matrix S (each real token row lands in
    # exactly one zone slot); its adjoint S^T is literally the combine
    # (padding-masked, see ep_dispatch_adjoint)
    import numpy as np

    splits, t_loc, wit = res
    d_recv, _ = cots   # recv_splits is integer output -> float0, dropped
    dx = ep_dispatch_adjoint(d_recv.astype(wit.dtype), splits, mesh, axis,
                             token_dim=t_loc, config=cfg)
    return dx, np.zeros(splits.shape, dtype=jax.dtypes.float0)


_ep_dispatch_diff.defvjp(_ep_dispatch_fwd, _ep_dispatch_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _ep_combine_diff(mesh, axis, cfg, token_dim, y, splits):
    return _ep_combine_run(mesh, axis, cfg, token_dim, y, splits)


def _ep_combine_fwd(mesh, axis, cfg, token_dim, y, splits):
    return _ep_combine_diff(mesh, axis, cfg, token_dim, y, splits), (
        splits, jnp.zeros((0,), y.dtype)
    )


def _ep_combine_bwd(mesh, axis, cfg, token_dim, res, dback):
    # combine = S^T, so its adjoint is the dispatch itself, zone-padding-
    # masked (see ep_combine_adjoint; routed via the differentiable
    # wrapper inside so second-order AD keeps working)
    import numpy as np

    splits, wit = res
    dy = ep_combine_adjoint(dback.astype(wit.dtype), splits, mesh, axis,
                            config=cfg)
    return dy, np.zeros(splits.shape, dtype=jax.dtypes.float0)


_ep_combine_diff.defvjp(_ep_combine_fwd, _ep_combine_bwd)


def _resolve_a2a_config(name: str, t: int, h: int, dtype, mesh, axis: str,
                        tracing: bool, make_thunk) -> AllToAllConfig:
    """``config=None`` hook of the EP all-to-all entries: the chunk
    sweep (``tune.autotuner.a2a_chunk_candidates``) resolved through the
    shared machinery — cached winner if one exists (jit'd layer calls
    included), measured when transparent tuning may run, the
    interpret-pinned 128-row default otherwise.  The contextual key
    carries the axis's WIRE CLASS (ISSUE 10): a chunk size crowned on the
    ICI torus must never leak onto a DCN edge, whose latency/bandwidth
    point favors different descriptor granularity."""
    from ..core import mesh as mesh_lib, platform
    from ..tune.autotuner import a2a_chunk_candidates, resolve_config

    n = mesh.shape[axis]
    cands = a2a_chunk_candidates(AllToAllConfig, t)
    return resolve_config(
        name,
        (t, h, str(dtype), n, mesh_lib.wire_class(mesh, axis),
         platform.device_kind()),
        cands, cands[0], make_thunk, tracing=tracing,
    )


def ep_dispatch(
    x: jax.Array,
    splits: jax.Array,
    mesh: Mesh,
    axis: str = EP_AXIS,
    *,
    config: AllToAllConfig | None = None,
    wire_dtype: str = "bf16",
):
    """Dispatch sorted tokens to their expert-owner ranks (reference
    ``all_to_all_single`` host entry ``low_latency_all_to_all.py:183-198``,
    ``ep_a2a.py:37-150``).

    ``wire_dtype``: "bf16" ships the model dtype; "int8"/"fp8" pack each
    row into the shared quantized wire message (payload + scale sidecar,
    ``lang.quant``) and dequantize on arrival — the reference's
    production fp8 A2A configuration; "auto" resolves through the
    contextual tuner per shape/ranks/wire class.  (The differentiable
    straight-through transports live in ``comm.quantized``; this entry's
    quantized path is forward-only.)

    ``x``: global (n*T, H) over ``axis`` — each rank's (T, H) shard holds
    its tokens sorted by expert id (T = static worst case, rows beyond the
    real token count are padding).  ``splits``: global (n*E,) int32 — each
    rank's (E,) per-expert row counts (padding rows NOT counted).

    Returns ``(recv, recv_splits)``: ``recv`` global (n*n, Z, H) — rank
    r's slab ``recv[r*n:(r+1)*n]`` is its n landing zones by source rank;
    ``recv_splits`` global (n*n, epr) — rank r's block gives, per source
    rank, the counts for each of r's own experts.  Differentiable in
    ``x`` (the adjoint is :func:`ep_combine`).
    """
    from .. import obs, resilience
    from ..tune.autotuner import is_tracer

    if isinstance(axis, (tuple, list)):
        # 2D-mesh routing (ISSUE 10): axis=(outer, inner) — outermost
        # first, matching the mesh axis order — runs the topology-
        # scheduled two-level A2A (DCN phase first, scheduled ICI phase
        # pipelining underneath)
        from . import hierarchical

        outer_axis, inner_axis = axis
        return hierarchical.scheduled_ep_dispatch(
            x, splits, mesh, inner_axis, outer_axis, config=config,
            wire_dtype=wire_dtype)
    n = mesh.shape[axis]
    t = x.shape[0] // max(n, 1)
    eager = not (is_tracer(x) or is_tracer(splits))
    if wire_dtype != "bf16" and n > 1:
        from ..lang import quant
        from . import quantized as _q

        if wire_dtype == "auto":
            wire_dtype = _q.resolve_wire_dtype(
                "a2a_wire", (tuple(x.shape), str(x.dtype)), mesh, axis,
                lambda wd: (lambda: ep_dispatch(x, splits, mesh, axis,
                                                config=config,
                                                wire_dtype=wd)),
                tracing=not eager,
            )
        if wire_dtype != "bf16":
            h = x.shape[-1]
            recv_u8, recv_splits = ep_dispatch(
                quant.pack_rows(x, wire_dtype), splits, mesh, axis,
                config=config)
            return (quant.unpack_rows(recv_u8, h, wire_dtype, x.dtype),
                    recv_splits)
    if config is None and n > 1:
        # chunk size through the contextual tuner (VERDICT r5 next #5):
        # cached winner / measured / interpret-pinned default — the
        # config=None path consults the same winner cache the GEMM ops do
        config = _resolve_a2a_config("ep_dispatch_cfg", t, x.shape[1],
                                     x.dtype, mesh, axis, not eager,
                                     lambda c: (lambda: ep_dispatch(
                                         x, splits, mesh, axis, config=c)))
    cfg = config or AllToAllConfig()
    payload = t * x.shape[1] * jnp.dtype(x.dtype).itemsize
    core = lambda: _ep_dispatch_diff(mesh, axis, cfg, x, splits)  # noqa: E731
    if eager and resilience.integrity.enabled():
        # consumer-side checksum verification (TDT_INTEGRITY=1): zones
        # land row blocks verbatim — fold-exact, peer-attributable
        core = resilience.integrity.checked(
            "ep_dispatch", core, ranks=n,
            verify=lambda out: resilience.integrity.verify_ep_dispatch(
                "ep_dispatch", x, splits, out, n))
    if eager and resilience.enabled():
        # the FULL ladder (ISSUE 7 satellite; PR 3 left these
        # watchdog-only): retry -> degraded zone-layout gather
        # (fallbacks.xla_ep_dispatch) -> breaker, uniform with the
        # other 6 entry points
        core = resilience.guarded(
            "ep_dispatch", core, family="all_to_all", ranks=n,
            payload_bytes=payload,
            fallback=lambda: resilience.fallbacks.xla_ep_dispatch(
                x, splits, mesh, axis, config=cfg),
        )
    if eager and (obs.enabled() or obs.flight.enabled()):
        chunk = min(cfg.chunk, _round_up(max(t, 1), 8))
        return obs.comm_call(
            "ep_dispatch", core,
            # wire: static upper bound — every local token leaves the
            # rank (true counts live in `splits`, a device array)
            payload_bytes=payload, wire_bytes=payload,
            chunks=_cdiv(max(t, 1), chunk),
            method=f"push_chunk{chunk}", ranks=n,
        )
    return core()


def _ep_dispatch_run(mesh, axis, cfg, x, splits):
    n = mesh.shape[axis]
    tn, h = x.shape
    if tn % n:
        raise ValueError(f"token dim {tn} not divisible by {axis}={n}")
    t = tn // n
    e_tot = splits.shape[0] // n
    if splits.shape[0] % n or e_tot % n:
        raise ValueError(
            f"splits {splits.shape} must be (n*E,) with E divisible by n"
        )
    epr = e_tot // n
    if n == 1:
        return (
            x.reshape(1, t, h),
            splits.reshape(1, e_tot)[:, :epr],
        )
    chunk = min(cfg.chunk, _round_up(t, 8))
    z = _round_up(t, chunk) + chunk   # worst case: every token to one peer
    t_pad = _round_up(t, chunk) + chunk
    x_p = jnp.pad(x.reshape(n, t, h), ((0, 0), (0, t_pad - t), (0, 0)))
    x_p = x_p.reshape(n * t_pad, h)
    fn = _build_dispatch(mesh, axis, t_pad, h, epr, chunk, z,
                         jnp.dtype(x.dtype), cfg.schedule)
    recv, recv_splits = fn(x_p, splits.astype(jnp.int32))
    return recv.reshape(n * n, z, h), recv_splits.reshape(n * n, epr)


def ep_combine(
    y: jax.Array,
    splits: jax.Array,
    mesh: Mesh,
    axis: str = EP_AXIS,
    *,
    token_dim: int,
    config: AllToAllConfig | None = None,
    wire_dtype: str = "bf16",
) -> jax.Array:
    """Return processed tokens to their owner ranks, restoring the original
    sorted-by-expert order (reference combine path ``ep_a2a.py:244-310``).

    ``y``: global (n*n, Z, H) — the zone layout ``ep_dispatch`` produced
    (rows processed in place).  ``splits``: the SAME global (n*E,) given to
    dispatch.  ``token_dim``: T, the per-rank token row count.  Returns
    global (n*T, H) over ``axis``.  Differentiable in ``y`` (the adjoint
    is :func:`ep_dispatch`).  ``wire_dtype``: see :func:`ep_dispatch`
    (quantized path forward-only here; STE transports in
    ``comm.quantized``).
    """
    from .. import obs, resilience
    from ..tune.autotuner import is_tracer

    if isinstance(axis, (tuple, list)):
        # 2D-mesh routing (ISSUE 10): see ep_dispatch
        from . import hierarchical

        outer_axis, inner_axis = axis
        return hierarchical.scheduled_ep_combine(
            y, splits, mesh, inner_axis, outer_axis, token_dim=token_dim,
            config=config, wire_dtype=wire_dtype)
    n = mesh.shape[axis]
    eager = not (is_tracer(y) or is_tracer(splits))
    if wire_dtype != "bf16" and n > 1:
        from ..lang import quant
        from . import quantized as _q

        if wire_dtype == "auto":
            wire_dtype = _q.resolve_wire_dtype(
                "a2a_wire", (tuple(y.shape), str(y.dtype)), mesh, axis,
                lambda wd: (lambda: ep_combine(y, splits, mesh, axis,
                                               token_dim=token_dim,
                                               config=config,
                                               wire_dtype=wd)),
                tracing=not eager,
            )
        if wire_dtype != "bf16":
            h = y.shape[-1]
            back_u8 = ep_combine(
                quant.pack_rows(y, wire_dtype), splits, mesh, axis,
                token_dim=token_dim, config=config)
            return quant.unpack_rows(back_u8, h, wire_dtype, y.dtype)
    if config is None and n > 1:
        # see ep_dispatch: the chunk sweep shares the tuner machinery
        config = _resolve_a2a_config("ep_combine_cfg", token_dim,
                                     y.shape[-1], y.dtype, mesh, axis,
                                     not eager,
                                     lambda c: (lambda: ep_combine(
                                         y, splits, mesh, axis,
                                         token_dim=token_dim, config=c)))
    cfg = config or AllToAllConfig()
    payload = token_dim * y.shape[-1] * jnp.dtype(y.dtype).itemsize
    core = lambda: _ep_combine_diff(mesh, axis, cfg, token_dim, y,  # noqa: E731
                                    splits)
    if eager and resilience.integrity.enabled():
        # consumer-side checksum verification (see ep_dispatch)
        core = resilience.integrity.checked(
            "ep_combine", core, ranks=n,
            verify=lambda out: resilience.integrity.verify_ep_combine(
                "ep_combine", y, splits, out, n, token_dim))
    if eager and resilience.enabled():
        # the FULL ladder, uniform with ep_dispatch (ISSUE 7 satellite)
        core = resilience.guarded(
            "ep_combine", core, family="all_to_all", ranks=n,
            payload_bytes=payload,
            fallback=lambda: resilience.fallbacks.xla_ep_combine(
                y, splits, mesh, axis, token_dim=token_dim, config=cfg),
        )
    if eager and (obs.enabled() or obs.flight.enabled()):
        chunk = min(cfg.chunk, _round_up(max(token_dim, 1), 8))
        return obs.comm_call(
            "ep_combine", core,
            payload_bytes=payload, wire_bytes=payload,
            chunks=_cdiv(max(token_dim, 1), chunk),
            method=f"push_chunk{chunk}", ranks=n,
        )
    return core()


def _ep_combine_run(mesh, axis, cfg, token_dim, y, splits):
    n = mesh.shape[axis]
    if n == 1:
        return y.reshape(-1, y.shape[-1])[:token_dim]
    nz, z, h = y.shape
    if nz != n * n:
        raise ValueError(f"zone dim {nz} != n*n = {n * n}")
    e_tot = splits.shape[0] // n
    epr = e_tot // n
    t = token_dim
    chunk = min(cfg.chunk, _round_up(t, 8))
    fn = _build_combine(mesh, axis, h, epr, chunk, z, t, jnp.dtype(y.dtype),
                        cfg.schedule)
    return fn(y, splits.astype(jnp.int32))
