"""Shared ring-phase building blocks for collective kernels.

One home for the two most delicate, previously copy-pasted pieces of the
collective kernels (the semaphore/drain accounting differs by ring size and
MUST stay identical everywhere it is used):

- the unidirectional AllGather forward ring (``allgather._ag_ring_kernel``,
  phase 2 of two-shot AllReduce and of fused GEMM+AR);
- the ACK-credit drain accounting of the ring ReduceScatter family
  (``reduce_scatter``, ``gemm_rs``, two-shot AllReduce, fused GEMM+AR).

Reference analogue: the per-tile barrier/flag bookkeeping shared across
``reduce_scatter.py`` / ``gemm_reduce_scatter.py`` / ``allreduce.py`` in
``python/triton_dist/kernels/nvidia/``.
"""

from __future__ import annotations

import jax
from jax.experimental import pallas as pl

from ..lang import primitives as dl
from ..lang.primitives import Team


def chunk(ref, idx, m):
    """Rows ``[idx*m, (idx+1)*m)`` of ``ref`` (dim-0 chunk view)."""
    return ref.at[pl.ds(idx * m, m)]


def ag_ring_phase(team: Team, out_ref, m: int, send_sem, recv_sems, right_id):
    """Unidirectional AG ring over chunks already placed at final offsets.

    Precondition: out-chunk ``me`` holds this rank's contribution.  Each of
    the n-1 steps forwards the chunk received last step (step 0: own chunk)
    to the right neighbor and waits for the incoming one.  Pair with
    :func:`ag_ring_drain` after the last consume.
    """
    me, n = team.rank(), team.size
    for step in range(n - 1):
        c_send = jax.lax.rem(me + n - step, n)
        dl.remote_copy(
            chunk(out_ref, c_send, m), chunk(out_ref, c_send, m),
            send_sem, recv_sems.at[c_send], right_id,
        )
        c_recv = jax.lax.rem(me + n - step - 1, n)
        dl.wait_recv(chunk(out_ref, c_recv, m), recv_sems.at[c_recv])


def ag_ring_drain(team: Team, out_ref, m: int, send_sem):
    """Drain the n-1 sends of :func:`ag_ring_phase` off the critical path."""
    me, n = team.rank(), team.size
    for _ in range(n - 1):
        dl.wait_send(chunk(out_ref, me, m), send_sem)


def bidir_ring_phase(team: Team, out_ref, m: int, send_sems, recv_sems,
                     consume=None):
    """Bidirectional AG ring over chunks at final offsets: the clockwise
    stream carries ceil((n-1)/2) chunks, the counter-clockwise
    floor((n-1)/2), using both ICI directions.  Forwarding happens
    immediately after each arrival gate and BEFORE ``consume`` (the fused
    ops' matmul), so the next transfer in each direction rides under the
    current chunk's compute.  ``consume(r)`` is called per chunk in arrival
    order (own chunk first); pass None for a pure collective.  Pair with
    :func:`bidir_ring_drain`.

    Precondition: out-chunk ``me`` holds this rank's contribution.
    """
    me, n = team.rank(), team.size
    left, right = team.neighbor_ranks()
    left_id, right_id = team.device_id(left), team.device_id(right)
    n_cw = (n - 1 + 1) // 2   # chunks arriving clockwise (from the left)
    n_ccw = (n - 1) // 2

    def send(r, sem_idx, dst_id):
        dl.remote_copy(chunk(out_ref, r, m), chunk(out_ref, r, m),
                       send_sems.at[sem_idx], recv_sems.at[r], dst_id)

    if n_cw >= 1:
        send(me, 0, right_id)
    if n_ccw >= 1:
        send(me, 1, left_id)
    if consume is not None:
        consume(me)
    for step in range(max(n_cw, n_ccw)):
        if step < n_cw:
            r = jax.lax.rem(me + n - step - 1, n)
            dl.wait_recv(chunk(out_ref, r, m), recv_sems.at[r])
            if step + 1 < n_cw:   # travels further clockwise
                send(r, 0, right_id)
            if consume is not None:
                consume(r)
        if step < n_ccw:
            r = jax.lax.rem(me + step + 1, n)
            dl.wait_recv(chunk(out_ref, r, m), recv_sems.at[r])
            if step + 1 < n_ccw:
                send(r, 1, left_id)
            if consume is not None:
                consume(r)


def bidir_ring_drain(team: Team, out_ref, m: int, send_sems):
    """Drain the n_cw + n_ccw sends of :func:`bidir_ring_phase`."""
    me, n = team.rank(), team.size
    n_cw = (n - 1 + 1) // 2
    n_ccw = (n - 1) // 2
    for _ in range(n_cw):
        dl.wait_send(chunk(out_ref, me, m), send_sems.at[0])
    for _ in range(n_ccw):
        dl.wait_send(chunk(out_ref, me, m), send_sems.at[1])


def gemm_rs_chunk_phase(team: Team, b: int, mm, add, a_ref, w_chunk,
                        out_ref, mm_buf, recv_buf, send_buf, send_sems,
                        recv_sems, ack_sems, acc_ref, right_id, left_id):
    """The travelling-partial phase of the column-chunked GEMM +
    two-shot-AllReduce kernels — ONE home for the delicate slot/ack
    accounting (the PR-9 "one home" discipline): the standalone
    ``ops.fused_decode._fused_mlp_ar_kernel`` and every chained instance
    of ``ops.persistent_decode._chained_ar`` run THIS body.

    ``mm(a, w, out, scratches=[acc_ref])`` computes one (B, cn) chunk
    GEMM; ``w_chunk(j)`` returns weight-column chunk j; ``add`` folds
    the travelling partial.  Ring step s's chunk GEMM computes while
    step s-1's partial is on the wire, chained through the DMA/ack
    semaphores — control never returns to the host.  The fully reduced
    chunk ``me`` lands at its replicated offset of ``out_ref``.  Pair
    with :func:`gemm_rs_send_drain` (+ an AG phase) and, per the
    caller's chaining policy, :func:`rs_ack_drain` — the persistent
    chain defers that drain to the NEXT instance's armed waits."""
    me, n = team.rank(), team.size
    j0 = jax.lax.rem(me + n - 1, n)
    mm(a_ref, w_chunk(j0), mm_buf.at[0], scratches=[acc_ref])
    dl.remote_copy(mm_buf.at[0], recv_buf.at[0], send_sems.at[0],
                   recv_sems.at[0], right_id)
    for s in range(1, n):
        j = jax.lax.rem(me + n - s - 1, n)
        slot_in = (s - 1) % 2
        slot_out = s % 2
        if s == 2:
            dl.wait_send(mm_buf.at[0], send_sems.at[0])
        mm(a_ref, w_chunk(j), mm_buf.at[slot_out], scratches=[acc_ref])
        dl.wait_recv(recv_buf.at[slot_in], recv_sems.at[slot_in])
        last = s == n - 1
        if last:
            # chunk ``me`` fully reduced: land at its replicated offset
            add(recv_buf.at[slot_in], mm_buf.at[slot_out],
                chunk(out_ref, me, b))
        else:
            if s >= 3:
                dl.wait_send(send_buf.at[slot_out], send_sems.at[slot_out])
            if s >= 2:
                dl.wait(ack_sems.at[slot_out], 1)
            add(recv_buf.at[slot_in], mm_buf.at[slot_out],
                send_buf.at[slot_out])
            dl.remote_copy(send_buf.at[slot_out], recv_buf.at[slot_out],
                           send_sems.at[slot_out], recv_sems.at[slot_out],
                           right_id)
        dl.notify(ack_sems.at[slot_in], left_id)


def gemm_rs_send_drain(n: int, send_buf, send_sems):
    """Drain the outstanding sends of :func:`gemm_rs_chunk_phase` (the
    slot parity depends on the ring size; ``send_buf.at[k]`` shapes the
    element count, which also covers the pre-loop mm_buf send)."""
    if n == 2:
        dl.wait_send(send_buf.at[0], send_sems.at[0])
    elif n == 3:
        dl.wait_send(send_buf.at[1], send_sems.at[1])
    else:
        dl.wait_send(send_buf.at[0], send_sems.at[0])
        dl.wait_send(send_buf.at[1], send_sems.at[1])


def rs_ack_drain(ack_sems, n: int):
    """Consume the outstanding ACK credits of a ring-RS at kernel exit.

    The in-loop ``wait(ack_sems[slot_out])`` at steps ``s >= 2`` covered the
    credits for sends 0..n-4; the credits for the last two sends (one when
    n == 2) arrive after the loop and must be consumed so repeated
    invocations start balanced.
    """
    if n == 2:
        dl.wait(ack_sems.at[0], 1)
    else:
        dl.wait(ack_sems.at[(n - 3) % 2], 1)
        dl.wait(ack_sems.at[(n - 2) % 2], 1)
