"""Contextual autotuner (reference: ``python/triton_dist/autotuner.py``)."""

from .autotuner import (
    Autotuner,
    TuneResult,
    autotune,
    fresh_tune_persistent_decode,
    lookup_winner,
    matmul_tile_candidates,
    resolve_config,
    transparent_tuning_enabled,
    tuned_ag_gemm,
    tuned_gemm_rs,
    tuned_matmul,
)
