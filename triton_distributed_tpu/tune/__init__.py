"""Contextual autotuner (reference: ``python/triton_dist/autotuner.py``)."""

from .autotuner import (
    Autotuner,
    TuneResult,
    autotune,
    matmul_tile_candidates,
    tuned_ag_gemm,
    tuned_gemm_rs,
    tuned_matmul,
)
