"""Contextual autotuner: measure candidate configs on the real device,
agree across processes, persist winners.

Reference: ``python/triton_dist/autotuner.py:97-256`` — the ``@autotune``
decorator times each candidate config on the first real invocation
(`contextual`: with the caller's actual tensors), synchronizes the choice
across ranks, and caches per call-site key.

TPU translation: candidates are whole JITTED THUNKS (a config change means
a different Pallas grid, so the unit of timing is the compiled executable,
not a kernel variant), timed with the slope method (``core.utils.perf_func``
— robust to tunneled-backend sync cost).  Cross-process agreement takes the
ALL-RANK MEAN of each candidate's time via ``jax.lax.pmean`` over a 1-chip
mesh collective when multiple processes exist (every process must pick the
same config or collective kernels would disagree on grids); single-process
runs skip it.  Winners persist to a JSON cache keyed by (name, shapes,
dtype, device kind) so steady-state serving never re-tunes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Any, Callable, Iterable, Sequence

import jax

from ..core import platform
from ..core.utils import dist_print, interleaved_time_samples

_DEFAULT_CACHE = os.path.join(
    os.path.expanduser("~"), ".cache", "triton_distributed_tpu",
    "autotune.json",
)


def cache_path() -> str:
    return os.environ.get("TDT_AUTOTUNE_CACHE", _DEFAULT_CACHE)


@dataclasses.dataclass(frozen=True)
class XlaBackend:
    """Dispatch-to-XLA candidate for GEMM-shaped ops.

    The reference's kernels compete with cuBLAS and fall back to it where
    the hand-written kernel loses; on TPU the analogue is XLA's own MXU
    GEMM, optionally compiled with a tuned scoped-VMEM budget
    (``core.compilation.xla_gemm_options``).  ``scoped_vmem_kib=0`` means
    default compile flags.  A crowned ``XlaBackend`` makes the op dispatch
    to ``jnp.matmul`` / ``lax.ragged_dot`` — as its own jitted computation
    (carrying the options) when called eagerly, inlined into the caller's
    trace (options cannot attach) under jit.
    """

    scoped_vmem_kib: int = 0


# Scoped-VMEM points for EXPLICIT XlaBackend configs: 32/64/112 MiB.
# NOT in the default sweeps: interleaved A/B of mixed-flag executables
# produces spectacular artifacts in BOTH directions (the same pair
# measured 0.82x-1.6x across processes/chip states) while ABA PHASE
# tests show no steady-state effect at the dense shapes — the
# "wins" are properties of alternating the executables, not of serving
# either one, so crowning them turns captures into a lottery.  The
# constants remain for explicit configs on toolchains where a raised
# budget has a real solo effect.
XLA_VMEM_SWEEP_KIB = (32768, 65536, 114688)

# A challenger only dethrones the default when it wins by this margin —
# tunnel noise exceeds true near-tie differences, and a persisted
# mis-crown costs every later run (the round-3 bench regression).  Flag
# variants get the STIFFER margin: mixed-flag interleaving has produced
# one-off artifacts (0.6x-2.1x for the same pair across processes) — a
# flag crown must survive both the sweep and the confirmation pass
# (``tune(fresh=...)``) to stick.
PALLAS_MARGIN = 0.08
XLA_FLAG_MARGIN = 0.10

# FRESH single-process tunes get a far finer margin: the crown is about
# to be USED in this process and every non-default crown is re-validated
# by the head-to-head confirmation pass (7 interleaved rounds, 0.4 s
# windows) before it sticks.  The conservative margins above exist to
# protect PERSISTED winners measured once from noise; with a
# confirmation pass the asymmetry flips — a mis-crown costs at most the
# confirm threshold (~1-2%), while a blocked genuine win costs the full
# measured gap (round-4 sweeps: scoped-VMEM XLA and big-tile Pallas
# candidates beat default XLA by a CONSISTENT 3-10% at the dense bench
# shapes, all under the old 8-10% gate).
FRESH_SWEEP_MARGIN = 0.015
FRESH_CONFIRM_MARGIN = 0.01


def margin_for(candidate) -> float:
    return (XLA_FLAG_MARGIN if isinstance(candidate, XlaBackend)
            else PALLAS_MARGIN)


def xla_backend_candidates() -> list:
    """The shared XLA-dispatch prefix of every backend sweep — the
    default-flag never-lose baseline ONLY (see XLA_VMEM_SWEEP_KIB for
    why the flag variants are excluded); single-sourced so a change
    reaches every dispatching op at once."""
    return [XlaBackend(0)]


@dataclasses.dataclass
class TuneResult:
    config: Any
    time_ms: float
    from_cache: bool
    # speed-of-light fraction of the winner (sol_ms / time), when the
    # caller supplied a model estimate and a fresh measurement ran
    sol_fraction: float | None = None


def _cands_digest(candidates: Sequence[Any]) -> str:
    """Fingerprint of the candidate list: persisted winners are INDICES, so
    a changed sweep must miss the cache instead of silently re-pointing an
    old index at a different config."""
    import hashlib

    return hashlib.sha1(
        str([str(c) for c in candidates]).encode()
    ).hexdigest()[:8]


def _cache_key(name: str, key: Sequence[Any],
               candidates: Sequence[Any]) -> str:
    return json.dumps([name, _cands_digest(candidates), *map(str, key)])


class Autotuner:
    """Process-wide tuner with a persistent JSON winner cache."""

    def __init__(self, path: str | None = None):
        self._path = path
        self._mem: dict[str, int] = {}
        self._times: dict[str, float] = {}
        self._lock = threading.Lock()
        self._disk: dict[str, int] | None = None
        # resolved-config fast path: (name, key) -> config.  An eager op
        # call in a hot loop must not pay the candidates-digest/JSON cache
        # key on every invocation (measured 228 us/call vs 24 us for the
        # bare jit dispatch — enough to starve the device queue in timed
        # windows).  Only SETTLED resolutions are memoized (a cached
        # winner or a fresh measurement), never the tracing/disabled
        # default fallthrough, so a later planted winner is still seen.
        self._resolved: dict = {}

    # -- persistence ------------------------------------------------------

    def _load_disk(self) -> dict[str, int]:
        if self._disk is None:
            p = self._path or cache_path()
            try:
                with open(p) as f:
                    self._disk = {k: int(v) for k, v in json.load(f).items()}
            except (OSError, ValueError):
                self._disk = {}
        return self._disk

    def _save_disk(self) -> None:
        p = self._path or cache_path()
        try:
            os.makedirs(os.path.dirname(p), exist_ok=True)
            tmp = p + f".tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self._load_disk(), f, indent=1, sort_keys=True)
            os.replace(tmp, p)
        except OSError:
            pass  # caching is best-effort; tuning results stay in memory

    # -- timing -----------------------------------------------------------

    @staticmethod
    def _measure_interleaved(thunks: dict, iters: int,
                             rounds: int = 5,
                             target_window_s: float = 0.15) -> dict:
        """Per-candidate median ms over interleaved rounds (the shared
        ``core.utils.interleaved_time_samples`` protocol, with adaptive
        ~150 ms timing windows: 8 iters of a 4 ms kernel is a 32 ms
        window — RTT-jitter-sized on the tunneled backend, and a
        sequential sweep at that granularity crowned wrong winners).
        RANKING uses the raw long-window estimator: candidates share its
        fixed sync cost (common mode in comparisons), where the slope
        estimator's independent calibrations give even identical
        candidates a +-3% spread — at the price of slightly understating
        true gaps (~sync/window share), i.e. effectively stiffer
        margins."""
        raw = interleaved_time_samples(thunks, iters, rounds,
                                       target_window_s=target_window_s)
        out = {}
        for name, xs in raw.items():
            # drop round 0: its raw sample predates the window
            # calibration, so its sync share is not yet equalized
            # across candidates
            tail = xs[1:] if len(xs) > 1 else xs
            rs = sorted(r for _, r in tail if r > 0)
            out[name] = rs[len(rs) // 2] * 1e3 if rs else float("inf")
        return out

    def _agree(self, times: list[float]) -> list[float]:
        """Average candidate times over processes so every rank picks the
        same winner (reference: the rank sync in ``autotuner.py:200-230``;
        shared primitive: ``core.utils.process_mean`` — the link
        calibration persists through the same agreement)."""
        from ..core.utils import process_mean

        return process_mean(times)

    # -- entry ------------------------------------------------------------

    def tune(
        self,
        name: str,
        key: Sequence[Any],
        candidates: Sequence[Any],
        make_thunk: Callable[[Any], Callable[[], Any]],
        *,
        iters: int = 8,
        verbose: bool = False,
        sol_ms: float | None = None,
        baseline_index: int | None = None,
        margin: float | Callable[[Any], float] = 0.08,
        fresh: bool = False,
    ) -> TuneResult:
        """Pick the fastest candidate for ``key``.

        ``make_thunk(candidate)`` returns a zero-arg thunk running the op
        with that candidate config (closing over the caller's REAL
        arguments — that is the "contextual" part).  Invalid candidates may
        raise during their first call and are skipped.  ``sol_ms`` (a
        ``tools.perf_model`` estimate) turns the winner's time into a
        fraction-of-speed-of-light sanity number on the result (reference:
        the SOL thresholds its perf models feed the autotuner/tests).
        ``baseline_index`` marks a known-good default candidate that a
        challenger must beat by ``margin`` to be crowned (a float, or a
        per-candidate callable — see :func:`margin_for`).  ``fresh``
        ignores any cached winner and re-measures NOW: winners are partly
        chip-state properties on throttling-prone parts, so benchmark/
        serving warmup re-tunes in the process that will run the traffic
        (the reference autotuner has no cross-process cache at all —
        every process re-measures; ``fresh`` recovers those semantics on
        demand).  A fresh crown always lands in process memory; it is
        written to the DISK cache only when it clears the conservative
        margins (near-tie fine-margin crowns stay process-local — see
        ``process_local`` below), and a fresh tune that demotes a
        previously persisted winner removes the stale disk entry either
        way.
        """
        from .. import obs

        ck = _cache_key(name, key, candidates)
        multi = jax.process_count() > 1
        if not fresh:
            with self._lock:
                if ck in self._mem:
                    if obs.enabled():
                        obs.counter("autotune_cache_hits", name=name,
                                    source="mem").inc()
                    # per-process memory: identical on every rank because
                    # SPMD programs issue the same tune() sequence
                    return TuneResult(candidates[self._mem[ck]],
                                      self._times.get(ck, float("nan")),
                                      True)
                # the DISK cache is per-node and may diverge across hosts
                # (one node replaced / cache cleared): a hit on rank A while
                # rank B measures would strand B's collective candidates ->
                # only single-process runs consult it
                if not multi:
                    disk = self._load_disk()
                    if ck in disk and disk[ck] < len(candidates):
                        self._mem[ck] = disk[ck]
                        if obs.enabled():
                            obs.counter("autotune_cache_hits", name=name,
                                        source="disk").inc()
                        return TuneResult(candidates[disk[ck]], float("nan"),
                                          True)
        if len(candidates) == 1:
            # nothing to choose; skip the measurement entirely
            with self._lock:
                self._mem[ck] = 0
            return TuneResult(candidates[0], float("nan"), True)

        import time as _obs_time

        _search_t0 = _obs_time.monotonic()
        # phase 1: compile/validate every candidate (first call builds)
        live: dict[int, Callable[[], Any]] = {}
        for i, cand in enumerate(candidates):
            try:
                thunk = make_thunk(cand)
                if obs.enabled():
                    # measurement thunks re-enter instrumented entry
                    # points (e.g. the ag_method sweep times all_gather
                    # itself, hundreds of calls per candidate): silence
                    # everything they record so comm counters/spans
                    # describe real traffic, not sweep traffic
                    thunk = obs.suppressed_thunk(thunk)
                from .. import resilience

                if resilience.enabled():
                    # ...and disarm the runtime guards: a deliberately
                    # timed candidate must not burn watchdog deadlines,
                    # feed the XLA fallback's time to the tuner, or walk
                    # the sticky breaker open from sweep traffic
                    thunk = resilience.suppressed_thunk(thunk)
                from ..core.utils import sync

                sync(thunk())
                live[i] = thunk
            except Exception as exc:  # invalid tile/OOM candidate
                if multi:
                    # a per-rank skip would desynchronize ranks mid-collective
                    # (peers are already blocked inside the failed candidate):
                    # candidates must be valid on EVERY rank in multi-process
                    # tuning, so fail loudly instead of hanging the job
                    raise RuntimeError(
                        f"autotune[{name}] candidate {cand} failed on this "
                        f"process during multi-process tuning; prune invalid "
                        f"candidates before tuning collectives"
                    ) from exc
                if verbose:
                    dist_print(f"autotune[{name}] {cand}: failed ({exc})",
                               rank=0)
        # phase 2: interleaved-round medians over the surviving candidates.
        # FRESH tunes (bench capture / serving warmup) pay for precision:
        # the fine-grained FRESH_SWEEP_MARGIN only makes sense if the
        # sweep itself can resolve few-percent differences, which the
        # default quick protocol (5 rounds, ~150 ms windows) cannot on
        # the tunneled chip (identical-program medians swing +-5%).
        from ..core import compilation

        if fresh and not multi and live and not compilation.interpret_mode():
            # ramp the REAL chip to steady state before any timed window:
            # the tunneled chip clocks up over the first seconds of
            # sustained work (round-5 measurement: the same XLA decode
            # read 327 GB/s at process start and 717 GB/s a minute
            # later), and a sweep whose early rounds straddle the ramp
            # crowns whichever candidate the calibration happened to
            # favor.  Interpret-mode (CPU test) builds have no clock to
            # ramp and skip the spin.
            import time as _time

            from ..core.utils import sync

            spin = live.get(baseline_index, next(iter(live.values())))
            t0 = _time.perf_counter()
            while _time.perf_counter() - t0 < 1.5:
                sync(spin())
        if fresh and not multi:
            measured = self._measure_interleaved(
                {i: t for i, t in live.items()}, iters,
                rounds=9, target_window_s=0.4,
            )
        else:
            measured = self._measure_interleaved(
                {i: t for i, t in live.items()}, iters
            )
        times = [measured.get(i, float("inf"))
                 for i in range(len(candidates))]
        if verbose:
            for i, cand in enumerate(candidates):
                dist_print(f"autotune[{name}] {cand}: {times[i]:.3f} ms",
                           rank=0)
        times = self._agree(times)
        best = min(range(len(candidates)), key=lambda i: times[i])
        if times[best] == float("inf"):
            raise RuntimeError(
                f"autotune[{name}]: every candidate failed for key {key}"
            )
        full_margin = margin(candidates[best]) if callable(margin) else margin
        m = full_margin
        confirmed = fresh and not multi
        if confirmed:
            # every non-default fresh crown is re-validated head-to-head
            # below, so the sweep gate can be fine-grained (see
            # FRESH_SWEEP_MARGIN) instead of noise-proof
            m = min(m, FRESH_SWEEP_MARGIN)
        if (baseline_index is not None
                and times[baseline_index] != float("inf")
                and times[best] >= (1.0 - m) * times[baseline_index]):
            # a known-good default only loses to a CLEAR winner: on noisy
            # (tunneled) backends the measured spread among near-tie tile
            # configs exceeds their true difference, and a mis-crowned
            # winner would be persisted
            best = baseline_index
        if (confirmed
                and baseline_index is not None and best != baseline_index
                and baseline_index in live and best in live):
            # (single-process only: the confirmation re-measure is
            # host-local, and a per-rank revert would break the
            # identical-winner-on-every-rank invariant _agree upholds)
            # confirmation pass: a fresh crown is about to be USED in this
            # process (bench capture / serving warmup), so a sweep-noise
            # artifact is maximally costly.  Head-to-head re-measure with
            # longer windows; the challenger keeps the crown only if it
            # wins by the margin AND wins CONSISTENTLY — in the chip's
            # unstable states per-round ratios flip sign round to round,
            # a fine-margin crown is a coin flip with real downside
            # (observed: a confirmed crown capturing 0.91x minutes
            # later), and the right call in chaos is the never-lose
            # default.  A genuine few-percent edge in a calm state wins
            # essentially every round.

            both = interleaved_time_samples(
                {0: live[best], 1: live[baseline_index]}, iters,
                rounds=8, target_window_s=0.4,
            )
            # decisions AND the recorded times both ride the RAW
            # estimator (shared sync cost cancels in the comparison, and
            # the process_local gate below compares these times against
            # the sweep's raw medians — one estimator throughout)
            pairs = [(b[1], d[1]) for b, d in zip(both[0][1:], both[1][1:])
                     if b[1] > 0 and d[1] > 0]
            wins = sum(1 for b, d in pairs
                       if b < (1.0 - FRESH_CONFIRM_MARGIN) * d)
            med_b = sorted(b for b, _ in pairs)[len(pairs) // 2] \
                if pairs else float("inf")
            med_d = sorted(d for _, d in pairs)[len(pairs) // 2] \
                if pairs else float("inf")
            consistent = (len(pairs) >= 3
                          and wins >= max(3, (3 * len(pairs)) // 4)
                          and med_b < (1.0 - FRESH_CONFIRM_MARGIN) * med_d)
            if pairs:
                # refresh with the confirmation's RAW medians — same
                # estimator the sweep recorded, so the process_local
                # comparison below never mixes estimators.  When the
                # pairwise filter dropped every round (jittery
                # backend), the sweep's finite raw medians stand.
                times[best] = med_b * 1e3
                times[baseline_index] = med_d * 1e3
            if not consistent:
                best = baseline_index
        # a fresh crown that cleared only the FINE margins is valid for
        # THIS process (this chip state, about to run the traffic) but
        # must not be inherited by later processes through the disk
        # cache without the conservative noise protection — flag wins
        # have measured 0.6x-2.1x across processes/chip states, and a
        # persisted near-tie mis-crown is the round-3 regression class.
        process_local = (
            confirmed and baseline_index is not None
            and best != baseline_index
            and times[baseline_index] != float("inf")
            and times[best] >= (1.0 - full_margin) * times[baseline_index]
        )
        with self._lock:
            self._mem[ck] = best
            self._times[ck] = times[best]
            if not process_local:
                self._load_disk()[ck] = best
                self._save_disk()
            elif self._load_disk().get(ck, best) != best:
                # a fine-margin fresh crown demoted a previously
                # persisted winner: the measurement that crowned the disk
                # entry is now contradicted, so later processes must not
                # inherit it — drop it and let them fall back to the
                # default (or re-measure)
                del self._load_disk()[ck]
                self._save_disk()
            # any memoized resolution may now be stale (fresh re-tunes
            # overwrite winners); the dict is tiny — drop it wholesale
            self._resolved.clear()
        if obs.enabled():
            search_s = _obs_time.monotonic() - _search_t0
            obs.counter("autotune_searches", name=name).inc()
            obs.counter("autotune_candidates_tried", name=name).inc(len(live))
            obs.gauge("autotune_last_search_s", name=name).set(search_s)
            obs.gauge("autotune_winner_index", name=name).set(best)
            if times[best] == times[best]:  # finite winner time
                obs.histogram("autotune_winner_ms", name=name).observe(
                    times[best])
            obs.instant("autotune", cat="tune", name=name,
                        winner=str(candidates[best]), search_s=search_s,
                        candidates=len(live), fresh=bool(fresh))
        frac = None
        if sol_ms and times[best] > 0 and times[best] == times[best]:
            frac = sol_ms / times[best]
            if verbose:
                dist_print(
                    f"autotune[{name}] winner {candidates[best]}: "
                    f"{times[best]:.3f} ms = {100 * frac:.0f}% of SOL",
                    rank=0,
                )
        return TuneResult(candidates[best], times[best], False, frac)


_GLOBAL = Autotuner()


def autotune(name, key, candidates, make_thunk, **kw) -> TuneResult:
    """Tune via the process-global :class:`Autotuner`."""
    return _GLOBAL.tune(name, key, candidates, make_thunk, **kw)


def transparent_tuning_enabled() -> bool:
    """Whether default-config ops may MEASURE candidates on first eager
    invocation (the reference's monkey-patched ``Autotuner.run``
    transparency, ``autotuner.py:250``).  ``TDT_AUTOTUNE=0`` opts out,
    ``=1`` forces on; the auto default measures only outside interpret
    mode (interpret-mode timings are simulation artifacts)."""
    env = os.environ.get("TDT_AUTOTUNE", "").lower()
    if env in ("0", "off", "never"):
        return False
    if env in ("1", "on", "always"):
        return True
    from ..core import compilation

    return not compilation.interpret_mode()


def lookup_winner(name: str, key: Sequence[Any],
                  candidates: Sequence[Any], *,
                  mem_only: bool = False) -> int | None:
    """Pure host-side cache consult (memory, then disk): the winner INDEX
    for ``key`` or None.  Safe under jit tracing — no device work.
    ``mem_only`` skips the per-host disk file — in multi-process programs
    only the in-process memory (written after a rank-synced measurement)
    is guaranteed identical on every rank."""
    ck = _cache_key(name, key, candidates)
    n = len(candidates)
    with _GLOBAL._lock:
        if ck in _GLOBAL._mem:
            idx = _GLOBAL._mem[ck]
            return idx if idx < n else None
        if mem_only:
            return None
        disk = _GLOBAL._load_disk()
        if ck in disk and disk[ck] < n:
            return disk[ck]
    return None


def resolve_config(
    name: str,
    key: Sequence[Any],
    candidates: Sequence[Any],
    default: Any,
    make_thunk: Callable[[Any], Callable[[], Any]],
    *,
    tracing: bool,
    force_measure: bool = False,
    sol_ms: float | None = None,
    fresh: bool = False,
) -> Any:
    """The default-config hook every op calls when the caller passed no
    explicit config: cached winner if one exists (works under tracing —
    the jit'd layer picks up whatever an earlier eager/tuned run learned),
    else measure now when allowed, else ``default``.  ``force_measure``
    (the explicit ``tuned_*`` entry points) measures even when transparent
    tuning is off — but never under tracing.  ``fresh`` additionally
    ignores cached winners and re-measures in THIS process (see
    ``Autotuner.tune``)."""
    rk = (name, tuple(map(str, key)))
    if not fresh:
        hit = _GLOBAL._resolved.get(rk)
        if hit is not None:
            return hit
    candidates = list(candidates)
    if default not in candidates:
        # the baseline must be in the sweep (and before the cache lookup,
        # so the candidates digest is stable across calls)
        candidates = [default, *candidates]
    # multi-process: every rank MUST resolve the same config or the ranks
    # launch mismatched collectives and hang.  Per-host state (disk cache,
    # env toggles) can diverge, so only the in-process memory (written
    # after a rank-synced measurement) and the deterministic default are
    # trusted; measurement happens only through the explicit tuned_* entry
    # points, whose tune() run rank-syncs candidate times.
    multi = jax.process_count() > 1
    if not fresh:
        idx = lookup_winner(name, key, candidates, mem_only=multi)
        if idx is not None:
            _GLOBAL._resolved[rk] = candidates[idx]
            return candidates[idx]
    if tracing:
        return default
    if multi and not force_measure:
        return default
    if not (force_measure or transparent_tuning_enabled()):
        return default
    cfg = autotune(name, key, candidates, make_thunk, sol_ms=sol_ms,
                   baseline_index=candidates.index(default),
                   margin=margin_for, fresh=fresh).config
    _GLOBAL._resolved[rk] = cfg
    return cfg


def is_tracer(x) -> bool:
    return isinstance(x, jax.core.Tracer)


def prune_infeasible(name: str, candidates: Sequence[Any], default: Any,
                     dims: dict) -> list:
    """Drop candidates whose static resource footprint
    (``analysis.footprint``) cannot build at ``dims`` BEFORE anything is
    measured: an infeasible candidate costs a compile attempt plus an
    interleaved timing slot, and in multi-process sweeps a per-rank
    build failure is fatal by contract (see ``Autotuner.tune``).  The
    pruning is deterministic in (name, config, dims) — it deliberately
    pins the physical-VMEM bound to the compile-time constant
    (``compilation.VMEM_BYTES``) rather than the ``TDT_VMEM_BUDGET``
    env override, so a per-host env divergence cannot break the
    multi-process identical-candidates invariant (the env knob scopes
    to the LINT, ``footprint.check_defaults``).  Because a pruned list
    has a different candidates digest than an unpruned one, EVERY
    resolve path sharing a cache key must consume the same pruned list
    — use the shared per-family helpers (``matmul_candidates_pruned``,
    ``fused_mlp_candidates_pruned``, ``resolve_gemm_like``), never a
    one-sided prune.  The DEFAULT is never pruned (the sweep's
    baseline; if it is itself infeasible the ``tdt_lint
    --completeness`` default-config leg flags it); non-tile candidates
    (``XlaBackend``) pass through.  Rejections land on the
    ``footprint_rejections`` counter."""
    from .. import obs
    from ..analysis import footprint
    from ..core import compilation

    kept = []
    for c in candidates:
        tile_like = isinstance(c, (tuple, list)) or hasattr(c, "bm")
        if c == default or not tile_like \
                or not footprint.config_feasible(
                    name, c, dims, physical=compilation.VMEM_BYTES):
            kept.append(c)
            continue
        if obs.enabled():
            obs.counter("footprint_rejections", name=name).inc()
    return kept


def matmul_candidates_pruned(m: int, n: int, k: int, dtype) -> list:
    """The ONE candidate list every matmul resolve path (transparent
    ``matmul(config=None)``, ``matmul_callable``, ``tuned_matmul``,
    ``fresh_tune_matmul``) must use: the backend sweep with statically
    infeasible tiles pruned.  Sharing the exact list keeps the
    candidates digest — and therefore the winner-cache entry — common
    to all paths."""
    return prune_infeasible("matmul", matmul_backend_candidates(m, n, k),
                            XlaBackend(), dict(m=m, n=n, k=k, dtype=dtype))


def fused_mlp_candidates_pruned(b: int, k_in: int, k_loc: int, n_dim: int,
                                num_ranks: int, dtype) -> list:
    """``matmul_candidates_pruned``'s analogue for the fused MLP+AR
    sweep, shared by ``ops.fused_decode._resolve_fused_mlp`` (the
    transparent path) and ``fresh_tune_fused_mlp``."""
    from ..ops.fused_decode import FusedMlpConfig, fused_mlp_candidates

    cn = n_dim // max(num_ranks, 1)
    return prune_infeasible(
        "fused_mlp_ar", fused_mlp_candidates(b, k_loc, cn),
        FusedMlpConfig().clip(b, k_loc, cn),
        dict(b=b, k_in=k_in, k_loc=k_loc, n_dim=n_dim,
             num_ranks=num_ranks, dtype=dtype))


def _gemm_like_footprint_dims(name: str, m: int, n: int, k: int,
                              n_ranks: int, dtype) -> dict:
    """The fused collective GEMMs' per-device calculator dims from the
    flat (m, n, k) problem ``resolve_gemm_like`` sees."""
    r = max(n_ranks, 1)
    if name == "ag_gemm":
        return dict(m_loc=max(m // r, 1), k=k, n_loc=max(n // r, 1),
                    num_ranks=r, dtype=dtype)
    return dict(m_loc=max(m // r, 1), k_loc=max(k // r, 1), n_dim=n,
                num_ranks=r, dtype=dtype)


def resolve_gemm_like(name: str, op, config_cls, cand_dims, default,
                      a, b, mesh, axis: str, kw: dict,
                      key_kw: dict | None = None, *,
                      force_measure: bool = False):
    """Default-config resolution for the fused collective GEMMs: the hook
    their entry points call when ``config=None``, and the body of the
    explicit ``tuned_*`` wrappers (``force_measure=True``).  One shared
    cache key — (shape, ranks, dtype, WIRE CLASS, device, canonical
    kernel-selecting kwargs) — so a one-time tuned or eager run teaches
    every later jit'd layer call, and a winner crowned on the ICI torus
    never leaks onto a DCN edge (ISSUE 10: tile choices trade
    compute-ahead against wire pacing, which differs per wire class).
    ``kw`` goes to the measurement thunks verbatim; ``key_kw`` (default
    ``kw``) is the canonicalized subset that keys the cache."""
    from ..core import mesh as mesh_lib

    n_ranks = mesh.shape[axis]
    (m, k), (_, n) = a.shape, b.shape
    dm, dn, dk = cand_dims(m, n, k, n_ranks)
    cands = [config_cls(bm, bn, bk)
             for bm, bn, bk in matmul_tile_candidates(dm, dn, dk)]
    cands = prune_infeasible(
        name, cands, default,
        _gemm_like_footprint_dims(name, m, n, k, n_ranks, a.dtype))
    kw_key = str(sorted((key_kw if key_kw is not None else kw).items()))
    return resolve_config(
        name,
        (m, k, n, n_ranks, str(a.dtype), mesh_lib.wire_class(mesh, axis),
         platform.device_kind(), kw_key),
        cands, default,
        lambda c: (lambda: op(a, b, mesh, axis, config=c, **kw)),
        tracing=is_tracer(a) or is_tracer(b),
        force_measure=force_measure,
        sol_ms=_fused_sol_ms(name, m, n, k, n_ranks, a.dtype),
    )


def collective_tile_candidates(config_cls, m: int, r: int) -> list:
    """(bm, bn) reduction-pipeline tile sweep for the signal-shaped
    collectives (VERDICT r5 next #5): the ``AllReduceConfig`` /
    ``ReduceScatterConfig`` add/sum-pipeline tiles, clipped to the
    problem through the config's own ``clip`` and deduped — at small
    shapes most tilings collapse onto the default, and a one-candidate
    sweep costs nothing (``Autotuner.tune`` short-circuits it).
    The (256, 512) default leads: the baseline the margins protect."""
    dims = [(256, 512), (512, 512), (256, 1024), (512, 1024),
            (128, 512), (512, 256)]
    out, seen = [], set()
    for bm, bn in dims:
        c = config_cls(bm=bm, bn=bn).clip(m, r)
        if c not in seen:
            seen.add(c)
            out.append(c)
    return out


def a2a_chunk_candidates(config_cls, t: int) -> list:
    """``AllToAllConfig.chunk`` sweep for the EP all-to-all: rows per DMA
    descriptor — smaller chunks pipeline the wire at more descriptors,
    larger ones amortize issue latency.  Values are pre-clamped to the
    op's own ``min(chunk, round_up(t, 8))`` rule and deduped, so every
    candidate launches a distinct kernel.  128 (the default) leads."""
    cap = max(8, -(-t // 8) * 8)
    out, seen = [], set()
    for ch in (128, 64, 256, 512):
        eff = min(ch, cap)
        if eff not in seen:
            seen.add(eff)
            out.append(config_cls(chunk=eff))
    return out


AG_GEMM_CAND_DIMS = lambda m, n, k, r: (max(m // r, 1), max(n // r, 1), k)   # noqa: E731
GEMM_RS_CAND_DIMS = lambda m, n, k, r: (max(m // r, 1), n, max(k // r, 1))   # noqa: E731
GEMM_AR_CAND_DIMS = lambda m, n, k, r: (max(m // r, 1), n, max(k // r, 1))   # noqa: E731


def ag_gemm_key_kw(n_ranks: int, kw: dict) -> dict:
    """Canonical cache-key kwargs for ag_gemm: ``bidir`` resolved to its
    concrete default so explicit and transparent callers share entries."""
    bidir = kw.get("bidir")
    if bidir is None:
        bidir = n_ranks >= 3
    return {"bidir": bool(bidir),
            "return_gathered": bool(kw.get("return_gathered", False))}


def matmul_tile_candidates(m: int, n: int, k: int) -> list[tuple[int, int, int]]:
    """Default (bm, bn, bk) sweep for GEMM-shaped ops: the measured-best
    512x1792x512 first (the wide-N tiling that beat XLA at 7168^3 bf16,
    see ``ops.matmul``), the 1024x1024x512 runner-up, the wide-M / deep-K
    tilings that win on skewed shapes (4096^3 and tall-narrow problems in
    the on-chip sweeps), and smaller tiles for problems where those do
    not fit."""
    cands = [
        (512, 1792, 512), (1024, 1024, 512), (512, 1024, 512),
        (1024, 512, 512), (2048, 512, 512), (512, 2048, 512),
        (512, 512, 2048), (512, 512, 512), (512, 512, 1024),
        (256, 1024, 512), (256, 512, 512), (256, 256, 512),
    ]
    return [c for c in cands if c[0] <= m and c[1] <= n and c[2] <= k] or [
        (min(256, m), min(256, n), min(256, k))
    ]


MATMUL_DEFAULT_TILES = (512, 1792, 512)


MATMUL_TILE_VL = 100 * 2**20


def matmul_backend_candidates(m: int, n: int, k: int) -> list:
    """Mixed backend sweep for ``ops.matmul``'s ``config=None`` path: XLA
    dispatch first (default flags = the never-lose baseline, then the
    scoped-VMEM variants — see :class:`XlaBackend`), followed by the
    Pallas grid tilings that have won shapes in on-chip sweeps.  Shared by
    the transparent resolve, ``tuned_matmul``, and ``fresh_tune_matmul``
    so all three hit one cache entry (the digest covers the list)."""
    xla = xla_backend_candidates()
    if any(d % 8 for d in (m, n, k)):
        return xla  # no sublane-aligned Pallas tiling exists; XLA handles it
    # big-accumulator Pallas tilings under a raised VMEM budget — the
    # round-4 sweep winners (1.01-1.03x of default XLA at the dense bench
    # shapes, stable across chip states, vs <=0.99x for every 16 MiB-
    # budget tiling).  The list is kept short: a fresh (bench/warmup)
    # tune pays one compile per candidate.
    tiles = [(2048, 1024, 512, MATMUL_TILE_VL),
             (1024, 2048, 512, MATMUL_TILE_VL),
             (512, 2048, 1024, MATMUL_TILE_VL)]
    return xla + [c for c in tiles
                  if c[0] <= m and c[1] <= n and c[2] <= k]


def matmul_resolve_key(m: int, n: int, k: int, dtype) -> tuple:
    """The ONE cache key the transparent ``matmul(config=None)`` path,
    ``tuned_matmul``, and ``fresh_tune_matmul`` use — a winner measured by
    any is found by the others."""
    return (m, n, k, str(dtype), platform.device_kind())


def _matmul_resolve(a: jax.Array, b: jax.Array, kw: dict, *,
                    fresh: bool) -> Any:
    from ..ops.matmul import matmul
    from ..tools import perf_model

    (m, k), (_, n) = a.shape, b.shape
    return resolve_config(
        "matmul", matmul_resolve_key(m, n, k, a.dtype),
        matmul_candidates_pruned(m, n, k, a.dtype),
        XlaBackend(),
        lambda c: (lambda: matmul(a, b, config=c, **kw)),
        tracing=is_tracer(a) or is_tracer(b),
        force_measure=True,
        fresh=fresh,
        sol_ms=perf_model.gemm_sol_ms(m, n, k, a.dtype),
    )


def tuned_matmul(a: jax.Array, b: jax.Array, **kw):
    """``ops.matmul`` with an autotuned backend (reference ``@autotune`` on
    the GEMM kernels).  Measures through the same resolver (and cache
    keys) the transparent default path consults."""
    from ..ops.matmul import matmul

    cfg = _matmul_resolve(a, b, kw, fresh=False)
    return matmul(a, b, config=cfg, **kw)


def fresh_tune_matmul(a: jax.Array, b: jax.Array, **kw) -> Any:
    """Re-measure the matmul backend sweep for this shape NOW, overwriting
    any cached winner (see ``Autotuner.tune(fresh=...)``).  The bench
    harness calls this before its timed rounds so the crowned backend
    matches the chip state the capture runs in — a winner inherited from
    another process's chip state is exactly what regressed the round-3
    record.  Returns the crowned config."""
    return _matmul_resolve(a, b, kw, fresh=True)


def fresh_tune_grouped_matmul(x: jax.Array, w: jax.Array,
                              splits: jax.Array) -> Any:
    """``fresh_tune_matmul``'s analogue for ``ops.group_gemm``'s grouped
    matmul (same cache entry as its transparent resolve)."""
    from ..ops.group_gemm import _grouped_resolve

    return _grouped_resolve(x, w, splits, fresh=True)


def fresh_tune_decode(q, k, v, kv_len, *, sm_scale=None,
                      soft_cap: float = 0.0) -> Any:
    """Fresh re-tune of the decode split geometry (``ops.attention``'s
    ``decode_split_candidates``) for this shape, NOW, in this process."""
    from ..ops.attention import _decode_resolve

    d = q.shape[-1]
    sm_scale = float(sm_scale) if sm_scale is not None else d ** -0.5
    return _decode_resolve(q, k, v, kv_len, sm_scale, float(soft_cap),
                           fresh=True)


def fresh_tune_fused_mlp(x, gate_up, down, mesh, axis: str = "tp") -> Any:
    """Fresh re-tune of the decode megakernel's fused MLP+AllReduce tile
    sweep (``ops.fused_decode.fused_mlp_candidates``) for this shape,
    NOW, in this process — same cache entry the transparent
    ``config=None`` path consults, so a bench/warmup crown teaches every
    later jitted decode step."""
    from ..core import platform
    from ..ops.fused_decode import FusedMlpConfig, fused_mlp_ar

    n = mesh.shape[axis]
    b, k_in = x.shape
    k_loc, n_dim = down.shape[0] // max(n, 1), down.shape[1]
    cn = n_dim // max(n, 1)
    return resolve_config(
        "fused_mlp_ar",
        (b, k_in, k_loc, n_dim, n, str(x.dtype), platform.device_kind()),
        fused_mlp_candidates_pruned(b, k_in, k_loc, n_dim, n, x.dtype),
        FusedMlpConfig().clip(b, k_loc, cn),
        lambda c: (lambda: fused_mlp_ar(x, gate_up, down, mesh, axis,
                                        config=c)),
        tracing=is_tracer(x),
        force_measure=True,
        fresh=True,
    )


def fresh_tune_persistent_decode(x, sp, pool_k, pool_v, block_table,
                                 seq_lens, mesh, axis: str = "tp", *,
                                 rope_theta: float = 10_000.0,
                                 rms_eps: float = 1e-6,
                                 qk_eps=None) -> Any:
    """Fresh re-tune of the persistent decode megakernel's tile sweep
    (``ops.persistent_decode.persistent_decode_candidates``) for this
    shape, NOW, in this process — same cache entry the transparent
    ``config=None`` path AND the ``serve.EngineBackend`` construction-
    time hoist consult, so a bench/serving-warmup crown reaches every
    later jitted step bundle without a per-dispatch consult."""
    from ..ops.persistent_decode import (
        PersistentDecodeConfig,
        persistent_candidates_pruned,
        persistent_config_key,
        persistent_decode_step,
    )

    n = mesh.shape[axis]
    layers, _, hk, ps, d = pool_k.shape
    b, k_dim = x.shape
    f_dim = sp.down.shape[1]
    h = sp.wo.shape[1] // d        # (L, H*D, K) — global head count
    return resolve_config(
        "persistent_decode",
        persistent_config_key(layers, b, k_dim, f_dim, hk, ps,
                              block_table.shape[1], d, n, x.dtype),
        persistent_candidates_pruned(layers, b, k_dim, f_dim, h, hk, ps,
                                     d, n, x.dtype),
        PersistentDecodeConfig(),
        lambda c: (lambda: persistent_decode_step(
            x, sp, pool_k, pool_v, block_table, seq_lens, mesh, axis,
            rope_theta=rope_theta, rms_eps=rms_eps, qk_eps=qk_eps,
            config=c)),
        tracing=is_tracer(x),
        force_measure=True,
        fresh=True,
    )


def fresh_tune_wire_dtype(op: str, x, mesh, axis: str = "tp") -> Any:
    """Fresh re-measure of a collective's ``wire_dtype`` axis (ISSUE 9:
    {bf16, int8, fp8} as a tuner dimension, keyed on shape AND wire
    class) for THIS shape, NOW, in this process — the same cache entry
    the entries' ``wire_dtype="auto"`` path consults, so a bench/warmup
    crown teaches later jitted calls.  ``op``: "all_gather" |
    "reduce_scatter" | "all_reduce"."""
    from .. import comm
    from ..comm.quantized import WIRE_DTYPES
    from ..core import mesh as mesh_lib

    fns = {"all_gather": comm.all_gather,
           "reduce_scatter": comm.reduce_scatter,
           "all_reduce": comm.all_reduce}
    entry = fns[op]
    name = {"all_gather": "ag_wire", "reduce_scatter": "rs_wire",
            "all_reduce": "ar_wire"}[op]
    return resolve_config(
        name,
        (tuple(x.shape), str(x.dtype), mesh.shape[axis],
         mesh_lib.wire_class(mesh, axis), platform.device_kind()),
        list(WIRE_DTYPES), "bf16",
        lambda wd: (lambda: entry(x, mesh, axis, wire_dtype=wd)),
        tracing=is_tracer(x),
        force_measure=True,
        fresh=True,
    )


def fresh_tune_flash_attention(q, k, v, *, causal: bool = True,
                               sm_scale=None,
                               soft_cap: float = 0.0) -> Any:
    """Fresh re-tune of the flash-attention block geometry for this
    shape, NOW, in this process."""
    from ..ops.attention import _flash_resolve

    d = q.shape[-1]
    sm_scale = float(sm_scale) if sm_scale is not None else d ** -0.5
    return _flash_resolve(q, k, v, bool(causal), sm_scale,
                          float(soft_cap), fresh=True)


def _tuned_collective(name, op, config_cls, cand_dims, default, key_kw,
                      a, b, mesh, axis, kw):
    """Shared flow of the tuned fused-op wrappers: validate the per-rank
    tile dims up front (so user shape errors surface with the actionable
    message, not as 'every candidate failed'), then measure through the
    same resolver (and cache keys) the transparent config=None path uses."""
    from ..core.utils import clip_block

    n_ranks = mesh.shape[axis]
    (m, k), (_, n) = a.shape, b.shape
    for d in cand_dims(m, n, k, n_ranks):
        clip_block(1024, d)   # raises the pad-to-granule message directly
    cfg = resolve_gemm_like(
        name, op, config_cls, cand_dims, default, a, b, mesh, axis, kw,
        key_kw, force_measure=True,
    )
    return op(a, b, mesh, axis, config=cfg, **kw)


def _fused_sol_ms(name: str, m: int, n: int, k: int, r: int,
                  dtype) -> float | None:
    """Overlap-aware speed of light for a fused collective GEMM:
    max(per-rank GEMM roofline, ring wire time) — a perfectly fused op
    hides the smaller of the two entirely (``tools.perf_model``)."""
    import jax.numpy as jnp

    from ..tools import perf_model

    b = int(jnp.dtype(dtype).itemsize)
    if name == "ag_gemm":
        t_gemm = perf_model.gemm_sol_ms(m, n // r, k, dtype)
        t_comm = perf_model.allgather_sol_ms((m // r) * k * b, r)
    elif name == "gemm_rs":
        t_gemm = perf_model.gemm_sol_ms(m, n, k // r, dtype)
        t_comm = perf_model.reduce_scatter_sol_ms((m // r) * n * b, r)
    else:
        return None
    return max(t_gemm, t_comm)


def tuned_ag_gemm(a: jax.Array, b: jax.Array, mesh, axis: str = "tp", **kw):
    """``ops.ag_gemm`` with autotuned consumer tiles — the fused-op analogue
    of the reference's ``@triton.autotune`` on the AG-GEMM kernel.  Tuning
    runs the REAL collective with the caller's arrays (contextual); all
    candidates are valid on every rank by construction (same shapes
    everywhere), satisfying the multi-process tuning contract."""
    from ..ops.ag_gemm import AgGemmConfig, ag_gemm

    if a.shape[0] % mesh.shape[axis] or b.shape[1] % mesh.shape[axis]:
        raise ValueError(
            f"M={a.shape[0]} and N={b.shape[1]} must be divisible by "
            f"{axis}={mesh.shape[axis]}"
        )
    return _tuned_collective(
        "ag_gemm", ag_gemm, AgGemmConfig, AG_GEMM_CAND_DIMS, AgGemmConfig(),
        ag_gemm_key_kw(mesh.shape[axis], kw), a, b, mesh, axis, kw,
    )


def tuned_gemm_rs(a: jax.Array, b: jax.Array, mesh, axis: str = "tp", **kw):
    """``ops.gemm_rs`` with autotuned producer tiles (see
    :func:`tuned_ag_gemm`)."""
    from ..ops.gemm_rs import GemmRsConfig, gemm_rs

    if a.shape[0] % mesh.shape[axis] or a.shape[1] % mesh.shape[axis]:
        raise ValueError(
            f"M={a.shape[0]} and K={a.shape[1]} must be divisible by "
            f"{axis}={mesh.shape[axis]}"
        )
    return _tuned_collective(
        "gemm_rs", gemm_rs, GemmRsConfig, GEMM_RS_CAND_DIMS, GemmRsConfig(),
        {}, a, b, mesh, axis, kw,
    )
