"""Contextual autotuner: measure candidate configs on the real device,
agree across processes, persist winners.

Reference: ``python/triton_dist/autotuner.py:97-256`` — the ``@autotune``
decorator times each candidate config on the first real invocation
(`contextual`: with the caller's actual tensors), synchronizes the choice
across ranks, and caches per call-site key.

TPU translation: candidates are whole JITTED THUNKS (a config change means
a different Pallas grid, so the unit of timing is the compiled executable,
not a kernel variant), timed with the slope method (``core.utils.perf_func``
— robust to tunneled-backend sync cost).  Cross-process agreement takes the
ALL-RANK MEAN of each candidate's time via ``jax.lax.pmean`` over a 1-chip
mesh collective when multiple processes exist (every process must pick the
same config or collective kernels would disagree on grids); single-process
runs skip it.  Winners persist to a JSON cache keyed by (name, shapes,
dtype, device kind) so steady-state serving never re-tunes.
"""

from __future__ import annotations

import dataclasses
import json
import os
import threading
from typing import Any, Callable, Iterable, Sequence

import jax

from ..core import platform
from ..core.utils import perf_func, dist_print

_DEFAULT_CACHE = os.path.join(
    os.path.expanduser("~"), ".cache", "triton_distributed_tpu",
    "autotune.json",
)


def cache_path() -> str:
    return os.environ.get("TDT_AUTOTUNE_CACHE", _DEFAULT_CACHE)


@dataclasses.dataclass
class TuneResult:
    config: Any
    time_ms: float
    from_cache: bool
    # speed-of-light fraction of the winner (sol_ms / time), when the
    # caller supplied a model estimate and a fresh measurement ran
    sol_fraction: float | None = None


class Autotuner:
    """Process-wide tuner with a persistent JSON winner cache."""

    def __init__(self, path: str | None = None):
        self._path = path
        self._mem: dict[str, int] = {}
        self._times: dict[str, float] = {}
        self._lock = threading.Lock()
        self._disk: dict[str, int] | None = None

    # -- persistence ------------------------------------------------------

    def _load_disk(self) -> dict[str, int]:
        if self._disk is None:
            p = self._path or cache_path()
            try:
                with open(p) as f:
                    self._disk = {k: int(v) for k, v in json.load(f).items()}
            except (OSError, ValueError):
                self._disk = {}
        return self._disk

    def _save_disk(self) -> None:
        p = self._path or cache_path()
        try:
            os.makedirs(os.path.dirname(p), exist_ok=True)
            tmp = p + f".tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(self._load_disk(), f, indent=1, sort_keys=True)
            os.replace(tmp, p)
        except OSError:
            pass  # caching is best-effort; tuning results stay in memory

    # -- timing -----------------------------------------------------------

    def _measure(self, thunk: Callable[[], Any], iters: int) -> float:
        _, ms = perf_func(thunk, iters=iters, warmup_iters=2)
        return ms

    def _agree(self, times: list[float]) -> list[float]:
        """Average candidate times over processes so every rank picks the
        same winner (reference: the rank sync in ``autotuner.py:200-230``)."""
        if jax.process_count() == 1:
            return times
        import jax.numpy as jnp

        arr = jnp.asarray(times)
        mean = jax.pmap(  # one device per process suffices for the mean
            lambda x: jax.lax.pmean(x, "p"), axis_name="p"
        )(arr[None])[0]
        return [float(t) for t in mean]

    # -- entry ------------------------------------------------------------

    def tune(
        self,
        name: str,
        key: Sequence[Any],
        candidates: Sequence[Any],
        make_thunk: Callable[[Any], Callable[[], Any]],
        *,
        iters: int = 8,
        verbose: bool = False,
        sol_ms: float | None = None,
    ) -> TuneResult:
        """Pick the fastest candidate for ``key``.

        ``make_thunk(candidate)`` returns a zero-arg thunk running the op
        with that candidate config (closing over the caller's REAL
        arguments — that is the "contextual" part).  Invalid candidates may
        raise during their first call and are skipped.  ``sol_ms`` (a
        ``tools.perf_model`` estimate) turns the winner's time into a
        fraction-of-speed-of-light sanity number on the result (reference:
        the SOL thresholds its perf models feed the autotuner/tests).
        """
        ck = json.dumps([name, *map(str, key)])
        multi = jax.process_count() > 1
        with self._lock:
            if ck in self._mem:
                # per-process memory: identical on every rank because SPMD
                # programs issue the same tune() sequence
                return TuneResult(candidates[self._mem[ck]],
                                  self._times.get(ck, float("nan")), True)
            # the DISK cache is per-node and may diverge across hosts (one
            # node replaced / cache cleared): a hit on rank A while rank B
            # measures would strand B's collective candidates -> only
            # single-process runs consult it
            if not multi:
                disk = self._load_disk()
                if ck in disk and disk[ck] < len(candidates):
                    self._mem[ck] = disk[ck]
                    return TuneResult(candidates[disk[ck]], float("nan"),
                                      True)
        if len(candidates) == 1:
            # nothing to choose; skip the measurement entirely
            with self._lock:
                self._mem[ck] = 0
            return TuneResult(candidates[0], float("nan"), True)

        times: list[float] = []
        for cand in candidates:
            try:
                thunk = make_thunk(cand)
                ms = self._measure(thunk, iters)
            except Exception as exc:  # invalid tile/OOM candidate
                if multi:
                    # a per-rank skip would desynchronize ranks mid-collective
                    # (peers are already blocked inside the failed candidate):
                    # candidates must be valid on EVERY rank in multi-process
                    # tuning, so fail loudly instead of hanging the job
                    raise RuntimeError(
                        f"autotune[{name}] candidate {cand} failed on this "
                        f"process during multi-process tuning; prune invalid "
                        f"candidates before tuning collectives"
                    ) from exc
                if verbose:
                    dist_print(f"autotune[{name}] {cand}: failed ({exc})",
                               rank=0)
                ms = float("inf")
            times.append(ms)
            if verbose:
                dist_print(f"autotune[{name}] {cand}: {ms:.3f} ms", rank=0)
        times = self._agree(times)
        best = min(range(len(candidates)), key=lambda i: times[i])
        if times[best] == float("inf"):
            raise RuntimeError(
                f"autotune[{name}]: every candidate failed for key {key}"
            )
        with self._lock:
            self._mem[ck] = best
            self._times[ck] = times[best]
            self._load_disk()[ck] = best
            self._save_disk()
        frac = None
        if sol_ms and times[best] > 0 and times[best] == times[best]:
            frac = sol_ms / times[best]
            if verbose:
                dist_print(
                    f"autotune[{name}] winner {candidates[best]}: "
                    f"{times[best]:.3f} ms = {100 * frac:.0f}% of SOL",
                    rank=0,
                )
        return TuneResult(candidates[best], times[best], False, frac)


_GLOBAL = Autotuner()


def autotune(name, key, candidates, make_thunk, **kw) -> TuneResult:
    """Tune via the process-global :class:`Autotuner`."""
    return _GLOBAL.tune(name, key, candidates, make_thunk, **kw)


def matmul_tile_candidates(m: int, n: int, k: int) -> list[tuple[int, int, int]]:
    """Default (bm, bn, bk) sweep for GEMM-shaped ops: the measured-best
    512x1792x512 first (the wide-N tiling that beat XLA at 7168^3 bf16,
    see ``ops.matmul``), then the 1024x1024x512 runner-up and smaller
    tiles for problems where those do not fit."""
    cands = [
        (512, 1792, 512), (1024, 1024, 512), (512, 1024, 512),
        (1024, 512, 512), (512, 512, 512), (512, 512, 1024),
        (256, 1024, 512), (256, 512, 512), (256, 256, 512),
    ]
    return [c for c in cands if c[0] <= m and c[1] <= n and c[2] <= k] or [
        (min(256, m), min(256, n), min(256, k))
    ]


def tuned_matmul(a: jax.Array, b: jax.Array, **kw):
    """``ops.matmul`` with autotuned tiles (reference ``@autotune`` on the
    GEMM kernels)."""
    from ..core.utils import clip_block
    from ..ops.matmul import matmul

    (m, k), (_, n) = a.shape, b.shape
    # surface unalignable dims HERE with the actionable pad message, not as
    # an opaque "every candidate failed" after the sweep
    for d in (m, n, k):
        clip_block(1024, d)
    cands = matmul_tile_candidates(m, n, k)
    from ..tools import perf_model

    res = autotune(
        "matmul", (m, n, k, str(a.dtype), platform.device_kind()), cands,
        lambda c: (lambda: matmul(a, b, bm=c[0], bn=c[1], bk=c[2], **kw)),
        sol_ms=perf_model.gemm_sol_ms(m, n, k, a.dtype),
    )
    bm, bn, bk = res.config
    return matmul(a, b, bm=bm, bn=bn, bk=bk, **kw)


def _tuned_collective(name, op, config_cls, cand_dims, a, b, mesh, axis, kw):
    """Shared flow of the tuned fused-op wrappers: validate the per-rank
    tile dims up front (so user shape errors surface with the actionable
    message, not as 'every candidate failed'), build clipped candidates,
    tune with the caller's real arrays, run with the winner."""
    from ..core.utils import clip_block

    n_ranks = mesh.shape[axis]
    (m, k), (_, n) = a.shape, b.shape
    dm, dn, dk = cand_dims(m, n, k, n_ranks)
    for d in (dm, dn, dk):
        clip_block(1024, d)   # raises the pad-to-granule message directly
    cands = [config_cls(bm, bn, bk)
             for bm, bn, bk in matmul_tile_candidates(dm, dn, dk)]
    # kernel-selecting kwargs (e.g. ag_gemm's bidir) must key the cache:
    # the two schedules want different tiles
    kw_key = str(sorted(kw.items()))
    res = autotune(
        name,
        (m, k, n, n_ranks, str(a.dtype), platform.device_kind(), kw_key),
        cands,
        lambda c: (lambda: op(a, b, mesh, axis, config=c, **kw)),
        sol_ms=_fused_sol_ms(name, m, n, k, n_ranks, a.dtype),
    )
    return op(a, b, mesh, axis, config=res.config, **kw)


def _fused_sol_ms(name: str, m: int, n: int, k: int, r: int,
                  dtype) -> float | None:
    """Overlap-aware speed of light for a fused collective GEMM:
    max(per-rank GEMM roofline, ring wire time) — a perfectly fused op
    hides the smaller of the two entirely (``tools.perf_model``)."""
    import jax.numpy as jnp

    from ..tools import perf_model

    b = int(jnp.dtype(dtype).itemsize)
    if name == "ag_gemm":
        t_gemm = perf_model.gemm_sol_ms(m, n // r, k, dtype)
        t_comm = perf_model.allgather_sol_ms((m // r) * k * b, r)
    elif name == "gemm_rs":
        t_gemm = perf_model.gemm_sol_ms(m, n, k // r, dtype)
        t_comm = perf_model.reduce_scatter_sol_ms((m // r) * n * b, r)
    else:
        return None
    return max(t_gemm, t_comm)


def tuned_ag_gemm(a: jax.Array, b: jax.Array, mesh, axis: str = "tp", **kw):
    """``ops.ag_gemm`` with autotuned consumer tiles — the fused-op analogue
    of the reference's ``@triton.autotune`` on the AG-GEMM kernel.  Tuning
    runs the REAL collective with the caller's arrays (contextual); all
    candidates are valid on every rank by construction (same shapes
    everywhere), satisfying the multi-process tuning contract."""
    from ..ops.ag_gemm import AgGemmConfig, ag_gemm

    if a.shape[0] % mesh.shape[axis] or b.shape[1] % mesh.shape[axis]:
        raise ValueError(
            f"M={a.shape[0]} and N={b.shape[1]} must be divisible by "
            f"{axis}={mesh.shape[axis]}"
        )
    return _tuned_collective(
        "ag_gemm", ag_gemm, AgGemmConfig,
        lambda m, n, k, r: (max(m // r, 1), max(n // r, 1), k),
        a, b, mesh, axis, kw,
    )


def tuned_gemm_rs(a: jax.Array, b: jax.Array, mesh, axis: str = "tp", **kw):
    """``ops.gemm_rs`` with autotuned producer tiles (see
    :func:`tuned_ag_gemm`)."""
    from ..ops.gemm_rs import GemmRsConfig, gemm_rs

    if a.shape[0] % mesh.shape[axis] or a.shape[1] % mesh.shape[axis]:
        raise ValueError(
            f"M={a.shape[0]} and K={a.shape[1]} must be divisible by "
            f"{axis}={mesh.shape[axis]}"
        )
    return _tuned_collective(
        "gemm_rs", gemm_rs, GemmRsConfig,
        lambda m, n, k, r: (max(m // r, 1), n, max(k // r, 1)),
        a, b, mesh, axis, kw,
    )
