"""Seedable, scoped fault injection at the primitives layer.

The harness hooks the SAME interception points the ``tdt.analysis``
recorder uses (``lang/primitives.py``: every ``notify`` / ``wait`` /
``remote_copy`` / ``wait_recv`` / ``wait_send`` / ``local_copy`` call
consults the thread's active fault scope before dispatching), so a fault
is injected where the wire would lose it — not by editing traces after
the fact.  Five fault classes, the failure taxonomy of device-initiated
symmetric-memory communication ("Demystifying NVSHMEM", PAPERS.md):

==================  ======================================================
``DROP_NOTIFY``     a semaphore signal is lost in flight.  On kernels with
                    no flat ``notify`` (pure DMA protocols) the nth
                    ``remote_copy``'s completion signal is lost instead
                    (the recv DMA semaphore is never credited) — the same
                    class seen from the DMA engine.
``DELAY_NOTIFY``    the signal arrives, arbitrarily late (delivery delay
                    in scheduler ticks).
``STALE_CREDIT``    a leftover credit from a previous invocation sits on
                    the semaphore the nth ``wait_recv``/``wait`` consumes,
                    so the wait can pass BEFORE its data lands — the
                    un-ACKed slot-reuse hazard.
``STRAGGLER``       one rank enters the kernel late by ``delay`` ticks.
``RANK_ABORT``      one rank dies mid-kernel: its nth primitive call
                    raises and nothing after it executes.
``CORRUPT_PAYLOAD`` the nth ``remote_copy``'s payload is flipped IN
                    FLIGHT: the credit arrives, the bytes are wrong —
                    the silent-data-corruption class host-side checks
                    never see on device-initiated transfers (ISSUE 7).
``CORRUPT_KV_PAGE`` bytes are flipped AT REST: the landing region the
                    nth ``wait_recv`` guards is poisoned after the DMA
                    settled but before consumption — the kernel-level
                    analogue of a poisoned paged-KV page between
                    scheduler steps (``resilience.integrity``).
==================  ======================================================

Injection composes with record mode: ``record_faulty_case`` records every
rank of an ``analysis.registry`` kernel case with the victim rank's scope
active, yielding :class:`FaultyTraces` (per-rank event lists plus timing
annotations) that ``resilience.simulate.run_bounded`` executes under a
deadline.  In LIVE (interpret / real hardware) mode the same scope makes
``notify`` genuinely skip its ``semaphore_signal`` at trace time, baking
the dropped signal into the built kernel; the time-shaped classes
(delay / straggler) and DMA-signal loss have no host-side lever once the
kernel is on the device and are record/simulation-only — the scope notes
them in ``live_unsupported`` instead of silently passing
(docs/robustness.md).
"""

from __future__ import annotations

import contextlib
import dataclasses
import enum

from ..lang import primitives as dl
from ..analysis.events import NotifyEv


class FaultKind(enum.Enum):
    DROP_NOTIFY = "drop_notify"
    DELAY_NOTIFY = "delay_notify"
    STALE_CREDIT = "stale_credit"
    STRAGGLER = "straggler"
    RANK_ABORT = "rank_abort"
    CORRUPT_PAYLOAD = "corrupt_payload"
    CORRUPT_KV_PAGE = "corrupt_kv_page"


FAULT_KINDS = tuple(FaultKind)

# the silent-data-corruption classes: liveness is unaffected (credits
# balance, the protocol completes on time) — only the checksum protocol
# (``resilience.integrity``) can see them
CORRUPTION_KINDS = (FaultKind.CORRUPT_PAYLOAD, FaultKind.CORRUPT_KV_PAGE)


@dataclasses.dataclass(frozen=True)
class FaultSpec:
    """One injected fault: ``kind`` at the victim ``rank``'s ``nth``
    matching primitive call (0-based).  ``delay`` is in scheduler ticks
    (DELAY_NOTIFY / STRAGGLER); ``amount`` overrides the stale credit
    size (default: exactly what the targeted wait consumes)."""

    kind: FaultKind
    rank: int
    nth: int = 0
    delay: int = 0
    amount: int | None = None


class RankAborted(RuntimeError):
    """Raised inside the victim rank's kernel body by RANK_ABORT: the
    rank dies at this primitive call; the harness records the truncated
    trace."""

    def __init__(self, rank: int, at_event: int):
        self.rank = rank
        self.at_event = at_event
        super().__init__(f"rank {rank} aborted at primitive call #{at_event}")


class FaultScope:
    """Per-thread interception state for ONE victim rank's execution.

    ``lang.primitives`` calls ``on_*`` before dispatching each primitive;
    the scope counts matching calls and fires at the nth.  ``on_notify``
    and ``on_remote_copy`` return an ACTION the primitive applies
    ("drop", ("delay", ticks), "drop_recv", or None); the primitive
    reports recorded event positions back via ``mark_*`` so the harness
    never has to re-derive them.  RANK_ABORT raises from the counting
    step itself.
    """

    def __init__(self, spec: FaultSpec, *, has_wait_recv: bool = True):
        self.spec = spec
        self.has_wait_recv = has_wait_recv
        self.counts: dict[str, int] = {}
        self.total_calls = 0
        self.fired = False
        self.delayed_events: list[tuple[int, int]] = []  # (event pos, ticks)
        self.dropped_recv_events: list[int] = []         # event positions
        self.stale: list[tuple[tuple, int]] = []         # (sem key, amount)
        self.corrupt_events: list[int] = []      # in-flight corrupt copies
        self.poisoned_events: list[int] = []     # at-rest poisoned wait_recvs
        self.live_unsupported: list[str] = []
        self._result_corrupted = False           # corrupt_result ran

    # -- bookkeeping --------------------------------------------------------

    def _tick(self, kind: str) -> int:
        """Count one primitive call; returns this kind's 0-based ordinal.
        RANK_ABORT fires on the TOTAL call ordinal (the rank dies at an
        arbitrary point, whatever primitive happens to be there)."""
        ordinal = self.counts.get(kind, 0)
        self.counts[kind] = ordinal + 1
        at = self.total_calls
        self.total_calls += 1
        if self.spec.kind is FaultKind.RANK_ABORT and at == self.spec.nth:
            self.fired = True
            raise RankAborted(self.spec.rank, at)
        return ordinal

    def _matches(self, kind: FaultKind, ordinal: int) -> bool:
        return self.spec.kind is kind and ordinal == self.spec.nth

    # -- interception points (called from lang.primitives) ------------------

    def on_notify(self, sem, device_id, inc):
        ordinal = self._tick("notify")
        if self._matches(FaultKind.DROP_NOTIFY, ordinal):
            self.fired = True
            return "drop"
        if self._matches(FaultKind.DELAY_NOTIFY, ordinal):
            self.fired = True
            return ("delay", max(int(self.spec.delay), 1))
        return None

    def on_wait(self, sem, value):
        ordinal = self._tick("wait")
        if self._matches(FaultKind.STALE_CREDIT, ordinal) and \
                not self.has_wait_recv:
            self.fired = True
            amount = self.spec.amount if self.spec.amount is not None \
                else int(value)
            # live-mode semaphores have no symbolic identity; the key is
            # only needed by the record-mode harness
            self.stale.append((self._sem_key(sem), amount))
            return ("stale", amount)
        return None

    @staticmethod
    def _sem_key(sem):
        key = getattr(sem, "key", None)
        return key() if callable(key) else None

    def on_remote_copy(self, src, dst, send_sem, recv_sem, device_id):
        ordinal = self._tick("remote_copy")
        if self._matches(FaultKind.DROP_NOTIFY, ordinal) and \
                self.counts.get("notify", 0) == 0:
            # DMA-only protocol: lose this copy's completion signal
            self.fired = True
            return "drop_recv"
        if self._matches(FaultKind.CORRUPT_PAYLOAD, ordinal):
            # the credit arrives intact; the bytes do not
            self.fired = True
            return "corrupt"
        return None

    def on_local_copy(self, src, dst, sem):
        self._tick("local_copy")
        return None

    def on_wait_recv(self, dst_ref, sem):
        ordinal = self._tick("wait_recv")
        if self._matches(FaultKind.STALE_CREDIT, ordinal) and \
                self.has_wait_recv:
            self.fired = True
            amount = self.spec.amount
            if amount is None:
                region = getattr(dst_ref, "region", None)
                amount = region().elements() if region is not None else 1
            self.stale.append((self._sem_key(sem), amount))
        if self._matches(FaultKind.CORRUPT_KV_PAGE, ordinal):
            # poison the landing region AFTER the DMA settled, BEFORE
            # this wait's consumer reads it (at-rest corruption)
            self.fired = True
            return "poison"
        return None

    def on_wait_send(self, src_ref, sem):
        self._tick("wait_send")
        return None

    # -- result plumbing (called from lang.primitives) ----------------------

    def mark_delayed(self, event_pos: int, ticks: int) -> None:
        self.delayed_events.append((event_pos, ticks))

    def mark_dropped_recv(self, event_pos: int) -> None:
        self.dropped_recv_events.append(event_pos)

    def mark_corrupt(self, event_pos: int) -> None:
        self.corrupt_events.append(event_pos)

    def mark_poisoned(self, event_pos: int) -> None:
        self.poisoned_events.append(event_pos)

    def mark_live_unsupported(self, what: str) -> None:
        self.live_unsupported.append(what)

    def corrupt_result(self, out):
        """LIVE injection lever for the corruption classes: in-kernel
        payload bytes are not host-reachable once a kernel is traced
        (the same limitation as ``drop_recv``), but the consumer-side
        verification layer (``resilience.integrity.checked``) IS host
        code — it consults this hook after the collective returns and
        before verification, so a live ``corrupt_payload`` /
        ``corrupt_kv_page`` spec flips one byte of the arrived result
        exactly where wire/at-rest corruption would land it.

        Gated on its OWN flag, not ``fired``: through a real kernel the
        trace-time hooks find the nth target first (setting ``fired``
        and noting ``live_unsupported`` — they cannot act), and the
        flip here is the act itself; keying on ``fired`` would turn
        live injection into a silent no-op exactly when a kernel
        traced."""
        if self._result_corrupted or self.spec.kind not in (
                FaultKind.CORRUPT_PAYLOAD, FaultKind.CORRUPT_KV_PAGE):
            return out
        import numpy as np

        self._result_corrupted = True
        self.fired = True

        def flip(a):
            arr = np.array(a)   # host copy; dtype/shape preserved
            flat = arr.reshape(-1).view(np.uint8)
            flat[self.spec.nth % max(flat.size, 1)] ^= 0x42
            return arr

        if isinstance(out, tuple):
            return (flip(out[0]), *out[1:])
        return flip(out)


# modules whose @lru_cache'd builders close over pallas_call kernels: a
# LIVE fault fires at trace time, so a faulty kernel must never persist
# in (nor a pre-cached clean kernel mask injection from) these caches
_LIVE_BUILDER_MODULES = (
    "triton_distributed_tpu.comm.allgather",
    "triton_distributed_tpu.comm.allreduce",
    "triton_distributed_tpu.comm.reduce_scatter",
    "triton_distributed_tpu.comm.all_to_all",
    "triton_distributed_tpu.ops.ag_gemm",
    "triton_distributed_tpu.ops.gemm_rs",
    "triton_distributed_tpu.ops.gemm_ar",
    "triton_distributed_tpu.resilience.fallbacks",
)


def _clear_live_kernel_caches() -> None:
    import sys

    for name in _LIVE_BUILDER_MODULES:
        mod = sys.modules.get(name)
        if mod is None:
            continue
        for attr in list(vars(mod).values()):
            clear = getattr(attr, "cache_clear", None)
            if callable(clear):
                try:
                    clear()
                except Exception:
                    pass


@contextlib.contextmanager
def scoped(scope: FaultScope | None):
    """Install ``scope`` as this thread's active fault scope for the
    duration (None = no-op).  Composes with record mode: the scope is
    consulted BEFORE the recorder, so a dropped signal never reaches the
    recorded trace — exactly as it never reaches the wire.

    LIVE usage (no recorder active): trace-time injection interacts with
    the builders' ``lru_cache``s — a pre-cached clean kernel would never
    retrace (injection silently no-ops), and a kernel traced under the
    scope has the fault baked in forever.  Both are handled by clearing
    the kernel-builder caches on entry AND exit: the scope always sees a
    fresh trace, and the faulty kernel never outlives it."""
    if scope is None:
        yield None
        return
    if dl.active_fault_scope() is not None:
        raise RuntimeError("fault scopes do not nest")
    live = dl.active_recorder() is None
    if live:
        _clear_live_kernel_caches()
    dl._set_fault_scope(scope)
    try:
        yield scope
    finally:
        dl._set_fault_scope(None)
        if live:
            _clear_live_kernel_caches()


# ---------------------------------------------------------------------------
# recording a faulty execution of a registry kernel case


@dataclasses.dataclass
class FaultyTraces:
    """Per-rank recorded traces of one kernel case under one fault, plus
    the timing annotations the bounded simulator consumes."""

    kernel: str
    n: int
    spec: FaultSpec
    traces: list                        # per-rank event lists
    start_delay: dict[int, int]         # rank -> entry delay ticks
    notify_delay: dict[tuple[int, int], int]  # (rank, event pos) -> ticks
    drop_recv: set[tuple[int, int]]     # (rank, event pos) of lost signals
    aborted: set[int]
    fired: bool                         # the fault found its target
    # (rank, event pos) of CopyEvs whose payload was flipped in flight
    corrupt: set = dataclasses.field(default_factory=set)
    # (rank, event pos) of WaitEvs whose guarded region was poisoned at
    # rest before consumption
    poisoned: set = dataclasses.field(default_factory=set)


def record_faulty_case(case, spec: FaultSpec) -> FaultyTraces:
    """Record all N ranks of an ``analysis.registry.KernelCase`` with
    ``spec`` injected on its victim rank, via the primitives-layer
    interception points."""
    from ..analysis.record import coords_of, recording

    if not 0 <= spec.rank < case.n:
        raise ValueError(f"victim rank {spec.rank} outside [0, {case.n})")
    axes = getattr(case, "axes", None) or (("tp", case.n),)
    has_recv = _case_has_wait_recv(case) \
        if spec.kind is FaultKind.STALE_CREDIT else True
    traces: list = []
    start_delay: dict[int, int] = {}
    notify_delay: dict[tuple[int, int], int] = {}
    drop_recv: set[tuple[int, int]] = set()
    corrupt: set[tuple[int, int]] = set()
    poisoned: set[tuple[int, int]] = set()
    aborted: set[int] = set()
    fired = False
    for rank in range(case.n):
        _, thunk = case.make(rank)
        scope = FaultScope(spec, has_wait_recv=has_recv) \
            if rank == spec.rank else None
        with recording(axes, coords_of(axes, rank)) as rec:
            with scoped(scope):
                try:
                    thunk()
                except RankAborted:
                    aborted.add(rank)
        events = list(rec.events)
        if scope is not None:
            fired = scope.fired
            if spec.kind is FaultKind.STRAGGLER:
                start_delay[rank] = max(int(spec.delay), 1)
                fired = True
            for pos, ticks in scope.delayed_events:
                notify_delay[(rank, pos)] = ticks
            drop_recv.update((rank, p) for p in scope.dropped_recv_events)
            corrupt.update((rank, p) for p in scope.corrupt_events)
            poisoned.update((rank, p) for p in scope.poisoned_events)
            # a stale credit pre-exists the kernel: it lands as a credit
            # event BEFORE the rank's first real event
            for sem_key, amount in scope.stale:
                events.insert(0, NotifyEv(sem_key, rank, amount))
    # harness meshes enumerate ranks row-major over their axes, so the
    # linearized device id == rank index (single- AND multi-axis) and
    # the stale self-credit above targets the victim's own instance
        traces.append(events)
    return FaultyTraces(case.name, case.n, spec, traces, start_delay,
                        notify_delay, drop_recv, aborted, fired,
                        corrupt=corrupt, poisoned=poisoned)


def _case_has_wait_recv(case) -> bool:
    from ..analysis.record import record_kernel

    _, thunk = case.make(0)
    rec = record_kernel(thunk, n=case.n, rank=0,
                        axes=getattr(case, "axes", None))
    return "wait_recv" in rec.signature


def sample_spec(case, kind: FaultKind, rng) -> FaultSpec:
    """Seedable target selection: pick a victim rank and a valid nth for
    ``kind`` from the case's clean trace structure (``rng``: a
    ``random.Random``)."""
    from ..analysis.record import record_kernel

    rank = rng.randrange(case.n)
    _, thunk = case.make(rank)
    rec = record_kernel(thunk, n=case.n, rank=rank,
                        axes=getattr(case, "axes", None))
    sig = rec.signature

    def count(name: str) -> int:
        return sum(1 for s in sig if s == name)

    if kind is FaultKind.STRAGGLER:
        return FaultSpec(kind, rank, delay=rng.randrange(1, 8))
    if kind is FaultKind.RANK_ABORT:
        total = sum(count(k) for k in ("notify", "wait", "remote_copy",
                                       "local_copy", "wait_recv",
                                       "wait_send"))
        nth = rng.randrange(max(total, 1))
        return FaultSpec(kind, rank, nth=nth)
    if kind is FaultKind.CORRUPT_PAYLOAD:
        n_copy = count("remote_copy")
        if n_copy == 0:
            raise ValueError(f"{case.name}: no remote_copy to corrupt")
        return FaultSpec(kind, rank, nth=rng.randrange(n_copy))
    if kind is FaultKind.CORRUPT_KV_PAGE:
        n_recv = count("wait_recv")
        if n_recv == 0:
            raise ValueError(f"{case.name}: no wait_recv landing region "
                             f"to poison")
        return FaultSpec(kind, rank, nth=rng.randrange(n_recv))
    if kind in (FaultKind.DROP_NOTIFY, FaultKind.DELAY_NOTIFY):
        n_not = count("notify")
        if n_not == 0 and kind is FaultKind.DROP_NOTIFY:
            n_copy = count("remote_copy")
            if n_copy == 0:
                raise ValueError(
                    f"{case.name}: no notify or remote_copy to drop"
                )
            return FaultSpec(kind, rank, nth=rng.randrange(n_copy))
        if n_not == 0:
            raise ValueError(f"{case.name}: no notify to delay")
        return FaultSpec(kind, rank, nth=rng.randrange(n_not),
                         delay=rng.randrange(1, 8))
    # STALE_CREDIT: an observable stale credit targets a wait the victim
    # actually executes
    has_recv = "wait_recv" in sig
    n_tgt = count("wait_recv") if has_recv else count("wait")
    if n_tgt == 0:
        raise ValueError(f"{case.name}: no wait to pre-credit")
    return FaultSpec(kind, rank, nth=rng.randrange(n_tgt))
