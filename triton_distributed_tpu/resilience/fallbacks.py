"""XLA (``jax.lax``) equivalents of the fused collective ops — the
degradation targets of the failure ladder.

Each fallback computes the SAME global-semantics result as its fused
Pallas counterpart (the goldens the op tests assert against), through
XLA's own collectives: no Pallas kernel, no custom semaphore protocol —
the code path a stuck ICI semaphore cannot reach.  Slower (no
compute/communication overlap), but correct; that is the contract of
"graceful degradation".

Builders are cached per (mesh, axis, ndim/shape class) like the fused
builders, so a degraded steady state pays the jit cache, not retracing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core import compilation


@functools.lru_cache(maxsize=None)
def _build_all_gather(mesh, axis: str, ndim: int):
    return compilation.jit_shard_map(
        lambda s: jax.lax.all_gather(s, axis, axis=0, tiled=True),
        mesh,
        in_specs=P(axis, *([None] * (ndim - 1))),
        out_specs=P(*([None] * ndim)),
    )


def xla_all_gather(x: jax.Array, mesh, axis: str) -> jax.Array:
    """Degraded ``comm.allgather.all_gather``."""
    return _build_all_gather(mesh, axis, x.ndim)(x)


@functools.lru_cache(maxsize=None)
def _build_all_reduce(mesh, axis: str, out_dtype):
    def local(s):
        return jax.lax.psum(s, axis).astype(out_dtype)

    return compilation.jit_shard_map(
        local, mesh, in_specs=P(axis, None), out_specs=P(None, None),
    )


def xla_all_reduce(x: jax.Array, mesh, axis: str, out_dtype=None
                   ) -> jax.Array:
    """Degraded ``comm.allreduce.all_reduce``: x is (n*M, R) stacked
    partials; returns the replicated (M, R) sum."""
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(x.dtype)
    return _build_all_reduce(mesh, axis, out_dtype)(x)


@functools.lru_cache(maxsize=None)
def _build_reduce_scatter(mesh, axis: str):
    def local(s):
        return jax.lax.psum_scatter(s, axis, scatter_dimension=0,
                                    tiled=True)

    return compilation.jit_shard_map(
        local, mesh, in_specs=P(axis, None), out_specs=P(axis, None),
    )


def xla_reduce_scatter(x: jax.Array, mesh, axis: str) -> jax.Array:
    """Degraded ``comm.reduce_scatter.reduce_scatter``: x is (n*M, R)
    stacked partials; returns (M, R) sharded row-chunks of the sum."""
    return _build_reduce_scatter(mesh, axis)(x)


@functools.lru_cache(maxsize=None)
def _build_ag_gemm(mesh, axis: str, out_dtype):
    def local(a_shard, b_shard):
        ag = jax.lax.all_gather(a_shard, axis, axis=0, tiled=True)
        return jnp.dot(ag, b_shard,
                       preferred_element_type=jnp.float32).astype(out_dtype)

    return compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(axis, None), P(None, axis)),
        out_specs=P(None, axis),
    )


def xla_ag_gemm(a: jax.Array, b: jax.Array, mesh, axis: str,
                out_dtype=None) -> jax.Array:
    """Degraded ``ops.ag_gemm.ag_gemm``: unfused AllGather + local GEMM."""
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(a.dtype)
    return _build_ag_gemm(mesh, axis, out_dtype)(a, b)


@functools.lru_cache(maxsize=None)
def _build_gemm_rs(mesh, axis: str, out_dtype):
    def local(a_shard, b_shard):
        part = jnp.dot(a_shard, b_shard,
                       preferred_element_type=jnp.float32)
        return jax.lax.psum_scatter(
            part, axis, scatter_dimension=0, tiled=True).astype(out_dtype)

    return compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(axis, None),
    )


def xla_gemm_rs(a: jax.Array, b: jax.Array, mesh, axis: str,
                out_dtype=None) -> jax.Array:
    """Degraded ``ops.gemm_rs.gemm_rs``: local partial GEMM + XLA
    ReduceScatter."""
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(a.dtype)
    return _build_gemm_rs(mesh, axis, out_dtype)(a, b)


@functools.lru_cache(maxsize=None)
def _build_gemm_ar(mesh, axis: str, out_dtype):
    def local(a_shard, b_shard):
        part = jnp.dot(a_shard, b_shard,
                       preferred_element_type=jnp.float32)
        return jax.lax.psum(part, axis).astype(out_dtype)

    return compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(None, None),
    )


def xla_gemm_ar(a: jax.Array, b: jax.Array, mesh, axis: str,
                out_dtype=None) -> jax.Array:
    """Degraded ``ops.gemm_ar.gemm_ar``: local partial GEMM + XLA
    AllReduce."""
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(a.dtype)
    return _build_gemm_ar(mesh, axis, out_dtype)(a, b)


@functools.lru_cache(maxsize=None)
def _build_fused_mlp_ar(mesh, axis: str, out_dtype):
    def local(x_rep, gu_shard, dn_shard):
        fused = jnp.dot(x_rep, gu_shard,
                        preferred_element_type=jnp.float32
                        ).astype(x_rep.dtype)
        wg, w1 = jnp.split(fused, 2, axis=-1)
        act = jax.nn.silu(wg) * w1
        part = jnp.dot(act, dn_shard, preferred_element_type=jnp.float32)
        return jax.lax.psum(part, axis).astype(out_dtype)

    return compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(None, None), P(None, axis), P(axis, None)),
        out_specs=P(None, None),
    )


def xla_fused_mlp_ar(x: jax.Array, gate_up: jax.Array, down: jax.Array,
                     mesh, axis: str, out_dtype=None) -> jax.Array:
    """Degraded ``ops.fused_decode.fused_mlp_ar``: the unfused decode-MLP
    psum path (local gate/up GEMM + SwiGLU + partial down GEMM + XLA
    AllReduce) — no Pallas kernel, no semaphore, the code path a stuck
    link cannot reach.  The ``fused_linear_ar`` variant degrades to
    :func:`xla_gemm_ar` (same math)."""
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(x.dtype)
    return _build_fused_mlp_ar(mesh, axis, out_dtype)(x, gate_up, down)


# ---------------------------------------------------------------------------
# EP all-to-all (ISSUE 7 satellite: the two entries PR 3 left
# watchdog-only).  The zone layout is a SELECTION of rows — no
# reduction, no ragged wire protocol — so the degraded path is a pure
# gather/scatter over the eager global arrays: index maps built from
# ``splits`` with jnp cumsum/searchsorted, then one ``jnp.take``.  No
# Pallas kernel, no semaphore, no remote DMA — the code path a stuck
# ICI link (or a quarantined peer) cannot reach.  Semantics match
# ``comm.all_to_all`` on every REAL row; padding rows are zero here
# (the kernel's chunk-rounded DMAs leave dragged-neighbor garbage
# there) — consumers mask by ``recv_splits``, per the layout contract.


def _a2a_geometry(splits, n: int):
    epr = splits.shape[0] // (n * n)
    sp = splits.reshape(n, n, epr).astype(jnp.int32)
    per_peer = sp.sum(-1)                                   # [src, dst]
    offs = jnp.concatenate(
        [jnp.zeros((n, 1), jnp.int32),
         jnp.cumsum(per_peer, axis=1)[:, :-1]], axis=1)
    return sp, per_peer, offs


def xla_ep_dispatch(x: jax.Array, splits: jax.Array, mesh, axis: str, *,
                    config=None):
    """Degraded ``comm.all_to_all.ep_dispatch``: same (recv,
    recv_splits) zone layout, built by host-side gather."""
    from ..comm.all_to_all import AllToAllConfig, _round_up

    n = mesh.shape[axis]
    tn, h = x.shape
    t = tn // max(n, 1)
    e_tot = splits.shape[0] // n
    epr = e_tot // n
    if n == 1:
        return x.reshape(1, t, h), splits.reshape(1, e_tot)[:, :epr]
    cfg = config or AllToAllConfig()
    chunk = min(cfg.chunk, _round_up(t, 8))
    z = _round_up(t, chunk) + chunk
    sp, per_peer, offs = _a2a_geometry(splits, n)
    # zone r*n+p row j <- x row p*t + offs[p, r] + j   for j < count
    r_idx = jnp.repeat(jnp.arange(n), n)       # destination of each zone
    p_idx = jnp.tile(jnp.arange(n), n)         # source of each zone
    j = jnp.arange(z)
    cnt = per_peer[p_idx, r_idx]               # (n*n,)
    src_row = p_idx[:, None] * t + offs[p_idx, r_idx][:, None] + j[None, :]
    valid = j[None, :] < cnt[:, None]
    gathered = jnp.take(x, jnp.where(valid, src_row, 0), axis=0)
    recv = jnp.where(valid[:, :, None], gathered, 0).astype(x.dtype)
    recv_splits = sp[p_idx, r_idx]             # (n*n, epr)
    return recv, recv_splits


def xla_ep_combine(y: jax.Array, splits: jax.Array, mesh, axis: str, *,
                   token_dim: int, config=None) -> jax.Array:
    """Degraded ``comm.all_to_all.ep_combine``: restore sorted-by-expert
    row order from the zone layout by host-side gather."""
    n = mesh.shape[axis]
    if n == 1:
        return y.reshape(-1, y.shape[-1])[:token_dim]
    nz, z, h = y.shape
    t = token_dim
    _, per_peer, offs = _a2a_geometry(splits, n)
    # out row p*t + i came back in zone r*n+p at i - offs[p, r], where r
    # is i's destination peer (searchsorted over p's cumulative counts)
    i = jnp.arange(t)
    cum = jnp.cumsum(per_peer, axis=1)                       # (n, n)
    r_of = jax.vmap(
        lambda c: jnp.searchsorted(c, i, side="right"))(cum)  # (n, t)
    r_of = jnp.clip(r_of, 0, n - 1)
    within = i[None, :] - jnp.take_along_axis(offs, r_of, axis=1)
    zone = r_of * n + jnp.arange(n)[:, None]                 # (n, t)
    idx = (zone * z + within).reshape(-1)
    return jnp.take(y.reshape(nz * z, h), idx, axis=0).astype(y.dtype)


# ---------------------------------------------------------------------------
# persistent multi-layer decode loop (ISSUE 13)


def xla_persistent_decode(x, sp, pool_k, pool_v, block_table, seq_lens,
                          mesh, axis: str, *, rope_theta: float,
                          rms_eps: float, qk_eps=None, sm_scale=None,
                          soft_cap: float = 0.0):
    """Degraded ``ops.persistent_decode.persistent_decode_step``: the
    pure-XLA layer loop (local GEMMs + materialized block-table
    attention + GSPMD reductions) — no Pallas kernel, no semaphore, the
    code path a stuck link cannot reach.  Same function doubles as the
    parity golden (``reference_decode_step``)."""
    from ..ops.persistent_decode import reference_decode_step

    return reference_decode_step(
        x, sp, pool_k, pool_v, block_table, seq_lens, mesh.shape[axis],
        rope_theta=rope_theta, rms_eps=rms_eps, qk_eps=qk_eps,
        sm_scale=sm_scale, soft_cap=soft_cap)
