"""XLA (``jax.lax``) equivalents of the fused collective ops — the
degradation targets of the failure ladder.

Each fallback computes the SAME global-semantics result as its fused
Pallas counterpart (the goldens the op tests assert against), through
XLA's own collectives: no Pallas kernel, no custom semaphore protocol —
the code path a stuck ICI semaphore cannot reach.  Slower (no
compute/communication overlap), but correct; that is the contract of
"graceful degradation".

Builders are cached per (mesh, axis, ndim/shape class) like the fused
builders, so a degraded steady state pays the jit cache, not retracing.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from ..core import compilation


@functools.lru_cache(maxsize=None)
def _build_all_gather(mesh, axis: str, ndim: int):
    return compilation.jit_shard_map(
        lambda s: jax.lax.all_gather(s, axis, axis=0, tiled=True),
        mesh,
        in_specs=P(axis, *([None] * (ndim - 1))),
        out_specs=P(*([None] * ndim)),
    )


def xla_all_gather(x: jax.Array, mesh, axis: str) -> jax.Array:
    """Degraded ``comm.allgather.all_gather``."""
    return _build_all_gather(mesh, axis, x.ndim)(x)


@functools.lru_cache(maxsize=None)
def _build_all_reduce(mesh, axis: str, out_dtype):
    def local(s):
        return jax.lax.psum(s, axis).astype(out_dtype)

    return compilation.jit_shard_map(
        local, mesh, in_specs=P(axis, None), out_specs=P(None, None),
    )


def xla_all_reduce(x: jax.Array, mesh, axis: str, out_dtype=None
                   ) -> jax.Array:
    """Degraded ``comm.allreduce.all_reduce``: x is (n*M, R) stacked
    partials; returns the replicated (M, R) sum."""
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(x.dtype)
    return _build_all_reduce(mesh, axis, out_dtype)(x)


@functools.lru_cache(maxsize=None)
def _build_reduce_scatter(mesh, axis: str):
    def local(s):
        return jax.lax.psum_scatter(s, axis, scatter_dimension=0,
                                    tiled=True)

    return compilation.jit_shard_map(
        local, mesh, in_specs=P(axis, None), out_specs=P(axis, None),
    )


def xla_reduce_scatter(x: jax.Array, mesh, axis: str) -> jax.Array:
    """Degraded ``comm.reduce_scatter.reduce_scatter``: x is (n*M, R)
    stacked partials; returns (M, R) sharded row-chunks of the sum."""
    return _build_reduce_scatter(mesh, axis)(x)


@functools.lru_cache(maxsize=None)
def _build_ag_gemm(mesh, axis: str, out_dtype):
    def local(a_shard, b_shard):
        ag = jax.lax.all_gather(a_shard, axis, axis=0, tiled=True)
        return jnp.dot(ag, b_shard,
                       preferred_element_type=jnp.float32).astype(out_dtype)

    return compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(axis, None), P(None, axis)),
        out_specs=P(None, axis),
    )


def xla_ag_gemm(a: jax.Array, b: jax.Array, mesh, axis: str,
                out_dtype=None) -> jax.Array:
    """Degraded ``ops.ag_gemm.ag_gemm``: unfused AllGather + local GEMM."""
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(a.dtype)
    return _build_ag_gemm(mesh, axis, out_dtype)(a, b)


@functools.lru_cache(maxsize=None)
def _build_gemm_rs(mesh, axis: str, out_dtype):
    def local(a_shard, b_shard):
        part = jnp.dot(a_shard, b_shard,
                       preferred_element_type=jnp.float32)
        return jax.lax.psum_scatter(
            part, axis, scatter_dimension=0, tiled=True).astype(out_dtype)

    return compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(axis, None),
    )


def xla_gemm_rs(a: jax.Array, b: jax.Array, mesh, axis: str,
                out_dtype=None) -> jax.Array:
    """Degraded ``ops.gemm_rs.gemm_rs``: local partial GEMM + XLA
    ReduceScatter."""
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(a.dtype)
    return _build_gemm_rs(mesh, axis, out_dtype)(a, b)


@functools.lru_cache(maxsize=None)
def _build_gemm_ar(mesh, axis: str, out_dtype):
    def local(a_shard, b_shard):
        part = jnp.dot(a_shard, b_shard,
                       preferred_element_type=jnp.float32)
        return jax.lax.psum(part, axis).astype(out_dtype)

    return compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(None, None),
    )


def xla_gemm_ar(a: jax.Array, b: jax.Array, mesh, axis: str,
                out_dtype=None) -> jax.Array:
    """Degraded ``ops.gemm_ar.gemm_ar``: local partial GEMM + XLA
    AllReduce."""
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(a.dtype)
    return _build_gemm_ar(mesh, axis, out_dtype)(a, b)
