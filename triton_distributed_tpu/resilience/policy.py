"""Retry, graceful degradation, and the sticky circuit breaker.

The per-op failure ladder (reference world: NCCL/NVSHMEM jobs simply
die; a serving system must keep answering):

1. **retry with backoff** — a timeout may be transient (interference, a
   straggler beyond slack); the fused kernel is retried up to
   ``max_retries`` times with exponential backoff.
2. **degrade to the XLA collective** — the fused Pallas kernel is a
   performance optimization over a semantically equal ``jax.lax``
   collective (``resilience.fallbacks``); when retries are exhausted the
   op completes through XLA, numerically correct and merely slower.
3. **sticky circuit breaker** — after ``breaker_threshold`` consecutive
   ladder-bottom failures the breaker OPENS and stays open (sticky):
   every subsequent call goes straight to the fallback without paying
   the timeout, until an operator calls :func:`reset_breaker` after
   remediation.  A flapping link must not cost a deadline per request.

Only :class:`~.errors.CollectiveTimeoutError` (and explicitly listed
exception types) ride the ladder: a shape/sharding ``ValueError`` is a
caller bug and propagates immediately.

``obs`` counters (``docs/observability.md``): ``resilience_timeouts``
(bumped by the watchdog), ``resilience_retries``,
``resilience_degraded_calls``, ``resilience_breaker_open``.
"""

from __future__ import annotations

import dataclasses
import threading
import time

from .errors import (
    CircuitOpenError,
    CollectiveTimeoutError,
    PayloadCorruption,
)
from . import watchdog


@dataclasses.dataclass(frozen=True)
class RetryPolicy:
    """Ladder knobs for one op class.

    ``PayloadCorruption`` rides the same ladder as a timeout: a single
    flipped bit may be transient (retry), a sick link is not (fallback,
    breaker, and — via ``resilience.integrity`` — per-peer quarantine).
    It is only ever raised with ``TDT_INTEGRITY=1``, so its presence in
    the default retry set costs nothing when integrity is off."""

    max_retries: int = 1
    backoff_ms: float = 25.0
    backoff_factor: float = 2.0
    breaker_threshold: int = 3
    retry_on: tuple[type, ...] = (CollectiveTimeoutError, PayloadCorruption)


DEFAULT_POLICY = RetryPolicy()


class CircuitBreaker:
    """Consecutive-failure breaker; OPEN is sticky until reset."""

    def __init__(self, op: str, threshold: int):
        self.op = op
        self.threshold = threshold
        self._lock = threading.Lock()
        self._consecutive = 0
        self._open = False

    @property
    def open(self) -> bool:
        return self._open

    @property
    def failures(self) -> int:
        return self._consecutive

    def record_success(self) -> None:
        with self._lock:
            self._consecutive = 0   # sticky: success does not close

    def record_failure(self) -> bool:
        """Count one ladder-bottom failure; returns True when this
        failure opened the breaker."""
        with self._lock:
            self._consecutive += 1
            if not self._open and self._consecutive >= self.threshold:
                self._open = True
                from .. import obs

                if obs.enabled():
                    obs.counter("resilience_breaker_open", op=self.op).inc()
                return True
        return False

    def reset(self) -> None:
        with self._lock:
            self._consecutive = 0
            self._open = False


_BREAKERS: dict[str, CircuitBreaker] = {}
_BREAKERS_LOCK = threading.Lock()
_LAST_ERROR: dict[str, str] = {}


def breaker(op: str, threshold: int | None = None) -> CircuitBreaker:
    """Get-or-create the op's breaker.  An explicit ``threshold``
    updates an existing breaker too (the LATEST policy governs — a
    cached breaker must not silently pin the first caller's value)."""
    b = _BREAKERS.get(op)
    if b is None:
        with _BREAKERS_LOCK:
            b = _BREAKERS.get(op)
            if b is None:
                b = CircuitBreaker(
                    op, threshold if threshold is not None
                    else DEFAULT_POLICY.breaker_threshold)
                _BREAKERS[op] = b
    if threshold is not None and b.threshold != threshold:
        with b._lock:
            b.threshold = threshold
    return b


def reset_breaker(op: str | None = None) -> None:
    """Close the breaker for ``op`` (None = all) after remediation."""
    with _BREAKERS_LOCK:
        targets = [_BREAKERS[op]] if op in _BREAKERS else (
            list(_BREAKERS.values()) if op is None else [])
    for b in targets:
        b.reset()


def resilient_call(op: str, thunk, *, fallback=None,
                   deadline_ms: float | None = None,
                   policy: RetryPolicy = DEFAULT_POLICY,
                   family: str | None = None, ranks: int | None = None):
    """Run ``thunk`` down the failure ladder (see module docstring).

    ``fallback`` (a zero-arg thunk computing the XLA-equivalent result)
    enables degradation; without one, the final error propagates and an
    open breaker raises :class:`CircuitOpenError` immediately.
    """
    from .. import obs
    from . import integrity

    # the quarantine rung (docs/robustness.md "Data integrity"): a team
    # containing a quarantined peer routes straight to the XLA fallback
    # — the code path the sick link cannot corrupt
    if fallback is not None and integrity.quarantine_blocks(ranks):
        if obs.enabled():
            obs.counter("resilience_degraded_calls", op=op,
                        reason="quarantined_peer").inc()
        obs.request_trace.note_rung(
            op, "fallback", "team contains a quarantined peer")
        return fallback()

    br = breaker(op, policy.breaker_threshold)
    if br.open:
        if fallback is None:
            raise CircuitOpenError(op, br.failures)
        if obs.enabled():
            obs.counter("resilience_degraded_calls", op=op,
                        reason="breaker_open").inc()
        # ladder rung -> the active request trace (TDT_TRACE=1): one
        # thread-local read when no trace is bound (obs.request_trace)
        obs.request_trace.note_rung(
            op, "fallback", f"breaker open after {br.failures} "
                            f"consecutive failures")
        return fallback()

    last: BaseException | None = None
    backoff = policy.backoff_ms
    for attempt in range(policy.max_retries + 1):
        try:
            result = watchdog.call_with_deadline(
                op, thunk, deadline_ms, family=family, ranks=ranks)
            br.record_success()
            return result
        except policy.retry_on as e:
            last = e
            _LAST_ERROR[op] = str(e)
            if attempt < policy.max_retries:
                if obs.enabled():
                    obs.counter("resilience_retries", op=op).inc()
                obs.request_trace.note_rung(op, "retry", str(e))
                if backoff > 0:
                    time.sleep(backoff / 1e3)
                backoff *= policy.backoff_factor

    br.record_failure()
    if fallback is not None:
        if obs.enabled():
            obs.counter("resilience_degraded_calls", op=op,
                        reason="retries_exhausted").inc()
        obs.request_trace.note_rung(op, "fallback",
                                    f"retries exhausted: {last}")
        result = fallback()
        return result
    assert last is not None
    raise last


def guarded(op: str, thunk, *, fallback=None, payload_bytes: int = 0,
            ranks: int = 1, family: str | None = None,
            policy: RetryPolicy = DEFAULT_POLICY,
            topology: tuple[int, int] | None = None):
    """The shape every ``comm``/``ops`` entry point wires: returns a
    zero-arg thunk running ``thunk`` under the perf-model-derived
    watchdog deadline and the failure ladder.  Composes under
    ``obs.comm_call`` so the recorded span covers retries and the
    degraded path too.  ``topology`` ((n_out, n_in)) selects the
    two-level deadline model that charges each level its own wire class
    (the hierarchical families, ISSUE 10)."""
    from . import integrity

    dl = watchdog.deadline_ms(op, payload_bytes=payload_bytes,
                              num_ranks=ranks, topology=topology)
    # the consumer-side integrity check runs INSIDE this deadline; a
    # wire-SOL budget alone would time out every verified call on a
    # fast slice (0 when integrity is off)
    dl += integrity.verify_budget_ms(payload_bytes, ranks)

    def run():
        return resilient_call(op, thunk, fallback=fallback, deadline_ms=dl,
                              policy=policy, family=family, ranks=ranks)
    return run


class AdmissionGovernor:
    """Scheduler-aware graceful degradation: shrink ADMISSION instead
    of failing requests.

    The serving scheduler (``serve.scheduler``) consults this before
    admitting: under preemption THRASH (a window where evictions keep
    recurring — every preemption burns a full prompt recompute, so a
    thrashing pool does negative work) or with the serve-step circuit
    breaker OPEN, the governor raises its degradation level, which
    (a) caps concurrent slots at ``slots >> level`` and (b) demands
    ``2^level - 1`` extra free pages of admission headroom.  Clean
    steps decay the level back to zero — admission RE-GROWS as pressure
    clears, the inverse ramp of how it shrank.  Deterministic: levels
    move on step counts, not wall time, so seeded load tests replay.
    """

    def __init__(self, *, window_steps: int = 16,
                 thrash_threshold: int = 3, max_level: int = 3,
                 recover_steps: int = 8, min_slots: int = 1,
                 breaker_op: str = "serve_decode_step"):
        self.window_steps = int(window_steps)
        self.thrash_threshold = int(thrash_threshold)
        self.max_level = int(max_level)
        self.recover_steps = int(recover_steps)
        self.min_slots = int(min_slots)
        self.breaker_op = breaker_op
        self.level = 0
        self._window: list[int] = []     # preemptions per recent step
        self._pending_preempts = 0
        self._clean_steps = 0
        self.advisories = 0

    def note_preemption(self) -> None:
        self._pending_preempts += 1

    def note_step_failure(self) -> None:
        # a failed dispatch is pressure too: count it like a preemption
        self._pending_preempts += 1

    def note_advisory(self) -> None:
        """An out-of-band pressure signal — the continuous profiler's
        anomaly detector offers each breaching window here (ISSUE 16).
        Advisory means exactly that: counted like one preemption, so a
        single anomalous window does nothing and only a RECURRING
        anomaly (>= thrash_threshold within the window) degrades
        admission.  The governor stays deterministic — advisories
        arrive at step boundaries, never from wall time."""
        self._pending_preempts += 1
        self.advisories += 1

    def note_step_ok(self) -> None:
        self._window.append(self._pending_preempts)
        self._pending_preempts = 0
        del self._window[:-self.window_steps]
        if sum(self._window) >= self.thrash_threshold:
            if self.level < self.max_level:
                self.level += 1
                from .. import obs

                if obs.enabled():
                    obs.counter("serve_admission_degraded").inc()
            self._window.clear()
            self._clean_steps = 0
        elif self._window and self._window[-1] == 0:
            self._clean_steps += 1
            if self._clean_steps >= self.recover_steps and self.level:
                self.level -= 1
                self._clean_steps = 0
        else:
            self._clean_steps = 0

    def _effective_level(self) -> int:
        if breaker(self.breaker_op).open:
            return self.max_level
        return self.level

    def headroom_pages(self) -> int:
        """Extra free pages admission must leave at the current level."""
        return (1 << self._effective_level()) - 1

    def slot_cap(self, slots: int) -> int:
        """Concurrent-sequence cap at the current level."""
        return max(self.min_slots, slots >> self._effective_level())

    def degraded(self) -> bool:
        return self._effective_level() > 0

    def snapshot(self) -> dict:
        return {
            "level": self.level,
            "effective_level": self._effective_level(),
            "breaker_open": breaker(self.breaker_op).open,
            "recent_preemptions": sum(self._window)
            + self._pending_preempts,
            "headroom_pages": self.headroom_pages(),
            "advisories": self.advisories,
        }


def quarantined_replicas() -> list[str]:
    """Fleet replicas whose per-replica sticky breaker
    (``replica:<id>``, ``serve.fleet``) is open — the replica-granular
    twin of ``integrity.quarantined_peers()``.  Replica ids are
    strings (they key schedulers, gauges and page-lifecycle pools),
    so no int cast."""
    prefix = "replica:"
    with _BREAKERS_LOCK:
        return sorted(op[len(prefix):] for op, b in _BREAKERS.items()
                      if op.startswith(prefix) and b.open)


def health_snapshot() -> dict:
    """Point-in-time serving-health view: breaker states, last errors,
    and the resilience counters — the engine's ``/health`` payload."""
    from .. import obs
    from ..obs.registry import REGISTRY

    from . import integrity

    counters = {}
    for row in REGISTRY.snapshot():
        if row["name"].startswith(("resilience_", "integrity_")) and \
                row["kind"] == "counter":
            label = ",".join(f"{k}={v}" for k, v in
                             sorted(row["labels"].items()))
            counters[f"{row['name']}{{{label}}}"] = row["value"]
    with _BREAKERS_LOCK:
        breakers = {
            op: {"open": b.open, "consecutive_failures": b.failures}
            for op, b in sorted(_BREAKERS.items())
        }
    degraded_ops = sorted(op for op, b in breakers.items() if b["open"])
    out = {
        "status": "degraded" if degraded_ops else "ok",
        # the ops currently serving through their XLA fallback (open
        # breakers) — what /healthz consumers alert on by name, without
        # walking the breakers map (docs/observability.md "Live
        # telemetry")
        "degraded_ops": degraded_ops,
        # peers whose quarantine breaker is open (repeated attributable
        # corruption, resilience.integrity — /healthz flips 503 because
        # an open peer breaker lands in degraded_ops too)
        "quarantined_peers": integrity.quarantined_peers(),
        # fleet replicas whose replica:<id> breaker is open (flap
        # quarantine or hard loss, serve.fleet — same 503-via-
        # degraded_ops mechanics as the peer quarantine above)
        "quarantined_replicas": quarantined_replicas(),
        "obs_enabled": obs.enabled(),
        "breakers": breakers,
        "last_errors": dict(sorted(_LAST_ERROR.items())),
        "counters": counters,
    }
    # the continuous profiler's anomaly state (ISSUE 16): a WARNING,
    # not a status flip — /healthz must answer 200 on perf drift (the
    # load balancer sheds on 503; a slow-but-correct replica still
    # serves).  Absent when the latest window was healthy, so an
    # unarmed process's snapshot is byte-identical to before.
    from ..obs import anomaly

    frag = anomaly.health_fragment()
    if frag is not None:
        out["profile"] = frag
    # calibration-drift sentinel (ISSUE 20): live achieved wire GB/s
    # diverging from the persisted LinkCalibration for sustained
    # windows names the stale wire class here — the same WARNING-only
    # rule as the anomaly fragment above (SOL attributions rot
    # silently otherwise, but drift must never 503 a replica)
    from ..obs import continuous

    cal = continuous.calibration_fragment()
    if cal is not None:
        out["linkcal"] = cal
    return out


def _reset_state_for_tests() -> None:
    with _BREAKERS_LOCK:
        _BREAKERS.clear()
        _LAST_ERROR.clear()
