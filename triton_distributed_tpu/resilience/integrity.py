"""End-to-end data integrity: checksummed collective payloads, KV-page
audit, and quarantine recovery (``TDT_INTEGRITY=1``).

The resilience stack so far detects *liveness* failures — a lost signal
stalls, a straggler overruns, an abort truncates.  A flipped bit in a
DMA'd chunk is invisible to all of it: device-initiated transfers
bypass every host-side check (the blind spot the NVSHMEM system
analysis documents for symmetric-memory ops, PAPERS.md), the credits
balance, the protocol completes on time, and the garbage ships.  This
module makes corruption a first-class, detected, recoverable fault:

**The checksum protocol.**  The producer stamps a cheap reduction of
each tile (a position-weighted 32-bit fold of the byte view,
:func:`fold32`) into a
sideband slot alongside the semaphore credit it already sends; the
consumer verifies the stamp against the arrived bytes BEFORE the
``consume_token``-equivalent use.  Two failure kinds fall out:

- ``payload``  — the stamp does not match at arrival: the bytes changed
  IN FLIGHT (wire corruption).  Attributable to the producing peer.
- ``kv_page``  — the stamp matched at arrival but the region differs at
  consumption / audit time: the bytes changed AT REST (memory
  corruption; the paged-KV pool between scheduler steps is the serving
  instance of this class).

Three layers implement it, mirroring how the fault injector spans
record mode and live execution (docs/robustness.md):

1. **Record mode** (:func:`check_traces`): the protocol runs
   symbolically over composed per-rank traces — every ``CopyEv``
   carries its stamp, every credit-consuming wait verifies what it
   consumed — so the fault matrix's ``corrupt_payload`` /
   ``corrupt_kv_page`` cells are classified headlessly, with the
   (semaphore, chunk, peer) triple named, on a box that cannot build a
   single kernel.
2. **Live eager entries** (:func:`checked` + the ``verify_*``
   helpers): the comm/ops entry points wrap their eager call with a
   consumer-side verification pass over the host-visible global
   arrays — byte-exact fold comparison for copy-type collectives
   (AG, A2A zones land payloads verbatim), a float32 re-reduction with
   tolerance for RS/AR, and a Freivalds random-projection check for the
   fused GEMMs (O(n^2) verification of an O(n^3) product).  A mismatch
   raises :class:`~.errors.PayloadCorruption` naming (semaphore, chunk,
   peer) and rides the SAME retry -> XLA-fallback -> breaker ladder a
   timeout does (``PayloadCorruption`` is in the default retry set).
3. **The KV-pool audit** (``serve.scheduler``): full pages are stamped
   when they fill, re-verified on a periodic cadence and at
   preempt-restore; a mismatch recovers the victim through the
   preemption-recompute path — pages evicted, request re-queued,
   deterministically recomputed from its prompt — while cohabitants'
   caches stay byte-intact.

**Quarantine.**  Repeated corruption attributed to ONE peer is a sick
link/chip, not noise: :func:`note_corruption` walks a per-peer sticky
breaker (``peer:<k>`` in the shared breaker registry) toward open;
once quarantined, every guarded collective whose team includes that
peer routes straight to its XLA fallback (``policy.resilient_call``),
and the peer surfaces in ``health_snapshot()["quarantined_peers"]`` /
``/healthz``.  ``reset_breaker("peer:<k>")`` readmits after
remediation.

Everything is OFF by default: with ``TDT_INTEGRITY`` unset every guard
site costs one cached-bool check and behavior is byte-identical (the
same discipline as ``TDT_OBS`` / ``TDT_RESILIENCE`` / ``TDT_FLIGHT``).
Limits are documented, not hidden: a 32-bit fold can collide, but only
under adversarial cancellation (any single-word flip always moves it —
see :func:`fold32`); the float checks catch sign/exponent/high-mantissa
flips but not last-ulp noise; reductions mix every peer's bytes, so
their corruption is detected-but-unattributable (no quarantine).
"""

from __future__ import annotations

import os
from collections import deque

import numpy as np

from .errors import CorruptionDiagnosis, PayloadCorruption


def _env_enabled() -> bool:
    from ..core.utils import env_flag

    return env_flag("TDT_INTEGRITY")


# cached like obs/resilience: a disabled guard site pays one global load
_ENABLED = _env_enabled()


def enabled() -> bool:
    """Whether integrity verification is armed (``TDT_INTEGRITY=1`` or
    :func:`enable`, and not inside a measurement-suppression block —
    autotune sweeps must not pay or trip the checks)."""
    if not _ENABLED:
        return False
    from .. import resilience

    return not resilience._suppressed()


def enable(on: bool | None = True) -> bool:
    """Turn integrity verification on/off; ``None`` re-reads
    ``TDT_INTEGRITY``.  Returns the new state."""
    global _ENABLED
    _ENABLED = _env_enabled() if on is None else bool(on)
    return _ENABLED


# ---------------------------------------------------------------------------
# the checksum primitive


# odd multiplier pair (Knuth/Fibonacci hashing constants): each word is
# weighted by an odd per-POSITION constant before summing, so the fold
# sees position, not just value
_FOLD_MULT = np.uint64(2654435761)
_FOLD_ADD = np.uint64(2654435769)


def fold32(*arrays) -> int:
    """The sideband stamp: a position-weighted 32-bit sum fold over the
    little-endian byte view of the arrays — word ``i`` contributes
    ``w_i * ((A*i + B) | 1)`` mod 2^64, folded to 32 bits and mixed
    with the total length.  Dtype-agnostic and byte-exact (a copy-type
    collective must deliver the SAME fold); cheap enough to stamp per
    tile.  Position weighting matters: a plain XOR/sum fold is blind to
    duplicated-word payloads (broadcast KV tiles are exactly that),
    where flipping one of N identical words — or permuting chunks —
    cancels.  The ``| 1`` is equally load-bearing: ``A*i + B`` with odd
    constants is EVEN at every odd ``i``, and an even weight annihilates
    a ±2^31 word delta (a float32 sign-bit flip — the canonical SDC)
    in the surviving low 32 bits of the accumulator; forcing the weight
    odd makes every single-word change move the fold by
    ``delta * odd`` != 0 mod 2^32."""
    acc = np.uint64(0)
    offset = 0
    old = np.seterr(over="ignore")   # uint64 wraparound IS the fold
    try:
        for a in arrays:
            b = np.ascontiguousarray(np.asarray(a))
            if b.nbytes % 4 == 0 and b.nbytes:
                w = b.reshape(-1).view(np.uint32)   # zero-copy reword
            else:
                raw = b.tobytes()
                raw += b"\0" * ((-len(raw)) % 4)
                w = np.frombuffer(raw, np.uint32)
            if w.size:
                ix = np.arange(offset, offset + w.size, dtype=np.uint64)
                wt = (_FOLD_MULT * ix + _FOLD_ADD) | np.uint64(1)
                acc += (w.astype(np.uint64) * wt).sum()
                offset += int(w.size)
    finally:
        np.seterr(**old)
    return int((acc ^ np.uint64(offset)) & np.uint64(0xFFFFFFFF))


def fold_page(cache, page: int) -> int:
    """Stamp one physical KV page: the fold over its k and v slices
    across every layer (the unit the serve-loop audit verifies).  A
    QUANTIZED cache's scale sidecars fold in too — a flipped scale byte
    corrupts every element of its (page, head) block on dequant, so the
    stamp must cover it (the poisoned-scale-sidecar fault cell)."""
    p = int(page)
    parts = [np.asarray(cache.k[:, p]), np.asarray(cache.v[:, p])]
    if getattr(cache, "k_scale", None) is not None:
        parts += [np.asarray(cache.k_scale[:, p]),
                  np.asarray(cache.v_scale[:, p])]
    return fold32(*parts)


def fold_pages(cache, pages) -> dict[int, int]:
    """:func:`fold_page` for a batch, with TWO device-to-host transfers
    total (one gather each for k and v) instead of two per page — the
    shape the scheduler's periodic audit calls on the decode loop,
    where per-page transfers would serialize hundreds of small copies
    against the step."""
    ids = sorted({int(p) for p in pages})
    if not ids:
        return {}
    k = np.asarray(cache.k[:, ids])
    v = np.asarray(cache.v[:, ids])
    if getattr(cache, "k_scale", None) is not None:
        ks = np.asarray(cache.k_scale[:, ids])
        vs = np.asarray(cache.v_scale[:, ids])
        return {p: fold32(k[:, i], v[:, i], ks[:, i], vs[:, i])
                for i, p in enumerate(ids)}
    return {p: fold32(k[:, i], v[:, i]) for i, p in enumerate(ids)}


# ---------------------------------------------------------------------------
# record-mode checksum protocol (the fault matrix's corruption detector)


def check_traces(ft) -> list[CorruptionDiagnosis]:
    """Run the checksum protocol over composed (possibly corrupt)
    per-rank traces: every ``CopyEv`` carries its producer stamp; every
    credit-consuming wait verifies the batches it consumes before use.
    Returns one finding per corrupt/poisoned transfer, naming the
    (semaphore, chunk, peer) triple — or an empty list when every byte
    that arrived is a byte that was sent.

    ``ft``: a :class:`~.faults.FaultyTraces` whose ``corrupt`` set marks
    in-flight-flipped copies and whose ``poisoned`` set marks waits
    whose guarded region was flipped at rest before consumption.
    """
    from ..analysis.events import CopyEv, NotifyEv, WaitEv, sem_label

    n, traces = ft.n, ft.traces
    # per (rank, sem) FIFO of credit batches:
    # [amount, src_rank, chunk_label, corrupt_flag_box]
    queues: dict[tuple[int, tuple], deque] = {}
    pcs = [0] * n
    findings: list[CorruptionDiagnosis] = []
    poisoned_reported: set[tuple[int, int]] = set()

    def push(rank, sem, amount, src, chunk, corrupt):
        queues.setdefault((rank, sem), deque()).append(
            [amount, src, chunk, [corrupt]])

    def avail(rank, sem) -> int:
        return sum(b[0] for b in queues.get((rank, sem), ()))

    def consume(r, ev, pos) -> bool:
        if avail(r, ev.sem) < ev.amount:
            return False
        need = ev.amount
        q = queues[(r, ev.sem)]
        at_rest = (r, pos) in ft.poisoned and (r, pos) not in \
            poisoned_reported
        while need > 0:
            batch = q[0]
            take = min(need, batch[0])
            batch[0] -= take
            need -= take
            if batch[3][0]:
                # the consumer's verify: the stamp that rode the credit
                # does not match the bytes in the region
                batch[3][0] = False    # one finding per corrupt transfer
                findings.append(CorruptionDiagnosis(
                    op=ft.kernel, kind="payload",
                    sem=sem_label(ev.sem), chunk=batch[2], peer=batch[1],
                    note="checksum mismatch at arrival: bytes flipped "
                         "in flight",
                ))
            if at_rest and batch[2] is not None:
                poisoned_reported.add((r, pos))
                at_rest = False
                findings.append(CorruptionDiagnosis(
                    op=ft.kernel, kind="kv_page",
                    sem=sem_label(ev.sem), chunk=batch[2], peer=batch[1],
                    note="stamp verified at arrival but the region "
                         "differs at consumption: bytes flipped at rest",
                ))
            if batch[0] == 0:
                q.popleft()
        if at_rest:
            # the poisoned wait consumed only non-copy credits: still a
            # detection, without a region to name
            poisoned_reported.add((r, pos))
            findings.append(CorruptionDiagnosis(
                op=ft.kernel, kind="kv_page", sem=sem_label(ev.sem),
                note="guarded region poisoned at rest before consumption",
            ))
        return True

    def step(r) -> bool:
        if pcs[r] >= len(traces[r]):
            return False
        ev = traces[r][pcs[r]]
        if isinstance(ev, WaitEv):
            if not consume(r, ev, pcs[r]):
                return False
        elif isinstance(ev, NotifyEv):
            push(ev.target, ev.sem, ev.amount, r, None, False)
        elif isinstance(ev, CopyEv):
            if ev.send_sem is not None:
                push(r, ev.send_sem, ev.src.elements(), r, None, False)
            if (r, pcs[r]) not in ft.drop_recv:
                push(ev.dst_rank, ev.recv_sem, ev.dst.elements(), r,
                     ev.dst.label(), (r, pcs[r]) in ft.corrupt)
        pcs[r] += 1
        return True

    progress = True
    while progress:
        progress = False
        for r in range(n):
            while step(r):
                progress = True

    # a corrupt transfer whose credits were never consumed was never
    # verified — that is ITSELF a protocol hole worth naming
    for (rank, sem), q in sorted(queues.items()):
        for batch in q:
            if batch[3][0]:
                findings.append(CorruptionDiagnosis(
                    op=ft.kernel, kind="payload", sem=sem_label(sem),
                    chunk=batch[2], peer=batch[1],
                    note="corrupt transfer never consumed: no verify "
                         "point guards this region",
                ))
    return findings


# ---------------------------------------------------------------------------
# live consumer-side verification (the eager comm/ops entry points)

# float checks: catches sign/exponent/high-mantissa flips; rtol leaves
# room for accumulation-order differences between the device reduction
# and the host float32 re-reduction
_RTOL = 1e-2


def _rademacher(shape_key: tuple, n: int) -> np.ndarray:
    """Deterministic ±1 projection vector (seeded by the shape class, so
    repeated calls at one config verify the same projection — ACROSS
    processes too: ``hash()`` is PYTHONHASHSEED-randomized, which would
    make a marginal Freivalds verdict unreproducible in a debug run)."""
    import zlib

    rng = np.random.default_rng(
        zlib.crc32(repr(("tdt-integrity", shape_key, n)).encode()))
    return rng.integers(0, 2, size=n).astype(np.float32) * 2.0 - 1.0


def verify_gather(op: str, x, out, n: int) -> CorruptionDiagnosis | None:
    """AllGather delivers every shard verbatim: the fold of input chunk
    ``k`` must equal the fold of output chunk ``k`` EXACTLY.  A mismatch
    is attributable: chunk ``k``'s producer is rank ``k``."""
    xa, oa = np.asarray(x), np.asarray(out)
    m = xa.shape[0] // n
    for k in range(n):
        if fold32(xa[k * m:(k + 1) * m]) != fold32(oa[k * m:(k + 1) * m]):
            return CorruptionDiagnosis(
                op=op, kind="payload", sem=f"recv_sems[{k}]",
                chunk=f"out[{k * m}:{(k + 1) * m}]", peer=k,
                note="fold32 mismatch between the shard sent and the "
                     "chunk received")
    return None


def _verify_float(op: str, got: np.ndarray, want: np.ndarray,
                  chunk_of, mag: np.ndarray | None = None,
                  rtol: float = _RTOL) -> CorruptionDiagnosis | None:
    """``mag`` is the per-element ACCUMULATED magnitude (sum of the
    |partials| that met at that element) — the same bound
    :func:`verify_gemm` uses.  Bounding against it, not the global max
    of the (possibly cancelling) result, keeps small-magnitude elements
    checkable: under a global-max bound any element below ~rtol*max
    could be corrupted arbitrarily within that window undetected."""
    if mag is None:
        mag = np.abs(want.astype(np.float64))
    err = np.abs(got.astype(np.float64) - want.astype(np.float64))
    bad = np.argwhere(err > rtol * np.maximum(mag, 1.0))
    if bad.size == 0:
        return None
    idx = tuple(int(i) for i in bad[0])
    return CorruptionDiagnosis(
        op=op, kind="payload", chunk=chunk_of(idx), peer=None,
        note=f"re-reduction mismatch at {idx}: |err| "
             f"{float(err[idx]):.3g} > tol (reductions mix every "
             f"peer's bytes — unattributable)")


def verify_reduce(op: str, x, out, n: int) -> CorruptionDiagnosis | None:
    """RS/AR: re-reduce the stacked partials in float32 and compare
    within tolerance.  ``x``: (n*M, R) stacked partials; ``out``:
    (M, R) — ONE signature for both ops: in global semantics RS's
    stacked row-chunks and AR's replicated output are the same full
    sum.

    Tolerance scales with the rank count and the OUTPUT dtype's ulp: a
    ring reduction accumulating in the wire dtype legitimately rounds
    each of its n-1 steps (worst case ~(n-1)·eps/2 relative for bf16
    two-shot), and a fixed 1% bound would flag healthy bf16 AR — a
    deterministic false positive the retry reproduces, permanently
    degrading the op.  Real SDC (sign/exponent/high-mantissa flips)
    lands orders of magnitude outside either bound."""
    xa = np.asarray(x).astype(np.float32)
    oa = np.asarray(out)
    m = oa.shape[0]
    want = xa.reshape(n, m, *xa.shape[1:]).sum(axis=0).astype(oa.dtype)
    mag = np.abs(xa).reshape(n, m, *xa.shape[1:]).sum(axis=0)
    try:
        # ml_dtypes.finfo covers bf16/fp8 AND the standard floats;
        # numpy's own finfo rejects the extension dtypes
        import ml_dtypes

        eps = float(ml_dtypes.finfo(oa.dtype).eps)
    except (ImportError, ValueError):
        try:
            eps = float(np.finfo(oa.dtype).eps)
        except ValueError:
            # non-float payloads keep the generic bound (the f32
            # re-reduction itself is inexact above 2^24, so this check
            # is tolerance-based for every dtype)
            eps = 0.0
    rtol = max(_RTOL, 2.0 * max(n - 1, 1) * eps)
    return _verify_float(op, np.asarray(oa), np.asarray(want),
                         lambda idx: f"out[{idx[0]}]", mag=mag, rtol=rtol)


def verify_reduce_q(op: str, x, out, n: int, wire_dtype: str, *,
                    residual=None,
                    two_hop: bool = False) -> CorruptionDiagnosis | None:
    """The quantized analogue of :func:`verify_reduce`: the golden is
    the CODEC-AWARE reduction (``lang.quant.reduce_roundtrip`` — each
    chunk partial round-trips through the wire codec, then an f32 sum;
    ``two_hop`` adds the AR return hop's second round-trip, and
    ``residual`` folds an error-feedback carry into the inputs), so the
    tolerance stays TIGHT — the codec's own error is in the golden, not
    the error budget, and a flipped payload or scale-sidecar byte lands
    far outside it."""
    import jax.numpy as jnp

    from ..lang import quant

    xa = np.asarray(x).astype(np.float32)
    oa = np.asarray(out)
    m = xa.shape[0] // n            # per-rank partial rows
    m_loc = m // n                  # chunk rows
    r = xa.shape[1]
    chunks = xa.reshape(n, n, m_loc, r)      # [rank, chunk, rows, r]
    if residual is not None:
        chunks = chunks + np.asarray(residual, np.float32).reshape(
            n, n, m_loc, r)
    rt = np.asarray(quant.roundtrip_rows(
        jnp.asarray(chunks), wire_dtype, out_dtype=jnp.float32))
    want = rt.sum(axis=0)                    # [chunk, rows, r]
    if two_hop:
        # the device casts the reduced chunk to the OUT dtype before
        # re-packing it (``red.astype(out_dtype)`` in ``_build_q_ar``);
        # requantizing from uncast f32 can land one codec ulp away
        # wherever the cast crosses a rounding boundary — a false
        # PayloadCorruption, so the golden must take the same cast
        want = np.asarray(quant.roundtrip_rows(
            jnp.asarray(want).astype(oa.dtype), wire_dtype,
            out_dtype=jnp.float32))
    want = want.reshape(n * m_loc, r).astype(oa.dtype)
    # accumulated-magnitude bound, like verify_reduce; floor the rtol at
    # one output-dtype ulp class (the device reduce may reorder)
    mag = np.abs(rt).sum(axis=0).reshape(n * m_loc, r)
    return _verify_float(op, np.asarray(oa), want,
                         lambda idx: f"out[{idx[0]}]", mag=mag,
                         rtol=_RTOL)


def verify_gemm(op: str, a, b, out) -> CorruptionDiagnosis | None:
    """Freivalds check for the fused GEMM+collective ops: with a seeded
    ±1 vector ``v``, ``out @ v`` must match ``A @ (B @ v)`` — O(n^2)
    verification of the O(n^3) product, catching any corruption that
    perturbs a row of the result beyond float noise."""
    aa = np.asarray(a).astype(np.float32)
    ba = np.asarray(b).astype(np.float32)
    oa = np.asarray(out).astype(np.float32)
    v = _rademacher((aa.shape, ba.shape), ba.shape[1])
    got = oa @ v
    want = aa @ (ba @ v)
    # tolerance against the magnitude actually accumulated, not the
    # (possibly cancelling) result
    mag = np.abs(aa) @ (np.abs(ba) @ np.abs(v))
    err = np.abs(got - want)
    bad = np.argwhere(err > _RTOL * np.maximum(mag, 1.0))
    if bad.size == 0:
        return None
    row = int(bad[0][0])
    return CorruptionDiagnosis(
        op=op, kind="payload", chunk=f"out[{row}, :]", peer=None,
        note=f"Freivalds projection mismatch on row {row}: |err| "
             f"{float(err[row]):.3g}")


def _a2a_meta(splits, n: int):
    """The zone geometry, from its ONE home (``fallbacks._a2a_geometry``
    — the same math the degraded path gathers by), as host arrays."""
    from .fallbacks import _a2a_geometry

    sp, per_peer, offs = _a2a_geometry(np.asarray(splits), n)
    return np.asarray(sp), np.asarray(per_peer), np.asarray(offs)


def verify_ep_dispatch(op: str, x, splits, result,
                       n: int) -> CorruptionDiagnosis | None:
    """Dispatch lands each (src, dst) row block verbatim at the head of
    zone ``dst*n + src``: fold-exact per block, peer-attributable."""
    recv, _ = result
    xa, ra = np.asarray(x), np.asarray(recv)
    t = xa.shape[0] // n
    _, per_peer, offs = _a2a_meta(splits, n)
    for r in range(n):
        for p in range(n):
            cnt = int(per_peer[p, r])
            if cnt == 0:
                continue
            o = int(offs[p, r])
            if fold32(xa[p * t + o:p * t + o + cnt]) != \
                    fold32(ra[r * n + p, :cnt]):
                return CorruptionDiagnosis(
                    op=op, kind="payload", sem=f"recv_sems[{p}]",
                    chunk=f"recv[{r * n + p}][0:{cnt}]", peer=p,
                    note="fold32 mismatch on the dispatched row block")
    return None


def verify_ep_combine(op: str, y, splits, out, n: int,
                      token_dim: int) -> CorruptionDiagnosis | None:
    """Combine returns zone ``dst*n + src``'s head rows verbatim into
    src's sorted row block [offs, offs+cnt): fold-exact per block."""
    ya, oa = np.asarray(y), np.asarray(out)
    t = token_dim
    _, per_peer, offs = _a2a_meta(splits, n)
    for p in range(n):          # owner rank receiving its rows back
        for r in range(n):      # peer that processed them
            cnt = int(per_peer[p, r])
            if cnt == 0:
                continue
            o = int(offs[p, r])
            if fold32(ya[r * n + p, :cnt]) != \
                    fold32(oa[p * t + o:p * t + o + cnt]):
                return CorruptionDiagnosis(
                    op=op, kind="payload", sem=f"recv_sems[{r}]",
                    chunk=f"out[{p * t + o}:{p * t + o + cnt}]", peer=r,
                    note="fold32 mismatch on the returned row block")
    return None


# conservative host verification throughput: device->host transfer of
# the result plus the numpy fold/re-reduction — far below the wire SOL
# the watchdog prices collectives at
_VERIFY_GBPS = 0.5


def verify_budget_ms(payload_bytes: int, ranks: int | None = None) -> float:
    """Extra watchdog budget for the consumer-side verification that
    runs INSIDE the guarded thunk (``policy.guarded`` adds this to the
    wire-SOL deadline).  Without it, arming integrity on a fast slice
    would make every healthy call breach a deadline priced for the wire
    alone — the verify materializes the full gathered result on the
    host, orders of magnitude slower than ICI.  Zero when integrity is
    off (the deadline is byte-identical)."""
    if not enabled():
        return 0.0
    n = max(int(ranks or 1), 1)
    # the checks touch the inputs plus the (up to n x payload) result
    total = max(int(payload_bytes), 0) * (n + 1)
    return total / (_VERIFY_GBPS * 1e9) * 1e3 + 50.0


# ---------------------------------------------------------------------------
# quarantine: per-peer sticky breakers over repeated attributable
# corruption

_QUARANTINE_PREFIX = "peer:"


def quarantine_threshold() -> int:
    try:
        return int(os.environ.get("TDT_QUARANTINE_THRESHOLD", "") or 3)
    except ValueError:
        return 3


def note_corruption(op: str, peer: int | None) -> bool:
    """Record one corruption attributed to ``peer`` (None = reduction
    output, unattributable — rides the ladder, never quarantines).
    Returns True when this corruption OPENED the peer's quarantine."""
    if peer is None:
        return False
    from . import policy

    opened = policy.breaker(f"{_QUARANTINE_PREFIX}{int(peer)}",
                            quarantine_threshold()).record_failure()
    _publish_gauge()
    return opened


def note_clean(ranks: int | None) -> None:
    """A verified-clean collective resets the consecutive-corruption
    count of every participating peer (open quarantines stay open —
    sticky, like every breaker: readmission is an operator decision)."""
    if not ranks:
        return
    from .policy import _BREAKERS, _BREAKERS_LOCK

    with _BREAKERS_LOCK:
        peers = [b for op, b in _BREAKERS.items()
                 if op.startswith(_QUARANTINE_PREFIX)
                 and int(op[len(_QUARANTINE_PREFIX):]) < int(ranks)]
    for b in peers:
        b.record_success()


def quarantined_peers() -> list[int]:
    """Logical peer ids whose quarantine breaker is open."""
    from .policy import _BREAKERS, _BREAKERS_LOCK

    with _BREAKERS_LOCK:
        return sorted(
            int(op[len(_QUARANTINE_PREFIX):])
            for op, b in _BREAKERS.items()
            if op.startswith(_QUARANTINE_PREFIX) and b.open)


def quarantine_blocks(ranks: int | None) -> bool:
    """Whether a guarded collective over ``ranks`` peers should route
    straight to its XLA fallback: integrity armed and some team member
    quarantined (``policy.resilient_call`` consults this)."""
    if ranks is None or not enabled():
        return False
    return any(p < int(ranks) for p in quarantined_peers())


def reset_quarantine(peer: int | None = None) -> None:
    """Readmit ``peer`` (None = all) after remediation."""
    from . import policy

    if peer is not None:
        policy.reset_breaker(f"{_QUARANTINE_PREFIX}{int(peer)}")
    else:
        for p in quarantined_peers():
            policy.reset_breaker(f"{_QUARANTINE_PREFIX}{p}")
    _publish_gauge()


def _publish_gauge() -> None:
    from .. import obs

    if obs.enabled():
        obs.gauge("quarantined_peers").set(float(len(quarantined_peers())))


# ---------------------------------------------------------------------------
# the entry-point wrapper


def checked(op: str, thunk, verify, *, ranks: int | None = None):
    """Wrap an eager collective thunk with consumer-side verification:
    run it, consult the live fault scope's corruption lever (so
    ``corrupt_payload``/``corrupt_kv_page`` specs inject through real
    entry points), verify the result, and on mismatch bump the
    ``integrity_failures`` counter, feed the peer's quarantine, and
    raise :class:`PayloadCorruption` — which rides the resilience
    ladder (retry -> XLA fallback -> breaker) exactly like a timeout.
    ``verify(result) -> CorruptionDiagnosis | None``."""
    from .. import obs
    from ..lang import primitives as dl

    def run():
        out = thunk()
        scope = dl.active_fault_scope()
        if scope is not None:
            out = scope.corrupt_result(out)
        if obs.enabled():
            obs.counter("integrity_checks", op=op).inc()
        diag = verify(out)
        if diag is None:
            note_clean(ranks)
            return out
        if obs.enabled():
            obs.counter("integrity_failures", op=op, kind=diag.kind).inc()
        note_corruption(op, diag.peer)
        raise PayloadCorruption(op, diag)

    return run


# ---------------------------------------------------------------------------
# quantized-wire selftest battery (scripts/tdt_lint.py --quant)


def run_quant_selftest() -> list[str]:
    """The codec-integrity battery behind ``tdt_lint --quant``: every
    wire codec round-trips inside its documented error envelope
    (including the all-negative / denormal / absmax-zero edge rows), the
    quantized-reduce verifier passes clean and catches a perturbation,
    and a POISONED SCALE SIDECAR — the quantized wire's new failure
    surface: 4 bytes that corrupt a whole row on dequant — is (a) caught
    byte-exactly by the wire checksum and (b) catastrophic enough that
    the dequant-parity tolerance could never absorb it.  Returns
    problems (empty = pass)."""
    import jax.numpy as jnp

    from ..lang import quant

    problems: list[str] = []
    rng = np.random.default_rng(11)
    h = 64
    rows = np.stack([
        rng.standard_normal(h) * 3.0,            # generic
        -np.abs(rng.standard_normal(h)) - 0.5,   # all-negative
        rng.standard_normal(h) * 1e-30,          # denormal-range values
        np.zeros(h),                             # absmax-zero row
    ]).astype(np.float32)
    for wd in quant.QUANTIZED_WIRE_DTYPES:
        x = jnp.asarray(rows)
        back = np.asarray(quant.roundtrip_rows(x, wd,
                                               out_dtype=jnp.float32))
        bound = quant.rel_error_bound(wd)
        absmax = np.abs(rows).max(axis=-1, keepdims=True)
        err = np.abs(back - rows)
        tol = np.asarray(quant.abs_error_bound(absmax, wd)) * (1 + 1e-5)
        if (err > tol).any():
            problems.append(
                f"{wd}: round-trip error {err.max():.3g} outside the "
                f"documented envelope (bound {bound})")
        # the packed wire message round-trips equivalently
        packed = np.asarray(quant.pack_rows(x, wd))
        if packed.shape != (rows.shape[0], h + quant.SIDECAR):
            problems.append(f"{wd}: packed shape {packed.shape} wrong")
        unpacked = np.asarray(quant.unpack_rows(
            jnp.asarray(packed), h, wd, jnp.float32))
        if not np.allclose(unpacked, back, atol=1e-6):
            problems.append(f"{wd}: pack/unpack disagrees with the bare "
                            f"codec round-trip")

        # poisoned scale sidecar: flip EXPONENT bits of the f32 scale
        # riding row 0's message (the canonical SDC class — a sign/
        # exponent flip moves the scale by binades, corrupting every
        # element of the row on dequant)
        poisoned = packed.copy()
        poisoned[0, h + 3] ^= 0x14
        if fold32(packed) == fold32(poisoned):
            problems.append(f"{wd}: fold32 missed a flipped scale-"
                            f"sidecar byte")
        bad = np.asarray(quant.unpack_rows(
            jnp.asarray(poisoned), h, wd, jnp.float32))
        delta = np.abs(bad[0] - back[0]).max()
        ref = max(float(np.abs(back[0]).max()), 1e-30)
        if not (delta > 10 * bound * ref or not np.isfinite(delta)):
            problems.append(
                f"{wd}: a poisoned scale sidecar moved dequant by only "
                f"{delta:.3g} — inside what parity tolerance could "
                f"absorb; the wire checksum must be the guard")

        # quantized-reduce verifier: clean pass, perturbation caught
        n = 4
        m_loc, r = 4, 16
        parts = rng.standard_normal((n, n * m_loc, r)).astype(np.float32)
        golden = np.asarray(quant.reduce_roundtrip(
            jnp.asarray(parts.reshape(n, n, m_loc, r)), wd,
            out_dtype=jnp.float32)).reshape(n * m_loc, r)
        if verify_reduce_q("q_rs", parts.reshape(n * n * m_loc, r),
                           golden, n, wd) is not None:
            problems.append(f"{wd}: verify_reduce_q flagged a clean "
                            f"quantized reduction")
        bad_out = golden.copy()
        bad_out[1, 2] += 10.0 * max(1.0, abs(float(bad_out[1, 2])))
        if verify_reduce_q("q_rs", parts.reshape(n * n * m_loc, r),
                           bad_out, n, wd) is None:
            problems.append(f"{wd}: verify_reduce_q missed a large "
                            f"perturbation")
        # the AR two-hop shape with the device's bf16 out-dtype cast
        # BEFORE the return-hop requantization (``_build_q_ar``): a
        # healthy device output must verify clean — the golden takes
        # the same cast, else elements near a codec rounding boundary
        # are a false PayloadCorruption
        dev = np.asarray(quant.roundtrip_rows(
            jnp.asarray(golden).astype(jnp.bfloat16), wd,
            out_dtype=jnp.bfloat16))
        if verify_reduce_q("q_ar", parts.reshape(n * n * m_loc, r),
                           dev, n, wd, two_hop=True) is not None:
            problems.append(f"{wd}: verify_reduce_q(two_hop) flagged a "
                            f"clean quantized AllReduce")
    return problems


# ---------------------------------------------------------------------------
# selftest battery (scripts/tdt_lint.py --integrity)


def run_selftest() -> list[str]:
    """Seeded-bad verification battery: every live verifier must catch a
    planted flip AND pass the clean input; quarantine must open at the
    threshold.  Returns problems (empty = pass)."""
    problems: list[str] = []
    rng = np.random.default_rng(7)

    def flip_one(a, byte=5):
        b = np.array(a)
        b.reshape(-1).view(np.uint8)[byte] ^= 0x42
        return b

    # gather: exact fold per chunk, peer named
    x = rng.standard_normal((8, 16)).astype(np.float32)
    if verify_gather("ag", x, x.copy(), 4) is not None:
        problems.append("verify_gather flagged a clean gather")
    bad = flip_one(x.copy().reshape(-1)).reshape(8, 16)
    d = verify_gather("ag", x, bad, 4)
    if d is None or d.peer != 0 or not d.chunk:
        problems.append(f"verify_gather missed a flipped byte or lost "
                        f"attribution: {d}")

    # reduce: float re-reduction with tolerance
    xs = rng.standard_normal((16, 8)).astype(np.float32)
    out = xs.reshape(4, 4, 8).sum(0)
    if verify_reduce("ar", xs, out, 4) is not None:
        problems.append("verify_reduce flagged a clean reduction")
    bad = out.copy()
    bad[2, 3] += 10.0 * max(1.0, abs(float(bad[2, 3])))
    if verify_reduce("ar", xs, bad, 4) is None:
        problems.append("verify_reduce missed a large perturbation")

    # Freivalds
    a = rng.standard_normal((12, 6)).astype(np.float32)
    b = rng.standard_normal((6, 10)).astype(np.float32)
    good = a @ b
    if verify_gemm("ag_gemm", a, b, good) is not None:
        problems.append("verify_gemm flagged a clean product")
    bad = good.copy()
    bad[3, 4] += 50.0
    if verify_gemm("ag_gemm", a, b, bad) is None:
        problems.append("verify_gemm missed a perturbed row")

    # quarantine walk + readmission
    from . import policy

    probe = 97   # a peer id no real mesh reaches
    policy.reset_breaker(f"{_QUARANTINE_PREFIX}{probe}")
    opened = False
    for _ in range(max(quarantine_threshold(), 1)):
        opened = note_corruption("selftest", probe)
    if not opened or probe not in quarantined_peers():
        problems.append("quarantine did not open at the threshold")
    reset_quarantine(probe)
    if probe in quarantined_peers():
        problems.append("reset_quarantine did not readmit the peer")
    return problems
