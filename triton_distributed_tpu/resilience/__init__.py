"""Runtime fault tolerance: bounded collectives, fault injection, and
graceful degradation.

Every collective in this framework ultimately spins on a semaphore
(``lang/primitives.py``), and a device-side spin has no timeout — the
failure mode device-initiated symmetric-memory communication is known
for ("Demystifying NVSHMEM", PAPERS.md).  PR 2 (``tdt.analysis``) made
the protocols statically verifiable; this package is the RUNTIME
counterpart — detect a stuck collective, name the offending
semaphore/chunk, and keep serving.  Three pillars
(docs/robustness.md):

- ``resilience.faults``    seedable, scoped fault injection hooked into
  the same primitives-layer interception points the analysis recorder
  uses (dropped/delayed notifies, stale recv credits, stragglers, rank
  aborts), composable with record mode and — for the signal-shaped
  classes — live kernels.
- ``resilience.watchdog`` + ``resilience.simulate``   bounded waits: a
  host-side deadline derived from ``tools/perf_model`` estimates x
  ``TDT_WATCHDOG_SLACK``, raising :class:`CollectiveTimeoutError` with
  a protocol-state diagnosis instead of hanging; the simulator executes
  (faulty) recorded traces under tick deadlines and produces the same
  named diagnosis.
- ``resilience.policy`` + ``resilience.fallbacks``   the per-op failure
  ladder: retry with backoff -> degrade to the equivalent ``jax.lax``
  XLA collective -> sticky circuit breaker; health snapshot for the
  engine's serve loop.

Everything is OFF by default and gated by ``TDT_RESILIENCE=1`` (or
:func:`enable`): a disabled guard site costs one cached-bool check and
the collective entry points behave exactly as before.
"""

from __future__ import annotations

from . import fallbacks, faults, integrity, matrix, policy, simulate, watchdog
from .errors import (
    CircuitOpenError,
    CollectiveTimeoutError,
    CorruptionDiagnosis,
    PayloadCorruption,
    PendingWait,
    TimeoutDiagnosis,
)
from .faults import (
    CORRUPTION_KINDS,
    FAULT_KINDS,
    FaultKind,
    FaultScope,
    FaultSpec,
    FaultyTraces,
    RankAborted,
    record_faulty_case,
    sample_spec,
    scoped,
)
from .matrix import (
    run_fleet_matrix,
    run_handoff_matrix,
    run_hier_cells,
    run_integrity_cells,
    run_matrix,
    run_persistent_cells,
    run_quant_cells,
    run_scheduler_matrix,
    verify_fleet_matrix,
    verify_handoff_matrix,
    verify_matrix,
    verify_scheduler_matrix,
)
from .policy import (
    DEFAULT_POLICY,
    AdmissionGovernor,
    CircuitBreaker,
    RetryPolicy,
    breaker,
    guarded,
    health_snapshot,
    quarantined_replicas,
    reset_breaker,
    resilient_call,
)
from .simulate import SimResult, check_hazards, clean_ticks, run_bounded
from .watchdog import call_with_deadline, deadline_ms, protocol_pending

__all__ = [
    "AdmissionGovernor", "CORRUPTION_KINDS", "CircuitBreaker",
    "CircuitOpenError", "CollectiveTimeoutError", "CorruptionDiagnosis",
    "DEFAULT_POLICY", "FAULT_KINDS", "FaultKind", "FaultScope", "FaultSpec",
    "FaultyTraces", "PayloadCorruption", "PendingWait", "RankAborted",
    "RetryPolicy", "SimResult",
    "TimeoutDiagnosis", "breaker", "call_with_deadline", "check_hazards",
    "clean_ticks", "deadline_ms", "enable", "enabled", "fallbacks", "faults",
    "guarded", "health_snapshot", "integrity", "matrix", "policy",
    "protocol_pending", "quarantined_replicas",
    "record_faulty_case", "reset_breaker", "resilient_call", "run_bounded",
    "run_fleet_matrix", "run_handoff_matrix", "run_hier_cells",
    "run_integrity_cells",
    "run_matrix", "run_persistent_cells", "run_quant_cells",
    "run_scheduler_matrix",
    "sample_spec", "scoped",
    "simulate", "suppress", "suppressed_thunk", "verify_fleet_matrix",
    "verify_handoff_matrix",
    "verify_matrix", "verify_scheduler_matrix", "watchdog",
]


def _env_enabled() -> bool:
    from ..core.utils import env_flag

    return env_flag("TDT_RESILIENCE")


# cached like obs._ENABLED: a disabled guard site pays one global load
_ENABLED = _env_enabled()

import contextlib as _contextlib
import threading as _threading

_tls = _threading.local()


def _suppressed() -> bool:
    from .. import obs

    # measurement-only traffic must not ride the failure ladder: a
    # deliberately timed slow candidate would burn a watchdog deadline,
    # feed the FALLBACK's time to the tuner, and walk the sticky
    # breaker toward open.  Both this package's own suppression and
    # obs's (the marker every measurement path already sets: autotune
    # sweeps, serve warmup) disarm the guards on this thread.
    return getattr(_tls, "depth", 0) > 0 or obs._suppressed()


def enabled() -> bool:
    """Whether the runtime guards are active (``TDT_RESILIENCE=1`` or
    :func:`enable`, and not inside a :func:`suppress` /
    ``obs.suppress`` block on this thread)."""
    return _ENABLED and not _suppressed()


def enable(on: bool | None = True) -> bool:
    """Turn the runtime guards on/off; ``None`` re-reads
    ``TDT_RESILIENCE``.  Returns the new state."""
    global _ENABLED
    _ENABLED = _env_enabled() if on is None else bool(on)
    return _ENABLED


@_contextlib.contextmanager
def suppress():
    """Disarm the runtime guards on this thread (measurement-only
    traffic — see :func:`_suppressed`)."""
    _tls.depth = getattr(_tls, "depth", 0) + 1
    try:
        yield
    finally:
        _tls.depth -= 1


def suppressed_thunk(f):
    """Wrap a measurement thunk so every later invocation runs
    unguarded (``tune.autotuner`` wraps each candidate once)."""
    def g():
        with suppress():
            return f()
    return g
