"""Bounded execution of composed (possibly faulty) protocol traces.

The static verifier's scheduler (``analysis.checks._simulate``) answers
"does an execution exist?"; this module answers the RUNTIME questions a
watchdog needs: *when* does the protocol finish under injected timing
faults, and — when it cannot finish — *which* semaphore/chunk is it
stuck on?  The model is a discrete-tick maximal execution:

- every executed event advances its rank's local clock by one tick;
- a wait completes at ``max(own clock, ready time of the credits it
  consumes) + 1`` — an injected delivery delay (DELAY_NOTIFY) or entry
  delay (STRAGGLER) therefore propagates through the wait-for structure
  exactly like real skew;
- credit AVAILABILITY ignores ready times (credits only ever accumulate,
  so the maximal execution stays schedule-insensitive: a rank blocks iff
  it blocks in every interleaving);
- a dropped completion signal (``drop_recv``) issues the data write but
  never credits the recv semaphore; an aborted rank's trace simply ends.

``run_bounded`` returns a :class:`SimResult` on completion and raises
:class:`~.errors.CollectiveTimeoutError` on a permanent stall, with the
pending semaphores, missing destination chunks, responsible source ranks
and the wait-for cycle named.  ``check_hazards`` runs the signal-balance
and unsettled-write checks over the same faulty traces — the detector
for faults that do NOT stall (a stale credit lets the protocol "finish"
with corrupt data; the surplus/unsettled write names it).
"""

from __future__ import annotations

import dataclasses
from collections import deque

from ..analysis.events import CopyEv, NotifyEv, WaitEv, sem_label
from .errors import CollectiveTimeoutError, PendingWait, TimeoutDiagnosis
from .faults import FaultyTraces


@dataclasses.dataclass(frozen=True)
class SimResult:
    kernel: str
    n: int
    ticks: int                       # completion time (max rank clock)
    clean_ticks: int | None = None   # fault-free completion, if computed


@dataclasses.dataclass
class _Credit:
    amount: int
    ready: int           # tick at which the credit becomes consumable


def run_bounded(ft: FaultyTraces, *, deadline_ticks: int | None = None,
                op: str | None = None) -> SimResult:
    """Execute the composed traces to completion or a provable stall.

    ``deadline_ticks`` bounds the COMPLETION time: a protocol that
    finishes later than the deadline (straggler/delay beyond the slack)
    raises the same :class:`CollectiveTimeoutError` a host watchdog
    would, with the overrun described.  ``None`` = unbounded (only
    permanent stalls raise).
    """
    n, traces = ft.n, ft.traces
    op = op or ft.kernel
    credits: dict[tuple[int, tuple], deque[_Credit]] = {}
    pcs = [0] * n
    clocks = [ft.start_delay.get(r, 0) for r in range(n)]

    def add_credit(rank, sem, amount, ready):
        credits.setdefault((rank, sem), deque()).append(
            _Credit(amount, ready))

    def available(rank, sem) -> int:
        return sum(c.amount for c in credits.get((rank, sem), ()))

    def step(r) -> bool:
        if pcs[r] >= len(traces[r]):
            return False
        ev = traces[r][pcs[r]]
        t = clocks[r]
        if isinstance(ev, WaitEv):
            if available(r, ev.sem) < ev.amount:
                return False
            need = ev.amount
            q = credits[(r, ev.sem)]
            latest = t
            while need > 0:
                c = q[0]
                take = min(need, c.amount)
                c.amount -= take
                need -= take
                latest = max(latest, c.ready)
                if c.amount == 0:
                    q.popleft()
            clocks[r] = latest + 1
        elif isinstance(ev, NotifyEv):
            ready = t + ft.notify_delay.get((r, pcs[r]), 0)
            add_credit(ev.target, ev.sem, ev.amount, ready)
            clocks[r] = t + 1
        elif isinstance(ev, CopyEv):
            if ev.send_sem is not None:
                add_credit(r, ev.send_sem, ev.src.elements(), t)
            if (r, pcs[r]) not in ft.drop_recv:
                add_credit(ev.dst_rank, ev.recv_sem, ev.dst.elements(), t)
            clocks[r] = t + 1
        else:  # ComputeEv and anything credit-neutral
            clocks[r] = t + 1
        pcs[r] += 1
        return True

    progress = True
    while progress:
        progress = False
        for r in range(n):
            while step(r):
                progress = True

    if all(pcs[r] >= len(traces[r]) for r in range(n)):
        ticks = max(clocks) if clocks else 0
        if deadline_ticks is not None and ticks > deadline_ticks:
            slow = max(range(n), key=lambda r: clocks[r])
            raise CollectiveTimeoutError(op, float(deadline_ticks),
                TimeoutDiagnosis(
                    ft.kernel, n, aborted=tuple(sorted(ft.aborted)),
                    note=(f"completed at tick {ticks} > deadline "
                          f"{deadline_ticks} (rank {slow} finished last — "
                          f"straggler/delayed-signal beyond the watchdog "
                          f"slack)"),
                ))
        return SimResult(ft.kernel, n, ticks)

    # permanent stall: name every blocked wait, its missing producer,
    # and the wait-for cycle
    blocked = {r: traces[r][pcs[r]] for r in range(n)
               if pcs[r] < len(traces[r])}
    pending: list[PendingWait] = []
    edges: dict[int, set[int]] = {}
    for r, ev in sorted(blocked.items()):
        chunk = source = None
        producers: set[int] = set()
        for p in range(n):
            for evp in traces[p][pcs[p]:]:
                if isinstance(evp, NotifyEv) and evp.target == r \
                        and evp.sem == ev.sem:
                    producers.add(p)
                elif isinstance(evp, CopyEv) and evp.dst_rank == r \
                        and evp.recv_sem == ev.sem:
                    producers.add(p)
                    chunk, source = evp.dst.label(), p
        if chunk is None:
            # the transfer may have EXECUTED with its signal dropped
            for (p, pos) in ft.drop_recv:
                evp = traces[p][pos]
                if evp.dst_rank == r and evp.recv_sem == ev.sem:
                    chunk, source = evp.dst.label(), p
        if source is None and ft.aborted:
            source = next(iter(sorted(ft.aborted)))
        pending.append(PendingWait(
            r, sem_label(ev.sem), ev.amount, available(r, ev.sem),
            pcs[r], chunk=chunk, source=source,
        ))
        edges[r] = {p for p in producers if p in blocked}
    diag = TimeoutDiagnosis(
        ft.kernel, n, pending=tuple(pending), cycle=tuple(_cycle(edges)),
        aborted=tuple(sorted(ft.aborted)),
        note="protocol is permanently stalled (no interleaving can make "
             "progress)",
    )
    raise CollectiveTimeoutError(op, None, diag)


def _cycle(edges: dict[int, set[int]]) -> list[int]:
    for start in sorted(edges):
        path, node = [start], start
        for _ in range(len(edges) + 1):
            nxts = sorted(edges.get(node, ()))
            if not nxts:
                break
            node = nxts[0]
            if node in path:
                return path[path.index(node):] + [node]
            path.append(node)
    return []


# ---------------------------------------------------------------------------
# hazard checks for faults that complete


def check_hazards(ft: FaultyTraces) -> list[str]:
    """Signal-balance over the faulty traces: a fault that does not
    stall the protocol still corrupts it when credits no longer balance
    — a surplus (stale credit) lets a FUTURE wait pass before its data
    lands; a deficit that happened not to starve this invocation starves
    the next.  Returns human-readable findings naming the semaphore."""
    produced: dict[tuple[int, tuple], int] = {}
    consumed: dict[tuple[int, tuple], int] = {}
    for r, events in enumerate(ft.traces):
        for pos, ev in enumerate(events):
            if isinstance(ev, NotifyEv):
                key = (ev.target, ev.sem)
                produced[key] = produced.get(key, 0) + ev.amount
            elif isinstance(ev, CopyEv):
                if ev.send_sem is not None:
                    key = (r, ev.send_sem)
                    produced[key] = produced.get(key, 0) + ev.src.elements()
                if (r, pos) not in ft.drop_recv:
                    key = (ev.dst_rank, ev.recv_sem)
                    produced[key] = produced.get(key, 0) + ev.dst.elements()
            elif isinstance(ev, WaitEv):
                key = (r, ev.sem)
                consumed[key] = consumed.get(key, 0) + ev.amount
    findings = []
    for key in sorted(set(produced) | set(consumed)):
        p, c = produced.get(key, 0), consumed.get(key, 0)
        if p != c:
            rank, sem = key
            what = ("stale surplus: a future wait passes before its data "
                    "lands" if p > c else
                    "credit deficit: the next invocation's wait starves")
            findings.append(
                f"semaphore {sem_label(sem)} on rank {rank}: produced {p} "
                f"!= consumed {c} — {what}"
            )
    return findings


def clean_ticks(case) -> int:
    """Fault-free completion ticks of a registry kernel case — the
    simulator-world analogue of the perf-model estimate the live
    watchdog derives deadlines from."""
    from .faults import FaultKind, FaultSpec, record_faulty_case

    # a spec whose nth is unreachable never fires: records clean traces
    ft = record_faulty_case(
        case, FaultSpec(FaultKind.DELAY_NOTIFY, rank=0, nth=10 ** 9))
    return run_bounded(ft).ticks
