"""Host-side collective watchdog: bounded waits for unbounded spins.

A Pallas semaphore wait has no timeout, so a lost signal parks the
device — and the host call that dispatched the collective — forever.
The watchdog bounds the HOST-visible wall time instead: every guarded
``comm``/``ops`` entry point runs under a deadline derived from the
``tools/perf_model`` speed-of-light estimate for its shape times a
configurable slack (``TDT_WATCHDOG_SLACK``, default 64x — generous
enough for autotune noise, interference and retries, still finite),
plus a floor (``TDT_WATCHDOG_FLOOR_MS``) covering dispatch/compile
fixed costs; the floor is raised massively under interpret mode, where
a simulated collective costs ~100 ms regardless of size.

On expiry :func:`call_with_deadline` raises
:class:`~.errors.CollectiveTimeoutError` carrying a STATIC protocol
diagnosis (``protocol_pending``): the live device state is not
introspectable from the host once a kernel hangs, but the protocol's
wait structure is — the ``tdt.analysis`` recorder lists exactly which
semaphores/chunks each rank spins on, so the error names the candidate
stall points instead of "it hangs".

The abandoned dispatch thread cannot be killed (Python threads are not
cancellable and the underlying XLA call is stuck in C++); it is leaked
as a daemon thread and the error says so — the process survives to
serve degraded traffic, which is the point.
"""

from __future__ import annotations

import functools
import os
import threading

from .errors import CollectiveTimeoutError, PendingWait, TimeoutDiagnosis


def slack() -> float:
    try:
        return float(os.environ.get("TDT_WATCHDOG_SLACK", "") or 64.0)
    except ValueError:
        return 64.0


def floor_ms() -> float:
    env = os.environ.get("TDT_WATCHDOG_FLOOR_MS", "")
    if env:
        try:
            return float(env)
        except ValueError:
            pass
    from ..core import platform

    # interpret mode (the CPU backend) simulates DMA/semaphores in
    # Python: a collective costs ~100 ms + compile; real hardware pays
    # dispatch + possible first-call compile, covered by retries rather
    # than the floor.  platform.on_cpu (not compilation.interpret_mode)
    # on purpose: the floor must resolve even on jax builds whose
    # pltpu lacks InterpretParams.
    return 60_000.0 if platform.on_cpu() else 1_000.0


# op name -> perf_model estimator(payload_bytes, num_ranks) in ms.  The
# fused GEMM ops use their collective half's wire model: the GEMM time
# is bounded separately by the same payload heuristic and the slack
# absorbs the difference.
def _estimate_ms(op: str, payload_bytes: int, num_ranks: int,
                 topology: tuple[int, int] | None = None) -> float:
    from ..tools import perf_model

    n = max(int(num_ranks), 2)
    b = max(int(payload_bytes), 1)
    if topology is not None:
        # two-level (ICI x DCN) families (ISSUE 10): each level is
        # charged ITS OWN wire class — pricing the DCN hop at ICI speed
        # would set a deadline the slow wire can never meet (spurious
        # timeouts on every healthy multi-slice call)
        n_out, n_in = (max(int(v), 1) for v in topology)
        if op in ("hier_all_gather",):
            return perf_model.hier_allgather_sol_ms(b, n_in, n_out)
        if op in ("hier_reduce_scatter",):
            return perf_model.hier_reduce_scatter_sol_ms(b, n_in, n_out)
        if op in ("hier_all_reduce",):
            return perf_model.hier_allreduce_sol_ms(b, n_in, n_out)
        if op in ("sched_ep_dispatch", "sched_ep_combine"):
            return perf_model.hier_a2a_sol_ms(b, n_in, n_out)
        # unknown two-level op: whole payload once per wire class
        return perf_model.hier_a2a_sol_ms(b, n_in, n_out)
    if op in ("all_gather", "ag_gemm"):
        return perf_model.allgather_sol_ms(b, n)
    if op in ("reduce_scatter", "gemm_rs"):
        return perf_model.reduce_scatter_sol_ms(b, n)
    if op in ("all_reduce", "gemm_ar", "fused_mlp_ar", "fused_linear_ar",
              "persistent_decode"):
        # persistent_decode's caller passes payload_bytes already summed
        # over its 2L chained reductions, so the two-shot model prices
        # the whole in-kernel chain
        # the decode megakernel reductions wire 2(n-1)/n of the payload
        # like any two-shot AllReduce; the chained GEMM/SwiGLU time is
        # bounded by the same payload heuristic under the slack
        return perf_model.allreduce_sol_ms(b, n)
    if op in ("ep_dispatch", "ep_combine"):
        # worst case: the whole local payload crosses the wire once
        return perf_model.allgather_sol_ms(b, 2)
    if op == "handoff_transfer":
        # the disaggregated KV handoff (serve.handoff): the payload
        # crosses the DCN exactly once, prefill slice -> decode slice —
        # priced at the calibrated (or documented) DCN rate; pricing it
        # at ICI speed would set a deadline the slow wire can never
        # meet (the ISSUE-10 per-wire-class rule)
        return b / (perf_model.dcn_gbps() * 1e9) * 1e3
    # unknown op: price it as a ring moving the payload once per rank
    return perf_model.allgather_sol_ms(b, n)


def deadline_ms(op: str, *, payload_bytes: int, num_ranks: int,
                topology: tuple[int, int] | None = None) -> float:
    """The watchdog budget for one collective call: SOL estimate x slack
    + floor.  Monotone in payload and rank count.  ``topology``
    ((n_out, n_in), the hierarchical families) prices each level by its
    own wire class — ``tools.perf_model``'s two-level sol terms."""
    return _estimate_ms(op, payload_bytes, num_ranks, topology) * slack() \
        + floor_ms()


@functools.lru_cache(maxsize=None)
def protocol_pending(family: str, n: int) -> TimeoutDiagnosis | None:
    """Static wait-structure diagnosis for a kernel family at ``n``
    ranks: every (rank, semaphore, chunk) the protocol blocks on,
    extracted by recording the registry case — the best the host can say
    about a device-side hang it cannot introspect."""
    if not family or n < 2:
        return None
    try:
        from ..analysis.events import CopyEv, WaitEv
        from ..analysis.record import record_kernel
        from ..analysis.registry import cases_for

        cases = cases_for(family, n)
    except Exception:
        return None
    if not cases:
        return None
    case = cases[0]
    pending: list[PendingWait] = []
    for rank in range(case.n):
        _, thunk = case.make(rank)
        rec = record_kernel(thunk, n=case.n, rank=rank,
                            axes=getattr(case, "axes", None))
        # chunk attribution: the most recent copy landing through a
        # semaphore is the transfer a wait on it would starve for
        last_chunk: dict[tuple, str] = {}
        for pos, ev in enumerate(rec.events):
            if isinstance(ev, CopyEv):
                last_chunk[ev.recv_sem] = ev.dst.label()
            elif isinstance(ev, WaitEv):
                from ..analysis.events import sem_label

                pending.append(PendingWait(
                    rank, sem_label(ev.sem), ev.amount, 0, pos,
                    chunk=last_chunk.get(ev.sem),
                ))
    # cap: a kernel has O(n^2) waits; the first few per rank carry the
    # semaphore/chunk names a human needs
    by_rank: dict[int, int] = {}
    capped = []
    for p in pending:
        if by_rank.get(p.rank, 0) < 4:
            by_rank[p.rank] = by_rank.get(p.rank, 0) + 1
            capped.append(p)
    return TimeoutDiagnosis(
        f"{family}@{n}", n, pending=tuple(capped), static=True,
        note="static protocol wait points (live device state is not "
             "host-introspectable; one of these semaphores is starved)",
    )


def call_with_deadline(op: str, thunk, deadline_ms: float | None, *,
                       family: str | None = None, ranks: int | None = None):
    """Run ``thunk`` bounded by ``deadline_ms`` host wall time.

    ``None``/non-positive deadline = unguarded direct call.  On expiry,
    the dispatch thread is abandoned (daemon; not cancellable), the
    ``resilience_timeouts`` counter is bumped, and
    :class:`CollectiveTimeoutError` is raised with the static protocol
    diagnosis for ``family``/``ranks`` when available.
    """
    if deadline_ms is None or deadline_ms <= 0:
        return thunk()
    from ..lang import primitives as dl

    # the fault-injection scope is thread-local; the dispatch thread
    # must inherit the caller's so live injection (docs/robustness.md)
    # still fires through the guard
    caller_scope = dl.active_fault_scope()
    box: dict = {}
    done = threading.Event()

    def run():
        if caller_scope is not None:
            dl._set_fault_scope(caller_scope)
        try:
            box["value"] = thunk()
        except BaseException as e:  # surfaced on the caller thread
            box["error"] = e
        finally:
            if caller_scope is not None:
                dl._set_fault_scope(None)
            done.set()

    t = threading.Thread(target=run, daemon=True,
                         name=f"tdt-watchdog-{op}")
    t.start()
    if not done.wait(deadline_ms / 1e3):
        from .. import obs

        if obs.enabled():
            obs.counter("resilience_timeouts", op=op).inc()
        diag = protocol_pending(family, int(ranks)) \
            if family and ranks else None
        if obs.flight.enabled():
            # attach the flight ring's recent history: what the protocol
            # was doing just before the deadline fired (TDT_FLIGHT=1;
            # docs/observability.md "Flight recorder")
            import dataclasses as _dc

            lines = obs.flight.recent_lines()
            if diag is None:
                diag = TimeoutDiagnosis(
                    family or op, int(ranks or 0), flight=lines,
                    note="no static protocol diagnosis available")
            else:
                diag = _dc.replace(diag, flight=lines)
        err = CollectiveTimeoutError(op, deadline_ms, diag)
        # callers with mutable state the abandoned thread might still
        # touch (Engine._mark_failed) need its identity to fence writes
        err.abandoned_thread = t
        if hasattr(err, "add_note"):
            err.add_note(
                f"the dispatch thread {t.name!r} is abandoned (a hung "
                f"XLA call cannot be cancelled); the process remains "
                f"serviceable"
            )
        raise err
    if "error" in box:
        raise box["error"]
    return box["value"]
