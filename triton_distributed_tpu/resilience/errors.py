"""The failure vocabulary of the runtime fault-tolerance layer.

Every collective in this framework ultimately spins on a semaphore
(``lang/primitives.py::wait`` / ``wait_recv``), and a device-side spin
wait has NO timeout: a single dropped notify, stale recv credit, or dead
rank hangs the whole mesh forever ("Demystifying NVSHMEM", PAPERS.md).
The resilience layer converts that silent stall into a *named* event:
:class:`CollectiveTimeoutError` carries a :class:`TimeoutDiagnosis` that
says which rank is blocked on which semaphore, how many credits it holds
vs needs, which destination chunk never arrived, and (when one exists)
the wait-for cycle — the protocol-state metadata the static verifier
(``tdt.analysis``) already knows how to extract.
"""

from __future__ import annotations

import dataclasses


@dataclasses.dataclass(frozen=True)
class PendingWait:
    """One blocked wait point: the unit of a hang diagnosis."""

    rank: int
    sem: str            # semaphore label, e.g. "recv_sems[1]"
    need: int           # credits the wait still requires
    have: int           # credits currently available
    event_index: int    # position in the rank's protocol trace
    chunk: str | None = None   # dst region of the missing transfer, if known
    source: int | None = None  # rank that should have produced the credit

    def describe(self) -> str:
        s = (f"rank {self.rank} blocked at event #{self.event_index} on "
             f"semaphore {self.sem} (need {self.need}, have {self.have})")
        if self.chunk is not None:
            s += f"; missing transfer into {self.chunk}"
            if self.source is not None:
                s += f" from rank {self.source}"
        return s


@dataclasses.dataclass(frozen=True)
class TimeoutDiagnosis:
    """Protocol-state snapshot attached to a collective timeout.

    ``pending`` is empty for a *late completion* (straggler beyond the
    deadline — the op would finish, just not in budget); non-empty for a
    permanent stall.  ``static`` marks a diagnosis derived from the
    protocol's recorded structure (the live device state is not
    introspectable from the host once a kernel hangs) rather than from a
    simulated execution.  ``flight`` carries the flight recorder's recent
    event lines when the ring was armed (``TDT_FLIGHT=1``,
    docs/observability.md) — what the protocol was doing just before the
    deadline fired.
    """

    kernel: str
    ranks: int
    pending: tuple[PendingWait, ...] = ()
    cycle: tuple[int, ...] = ()
    aborted: tuple[int, ...] = ()
    note: str = ""
    static: bool = False
    flight: tuple[str, ...] = ()

    def describe(self) -> str:
        lines = []
        if self.note:
            lines.append(self.note)
        lines.extend(p.describe() for p in self.pending)
        if self.cycle:
            lines.append("wait-for cycle: " +
                         " -> ".join(f"rank {r}" for r in self.cycle))
        if self.aborted:
            lines.append("aborted rank(s): " +
                         ", ".join(str(r) for r in self.aborted))
        if self.flight:
            lines.append("recent flight events: " +
                         " | ".join(self.flight))
        return "; ".join(lines) if lines else "no protocol state recorded"

    def semaphores(self) -> tuple[str, ...]:
        return tuple(sorted({p.sem for p in self.pending}))


class CollectiveTimeoutError(RuntimeError):
    """A collective exceeded its watchdog deadline (or is provably
    stalled).  Replaces the un-debuggable infinite spin with an error
    naming the pending semaphore/chunk; the policy layer
    (``resilience.policy``) may catch it and degrade to the XLA
    fallback."""

    def __init__(self, op: str, deadline_ms: float | None,
                 diagnosis: TimeoutDiagnosis | None = None):
        self.op = op
        self.deadline_ms = deadline_ms
        self.diagnosis = diagnosis
        head = f"collective {op!r}"
        if deadline_ms is not None:
            head += f" exceeded its watchdog deadline ({deadline_ms:.1f} ms)"
        else:
            head += " stalled"
        body = diagnosis.describe() if diagnosis is not None else \
            "no diagnosis available"
        super().__init__(f"{head}: {body}")


@dataclasses.dataclass(frozen=True)
class CorruptionDiagnosis:
    """Protocol-state snapshot attached to a data-integrity failure —
    the corruption analogue of :class:`TimeoutDiagnosis`.

    ``sem``/``chunk``/``peer`` name the semaphore whose credit gated the
    corrupt transfer, the destination region whose bytes differ from
    what the producer stamped, and the producing rank (``None`` when the
    op is a reduction whose output mixes every peer's contribution —
    unattributable corruption rides the ladder but cannot quarantine).
    ``kind``: ``"payload"`` (bytes changed in flight — the checksum that
    arrived beside the credit does not match the data) or ``"kv_page"``
    (bytes changed at rest — the region verified clean at arrival but
    differs at consumption / audit time).
    """

    op: str
    kind: str                  # "payload" | "kv_page"
    sem: str | None = None     # semaphore label guarding the transfer
    chunk: str | None = None   # destination region label
    peer: int | None = None    # producing rank, when attributable
    note: str = ""

    def describe(self) -> str:
        s = f"{self.kind} corruption in {self.op!r}"
        if self.chunk is not None:
            s += f": region {self.chunk}"
        if self.sem is not None:
            s += f" gated by semaphore {self.sem}"
        if self.peer is not None:
            s += f", produced by rank {self.peer}"
        if self.note:
            s += f" ({self.note})"
        return s


class PayloadCorruption(RuntimeError):
    """A consumer-side integrity check failed: the bytes that arrived
    are NOT the bytes that were sent (or the bytes at rest are no longer
    the bytes that were written).  Carries a
    :class:`CorruptionDiagnosis` naming (semaphore, chunk, peer) exactly
    as :class:`CollectiveTimeoutError` names a stall; the policy layer
    retries (a transient flip), degrades to the XLA fallback, and
    QUARANTINES a peer that corrupts repeatedly
    (``resilience.integrity``, docs/robustness.md "Data integrity")."""

    def __init__(self, op: str, diagnosis: CorruptionDiagnosis | None = None):
        self.op = op
        self.diagnosis = diagnosis
        body = diagnosis.describe() if diagnosis is not None else \
            "no diagnosis available"
        super().__init__(f"collective {op!r} payload failed verification: "
                         f"{body}")


class CircuitOpenError(RuntimeError):
    """The sticky circuit breaker for an op is open and no degraded
    fallback exists — the caller must shed or reroute this op."""

    def __init__(self, op: str, failures: int):
        self.op = op
        self.failures = failures
        super().__init__(
            f"circuit breaker for {op!r} is open after {failures} "
            f"consecutive failures; no fallback is wired — call "
            f"resilience.reset_breaker({op!r}) after remediation"
        )
