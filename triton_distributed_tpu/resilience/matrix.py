"""The fault-injection matrix: every fault class against every guarded
kernel family, headlessly (CPU-only, no interpret mode, no hardware).

For each (kernel case, fault class) the matrix:

1. samples a seedable injection target from the clean trace structure
   (``faults.sample_spec``),
2. records the faulty execution through the primitives-layer
   interception points (``faults.record_faulty_case``),
3. runs the bounded simulator under a deadline derived from the
   fault-free completion ticks x slack (the simulator-world analogue of
   the live watchdog's perf-model x slack deadline), and
4. classifies the outcome:

   - ``detected``  — :class:`CollectiveTimeoutError` raised (stall or
     beyond-deadline completion) naming the pending semaphore/chunk, OR
     the protocol completed but the hazard check names a credit
     imbalance (the stale-credit corruption class);
   - ``survived``  — completed within deadline with clean credits: the
     protocol absorbed the fault and the results are trustworthy.

``verify_matrix`` turns the rows into CI problems: a fault class that is
neither detected nor survived anywhere it applies (or a detection that
fails to NAME a semaphore/chunk) fails ``scripts/tdt_lint.py --faults``.
"""

from __future__ import annotations

import random

from .errors import CollectiveTimeoutError
from .faults import (
    CORRUPTION_KINDS,
    FAULT_KINDS,
    FaultKind,
    record_faulty_case,
    sample_spec,
)
from .simulate import check_hazards, clean_ticks, run_bounded

# simulator-tick deadline: clean completion x slack + floor; injected
# delays are sampled in [1, 8) ticks so the time-shaped faults land
# within slack (the "survived" leg) — the beyond-slack leg is exercised
# separately (tests/test_resilience.py straggler-overrun case)
DEADLINE_SLACK = 4
DEADLINE_FLOOR = 16

DEFAULT_KERNELS = (
    "allgather/push_1shot",
    "reduce_scatter/ring",
    "allreduce/two_shot",
    "all_to_all/dispatch",
    # ag_gemm joined the matrix in ISSUE 15: the cross-subsystem
    # completeness lint (`tdt_lint --completeness`) found it was the one
    # registry family with NO fault-injection coverage
    "ag_gemm/unidir",
    "gemm_rs/ring",
    "gemm_ar/ring",
    # the decode megakernel's semaphore-chained MLP+AR (ISSUE 8): the
    # fused reduction must stay covered by injection like every other
    # signal-shaped kernel
    "fused_mlp_ar/swiglu",
    # the quantized wire variants (ISSUE 9) at their packed-u8 shapes:
    # same protocols, different payload geometry — a flipped byte
    # anywhere in the message (scale sidecar included) must be caught
    "quant_allgather/push_1shot",
    "quant_exchange/oneshot",
    # the two-level (ICI x DCN) families (ISSUE 10) at the 2x2 layout
    # (the default matrix runs at ranks=4); the 2x4/4x2 layouts ride
    # `tdt_lint --hier` (run_hier_cells)
    "hier_allreduce/2x2",
    "hier_a2a/2x2",
    # the persistent multi-layer decode chain (ISSUE 13): 2L ring
    # reductions on ONE re-armed semaphore set — a dropped credit
    # anywhere in the chain must name the inter-layer semaphore
    "persistent_decode/chain",
)

# the `tdt_lint --quant` slice of the kernel axis
QUANT_KERNELS = ("quant_allgather/push_1shot",
                 "quant_allgather/ring_bidir",
                 "quant_exchange/oneshot")

# the `tdt_lint --persistent` slice: the chained family alone, every
# fault class (verify with min_kernels_per_class=1 — one kernel case
# carries the whole chain)
PERSISTENT_KERNELS = ("persistent_decode/chain",)


def run_persistent_cells(seed: int = 0, *, ranks: int = 4) -> list[dict]:
    """The ``tdt_lint --persistent`` fault slice: every fault class
    against the chained multi-layer decode case.  A detection anywhere
    mid-chain must name a semaphore of the SHARED re-armed set (the
    inter-layer dependency edge); verify with
    ``verify_matrix(rows, min_kernels_per_class=1)``."""
    return run_matrix(seed=seed, kernels=PERSISTENT_KERNELS, ranks=ranks)

# the `tdt_lint --hier` slice: every two-level family, with the
# inter-slice (DCN) protocol model in the loop — the dropped-inter-slice-
# credit class is drop_notify/stale_credit landing on the dcn semaphores
HIER_KERNELS_4 = ("hier_allgather/2x2", "hier_reduce_scatter/2x2",
                  "hier_allreduce/2x2", "hier_a2a/2x2")
HIER_KERNELS_8 = ("hier_allgather/2x4", "hier_reduce_scatter/2x4",
                  "hier_allreduce/2x4", "hier_a2a/2x4",
                  "hier_allgather/4x2", "hier_reduce_scatter/4x2",
                  "hier_allreduce/4x2", "hier_a2a/4x2")

# classes whose injection MUST be caught: they stall or corrupt
MUST_DETECT = (FaultKind.DROP_NOTIFY, FaultKind.STALE_CREDIT,
               FaultKind.RANK_ABORT, FaultKind.CORRUPT_PAYLOAD,
               FaultKind.CORRUPT_KV_PAGE)


def _cases(kernels, n: int):
    from ..analysis.registry import all_cases

    by_name = {c.name: c for c in all_cases(ranks=(n,))}
    out = []
    for name in kernels:
        if name not in by_name:
            raise KeyError(f"unknown kernel case {name!r}; known: "
                           f"{sorted(by_name)}")
        out.append(by_name[name])
    return out


def run_case(case, kind: FaultKind, rng) -> dict | None:
    """One matrix cell; None when the fault class has no valid target in
    this kernel (e.g. DELAY_NOTIFY on a pure-DMA protocol)."""
    from .. import obs

    try:
        spec = sample_spec(case, kind, rng)
    except ValueError:
        return None
    ft = record_faulty_case(case, spec)
    deadline = clean_ticks(case) * DEADLINE_SLACK + DEADLINE_FLOOR
    row = {
        "kernel": case.name, "ranks": case.n, "fault": kind.value,
        "victim_rank": spec.rank, "nth": spec.nth, "fired": ft.fired,
        "deadline_ticks": deadline,
    }
    if obs.enabled():
        obs.counter("resilience_faults_injected", kernel=case.family,
                    fault=kind.value).inc()
    try:
        res = run_bounded(ft, deadline_ticks=deadline)
    except CollectiveTimeoutError as e:
        row["outcome"] = "detected"
        row["detail"] = str(e)
        row["named"] = list(e.diagnosis.semaphores()) \
            if e.diagnosis is not None else []
        if obs.enabled():
            obs.counter("resilience_timeouts", op=case.name).inc()
        return row
    if kind in CORRUPTION_KINDS:
        # liveness is untouched (credits balance, completion on time):
        # only the checksum protocol can see these classes
        from . import integrity

        findings = integrity.check_traces(ft)
        if findings:
            row["outcome"] = "detected"
            row["detail"] = "; ".join(f.describe() for f in findings)
            row["named"] = sorted({s for f in findings
                                   for s in (f.sem, f.chunk,
                                             None if f.peer is None
                                             else f"rank {f.peer}")
                                   if s})
        else:
            # completed, balanced, and silent: the exact SDC blind spot
            # verify_matrix fails the build on
            row["outcome"] = "undetected"
            row["detail"] = (f"completed at tick {res.ticks} with "
                             f"balanced credits and NO checksum finding")
            row["named"] = []
        return row
    hazards = check_hazards(ft)
    if hazards:
        row["outcome"] = "detected"
        row["detail"] = "; ".join(hazards)
        row["named"] = [h.split(":", 1)[0] for h in hazards]
    else:
        row["outcome"] = "survived"
        row["detail"] = (f"completed at tick {res.ticks} <= deadline "
                         f"{deadline} with balanced credits")
        row["named"] = []
    return row


# -- scheduler cells (ISSUE 6): the PR-3 whole-batch isolation story at
# per-SEQUENCE granularity.  Each cell drives the REAL continuous-
# batching scheduler (serve.Scheduler over the deterministic
# SimBackend, which runs the real paged-cache plumbing headlessly) with
# a fault injected mid-decode under a multi-request load, then
# classifies:
#
#   detected  — the victim request FAILED with the fault named in its
#               error, every cohabitant completed, and the page pool
#               drained to zero (per-request isolation held);
#   survived  — the fault was absorbed (straggler within deadline
#               slack): everything completed, zero pages leaked.
#
# Anything else — a cohabitant failing, a leaked page, a hung drain —
# is an isolation breach ``verify_scheduler_matrix`` turns into a CI
# problem.

SCHED_FAULTS = (FaultKind.RANK_ABORT, FaultKind.STRAGGLER)


class _SchedInjector:
    """One-shot decode-step fault hook for the SimBackend."""

    def __init__(self, kind: FaultKind, at_step: int, *,
                 delay_s: float = 0.0, rank: int = 0):
        self.kind = kind
        self.at_step = at_step
        self.delay_s = delay_s
        self.rank = rank
        self.fired = False

    def __call__(self, step: int) -> None:
        if self.fired or step != self.at_step:
            return
        self.fired = True   # set BEFORE acting: an abandoned straggler
        # thread must not re-fire on the retry dispatch
        if self.kind is FaultKind.RANK_ABORT:
            from .faults import RankAborted

            raise RankAborted(self.rank, step)
        if self.kind is FaultKind.STRAGGLER:
            import time

            time.sleep(self.delay_s)


def _lifecycle_summary(rec) -> dict:
    """Discharge a matrix cell's "zero leaked pages" claim STATICALLY:
    run the recorded page trace through the ``analysis.pages`` ownership
    state machine and fold the verdict into the row.  A cell whose
    replay freed everything dynamically but whose TRACE shows a
    use-after-free / read-before-stamp / scrub-under-reader (or shows
    zero events — interception unwired) still fails its verify."""
    from ..analysis.pages import check_recorder

    return {
        "lifecycle_events": len(rec.events),
        "lifecycle_violations": [
            str(v) for v in check_recorder(rec, label="matrix")],
    }


def _sched_cell(kind: FaultKind, leg: str, rng) -> dict:
    """One scheduler matrix cell: seeded 12-request load on 3 slots
    over a 24-page pool, fault injected at a sampled decode step."""
    import time as _time

    from ..serve import (
        RequestState, Scheduler, SchedulerConfig, SimBackend, replay,
        synthetic_trace,
    )

    at_step = rng.randint(2, 6)
    # straggler legs: "slack" delays well inside the request deadline
    # (absorbed); "overrun" delays past it (the watchdog converts the
    # stall into a CollectiveTimeoutError naming the step)
    delay_s = {"slack": 0.05, "overrun": 0.4}.get(leg, 0.0)
    deadline_ms = 250.0 if leg == "overrun" else 10_000.0
    inj = _SchedInjector(kind, at_step, delay_s=delay_s,
                         rank=rng.randrange(4))
    backend = SimBackend(slots=3, page_size=4, pool_pages=24,
                         max_length=48, step_hook=inj)
    sched = Scheduler(backend, SchedulerConfig(
        max_queue_depth=32, step_deadline_floor_ms=25.0))
    arrivals = synthetic_trace(rng.randrange(1 << 16), 12,
                               mean_interarrival_steps=0.5,
                               prompt_len=(2, 8), max_new=(3, 8))
    if kind is FaultKind.STRAGGLER and leg == "overrun":
        # exactly one deadline-carrying request: the designated victim —
        # the watchdog budget binds to it, so the breach is attributable.
        # Pinned LONG so it is still mid-decode when the injection step
        # arrives (a short request finishing first would leave the step
        # unbounded and the straggle absorbed)
        arrivals[0].request.deadline_ms = deadline_ms
        arrivals[0].request.max_new_tokens = 24
    from ..analysis import pages as _pages

    t0 = _time.monotonic()
    with _pages.record() as rec:
        report = replay(sched, arrivals, max_steps=4000)
    if kind is FaultKind.STRAGGLER and leg == "overrun":
        # the watchdog ABANDONED the straggling dispatch thread (by
        # design); let it wake from its sleep and finish its discarded
        # step while the runtime is alive — a zombie still inside an
        # eager op at interpreter shutdown aborts the process in XLA
        # teardown
        _time.sleep(delay_s + 0.1)
    row = {
        "kernel": "serve/scheduler", "fault": kind.value, "leg": leg,
        "at_step": at_step, "fired": inj.fired,
        "requests": len(report.requests),
        "completed": len(report.completed),
        "failed": len(report.failed),
        "shed": len(report.shed),
        "pages_leaked": report.leaked_pages,
        "drain_monotone": report.drain_monotone,
        "wall_s": round(_time.monotonic() - t0, 3),
        **_lifecycle_summary(rec),
    }
    problems = report.problems()
    victims = report.failed
    cohab_ok = all(
        r.state in (RequestState.DONE, RequestState.SHED)
        for r in report.requests if r not in victims
    )
    if victims and cohab_ok and not problems:
        row["outcome"] = "detected"
        row["named"] = sorted({(r.error or "").split(":")[0]
                               for r in victims})
        row["detail"] = (f"victim(s) {[r.req_id for r in victims]} "
                         f"failed isolated; "
                         f"{row['completed']} cohabitants completed")
    elif not victims and not problems and inj.fired:
        row["outcome"] = "survived"
        row["named"] = []
        row["detail"] = (f"fault absorbed; all {row['completed']} "
                         f"requests completed, zero pages leaked")
    else:
        row["outcome"] = "unisolated"
        row["named"] = []
        row["detail"] = "; ".join(problems) or \
            "cohabitant failure alongside the victim"
    return row


def _sched_poison_cell(rng) -> dict:
    """corrupt_kv_page at serving granularity: one full KV page of an
    active sequence is flipped BETWEEN scheduler steps (at-rest
    corruption the decode path would silently attend over).  With
    ``TDT_INTEGRITY=1`` the periodic pool audit catches the stamp
    mismatch and RECOVERS the victim through the preemption-recompute
    path — pages evicted, request re-queued, prompt deterministically
    recomputed — so the victim still completes with CORRECT tokens
    while cohabitants' caches stay byte-intact and zero pages leak.
    (The SimBackend's token rule does not read KV, so the cell proves
    the detection+recovery machinery, and the byte-intactness of
    cohabitant pages is pinned by the serve tests.)"""
    import dataclasses as _dc

    from . import integrity
    from ..serve import (
        Request, RequestState, Scheduler, SchedulerConfig, SimBackend,
    )

    from ..analysis import pages as _pages

    prev = integrity._ENABLED
    integrity.enable(True)
    try:
        backend = SimBackend(slots=3, page_size=4, pool_pages=32,
                             max_length=64)
        sched = Scheduler(backend, SchedulerConfig(
            kv_audit_interval_steps=2))
        reqs = [
            Request(prompt=tuple(rng.randrange(1, 90) for _ in range(6)),
                    max_new_tokens=rng.randint(8, 12), priority=i)
            for i in range(3)
        ]
        for r in reqs:
            sched.submit(r)
        fired = False
        victim = None
        page = None
        with _pages.record() as rec:
            for _ in range(400):
                res = sched.step()
                if not fired:
                    cand = next(
                        (s for s in sched.slots
                         if s is not None and s.page_stamps
                         and s.request.state is RequestState.DECODE),
                        None)
                    if cand is not None:
                        j = max(cand.page_stamps)
                        page = int(cand.pages[j])
                        victim = cand.request
                        sched.cache = _dc.replace(
                            sched.cache,
                            k=sched.cache.k.at[:, page].add(1000.0))
                        fired = True
                if res.idle and fired:
                    break
    finally:
        integrity.enable(prev)

    detections = [c for c in sched.kv_corruptions
                  if c["page"] == page]
    recovered = (victim is not None
                 and victim.state is RequestState.DONE
                 and victim.tokens == backend.expected_tokens(victim))
    cohab_ok = all(
        r.state is RequestState.DONE
        and r.tokens == backend.expected_tokens(r)
        for r in reqs if r is not victim)
    leaked = sched.pool.used_pages
    row = {
        "kernel": "serve/scheduler", "fault": "corrupt_kv_page",
        "leg": "poison", "fired": fired,
        "requests": len(reqs),
        "completed": sum(r.state is RequestState.DONE for r in reqs),
        "failed": sum(r.state is RequestState.FAILED for r in reqs),
        "shed": 0,
        "pages_leaked": leaked,
        "drain_monotone": True,
        "preemptions": sched.preemptions,
        **_lifecycle_summary(rec),
    }
    if fired and detections and recovered and cohab_ok and not leaked:
        row["outcome"] = "detected"
        row["named"] = ["corrupt_kv_page", f"page {page}"]
        row["detail"] = (
            f"audit named page {page} at step {detections[0]['step']}; "
            f"victim {victim.req_id} recovered via preemption-recompute "
            f"({sched.preemptions} preemption(s)); cohabitants intact")
    else:
        row["outcome"] = "unisolated"
        row["named"] = []
        row["detail"] = (
            f"fired={fired} detections={len(detections)} "
            f"recovered={recovered} cohab_ok={cohab_ok} leaked={leaked}")
    return row


def run_scheduler_matrix(seed: int = 0) -> list[dict]:
    """The scheduler cells: rank_abort mid-decode, straggler within
    slack, straggler past the victim's deadline, and a KV page poisoned
    between steps (recovered via preemption-recompute)."""
    rng = random.Random(seed)
    return [
        _sched_cell(FaultKind.RANK_ABORT, "abort", rng),
        _sched_cell(FaultKind.STRAGGLER, "slack", rng),
        _sched_cell(FaultKind.STRAGGLER, "overrun", rng),
        _sched_poison_cell(rng),
    ]


# -- handoff cells (ISSUE 12): the disaggregated prefill/decode topology's
# threat model (docs/robustness.md "KV handoff").  Each cell drives a REAL
# two-tier router (serve.DisaggRouter over deterministic SimBackends and
# the ModeledDCN transport) under a seeded multi-request load with ONE
# fault class planned on the wire, then classifies:
#
#   detected  — the fault produced its NAMED artifact (a dropped
#               transfer's watchdog timeout, a corrupt/stale page's
#               PayloadCorruption naming the page) AND every faulted
#               request still completed with token parity — via a clean
#               retry or the terminal re-prefill fallback — with zero
#               pages leaked on BOTH tiers;
#   survived  — the condition was absorbed by a scheduling decision
#               (decode-tier saturation -> colocated mode): everything
#               completed, nothing leaked, no artifact required.
#
# Anything else is an isolation breach `verify_handoff_matrix` turns
# into a CI problem.

HANDOFF_LEGS = {
    "transfer_drop": "reprefill",
    "corrupt_page_in_flight": "retry",
    "stale_stamp": "retry",
    "prefill_rank_abort": "reprefill",
    "decode_saturated": "colocate",
}


def _handoff_cell(kind, rng) -> dict:
    from ..serve import (
        DisaggRouter, HandoffFault, HandoffPlane, ModeledDCN, Request,
        RequestState, Scheduler, SchedulerConfig, SimBackend, WireFault,
    )
    from ..serve.handoff import HANDOFF_OP
    from . import policy

    leg = HANDOFF_LEGS[kind.value]
    at_transfer = rng.randint(0, 2)
    faults = []
    decode_slots, decode_pool = 3, 32
    if kind is HandoffFault.DECODE_SATURATED:
        # a decode tier that can adopt (almost) nothing: the router must
        # shed back to colocated mode, not wedge parked handoffs
        decode_slots, decode_pool = 1, 3
    elif leg == "retry":
        # first attempt corrupted/stale, the retry lands clean
        faults = [WireFault(kind, at_transfer, attempts=1)]
    else:
        # every attempt fails: the ladder must bottom out to re-prefill
        faults = [WireFault(kind, at_transfer)]
    pre = Scheduler(
        SimBackend(slots=3, page_size=4, pool_pages=24, max_length=48),
        SchedulerConfig(max_queue_depth=32, prefill_only=True))
    dec = Scheduler(
        SimBackend(slots=decode_slots, page_size=4,
                   pool_pages=decode_pool, max_length=48),
        SchedulerConfig(max_queue_depth=32))
    plane = HandoffPlane(dcn_channel=ModeledDCN(
        faults=faults, seed=rng.randrange(1 << 16)))
    router = DisaggRouter(pre, dec, plane=plane)
    # cells must not inherit (or donate) ladder state through the
    # process-global handoff breaker
    policy.reset_breaker(HANDOFF_OP)
    reqs = [
        Request(prompt=tuple(rng.randrange(1, 90)
                             for _ in range(rng.randint(2, 6))),
                max_new_tokens=rng.randint(3, 8))
        for _ in range(6)
    ]
    from ..analysis import pages as _pages

    for r in reqs:
        router.submit(r)
    with _pages.record() as rec:
        router.run_until_idle(max_steps=4000)
    policy.reset_breaker(HANDOFF_OP)

    fired = {
        "transfer_drop": plane.dcn.drops > 0,
        "corrupt_page_in_flight": bool(plane.corruptions),
        "stale_stamp": bool(plane.corruptions),
        "prefill_rank_abort": router.aborts > 0,
        "decode_saturated": router.colocated > 0,
    }[kind.value]
    complete = all(r.state is RequestState.DONE for r in reqs)
    parity = all(r.tokens == pre.backend.expected_tokens(r)
                 for r in reqs if r.state is RequestState.DONE)
    leaked = router.leaked_pages()
    row = {
        "kernel": "serve/handoff", "fault": kind.value, "leg": leg,
        "at_transfer": at_transfer, "fired": fired,
        "requests": len(reqs),
        "completed": sum(r.state is RequestState.DONE for r in reqs),
        "failed": sum(r.state is RequestState.FAILED for r in reqs),
        "pages_leaked": leaked,
        "handoffs": router.handoffs, "colocated": router.colocated,
        "reprefills": router.reprefills, "retries": plane.retries,
        **_lifecycle_summary(rec),
    }
    named: list[str] = []
    recovered = False
    if leg == "retry":
        named = [kind.value] + [c["chunk"] for c in plane.corruptions[:1]]
        recovered = bool(plane.corruptions) and plane.retries >= 1
    elif kind is HandoffFault.TRANSFER_DROP:
        last = policy._LAST_ERROR.get(HANDOFF_OP, "")
        named = [kind.value] + (["watchdog deadline"]
                                if "deadline" in last else [])
        recovered = plane.exhausted >= 1 and router.reprefills >= 1
    elif kind is HandoffFault.PREFILL_ABORT:
        named = [kind.value, "RankAborted"]
        recovered = router.aborts >= 1 and router.reprefills >= 1
    if kind is HandoffFault.DECODE_SATURATED:
        if fired and complete and parity and not leaked \
                and not row["failed"]:
            row["outcome"] = "survived"
            row["named"] = []
            row["detail"] = (
                f"decode tier refused adoption {router.colocated} "
                f"time(s); router shed to colocated mode, all "
                f"{row['completed']} requests completed, zero leaks")
        else:
            row["outcome"] = "unisolated"
            row["named"] = []
            row["detail"] = (f"fired={fired} complete={complete} "
                             f"parity={parity} leaked={leaked}")
        return row
    if fired and recovered and complete and parity and not leaked:
        row["outcome"] = "detected"
        row["named"] = [n for n in named if n]
        via = ("clean retry" if leg == "retry"
               else f"re-prefill on the decode tier "
                    f"({router.reprefills} re-prefill(s))")
        row["detail"] = (f"fault named ({row['named']}); faulted "
                         f"request(s) completed via {via} with token "
                         f"parity; zero pages leaked on both tiers")
    else:
        row["outcome"] = "unisolated"
        row["named"] = []
        row["detail"] = (f"fired={fired} recovered={recovered} "
                         f"complete={complete} parity={parity} "
                         f"leaked={leaked}")
    return row


def run_handoff_matrix(seed: int = 0) -> list[dict]:
    """The handoff fault cells: one per
    :class:`~..serve.handoff.HandoffFault` class (the golden listing in
    ``tests/test_integrity.py`` pins exactly this shape — a class added
    without a cell fails there with the diff as the message)."""
    from ..serve import HANDOFF_FAULT_KINDS

    rng = random.Random(seed)
    return [_handoff_cell(kind, rng) for kind in HANDOFF_FAULT_KINDS]


def verify_handoff_matrix(rows: list[dict]) -> list[str]:
    """CI problems in the handoff cells (empty = pass): every class
    exercised and fired, wire faults DETECTED with a named artifact
    (drop/corrupt/stale/abort absorbed silently would mean garbage KV
    or a wedged request shipped), saturation SURVIVED via colocation,
    zero leaked pages on both tiers."""
    from ..serve import HANDOFF_FAULT_KINDS

    problems = []
    seen = {row["fault"] for row in rows}
    missing = {k.value for k in HANDOFF_FAULT_KINDS} - seen
    if missing:
        problems.append(
            f"handoff fault class(es) without a matrix cell: "
            f"{sorted(missing)}")
    for row in rows:
        key = f"{row['kernel']} x {row['fault']}/{row['leg']}"
        if not row["fired"]:
            problems.append(f"{key}: injection never reached its "
                            f"transfer (at_transfer="
                            f"{row['at_transfer']})")
            continue
        if row["pages_leaked"]:
            problems.append(f"{key}: {row['pages_leaked']} page(s) "
                            f"leaked across the tiers")
        want = "survived" if row["fault"] == "decode_saturated" \
            else "detected"
        if row["outcome"] != want:
            problems.append(
                f"{key}: expected {want}, got {row['outcome']!r} — "
                f"{row['detail']}")
        if row["outcome"] == "detected" and not row["named"]:
            problems.append(f"{key}: detected but no artifact named")
        problems.extend(_lifecycle_problems(key, row))
    return problems


def _lifecycle_problems(key: str, row: dict) -> list[str]:
    """The static leg of a cell's verify: the recorded page trace must
    be non-empty (interception wired) and ownership-clean (the "zero
    leaked pages" claim discharged by the state machine, not just the
    free-list counter)."""
    out = []
    if row.get("lifecycle_events") == 0:
        out.append(f"{key}: lifecycle recorder saw zero page events — "
                   f"the call-site interception is unwired")
    for v in row.get("lifecycle_violations", []):
        out.append(f"{key}: page-lifetime violation in the recorded "
                   f"trace — {v}")
    return out


def run_hier_cells(seed: int = 0) -> list[dict]:
    """The ``tdt_lint --hier`` fault slice: every fault class against the
    two-level kernel cases at all three slice layouts ({2x2} at ranks=4,
    {2x4, 4x2} at ranks=8).  Verify with :func:`verify_matrix`."""
    return (run_matrix(seed=seed, kernels=HIER_KERNELS_4, ranks=4)
            + run_matrix(seed=seed + 1, kernels=HIER_KERNELS_8, ranks=8))


def run_quant_cells(seed: int = 0) -> list[dict]:
    """The ``tdt_lint --quant`` protocol slice: BOTH corruption classes
    (in-flight payload flips and at-rest poisons — a flipped scale-
    sidecar byte is just a payload byte to the checksum protocol, which
    is the point) against every quantized kernel variant, through the
    record-mode checksum protocol.  Verify with :func:`verify_matrix`
    (``kinds=CORRUPTION_KINDS``)."""
    return run_matrix(seed=seed, kernels=QUANT_KERNELS,
                      kinds=CORRUPTION_KINDS)


def run_integrity_cells(seed: int = 0) -> tuple[list[dict], list[dict]]:
    """The ``tdt_lint --integrity`` slice: (kernel rows, scheduler
    cells) — both corruption classes over every kernel family through
    the record-mode checksum protocol, plus the KV-page poison cell.
    Verify the halves with :func:`verify_matrix` (``kinds=
    CORRUPTION_KINDS``) and :func:`verify_scheduler_matrix`."""
    rows = run_matrix(seed=seed, kinds=CORRUPTION_KINDS)
    cells = [_sched_poison_cell(random.Random(seed))]
    return rows, cells


def verify_scheduler_matrix(rows: list[dict]) -> list[str]:
    """CI problems in the scheduler cells (empty = pass): every
    injection must land, per-request isolation must hold, rank aborts
    and deadline overruns must be DETECTED (a silently-absorbed dead
    rank would mean the victim's garbage tokens shipped)."""
    problems = []
    for row in rows:
        key = f"{row['kernel']} x {row['fault']}/{row['leg']}"
        if not row["fired"]:
            problems.append(f"{key}: injection never reached its decode "
                            f"step (at_step={row['at_step']})")
            continue
        if row["outcome"] == "unisolated":
            problems.append(f"{key}: isolation breach — {row['detail']}")
        if row["pages_leaked"]:
            problems.append(f"{key}: {row['pages_leaked']} page(s) leaked")
        if row["leg"] in ("abort", "overrun") and \
                row["outcome"] != "detected":
            problems.append(
                f"{key}: expected a detected+isolated victim, got "
                f"{row['outcome']!r} — the fault was absorbed silently")
        if row["leg"] == "poison" and row["outcome"] != "detected":
            problems.append(
                f"{key}: a poisoned KV page must be detected by the "
                f"audit and recovered via preemption-recompute, got "
                f"{row['outcome']!r} — garbage KV would be attended "
                f"over silently")
        if row["leg"] == "slack" and row["outcome"] != "survived":
            problems.append(
                f"{key}: an in-slack straggler should be absorbed, got "
                f"{row['outcome']!r}")
        if row["outcome"] == "detected" and not row["named"]:
            problems.append(f"{key}: detected but the victim's error "
                            f"names no fault class")
        problems.extend(_lifecycle_problems(key, row))
    return problems


def run_matrix(seed: int = 0, *, kernels=DEFAULT_KERNELS, ranks: int = 4,
               kinds=FAULT_KINDS) -> list[dict]:
    """The full (kernel x fault class) sweep; rows sorted by kernel.
    ``kinds`` restricts the fault-class axis (``tdt_lint --integrity``
    runs the corruption slice alone)."""
    rng = random.Random(seed)
    rows = []
    for case in _cases(kernels, ranks):
        for kind in kinds:
            row = run_case(case, kind, rng)
            if row is not None:
                rows.append(row)
    return rows


# -- fleet cells (ISSUE 18): the N-replica serving topology's threat
# model (docs/robustness.md "Fleet tier").  Each cell drives a REAL
# 2-prefill + 2-decode FleetRouter (serve.fleet over deterministic
# SimBackends and the ModeledDCN transport) under a seeded multi-request
# load with one fleet fault injected, then classifies:
#
#   detected  — the fault produced its NAMED artifact (the lost/flapping
#               REPLICA is named; its breaker/quarantine walked) AND
#               every faulted request still completed on a SURVIVOR with
#               token parity vs the unfaulted golden, with zero pages
#               leaked on EVERY replica (page-lifecycle discharge per
#               pool, not just free-list counters);
#   survived  — the condition was absorbed by a membership decision
#               (rebalance converted a drained donor; a quarantined
#               replica re-earned admission through probes): everything
#               completed, nothing leaked.
#
# Anything else is a membership breach ``verify_fleet_matrix`` turns
# into a CI problem.  FLEET_GOLDEN pins (fault -> leg/outcome) and
# ``analysis.completeness.check_fleet_coverage`` asserts it stays in
# lockstep with the live FleetFault enum BOTH directions.

FLEET_GOLDEN = {
    "replica_abort_mid_decode": {"leg": "failover", "outcome": "detected"},
    "replica_flap": {"leg": "quarantine", "outcome": "detected"},
    "rebalance_under_load": {"leg": "rebalance", "outcome": "survived"},
    "quarantine_readmit": {"leg": "readmit", "outcome": "survived"},
}


class _FlapInjector:
    """Decode-step fault hook raising ``RankAborted`` on every dispatch
    whose backend step counter falls in ``[first, last]`` — a flapping
    replica, not a one-shot fault."""

    def __init__(self, first: int, last: int, *, rank: int = 0):
        self.first = first
        self.last = last
        self.rank = rank
        self.fired = 0

    def __call__(self, step: int) -> None:
        if self.first <= step <= self.last:
            from .faults import RankAborted

            self.fired += 1
            raise RankAborted(self.rank, step)


def _reset_fleet_breakers() -> None:
    """Cells must not inherit (or donate) quarantine state through the
    process-global ``replica:<id>`` breakers (ids repeat across cells)
    or the handoff-transfer breaker."""
    from . import policy
    from ..serve.fleet import REPLICA_BREAKER_PREFIX
    from ..serve.handoff import HANDOFF_OP

    with policy._BREAKERS_LOCK:
        ops = [op for op in policy._BREAKERS
               if op.startswith(REPLICA_BREAKER_PREFIX)]
    for op in ops:
        policy.reset_breaker(op)
    policy.reset_breaker(HANDOFF_OP)


def _fleet_setup(rng, *, decode_slots: int = 3, decode_pool: int = 32,
                 step_hooks: dict | None = None, config=None):
    """The seeded 2-prefill + 2-decode fleet every cell drives
    (``p0 p1 d0 d1``); ``step_hooks`` maps a replica id to a SimBackend
    decode-step hook (the flap injection point)."""
    from ..serve import (
        FleetRouter, HandoffPlane, ModeledDCN, Replica, Scheduler,
        SchedulerConfig, SimBackend,
    )

    hooks = step_hooks or {}
    replicas = []
    for i in range(2):
        rid = f"p{i}"
        replicas.append(Replica(
            rid,
            Scheduler(
                SimBackend(slots=3, page_size=4, pool_pages=24,
                           max_length=64, step_hook=hooks.get(rid)),
                SchedulerConfig(max_queue_depth=32, prefill_only=True)),
            "prefill"))
    for i in range(2):
        rid = f"d{i}"
        replicas.append(Replica(
            rid,
            Scheduler(
                SimBackend(slots=decode_slots, page_size=4,
                           pool_pages=decode_pool, max_length=64,
                           step_hook=hooks.get(rid)),
                SchedulerConfig(max_queue_depth=32)),
            "decode"))
    plane = HandoffPlane(dcn_channel=ModeledDCN(
        seed=rng.randrange(1 << 16)))
    return FleetRouter(replicas, plane=plane, config=config)


def _fleet_requests(rng, n: int, *, max_new=(4, 8)) -> list:
    from ..serve import Request

    return [
        Request(prompt=tuple(rng.randrange(1, 90)
                             for _ in range(rng.randint(2, 6))),
                max_new_tokens=rng.randint(*max_new))
        for _ in range(n)
    ]


def _fleet_row(router, reqs, kind, leg, rec) -> dict:
    from ..serve import RequestState

    backend = router.replicas[0].scheduler.backend
    leaked_by = {rep.replica_id: rep.scheduler.pool.used_pages
                 for rep in router.replicas}
    return {
        "kernel": "serve/fleet", "fault": kind.value, "leg": leg,
        "requests": len(reqs),
        "completed": sum(r.state is RequestState.DONE for r in reqs),
        "failed": sum(r.state is RequestState.FAILED for r in reqs),
        "shed": sum(r.state is RequestState.SHED for r in reqs),
        "parity": all(r.tokens == backend.expected_tokens(r)
                      for r in reqs if r.state is RequestState.DONE),
        "pages_leaked": router.leaked_pages(),
        "pages_leaked_by_replica": leaked_by,
        "handoffs": router.handoffs, "colocated": router.colocated,
        "reprefills": router.reprefills, "failovers": router.failovers,
        "quarantined": [r.replica_id for r in router.replicas
                        if r.quarantined],
        "readmissions": list(router.readmissions),
        "rebalances": list(router.rebalances),
        **_lifecycle_summary(rec),
    }


def _fleet_abort_cell(rng) -> dict:
    """replica_abort_mid_decode: a decode replica dies with residents
    mid-decode; every resident re-prefills on the survivor, original
    clock carried, zero pages left behind."""
    from ..serve import FleetConfig, FleetFault, RequestState

    from ..analysis import pages as _pages

    _reset_fleet_breakers()
    router = _fleet_setup(rng, config=FleetConfig(
        probe_interval_steps=1 << 30))
    reqs = _fleet_requests(rng, 8, max_new=(6, 10))
    victim_id = None
    moved: list[int] = []
    with _pages.record() as rec:
        for r in reqs:
            router.submit(r)
        for _ in range(400):
            router.step()
            cand = next(
                (rep for rep in router.replicas
                 if rep.role == "decode" and any(
                     s is not None
                     and s.request.state is RequestState.DECODE
                     for s in rep.scheduler.slots)),
                None)
            if cand is not None:
                victim_id = cand.replica_id
                moved = router.lose_replica(
                    victim_id, reason="injected mid-decode replica loss")
                break
        router.run_until_idle(max_steps=4000)
    row = _fleet_row(router, reqs, FleetFault.REPLICA_ABORT_MID_DECODE,
                     "failover", rec)
    row["fired"] = victim_id is not None and bool(moved)
    row["replica"] = victim_id
    row["moved"] = len(moved)
    complete = all(r.state is RequestState.DONE for r in reqs)
    # a LOST replica is not "quarantined" (loss is terminal, quarantine
    # is probation) — it must show up in lost_replicas instead, and no
    # survivor may have been collaterally quarantined
    lost_ok = (victim_id in router.lost_replicas
               and row["quarantined"] == [])
    if row["fired"] and complete and row["parity"] \
            and not row["pages_leaked"] and lost_ok:
        row["outcome"] = "detected"
        row["named"] = [victim_id, "replica_lost"]
        row["detail"] = (
            f"replica {victim_id} lost with {len(moved)} resident(s); "
            f"all re-prefilled on survivors with token parity, zero "
            f"pages leaked on every replica")
    else:
        row["outcome"] = "unisolated"
        row["named"] = []
        row["detail"] = (
            f"fired={row['fired']} complete={complete} "
            f"parity={row['parity']} leaked={row['pages_leaked']} "
            f"quarantined={row['quarantined']}")
    _reset_fleet_breakers()
    return row


def _fleet_flap_cell(rng, *, readmit: bool) -> dict:
    """replica_flap / quarantine_readmit: a decode replica aborts every
    dispatch in a step window; its sticky breaker walks open, it drains
    and evicts.  With ``readmit`` the probe ladder then re-earns
    admission once the flap clears."""
    from ..serve import FleetConfig, FleetFault, RequestState
    from . import policy as _policy
    from ..serve.fleet import replica_breaker_name

    from ..analysis import pages as _pages

    _reset_fleet_breakers()
    kind = FleetFault.QUARANTINE_READMIT if readmit \
        else FleetFault.REPLICA_FLAP
    leg = FLEET_GOLDEN[kind.value]["leg"]
    inj = _FlapInjector(2, 12, rank=rng.randrange(4))
    router = _fleet_setup(
        rng, step_hooks={"d1": inj},
        config=FleetConfig(
            flap_threshold=3,
            probe_interval_steps=8 if readmit else 1 << 30,
            readmit_probe_successes=2))
    reqs = _fleet_requests(rng, 10, max_new=(6, 10))
    with _pages.record() as rec:
        for r in reqs:
            router.submit(r)
        for _ in range(2000):
            res = router.step()
            if readmit and router.readmissions:
                break
            if not readmit and res.idle and "d1" in [
                    rep.replica_id for rep in router.replicas
                    if rep.quarantined]:
                break
        router.run_until_idle(max_steps=4000)
    row = _fleet_row(router, reqs, kind, leg, rec)
    row["fired"] = inj.fired >= 3
    row["replica"] = "d1"
    row["flaps"] = inj.fired
    complete = all(r.state is RequestState.DONE for r in reqs)
    breaker_open = _policy.breaker(replica_breaker_name("d1")).open
    if readmit:
        ok = (row["fired"] and complete and row["parity"]
              and not row["pages_leaked"]
              and "d1" in router.quarantined_history
              and "d1" in router.readmissions
              and not breaker_open and row["quarantined"] == [])
        if ok:
            row["outcome"] = "survived"
            row["named"] = ["d1"]
            row["detail"] = (
                f"replica d1 flapped {inj.fired}x into quarantine, "
                f"then re-earned admission through "
                f"{router.cfg.readmit_probe_successes} green probe(s); "
                f"all requests completed with parity, zero leaks")
        else:
            row["outcome"] = "unisolated"
            row["named"] = []
            row["detail"] = (
                f"fired={row['fired']} complete={complete} "
                f"parity={row['parity']} leaked={row['pages_leaked']} "
                f"quarantined_hist={router.quarantined_history} "
                f"readmissions={router.readmissions} "
                f"breaker_open={breaker_open}")
    else:
        ok = (row["fired"] and complete and row["parity"]
              and not row["pages_leaked"]
              and row["quarantined"] == ["d1"] and breaker_open
              and router.failovers >= 1)
        if ok:
            row["outcome"] = "detected"
            row["named"] = ["d1", "RankAborted"]
            row["detail"] = (
                f"replica d1 flapped {inj.fired}x; breaker "
                f"replica:d1 open, drained then evicted (exactly d1 "
                f"quarantined); {router.failovers} failover(s) "
                f"completed on survivors with parity, zero leaks")
        else:
            row["outcome"] = "unisolated"
            row["named"] = []
            row["detail"] = (
                f"fired={row['fired']} complete={complete} "
                f"parity={row['parity']} leaked={row['pages_leaked']} "
                f"quarantined={row['quarantined']} "
                f"breaker_open={breaker_open} "
                f"failovers={router.failovers}")
    _reset_fleet_breakers()
    return row


def _fleet_rebalance_cell(rng) -> dict:
    """rebalance_under_load: sustained decode-dominant p99 attribution
    with the decode role pressured recruits a drained prefill replica
    into the decode role (drain-before-convert; the donor role keeps a
    member).  Needs the trace plane armed — the actuation signal IS the
    attributor's dominant_phase over live exemplars."""
    from .. import obs
    from ..obs import request_trace as rtrace
    from ..serve import FleetConfig, FleetFault, RequestState

    from ..analysis import pages as _pages

    _reset_fleet_breakers()
    prev_obs = obs.enable(True)
    prev_trace = rtrace.enable(True)
    rtrace.RING.clear()
    obs.serve_stats.STATS.reset()
    try:
        # tiny decode pools + colocation effectively off (prompts PARK
        # in handoff until a decode slot frees): adopted requests
        # outgrow the pools (preemption thrash), the parked backlog
        # makes the p99 handoff/decode-dominant, and the low pressure
        # threshold keeps both decode replicas reading saturated —
        # decode-capacity shortage by construction.  The load is
        # SUSTAINED: decode-heavy waves keep arriving until the
        # membership converts (the p99 exemplar rides wall-clock
        # request_ms, so any single wave's tick alignment is timing-
        # sensitive; sustained demand is what the actuator is FOR).
        router = _fleet_setup(
            rng, decode_slots=2, decode_pool=10,
            config=FleetConfig(
                rebalance_interval_steps=2, rebalance_sustain=2,
                adopt_patience_steps=10_000, pool_pressure=0.55,
                probe_interval_steps=1 << 30))
        reqs: list = []
        with _pages.record() as rec:
            for _wave in range(6):
                wave = _fleet_requests(rng, 12, max_new=(16, 24))
                reqs.extend(wave)
                for r in wave:
                    router.submit(r)
                router.run_until_idle(max_steps=6000)
                # a recruit initiated on the final drain steps converts
                # on the next (idle) ticks
                for _ in range(50):
                    if router._recruit is None:
                        break
                    router.step()
                if router.rebalances:
                    break
    finally:
        obs.serve_stats.STATS.reset()
        rtrace.RING.clear()
        rtrace.enable(prev_trace)
        obs.enable(prev_obs)
    row = _fleet_row(router, reqs, FleetFault.REBALANCE_UNDER_LOAD,
                     "rebalance", rec)
    converted = [rb for rb in router.rebalances
                 if rb["from"] == "prefill" and rb["to"] == "decode"]
    row["fired"] = bool(converted)
    row["replica"] = converted[0]["replica"] if converted else None
    row["convergence_steps"] = router.last_convergence_steps
    complete = all(r.state is RequestState.DONE for r in reqs)
    roles = {role: sum(1 for rep in router.replicas if rep.role == role)
             for role in ("prefill", "decode")}
    if row["fired"] and complete and row["parity"] \
            and not row["pages_leaked"] and roles["prefill"] >= 1:
        row["outcome"] = "survived"
        row["named"] = [converted[0]["replica"]]
        row["detail"] = (
            f"decode-dominant p99 under pressure recruited "
            f"{converted[0]['replica']} prefill->decode in "
            f"{converted[0]['convergence_steps']} step(s); roles now "
            f"{roles}; all requests completed with parity, zero leaks")
    else:
        row["outcome"] = "unisolated"
        row["named"] = []
        row["detail"] = (
            f"fired={row['fired']} complete={complete} "
            f"parity={row['parity']} leaked={row['pages_leaked']} "
            f"rebalances={router.rebalances} roles={roles}")
    _reset_fleet_breakers()
    return row


def run_fleet_matrix(seed: int = 0) -> list[dict]:
    """The fleet fault cells: one per :class:`~..serve.fleet.FleetFault`
    class, in enum order (``FLEET_GOLDEN`` pins the expected leg and
    outcome; ``analysis.completeness`` pins golden <-> enum both
    directions)."""
    rng = random.Random(seed)
    return [
        _fleet_abort_cell(rng),
        _fleet_flap_cell(rng, readmit=False),
        _fleet_rebalance_cell(rng),
        _fleet_flap_cell(rng, readmit=True),
    ]


def verify_fleet_matrix(rows: list[dict]) -> list[str]:
    """CI problems in the fleet cells (empty = pass): both-directions
    coverage against FLEET_GOLDEN, every injection landed, every cell's
    outcome matches its golden, the faulted REPLICA is named, and zero
    pages leaked on EVERY replica (per-pool lifecycle discharge)."""
    problems = []
    seen = {row["fault"] for row in rows}
    for missing in sorted(set(FLEET_GOLDEN) - seen):
        problems.append(
            f"fleet fault class {missing!r} has a golden row but no "
            f"matrix cell ran for it")
    for extra in sorted(seen - set(FLEET_GOLDEN)):
        problems.append(
            f"fleet matrix cell {extra!r} has no FLEET_GOLDEN row — "
            f"pin its leg and outcome")
    for row in rows:
        key = f"{row['kernel']} x {row['fault']}/{row['leg']}"
        golden = FLEET_GOLDEN.get(row["fault"])
        if not row["fired"]:
            problems.append(f"{key}: injection never landed — "
                            f"{row['detail']}")
            continue
        leaked = {rid: n for rid, n
                  in row["pages_leaked_by_replica"].items() if n}
        if leaked:
            problems.append(
                f"{key}: page(s) leaked per replica: {leaked}")
        if golden is not None and row["outcome"] != golden["outcome"]:
            problems.append(
                f"{key}: expected {golden['outcome']!r}, got "
                f"{row['outcome']!r} — {row['detail']}")
        if golden is not None and row["leg"] != golden["leg"]:
            problems.append(
                f"{key}: leg drifted — golden {golden['leg']!r}, "
                f"ran {row['leg']!r}")
        if row["outcome"] in ("detected", "survived") \
                and not row.get("replica"):
            problems.append(
                f"{key}: {row['outcome']} but no replica named")
        problems.extend(_lifecycle_problems(key, row))
    return problems


def verify_matrix(rows: list[dict], *, min_kernels_per_class: int = 3,
                  kinds=FAULT_KINDS) -> list[str]:
    """CI problems in a matrix run (empty = pass):

    - a fired fault whose outcome is neither detected nor survived
      (cannot happen by construction — guards classifier drift);
    - a MUST_DETECT class that some kernel survived silently;
    - a detection with no semaphore/chunk named;
    - a fault class applicable to fewer than ``min_kernels_per_class``
      kernels (matrix rot).
    """
    problems = []
    per_class: dict[str, int] = {}
    for row in rows:
        key = f"{row['kernel']} x {row['fault']}"
        per_class[row["fault"]] = per_class.get(row["fault"], 0) + 1
        if not row["fired"]:
            problems.append(f"{key}: injection never reached its target "
                            f"(nth={row['nth']} sampling drifted)")
            continue
        if row["outcome"] not in ("detected", "survived"):
            problems.append(f"{key}: unclassified outcome {row['outcome']!r}")
        if row["fault"] in {k.value for k in MUST_DETECT} and \
                row["outcome"] != "detected":
            problems.append(
                f"{key}: a {row['fault']} fault completed undetected — "
                f"the protocol would serve corrupt results"
            )
        if row["outcome"] == "detected" and not row["named"]:
            problems.append(
                f"{key}: detected but no semaphore/chunk named — the "
                f"diagnosis lost its protocol state"
            )
    for kind in kinds:
        if per_class.get(kind.value, 0) < min_kernels_per_class:
            problems.append(
                f"fault class {kind.value!r} exercised on only "
                f"{per_class.get(kind.value, 0)} kernel(s) "
                f"(need >= {min_kernels_per_class})"
            )
    return problems
