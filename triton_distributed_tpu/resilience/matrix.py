"""The fault-injection matrix: every fault class against every guarded
kernel family, headlessly (CPU-only, no interpret mode, no hardware).

For each (kernel case, fault class) the matrix:

1. samples a seedable injection target from the clean trace structure
   (``faults.sample_spec``),
2. records the faulty execution through the primitives-layer
   interception points (``faults.record_faulty_case``),
3. runs the bounded simulator under a deadline derived from the
   fault-free completion ticks x slack (the simulator-world analogue of
   the live watchdog's perf-model x slack deadline), and
4. classifies the outcome:

   - ``detected``  — :class:`CollectiveTimeoutError` raised (stall or
     beyond-deadline completion) naming the pending semaphore/chunk, OR
     the protocol completed but the hazard check names a credit
     imbalance (the stale-credit corruption class);
   - ``survived``  — completed within deadline with clean credits: the
     protocol absorbed the fault and the results are trustworthy.

``verify_matrix`` turns the rows into CI problems: a fault class that is
neither detected nor survived anywhere it applies (or a detection that
fails to NAME a semaphore/chunk) fails ``scripts/tdt_lint.py --faults``.
"""

from __future__ import annotations

import random

from .errors import CollectiveTimeoutError
from .faults import (
    FAULT_KINDS,
    FaultKind,
    record_faulty_case,
    sample_spec,
)
from .simulate import check_hazards, clean_ticks, run_bounded

# simulator-tick deadline: clean completion x slack + floor; injected
# delays are sampled in [1, 8) ticks so the time-shaped faults land
# within slack (the "survived" leg) — the beyond-slack leg is exercised
# separately (tests/test_resilience.py straggler-overrun case)
DEADLINE_SLACK = 4
DEADLINE_FLOOR = 16

DEFAULT_KERNELS = (
    "allgather/push_1shot",
    "reduce_scatter/ring",
    "allreduce/two_shot",
    "all_to_all/dispatch",
    "gemm_rs/ring",
    "gemm_ar/ring",
)

# classes whose injection MUST be caught: they stall or corrupt
MUST_DETECT = (FaultKind.DROP_NOTIFY, FaultKind.STALE_CREDIT,
               FaultKind.RANK_ABORT)


def _cases(kernels, n: int):
    from ..analysis.registry import all_cases

    by_name = {c.name: c for c in all_cases(ranks=(n,))}
    out = []
    for name in kernels:
        if name not in by_name:
            raise KeyError(f"unknown kernel case {name!r}; known: "
                           f"{sorted(by_name)}")
        out.append(by_name[name])
    return out


def run_case(case, kind: FaultKind, rng) -> dict | None:
    """One matrix cell; None when the fault class has no valid target in
    this kernel (e.g. DELAY_NOTIFY on a pure-DMA protocol)."""
    from .. import obs

    try:
        spec = sample_spec(case, kind, rng)
    except ValueError:
        return None
    ft = record_faulty_case(case, spec)
    deadline = clean_ticks(case) * DEADLINE_SLACK + DEADLINE_FLOOR
    row = {
        "kernel": case.name, "ranks": case.n, "fault": kind.value,
        "victim_rank": spec.rank, "nth": spec.nth, "fired": ft.fired,
        "deadline_ticks": deadline,
    }
    if obs.enabled():
        obs.counter("resilience_faults_injected", kernel=case.family,
                    fault=kind.value).inc()
    try:
        res = run_bounded(ft, deadline_ticks=deadline)
    except CollectiveTimeoutError as e:
        row["outcome"] = "detected"
        row["detail"] = str(e)
        row["named"] = list(e.diagnosis.semaphores()) \
            if e.diagnosis is not None else []
        if obs.enabled():
            obs.counter("resilience_timeouts", op=case.name).inc()
        return row
    hazards = check_hazards(ft)
    if hazards:
        row["outcome"] = "detected"
        row["detail"] = "; ".join(hazards)
        row["named"] = [h.split(":", 1)[0] for h in hazards]
    else:
        row["outcome"] = "survived"
        row["detail"] = (f"completed at tick {res.ticks} <= deadline "
                         f"{deadline} with balanced credits")
        row["named"] = []
    return row


def run_matrix(seed: int = 0, *, kernels=DEFAULT_KERNELS, ranks: int = 4
               ) -> list[dict]:
    """The full (kernel x fault class) sweep; rows sorted by kernel."""
    rng = random.Random(seed)
    rows = []
    for case in _cases(kernels, ranks):
        for kind in FAULT_KINDS:
            row = run_case(case, kind, rng)
            if row is not None:
                rows.append(row)
    return rows


def verify_matrix(rows: list[dict], *, min_kernels_per_class: int = 3
                  ) -> list[str]:
    """CI problems in a matrix run (empty = pass):

    - a fired fault whose outcome is neither detected nor survived
      (cannot happen by construction — guards classifier drift);
    - a MUST_DETECT class that some kernel survived silently;
    - a detection with no semaphore/chunk named;
    - a fault class applicable to fewer than ``min_kernels_per_class``
      kernels (matrix rot).
    """
    problems = []
    per_class: dict[str, int] = {}
    for row in rows:
        key = f"{row['kernel']} x {row['fault']}"
        per_class[row["fault"]] = per_class.get(row["fault"], 0) + 1
        if not row["fired"]:
            problems.append(f"{key}: injection never reached its target "
                            f"(nth={row['nth']} sampling drifted)")
            continue
        if row["outcome"] not in ("detected", "survived"):
            problems.append(f"{key}: unclassified outcome {row['outcome']!r}")
        if row["fault"] in {k.value for k in MUST_DETECT} and \
                row["outcome"] != "detected":
            problems.append(
                f"{key}: a {row['fault']} fault completed undetected — "
                f"the protocol would serve corrupt results"
            )
        if row["outcome"] == "detected" and not row["named"]:
            problems.append(
                f"{key}: detected but no semaphore/chunk named — the "
                f"diagnosis lost its protocol state"
            )
    for kind in FAULT_KINDS:
        if per_class.get(kind.value, 0) < min_kernels_per_class:
            problems.append(
                f"fault class {kind.value!r} exercised on only "
                f"{per_class.get(kind.value, 0)} kernel(s) "
                f"(need >= {min_kernels_per_class})"
            )
    return problems
