"""Parallelism conventions: mesh axes, sharding helpers, and the shard_map
wrapper — one namespace for how this framework spells SPMD.

This is deliberately a facade over ``core``: the conventions themselves
(axis names, the all-device mesh rule, check_vma-off shard_map for Pallas
outputs) live next to the runtime; this module is the documented import
surface the layers/models/tests use.  Reference analogue: the TP/EP group
bookkeeping of ``python/triton_dist/utils.py:190`` (``TP_GROUP`` etc.),
which on TPU collapses into named mesh axes + PartitionSpecs.

Conventions:

- axes: ``dp`` (data), ``tp`` (tensor), ``sp`` (sequence/context),
  ``ep`` (expert), ``pp`` (pipeline); DCN-level axes are prefixed
  ``dcn_`` (see ``is_dcn_axis``).
- weights: column-parallel = P(None, tp); row-parallel = P(tp, None);
  per-expert = P(ep, None, None).
- activations: token-sharded = P(tp, None) (sequence parallel regions);
  replicated = P(None, None) (small-M decode regions).
"""

from ..core.compilation import jit_shard_map
from .pipeline import pipeline_forward
from ..core.mesh import (
    DP_AXIS,
    EP_AXIS,
    PP_AXIS,
    SP_AXIS,
    TP_AXIS,
    axis_size,
    is_dcn_axis,
    make_mesh,
    replicated,
    shard,
    sharding,
    tp_mesh,
)
