"""Pipeline parallelism: a microbatched GPipe-style forward over a ``pp``
mesh axis.

The reference has no pipeline parallelism (SURVEY.md section 2.5:
"DP / PP / Ulysses — not present"); this module exists because a TPU
framework's mesh story is incomplete without the ``pp`` axis the rest of
the stack already names (``parallel.PP_AXIS``, the DCN classification,
the driver's multichip dryrun).  The design is the standard SPMD
pipeline: every stage runs the SAME program, activations hop stages via
``lax.ppermute`` inside a ``lax.scan`` over ticks, and bubble ticks
compute on don't-care values that the output selection never reads —
compiler-friendly control flow, no host round trips.
"""

from __future__ import annotations

from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core.compilation import jit_shard_map
from ..core.mesh import PP_AXIS


def pipeline_forward(
    stage_fn: Callable[[Any, jax.Array], jax.Array],
    stage_params: Any,
    x: jax.Array,
    mesh: Mesh,
    axis: str = PP_AXIS,
    *,
    num_microbatches: int | None = None,
) -> jax.Array:
    """Run ``num_stages`` copies of ``stage_fn`` as a microbatched pipeline.

    ``stage_params``: a pytree whose leaves have a leading stage axis of
    size ``n = mesh.shape[axis]``, sharded ``P(axis, ...)`` — device s
    holds stage s's parameters.  ``stage_fn(params_s, x_mb)`` must be
    shape-preserving on the microbatch (the usual transformer-block
    contract).  ``x``: (B, ...) full batch, replicated; ``B`` must divide
    by ``num_microbatches`` (default: the stage count).  Returns the (B,
    ...) result of applying stages 0..n-1 in order, replicated.

    Schedule: GPipe forward — microbatch m enters stage s at tick m + s;
    total ticks n - 1 + M.  Bubble ticks process zeros whose outputs are
    never selected.
    """
    n = mesh.shape[axis]
    if num_microbatches is None:
        num_microbatches = n
    b = x.shape[0]
    if b % num_microbatches:
        raise ValueError(
            f"batch {b} not divisible by num_microbatches={num_microbatches}"
        )
    if n == 1:
        return stage_fn(jax.tree.map(lambda p: p[0], stage_params), x)
    mb = b // num_microbatches
    ticks = n - 1 + num_microbatches
    fwd = [(i, i + 1) for i in range(n - 1)]

    def local(params_stacked, x_rep):
        params = jax.tree.map(lambda p: p[0], params_stacked)  # this stage
        idx = jax.lax.axis_index(axis)
        micro = x_rep.reshape(num_microbatches, mb, *x_rep.shape[1:])

        def tick(buf, t):
            # stage 0 injects microbatch t; later stages consume the hop
            inject = micro[jnp.clip(t, 0, num_microbatches - 1)]
            cur = jnp.where(idx == 0, inject, buf)
            y = stage_fn(params, cur)
            # hop to the next stage (stage 0 receives zeros: overwritten
            # by the injection next tick)
            return jax.lax.ppermute(y, axis, fwd), y

        _, ys = jax.lax.scan(
            tick, jnp.zeros((mb, *x_rep.shape[1:]), x_rep.dtype),
            jnp.arange(ticks),
        )
        # microbatch m leaves the last stage at tick (n - 1) + m
        out = ys[n - 1:].reshape(num_microbatches * mb, *x_rep.shape[1:])
        # only the last stage's selection is the answer; broadcast it
        return jax.lax.psum(
            jnp.where(idx == n - 1, out, jnp.zeros_like(out)), axis
        )

    ndim_p = P(axis)
    return jit_shard_map(
        local, mesh,
        in_specs=(
            jax.tree.map(lambda _: ndim_p, stage_params),
            P(*([None] * x.ndim)),
        ),
        out_specs=P(*([None] * x.ndim)),
    )(stage_params, x)
