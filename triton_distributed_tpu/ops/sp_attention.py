"""Sequence-parallel (ring) attention for long-context prefill.

Reference: ``python/triton_dist/kernels/nvidia/sp_ag_attention_intra_node.py``
— producer copy-engine AllGather of per-rank KV chunks (``:105``) feeding a
consumer causal flash-attention that waits on per-chunk arrival signals
(``:256``); host entry ``:430-521``.

TPU design — ring attention over ICI instead of AG-into-workspace:

- every rank holds the (Sq/n) query rows and (S/n) KV rows of its sequence
  shard; KV chunks rotate around the ring via ``lax.ppermute`` while each
  station folds the resident chunk into its carried online-softmax state
  with the Pallas chunk kernel (``ops/attention.flash_attention_chunk``);
- overlap comes from XLA's async collective-permute: the rotation of chunk
  s+1 and the flash pass over chunk s both depend only on chunk s, so the
  scheduler runs wire and MXU concurrently — the role the reference's
  producer/consumer split plays on CUDA (SURVEY.md section 7: "XLA
  schedules what the reference hand-stages");
- the n-step rotation moves each chunk over every link once
  (bandwidth-optimal, like the reference's full AG) but peak memory stays
  at ONE extra chunk instead of the whole gathered sequence — the property
  that makes million-token contexts shardable at all;
- causality is enforced in absolute positions inside the chunk kernel, so
  future chunks cost zero flash work (the kv loop clamps to 0 blocks) yet
  keep rotating for the ranks that need them.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core import compilation
from ..core.mesh import SP_AXIS
from .attention import (
    finalize_attention_state,
    flash_attention,
    flash_attention_chunk,
    init_attention_state,
)


@functools.lru_cache(maxsize=None)
def _build_sp_attention(mesh: Mesh, axis: str, shapes_key):
    (b, h, hk, s_loc, d, causal, has_segs, sm_scale, soft_cap, bq, bk,
     dtype) = shapes_key
    n = mesh.shape[axis]

    def local_fn(q_loc, k_loc, v_loc, *segs):
        r = jax.lax.axis_index(axis)
        sq_loc = segs[0] if has_segs else None     # (B, s_loc) my q segs

        def fold(state, k_c, v_c, sk_c, s):
            # chunk resident after s rotations came from rank (r - s) mod n
            src = jax.lax.rem(r - s + n, n)
            return flash_attention_chunk(
                q_loc, k_c, v_c, state,
                q_offset=r * s_loc, kv_offset=src * s_loc,
                causal=causal, sm_scale=sm_scale, soft_cap=soft_cap,
                block_q=bq, block_k=bk,
                segment_ids_q=sq_loc,
                segment_ids_kv=sk_c if has_segs else None,
            )

        # own chunk first, then n-1 rotate-and-fold steps (no final wasted
        # rotation); under varlen the KV SEGMENT IDS rotate alongside K/V
        sk0 = segs[0] if has_segs else None
        state0 = fold(init_attention_state(b, h, s_loc, d),
                      k_loc, v_loc, sk0, 0)

        def step(carry, s):
            k_c, v_c, sk_c, state = carry
            # the incoming rotation for step s and the fold of step s-1
            # both hang off step s-1's chunk — XLA overlaps wire and MXU.
            # (Interpret mode runs the permute rendezvous and the Pallas
            # barriers on the same client thread pool; that is safe ONLY
            # with spare virtual devices — see platform.force_cpu.)
            perm = [(i, (i + 1) % n) for i in range(n)]
            k_c = jax.lax.ppermute(k_c, axis, perm)
            v_c = jax.lax.ppermute(v_c, axis, perm)
            if has_segs:
                sk_c = jax.lax.ppermute(sk_c, axis, perm)
            return (k_c, v_c, sk_c, fold(state, k_c, v_c, sk_c, s)), None

        sk_init = sk0 if has_segs else jnp.zeros((), jnp.int32)
        (k_f, v_f, sk_f, state), _ = jax.lax.scan(
            step, (k_loc, v_loc, sk_init, state0), jnp.arange(1, n)
        )
        del k_f, v_f, sk_f
        return finalize_attention_state(state, dtype)

    seg_specs = (P(None, axis),) if has_segs else ()
    return compilation.jit_shard_map(
        local_fn, mesh,
        in_specs=(
            P(None, None, axis, None),
            P(None, None, axis, None),
            P(None, None, axis, None),
            *seg_specs,
        ),
        out_specs=P(None, None, axis, None),
    )


def sp_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    axis: str = SP_AXIS,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
    block_q: int = 512,
    block_k: int = 512,
    segment_ids: jax.Array | None = None,
) -> jax.Array:
    """Attention over a sequence-sharded (B, H, S, D) tensor set (reference
    host entry ``sp_ag_attention_intra_node.py:430-521``).

    ``q``: (B, H, S, D) and ``k``/``v``: (B, Hkv, S, D), all sharded on the
    sequence dim over ``axis``.  ``segment_ids``: optional (B, S) int32 for
    PACKED variable-length batches (the reference's varlen cu_seqlens
    support) — positions attend only within their segment; the KV segment
    ids rotate around the ring alongside the chunks.  Returns (B, H, S, D)
    with the same sharding.  Golden: single-device ``flash_attention`` on
    the gathered arrays.
    """
    n = mesh.shape[axis]
    b, h, s_tot, d = q.shape
    _, hk, sk, _ = k.shape
    if v.shape != k.shape or sk != s_tot:
        raise ValueError(f"shape mismatch: q={q.shape} k={k.shape} v={v.shape}")
    if h % hk:
        raise ValueError(f"GQA requires H % Hkv == 0, got {h} % {hk}")
    if segment_ids is not None and segment_ids.shape != (b, s_tot):
        raise ValueError(
            f"segment_ids {segment_ids.shape} != (B, S) = ({b}, {s_tot})"
        )
    if n == 1:
        return flash_attention(
            q, k, v, causal=causal, sm_scale=sm_scale, soft_cap=soft_cap,
            block_q=block_q, block_k=block_k, segment_ids=segment_ids,
        )
    if s_tot % n:
        raise ValueError(f"seq {s_tot} not divisible by {axis}={n}")
    s_loc = s_tot // n
    sm_scale = float(sm_scale) if sm_scale is not None else d ** -0.5
    fn = _build_sp_attention(
        mesh, axis,
        (b, h, hk, s_loc, d, bool(causal), segment_ids is not None,
         sm_scale, float(soft_cap),
         min(block_q, s_loc), min(block_k, s_loc), jnp.dtype(q.dtype)),
    )
    if segment_ids is not None:
        return fn(q, k, v, segment_ids.astype(jnp.int32))
    return fn(q, k, v)


# ---------------------------------------------------------------------------
# hierarchical (inter-slice) ring attention: inner=ICI ring, outer=DCN hops


@functools.lru_cache(maxsize=None)
def _build_hier_sp_attention(mesh: Mesh, inner_axis: str, outer_axis: str,
                             shapes_key):
    (b, h, hk, s_loc, d, causal, has_segs, sm_scale, soft_cap, bq, bk,
     dtype) = shapes_key
    n_in = mesh.shape[inner_axis]
    n_out = mesh.shape[outer_axis]

    def local_fn(q_loc, k_loc, v_loc, *segs):
        o = jax.lax.axis_index(outer_axis)
        i = jax.lax.axis_index(inner_axis)
        me = o * n_in + i        # global sequence rank (outer-major layout)
        sq_loc = segs[0] if has_segs else None     # (B, s_loc) my q segs

        def fold(state, k_c, v_c, sk_c, s, t):
            # after t outer hops (each preceded by n_in - 1 inner
            # rotations that are NOT unwound — the completion rotation is
            # absorbed into this index instead of paying an extra ICI hop)
            # and s inner rotations this step, the resident chunk
            # originated at global rank
            # ((o - t) % n_out, (i - s - t*(n_in-1)) % n_in)
            src = (jnp.mod(o - t, n_out) * n_in
                   + jnp.mod(i - s - t * (n_in - 1), n_in))
            return flash_attention_chunk(
                q_loc, k_c, v_c, state,
                q_offset=me * s_loc, kv_offset=src * s_loc,
                causal=causal, sm_scale=sm_scale, soft_cap=soft_cap,
                block_q=bq, block_k=bk,
                segment_ids_q=sq_loc,
                segment_ids_kv=sk_c if has_segs else None,
            )

        perm_in = [(j, (j + 1) % n_in) for j in range(n_in)]
        perm_out = [(j, (j + 1) % n_out) for j in range(n_out)]

        def inner_ring(k_c, v_c, sk_c, state, t):
            """One full ICI ring over the slice-resident chunk set: fold
            the resident chunk, then n_in - 1 rotate-and-folds (the wire
            overlaps the previous chunk's fold, as in the flat ring).
            Under varlen the KV segment ids ride every rotation with
            their chunk (reference inter-node varlen:
            ``sp_ag_attention_inter_node.py:56,328`` threads cu_seqlens
            through the same 2D schedule)."""
            state = fold(state, k_c, v_c, sk_c, 0, t)

            def inner_step(c2, s):
                k_c, v_c, sk_c, state = c2
                k_c = jax.lax.ppermute(k_c, inner_axis, perm_in)
                v_c = jax.lax.ppermute(v_c, inner_axis, perm_in)
                if has_segs:
                    sk_c = jax.lax.ppermute(sk_c, inner_axis, perm_in)
                return (k_c, v_c, sk_c,
                        fold(state, k_c, v_c, sk_c, s, t)), None

            (k_c, v_c, sk_c, state), _ = jax.lax.scan(
                inner_step, (k_c, v_c, sk_c, state), jnp.arange(1, n_in)
            )
            return k_c, v_c, sk_c, state

        def outer_body(carry, t):
            k_c, v_c, sk_c, state = carry
            k_c, v_c, sk_c, state = inner_ring(k_c, v_c, sk_c, state, t)
            # hop the slice-resident set one slice over DCN WITHOUT first
            # unwinding the inner rotation (fold's source index accounts
            # for the accumulated in-slice offset); each superchunk
            # crosses DCN n_out - 1 times total (the last outer step is
            # peeled below — fold only, no hops).  Segment ids hop too.
            k_c = jax.lax.ppermute(k_c, outer_axis, perm_out)
            v_c = jax.lax.ppermute(v_c, outer_axis, perm_out)
            if has_segs:
                sk_c = jax.lax.ppermute(sk_c, outer_axis, perm_out)
            return (k_c, v_c, sk_c, state), None

        sk0 = segs[0] if has_segs else jnp.zeros((), jnp.int32)
        state0 = init_attention_state(b, h, s_loc, d)
        (k_c, v_c, sk_c, state), _ = jax.lax.scan(
            outer_body, (k_loc, v_loc, sk0, state0), jnp.arange(n_out - 1)
        )
        _, _, _, state = inner_ring(k_c, v_c, sk_c, state, n_out - 1)
        return finalize_attention_state(state, dtype)

    seg_specs = ((P(None, (outer_axis, inner_axis)),) if has_segs else ())
    return compilation.jit_shard_map(
        local_fn, mesh,
        in_specs=(
            P(None, None, (outer_axis, inner_axis), None),
            P(None, None, (outer_axis, inner_axis), None),
            P(None, None, (outer_axis, inner_axis), None),
            *seg_specs,
        ),
        out_specs=P(None, None, (outer_axis, inner_axis), None),
    )


def hierarchical_sp_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    inner_axis: str,
    outer_axis: str,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
    block_q: int = 512,
    block_k: int = 512,
    segment_ids: jax.Array | None = None,
) -> jax.Array:
    """Ring attention composed over (outer=DCN, inner=ICI) — the TPU form
    of the reference's dedicated inter-node SP attention
    (``sp_ag_attention_inter_node.py:115-192``: NVSHMEM 2D push across
    nodes + intra-node consumer), which its flat intra-node path cannot
    serve across slices.

    The sequence dim is sharded over BOTH axes (outer-major).  Each outer
    step runs the full ICI ring within every slice (per-chunk folds with
    the carried softmax state), then the slice-resident chunk sets hop one
    slice over DCN — so each superchunk crosses the slow DCN links only
    ``n_out - 1`` times (the final outer step is fold-only) while all
    fine-grained rotation stays on ICI, mirroring the hierarchical
    AG/RS/AR collectives (``comm/allgather.py``).

    ``q``: (B, H, S, D), ``k``/``v``: (B, Hkv, S, D), sequence-sharded over
    ``(outer_axis, inner_axis)``.  ``segment_ids``: optional (B, S) int32
    for PACKED variable-length batches (the reference inter-node varlen
    path, ``sp_ag_attention_inter_node.py:56,328``): positions attend only
    within their segment, and the KV segment ids ride both the inner ICI
    rotations and the outer DCN hops alongside their chunks.  Returns the
    same sharding.  Golden: single-device ``flash_attention`` on the
    gathered arrays (packed, where segment_ids are given).
    """
    n_in = mesh.shape[inner_axis]
    n_out = mesh.shape[outer_axis]
    if n_out == 1:
        return sp_attention(
            q, k, v, mesh, inner_axis, causal=causal, sm_scale=sm_scale,
            soft_cap=soft_cap, block_q=block_q, block_k=block_k,
            segment_ids=segment_ids,
        )
    b, h, s_tot, d = q.shape
    _, hk, sk, _ = k.shape
    if v.shape != k.shape or sk != s_tot:
        raise ValueError(f"shape mismatch: q={q.shape} k={k.shape} v={v.shape}")
    if h % hk:
        raise ValueError(f"GQA requires H % Hkv == 0, got {h} % {hk}")
    if segment_ids is not None and segment_ids.shape != (b, s_tot):
        raise ValueError(
            f"segment_ids {segment_ids.shape} != (B, S) = ({b}, {s_tot})"
        )
    n = n_in * n_out
    if s_tot % n:
        raise ValueError(
            f"seq {s_tot} not divisible by "
            f"{outer_axis}*{inner_axis} = {n}"
        )
    s_loc = s_tot // n
    sm_scale = float(sm_scale) if sm_scale is not None else d ** -0.5
    fn = _build_hier_sp_attention(
        mesh, inner_axis, outer_axis,
        (b, h, hk, s_loc, d, bool(causal), segment_ids is not None,
         sm_scale, float(soft_cap),
         min(block_q, s_loc), min(block_k, s_loc), jnp.dtype(q.dtype)),
    )
    if segment_ids is not None:
        return fn(q, k, v, segment_ids.astype(jnp.int32))
    return fn(q, k, v)
