"""MoE routing utilities: top-k routing, expert sorting, weighted combine.

Reference: ``python/triton_dist/kernels/nvidia/moe_utils.py:94-360`` —
``calc_gather_scatter_index_triton`` (histogram + argsort of top-k expert
ids producing gather/scatter indices) and the weighted ``reduce_topk``
kernels.  On TPU these index computations are sorts/segment-sums over a
few thousand int32s — XLA compiles them natively (no kernel needed), and
static shapes fall out of the fixed (T, k) routing tensors.

Convention: routing REPLICATES each token k times (one row per chosen
expert); ``sort_by_expert`` orders the replicated rows by expert id;
``unsort_combine`` inverts the sort and sums the k copies with their
routing weights — together the exact data flow of the reference's
gather-scatter index pair.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


# The e4m3 quantization machinery this module pioneered now lives in the
# SHARED quant module (``lang.quant``, ISSUE 9) — one home for every
# wire producer (quantized collectives, MoE EP wire, int8 KV cache).
# The names below stay as thin aliases so existing callers keep working.
from ..lang.quant import E4M3_MAX, SCALE_EPS  # noqa: F401 (re-export)
from ..lang import quant as _quant


def quantize_e4m3(x: jax.Array, *, axis: int = -1):
    """Per-row fp8 quantization for the low-latency A2A payload
    (reference: the fp8 + scale-sidecar configuration of
    ``low_latency_all_to_all.py:36-120``, its headline 137 us case).
    Alias of ``lang.quant.quantize_rows(x, "fp8")`` — see there."""
    return _quant.quantize_rows(x, "fp8", axis=axis)


def dequantize(x8: jax.Array, scale: jax.Array, dtype=jnp.bfloat16):
    """Inverse of :func:`quantize_e4m3` (``lang.quant.dequantize_rows``)."""
    return _quant.dequantize_rows(x8, scale, dtype)


def topk_route(logits: jax.Array, k: int, *, renormalize: bool = True):
    """Softmax top-k routing (reference ``moe_utils.py`` router prep).

    ``logits``: (T, E).  Returns ``(expert_ids, weights)`` both (T, k);
    weights are the softmax probabilities of the chosen experts,
    renormalized to sum to 1 per token when ``renormalize``.
    """
    probs = jax.nn.softmax(logits.astype(jnp.float32), axis=-1)
    weights, expert_ids = jax.lax.top_k(probs, k)
    if renormalize:
        weights = weights / weights.sum(axis=-1, keepdims=True)
    return expert_ids.astype(jnp.int32), weights


def flatten_topk(x: jax.Array, expert_ids: jax.Array, weights: jax.Array):
    """Replicate tokens per routing choice: (T, H) + (T, k) ->
    ``(x_rep (T*k, H), eid (T*k,), w (T*k,))``, row-major in (token, choice)
    order so ``unsort_combine`` can fold the k copies back."""
    t, k = expert_ids.shape
    x_rep = jnp.repeat(x, k, axis=0)
    return x_rep, expert_ids.reshape(t * k), weights.reshape(t * k)


def sort_by_expert(x: jax.Array, expert_ids: jax.Array, num_experts: int):
    """Stable-sort rows by expert id (reference
    ``calc_gather_scatter_index``).

    Returns ``(x_sorted, splits, unsort_idx)``: ``splits`` (num_experts,)
    int32 row counts per expert; ``x_sorted[i] = x[sort_idx[i]]`` and
    ``x_sorted[unsort_idx] == x`` (the scatter index for the return trip).
    """
    sort_idx = jnp.argsort(expert_ids, stable=True)
    x_sorted = jnp.take(x, sort_idx, axis=0)
    splits = jnp.bincount(expert_ids, length=num_experts).astype(jnp.int32)
    unsort_idx = jnp.argsort(sort_idx, stable=True)
    return x_sorted, splits, unsort_idx


def unsort_combine(y_sorted: jax.Array, unsort_idx: jax.Array,
                   weights: jax.Array, k: int) -> jax.Array:
    """Invert :func:`sort_by_expert` and reduce the k routed copies with
    their weights (reference ``reduce_topk`` kernels): (T*k, N) -> (T, N).
    """
    y = jnp.take(y_sorted, unsort_idx, axis=0)          # back to (token, choice)
    tk, n_dim = y.shape
    y = y.reshape(tk // k, k, n_dim)
    w = weights.reshape(tk // k, k, 1).astype(y.dtype)
    return (y * w).sum(axis=1)


def global_presort_index(perm: jax.Array,
                         per_rank_unsort: jax.Array) -> jax.Array:
    """Compose the block-merge permutation with each rank's local unsort.

    ``perm``: (n*T,) from :func:`expert_block_permutation` (global expert
    order <- concatenated per-rank sorted blocks); ``per_rank_unsort``:
    (n, T) each rank's ``unsort_idx`` from :func:`sort_by_expert`.  Returns
    ``g`` (n*T,) such that ``y_global_sorted[g]`` enumerates rows in the
    original pre-sort (rank-major, then token, then routing choice) order —
    the index the weighted top-k fold consumes.
    """
    n, tkk = per_rank_unsort.shape
    inv = jnp.argsort(perm, stable=True)
    block_idx = (per_rank_unsort
                 + jnp.arange(n, dtype=per_rank_unsort.dtype)[:, None] * tkk
                 ).reshape(-1)
    return jnp.take(inv, block_idx)


def expert_block_permutation(splits_per_rank: jax.Array,
                             tokens_per_rank: int):
    """Permutation merging n per-rank expert-sorted blocks into one
    globally expert-sorted order (the index prep of the reference's
    AG + scatter group-GEMM, ``allgather_group_gemm.py:398-605``).

    ``splits_per_rank``: (n, E) counts per (source rank, expert);
    ``tokens_per_rank``: the STATIC per-rank row count (splits sum to it —
    passed explicitly so the whole index prep stays jittable).  Returns
    ``(perm, total_splits)``: gathering rows of the n concatenated sorted
    blocks with ``perm`` yields global expert order (rank-major within an
    expert); ``total_splits`` (E,) sums counts over ranks.
    """
    n, e = splits_per_rank.shape
    # expert id of each row of the concatenated blocks
    idx = jnp.arange(tokens_per_rank)
    eids = jax.vmap(
        lambda counts: jnp.searchsorted(jnp.cumsum(counts), idx, side="right")
    )(splits_per_rank).reshape(n * tokens_per_rank).astype(jnp.int32)
    perm = jnp.argsort(eids, stable=True)
    total_splits = splits_per_rank.sum(axis=0).astype(jnp.int32)
    return perm, total_splits
