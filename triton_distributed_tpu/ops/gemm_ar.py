"""Fused GEMM-AllReduce: row-parallel GEMM with the full sum on every rank.

Reference: the GEMM + AllReduce path of the TP MLP
(``python/triton_dist/layers/nvidia/tp_mlp.py:177`` dispatches to
``all_reduce`` after the down-projection when M is small;
``kernels/nvidia/allreduce.py:695-780`` host entries) — the reference's best
small-M configuration (1.37x at M=128, BASELINE.md).

TPU design — the compute-ahead-of-wire ring of ``ops/gemm_rs.py`` extended
by the in-kernel AllGather phase of ``comm/allreduce.py``'s two-shot kernel:

1. phase 1 (fused GEMM+RS): per ring step, matmul the output chunk that must
   leave next and fold it into the travelling partial — compute of step s
   hides the wire time of step s-1; the fully reduced chunk ``me`` lands in
   its final offset of the replicated output;
2. phase 2 (AG ring): reduced chunks are forwarded to their final offsets on
   every rank.  No inter-phase barrier: phase-1 writes only chunk ``me`` and
   each phase-2 consume is gated by its own per-chunk DMA semaphore.

Computes ``AllReduce_sum(A[M, K_loc] @ B_loc[K_loc, N])`` replicated — the
row-parallel half of a TP layer when the caller wants the full activation on
every rank (sequence parallelism off).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..comm import ring
from ..comm.ring import chunk as _chunk
from ..core import compilation
from ..core.mesh import TP_AXIS
from ..core.utils import clip_block
from ..lang import primitives as dl
from ..lang.primitives import Team
from . import blocks


@dataclasses.dataclass(frozen=True)
class GemmArConfig:
    bm: int = 1024
    bn: int = 1024
    bk: int = 512

    def clip(self, m_loc: int, k_loc: int, n_dim: int) -> "GemmArConfig":
        return GemmArConfig(
            bm=clip_block(self.bm, m_loc), bn=clip_block(self.bn, n_dim),
            bk=clip_block(self.bk, k_loc),
        )


def _gemm_ar_kernel(
    team: Team,
    m_loc: int,
    k_loc: int,
    n_dim: int,
    cfg: GemmArConfig,
    out_dtype,
    a_ref,        # (n*m_loc, k_loc) local A (K-shard)          [ANY]
    b_ref,        # (k_loc, n) local B (row shard)              [ANY]
    out_ref,      # (n*m_loc, n) full reduced result            [ANY]
    mm_buf,       # (2, m_loc, n) fresh local contributions     [HBM scratch]
    recv_buf,     # (2, m_loc, n) incoming partials             [HBM scratch]
    send_buf,     # (2, m_loc, n) outgoing accumulated          [HBM scratch]
    send_sems,    # (2,) per-parity RS send completion
    recv_sems,    # (2,) per-parity RS arrival
    ack_sems,     # (2,) RS consumption credits (REGULAR)
    ag_send_sem,  # AG phase sends
    ag_recv_sems,  # (n,) AG per-chunk arrival
    acc_ref,      # (bm, bn) f32                                 [VMEM scratch]
):
    me, n = team.rank(), team.size
    left, right = team.neighbor_ranks()
    left_id, right_id = team.device_id(left), team.device_id(right)

    mm = blocks.make_matmul_pipeline(
        m_loc, n_dim, k_loc, cfg.bm, cfg.bn, cfg.bk, out_dtype
    )
    add = blocks.make_add_pipeline(m_loc, n_dim, cfg.bm, cfg.bn)

    def a_chunk(c):
        return _chunk(a_ref, c, m_loc)

    dl.collective_prologue(team, neighbors_only=True)

    # ---- phase 1: fused GEMM + ring ReduceScatter (ops/gemm_rs.py flow,
    # final accumulation landing in out-chunk ``me``) ----
    j0 = jax.lax.rem(me + n - 1, n)
    mm(a_chunk(j0), b_ref, mm_buf.at[0], scratches=[acc_ref])
    dl.remote_copy(mm_buf.at[0], recv_buf.at[0], send_sems.at[0],
                   recv_sems.at[0], right_id)

    for s in range(1, n):
        j = jax.lax.rem(me + n - s - 1, n)
        slot_in = (s - 1) % 2
        slot_out = s % 2
        if s == 2:
            dl.wait_send(mm_buf.at[0], send_sems.at[0])
        mm(a_chunk(j), b_ref, mm_buf.at[slot_out], scratches=[acc_ref])
        dl.wait_recv(recv_buf.at[slot_in], recv_sems.at[slot_in])
        last = s == n - 1
        if last:
            # j == me: reduced chunk lands at its final replicated offset
            add(recv_buf.at[slot_in], mm_buf.at[slot_out],
                _chunk(out_ref, me, m_loc))
        else:
            if s >= 3:
                dl.wait_send(send_buf.at[slot_out], send_sems.at[slot_out])
            if s >= 2:
                dl.wait(ack_sems.at[slot_out], 1)
            add(recv_buf.at[slot_in], mm_buf.at[slot_out],
                send_buf.at[slot_out])
            dl.remote_copy(send_buf.at[slot_out], recv_buf.at[slot_out],
                           send_sems.at[slot_out], recv_sems.at[slot_out],
                           right_id)
        dl.notify(ack_sems.at[slot_in], left_id)

    # ---- phase 2: ring AllGather of reduced chunks ----
    ring.ag_ring_phase(team, out_ref, m_loc, ag_send_sem, ag_recv_sems,
                       right_id)

    # ---- drains (RS send accounting identical to ops/gemm_rs.py) ----
    if n == 2:
        dl.wait_send(send_buf.at[0], send_sems.at[0])
    elif n == 3:
        dl.wait_send(send_buf.at[1], send_sems.at[1])
    else:
        dl.wait_send(send_buf.at[0], send_sems.at[0])
        dl.wait_send(send_buf.at[1], send_sems.at[1])
    ring.rs_ack_drain(ack_sems, n)
    ring.ag_ring_drain(team, out_ref, m_loc, ag_send_sem)


@functools.lru_cache(maxsize=None)
def _build_gemm_ar(
    mesh: Mesh,
    axis: str,
    m_loc: int,
    k_loc: int,
    n_dim: int,
    dtype: jnp.dtype,
    out_dtype: jnp.dtype,
    cfg: GemmArConfig,
):
    team = Team.of(mesh, axis)
    n = team.size
    compilation.verify_protocol("gemm_ar", n)

    from ..obs import costs

    kernel = functools.partial(
        _gemm_ar_kernel, team, m_loc, k_loc, n_dim, cfg, out_dtype
    )
    call = pl.pallas_call(
        kernel,
        # kernel cost attribution sourced from obs.costs (one flop/byte
        # truth for Mosaic, the SOL model, and the flight timeline)
        cost_estimate=costs.pallas_cost(
            costs.gemm_ar(m_loc, k_loc, n_dim, n, dtype, out_dtype)),
        out_shape=jax.ShapeDtypeStruct((n * m_loc, n_dim), out_dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.HBM((2, m_loc, n_dim), out_dtype),
            pltpu.HBM((2, m_loc, n_dim), out_dtype),
            pltpu.HBM((2, m_loc, n_dim), out_dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((n,)),
            pltpu.VMEM((cfg.bm, cfg.bn), jnp.float32),
        ],
        compiler_params=compilation.compiler_params(
            collective=True,
            collective_id=compilation.collective_id("gemm_ar"),
        ),
        interpret=compilation.interpret_mode(),
    )
    return compilation.jit_shard_map(
        call, mesh,
        in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(None, None),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _gemm_ar_core(mesh, axis, cfg, out_dtype, a, b):
    """Differentiable n>1 core.  The AllReduce's adjoint on a replicated
    cotangent is the identity, so the backward pass is two LOCAL GEMMs —
    no wire at all (cf. ``ag_gemm``/``gemm_rs``, whose adjoints are each
    other)."""
    n = mesh.shape[axis]
    fn = _build_gemm_ar(
        mesh, axis, a.shape[0] // n, a.shape[1] // n, b.shape[1],
        jnp.dtype(a.dtype), out_dtype, cfg,
    )
    return fn(a, b)


def _gemm_ar_fwd(mesh, axis, cfg, out_dtype, a, b):
    return _gemm_ar_core(mesh, axis, cfg, out_dtype, a, b), (a, b)


def _gemm_ar_bwd(mesh, axis, cfg, out_dtype, res, dout):
    from ..core import compilation

    a, b = res

    def local(ar, br, d):
        da = jnp.dot(d, br.T, preferred_element_type=jnp.float32)
        db = jnp.dot(ar.T, d, preferred_element_type=jnp.float32)
        return da.astype(a.dtype), db.astype(b.dtype)

    return compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(None, axis), P(axis, None), P(None, None)),
        out_specs=(P(None, axis), P(axis, None)),
    )(a, b, dout)


_gemm_ar_core.defvjp(_gemm_ar_fwd, _gemm_ar_bwd)


def gemm_ar(
    a: jax.Array,
    b: jax.Array,
    mesh: Mesh,
    axis: str = TP_AXIS,
    *,
    config: GemmArConfig | None = None,
    out_dtype=None,
    wire_dtype: str = "bf16",
) -> jax.Array:
    """Overlapped ``AllReduce(a @ b)`` (reference: ``tp_mlp.py:177`` GEMM+AR
    dispatch; ``kernels/nvidia/allreduce.py:695-780``).

    ``a``: (M, K) sharded on dim 1 over ``axis`` (activations, K-parallel).
    ``b``: (K, N) sharded on dim 0 over ``axis`` (row-parallel weight).
    Returns (M, N) replicated on every rank: the full sum.

    ``wire_dtype``: "int8"/"fp8" reduces the local partial through the
    quantized two-hop exchange (``comm.quantized`` — both hops packed;
    the error-feedback option lives on ``quantized_all_reduce``); "auto"
    resolves through the contextual tuner per shape/ranks/wire class.
    """
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(a.dtype)
    n = mesh.shape[axis]

    m_tot, k_dim = a.shape
    k2, n_dim = b.shape
    if k2 != k_dim:
        raise ValueError(f"inner dims mismatch: {a.shape} @ {b.shape}")
    if n == 1:
        return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(out_dtype)
    if m_tot % n or k_dim % n:
        raise ValueError(
            f"M={m_tot} and K={k_dim} must be divisible by {axis}={n}"
        )
    if wire_dtype != "bf16":
        from ..comm import quantized as _q
        from ..tune.autotuner import is_tracer as _q_is_tracer

        if wire_dtype == "auto":
            wire_dtype = _q.resolve_wire_dtype(
                "gemm_ar_wire", (m_tot, k_dim, n_dim, str(a.dtype)),
                mesh, axis,
                lambda wd: (lambda: gemm_ar(
                    a, b, mesh, axis, config=config, out_dtype=out_dtype,
                    wire_dtype=wd)),
                tracing=_q_is_tracer(a),
            )
        if wire_dtype != "bf16":
            parts = _q.stacked_partial_gemm(a, b, mesh, axis, out_dtype)
            return _q.quantized_all_reduce(
                parts, mesh, axis, wire_dtype=wire_dtype,
                out_dtype=out_dtype)

    if config is None:
        # transparent contextual tuning (see ops/ag_gemm.py)
        from ..tune import autotuner as _tune

        config = _tune.resolve_gemm_like(
            "gemm_ar", gemm_ar, GemmArConfig, _tune.GEMM_AR_CAND_DIMS,
            GemmArConfig(), a, b, mesh, axis, dict(out_dtype=out_dtype), {},
        )
    cfg = config

    m_loc, k_loc = m_tot // n, k_dim // n
    cfg = cfg.clip(m_loc, k_loc, n_dim)
    from .. import resilience
    from ..tune.autotuner import is_tracer

    core = lambda: _gemm_ar_core(mesh, axis, cfg, out_dtype, a, b)  # noqa: E731
    eager = not is_tracer(a)
    if eager and resilience.integrity.enabled():
        # consumer-side Freivalds verification (TDT_INTEGRITY=1)
        core = resilience.integrity.checked(
            "gemm_ar", core, ranks=n,
            verify=lambda out: resilience.integrity.verify_gemm(
                "gemm_ar", a, b, out))
    if eager and resilience.enabled():
        # eager calls only (see comm/allgather.py): watchdog + ladder,
        # degraded fallback = local partial GEMM + XLA AllReduce
        return resilience.guarded(
            "gemm_ar", core,
            family="gemm_ar", ranks=n,
            payload_bytes=m_tot * n_dim * jnp.dtype(out_dtype).itemsize,
            fallback=lambda: resilience.fallbacks.xla_gemm_ar(
                a, b, mesh, axis, out_dtype),
        )()
    return core()
