"""Rotary position embeddings (RoPE), rotate-half convention.

The reference applies RoPE inside its attention layer with a dedicated
Triton kernel (``python/triton_dist/layers/nvidia/tp_attn.py:78-150``).  On
TPU a hand-written kernel would be a pessimization: RoPE is a pure
elementwise+transpose pattern that XLA fuses directly into the surrounding
attention matmuls, so the TPU-native form IS the jnp expression below
(SURVEY.md section 7: "elementwise epilogues collapse into XLA fusion").

Convention: GPT-NeoX / LLaMA / Qwen rotate-half — the head dim is split in
two halves, rotated as complex pairs (x1, x2) -> (x1 cos - x2 sin,
x2 cos + x1 sin).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_freqs(
    positions: jax.Array,
    head_dim: int,
    *,
    theta: float = 10_000.0,
    dtype=jnp.float32,
) -> tuple[jax.Array, jax.Array]:
    """(cos, sin) tables of shape ``positions.shape + (head_dim // 2,)``."""
    half = head_dim // 2
    inv_freq = 1.0 / (
        theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )
    angles = positions.astype(jnp.float32)[..., None] * inv_freq
    return jnp.cos(angles).astype(dtype), jnp.sin(angles).astype(dtype)


def apply_rope(
    x: jax.Array,
    cos: jax.Array,
    sin: jax.Array,
) -> jax.Array:
    """Rotate ``x`` (..., seq, head_dim) by tables (..., seq, head_dim//2).

    Tables broadcast over leading axes, so one (seq, half) table serves a
    (B, H, seq, D) activation.
    """
    half = x.shape[-1] // 2
    x1, x2 = x[..., :half], x[..., half:]
    x1f, x2f = x1.astype(jnp.float32), x2.astype(jnp.float32)
    out = jnp.concatenate(
        [x1f * cos - x2f * sin, x2f * cos + x1f * sin], axis=-1
    )
    return out.astype(x.dtype)


def apply_rope_at(
    x: jax.Array,
    positions: jax.Array,
    *,
    theta: float = 10_000.0,
) -> jax.Array:
    """Convenience: rotate ``x`` (..., seq, head_dim) at absolute
    ``positions`` (seq,) or broadcastable."""
    cos, sin = rope_freqs(positions, x.shape[-1], theta=theta)
    return apply_rope(x, cos, sin)
