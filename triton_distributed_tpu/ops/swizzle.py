"""Tile scheduling: ring-arrival consumption orders and grouped-GEMM
work-unit schedules.

Reference: the threadblock-swizzle family —
``kernels/nvidia/ag_gemm_threadblock_swizzle.py`` /
``gemm_rs_threadblock_swizzle.py`` (tile visit orders following ring
arrival), ``threadblock_swizzle_ag_moe.{py,cu,cc}`` (AG-MoE tile order,
shipped in python/Triton/native-CUDA triplicate) and the host alignment op
``csrc/lib/moe_utils.cu:61-314`` (``moe_ag_scatter_align_block_size``: pad
each expert's token run to block multiples and emit per-block expert ids).

On TPU the consumers differ, so the module splits in two:

- **ring orders** (:func:`ring_chunk_order`): the chunk consumption
  sequence of the fused collective kernels (``ops.ag_gemm``), self first
  then by ring arrival — trace-time integer math, no kernel;
- **grouped schedules** (:func:`grouped_tile_schedule`): the reference's
  block-alignment kernel becomes a *jittable index computation* whose
  outputs feed a Pallas kernel through scalar prefetch
  (``ops.group_gemm.grouped_matmul``).  Instead of physically padding the
  token array to block multiples (the reference materializes
  ``sorted_token_ids`` with pad slots), the schedule enumerates
  (m-tile, group) work units over the *unpadded* rows and the kernel masks
  the rows of other groups — same tiling, no HBM copy of the inputs.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp


def ring_chunk_order(rank, size: int, step: int):
    """Chunk id consumed at ring step ``step`` by ``rank`` (0 = the local
    shard, then counter-flow arrival order: me, me-1, me-2, ...).

    The unidirectional-ring swizzle of ``ops.ag_gemm`` (reference:
    rank-offset tile reordering, ``allgather_gemm.py:205-215``).  ``rank``
    may be a traced scalar; ``size``/``step`` are trace-time ints.
    """
    if step == 0:
        return rank
    return jax.lax.rem(rank + size - step, size)


class GroupedSchedule(NamedTuple):
    """Scalar-prefetch arrays for :func:`ops.group_gemm.grouped_matmul`.

    All int32 of length ``num_slots = num_rows//bm + num_groups`` (static).
    Slot ``s`` multiplies m-tile ``tile_ids[s]`` by group ``group_ids[s]``'s
    weights, contributing only rows in ``[row_starts[s], row_ends[s])``
    (global row ids; empty for padding slots).  ``is_first[s]`` is 1 on the
    first slot of each tile (the kernel initializes the output block there,
    accumulating on later slots).  Slots are tile-major, so revisits of an
    output block are always grid-adjacent.

    ``valid[s]`` is 0 on PAD slots (the unused tail of the worst-case
    ``nt + E`` allocation).  Pads carry the same tile/group ids as the
    last real slot so the kernel's index maps can freeze their block
    fetches (consecutive identical indices are elided by Pallas — without
    this, every pad slot re-streams a full (bm, K) x-stripe and (K, bn)
    w-stripe it never uses; at the MoE bench shape that was ~30% of the
    kernel's HBM traffic).  ``covers[s]`` is 1 when the slot's rows span
    its whole tile (the common, splits-aligned case): the kernel then
    writes the accumulator straight out and skips the row-mask arithmetic.
    """

    tile_ids: jax.Array
    group_ids: jax.Array
    row_starts: jax.Array
    row_ends: jax.Array
    is_first: jax.Array
    valid: jax.Array
    covers: jax.Array


def grouped_tile_schedule(group_sizes: jax.Array, num_rows: int,
                          bm: int) -> GroupedSchedule:
    """Work-unit schedule for a grouped matmul over expert-sorted rows.

    ``group_sizes``: (E,) int32 row counts per group, contiguous from row 0
    (sum <= num_rows; trailing rows belong to no group and are zero-filled
    by the kernel).  ``num_rows`` must divide by ``bm``.

    Jittable: every output has static shape ``(num_rows//bm + E,)``; the
    values are data-dependent, which is exactly what scalar prefetch
    exists for.  This is the reference's ``moe_ag_scatter_align_block_size``
    re-derived for TPU: where the CUDA kernel pads token ids so every block
    is single-expert, this schedule lets a block span a group boundary and
    assigns it one work unit per overlapped group.
    """
    (num_groups,) = group_sizes.shape
    if num_rows % bm:
        raise ValueError(f"num_rows={num_rows} not divisible by bm={bm}")
    nt = num_rows // bm
    num_slots = nt + num_groups

    sizes = group_sizes.astype(jnp.int32)
    ends = jnp.cumsum(sizes)
    starts = ends - sizes
    tile_lo = jnp.arange(nt, dtype=jnp.int32) * bm

    # groups intersecting tile t: first = first group ending past the tile
    # start, last = last group starting before the tile end
    first = jnp.searchsorted(ends, tile_lo, side="right").astype(jnp.int32)
    last = (jnp.searchsorted(starts, tile_lo + bm, side="left") - 1).astype(
        jnp.int32
    )
    per_tile = jnp.maximum(last - first + 1, 0)
    # every tile gets >= 1 slot so uncovered trailing tiles still zero-fill
    slots_per_tile = jnp.maximum(per_tile, 1)
    slot_end = jnp.cumsum(slots_per_tile)
    total = slot_end[nt - 1]

    s = jnp.arange(num_slots, dtype=jnp.int32)
    tile = jnp.minimum(
        jnp.searchsorted(slot_end, s, side="right").astype(jnp.int32), nt - 1
    )
    rank_in_tile = s - (slot_end[tile] - slots_per_tile[tile])
    group = jnp.clip(first[tile] + rank_in_tile, 0, num_groups - 1)

    lo = tile * bm
    row_start = jnp.maximum(starts[group], lo)
    row_end = jnp.minimum(ends[group], lo + bm)
    valid = s < total
    row_start = jnp.where(valid, row_start, 0)
    row_end = jnp.where(valid, row_end, 0)
    is_first = ((rank_in_tile == 0) & valid).astype(jnp.int32)
    # pads inherit the last REAL slot's tile/group so their (frozen) block
    # fetches are grid-adjacent duplicates the pipeline elides
    last = jnp.maximum(total - 1, 0)
    tile = jnp.where(valid, tile, jnp.take(tile, last))
    group = jnp.where(valid, group, jnp.take(group, last))
    covers = (valid & (row_start == lo) & (row_end == lo + bm)).astype(
        jnp.int32
    )
    return GroupedSchedule(tile, group, row_start, row_end, is_first,
                           valid.astype(jnp.int32), covers)
