"""Persistent serving megakernel: the device-resident multi-layer decode
loop (ROADMAP item 2, the step past PR 8's per-layer fusion).

PR 8 fused decode *within* a layer — qkv/rope/append/flash in one kernel,
the MLP chained into its AllReduce — but the step loop still returned
control to the host L times per token: per layer one attention launch and
two chained-reduction launches, plus the once-per-step
``replace_layer_slices`` pool rebuild and the autotuner winner-cache
consult inside the hot path.  Those are exactly the *hidden
serialization* seams "Eliminating Hidden Serialization in Multi-Node
Megakernel Communication" (PAPERS.md) names: the exposed cost is no
longer kernels, it is the host-visible boundaries between them.  The
flight recorder + timeline attributor (PRs 4-5) can show every one of
them as an exposed wait at a dispatch boundary.

This module removes the seams (docs/perf.md "Persistent decode loop"):

- **One persistent grid for all L layers**
  (:func:`persistent_decode_step`): the PR-8 per-layer megakernels chain
  inside ONE collective ``pallas_call`` — per layer the attention cell
  (qkv GEMM + qk-norm + rope + ragged paged append + block-table flash
  decode), the o-proj column-ring AllReduce, and the SwiGLU-MLP
  column-ring AllReduce, with the residual/norm glue fused between
  stages (``blocks.make_rmsnorm_pipeline`` / ``make_add_pipeline``).
  Layer weights live in stacked ``(L, ...)`` HBM arrays and stream
  through the double-buffered VMEM pipelines the ``ops.blocks``
  emit-pipeline factories build — no whole-layer weight resident set.
- **Semaphores re-armed in-kernel**: all 2L ring-reduction instances
  share ONE semaphore/buffer set.  Instance j+1's first sends wait the
  outstanding ACK credits of instance j (the credits the single-kernel
  form drains at exit), so the inter-layer dependency is carried by the
  same two-shot-AR semaphore protocol ``fused_mlp_ar`` uses between its
  GEMM and reduction — never by a host-visible semaphore reset.  One
  ``rs_ack_drain`` runs at kernel exit for the final instance.
- **KV writeback folded into the aliased pool**: the stacked page pools
  ride ``input_output_aliases`` through the one launch; each layer's
  token append is an in-place DMA into its pool rows.  The per-step
  ``replace_layer_slices`` rebuild (2 pool materializations per step)
  disappears from the persistent path entirely.
- **N steps per dispatch** (:func:`decode_bundle` /
  ``Qwen3.decode_multi``): the step bundle — embed gather, the
  megakernel, final-norm + lm_head, greedy argmax feedback — runs under
  ``lax.scan`` inside ONE jitted dispatch, so batch-membership changes
  apply only *between* dispatches (the PR-6 stateless step × scheduler
  split; ``serve.EngineBackend`` grows the ``steps_per_dispatch`` knob
  and the scheduler batches membership-stable windows).  The static
  dispatch counter (:func:`count_bundle_dispatches`) sees exactly TWO
  launch-shaped equations per step bundle: the megakernel and the
  lm_head GEMM — down from 2·L per token.
- **Config resolution hoisted out of the step**: the tile config
  resolves through the contextual autotuner once per (shape, steps)
  executable — ``serve.EngineBackend`` resolves it at construction and
  threads it explicitly, so the hot loop never consults the winner
  cache per dispatch (``tune.fresh_tune_persistent_decode`` is the
  bench/warmup re-measure hook).

Scope: full-precision paged pools (an int8 pool's in-kernel append would
have to re-encode page scales — those deployments keep
``decode_mode="fused"``, whose per-layer kernels return the token for
the exact quantized scatter); dense MLP (MoE decodes through the
replicated EP path).  ``n == 1`` degenerates to the pure-XLA reference
step (:func:`reference_decode_step`) — also the parity golden and the
resilience ladder's degraded fallback
(``resilience.fallbacks.xla_persistent_decode``).

Verification discipline (the PR-8 pattern): the kernel body is written
entirely in the recordable vocabulary — ``lang.primitives`` DMA/signal
ops, ``ops.blocks`` factories (protocol stubs under record mode), ring
helpers — so ``analysis.registry`` family ``persistent_decode`` verifies
the whole chained multi-layer protocol at ranks {2, 4, 8}; the fault
matrix injects into the chain (``scripts/tdt_lint.py --persistent``);
``obs.costs`` prices the family for the watchdog, Mosaic and timeline.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..comm import ring
from ..core import compilation
from ..core.mesh import TP_AXIS
from ..core.utils import clip_block
from ..lang import primitives as dl
from ..lang.primitives import Team
from . import blocks
from .rope import apply_rope_at

# ---------------------------------------------------------------------------
# stacked layer parameters (the kernel's weight layout)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class StackedDecodeParams:
    """Per-layer decode weights stacked on a leading (L,) axis — the
    persistent kernel streams layer ``l``'s slices through its
    double-buffered pipelines instead of taking L separate pytrees.
    Layouts match ``models.qwen`` (``wqkv`` columns rank-blocked
    ``[q_r | k_r | v_r]``, ``gate_up`` columns rank-blocked
    ``[gate_r | up_r]``, ``wo``/``down`` row-parallel).  Built once per
    trace by ``models.qwen.stack_decode_params``."""

    ln1: jax.Array                    # (L, K)
    wqkv: jax.Array                   # (L, K, (H + 2*Hk) * D)
    q_norm: jax.Array | None          # (L, D) when qk-norm, else None
    k_norm: jax.Array | None
    wo: jax.Array                     # (L, H*D, K)
    ln2: jax.Array                    # (L, K)
    gate_up: jax.Array                # (L, K, 2*F)
    down: jax.Array                   # (L, F, K)


# ---------------------------------------------------------------------------
# config


_PERSISTENT_VL = 100 * 2**20


@dataclasses.dataclass(frozen=True)
class PersistentDecodeConfig:
    """Tile knobs of the persistent decode megakernel: ``bm`` rows
    (clipped to B), ``bn`` output columns per matmul block, ``bk``
    contraction depth, ``bf`` the gate/up feature tile; ``vmem_limit``
    raises Mosaic's scoped budget.  The default REQUESTS the raised
    budget: the per-layer streamed weight working set (double-buffered
    qkv/o/gate-up/down stacks) is ~2x the layer's weight bytes and
    exceeds the 16 MiB Mosaic default at every serving hidden size —
    the ISSUE-15 footprint lint (``analysis.footprint.check_defaults``)
    caught the old ``None`` default as statically unbuildable exactly
    when the autotuner is cold."""

    bm: int = 1024
    bn: int = 512
    bk: int = 512
    bf: int = 512
    vmem_limit: int | None = _PERSISTENT_VL


def persistent_decode_candidates(b: int, k_loc: int, cn: int) -> list:
    """Default-first sweep for the ``config=None`` path, clipped to the
    problem and deduped like ``fused_mlp_candidates`` — at decode shapes
    most tilings collapse onto the default and the one-candidate sweep
    short-circuits.  The default-budget (``None``) variant stays in the
    sweep for small models whose streamed set fits 16 MiB; the footprint
    pruner drops it where it cannot build."""
    dims = [(1024, 512, 512, 512, _PERSISTENT_VL),
            (1024, 1024, 512, 512, _PERSISTENT_VL),
            (1024, 512, 1024, 1024, _PERSISTENT_VL),
            (1024, 512, 512, 512, None)]
    # NOTE: resolve paths consume this through
    # ``persistent_candidates_pruned`` (the footprint pruner drops the
    # default-budget variant where it cannot build); this raw list is
    # the unpruned sweep definition
    out, seen = [], set()
    for bm, bn, bk, bf, vl in dims:
        c = PersistentDecodeConfig(
            bm=clip_block(bm, b), bn=clip_block(bn, cn),
            bk=clip_block(bk, k_loc), bf=clip_block(bf, k_loc),
            vmem_limit=vl)
        if c not in seen:
            seen.add(c)
            out.append(c)
    return out


def persistent_candidates_pruned(layers: int, b: int, k_dim: int,
                                 f_dim: int, h: int, hk: int, ps: int,
                                 d: int, n: int, dtype) -> list:
    """The ONE pruned sweep every persistent resolve path must consume —
    the transparent ``persistent_decode_step(config=None)`` path,
    ``tune.fresh_tune_persistent_decode``, and the ``serve.EngineBackend``
    construction-time hoist: the candidates digest keys the winner
    cache, so a one-sided prune would split it (the review-pinned
    invariant, see ``tune.autotuner.prune_infeasible``), and the
    per-device streamed weight working set decides which budget
    variants can build at all (at serving hidden sizes the
    default-budget variant cannot — measuring it pays a doomed compile,
    fatal per-rank in multi-process sweeps).  Dims are GLOBAL (the
    entry-point shapes); per-device hk/g/f_loc are derived here exactly
    as the builder derives them."""
    from ..tune.autotuner import prune_infeasible

    n = max(n, 1)
    hk_loc = max(hk // n, 1)
    g = max((h // n) // hk_loc, 1)
    return prune_infeasible(
        "persistent_decode",
        persistent_decode_candidates(b, f_dim // n, k_dim // n),
        PersistentDecodeConfig(),
        dict(layers=layers, b=b, k_dim=k_dim, hk=hk_loc, g=g, d=d,
             page_size=ps, f_loc=f_dim // n, num_ranks=n, dtype=dtype))


# ---------------------------------------------------------------------------
# the chained column-ring AllReduce (one instance = one fused reduction)


def _chained_ar(team: Team, b: int, cn: int, mm, add, a_ref, w_chunk,
                out_ref, mm_buf, recv_buf, send_buf, send_sems, recv_sems,
                ack_sems, ag_send_sem, ag_recv_sems, acc_ref, *,
                armed: bool):
    """One ``AllReduce(a @ W)`` instance over OUTPUT column chunks — the
    ``fused_mlp_ar`` two-shot ring (GEMM-RS phase 1, AG phase 2) on a
    SHARED semaphore/buffer set.

    ``armed`` marks a non-first instance in the persistent chain: its
    first sends reuse ring buffers the previous instance's consumer may
    still hold, so it first consumes the previous instance's outstanding
    ACK credits — the credits the standalone kernel's ``rs_ack_drain``
    would have burned at exit.  That wait IS the inter-layer dependency
    edge, carried in-kernel by the same semaphores instead of a host
    boundary; the caller runs ONE ``rs_ack_drain`` at kernel exit for
    the final instance.  Chunk ``c`` of the reduced output lands at rows
    ``[c*b, (c+1)*b)`` of ``out_ref`` (chunk-major, like
    ``fused_mlp_ar``)."""
    n = team.size
    left, right = team.neighbor_ranks()
    left_id, right_id = team.device_id(left), team.device_id(right)

    if armed:
        # re-arm in kernel: the previous instance left exactly the
        # credits its standalone form drains at exit — consuming them
        # HERE (the SAME rs_ack_drain accounting, one home) proves the
        # right neighbor consumed every ring slot of the previous
        # instance before this one's first write reuses them
        ring.rs_ack_drain(ack_sems, n)

    # phase 1: chunk GEMM + travelling-partial ring — the ONE shared
    # body (ring.gemm_rs_chunk_phase, also run by the standalone
    # fused_mlp_ar kernel): step s's partial computes while step s-1's
    # chunk is on the wire, chained through the DMA/ack semaphores
    ring.gemm_rs_chunk_phase(team, b, mm, add, a_ref, w_chunk, out_ref,
                             mm_buf, recv_buf, send_buf, send_sems,
                             recv_sems, ack_sems, acc_ref, right_id,
                             left_id)

    # phase 2: AG ring of reduced chunks + per-instance local drains
    # (the fused_mlp_ar accounting; ACK credits deliberately NOT drained
    # here — the next instance's armed waits consume them)
    ring.ag_ring_phase(team, out_ref, b, ag_send_sem, ag_recv_sems,
                       right_id)
    ring.gemm_rs_send_drain(n, send_buf, send_sems)
    ring.ag_ring_drain(team, out_ref, b, ag_send_sem)


# ---------------------------------------------------------------------------
# the attention cell (real-mode only; a protocol stub under record mode)


def _attn_cell_real(l: int, b: int, hk: int, g: int, d: int, ps: int,
                    mp: int, pool_pages: int, theta: float, qk_eps,
                    sm_scale: float, soft_cap: float, qkv_hbm, qn_s, kn_s,
                    table_ref, lens_ref, pool_k, pool_v, out_vm, qrow,
                    qn_vm, kn_vm, ktok, vtok, kbuf, vbuf, stage_sems,
                    pg_sems, tok_sems):
    """One layer's attention-side decode inside the persistent loop:
    the ``_fused_attn_kernel`` cell (qk-norm + rope + ragged in-place
    paged append + double-buffered page-streamed flash decode with the
    fresh token folded from registers) with the (kv-head, batch) grid
    unrolled as static loops and the pool rows offset into layer ``l``'s
    block of the stacked pool."""
    from .attention import _init_carry, _tile_update, safe_normalize_decode
    from .fused_decode import _rms, _rope1

    h_loc = hk * g
    base = l * pool_pages
    if qk_eps is not None:
        cq = pltpu.make_async_copy(qn_s.at[pl.ds(l, 1)], qn_vm,
                                   stage_sems.at[1])
        ck = pltpu.make_async_copy(kn_s.at[pl.ds(l, 1)], kn_vm,
                                   stage_sems.at[1])
        cq.start()
        ck.start()
        cq.wait()
        ck.wait()
    for b_i in range(b):
        cp = pltpu.make_async_copy(qkv_hbm.at[pl.ds(b_i, 1)], qrow,
                                   stage_sems.at[1])
        cp.start()
        cp.wait()
        pos = lens_ref[b_i]
        for h_i in range(hk):
            q = qrow[0, h_i * g * d:(h_i + 1) * g * d].reshape(g, d)
            k_new = qrow[0, (h_loc + h_i) * d:(h_loc + h_i + 1) * d
                         ].reshape(1, d)
            v_new = qrow[0, (h_loc + hk + h_i) * d:
                         (h_loc + hk + h_i + 1) * d].reshape(1, d)
            if qk_eps is not None:
                q = _rms(q, qn_vm[...], qk_eps)
                k_new = _rms(k_new, kn_vm[...], qk_eps)
            q = _rope1(q, pos, theta)
            k_new = _rope1(k_new, pos, theta)

            # ragged in-place append into layer l's pool rows (the KV
            # writeback folded into the persistent loop's aliased pool)
            pg = jnp.minimum(pos // ps, mp - 1)
            row = (base + table_ref[b_i * mp + pg]) * hk + h_i
            off = pos % ps
            ktok[...] = k_new.astype(ktok.dtype)
            vtok[...] = v_new.astype(vtok.dtype)
            wk = pltpu.make_async_copy(
                ktok, pool_k.at[row, pl.ds(off, 1)], tok_sems.at[0])
            wv = pltpu.make_async_copy(
                vtok, pool_v.at[row, pl.ds(off, 1)], tok_sems.at[1])
            wk.start()
            wv.start()
            wk.wait()
            wv.wait()

            q_s = (q.astype(jnp.float32) * sm_scale).astype(q.dtype)
            npages = jnp.minimum((pos + ps - 1) // ps, mp)

            def page_dma(slot, j, b_i=b_i, h_i=h_i):
                r = (base + table_ref[b_i * mp + j]) * hk + h_i
                return (
                    pltpu.make_async_copy(pool_k.at[r], kbuf.at[slot],
                                          pg_sems.at[slot, 0]),
                    pltpu.make_async_copy(pool_v.at[r], vbuf.at[slot],
                                          pg_sems.at[slot, 1]),
                )

            @pl.when(npages > 0)
            def _():
                ck0, cv0 = page_dma(0, 0)
                ck0.start()
                cv0.start()

            def body(j, carry, q_s=q_s, pos=pos, page_dma=page_dma):
                @pl.when(j + 1 < npages)
                def _():
                    ckn, cvn = page_dma((j + 1) % 2, j + 1)
                    ckn.start()
                    cvn.start()

                ckj, cvj = page_dma(j % 2, j)
                ckj.wait()
                cvj.wait()
                kpos = j * ps + jax.lax.broadcasted_iota(
                    jnp.int32, (g, ps), 1)
                return _tile_update(q_s, kbuf[j % 2], vbuf[j % 2],
                                    kpos < pos, soft_cap, carry)

            carry = jax.lax.fori_loop(0, npages, body, _init_carry(g, d))

            kt8 = jnp.concatenate(
                [k_new, jnp.zeros((7, d), k_new.dtype)], axis=0)
            vt8 = jnp.concatenate(
                [v_new, jnp.zeros((7, d), v_new.dtype)], axis=0)
            tok_mask = jax.lax.broadcasted_iota(jnp.int32, (g, 8), 1) == 0
            m1, l1, acc1 = _tile_update(q_s, kt8, vt8, tok_mask, soft_cap,
                                        carry)
            out_vm[b_i, h_i * g * d:(h_i + 1) * g * d] = \
                safe_normalize_decode(acc1, l1, out_vm.dtype).reshape(g * d)


# ---------------------------------------------------------------------------
# the persistent kernel body (shared: real Pallas build AND record mode)


def _persistent_decode_kernel(
    team: Team,
    layers: int,
    b: int,
    k_dim: int,
    hk: int,
    g: int,
    d: int,
    ps: int,
    mp: int,
    pool_pages: int,
    f_loc: int,
    theta: float,
    rms_eps: float,
    qk_eps,
    sm_scale: float,
    soft_cap: float,
    cfg: PersistentDecodeConfig,
    out_dtype,
    *refs,
    # inputs: table (B*mp,) SMEM; lens (B,) SMEM; x (B, K) ANY;
    # ln1_s (L, K); wqkv_s (L, K, (hk*g+2hk)*d); [qn_s/kn_s (L, d)];
    # wo_s (L, hk*g*d, K); ln2_s (L, K); gate_up_s (L, K, 2*f_loc);
    # down_s (L, f_loc, K); pool_k/pool_v (L*P*hk, ps, d) ANY (aliased).
    # outputs: x_out (B, K) ANY; pool_k/pool_v aliased ANY.
    # scratch: xa/xb/h_buf (B, K) HBM; qkv_hbm (B, qkv_cols) HBM;
    # attn_vm (B, hk*g*d) VMEM; attn_hbm same HBM; g/u/act (B, f_loc)
    # HBM; red_buf (n*B, cn) HBM; mm/recv/send (2, B, cn) HBM;
    # qrow (1, qkv_cols) / qn_vm / kn_vm (1, d) / ktok / vtok (1, d) /
    # kbuf / vbuf (2, ps, d) VMEM; stage (2,) / pg (2,2) / tok (2,) /
    # send (2,) / recv (2,) DMA sems; ack (2,) REGULAR; ag_send;
    # ag_recv (n,); acc_qkv / acc_ar / acc_up VMEM f32 accumulators
):
    refs = list(refs)
    table_ref, lens_ref, x_ref, ln1_s, wqkv_s = refs[:5]
    del refs[:5]
    if qk_eps is not None:
        qn_s, kn_s = refs[:2]
        del refs[:2]
    else:
        qn_s = kn_s = None
    (wo_s, ln2_s, gu_s, dn_s, _pk_in, _pv_in,
     x_out, pool_k, pool_v) = refs[:9]
    del refs[:9]
    (xa, xb, h_buf, qkv_hbm, attn_vm, attn_hbm, g_buf, u_buf, act_buf,
     red_buf, mm_buf, recv_buf, send_buf,
     qrow, qn_vm, kn_vm, ktok, vtok, kbuf, vbuf,
     stage_sems, pg_sems, tok_sems,
     send_sems, recv_sems, ack_sems, ag_send_sem, ag_recv_sems,
     acc_qkv, acc_ar, acc_up) = refs

    n = team.size
    h_loc = hk * g
    cn = k_dim // n
    qkv_cols = (h_loc + 2 * hk) * d
    bm = clip_block(cfg.bm, b)
    bk = clip_block(cfg.bk, k_dim)

    # hoisted pipelines: one geometry serves every layer (the blocks
    # factories stream their ANY-space operands through double-buffered
    # VMEM blocks — this IS the layer-weight streaming pipeline)
    rms_pipe = blocks.make_rmsnorm_pipeline(b, k_dim, bm, rms_eps,
                                            out_dtype)
    mm_qkv = blocks.make_matmul_pipeline(
        b, qkv_cols, k_dim, bm, clip_block(cfg.bn, qkv_cols), bk,
        out_dtype)
    mm_o = blocks.make_matmul_pipeline(
        b, cn, h_loc * d, bm, clip_block(cfg.bn, cn),
        clip_block(cfg.bk, h_loc * d), out_dtype)
    mm_up = blocks.make_matmul_pipeline(
        b, f_loc, k_dim, bm, clip_block(cfg.bf, f_loc), bk, out_dtype)
    sw_pipe = blocks.make_swiglu_pipeline(b, f_loc, bm,
                                          clip_block(cfg.bf, f_loc),
                                          out_dtype)
    mm_dn = blocks.make_matmul_pipeline(
        b, cn, f_loc, bm, clip_block(cfg.bn, cn),
        clip_block(cfg.bk, f_loc), out_dtype)
    add_cn = blocks.make_add_pipeline(b, cn, bm, clip_block(cfg.bn, cn))
    copy_out = blocks.make_copy_pipeline(b, k_dim, bm,
                                         clip_block(cfg.bn, k_dim))
    attn_stub = blocks._protocol_stub("attn_decode")

    dl.collective_prologue(team, neighbors_only=True)

    cur = x_ref
    for l in range(layers):
        nxt = xa if cur is not xa else xb
        # --- attention side ------------------------------------------------
        rms_pipe(cur, ln1_s.at[pl.ds(l, 1)], h_buf)
        mm_qkv(h_buf, wqkv_s.at[l], qkv_hbm, scratches=[acc_qkv])
        if attn_stub is not None:
            attn_stub(qkv_hbm, pool_k, pool_v, attn_vm)
        else:
            _attn_cell_real(l, b, hk, g, d, ps, mp, pool_pages, theta,
                            qk_eps, sm_scale, soft_cap, qkv_hbm, qn_s,
                            kn_s, table_ref, lens_ref, pool_k, pool_v,
                            attn_vm, qrow, qn_vm, kn_vm, ktok, vtok,
                            kbuf, vbuf, stage_sems, pg_sems, tok_sems)
        dl.local_copy(attn_vm, attn_hbm, stage_sems.at[0]).wait()

        # --- o-proj + chained AllReduce ring (instance 2l) -----------------
        _chained_ar(team, b, cn, mm_o, add_cn, attn_hbm,
                    lambda c, l=l: wo_s.at[l].at[:, pl.ds(c * cn, cn)],
                    red_buf, mm_buf, recv_buf, send_buf, send_sems,
                    recv_sems, ack_sems, ag_send_sem, ag_recv_sems,
                    acc_ar, armed=l > 0)
        for c in range(n):     # residual, un-chunked in place
            add_cn(cur.at[:, pl.ds(c * cn, cn)],
                   red_buf.at[pl.ds(c * b, b)],
                   nxt.at[:, pl.ds(c * cn, cn)])
        cur = nxt
        nxt = xa if cur is not xa else xb

        # --- MLP + chained AllReduce ring (instance 2l+1) ------------------
        rms_pipe(cur, ln2_s.at[pl.ds(l, 1)], h_buf)
        mm_up(h_buf, gu_s.at[l].at[:, pl.ds(0, f_loc)], g_buf,
              scratches=[acc_up])
        mm_up(h_buf, gu_s.at[l].at[:, pl.ds(f_loc, f_loc)], u_buf,
              scratches=[acc_up])
        sw_pipe(g_buf, u_buf, act_buf)
        _chained_ar(team, b, cn, mm_dn, add_cn, act_buf,
                    lambda c, l=l: dn_s.at[l].at[:, pl.ds(c * cn, cn)],
                    red_buf, mm_buf, recv_buf, send_buf, send_sems,
                    recv_sems, ack_sems, ag_send_sem, ag_recv_sems,
                    acc_ar, armed=True)
        for c in range(n):
            add_cn(cur.at[:, pl.ds(c * cn, cn)],
                   red_buf.at[pl.ds(c * b, b)],
                   nxt.at[:, pl.ds(c * cn, cn)])
        cur = nxt

    # the final instance's outstanding ACK credits (every earlier
    # instance's were consumed by its successor's armed waits)
    ring.rs_ack_drain(ack_sems, n)
    copy_out(cur, x_out)


# ---------------------------------------------------------------------------
# builder + entry


@functools.lru_cache(maxsize=None)
def _build_persistent_decode(
    mesh: Mesh,
    axis: str,
    layers: int,
    b: int,
    k_dim: int,
    hk_loc: int,
    g: int,
    d: int,
    pool_pages: int,
    ps: int,
    mp: int,
    theta: float,
    rms_eps: float,
    qk_eps,
    sm_scale: float,
    soft_cap: float,
    f_loc: int,
    dtype: jnp.dtype,
    pool_dtype: jnp.dtype,
    cfg: PersistentDecodeConfig,
):
    team = Team.of(mesh, axis)
    n = team.size
    compilation.verify_protocol("persistent_decode", n)
    h_loc = hk_loc * g
    cn = k_dim // n
    qkv_cols = (h_loc + 2 * hk_loc) * d
    pool_rows = layers * pool_pages * hk_loc

    from ..obs import costs

    kernel = functools.partial(
        _persistent_decode_kernel, team, layers, b, k_dim, hk_loc, g, d,
        ps, mp, pool_pages, f_loc, theta, rms_eps, qk_eps, sm_scale,
        soft_cap, cfg, dtype,
    )
    n_in = 11 + (2 if qk_eps is not None else 0) + 1  # + x
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),        # table
        pl.BlockSpec(memory_space=pltpu.SMEM),        # lens
    ] + [pl.BlockSpec(memory_space=pl.ANY)] * (n_in - 2)
    out_specs = [pl.BlockSpec(memory_space=pl.ANY)] * 3
    out_shape = [
        jax.ShapeDtypeStruct((b, k_dim), dtype),
        jax.ShapeDtypeStruct((pool_rows, ps, d), pool_dtype),
        jax.ShapeDtypeStruct((pool_rows, ps, d), pool_dtype),
    ]
    bm = clip_block(cfg.bm, b)
    scratch = [
        pltpu.HBM((b, k_dim), dtype),                 # xa
        pltpu.HBM((b, k_dim), dtype),                 # xb
        pltpu.HBM((b, k_dim), dtype),                 # h_buf
        pltpu.HBM((b, qkv_cols), dtype),              # qkv_hbm
        pltpu.VMEM((b, h_loc * d), dtype),            # attn_vm
        pltpu.HBM((b, h_loc * d), dtype),             # attn_hbm
        pltpu.HBM((b, f_loc), dtype),                 # g_buf
        pltpu.HBM((b, f_loc), dtype),                 # u_buf
        pltpu.HBM((b, f_loc), dtype),                 # act_buf
        pltpu.HBM((n * b, cn), dtype),                # red_buf
        pltpu.HBM((2, b, cn), dtype),                 # mm_buf
        pltpu.HBM((2, b, cn), dtype),                 # recv_buf
        pltpu.HBM((2, b, cn), dtype),                 # send_buf
        pltpu.VMEM((1, qkv_cols), dtype),             # qrow
        pltpu.VMEM((1, d), dtype),                    # qn_vm
        pltpu.VMEM((1, d), dtype),                    # kn_vm
        pltpu.VMEM((1, d), pool_dtype),               # ktok
        pltpu.VMEM((1, d), pool_dtype),               # vtok
        pltpu.VMEM((2, ps, d), pool_dtype),           # kbuf
        pltpu.VMEM((2, ps, d), pool_dtype),           # vbuf
        pltpu.SemaphoreType.DMA((2,)),                # stage_sems
        pltpu.SemaphoreType.DMA((2, 2)),              # pg_sems
        pltpu.SemaphoreType.DMA((2,)),                # tok_sems
        pltpu.SemaphoreType.DMA((2,)),                # send_sems
        pltpu.SemaphoreType.DMA((2,)),                # recv_sems
        pltpu.SemaphoreType.REGULAR((2,)),            # ack_sems
        pltpu.SemaphoreType.DMA(()),                  # ag_send_sem
        pltpu.SemaphoreType.DMA((n,)),                # ag_recv_sems
        pltpu.VMEM((bm, clip_block(cfg.bn, qkv_cols)), jnp.float32),
        pltpu.VMEM((bm, clip_block(cfg.bn, cn)), jnp.float32),
        pltpu.VMEM((bm, clip_block(cfg.bf, f_loc)), jnp.float32),
    ]
    call = pl.pallas_call(
        kernel,
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        # the stacked pools travel in place: each layer's token append
        # touches one (1, d) slot of the aliased buffers — no per-step
        # pool rebuild ever materializes on this path
        input_output_aliases={n_in - 2: 1, n_in - 1: 2},
        scratch_shapes=scratch,
        cost_estimate=costs.pallas_cost(
            costs.persistent_decode(layers, b, k_dim, h_loc, hk_loc,
                                    mp * ps, d, f_loc, n, pool_dtype)),
        compiler_params=compilation.compiler_params(
            collective=True,
            collective_id=compilation.collective_id("persistent_decode"),
            vmem_limit_bytes=cfg.vmem_limit,
        ),
        interpret=compilation.interpret_mode(),
    )

    has_qk = qk_eps is not None

    def local(table, lens, x, ln1, wqkv, *rest):
        if has_qk:
            qn, kn, wo, ln2, gu, dn, pk, pv = rest
        else:
            wo, ln2, gu, dn, pk, pv = rest
        args = [table.astype(jnp.int32).reshape(b * mp),
                lens.astype(jnp.int32), x, ln1, wqkv]
        if has_qk:
            args += [qn, kn]
        args += [wo, ln2, gu, dn,
                 pk.reshape(pool_rows, ps, d),
                 pv.reshape(pool_rows, ps, d)]
        xo, pk2, pv2 = call(*args)
        shape5 = (layers, pool_pages, hk_loc, ps, d)
        return xo, pk2.reshape(shape5), pv2.reshape(shape5)

    in_p = [P(None, None), P(None), P(None, None), P(None, None),
            P(None, None, axis)]
    if has_qk:
        in_p += [P(None, None), P(None, None)]
    in_p += [P(None, axis, None), P(None, None),
             P(None, None, axis), P(None, axis, None),
             P(None, None, axis, None, None),
             P(None, None, axis, None, None)]
    pool_p = P(None, None, axis, None, None)
    return compilation.jit_shard_map(
        local, mesh, in_specs=tuple(in_p),
        out_specs=(P(None, None), pool_p, pool_p),
    )


def _heads_from_qkv_global(qkv: jax.Array, b: int, n: int, h: int,
                           hk: int, d: int):
    """Split a (B, (H+2Hk)*D) qkv row whose columns are rank-blocked
    ``[q_r | k_r | v_r]`` into rank-major global-head (B, H, D) /
    (B, Hk, D) / (B, Hk, D) — the decode-step (S=1) form of
    ``Qwen3._heads_from_qkv``."""
    hl, hkl = h // n, hk // n
    t = qkv.reshape(b, n, (hl + 2 * hkl) * d)
    q = t[..., :hl * d].reshape(b, n * hl, d)
    k = t[..., hl * d:(hl + hkl) * d].reshape(b, n * hkl, d)
    v = t[..., (hl + hkl) * d:].reshape(b, n * hkl, d)
    return q, k, v


def reference_decode_step(
    x: jax.Array,
    sp: StackedDecodeParams,
    pool_k: jax.Array,
    pool_v: jax.Array,
    block_table: jax.Array,
    seq_lens: jax.Array,
    n: int,
    *,
    rope_theta: float,
    rms_eps: float,
    qk_eps: float | None = None,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
):
    """Pure-XLA golden of one persistent decode step (all L layers, the
    hidden state returned pre-final-norm): the parity reference, the
    ``n == 1`` degenerate path, and the resilience ladder's degraded
    fallback (``resilience.fallbacks.xla_persistent_decode``).  ``n`` is
    the TP width the rank-blocked weight layouts were built for.
    Returns ``(x_out, pool_k, pool_v)`` with the token appended at each
    sequence's position."""
    from ..layers.norm import rms_norm

    layers, pages, hk, ps, d = pool_k.shape
    b, k_dim = x.shape
    qkv_cols = sp.wqkv.shape[2]
    h = qkv_cols // d - 2 * hk
    f_dim = sp.down.shape[1]
    mp = block_table.shape[1]
    max_len = mp * ps
    sm = float(sm_scale) if sm_scale is not None else d ** -0.5
    lens = seq_lens.astype(jnp.int32)
    rep = h // hk

    for l in range(layers):
        hN = rms_norm(x, sp.ln1[l], rms_eps)
        qkv = jnp.dot(hN, sp.wqkv[l],
                      preferred_element_type=jnp.float32).astype(x.dtype)
        q, k, v = _heads_from_qkv_global(qkv, b, n, h, hk, d)
        if qk_eps is not None:
            q = rms_norm(q, sp.q_norm[l], qk_eps)
            k = rms_norm(k, sp.k_norm[l], qk_eps)
        pos = lens[:, None, None]
        q = apply_rope_at(q[:, :, None, :], pos, theta=rope_theta)[:, :, 0]
        k = apply_rope_at(k[:, :, None, :], pos, theta=rope_theta)[:, :, 0]
        # ragged append into layer l's pool
        pages_b = jnp.take_along_axis(
            block_table, (lens // ps)[:, None], axis=1)[:, 0]
        offs = lens % ps
        pool_k = pool_k.at[l, pages_b, :, offs].set(
            k.astype(pool_k.dtype))
        pool_v = pool_v.at[l, pages_b, :, offs].set(
            v.astype(pool_v.dtype))
        # attend over [0, pos] through the block table (token included)
        kc = pool_k[l][block_table]          # (B, mp, Hk, ps, D)
        vc = pool_v[l][block_table]
        kc = kc.transpose(0, 2, 1, 3, 4).reshape(b, hk, max_len, d)
        vc = vc.transpose(0, 2, 1, 3, 4).reshape(b, hk, max_len, d)
        kc = jnp.repeat(kc, rep, axis=1).astype(jnp.float32)
        vc = jnp.repeat(vc, rep, axis=1).astype(jnp.float32)
        scores = jnp.einsum("bhd,bhkd->bhk", q.astype(jnp.float32),
                            kc) * sm
        if soft_cap > 0.0:
            scores = soft_cap * jnp.tanh(scores / soft_cap)
        mask = jnp.arange(max_len, dtype=jnp.int32)[None, :] <= \
            lens[:, None]
        scores = jnp.where(mask[:, None, :], scores, -jnp.inf)
        probs = jax.nn.softmax(scores, axis=-1)
        attn = jnp.einsum("bhk,bhkd->bhd", probs, vc).astype(x.dtype)
        x = x + jnp.dot(attn.reshape(b, h * d), sp.wo[l],
                        preferred_element_type=jnp.float32).astype(x.dtype)
        # dense MLP, rank-blocked [gate_r | up_r] feature layout
        h2 = rms_norm(x, sp.ln2[l], rms_eps)
        fused = jnp.dot(h2, sp.gate_up[l],
                        preferred_element_type=jnp.float32).astype(x.dtype)
        t = fused.reshape(b, n, 2, f_dim // n)
        act = (jax.nn.silu(t[..., 0, :].astype(jnp.float32))
               * t[..., 1, :].astype(jnp.float32)).astype(x.dtype)
        x = x + jnp.dot(act.reshape(b, f_dim), sp.down[l],
                        preferred_element_type=jnp.float32).astype(x.dtype)
    return x, pool_k, pool_v


def persistent_decode_step(
    x: jax.Array,
    sp: StackedDecodeParams,
    pool_k: jax.Array,
    pool_v: jax.Array,
    block_table: jax.Array,
    seq_lens: jax.Array,
    mesh: Mesh,
    axis: str = TP_AXIS,
    *,
    rope_theta: float = 10_000.0,
    rms_eps: float = 1e-6,
    qk_eps: float | None = None,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
    config: PersistentDecodeConfig | None = None,
):
    """ONE decode step through ALL L layers as a single persistent
    collective kernel (module docstring).  ``x``: (B, K) embedded
    tokens; ``sp``: stacked layer weights; ``pool_k``/``pool_v``:
    (L, P, Hk, ps, D) full-precision page pools (aliased in place);
    ``block_table``: (B, max_pages); ``seq_lens``: (B,).  Returns
    ``(x_out, pool_k, pool_v)`` with ``x_out`` the post-layer-stack
    hidden state (final norm + lm_head stay in the step bundle — see
    :func:`decode_bundle`) and the token appended at each sequence's
    position.  ``n == 1`` runs :func:`reference_decode_step` (no
    collective exists to fuse)."""
    n = mesh.shape[axis]
    layers, pages, hk, ps, d = pool_k.shape
    b, k_dim = x.shape
    if pool_v.shape != pool_k.shape:
        raise ValueError(
            f"pool shape mismatch: {pool_k.shape} vs {pool_v.shape}")
    if jnp.dtype(pool_k.dtype) == jnp.int8:
        raise NotImplementedError(
            "persistent decode needs full-precision pools (the in-kernel "
            "append cannot re-encode a page's int8 scale); int8-KV "
            "deployments keep decode_mode='fused'")
    qkv_cols = sp.wqkv.shape[2]
    h = qkv_cols // d - 2 * hk
    f_dim = sp.down.shape[1]
    mp = block_table.shape[1]
    if block_table.shape[0] != b or seq_lens.shape != (b,):
        raise ValueError(
            f"block_table {block_table.shape} / seq_lens {seq_lens.shape} "
            f"inconsistent with B={b}")
    if h < 1 or h % hk:
        raise ValueError(
            f"wqkv {sp.wqkv.shape} does not hold [q|k|v] for {hk} kv "
            f"heads at head_dim {d}")
    sm = float(sm_scale) if sm_scale is not None else d ** -0.5
    eps = None if qk_eps is None else float(qk_eps)
    if n == 1:
        return reference_decode_step(
            x, sp, pool_k, pool_v, block_table, seq_lens, 1,
            rope_theta=rope_theta, rms_eps=rms_eps, qk_eps=eps,
            sm_scale=sm, soft_cap=soft_cap)
    if k_dim % n or f_dim % n or hk % n or h % n:
        raise ValueError(
            f"hidden={k_dim}, intermediate={f_dim}, heads={h}, "
            f"kv_heads={hk} must all divide by {axis}={n}")

    from ..tune import autotuner as _tune

    if config is None:
        def thunk(c):
            return lambda: persistent_decode_step(
                x, sp, pool_k, pool_v, block_table, seq_lens, mesh, axis,
                rope_theta=rope_theta, rms_eps=rms_eps, qk_eps=qk_eps,
                sm_scale=sm, soft_cap=soft_cap, config=c)

        config = _tune.resolve_config(
            "persistent_decode",
            persistent_config_key(layers, b, k_dim, f_dim, hk, ps, mp, d,
                                  n, x.dtype),
            persistent_candidates_pruned(layers, b, k_dim, f_dim, h, hk,
                                         ps, d, n, x.dtype),
            PersistentDecodeConfig(),
            thunk,
            tracing=any(map(_tune.is_tracer, (x, pool_k, seq_lens))),
        )
    cfg = config

    def run():
        fn = _build_persistent_decode(
            mesh, axis, layers, b, k_dim, hk // n, (h // n) // (hk // n),
            d, pages, ps, mp, float(rope_theta), float(rms_eps), eps, sm,
            float(soft_cap), f_dim // n, jnp.dtype(x.dtype),
            jnp.dtype(pool_k.dtype), cfg,
        )
        args = [block_table, seq_lens, x, sp.ln1, sp.wqkv]
        if eps is not None:
            args += [sp.q_norm, sp.k_norm]
        args += [sp.wo, sp.ln2, sp.gate_up, sp.down, pool_k, pool_v]
        return fn(*args)

    from .. import resilience

    eager = not _tune.is_tracer(x)
    if eager and resilience.enabled():
        itemsize = jnp.dtype(x.dtype).itemsize
        return resilience.guarded(
            "persistent_decode", run,
            family="persistent_decode", ranks=n,
            # 2L chained reductions, each wiring a (B, K) payload
            payload_bytes=2 * layers * b * k_dim * itemsize,
            fallback=lambda: resilience.fallbacks.xla_persistent_decode(
                x, sp, pool_k, pool_v, block_table, seq_lens, mesh, axis,
                rope_theta=rope_theta, rms_eps=rms_eps, qk_eps=eps,
                sm_scale=sm, soft_cap=soft_cap),
        )()
    return run()


def persistent_config_key(layers: int, b: int, k_dim: int, f_dim: int,
                          hk: int, ps: int, mp: int, d: int, n: int,
                          dtype) -> tuple:
    """The ONE autotuner cache key of the persistent kernel — shared by
    the transparent ``config=None`` resolve,
    ``tune.fresh_tune_persistent_decode``, and the
    ``serve.EngineBackend`` construction-time hoist, so a bench/warmup
    crown reaches the serving path without any per-dispatch consult."""
    from ..core import platform

    return (layers, b, k_dim, f_dim, hk, ps, mp, d, n, str(dtype),
            platform.device_kind())


# ---------------------------------------------------------------------------
# the step bundle: N decode steps per dispatch


def decode_bundle(step, cache_state, tokens: jax.Array, steps: int):
    """Run ``steps`` greedy decode steps inside ONE traced dispatch.

    ``step(cache_state, tokens) -> (logits, cache_state)`` is one decode
    step (the persistent megakernel step, or any ``Qwen3.decode``-shaped
    chain); the bundle scans it with the argmax token fed back on
    device, so the host sees a single dispatch per N tokens.  Returns
    ``(tokens (steps, B), cache_state)``.  ``lax.scan`` (not a Python
    loop) keeps the traced body ONE copy of the step — the static
    dispatch counter (:func:`count_bundle_dispatches`) charges the
    bundle the step's own launches plus nothing: the scan harness adds
    zero dispatch-shaped equations."""
    def body(carry, _):
        cache, tok = carry
        logits, cache = step(cache, tok)
        nxt = jnp.argmax(logits, axis=-1).astype(jnp.int32)
        return (cache, nxt), nxt

    (cache_state, _), toks = jax.lax.scan(
        body, (cache_state, tokens.astype(jnp.int32)), None,
        length=int(steps))
    return toks, cache_state


def count_bundle_dispatches(model, params, cache, tokens,
                            steps: int) -> int:
    """Static dispatch count of one ``model.decode_multi`` step bundle
    (the metric ``bench.py decode`` records as
    ``decode_dispatches_per_bundle``): scan bodies count ONCE, so this
    is dispatches per *bundle*, the number the persistent kernel exists
    to pin at <= 2 (megakernel + lm_head)."""
    from .fused_decode import count_jaxpr_dispatches

    return count_jaxpr_dispatches(
        lambda p, c, t: model.decode_multi(p, c, t, steps),
        params, cache, tokens)
