"""Overlapped compute kernels (reference: the compute half of
``python/triton_dist/kernels/nvidia/`` — AG-GEMM, GEMM-RS, MoE group-GEMM,
distributed flash-decode, SP attention)."""

from .ag_gemm import AgGemmConfig, ag_gemm
from .gemm_ar import GemmArConfig, gemm_ar
from .gemm_rs import GemmRsConfig, gemm_rs
