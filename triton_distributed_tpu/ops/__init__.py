"""Overlapped compute kernels (reference: the compute half of
``python/triton_dist/kernels/nvidia/`` — AG-GEMM, GEMM-RS, MoE group-GEMM,
distributed flash-decode, SP attention)."""

from .ag_gemm import AgGemmConfig, ag_gemm
from .attention import (
    decode_attention,
    decode_attention_state,
    finalize_attention_state,
    flash_attention,
    flash_attention_chunk,
    init_attention_state,
    merge_decode_states,
    paged_decode_attention,
    paged_decode_attention_state,
)
from .flash_decode import sp_flash_decode, sp_paged_flash_decode
from .fused_decode import (
    FusedAttnConfig,
    FusedMlpConfig,
    count_decode_dispatches,
    fused_attn_decode,
    fused_linear_ar,
    fused_mlp_ar,
)
from .gemm_ar import GemmArConfig, gemm_ar
from .gemm_rs import GemmRsConfig, gemm_rs
from .persistent_decode import (
    PersistentDecodeConfig,
    StackedDecodeParams,
    count_bundle_dispatches,
    decode_bundle,
    persistent_decode_step,
)
from .group_gemm import (
    GroupGemmConfig,
    ag_group_gemm,
    group_gemm,
    grouped_matmul,
    moe_reduce_rs,
)
from .moe_utils import (
    dequantize,
    expert_block_permutation,
    flatten_topk,
    global_presort_index,
    quantize_e4m3,
    sort_by_expert,
    topk_route,
    unsort_combine,
)
from .rope import apply_rope, apply_rope_at, rope_freqs
from .sp_attention import hierarchical_sp_attention, sp_attention
from .swizzle import GroupedSchedule, grouped_tile_schedule, ring_chunk_order
