"""Overlapped compute kernels (reference: the compute half of
``python/triton_dist/kernels/nvidia/`` — AG-GEMM, GEMM-RS, MoE group-GEMM,
distributed flash-decode, SP attention)."""

from .ag_gemm import AgGemmConfig, ag_gemm
from .attention import (
    decode_attention,
    decode_attention_state,
    flash_attention,
    merge_decode_states,
)
from .gemm_ar import GemmArConfig, gemm_ar
from .gemm_rs import GemmRsConfig, gemm_rs
from .rope import apply_rope, apply_rope_at, rope_freqs
