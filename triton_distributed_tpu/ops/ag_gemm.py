"""Fused AllGather-GEMM: tile-granular communication/compute overlap.

The canonical op of the framework (reference:
``python/triton_dist/kernels/nvidia/allgather_gemm.py`` — producer AG +
consumer persistent GEMM that ``dl.wait``s per-rank readiness flags before
consuming each rank's tiles, rank-swizzled so the local chunk is computed
first, ``allgather_gemm.py:146-215``; host entry ``ag_gemm:534``, context
``AllGatherGEMMTensorParallelContext:405``).

TPU design — ONE Pallas kernel per device instead of producer stream +
consumer kernel:

- the ring AG is issued as async remote DMA *inside* the kernel: each step
  forwards the chunk received last step to the right neighbor, so the ICI
  transfer of chunk s+1 rides under the MXU matmul of chunk s;
- per-chunk DMA recv semaphores play the role of the reference's readiness
  flags (``ready_ptr`` spin-waits);
- the consumer is an inner ``emit_pipeline`` blocked matmul (VMEM
  double-buffered by the pipeline emitter) — the Pallas analogue of the
  reference's persistent tile loop;
- chunk consumption order is the ring arrival order starting with the local
  shard — the same "self first, then by arrival distance" swizzle as
  ``allgather_gemm.py:205-215``.

Computes ``C[M, N_loc] = AllGather(A_shard)[M, K] @ B_loc[K, N_loc]`` — the
column-parallel half of a TP layer.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..comm import ring
from ..core import compilation
from ..core.mesh import TP_AXIS
from ..core.utils import clip_block
from ..lang import primitives as dl
from ..lang.primitives import Team
from . import blocks
from .swizzle import ring_chunk_order


@dataclasses.dataclass(frozen=True)
class AgGemmConfig:
    """Tile sizes for the consumer matmul (the autotuner's knobs — reference
    tunes BLOCK_SIZE_M/N/K + num_stages via ``@triton.autotune``)."""

    bm: int = 1024
    bn: int = 1024
    bk: int = 512

    def clip(self, m_loc: int, k: int, n_loc: int) -> "AgGemmConfig":
        return AgGemmConfig(
            bm=clip_block(self.bm, m_loc), bn=clip_block(self.bn, n_loc),
            bk=clip_block(self.bk, k),
        )


def _ag_gemm_kernel(
    team: Team,
    m_loc: int,
    k_dim: int,
    n_loc: int,
    cfg: AgGemmConfig,
    out_dtype,
    a_ref,      # (m_loc, k)   local A shard             [ANY]
    b_ref,      # (k, n_loc)   local B (column) shard    [ANY]
    ag_ref,     # (n*m_loc, k) gathered-A workspace      [ANY, output]
    c_ref,      # (n*m_loc, n_loc) C output              [ANY, output]
    local_sem,
    send_sem,
    recv_sems,  # per-chunk arrival gates (== reference ready flags)
    acc_ref,    # (bm, bn) f32 accumulator               [VMEM scratch]
):
    me, n = team.rank(), team.size
    _, right = team.neighbor_ranks()
    right_id = team.device_id(right)

    pipeline = blocks.make_matmul_pipeline(
        m_loc, n_loc, k_dim, cfg.bm, cfg.bn, cfg.bk, out_dtype
    )

    def chunk_rows(ref, r):
        return ref.at[pl.ds(r * m_loc, m_loc)]

    local = dl.local_copy(a_ref, chunk_rows(ag_ref, me), local_sem)
    dl.collective_prologue(team, neighbors_only=True)
    local.wait()

    for s in range(n):
        r = ring_chunk_order(me, n, s)
        if s > 0:
            # arrival gate for chunk r (reference: dl.wait on ready flags)
            dl.wait_recv(chunk_rows(ag_ref, r), recv_sems.at[r])
        if s < n - 1 and n > 1:
            # forward on the ring BEFORE computing, so the transfer of the
            # next chunk rides under this chunk's matmul
            dl.remote_copy(
                chunk_rows(ag_ref, r),
                chunk_rows(ag_ref, r),
                send_sem,
                recv_sems.at[r],
                right_id,
            )
        pipeline(
            chunk_rows(ag_ref, r),
            b_ref,
            chunk_rows(c_ref, r),
            scratches=[acc_ref],
        )

    for s in range(n - 1):
        dl.wait_send(chunk_rows(ag_ref, me), send_sem)


def _ag_gemm_bidir_kernel(
    team: Team,
    m_loc: int,
    k_dim: int,
    n_loc: int,
    cfg: AgGemmConfig,
    out_dtype,
    a_ref,
    b_ref,
    ag_ref,
    c_ref,
    local_sem,
    send_sems,  # (2,) clockwise / counter-clockwise
    recv_sems,
    acc_ref,
):
    """Bidirectional-ring variant: both ICI directions carry chunks (the
    fused analogue of ``comm/allgather._ag_ring_bidir_kernel``; the
    reference's NUMA-aware 2D ring plays this role on NVLink).  The shared
    ``ring.bidir_ring_phase`` forwards every arrival BEFORE its matmul, so
    the next transfer in each direction rides under the current chunk's
    compute; consumption order is arrival order: me, me-1, me+1, ..."""
    me, n = team.rank(), team.size

    pipeline = blocks.make_matmul_pipeline(
        m_loc, n_loc, k_dim, cfg.bm, cfg.bn, cfg.bk, out_dtype
    )

    def chunk_rows(ref, r):
        return ref.at[pl.ds(r * m_loc, m_loc)]

    def consume(r):
        pipeline(chunk_rows(ag_ref, r), b_ref, chunk_rows(c_ref, r),
                 scratches=[acc_ref])

    local = dl.local_copy(a_ref, chunk_rows(ag_ref, me), local_sem)
    dl.collective_prologue(team, neighbors_only=True)
    local.wait()
    ring.bidir_ring_phase(team, ag_ref, m_loc, send_sems, recv_sems,
                          consume=consume)
    ring.bidir_ring_drain(team, ag_ref, m_loc, send_sems)


@functools.lru_cache(maxsize=None)
def _build_ag_gemm(
    mesh: Mesh,
    axis: str,
    m_loc: int,
    k_dim: int,
    n_loc: int,
    dtype: jnp.dtype,
    out_dtype: jnp.dtype,
    cfg: AgGemmConfig,
    bidir: bool,
):
    team = Team.of(mesh, axis)
    n = team.size
    compilation.verify_protocol("ag_gemm", n)

    from ..obs import costs

    kern = _ag_gemm_bidir_kernel if bidir else _ag_gemm_kernel
    kernel = functools.partial(
        kern, team, m_loc, k_dim, n_loc, cfg, out_dtype
    )
    call = pl.pallas_call(
        kernel,
        # kernel cost attribution (reference launch_metadata): the same
        # flop/byte source the SOL model and flight timeline read
        cost_estimate=costs.pallas_cost(
            costs.ag_gemm(m_loc, k_dim, n_loc, n, dtype, out_dtype)),
        out_shape=(
            jax.ShapeDtypeStruct((n * m_loc, k_dim), dtype),       # gathered A
            jax.ShapeDtypeStruct((n * m_loc, n_loc), out_dtype),   # C
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ),
        scratch_shapes=[
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2,)) if bidir
            else pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((n,)),
            pltpu.VMEM((cfg.bm, cfg.bn), jnp.float32),
        ],
        compiler_params=compilation.compiler_params(
            collective=True,
            collective_id=compilation.collective_id("ag_gemm"),
        ),
        interpret=compilation.interpret_mode(),
    )

    return compilation.jit_shard_map(
        call, mesh,
        in_specs=(P(axis, None), P(None, axis)),
        out_specs=(P(), P(None, axis)),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3, 4))
def _ag_gemm_core(mesh, axis, cfg, bidir, out_dtype, a, b):
    """Differentiable n>1 core (C only).  The VJP is the TP adjoint
    duality: d/dA rides the *other* fused op (``gemm_rs``) and d/dB a
    plain AllGather + local GEMM — so the backward pass overlaps its
    collectives exactly like the forward (the training-step property the
    reference leaves implicit in its torch autograd fallback)."""
    n = mesh.shape[axis]
    fn = _build_ag_gemm(
        mesh, axis, a.shape[0] // n, a.shape[1], b.shape[1] // n,
        jnp.dtype(a.dtype), out_dtype, cfg, bidir,
    )
    _, c = fn(a, b)
    return c


def _ag_gemm_fwd(mesh, axis, cfg, bidir, out_dtype, a, b):
    return _ag_gemm_core(mesh, axis, cfg, bidir, out_dtype, a, b), (a, b)


def _ag_gemm_bwd(mesh, axis, cfg, bidir, out_dtype, res, dc):
    from ..comm.allgather import all_gather
    from .gemm_rs import gemm_rs

    a, b = res
    # dA = dC @ B^T: (M, N)x(N, K) with N contracted over ranks -> the
    # adjoint of the AllGather is a ReduceScatter: the other fused op
    da = gemm_rs(dc, b.T, mesh, axis, out_dtype=a.dtype)
    # dB = A^T @ dC: gather A once, local GEMM per N-shard
    ag_a = all_gather(a, mesh, axis)

    def local(ag, dcr):
        return jnp.dot(ag.T, dcr,
                       preferred_element_type=jnp.float32).astype(b.dtype)

    db = compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(None, None), P(None, axis)),
        out_specs=P(None, axis),
    )(ag_a, dc)
    return da, db


_ag_gemm_core.defvjp(_ag_gemm_fwd, _ag_gemm_bwd)


def ag_gemm(
    a: jax.Array,
    b: jax.Array,
    mesh: Mesh,
    axis: str = TP_AXIS,
    *,
    config: AgGemmConfig | None = None,
    out_dtype=None,
    return_gathered: bool = False,
    bidir: bool | None = None,
    wire_dtype: str = "bf16",
):
    """Overlapped ``AllGather(a) @ b`` (reference host entry ``ag_gemm:534``).

    ``a``: (M, K) sharded on dim 0 over ``axis`` (the activations).
    ``b``: (K, N) sharded on dim 1 over ``axis`` (column-parallel weight).
    Returns C = (M, N) sharded on dim 1; with ``return_gathered`` also the
    replicated gathered A (the reference keeps it in ctx workspace for reuse,
    e.g. by the attention layer).

    ``bidir`` selects the two-direction ring (default for n >= 3: both ICI
    directions carry chunks, halving the longest path; at n == 2 the single
    transfer makes the streams identical).

    ``wire_dtype``: "int8"/"fp8" ships the A shards quantized
    (``comm.quantized.quantized_all_gather`` — producer-packed payload +
    scale sidecar, consumer dequant) feeding the local GEMM: half the
    wire bytes against the fused ring's compute overlap, a trade the
    "auto" setting resolves through the contextual tuner per
    shape/ranks/wire class.
    """
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(a.dtype)
    n = mesh.shape[axis]

    m_tot, k_dim = a.shape
    k2, n_tot = b.shape
    if k2 != k_dim:
        raise ValueError(f"inner dims mismatch: {a.shape} @ {b.shape}")
    if m_tot % n or n_tot % n:
        raise ValueError(
            f"M={m_tot} and N={n_tot} must be divisible by {axis}={n}"
        )

    if n == 1:
        c = jnp.dot(a, b, preferred_element_type=jnp.float32).astype(out_dtype)
        return (c, a) if return_gathered else c

    if wire_dtype != "bf16":
        from ..comm import quantized as _q
        from ..tune.autotuner import is_tracer as _q_is_tracer

        if wire_dtype == "auto":
            wire_dtype = _q.resolve_wire_dtype(
                "ag_gemm_wire", (m_tot, k_dim, n_tot, str(a.dtype)),
                mesh, axis,
                lambda wd: (lambda: ag_gemm(
                    a, b, mesh, axis, config=config, out_dtype=out_dtype,
                    return_gathered=return_gathered, bidir=bidir,
                    wire_dtype=wd)),
                tracing=_q_is_tracer(a),
            )
        if wire_dtype != "bf16":
            gathered = _q.quantized_all_gather(
                a, mesh, axis, wire_dtype=wire_dtype)
            c = jnp.dot(gathered, b,
                        preferred_element_type=jnp.float32).astype(out_dtype)
            return (c, gathered) if return_gathered else c

    if config is None:
        # transparent contextual tuning: cached per-shape winner, measured
        # on first eager real-hardware call, static default otherwise
        from ..tune import autotuner as _tune

        kw = dict(out_dtype=out_dtype, return_gathered=return_gathered,
                  bidir=bidir)
        config = _tune.resolve_gemm_like(
            "ag_gemm", ag_gemm, AgGemmConfig, _tune.AG_GEMM_CAND_DIMS,
            AgGemmConfig(), a, b, mesh, axis, kw,
            _tune.ag_gemm_key_kw(n, kw),
        )
    cfg = config

    if bidir is None:
        bidir = n >= 3
    # clip BEFORE the cache lookup so configs that normalize to the same
    # effective tiles share one compiled kernel
    cfg = cfg.clip(m_tot // n, k_dim, n_tot // n)
    if return_gathered:
        # workspace-reuse path (e.g. the attention layer): not wired for
        # autodiff — the gathered output has no defined cotangent
        fn = _build_ag_gemm(
            mesh, axis, m_tot // n, k_dim, n_tot // n,
            jnp.dtype(a.dtype), out_dtype, cfg, bool(bidir),
        )
        gathered, c = fn(a, b)
        return c, gathered
    from .. import resilience
    from ..tune.autotuner import is_tracer

    core = lambda: _ag_gemm_core(mesh, axis, cfg, bool(bidir),  # noqa: E731
                                 out_dtype, a, b)
    eager = not is_tracer(a)
    if eager and resilience.integrity.enabled():
        # consumer-side Freivalds verification (TDT_INTEGRITY=1): a
        # corrupt chunk raises PayloadCorruption and rides the ladder
        core = resilience.integrity.checked(
            "ag_gemm", core, ranks=n,
            verify=lambda out: resilience.integrity.verify_gemm(
                "ag_gemm", a, b, out))
    if eager and resilience.enabled():
        # eager calls only (see comm/allgather.py): ride the failure
        # ladder — watchdog deadline from the AG wire estimate, degraded
        # fallback = unfused XLA AllGather + local GEMM
        return resilience.guarded(
            "ag_gemm", core,
            family="ag_gemm", ranks=n,
            payload_bytes=(m_tot // n) * k_dim * jnp.dtype(a.dtype).itemsize,
            fallback=lambda: resilience.fallbacks.xla_ag_gemm(
                a, b, mesh, axis, out_dtype),
        )()
    return core()
