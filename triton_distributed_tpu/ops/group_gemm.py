"""Grouped (per-expert) GEMM ops: local, AG-fused, and RS-fused MoE paths.

Reference:

- local scatter group-GEMM ``allgather_group_gemm.py:532`` (M-parallel
  Triton kernel over expert row groups);
- AG + group-GEMM ``allgather_group_gemm.py:398-605`` (tokens gathered
  over TP, scattered to expert order, group-GEMM against the local expert
  weight shard);
- group-GEMM + ReduceScatter ``moe_reduce_rs.py:486,605,816`` (down
  projection, top-k weighted reduce, RS over TP).

TPU design: the ragged per-expert matmul is XLA's native
``lax.ragged_dot`` — the hand-written Triton group GEMM collapses into it
the way the codegen layers collapse into Pallas/Mosaic (SURVEY.md
section 2.4); it tiles expert row groups onto the MXU with static shapes.
The communication halves remain this framework's Pallas collectives
(``comm.all_gather``, ``comm.reduce_scatter``), and the index plumbing is
``ops.moe_utils``.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..comm.allgather import all_gather
from ..comm.reduce_scatter import reduce_scatter
from ..core import compilation
from ..core.mesh import TP_AXIS
from .moe_utils import expert_block_permutation, unsort_combine


def group_gemm(x_sorted: jax.Array, w: jax.Array,
               splits: jax.Array) -> jax.Array:
    """Per-expert matmul of expert-sorted rows (reference local group GEMM
    ``allgather_group_gemm.py:532``).

    ``x_sorted``: (T, K) rows grouped by expert; ``w``: (E, K, N);
    ``splits``: (E,) int32 row counts (sum <= T; padding rows at the tail
    multiply expert E-1 garbage-free — their outputs are never gathered).
    Returns (T, N).
    """
    t, k = x_sorted.shape
    e, k2, n_dim = w.shape
    if k2 != k:
        raise ValueError(f"inner dims mismatch: {x_sorted.shape} @ {w.shape}")
    if splits.shape != (e,):
        raise ValueError(f"splits {splits.shape} != (E,) = ({e},)")
    return jax.lax.ragged_dot(x_sorted, w, splits.astype(jnp.int32))


def ag_group_gemm(
    x_sorted: jax.Array,
    w: jax.Array,
    splits: jax.Array,
    mesh: Mesh,
    axis: str = TP_AXIS,
):
    """AllGather tokens over ``axis``, merge to global expert order, and
    group-GEMM against the column-sharded expert weights (reference
    ``ag_group_gemm``, ``allgather_group_gemm.py:398-605``).

    ``x_sorted``: global (n*T, K) over ``axis`` — each rank's shard sorted
    by expert; ``splits``: global (n*E,) int32; ``w``: (E, K, N) with N
    sharded over ``axis`` (column-parallel expert weights).

    Returns ``(y, total_splits, perm)``: ``y`` (n*T, N) N-sharded rows in
    GLOBAL expert order; ``total_splits`` (E,) and ``perm`` (n*T,) for the
    downstream combine.
    """
    n = mesh.shape[axis]
    e = w.shape[0]
    if n == 1:
        return group_gemm(x_sorted, w, splits), splits, jnp.arange(
            x_sorted.shape[0]
        )
    gathered = all_gather(x_sorted, mesh, axis)          # (n*T, K) replicated
    perm, total_splits = expert_block_permutation(
        splits.reshape(n, e), x_sorted.shape[0] // n
    )
    x_glob = jnp.take(gathered, perm, axis=0)            # global expert order

    def local(xg, w_loc):
        return jax.lax.ragged_dot(xg, w_loc, total_splits)

    y = compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(None, None), P(None, None, axis)),
        out_specs=P(None, axis),
    )(x_glob, w)
    return y, total_splits, perm


def moe_reduce_rs(
    y_sorted: jax.Array,
    w: jax.Array,
    total_splits: jax.Array,
    presort_idx: jax.Array,
    weights: jax.Array,
    topk: int,
    mesh: Mesh,
    axis: str = TP_AXIS,
) -> jax.Array:
    """Down-project expert outputs, fold the top-k copies with their
    routing weights, and ReduceScatter the partial sums back to token
    owners (reference ``moe_reduce_rs.py:486-816``).

    ``y_sorted``: (n*T, N) N-sharded rows in global expert order (from
    :func:`ag_group_gemm`); ``w``: (E, N, K) with N sharded (row-parallel
    down weights); ``presort_idx``: (n*T,) from
    ``moe_utils.global_presort_index`` (global expert order -> original
    pre-sort row order); ``weights``: (n*T,) routing weights in pre-sort
    row order; ``topk``: routing copies per token.  Returns global
    (n*T//topk, K) token rows sharded over ``axis``.
    """
    n = mesh.shape[axis]

    def local(y_loc, w_loc):
        # partial down-projection (this rank's N slice -> partial sums)
        part = jax.lax.ragged_dot(y_loc, w_loc, total_splits)
        # back to pre-sort order, weighted top-k fold: (n*T//topk, K)
        return unsort_combine(part, presort_idx, weights, topk)

    # out_specs P(axis): rank r's partial becomes row-block r — exactly the
    # stacked-partials convention reduce_scatter consumes
    partials = compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(None, axis), P(None, axis, None)),
        out_specs=P(axis, None),
    )(y_sorted, w)
    if n == 1:
        return partials
    return reduce_scatter(partials, mesh, axis)
