"""Grouped (per-expert) GEMM ops: local, AG-fused, and RS-fused MoE paths.

Reference:

- local scatter group-GEMM ``allgather_group_gemm.py:532`` (M-parallel
  Triton kernel over expert row groups);
- AG + group-GEMM ``allgather_group_gemm.py:398-605`` (tokens gathered
  over TP, scattered to expert order, group-GEMM against the local expert
  weight shard);
- group-GEMM + ReduceScatter ``moe_reduce_rs.py:486,605,816`` (down
  projection, top-k weighted reduce, RS over TP).

TPU design: the ragged per-expert matmul is XLA's native
``lax.ragged_dot`` — the hand-written Triton group GEMM collapses into it
the way the codegen layers collapse into Pallas/Mosaic (SURVEY.md
section 2.4); it tiles expert row groups onto the MXU with static shapes.
The communication halves remain this framework's Pallas collectives
(``comm.all_gather``, ``comm.reduce_scatter``), and the index plumbing is
``ops.moe_utils``.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..comm.allgather import all_gather
from ..comm.reduce_scatter import reduce_scatter
from ..core import compilation
from ..core.mesh import TP_AXIS
from ..core.utils import clip_block
from .moe_utils import expert_block_permutation, unsort_combine
from .swizzle import grouped_tile_schedule


@dataclasses.dataclass(frozen=True)
class GroupGemmConfig:
    """Tile sizes for :func:`grouped_matmul`'s Pallas path (same knob set
    as the dense ``matmul``).

    Round-4 measured state (v5e, bench shape T=8192, E=8, 7168->2048
    bf16, interleaved medians): with PAD-SLOT ELISION in the kernel (pad
    slots' block fetches frozen so the pipeline skips their DMAs — they
    were ~30% of HBM traffic at this shape) the Pallas tilings run
    1.54-1.73 ms STABLY across chip states, while ``lax.ragged_dot``
    swings 1.74-3.57 ms with the chip's clock state.  Best tile
    512x2048x1024 under a raised VMEM budget: 145-156 TF/s, 1.06-2.3x of
    ragged_dot per interleaved round.  The ``config=None`` path still
    resolves a BACKEND per shape (XLA dispatch vs these tiles) so untuned
    shapes never lose to XLA; at tuned shapes the Pallas kernel is the
    expected winner."""

    bm: int = 256
    bn: int = 2048
    bk: int = 512
    # scoped-VMEM budget override (bytes): big-accumulator tiles (>= 4 MB
    # f32 acc) fail to compile under Mosaic's 16 MiB default; the v5e has
    # 128 MiB of VMEM, and larger bm is what cuts per-expert weight
    # re-streaming (weight traffic ~ (T/bm + E) * K * N bytes)
    vmem_limit: int | None = None


def _grouped_matmul_kernel(
    bm: int, nk: int, out_dtype,
    # scalar prefetch (swizzle.GroupedSchedule)
    tile_ids, group_ids, row_starts, row_ends, is_first, valid, covers,
    x_ref,      # (bm, bk) rows of the current m-tile
    w_ref,      # (bk, bn) current group's weight block (leading dim squeezed)
    o_ref,      # (bm, bn) output tile (revisited per overlapping group)
    acc_ref,    # (bm, bn) f32 scratch
):
    wi = pl.program_id(1)
    kk = pl.program_id(2)

    @pl.when(kk == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    # empty-row slots (pads, zero-fill tiles) skip the MXU work entirely
    @pl.when(row_starts[wi] < row_ends[wi])
    def _():
        acc_ref[...] += jnp.dot(
            x_ref[...], w_ref[...], preferred_element_type=jnp.float32
        )

    # PAD slots (valid == 0) write nothing at all — their block fetches
    # are frozen by the index maps and their output visit leaves the
    # already-written tile untouched.  Zero-fill slots (valid, empty row
    # range, is_first) still write zeros through the masked path.
    @pl.when((kk == nk - 1) & (valid[wi] == 1) & (covers[wi] == 1))
    def _():
        # the slot owns its whole tile (splits aligned to bm — the common
        # case): straight write, no row-mask arithmetic
        o_ref[...] = acc_ref[...].astype(out_dtype)

    @pl.when((kk == nk - 1) & (valid[wi] == 1) & (covers[wi] == 0))
    def _():
        # zero the rows of this tile that belong to other groups; their
        # slots contribute them, so the adds across slots stay exact
        row = tile_ids[wi] * bm + jax.lax.broadcasted_iota(
            jnp.int32, (bm, 1), 0
        )
        mask = (row >= row_starts[wi]) & (row < row_ends[wi])
        val = jnp.where(mask, acc_ref[...], 0.0).astype(out_dtype)

        @pl.when(is_first[wi] == 1)
        def _():
            o_ref[...] = val

        @pl.when(is_first[wi] == 0)
        def _():
            o_ref[...] = o_ref[...] + val


@functools.lru_cache(maxsize=None)
def _build_grouped_matmul(t, k, n_dim, e, bm, bn, bk, dtype, out_dtype,
                          vmem_limit=None):
    nt, nj, nk = t // bm, n_dim // bn, k // bk
    num_slots = nt + e
    # pad slots freeze their k index at 0 (and carry the last real slot's
    # tile/group ids — see GroupedSchedule): consecutive identical block
    # indices are elided by the pipeline, so pads cost no HBM traffic
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=7,
        grid=(nj, num_slots, nk),
        in_specs=[
            pl.BlockSpec(
                (bm, bk),
                lambda j, w, kk, tid, gid, rs, re, isf, val, cov:
                    (tid[w], kk * val[w]),
            ),
            pl.BlockSpec(
                (None, bk, bn),
                lambda j, w, kk, tid, gid, rs, re, isf, val, cov:
                    (gid[w], kk * val[w], j),
            ),
        ],
        out_specs=pl.BlockSpec(
            (bm, bn),
            lambda j, w, kk, tid, gid, rs, re, isf, val, cov: (tid[w], j),
        ),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
    )
    # bytes: x re-read per n-tile, w blocks once per slot, out written once
    cost = pl.CostEstimate(
        flops=2 * t * k * n_dim,
        bytes_accessed=(t * k * nj * jnp.dtype(dtype).itemsize
                        + num_slots * k * bn * jnp.dtype(dtype).itemsize
                        + t * n_dim * jnp.dtype(out_dtype).itemsize),
        transcendentals=0,
    )
    call = pl.pallas_call(
        functools.partial(_grouped_matmul_kernel, bm, nk, out_dtype),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((t, n_dim), out_dtype),
        cost_estimate=cost,
        compiler_params=compilation.compiler_params(
            collective=False,
            # slots revisit output blocks, so both w and k are sequential
            dimension_semantics=("parallel", "arbitrary", "arbitrary"),
            vmem_limit_bytes=vmem_limit,
        ),
        interpret=compilation.interpret_mode(),
    )
    return jax.jit(call)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1))
def _grouped_matmul_vjp(cfg: GroupGemmConfig, out_dtype, x_sorted, w,
                        splits):
    return _grouped_matmul_run(cfg, out_dtype, x_sorted, w, splits)


def _grouped_matmul_run(cfg, out_dtype, x_sorted, w, splits):
    t, k = x_sorted.shape
    e, _, n_dim = w.shape
    bm, bn, bk = (
        clip_block(cfg.bm, t), clip_block(cfg.bn, n_dim), clip_block(cfg.bk, k)
    )
    sched = grouped_tile_schedule(splits, t, bm)
    fn = _build_grouped_matmul(
        t, k, n_dim, e, bm, bn, bk, jnp.dtype(x_sorted.dtype), out_dtype,
        cfg.vmem_limit,
    )
    return fn(*sched, x_sorted, w)


@functools.lru_cache(maxsize=None)
def _jitted_pallas_entry(cfg, out_dtype):
    """One jitted wrapper per config: eager calls pay a single dispatch
    (the tile-schedule arithmetic traces inside) instead of one tunnel
    round-trip per scalar op of ``grouped_tile_schedule``."""
    return jax.jit(functools.partial(_grouped_matmul_vjp, cfg, out_dtype))


def _ragged_dot_body(x, w, s, out_dtype):
    """The ONE XLA ragged-dot emission both the jitted-with-options and
    the inlined-under-jit dispatch branches share, holding the same
    numeric contract as ``ops.matmul._xla_dot``: f32 operands get true
    f32 accumulation (TPU DEFAULT precision would run bf16 passes), and
    a widening ``out_dtype`` accumulates AT that dtype instead of
    rounding the natural-dtype result up."""
    in_dtype = jnp.result_type(x, w)
    prec = (jax.lax.Precision.HIGHEST
            if in_dtype == jnp.float32 else None)
    pet = out_dtype if jnp.promote_types(in_dtype, out_dtype) != in_dtype \
        else None
    return jax.lax.ragged_dot(
        x, w, s.astype(jnp.int32), precision=prec,
        preferred_element_type=pet,
    ).astype(out_dtype)


@functools.lru_cache(maxsize=None)
def _xla_ragged_fn(scoped_vmem_kib: int, out_dtype):
    """Jitted ``lax.ragged_dot`` carrying the XLA backend's compile
    options (``core.compilation.xla_gemm_options``)."""
    return jax.jit(
        functools.partial(_ragged_dot_body, out_dtype=out_dtype),
        compiler_options=compilation.xla_gemm_options(scoped_vmem_kib)
        or None,
    )


def _xla_grouped(x_sorted, w, splits, out_dtype, cfg):
    from ..tune.autotuner import is_tracer

    if is_tracer(x_sorted) or is_tracer(w) or is_tracer(splits):
        # inlined into an outer jit: options cannot attach there
        return _ragged_dot_body(x_sorted, w, splits, out_dtype)
    return _xla_ragged_fn(cfg.scoped_vmem_kib, out_dtype)(
        x_sorted, w, splits
    )


def _backend_candidates(t: int, k: int, n_dim: int) -> list:
    """Mixed backend sweep for the grouped matmul (see
    ``tune.autotuner.matmul_backend_candidates`` for the rationale):
    ragged_dot dispatch variants first, then the Pallas tilings."""
    from ..tune.autotuner import MATMUL_TILE_VL, xla_backend_candidates

    xla = xla_backend_candidates()
    # the three best-measured pad-eliding Pallas tilings (round-4 sweep at
    # the bench shape: 145-156 TF/s stable vs ragged_dot's 67-138 —
    # see GroupGemmConfig); raised VMEM budget (the shared big-tile
    # budget knob, tune.autotuner.MATMUL_TILE_VL) for the deep-k
    # variants.  Short list = cheap fresh tunes.
    tiles = [(512, 2048, 1024), (512, 2048, 512), (512, 1024, 512)]
    return xla + [GroupGemmConfig(bm, bn, bk, MATMUL_TILE_VL)
                  for bm, bn, bk in tiles
                  if bm <= t and bn <= n_dim and bk <= k]


def _grouped_resolve(x_sorted, w, splits, *, fresh: bool = False):
    """The shared backend resolution for ``grouped_matmul(config=None)``
    and ``tune.autotuner.fresh_tune_grouped_matmul`` (one cache entry).
    Splits are part of the measurement closure (contextual) but not the
    key — the winning backend is a shape-class property, not a routing
    property."""
    from ..core import platform
    from ..tune import autotuner as _tune

    t, k = x_sorted.shape
    e, _, n_dim = w.shape
    out_dtype = jnp.dtype(x_sorted.dtype)
    return _tune.resolve_config(
        "grouped_matmul",
        (t, k, n_dim, e, str(x_sorted.dtype), platform.device_kind()),
        _backend_candidates(t, k, n_dim),
        _tune.XlaBackend(),
        lambda c: (lambda: grouped_matmul(x_sorted, w, splits, config=c,
                                          out_dtype=out_dtype)),
        tracing=(_tune.is_tracer(x_sorted) or _tune.is_tracer(w)
                 or _tune.is_tracer(splits)),
        force_measure=fresh,
        fresh=fresh,
    )


def _gm_fwd(cfg, out_dtype, x_sorted, w, splits):
    return _grouped_matmul_vjp(cfg, out_dtype, x_sorted, w, splits), (
        x_sorted, w, splits
    )


def _gm_bwd(cfg, out_dtype, res, dy):
    # fast Pallas forward, XLA backward: ragged_dot computes the same
    # function, so its vjp supplies dx (grouped matmul against transposed
    # expert weights) and dw (the grouped outer product)
    import numpy as np

    x_sorted, w, splits = res
    # accumulate the backward matmuls at the wider of cotangent and input
    # dtype: an f32 dy over bf16 inputs keeps its precision (not truncated
    # at entry), and a bf16 dy over f32 inputs still accumulates in f32;
    # jax.vjp casts dx/dw back to the primal dtypes on the way out
    acc_dtype = jnp.promote_types(dy.dtype, x_sorted.dtype)
    _, vjp = jax.vjp(
        lambda x_, w_: jax.lax.ragged_dot(
            x_, w_, splits.astype(jnp.int32),
            preferred_element_type=acc_dtype,
        ),
        x_sorted, w,
    )
    dx, dw = vjp(dy.astype(acc_dtype))
    d_splits = np.zeros(splits.shape, dtype=jax.dtypes.float0)
    return dx, dw, d_splits


_grouped_matmul_vjp.defvjp(_gm_fwd, _gm_bwd)


def grouped_matmul(
    x_sorted: jax.Array,
    w: jax.Array,
    splits: jax.Array,
    *,
    config: GroupGemmConfig | None = None,
    out_dtype=None,
) -> jax.Array:
    """Tile-scheduled Pallas grouped matmul: (T, K) x (E, K, N) -> (T, N).

    The kernel half of the reference's aligned group GEMM
    (``allgather_group_gemm.py:532`` consuming
    ``moe_ag_scatter_align_block_size``'s block schedule): m-tiles are
    enumerated by ``swizzle.grouped_tile_schedule`` into (tile, group) work
    units delivered through scalar prefetch — the expert id picks the
    weight block via the BlockSpec index map, boundary tiles are visited
    once per overlapping group with other groups' rows masked, and rows
    past ``sum(splits)`` come back zero-filled.  Where the reference pads
    and physically reorders token ids so each CUDA block is single-expert,
    the TPU kernel masks in VMEM and never copies ``x``.
    """
    t, k = x_sorted.shape
    e, k2, n_dim = w.shape
    if k2 != k:
        raise ValueError(f"inner dims mismatch: {x_sorted.shape} @ {w.shape}")
    if splits.shape != (e,):
        raise ValueError(f"splits {splits.shape} != (E,) = ({e},)")
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(
        x_sorted.dtype
    )
    if config is None:
        # transparent contextual BACKEND tuning (see ops/ag_gemm.py and
        # _grouped_resolve): XLA ragged_dot dispatch variants vs the
        # Pallas tile-scheduled kernel, crowned per shape class
        config = _grouped_resolve(x_sorted, w, splits)
    from ..tune.autotuner import XlaBackend

    if isinstance(config, XlaBackend):
        return _xla_grouped(x_sorted, w, splits, out_dtype, config)
    from ..tune.autotuner import is_tracer

    if is_tracer(x_sorted) or is_tracer(w) or is_tracer(splits):
        return _grouped_matmul_vjp(config, out_dtype, x_sorted, w, splits)
    return _jitted_pallas_entry(config, out_dtype)(x_sorted, w, splits)


def grouped_matmul_callable(x_sorted: jax.Array, w: jax.Array,
                            splits: jax.Array, *, out_dtype=None):
    """Resolve the tuned backend ONCE and return the underlying jitted
    callable ``(x_sorted, w, splits) -> y`` (see
    ``ops.matmul.matmul_callable`` for why timed loops must not pay the
    eager wrapper's Python per call).  Eager-only."""
    from ..tune.autotuner import XlaBackend, is_tracer

    if is_tracer(x_sorted) or is_tracer(w) or is_tracer(splits):
        raise TypeError(
            "grouped_matmul_callable is eager-only (it measures/resolves "
            "on real arrays); call grouped_matmul() inside jit instead"
        )
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(
        x_sorted.dtype
    )

    config = _grouped_resolve(x_sorted, w, splits)
    if isinstance(config, XlaBackend):
        return _xla_ragged_fn(config.scoped_vmem_kib, out_dtype)
    return _jitted_pallas_entry(config, out_dtype)


def group_gemm(x_sorted: jax.Array, w: jax.Array,
               splits: jax.Array) -> jax.Array:
    """Per-expert matmul of expert-sorted rows (reference local group GEMM
    ``allgather_group_gemm.py:532``).

    ``x_sorted``: (T, K) rows grouped by expert; ``w``: (E, K, N);
    ``splits``: (E,) int32 row counts (sum <= T; padding rows at the tail
    multiply expert E-1 garbage-free — their outputs are never gathered).
    Returns (T, N).  This is the XLA path (``lax.ragged_dot``);
    :func:`grouped_matmul` is the tile-scheduled Pallas path with
    explicit block-size control.
    """
    t, k = x_sorted.shape
    e, k2, n_dim = w.shape
    if k2 != k:
        raise ValueError(f"inner dims mismatch: {x_sorted.shape} @ {w.shape}")
    if splits.shape != (e,):
        raise ValueError(f"splits {splits.shape} != (E,) = ({e},)")
    return jax.lax.ragged_dot(x_sorted, w, splits.astype(jnp.int32))


def _local_group_gemm(x, w, splits, config: GroupGemmConfig | None):
    """Per-shard grouped matmul dispatch: the autotuned backend on real
    TPU (XLA ``ragged_dot`` variants vs the tile-scheduled Pallas kernel
    — see :class:`GroupGemmConfig` for the current measurements),
    ``ragged_dot`` directly under CPU interpret mode where simulating the
    Pallas grid costs more than it models.  Pass ``config`` to force the
    Pallas path with explicit tiles anywhere."""
    from ..core import platform

    if config is None and platform.on_cpu():
        return jax.lax.ragged_dot(x, w, splits.astype(jnp.int32))
    return grouped_matmul(x, w, splits, config=config)


def ag_group_gemm(
    x_sorted: jax.Array,
    w: jax.Array,
    splits: jax.Array,
    mesh: Mesh,
    axis: str = TP_AXIS,
    *,
    config: GroupGemmConfig | None = None,
):
    """AllGather tokens over ``axis``, merge to global expert order, and
    group-GEMM against the column-sharded expert weights (reference
    ``ag_group_gemm``, ``allgather_group_gemm.py:398-605``).

    ``x_sorted``: global (n*T, K) over ``axis`` — each rank's shard sorted
    by expert; ``splits``: global (n*E,) int32; ``w``: (E, K, N) with N
    sharded over ``axis`` (column-parallel expert weights).

    Returns ``(y, total_splits, perm)``: ``y`` (n*T, N) N-sharded rows in
    GLOBAL expert order; ``total_splits`` (E,) and ``perm`` (n*T,) for the
    downstream combine.
    """
    n = mesh.shape[axis]
    e = w.shape[0]
    if n == 1:
        return group_gemm(x_sorted, w, splits), splits, jnp.arange(
            x_sorted.shape[0]
        )
    gathered = all_gather(x_sorted, mesh, axis)          # (n*T, K) replicated
    perm, total_splits = expert_block_permutation(
        splits.reshape(n, e), x_sorted.shape[0] // n
    )
    x_glob = jnp.take(gathered, perm, axis=0)            # global expert order

    def local(xg, w_loc):
        return _local_group_gemm(xg, w_loc, total_splits, config)

    y = compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(None, None), P(None, None, axis)),
        out_specs=P(None, axis),
    )(x_glob, w)
    return y, total_splits, perm


def moe_reduce_rs(
    y_sorted: jax.Array,
    w: jax.Array,
    total_splits: jax.Array,
    presort_idx: jax.Array,
    weights: jax.Array,
    topk: int,
    mesh: Mesh,
    axis: str = TP_AXIS,
    *,
    config: GroupGemmConfig | None = None,
) -> jax.Array:
    """Down-project expert outputs, fold the top-k copies with their
    routing weights, and ReduceScatter the partial sums back to token
    owners (reference ``moe_reduce_rs.py:486-816``).

    ``y_sorted``: (n*T, N) N-sharded rows in global expert order (from
    :func:`ag_group_gemm`); ``w``: (E, N, K) with N sharded (row-parallel
    down weights); ``presort_idx``: (n*T,) from
    ``moe_utils.global_presort_index`` (global expert order -> original
    pre-sort row order); ``weights``: (n*T,) routing weights in pre-sort
    row order; ``topk``: routing copies per token.  Returns global
    (n*T//topk, K) token rows sharded over ``axis``.
    """
    n = mesh.shape[axis]

    def local(y_loc, w_loc):
        # partial down-projection (this rank's N slice -> partial sums)
        part = _local_group_gemm(y_loc, w_loc, total_splits, config)
        # back to pre-sort order, weighted top-k fold: (n*T//topk, K)
        return unsort_combine(part, presort_idx, weights, topk)

    # out_specs P(axis): rank r's partial becomes row-block r — exactly the
    # stacked-partials convention reduce_scatter consumes
    partials = compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(None, axis), P(None, axis, None)),
        out_specs=P(axis, None),
    )(y_sorted, w)
    if n == 1:
        return partials
    return reduce_scatter(partials, mesh, axis)
