"""Local attention kernels: flash-attention prefill and split-KV decode.

The single-chip attention building blocks under the distributed attention
ops (``sp_attention``, ``flash_decode``) and the TP attention layer —
the role the reference's Triton flash kernels play
(``python/triton_dist/kernels/nvidia/flash_decode.py:130`` split-KV decode
stage, ``sp_ag_attention_intra_node.py:256`` consumer causal flash-attn).

TPU design notes:

- The online-softmax tiling is blocked on the query axis only; each (batch,
  q-head, q-block) grid cell streams the full K/V slice for its kv-head
  through VMEM.  At d=128, seq 8k, bf16 that is 2 MiB each for K and V —
  well inside VMEM — and lets the MXU run (bq, d) x (d, bk) matmuls
  back-to-back.  Longer sequences belong to the SP/CP ops, which chunk KV
  across devices before this kernel runs.
- GQA is folded into the BlockSpec index maps (q-head -> kv-head integer
  division), not a data relayout like the reference's BLOCK_H head packing
  (``flash_decode.py:130``): Mosaic prefetches the right kv slice per grid
  cell and replication never materializes.
- Softmax statistics are carried as f32 ``fori_loop`` values across kv
  tiles (one shared tile body, ``_tile_update``, serves prefill, chunked,
  and decode kernels); the causal variants bound the kv loop at the
  diagonal block (a traced loop bound, not a mask over the full sequence).
- ``soft_cap`` (tanh logit capping, reference ``flash_decode.py:161``) is
  applied inside the tile loop when set.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core import compilation
from ..core.utils import cdiv, clip_block

_NEG_INF = -1e30


def _init_carry(bq: int, d: int):
    """Fresh online-softmax loop carry: (m, l, acc) as f32 values."""
    return (
        jnp.full((bq, 1), _NEG_INF, jnp.float32),
        jnp.zeros((bq, 1), jnp.float32),
        jnp.zeros((bq, d), jnp.float32),
    )


def _tile_update(q, k, v, mask, soft_cap, carry, k_scale=None,
                 v_scale=None):
    """One online-softmax tile step, shared by every attention kernel here.

    ``q``: (bq, d) pre-scaled queries in their STORAGE dtype; ``k``/``v``:
    (bk, d) tile, storage dtype; ``mask``: (bq, bk) bool (True = keep) or
    None; ``carry``: (m, l, acc) f32 from :func:`_init_carry`.  Both
    matmuls run with bf16 (storage-dtype) operands and f32 MXU
    accumulation — feeding f32 operands to the MXU quarters its rate; the
    probability tile is cast back to the storage dtype for the p·V dot
    while (m, l, acc) stay f32.  A fully-masked row keeps p = 0 so it
    contributes a zero denominator instead of silently averaging V.

    ``k_scale``/``v_scale``: scalar f32 dequantization factors of an
    int8 K/V tile (the quantized KV cache's per-(page, head) scales,
    ISSUE 9).  The dequant FUSES into the existing math: int8 tiles cast
    to the q dtype exactly (|q| <= 127 is exact in bf16's 8-bit
    mantissa), the K scale folds into the score tile as ONE scalar
    multiply after the MXU dot, and the V scale folds into the p·V
    accumulation — two scalar ops per tile, no dequantized tile ever
    materialized in HBM.
    """
    m_prev, l_prev, acc = carry
    if k_scale is not None:
        k = k.astype(q.dtype)
    s = jax.lax.dot_general(
        q, k, (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )  # (bq, bk) f32
    if k_scale is not None:
        s = s * k_scale
    if soft_cap:
        s = jnp.tanh(s / soft_cap) * soft_cap
    if mask is not None:
        s = jnp.where(mask, s, _NEG_INF)
    m_cur = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
    if mask is not None:
        # a fully-masked row keeps m = -inf; exp(-inf - -inf) would be NaN
        p = jnp.where(m_cur > _NEG_INF / 2, jnp.exp(s - m_cur), 0.0)
    else:
        # unmasked tile: every row has a finite max, no NaN guard needed —
        # the kernels are VPU-bound (softmax arithmetic over (bq, bk)
        # tiles, not the MXU dots), so one elided select per element is a
        # measurable win
        p = jnp.exp(s - m_cur)
    alpha = jnp.exp(m_prev - m_cur)
    l_cur = l_prev * alpha + p.sum(axis=1, keepdims=True)
    if v_scale is not None:
        pv = jax.lax.dot(p.astype(q.dtype), v.astype(q.dtype),
                         preferred_element_type=jnp.float32) * v_scale
    else:
        pv = jax.lax.dot(p.astype(v.dtype), v,
                         preferred_element_type=jnp.float32)
    acc = acc * alpha + pv
    return m_cur, l_cur, acc


def _scaled_q(q_ref_slice, sm_scale):
    """Scale q in f32, return in the storage dtype for the MXU dot."""
    dtype = q_ref_slice.dtype
    return (q_ref_slice.astype(jnp.float32) * sm_scale).astype(dtype)


def _attn_kernel(
    seq_kv: int,
    bq: int,
    bk: int,
    causal: bool,
    has_segs: bool,
    sm_scale: float,
    soft_cap: float,
    *refs,
    # refs: q (1, bq, d), k (1, seq_kv, d), v (1, seq_kv, d),
    # [seg_q (1, bq), seg_kv (1, seq_kv) when has_segs], o (1, bq, d)
):
    if has_segs:
        q_ref, k_ref, v_ref, sq_ref, sk_ref, o_ref = refs
    else:
        q_ref, k_ref, v_ref, o_ref = refs
        sq_ref = sk_ref = None
    iq = pl.program_id(1)
    d = q_ref.shape[-1]
    q = _scaled_q(q_ref[0], sm_scale)            # (bq, d) storage dtype
    sq = sq_ref[0] if has_segs else None         # (bq,)

    def seg_mask_at(j):
        # packed varlen: attend only within the same segment (the
        # reference's cu_seqlens support, re-expressed as segment ids)
        sk = sk_ref[0, pl.ds(j * bk, bk)]                      # (bk,)
        return sq[:, None] == sk[None, :]

    def body_interior(j, carry):
        # tiles fully below the causal diagonal: no mask arithmetic
        k = k_ref[0, pl.ds(j * bk, bk)]                        # (bk, d)
        v = v_ref[0, pl.ds(j * bk, bk)]
        mask = seg_mask_at(j) if has_segs else None
        return _tile_update(q, k, v, mask, soft_cap, carry)

    def body_diagonal(j, carry):
        k = k_ref[0, pl.ds(j * bk, bk)]
        v = v_ref[0, pl.ds(j * bk, bk)]
        # rows are absolute q positions, cols absolute kv positions
        qpos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
        kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
        mask = qpos >= kpos
        if has_segs:
            mask = mask & seg_mask_at(j)
        return _tile_update(q, k, v, mask, soft_cap, carry)

    # (measured round 4: a 2x-unrolled interior loop and a base-2
    # exp2-domain softmax were both neutral here — Mosaic already
    # overlaps adjacent tiles' MXU/VPU work, and XLA's exp lowering is
    # already exp2-based.  See docs/perf.md's attention roofline.)
    carry = _init_carry(bq, d)
    if causal:
        # kv blocks at or left of this q-block's diagonal; blocks whose last
        # position is <= the q block's first need no causal mask at all
        nkv = (iq * bq + bq + bk - 1) // bk
        nfull = (iq * bq + 1) // bk
        carry = jax.lax.fori_loop(0, nfull, body_interior, carry)
        carry = jax.lax.fori_loop(nfull, nkv, body_diagonal, carry)
    else:
        carry = jax.lax.fori_loop(0, seq_kv // bk, body_interior, carry)
    _, l, acc = carry
    o_ref[0] = (acc / l).astype(o_ref.dtype)


@functools.lru_cache(maxsize=None)
def _build_flash_attention(
    b, h, hk, seq_q, seq_kv, d, bq, bk, causal, has_segs, sm_scale,
    soft_cap, dtype, vmem_limit=None
):
    group = h // hk
    kernel = functools.partial(
        _attn_kernel, seq_kv, bq, bk, causal, has_segs, sm_scale, soft_cap
    )
    in_specs = [
        pl.BlockSpec((1, bq, d), lambda bh, iq: (bh, iq, 0)),
        # GQA in the index map: q-head bh%h -> kv-head (bh%h)//group
        pl.BlockSpec(
            (1, seq_kv, d),
            lambda bh, iq: ((bh // h) * hk + (bh % h) // group, 0, 0),
        ),
        pl.BlockSpec(
            (1, seq_kv, d),
            lambda bh, iq: ((bh // h) * hk + (bh % h) // group, 0, 0),
        ),
    ]
    if has_segs:
        in_specs += [
            pl.BlockSpec((1, bq), lambda bh, iq: (bh // h, iq)),
            pl.BlockSpec((1, seq_kv), lambda bh, iq: (bh // h, 0)),
        ]
    from ..obs import costs

    call = pl.pallas_call(
        kernel,
        grid=(b * h, seq_q // bq),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, iq: (bh, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, seq_q, d), dtype),
        # kernel cost attribution sourced from obs.costs (the VPU-bound
        # exp count rides in transcendentals — docs/perf.md roofline)
        cost_estimate=costs.pallas_cost(
            costs.flash_attention(b, h, seq_q, seq_kv, d, causal, dtype)),
        compiler_params=compilation.compiler_params(
            collective=False,
            dimension_semantics=("parallel", "arbitrary"),
            vmem_limit_bytes=vmem_limit,
        ),
        interpret=compilation.interpret_mode(),
    )
    return jax.jit(call)


# (block_q, block_k, vmem_limit) — the tuned knob set of the prefill
# kernel.  512x1024 under the default 16 MiB scoped budget is the
# measured-best STATIC choice at the bench shape; which config wins in a
# given process tracks the chip's clock/bandwidth state, so the
# config=None path resolves it contextually like the GEMM backends.
FLASH_DEFAULT_BLOCKS = (512, 1024, None)
_FLASH_VL = 100 * 2**20


def flash_block_candidates(seq_q: int, seq_kv: int) -> list:
    cands = [
        FLASH_DEFAULT_BLOCKS,
        (512, 2048, _FLASH_VL), (1024, 1024, _FLASH_VL),
        (2048, 1024, _FLASH_VL), (512, 4096, _FLASH_VL),
        (256, 1024, None), (512, 512, None),
    ]
    return [c for c in cands
            if c[0] <= seq_q and c[1] <= seq_kv and seq_kv % c[1] == 0]


@functools.lru_cache(maxsize=None)
def _jitted_flash(bq, bk, vl, causal, sm_scale, soft_cap):
    return jax.jit(functools.partial(
        flash_attention, causal=causal, sm_scale=sm_scale,
        soft_cap=soft_cap, block_q=bq, block_k=bk, vmem_limit=vl,
    ))


def _flash_resolve(q, k, v, causal, sm_scale, soft_cap, *,
                   fresh: bool = False):
    from ..core import platform
    from ..tune import autotuner as _tune

    b, h, seq_q, d = q.shape
    _, hk, seq_kv, _ = k.shape
    return _tune.resolve_config(
        "flash_attention",
        (b, h, hk, seq_q, seq_kv, d, bool(causal), str(q.dtype),
         platform.device_kind()),
        flash_block_candidates(seq_q, seq_kv),
        FLASH_DEFAULT_BLOCKS,
        lambda c: (lambda: _jitted_flash(
            c[0], c[1], c[2], bool(causal), sm_scale, soft_cap)(q, k, v)),
        tracing=any(map(_tune.is_tracer, (q, k, v))),
        force_measure=fresh,
        fresh=fresh,
    )


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
    segment_ids: jax.Array | None = None,
    block_q: int | None = None,
    block_k: int | None = None,
    vmem_limit: int | None = None,
) -> jax.Array:
    """Blocked online-softmax attention (local; no collectives).

    ``q``: (B, H, Sq, D); ``k``/``v``: (B, Hkv, Skv, D) with H a multiple of
    Hkv (GQA).  ``causal`` aligns the LAST q position with the last kv
    position (decode-style suffix alignment when Sq < Skv is NOT applied —
    use :func:`decode_attention` for single-token decode).
    Golden: softmax(q k^T * scale + mask) v in f32.

    ``segment_ids``: optional (B, S) int32 for PACKED variable-length
    batches (the reference's cu_seqlens support,
    ``sp_ag_attention_intra_node.py`` varlen path): positions attend only
    within their segment.  Requires Sq == Skv.  Padding positions should
    share a sentinel id; their rows compute self-attention garbage that
    callers slice off.

    Default blocks 512x1024: doubling the kv block over 512x512 measured
    ~1.8x at (1, 32, 4096, 128) bf16 prefill — half the online-softmax
    rescale passes per q tile, and the 1024-row K/V streams keep the DMA
    ahead of the MXU (interleaved medians over 12 rounds).
    """
    b, h, seq_q, d = q.shape
    bk_, hk, seq_kv, dk = k.shape
    if (bk_, dk) != (b, d) or v.shape != k.shape:
        raise ValueError(f"shape mismatch: q={q.shape} k={k.shape} v={v.shape}")
    if h % hk:
        raise ValueError(f"GQA requires H % Hkv == 0, got {h} % {hk}")
    if causal and seq_q != seq_kv:
        raise ValueError(
            "causal prefill requires Sq == Skv (decode uses decode_attention)"
        )
    if segment_ids is not None:
        if seq_q != seq_kv:
            raise ValueError("segment_ids requires Sq == Skv (packed batch)")
        if segment_ids.shape != (b, seq_q):
            raise ValueError(
                f"segment_ids {segment_ids.shape} != (B, S) = ({b}, {seq_q})"
            )
    sm_scale = float(sm_scale) if sm_scale is not None else d ** -0.5
    if block_q is None and block_k is None:
        # contextual block tuning (segment-id batches share the dense
        # winner: the masking cost is identical per tile)
        block_q, block_k, vl = _flash_resolve(
            q, k, v, causal, sm_scale, float(soft_cap)
        )
        vmem_limit = vmem_limit or vl
    else:
        dq, dk, _ = FLASH_DEFAULT_BLOCKS
        block_q, block_k = block_q or dq, block_k or dk
    bq = clip_block(min(block_q, seq_q), seq_q)
    bkv = clip_block(min(block_k, seq_kv), seq_kv)
    fn = _build_flash_attention(
        b, h, hk, seq_q, seq_kv, d, bq, bkv, bool(causal),
        segment_ids is not None, sm_scale, float(soft_cap),
        jnp.dtype(q.dtype), vmem_limit,
    )
    args = [
        q.reshape(b * h, seq_q, d),
        k.reshape(b * hk, seq_kv, d),
        v.reshape(b * hk, seq_kv, d),
    ]
    if segment_ids is not None:
        segs = segment_ids.astype(jnp.int32)
        args += [segs, segs]
    out = fn(*args)
    return out.reshape(b, h, seq_q, d)


# ---------------------------------------------------------------------------
# chunked prefill with carried softmax state (the ring-attention step)


def _attn_chunk_kernel(
    seq_c: int,
    bq: int,
    bk: int,
    causal: bool,
    has_segs: bool,
    sm_scale: float,
    soft_cap: float,
    *refs,
    # refs: off (2,) int32 [q_off, kv_off] SMEM; q (1, bq, d);
    # k/v (1, seq_c, d); [seg_q (1, bq), seg_kv (1, seq_c) when has_segs];
    # m/l/acc in; m/l/acc out
):
    """One online-softmax pass of a KV *chunk* against a q block, reading and
    writing the carried (m, l, acc) state — the consumer step of ring/SP
    attention (reference ``sp_ag_attention_intra_node.py:256``: consumer
    causal flash-attn over per-chunk arrivals; its varlen cu_seqlens support
    is the segment-id mask here).  Causality is enforced in ABSOLUTE
    positions via the scalar offsets, so the same kernel serves every
    (rank, ring-step) pair; chunks entirely in the future contribute zero
    blocks (the kv loop bound clamps to 0) and the state passes through."""
    if has_segs:
        (off_ref, q_ref, k_ref, v_ref, sq_ref, sk_ref,
         m_in, l_in, acc_in, m_out, l_out, acc_out) = refs
    else:
        (off_ref, q_ref, k_ref, v_ref,
         m_in, l_in, acc_in, m_out, l_out, acc_out) = refs
        sq_ref = sk_ref = None
    iq = pl.program_id(1)
    q_off, kv_off = off_ref[0], off_ref[1]
    q = _scaled_q(q_ref[0], sm_scale)                  # (bq, d)
    sq = sq_ref[0] if has_segs else None               # (bq,)
    m0 = m_in[0][:, None]                              # (bq, 1)
    l0 = l_in[0][:, None]
    acc0 = acc_in[0]                                   # (bq, d)

    def seg_mask_at(j):
        sk = sk_ref[0, pl.ds(j * bk, bk)]              # (bk,)
        return sq[:, None] == sk[None, :]

    def body_interior(j, carry):
        k = k_ref[0, pl.ds(j * bk, bk)]
        v = v_ref[0, pl.ds(j * bk, bk)]
        mask = seg_mask_at(j) if has_segs else None
        return _tile_update(q, k, v, mask, soft_cap, carry)

    def body_diagonal(j, carry):
        k = k_ref[0, pl.ds(j * bk, bk)]
        v = v_ref[0, pl.ds(j * bk, bk)]
        qpos = q_off + iq * bq + jax.lax.broadcasted_iota(
            jnp.int32, (q.shape[0], bk), 0
        )
        kpos = kv_off + j * bk + jax.lax.broadcasted_iota(
            jnp.int32, (q.shape[0], bk), 1
        )
        mask = qpos >= kpos
        if has_segs:
            mask = mask & seg_mask_at(j)
        return _tile_update(q, k, v, mask, soft_cap, carry)

    if causal:
        # kv blocks whose first position is <= this q block's last position;
        # blocks entirely below the diagonal skip the causal arithmetic
        q_min = q_off + iq * bq
        q_max = q_min + bq - 1
        nkv = jnp.clip((q_max - kv_off) // bk + 1, 0, seq_c // bk)
        nfull = jnp.clip((q_min - kv_off + 1) // bk, 0, nkv)
        carry = jax.lax.fori_loop(0, nfull, body_interior, (m0, l0, acc0))
        m1, l1, acc1 = jax.lax.fori_loop(nfull, nkv, body_diagonal, carry)
    else:
        m1, l1, acc1 = jax.lax.fori_loop(
            0, seq_c // bk, body_interior, (m0, l0, acc0)
        )
    m_out[0] = m1[:, 0]
    l_out[0] = l1[:, 0]
    acc_out[0] = acc1


@functools.lru_cache(maxsize=None)
def _build_attn_chunk(b, h, hk, seq_q, seq_c, d, bq, bk, causal, has_segs,
                      sm_scale, soft_cap):
    group = h // hk
    kernel = functools.partial(
        _attn_chunk_kernel, seq_c, bq, bk, causal, has_segs, sm_scale,
        soft_cap,
    )
    kv_spec = pl.BlockSpec(
        (1, seq_c, d),
        lambda bh, iq: ((bh // h) * hk + (bh % h) // group, 0, 0),
    )
    state2_spec = pl.BlockSpec((1, bq), lambda bh, iq: (bh, iq))
    state3_spec = pl.BlockSpec((1, bq, d), lambda bh, iq: (bh, iq, 0))
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),
        pl.BlockSpec((1, bq, d), lambda bh, iq: (bh, iq, 0)),
        kv_spec,
        kv_spec,
    ]
    if has_segs:
        in_specs += [
            pl.BlockSpec((1, bq), lambda bh, iq: (bh // h, iq)),
            pl.BlockSpec((1, seq_c), lambda bh, iq: (bh // h, 0)),
        ]
    in_specs += [state2_spec, state2_spec, state3_spec]
    from ..obs import costs

    call = pl.pallas_call(
        kernel,
        grid=(b * h, seq_q // bq),
        in_specs=in_specs,
        out_specs=[state2_spec, state2_spec, state3_spec],
        out_shape=[
            jax.ShapeDtypeStruct((b * h, seq_q), jnp.float32),
            jax.ShapeDtypeStruct((b * h, seq_q), jnp.float32),
            jax.ShapeDtypeStruct((b * h, seq_q, d), jnp.float32),
        ],
        # the ring (sp_attention) chunk fold: one attention tile's cost
        cost_estimate=costs.pallas_cost(
            costs.flash_attention(b, h, seq_q, seq_c, d, causal,
                                  jnp.float32)),
        compiler_params=compilation.compiler_params(
            collective=False,
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=compilation.interpret_mode(),
    )
    return jax.jit(call)


def init_attention_state(b: int, h: int, seq_q: int, d: int):
    """Fresh (m, l, acc) carried state for :func:`flash_attention_chunk`."""
    return (
        jnp.full((b, h, seq_q), _NEG_INF, jnp.float32),
        jnp.zeros((b, h, seq_q), jnp.float32),
        jnp.zeros((b, h, seq_q, d), jnp.float32),
    )


def flash_attention_chunk(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    state,
    q_offset: jax.Array | int,
    kv_offset: jax.Array | int,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
    block_q: int = 512,
    block_k: int = 512,
    segment_ids_q: jax.Array | None = None,
    segment_ids_kv: jax.Array | None = None,
):
    """Fold one KV chunk into a carried attention state.

    ``q``: (B, H, Sq, D) at absolute positions ``q_offset + [0, Sq)``;
    ``k``/``v``: (B, Hkv, Sc, D) chunk at ``kv_offset + [0, Sc)``;
    ``state``: from :func:`init_attention_state` or a previous call.
    ``segment_ids_q`` (B, Sq) / ``segment_ids_kv`` (B, Sc): optional
    PACKED-varlen masking (the reference SP attention's cu_seqlens
    support) — positions attend only within their segment; pass both or
    neither.  Returns the updated state; normalize with
    :func:`finalize_attention_state` after the last chunk.
    """
    b, h, seq_q, d = q.shape
    bk_, hk, seq_c, dk = k.shape
    if (bk_, dk) != (b, d) or v.shape != k.shape:
        raise ValueError(f"shape mismatch: q={q.shape} k={k.shape} v={v.shape}")
    if h % hk:
        raise ValueError(f"GQA requires H % Hkv == 0, got {h} % {hk}")
    has_segs = segment_ids_q is not None
    if has_segs != (segment_ids_kv is not None):
        raise ValueError("pass both segment_ids_q and segment_ids_kv or neither")
    if has_segs and (segment_ids_q.shape != (b, seq_q)
                     or segment_ids_kv.shape != (b, seq_c)):
        raise ValueError(
            f"segment ids {segment_ids_q.shape}/{segment_ids_kv.shape} != "
            f"({b}, {seq_q})/({b}, {seq_c})"
        )
    sm_scale = float(sm_scale) if sm_scale is not None else d ** -0.5
    bq = clip_block(min(block_q, seq_q), seq_q)
    bk = clip_block(min(block_k, seq_c), seq_c)
    fn = _build_attn_chunk(
        b, h, hk, seq_q, seq_c, d, bq, bk, bool(causal), has_segs, sm_scale,
        float(soft_cap),
    )
    m, l, acc = state
    offs = jnp.stack([
        jnp.asarray(q_offset, jnp.int32), jnp.asarray(kv_offset, jnp.int32)
    ])
    args = [
        offs,
        q.reshape(b * h, seq_q, d),
        k.reshape(b * hk, seq_c, d),
        v.reshape(b * hk, seq_c, d),
    ]
    if has_segs:
        args += [segment_ids_q.astype(jnp.int32),
                 segment_ids_kv.astype(jnp.int32)]
    m1, l1, acc1 = fn(
        *args,
        m.reshape(b * h, seq_q),
        l.reshape(b * h, seq_q),
        acc.reshape(b * h, seq_q, d),
    )
    return (
        m1.reshape(b, h, seq_q),
        l1.reshape(b, h, seq_q),
        acc1.reshape(b, h, seq_q, d),
    )


def finalize_attention_state(state, dtype) -> jax.Array:
    """Normalize a carried state into the attention output (B, H, Sq, D)."""
    m, l, acc = state
    return (acc / l[..., None]).astype(dtype)


# ---------------------------------------------------------------------------
# split-KV decode


def _decode_kernel(
    hk: int,
    bk: int,
    sm_scale: float,
    soft_cap: float,
    kv_len_ref,  # (B,) int32 valid kv length per sequence (RAGGED) [SMEM]
    q_ref,    # (1, g, d)  VMEM — one kv-head's query group
    k_ref,    # (1, sp, d) VMEM — this split's K slice
    v_ref,    # (1, sp, d) VMEM
    o_ref,    # (1, 1, g, d)   partial numerator (unnormalized)
    m_ref,    # (1, 1, g, 128) f32 running max
    l_ref,    # (1, 1, g, 128) f32 denominator
):
    """One grid cell = (batch*kv_head, split): flash pass over the split's
    KV slice producing the (m, l, acc) softmax state — the merge across
    splits (and across ranks, in ``ops/flash_decode``) is associative
    (reference split-KV stage ``flash_decode.py:130`` + combine ``:482``).
    Lengths are per SEQUENCE, so ragged batches ride the same grid (like
    the paged kernel)."""
    split = pl.program_id(1)
    sp = k_ref.shape[1]
    g, d = q_ref.shape[1], q_ref.shape[2]
    kv_len = kv_len_ref[pl.program_id(0) // hk]
    q = _scaled_q(q_ref[0], sm_scale)            # (g, d)

    def body_valid(j, carry):
        # tiles entirely below kv_len: no mask arithmetic
        k = k_ref[0, pl.ds(j * bk, bk)]
        v = v_ref[0, pl.ds(j * bk, bk)]
        return _tile_update(q, k, v, None, soft_cap, carry)

    def body_edge(j, carry):
        k = k_ref[0, pl.ds(j * bk, bk)]
        v = v_ref[0, pl.ds(j * bk, bk)]
        kpos = split * sp + j * bk + jax.lax.broadcasted_iota(
            jnp.int32, (g, bk), 1
        )
        # an entirely masked split contributes l=0 and drops out of the
        # merge (see _tile_update's guard)
        return _tile_update(q, k, v, kpos < kv_len, soft_cap, carry)

    nfull = jnp.clip((kv_len - split * sp) // bk, 0, sp // bk)
    carry = jax.lax.fori_loop(0, nfull, body_valid, _init_carry(g, d))
    m1, l1, acc1 = jax.lax.fori_loop(nfull, sp // bk, body_edge, carry)
    # emit the state: numerator in o, statistics for the cross-split merge
    o_ref[0, 0] = acc1.astype(o_ref.dtype)
    m_ref[0, 0] = jnp.broadcast_to(m1, (g, 128))
    l_ref[0, 0] = jnp.broadcast_to(l1, (g, 128))


@functools.lru_cache(maxsize=None)
def _build_decode(b, h, hk, seq_kv, d, n_split, bk, sm_scale, soft_cap, dtype):
    group = h // hk
    sp = seq_kv // n_split
    kernel = functools.partial(_decode_kernel, hk, bk, sm_scale, soft_cap)
    from ..obs import costs

    call = pl.pallas_call(
        kernel,
        grid=(b * hk, n_split),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, group, d), lambda bh, s: (bh, 0, 0)),
            pl.BlockSpec((1, sp, d), lambda bh, s: (bh, s, 0)),
            pl.BlockSpec((1, sp, d), lambda bh, s: (bh, s, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, group, d), lambda bh, s: (bh, s, 0, 0)),
            pl.BlockSpec((1, 1, group, 128), lambda bh, s: (bh, s, 0, 0)),
            pl.BlockSpec((1, 1, group, 128), lambda bh, s: (bh, s, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * hk, n_split, group, d), jnp.float32),
            jax.ShapeDtypeStruct((b * hk, n_split, group, 128), jnp.float32),
            jax.ShapeDtypeStruct((b * hk, n_split, group, 128), jnp.float32),
        ],
        # KV-bandwidth-bound decode: cost = streaming the cache once
        # (flash_decode's per-rank stage reuses this builder)
        cost_estimate=costs.pallas_cost(
            costs.decode_attention(b, h, hk, seq_kv, d, dtype)),
        compiler_params=compilation.compiler_params(
            collective=False,
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=compilation.interpret_mode(),
    )
    return jax.jit(call)


def auto_n_split(seq_kv: int) -> int:
    """Default split count for the STATE-returning split-KV decode path
    (``decode_attention_state``): 4 balances split parallelism against the
    f32 (num, m, l) state round-trips that path pays per split, halved
    until it divides the cache length."""
    n = 4
    while n > 1 and seq_kv % n:
        n //= 2
    return n


_DECODE_BLOCK_BYTES = 2 * 2**20  # bytes per VMEM KV operand block —
# K + V double-buffered = 8 MiB, inside Mosaic's 16 MiB scoped default
# (the kernel passes no vmem_limit), so the DEFAULT geometry always
# compiles — it is what the jit-tracing resolve path returns UNVALIDATED


def default_decode_geometry(seq_kv: int, head_dim: int = 128,
                            itemsize: int = 2) -> tuple[int, int]:
    """Default (n_split, block_k) of the FUSED local decode kernel:
    fewest-splits streaming with a 2048-row kv tile.  The round-5 on-chip
    steady-state sweeps (8k cache, B=8, GQA 32/8) put (1, 2048) and
    (1, seq_kv) at 800-890 GB/s — essentially HBM speed — while the old
    (4, 512) default sat at 540-600 GB/s: with one grid step per (b, hk)
    cell the per-step pipeline overhead amortizes over a 512 KiB DMA
    instead of 128 KiB.  Splits only appear when one split's KV slice
    would blow the VMEM budget (``_DECODE_BLOCK_BYTES``, a ROW cap of
    bytes / (head_dim * itemsize) — 8192 rows at d=128 bf16, halved for
    f32), so a 128k bf16 cache gets (16, 2048) instead of an
    uncompilable (1, 131072) block.  A cache length over the cap with no
    usable divisor (prime-ish) raises with pad guidance rather than
    silently degenerating to thousands of 1-row grid steps.  (The state
    path keeps :func:`auto_n_split`: its cost model differs — splits
    multiply ITS f32 state traffic.)"""
    cap = max(256, _DECODE_BLOCK_BYTES // (head_dim * itemsize))
    if seq_kv <= cap:
        return (1, min(2048, seq_kv))
    for ns in range(cdiv(seq_kv, cap), seq_kv + 1):
        if seq_kv % ns == 0:
            sp = seq_kv // ns
            if sp >= 256:
                return (ns, min(2048, sp))
            break  # largest usable divisor is already pathological
    raise ValueError(
        f"KV cache length {seq_kv} (head_dim={head_dim}, "
        f"itemsize={itemsize}) has no split with 256-{cap} rows; pad the "
        f"cache to a multiple of 2048"
    )


def decode_split_candidates(seq_kv: int, head_dim: int = 128,
                            itemsize: int = 2) -> list:
    """(n_split, block_k) sweep for the decode kernel's ``config=None``
    path, best-first from the round-5 steady-state sweeps.  Which
    geometry wins tracks the chip's clock state, so the choice is
    contextual — resolved per shape from the winner cache or a
    first-eager-call measurement, like the GEMM backends.  The sweep also
    carries the XLA-dispatch candidate (``tune.XlaBackend``): the unfused
    einsum decode is the reference baseline, and crowning it when it
    genuinely wins a chip state makes the resolved op never-lose."""
    cands = [
        default_decode_geometry(seq_kv, head_dim, itemsize),
        (1, seq_kv), (4, 2048),
        (2, 512), (auto_n_split(seq_kv), 512), (8, 1024),
    ]
    cap = max(256, _DECODE_BLOCK_BYTES // (head_dim * itemsize))
    out = []
    for ns, bk in cands:
        if ns < 1 or seq_kv % ns:
            continue
        sp = seq_kv // ns
        if bk > sp or sp % bk or sp > cap:
            continue
        if (ns, bk) not in out:
            out.append((ns, bk))
    from ..tune.autotuner import xla_backend_candidates

    return out + xla_backend_candidates()


@functools.lru_cache(maxsize=None)
def _xla_decode_fn(b: int, h: int, hk: int, seq_kv: int, d: int,
                   sm_scale: float, soft_cap: float, dtype):
    """Unfused GQA decode as one jitted XLA computation (the never-lose
    dispatch target when ``XlaBackend`` is crowned) — materializes the
    (B, Hkv, G, S) score matrix, with ragged ``kv_len`` masking."""
    group = h // hk

    def fn(q, k, v, kv_len):
        kv_len = jnp.broadcast_to(jnp.asarray(kv_len, jnp.int32), (b,))
        qh = q.reshape(b, hk, group, d).astype(jnp.float32)
        sc = jnp.einsum("bkgd,bksd->bkgs", qh, k.astype(jnp.float32))
        sc = sc * sm_scale
        if soft_cap:
            sc = jnp.tanh(sc / soft_cap) * soft_cap
        pos = jnp.arange(seq_kv, dtype=jnp.int32)
        valid = pos[None, :] < kv_len[:, None]               # (B, S)
        sc = jnp.where(valid[:, None, None, :], sc, -jnp.inf)
        p = jax.nn.softmax(sc, axis=-1)
        p = jnp.where(valid[:, None, None, :], p, 0.0)       # all-masked rows
        out = jnp.einsum("bkgs,bksd->bkgd", p, v.astype(jnp.float32))
        return out.reshape(b, h, d).astype(dtype)

    return jax.jit(fn)


@functools.lru_cache(maxsize=None)
def _jitted_decode(ns, bk, sm_scale, soft_cap):
    return jax.jit(functools.partial(
        decode_attention, n_split=ns, block_k=bk, sm_scale=sm_scale,
        soft_cap=soft_cap,
    ))


def _decode_resolve(q, k, v, kv_len, sm_scale, soft_cap, *,
                    fresh: bool = False):
    from ..core import platform
    from ..tune import autotuner as _tune

    b, h, d = q.shape
    _, hk, seq_kv, _ = k.shape

    def thunk(c):
        if isinstance(c, _tune.XlaBackend):
            fn = _xla_decode_fn(b, h, hk, seq_kv, d, sm_scale, soft_cap,
                                jnp.dtype(q.dtype))
            return lambda: fn(q, k, v, kv_len)
        return lambda: _jitted_decode(
            c[0], c[1], sm_scale, soft_cap)(q, k, v, kv_len)

    return _tune.resolve_config(
        "decode_attention",
        # k dtype is in the key: the sweep geometry and default are
        # itemsize-aware, so a bf16-cache crown must not serve f32
        (b, h, hk, seq_kv, d, str(q.dtype), str(k.dtype),
         platform.device_kind()),
        decode_split_candidates(seq_kv, d, jnp.dtype(k.dtype).itemsize),
        default_decode_geometry(seq_kv, d, jnp.dtype(k.dtype).itemsize),
        thunk,
        tracing=any(map(_tune.is_tracer, (q, k, v, kv_len))),
        force_measure=fresh,
        fresh=fresh,
    )


def decode_attention_state(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_len: jax.Array | int,
    *,
    n_split: int | None = None,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
    block_k: int | None = None,
):
    """Split-KV decode pass returning the mergeable softmax state.

    ``q``: (B, H, D) single decode token; ``k``/``v``: (B, Hkv, Skv, D)
    cache (positions >= ``kv_len`` masked).  ``kv_len``: a scalar, or a
    (B,) int32 array of RAGGED per-sequence lengths (like the paged
    kernel).  Returns ``(num, m, l)`` with ``num``: (B, H, n_split, D)
    unnormalized numerators, ``m``/``l``: (B, H, n_split) statistics.
    Merging over any set of states (splits or ranks) with
    :func:`merge_decode_states` then dividing gives exact attention —
    associativity is what the distributed flash-decode rides.
    ``n_split=None`` picks :func:`auto_n_split`.
    """
    b, h, d = q.shape
    bk_, hk, seq_kv, dk = k.shape
    if (bk_, dk) != (b, d) or v.shape != k.shape:
        raise ValueError(f"shape mismatch: q={q.shape} k={k.shape} v={v.shape}")
    if h % hk:
        raise ValueError(f"GQA requires H % Hkv == 0, got {h} % {hk}")
    sm_scale = float(sm_scale) if sm_scale is not None else d ** -0.5
    # static defaults here, NOT the tuned geometry: the winner cache is
    # measured on the FUSED kernel (decode_attention), whose cost model
    # differs — high n_split is nearly free there but multiplies this
    # path's f32 state round-trips
    if n_split is None:
        n_split = auto_n_split(seq_kv)
    if block_k is None:
        block_k = 512
    if seq_kv % n_split:
        raise ValueError(f"Skv={seq_kv} not divisible by n_split={n_split}")
    group = h // hk
    sp = seq_kv // n_split
    bk = clip_block(min(block_k, sp), sp)
    fn = _build_decode(
        b, h, hk, seq_kv, d, n_split, bk, sm_scale, float(soft_cap),
        jnp.dtype(q.dtype),
    )
    kv_len = jnp.broadcast_to(jnp.asarray(kv_len, jnp.int32), (b,))
    num, m, l = fn(
        kv_len,
        q.reshape(b * hk, group, d),
        k.reshape(b * hk, seq_kv, d),
        v.reshape(b * hk, seq_kv, d),
    )
    num = num.reshape(b, hk, n_split, group, d).transpose(0, 1, 3, 2, 4)
    m = m[..., 0].reshape(b, hk, n_split, group).transpose(0, 1, 3, 2)
    l = l[..., 0].reshape(b, hk, n_split, group).transpose(0, 1, 3, 2)
    return (
        num.reshape(b, h, n_split, d),
        m.reshape(b, h, n_split),
        l.reshape(b, h, n_split),
    )


def safe_normalize_decode(num, l, dtype) -> jax.Array:
    """``num / l`` with EMPTY rows (l == 0 — a ragged sequence of length
    0, realistic in padded serving batches) returning zeros instead of
    0/0 NaN.  The shared final step of every decode entry."""
    return jnp.where(l > 0, num / jnp.maximum(l, 1e-38), 0.0).astype(dtype)


def merge_decode_states(num, m, l):
    """Combine split-KV softmax states over the split axis (reference
    inter-rank combine ``flash_decode.py:482``): rescale each partial
    numerator and denominator by exp(m_i - m*) and sum.  ``num``:
    (..., S, D); ``m``/``l``: (..., S).  Returns (num, m, l) with the split
    axis reduced to size 1 — associative, so states may be merged in any
    grouping (splits first, then ranks)."""
    m_star = m.max(axis=-1, keepdims=True)            # (..., 1)
    scale = jnp.exp(m - m_star)                       # (..., S)
    num = (num * scale[..., None]).sum(axis=-2, keepdims=True)
    l = (l * scale).sum(axis=-1, keepdims=True)
    return num, m_star, l


def decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_len: jax.Array | int,
    *,
    n_split: int | None = None,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
    block_k: int | None = None,
) -> jax.Array:
    """Single-token decode attention over a (possibly padded) KV cache.

    Delegates to the fused single-kernel path
    (:func:`decode_attention_fused`) — the local decode has no cross-rank
    merge, so the 3-stage state pipeline (split kernel -> merge ->
    normalize) only pays launches and f32 state traffic for structure it
    does not need.  ``decode_attention_state`` + ``merge_decode_states``
    remain the distributed building blocks (``ops.flash_decode``).
    Returns (B, H, D); ``n_split=None``/``block_k=None`` resolve the
    tuned split geometry (:func:`decode_split_candidates`).
    """
    return decode_attention_fused(
        q, k, v, kv_len, n_split=n_split, sm_scale=sm_scale,
        soft_cap=soft_cap, block_k=block_k,
    )


# ---------------------------------------------------------------------------
# fused single-pass decode (local fast path)


def _decode_fused_kernel(
    hk: int,
    n_split: int,
    bk: int,
    sm_scale: float,
    soft_cap: float,
    kv_len_ref,  # (B,) int32 valid kv length per sequence (RAGGED) [SMEM]
    q_ref,    # (1, g, d)  VMEM — one kv-head's query group
    k_ref,    # (1, sp, d) VMEM — this split's K slice
    v_ref,    # (1, sp, d) VMEM
    o_ref,    # (1, g, d)  normalized output (written at the last split)
    m_sc,     # (g, 1) f32 scratch — persists across the split steps
    l_sc,     # (g, 1) f32
    acc_sc,   # (g, d) f32
):
    """The split-KV decode collapsed to ONE kernel: the softmax state
    lives in VMEM scratch across the split grid steps (sequential
    ``arbitrary`` dimension) instead of round-tripping f32 (num, m, l)
    through HBM into a separate merge + normalize computation.  At the
    ~0.4 ms scale of a serving decode step the extra kernel launches and
    state traffic of the 3-stage pipeline are a measurable fraction of
    the whole op; the fused form exists for exactly the reason the
    reference fuses its decode epilogue into the split kernel when no
    cross-rank merge follows (``flash_decode.py:482`` combine is only for
    the distributed path).  The state-returning ``decode_attention_state``
    remains the distributed building block."""
    split = pl.program_id(1)
    sp = k_ref.shape[1]
    g, d = q_ref.shape[1], q_ref.shape[2]
    kv_len = kv_len_ref[pl.program_id(0) // hk]
    q = _scaled_q(q_ref[0], sm_scale)            # (g, d)

    @pl.when(split == 0)
    def _():
        m_sc[...] = jnp.full((g, 1), _NEG_INF, jnp.float32)
        l_sc[...] = jnp.zeros((g, 1), jnp.float32)
        acc_sc[...] = jnp.zeros((g, d), jnp.float32)

    def body_valid(j, carry):
        k = k_ref[0, pl.ds(j * bk, bk)]
        v = v_ref[0, pl.ds(j * bk, bk)]
        return _tile_update(q, k, v, None, soft_cap, carry)

    def body_edge(j, carry):
        k = k_ref[0, pl.ds(j * bk, bk)]
        v = v_ref[0, pl.ds(j * bk, bk)]
        kpos = split * sp + j * bk + jax.lax.broadcasted_iota(
            jnp.int32, (g, bk), 1
        )
        return _tile_update(q, k, v, kpos < kv_len, soft_cap, carry)

    nfull = jnp.clip((kv_len - split * sp) // bk, 0, sp // bk)
    carry = (m_sc[...], l_sc[...], acc_sc[...])
    carry = jax.lax.fori_loop(0, nfull, body_valid, carry)
    m1, l1, acc1 = jax.lax.fori_loop(nfull, sp // bk, body_edge, carry)
    m_sc[...] = m1
    l_sc[...] = l1
    acc_sc[...] = acc1

    @pl.when(split == n_split - 1)
    def _():
        # shared epilogue: empty rows (ragged length 0) return zeros
        o_ref[0] = safe_normalize_decode(acc1, l1, o_ref.dtype)


@functools.lru_cache(maxsize=None)
def _build_decode_fused(b, h, hk, seq_kv, d, n_split, bk, sm_scale,
                        soft_cap, dtype):
    group = h // hk
    sp = seq_kv // n_split
    kernel = functools.partial(
        _decode_fused_kernel, hk, n_split, bk, sm_scale, soft_cap
    )
    from ..obs import costs

    call = pl.pallas_call(
        kernel,
        grid=(b * hk, n_split),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, group, d), lambda bh, s: (bh, 0, 0)),
            pl.BlockSpec((1, sp, d), lambda bh, s: (bh, s, 0)),
            pl.BlockSpec((1, sp, d), lambda bh, s: (bh, s, 0)),
        ],
        out_specs=pl.BlockSpec((1, group, d), lambda bh, s: (bh, 0, 0)),
        out_shape=jax.ShapeDtypeStruct((b * hk, group, d), dtype),
        cost_estimate=costs.pallas_cost(
            costs.decode_attention(b, h, hk, seq_kv, d, dtype)),
        scratch_shapes=[
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, 1), jnp.float32),
            pltpu.VMEM((group, d), jnp.float32),
        ],
        compiler_params=compilation.compiler_params(
            collective=False,
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=compilation.interpret_mode(),
    )
    return jax.jit(call)


def decode_attention_fused(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_len: jax.Array | int,
    *,
    n_split: int | None = None,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
    block_k: int | None = None,
) -> jax.Array:
    """Single-kernel decode attention (see ``_decode_fused_kernel``);
    returns (B, H, D).  Golden: :func:`decode_attention`'s 3-stage path."""
    b, h, d = q.shape
    bk_, hk, seq_kv, dk = k.shape
    if (bk_, dk) != (b, d) or v.shape != k.shape:
        raise ValueError(f"shape mismatch: q={q.shape} k={k.shape} v={v.shape}")
    if h % hk:
        raise ValueError(f"GQA requires H % Hkv == 0, got {h} % {hk}")
    sm_scale = float(sm_scale) if sm_scale is not None else d ** -0.5
    if n_split is None and block_k is None:
        cfg = _decode_resolve(q, k, v, kv_len, sm_scale, float(soft_cap))
        from ..tune.autotuner import XlaBackend

        if isinstance(cfg, XlaBackend):
            # crowned never-lose dispatch: the unfused einsum decode won
            # this chip state outright (see decode_split_candidates)
            fn = _xla_decode_fn(b, h, hk, seq_kv, d, sm_scale,
                                float(soft_cap), jnp.dtype(q.dtype))
            return fn(q, k, v, kv_len)
        n_split, block_k = cfg
    elif n_split is None:
        n_split = default_decode_geometry(
            seq_kv, d, jnp.dtype(k.dtype).itemsize)[0]
    elif block_k is None:
        block_k = 2048 if n_split == 1 else 512
    if seq_kv % n_split:
        raise ValueError(f"Skv={seq_kv} not divisible by n_split={n_split}")
    group = h // hk
    sp = seq_kv // n_split
    bk = clip_block(min(block_k, sp), sp)
    fn = _build_decode_fused(
        b, h, hk, seq_kv, d, n_split, bk, sm_scale, float(soft_cap),
        jnp.dtype(q.dtype),
    )
    kv_len = jnp.broadcast_to(jnp.asarray(kv_len, jnp.int32), (b,))
    out = fn(
        kv_len,
        q.reshape(b * hk, group, d),
        k.reshape(b * hk, seq_kv, d),
        v.reshape(b * hk, seq_kv, d),
    )
    return out.reshape(b, hk, group, d).reshape(b, h, d)


# ---------------------------------------------------------------------------
# paged (block-table) split-KV decode


def _paged_decode_kernel(
    hk: int,
    page_size: int,
    sm_scale: float,
    soft_cap: float,
    quantized: bool,
    *refs,
    # scalar-prefetch: table (B, max_pages) int32, lens (B,) int32, and
    # when ``quantized``: kscale/vscale (P*hk,) f32 — per-(page, head)
    # dequant factors flattened to the pool's row order [SMEM].
    # then: q (1, g, d) VMEM; k/v (1, page_size, d) VMEM (int8 when
    # quantized — the gathered physical page streams in storage form);
    # outputs o (1, 1, g, d), m/l (1, 1, g, 128) f32.
):
    """One grid cell = (batch*kv_head, logical page): the split-KV decode
    body (``_decode_kernel``) with the KV slice GATHERED through the block
    table — the scalar-prefetched index maps hand Mosaic the physical page
    id before the cell runs, so page DMAs pipeline exactly like contiguous
    splits (reference paged decode ``flash_decode.py:587-720``:
    ``gqa_fwd_batch_decode`` walking ``block_table``).  Pages at or past a
    sequence's length mask to l = 0 and drop out of the merge, which is how
    RAGGED per-sequence lengths ride an identical grid.

    ``quantized``: the int8 KV-cache path (ISSUE 9) — pages stream from
    HBM in int8 (HALF the cache bandwidth of bf16, the whole point) and
    the per-(page, head) scale dequantizes INSIDE the tile update (two
    scalar multiplies; see ``_tile_update``) — no full-precision pool is
    ever materialized."""
    if quantized:
        (table_ref, lens_ref, kscale_ref, vscale_ref,
         q_ref, k_ref, v_ref, o_ref, m_ref, l_ref) = refs
    else:
        table_ref, lens_ref, q_ref, k_ref, v_ref, o_ref, m_ref, l_ref = refs
        kscale_ref = vscale_ref = None
    bh, j = pl.program_id(0), pl.program_id(1)
    g, d = q_ref.shape[1], q_ref.shape[2]
    kv_len = lens_ref[bh // hk]
    q = _scaled_q(q_ref[0], sm_scale)            # (g, d)

    k = k_ref[0]                                 # (page_size, d)
    v = v_ref[0]
    ks = vs = None
    if quantized:
        srow = table_ref[bh // hk, j] * hk + jax.lax.rem(bh, hk)
        ks = kscale_ref[srow]
        vs = vscale_ref[srow]
    kpos = j * page_size + jax.lax.broadcasted_iota(
        jnp.int32, (g, page_size), 1
    )
    m1, l1, acc1 = _tile_update(
        q, k, v, kpos < kv_len, soft_cap, _init_carry(g, d),
        k_scale=ks, v_scale=vs,
    )
    o_ref[0, 0] = acc1.astype(o_ref.dtype)
    m_ref[0, 0] = jnp.broadcast_to(m1, (g, 128))
    l_ref[0, 0] = jnp.broadcast_to(l1, (g, 128))


@functools.lru_cache(maxsize=None)
def _build_paged_decode(b, h, hk, num_pages, page_size, max_pages, d,
                        sm_scale, soft_cap, dtype, quantized=False,
                        pool_dtype=None):
    group = h // hk
    kernel = functools.partial(
        _paged_decode_kernel, hk, page_size, sm_scale, soft_cap, quantized
    )
    n_prefetch = 4 if quantized else 2
    # pool arrives reshaped (num_pages * hk, page_size, d); the physical row
    # for grid cell (bh, j) is table[bh // hk, j] * hk + bh % hk (the
    # prefetch tail — scales, when quantized — is unused by index maps)
    kv_spec = pl.BlockSpec(
        (1, page_size, d),
        lambda bh, j, table, lens, *_: (
            table[bh // hk, j] * hk + bh % hk, 0, 0),
    )
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=n_prefetch,
        grid=(b * hk, max_pages),
        in_specs=[
            pl.BlockSpec((1, group, d), lambda bh, j, *_: (bh, 0, 0)),
            kv_spec,
            kv_spec,
        ],
        out_specs=[
            pl.BlockSpec((1, 1, group, d), lambda bh, j, *_: (bh, j, 0, 0)),
            pl.BlockSpec((1, 1, group, 128), lambda bh, j, *_: (bh, j, 0, 0)),
            pl.BlockSpec((1, 1, group, 128), lambda bh, j, *_: (bh, j, 0, 0)),
        ],
    )
    from ..obs import costs

    call = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=[
            jax.ShapeDtypeStruct((b * hk, max_pages, group, d), jnp.float32),
            jax.ShapeDtypeStruct((b * hk, max_pages, group, 128), jnp.float32),
            jax.ShapeDtypeStruct((b * hk, max_pages, group, 128), jnp.float32),
        ],
        # paged decode streams max_pages * page_size rows of cache (at
        # the POOL dtype's bandwidth — int8 halves it)
        cost_estimate=costs.pallas_cost(
            costs.decode_attention(b, h, hk, max_pages * page_size, d,
                                   pool_dtype or dtype)),
        compiler_params=compilation.compiler_params(
            collective=False,
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=compilation.interpret_mode(),
    )
    return jax.jit(call)


def paged_decode_attention_state(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    block_table: jax.Array,
    seq_lens: jax.Array,
    *,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
):
    """Split-KV decode over a PAGED cache, returning the mergeable state.

    ``q``: (B, H, D) decode token; ``pool_k``/``pool_v``: (P, Hkv,
    page_size, D) physical page pools; ``block_table``: (B, max_pages)
    int32 — logical page j of sequence b lives in pool page
    ``block_table[b, j]`` (entries past a sequence's page count must still
    be valid pool indices, e.g. 0 — they mask out); ``seq_lens``: (B,)
    int32 RAGGED per-sequence lengths.  Returns ``(num, m, l)`` with the
    page axis in place of the split axis — merge with
    :func:`merge_decode_states`.  Reference:
    ``flash_decode.py:587-720`` (``gqa_fwd_batch_decode*`` with
    ``block_table``), ``sp_flash_decode_layer.py:83-108``.

    ``k_scale``/``v_scale``: (P, Hkv) f32 per-(page, head) scales of an
    int8-quantized pool (``models.kv_cache`` ``kv_dtype="int8"``) —
    dequantization fuses into the page-streaming loop (see
    ``_paged_decode_kernel``); pass both or neither.
    """
    b, h, d = q.shape
    p, hk, page_size, dk = pool_k.shape
    if dk != d or pool_v.shape != pool_k.shape:
        raise ValueError(
            f"shape mismatch: q={q.shape} pool_k={pool_k.shape} "
            f"pool_v={pool_v.shape}"
        )
    if h % hk:
        raise ValueError(f"GQA requires H % Hkv == 0, got {h} % {hk}")
    if block_table.shape[0] != b or seq_lens.shape != (b,):
        raise ValueError(
            f"block_table {block_table.shape} / seq_lens {seq_lens.shape} "
            f"inconsistent with B={b}"
        )
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("pass both k_scale and v_scale or neither")
    if quantized and (k_scale.shape != (p, hk) or v_scale.shape != (p, hk)):
        raise ValueError(
            f"scales {k_scale.shape}/{v_scale.shape} != (P, Hkv) = "
            f"({p}, {hk})")
    group = h // hk
    max_pages = block_table.shape[1]
    sm_scale = float(sm_scale) if sm_scale is not None else d ** -0.5
    fn = _build_paged_decode(
        b, h, hk, p, page_size, max_pages, d, sm_scale, float(soft_cap),
        jnp.dtype(q.dtype), quantized, jnp.dtype(pool_k.dtype),
    )
    args = [block_table.astype(jnp.int32), seq_lens.astype(jnp.int32)]
    if quantized:
        args += [k_scale.reshape(p * hk).astype(jnp.float32),
                 v_scale.reshape(p * hk).astype(jnp.float32)]
    num, m, l = fn(
        *args,
        q.reshape(b * hk, group, d),
        pool_k.reshape(p * hk, page_size, d),
        pool_v.reshape(p * hk, page_size, d),
    )
    num = num.reshape(b, hk, max_pages, group, d).transpose(0, 1, 3, 2, 4)
    m = m[..., 0].reshape(b, hk, max_pages, group).transpose(0, 1, 3, 2)
    l = l[..., 0].reshape(b, hk, max_pages, group).transpose(0, 1, 3, 2)
    return (
        num.reshape(b, h, max_pages, d),
        m.reshape(b, h, max_pages),
        l.reshape(b, h, max_pages),
    )


def paged_decode_attention(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    block_table: jax.Array,
    seq_lens: jax.Array,
    *,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """Single-token decode attention over a paged cache; returns (B, H, D).
    Golden: :func:`decode_attention` on the contiguously-materialized cache
    with per-sequence masking (DEQUANTIZED first for an int8 pool —
    ``k_scale``/``v_scale`` as in :func:`paged_decode_attention_state`)."""
    num, m, l = paged_decode_attention_state(
        q, pool_k, pool_v, block_table, seq_lens,
        sm_scale=sm_scale, soft_cap=soft_cap,
        k_scale=k_scale, v_scale=v_scale,
    )
    num, _, l = merge_decode_states(num, m, l)
    return safe_normalize_decode(
        num[..., 0, :], l[..., 0][..., None], q.dtype
    )
