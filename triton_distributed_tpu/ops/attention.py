"""Local attention kernels: flash-attention prefill and split-KV decode.

The single-chip attention building blocks under the distributed attention
ops (``sp_attention``, ``flash_decode``) and the TP attention layer —
the role the reference's Triton flash kernels play
(``python/triton_dist/kernels/nvidia/flash_decode.py:130`` split-KV decode
stage, ``sp_ag_attention_intra_node.py:256`` consumer causal flash-attn).

TPU design notes:

- The online-softmax tiling is blocked on the query axis only; each (batch,
  q-head, q-block) grid cell streams the full K/V slice for its kv-head
  through VMEM.  At d=128, seq 8k, bf16 that is 2 MiB each for K and V —
  well inside VMEM — and lets the MXU run (bq, d) x (d, bk) matmuls
  back-to-back.  Longer sequences belong to the SP/CP ops, which chunk KV
  across devices before this kernel runs.
- GQA is folded into the BlockSpec index maps (q-head -> kv-head integer
  division), not a data relayout like the reference's BLOCK_H head packing
  (``flash_decode.py:130``): Mosaic prefetches the right kv slice per grid
  cell and replication never materializes.
- Softmax statistics are carried in f32 VMEM scratch across kv blocks; the
  causal variant bounds the kv loop at the diagonal block (a traced
  ``fori_loop`` bound, not a mask over the full sequence).
- ``soft_cap`` (tanh logit capping, reference ``flash_decode.py:161``) is
  applied inside the tile loop when set.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core import compilation
from ..core.utils import clip_block

_NEG_INF = -1e30


def _attn_kernel(
    seq_kv: int,
    bq: int,
    bk: int,
    causal: bool,
    sm_scale: float,
    soft_cap: float,
    q_ref,    # (1, bq, d)    VMEM
    k_ref,    # (1, seq_kv, d) VMEM
    v_ref,    # (1, seq_kv, d) VMEM
    o_ref,    # (1, bq, d)    VMEM
    m_ref,    # (bq, 128) f32 running max        [VMEM scratch]
    l_ref,    # (bq, 128) f32 running denominator [VMEM scratch]
    acc_ref,  # (bq, d) f32 output accumulator    [VMEM scratch]
):
    iq = pl.program_id(1)
    m_ref[...] = jnp.full_like(m_ref, _NEG_INF)
    l_ref[...] = jnp.zeros_like(l_ref)
    acc_ref[...] = jnp.zeros_like(acc_ref)

    q = q_ref[0].astype(jnp.float32) * sm_scale  # (bq, d)

    def body(j, _):
        k = k_ref[0, pl.ds(j * bk, bk)].astype(jnp.float32)    # (bk, d)
        v = v_ref[0, pl.ds(j * bk, bk)].astype(jnp.float32)    # (bk, d)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (bq, bk)
        if soft_cap:
            s = jnp.tanh(s / soft_cap) * soft_cap
        if causal:
            # rows are absolute q positions, cols absolute kv positions
            qpos = iq * bq + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 0)
            kpos = j * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
            s = jnp.where(qpos >= kpos, s, _NEG_INF)
        m_prev = m_ref[:, :1]                                   # (bq, 1)
        m_cur = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        p = jnp.exp(s - m_cur)                                  # (bq, bk)
        l_ref[...] = l_ref[...] * alpha + p.sum(axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_ref[...] = jnp.broadcast_to(m_cur, m_ref.shape)
        return 0

    if causal:
        # kv blocks at or left of this q-block's diagonal
        nkv = (iq * bq + bq + bk - 1) // bk
    else:
        nkv = seq_kv // bk
    jax.lax.fori_loop(0, nkv, body, 0)
    o_ref[0] = (acc_ref[...] / l_ref[:, :1]).astype(o_ref.dtype)


@functools.lru_cache(maxsize=None)
def _build_flash_attention(
    b, h, hk, seq_q, seq_kv, d, bq, bk, causal, sm_scale, soft_cap, dtype
):
    group = h // hk
    kernel = functools.partial(
        _attn_kernel, seq_kv, bq, bk, causal, sm_scale, soft_cap
    )
    call = pl.pallas_call(
        kernel,
        grid=(b * h, seq_q // bq),
        in_specs=[
            pl.BlockSpec((1, bq, d), lambda bh, iq: (bh, iq, 0)),
            # GQA in the index map: q-head bh%h -> kv-head (bh%h)//group
            pl.BlockSpec(
                (1, seq_kv, d),
                lambda bh, iq: ((bh // h) * hk + (bh % h) // group, 0, 0),
            ),
            pl.BlockSpec(
                (1, seq_kv, d),
                lambda bh, iq: ((bh // h) * hk + (bh % h) // group, 0, 0),
            ),
        ],
        out_specs=pl.BlockSpec((1, bq, d), lambda bh, iq: (bh, iq, 0)),
        out_shape=jax.ShapeDtypeStruct((b * h, seq_q, d), dtype),
        scratch_shapes=[
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, 128), jnp.float32),
            pltpu.VMEM((bq, d), jnp.float32),
        ],
        compiler_params=compilation.compiler_params(
            collective=False,
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=compilation.interpret_mode(),
    )
    return jax.jit(call)


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
    block_q: int = 512,
    block_k: int = 512,
) -> jax.Array:
    """Blocked online-softmax attention (local; no collectives).

    ``q``: (B, H, Sq, D); ``k``/``v``: (B, Hkv, Skv, D) with H a multiple of
    Hkv (GQA).  ``causal`` aligns the LAST q position with the last kv
    position (decode-style suffix alignment when Sq < Skv is NOT applied —
    use :func:`decode_attention` for single-token decode).
    Golden: softmax(q k^T * scale + mask) v in f32.
    """
    b, h, seq_q, d = q.shape
    bk_, hk, seq_kv, dk = k.shape
    if (bk_, dk) != (b, d) or v.shape != k.shape:
        raise ValueError(f"shape mismatch: q={q.shape} k={k.shape} v={v.shape}")
    if h % hk:
        raise ValueError(f"GQA requires H % Hkv == 0, got {h} % {hk}")
    if causal and seq_q != seq_kv:
        raise ValueError(
            "causal prefill requires Sq == Skv (decode uses decode_attention)"
        )
    sm_scale = float(sm_scale) if sm_scale is not None else d ** -0.5
    bq = clip_block(min(block_q, seq_q), seq_q)
    bkv = clip_block(min(block_k, seq_kv), seq_kv)
    fn = _build_flash_attention(
        b, h, hk, seq_q, seq_kv, d, bq, bkv, bool(causal), sm_scale,
        float(soft_cap), jnp.dtype(q.dtype),
    )
    out = fn(
        q.reshape(b * h, seq_q, d),
        k.reshape(b * hk, seq_kv, d),
        v.reshape(b * hk, seq_kv, d),
    )
    return out.reshape(b, h, seq_q, d)


# ---------------------------------------------------------------------------
# split-KV decode


def _decode_kernel(
    bk: int,
    sm_scale: float,
    soft_cap: float,
    kv_len_ref,  # (1, 1) int32 valid kv length                  [SMEM]
    q_ref,    # (1, g, d)  VMEM — one kv-head's query group
    k_ref,    # (1, sp, d) VMEM — this split's K slice
    v_ref,    # (1, sp, d) VMEM
    o_ref,    # (1, g, d)  partial numerator (unnormalized)
    m_ref,    # (1, g, 128) f32 running max
    l_ref,    # (1, g, 128) f32 denominator
    acc_ref,  # (g, d) f32
    m_s,      # (g, 128) f32 scratch
    l_s,      # (g, 128) f32 scratch
):
    """One grid cell = (batch*kv_head, split): flash pass over the split's
    KV slice producing the (m, l, acc) softmax state — the merge across
    splits (and across ranks, in ``ops/flash_decode``) is associative
    (reference split-KV stage ``flash_decode.py:130`` + combine ``:482``)."""
    split = pl.program_id(1)
    sp = k_ref.shape[1]
    kv_len = kv_len_ref[0, 0]
    m_s[...] = jnp.full_like(m_s, _NEG_INF)
    l_s[...] = jnp.zeros_like(l_s)
    acc_ref[...] = jnp.zeros_like(acc_ref)
    q = q_ref[0].astype(jnp.float32) * sm_scale  # (g, d)

    def body(j, _):
        k = k_ref[0, pl.ds(j * bk, bk)].astype(jnp.float32)
        v = v_ref[0, pl.ds(j * bk, bk)].astype(jnp.float32)
        s = jax.lax.dot_general(
            q, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # (g, bk)
        if soft_cap:
            s = jnp.tanh(s / soft_cap) * soft_cap
        kpos = split * sp + j * bk + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1
        )
        s = jnp.where(kpos < kv_len, s, _NEG_INF)
        m_prev = m_s[:, :1]
        m_cur = jnp.maximum(m_prev, s.max(axis=1, keepdims=True))
        alpha = jnp.exp(m_prev - m_cur)
        # fully-masked tile: m_cur is still _NEG_INF and exp(s - m_cur)
        # would be exp(0)=1 per masked position, silently averaging V;
        # force p to 0 so an empty split contributes l=0 (and an all-empty
        # cache yields 0/0=nan rather than a plausible wrong value)
        p = jnp.where(m_cur > _NEG_INF / 2, jnp.exp(s - m_cur), 0.0)
        l_s[...] = l_s[...] * alpha + p.sum(axis=1, keepdims=True)
        acc_ref[...] = acc_ref[...] * alpha + jax.lax.dot(
            p, v, preferred_element_type=jnp.float32
        )
        m_s[...] = jnp.broadcast_to(m_cur, m_s.shape)
        return 0

    jax.lax.fori_loop(0, sp // bk, body, 0)
    # emit the state: numerator in o, statistics for the cross-split merge
    o_ref[0, 0] = acc_ref[...].astype(o_ref.dtype)
    m_ref[0, 0] = m_s[...]
    l_ref[0, 0] = l_s[...]


@functools.lru_cache(maxsize=None)
def _build_decode(b, h, hk, seq_kv, d, n_split, bk, sm_scale, soft_cap, dtype):
    group = h // hk
    sp = seq_kv // n_split
    kernel = functools.partial(_decode_kernel, bk, sm_scale, soft_cap)
    call = pl.pallas_call(
        kernel,
        grid=(b * hk, n_split),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),
            pl.BlockSpec((1, group, d), lambda bh, s: (bh, 0, 0)),
            pl.BlockSpec((1, sp, d), lambda bh, s: (bh, s, 0)),
            pl.BlockSpec((1, sp, d), lambda bh, s: (bh, s, 0)),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, group, d), lambda bh, s: (bh, s, 0, 0)),
            pl.BlockSpec((1, 1, group, 128), lambda bh, s: (bh, s, 0, 0)),
            pl.BlockSpec((1, 1, group, 128), lambda bh, s: (bh, s, 0, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b * hk, n_split, group, d), jnp.float32),
            jax.ShapeDtypeStruct((b * hk, n_split, group, 128), jnp.float32),
            jax.ShapeDtypeStruct((b * hk, n_split, group, 128), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((group, d), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
            pltpu.VMEM((group, 128), jnp.float32),
        ],
        compiler_params=compilation.compiler_params(
            collective=False,
            dimension_semantics=("parallel", "arbitrary"),
        ),
        interpret=compilation.interpret_mode(),
    )
    return jax.jit(call)


def decode_attention_state(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_len: jax.Array | int,
    *,
    n_split: int = 1,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
    block_k: int = 512,
):
    """Split-KV decode pass returning the mergeable softmax state.

    ``q``: (B, H, D) single decode token; ``k``/``v``: (B, Hkv, Skv, D)
    cache (positions >= ``kv_len`` masked).  Returns ``(num, m, l)`` with
    ``num``: (B, H, n_split, D) unnormalized numerators, ``m``/``l``:
    (B, H, n_split) statistics.  Merging over any set of states (splits or
    ranks) with :func:`merge_decode_states` then dividing gives exact
    attention — associativity is what the distributed flash-decode rides.
    """
    b, h, d = q.shape
    bk_, hk, seq_kv, dk = k.shape
    if (bk_, dk) != (b, d) or v.shape != k.shape:
        raise ValueError(f"shape mismatch: q={q.shape} k={k.shape} v={v.shape}")
    if h % hk:
        raise ValueError(f"GQA requires H % Hkv == 0, got {h} % {hk}")
    if seq_kv % n_split:
        raise ValueError(f"Skv={seq_kv} not divisible by n_split={n_split}")
    group = h // hk
    sm_scale = float(sm_scale) if sm_scale is not None else d ** -0.5
    sp = seq_kv // n_split
    bk = clip_block(min(block_k, sp), sp)
    fn = _build_decode(
        b, h, hk, seq_kv, d, n_split, bk, sm_scale, float(soft_cap),
        jnp.dtype(q.dtype),
    )
    kv_len = jnp.full((1, 1), kv_len, jnp.int32)
    num, m, l = fn(
        kv_len,
        q.reshape(b * hk, group, d),
        k.reshape(b * hk, seq_kv, d),
        v.reshape(b * hk, seq_kv, d),
    )
    num = num.reshape(b, hk, n_split, group, d).transpose(0, 1, 3, 2, 4)
    m = m[..., 0].reshape(b, hk, n_split, group).transpose(0, 1, 3, 2)
    l = l[..., 0].reshape(b, hk, n_split, group).transpose(0, 1, 3, 2)
    return (
        num.reshape(b, h, n_split, d),
        m.reshape(b, h, n_split),
        l.reshape(b, h, n_split),
    )


def merge_decode_states(num, m, l):
    """Combine split-KV softmax states over the split axis (reference
    inter-rank combine ``flash_decode.py:482``): rescale each partial
    numerator and denominator by exp(m_i - m*) and sum.  ``num``:
    (..., S, D); ``m``/``l``: (..., S).  Returns (num, m, l) with the split
    axis reduced to size 1 — associative, so states may be merged in any
    grouping (splits first, then ranks)."""
    m_star = m.max(axis=-1, keepdims=True)            # (..., 1)
    scale = jnp.exp(m - m_star)                       # (..., S)
    num = (num * scale[..., None]).sum(axis=-2, keepdims=True)
    l = (l * scale).sum(axis=-1, keepdims=True)
    return num, m_star, l


def decode_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_len: jax.Array | int,
    *,
    n_split: int = 1,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
) -> jax.Array:
    """Single-token decode attention over a (possibly padded) KV cache.

    Thin entry over :func:`decode_attention_state` + merge + normalize;
    returns (B, H, D).
    """
    num, m, l = decode_attention_state(
        q, k, v, kv_len, n_split=n_split, sm_scale=sm_scale, soft_cap=soft_cap
    )
    num, _, l = merge_decode_states(num, m, l)
    out = num[..., 0, :] / l[..., 0][..., None]
    return out.astype(q.dtype)
