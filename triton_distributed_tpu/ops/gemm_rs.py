"""Fused GEMM-ReduceScatter: the mirror image of AG-GEMM.

Reference: ``python/triton_dist/kernels/nvidia/gemm_reduce_scatter.py``
(producer persistent GEMM writes tiles and ``notify``s per-tile barriers
``kernel_gemm_rs_producer_persistent:130``; consumer RS; host entry
``gemm_rs:576``) + the paired ring reduce in ``reduce_scatter.py:688-882``.

TPU design — one kernel per device interleaving three activities:

1. blocked matmul (inner ``emit_pipeline``) of the output chunk that must
   leave next, in ring order starting with the chunk that travels farthest
   (rank me-1), so compute runs ahead of the wire;
2. ring forwarding: received partial + freshly computed local contribution
   are combined by a tiled add pipeline and pushed right — each chunk visits
   every rank once (bandwidth-optimal, like the reference ring);
3. the matmul of step s overlaps the in-flight transfer of step s-1 —
   compute-communication overlap without a producer stream.

Computes ``ReduceScatter_M(A[M, K_loc] @ B_loc[K_loc, N])`` — the
row-parallel half of a TP layer: A is K-sharded, B row-sharded, the M-sharded
sum comes out.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..comm import ring
from ..core import compilation
from ..core.mesh import TP_AXIS
from ..core.utils import clip_block
from ..lang import primitives as dl
from ..lang.primitives import Team
from . import blocks


@dataclasses.dataclass(frozen=True)
class GemmRsConfig:
    bm: int = 1024
    bn: int = 1024
    bk: int = 512

    def clip(self, m_loc: int, k_loc: int, n_dim: int) -> "GemmRsConfig":
        return GemmRsConfig(
            bm=clip_block(self.bm, m_loc), bn=clip_block(self.bn, n_dim),
            bk=clip_block(self.bk, k_loc),
        )


def _gemm_rs_kernel(
    team: Team,
    m_loc: int,
    k_loc: int,
    n_dim: int,
    cfg: GemmRsConfig,
    out_dtype,
    a_ref,       # (n*m_loc, k_loc) local A (K-shard)          [ANY]
    b_ref,       # (k_loc, n) local B (row shard)              [ANY]
    out_ref,     # (m_loc, n) reduced output chunk             [ANY]
    mm_buf,      # (2, m_loc, n) fresh local contributions     [HBM scratch]
    recv_buf,    # (2, m_loc, n) incoming partials             [HBM scratch]
    send_buf,    # (2, m_loc, n) outgoing accumulated          [HBM scratch]
    send_sems,   # (2,) per-parity send completion (see reduce_scatter.py)
    recv_sems,   # (2,)
    ack_sems,    # (2,) consumption credits (REGULAR)
    acc_ref,     # (bm, bn) f32                                 [VMEM scratch]
):
    me, n = team.rank(), team.size
    left, right = team.neighbor_ranks()
    left_id, right_id = team.device_id(left), team.device_id(right)

    mm = blocks.make_matmul_pipeline(
        m_loc, n_dim, k_loc, cfg.bm, cfg.bn, cfg.bk, out_dtype
    )
    add = blocks.make_add_pipeline(m_loc, n_dim, cfg.bm, cfg.bn)

    def a_chunk(c):
        return a_ref.at[pl.ds(c * m_loc, m_loc)]

    dl.collective_prologue(team, neighbors_only=True)

    # step 0: matmul the chunk that travels farthest; its raw value IS the
    # step-0 payload (no partial to add yet)
    j0 = jax.lax.rem(me + n - 1, n)
    mm(a_chunk(j0), b_ref, mm_buf.at[0], scratches=[acc_ref])
    dl.remote_copy(mm_buf.at[0], recv_buf.at[0], send_sems.at[0],
                   recv_sems.at[0], right_id)

    for s in range(1, n):
        j = jax.lax.rem(me + n - s - 1, n)
        slot_in = (s - 1) % 2
        slot_out = s % 2
        if s == 2:
            # mm is about to rewrite mm_buf[0], whose step-0 payload may
            # still be on the wire (the only send ever issued from mm_buf)
            dl.wait_send(mm_buf.at[0], send_sems.at[0])
        # local contribution for chunk j — INDEPENDENT of the in-flight
        # transfer s-1, so the MXU hides the wire time (the whole point)
        mm(a_chunk(j), b_ref, mm_buf.at[slot_out], scratches=[acc_ref])
        dl.wait_recv(recv_buf.at[slot_in], recv_sems.at[slot_in])
        last = s == n - 1
        if last:
            add(recv_buf.at[slot_in], mm_buf.at[slot_out], out_ref)
        else:
            if s >= 3:
                # send_buf[slot_out]'s step s-2 send must be off the wire
                dl.wait_send(send_buf.at[slot_out], send_sems.at[slot_out])
            if s >= 2:
                # right must have consumed what we sent into its recv
                # slot_out two steps ago
                dl.wait(ack_sems.at[slot_out], 1)
            add(recv_buf.at[slot_in], mm_buf.at[slot_out],
                send_buf.at[slot_out])
            dl.remote_copy(send_buf.at[slot_out], recv_buf.at[slot_out],
                           send_sems.at[slot_out], recv_sems.at[slot_out],
                           right_id)
        dl.notify(ack_sems.at[slot_in], left_id)

    # Drain (counting per parity: issued minus in-loop waits).
    # n==2: only the parity-0 step-0 send is outstanding.
    # n==3: step-0's wait happened at s==2; parity-1 (step 1) outstanding.
    # n>=4: one send outstanding on each parity.
    if n == 2:
        dl.wait_send(send_buf.at[0], send_sems.at[0])
    elif n == 3:
        dl.wait_send(send_buf.at[1], send_sems.at[1])
    else:
        dl.wait_send(send_buf.at[0], send_sems.at[0])
        dl.wait_send(send_buf.at[1], send_sems.at[1])
    ring.rs_ack_drain(ack_sems, n)


@functools.lru_cache(maxsize=None)
def _build_gemm_rs(
    mesh: Mesh,
    axis: str,
    m_loc: int,
    k_loc: int,
    n_dim: int,
    dtype: jnp.dtype,
    out_dtype: jnp.dtype,
    cfg: GemmRsConfig,
):
    team = Team.of(mesh, axis)
    n = team.size
    compilation.verify_protocol("gemm_rs", n)

    from ..obs import costs

    kernel = functools.partial(
        _gemm_rs_kernel, team, m_loc, k_loc, n_dim, cfg, out_dtype
    )
    call = pl.pallas_call(
        kernel,
        # kernel cost attribution sourced from obs.costs (one flop/byte
        # truth for Mosaic, the SOL model, and the flight timeline)
        cost_estimate=costs.pallas_cost(
            costs.gemm_rs(m_loc, k_loc, n_dim, n, dtype, out_dtype)),
        out_shape=jax.ShapeDtypeStruct((m_loc, n_dim), out_dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[
            pltpu.HBM((2, m_loc, n_dim), out_dtype),
            pltpu.HBM((2, m_loc, n_dim), out_dtype),
            pltpu.HBM((2, m_loc, n_dim), out_dtype),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.DMA((2,)),
            pltpu.SemaphoreType.REGULAR((2,)),
            pltpu.VMEM((cfg.bm, cfg.bn), jnp.float32),
        ],
        compiler_params=compilation.compiler_params(
            collective=True,
            collective_id=compilation.collective_id("gemm_rs"),
        ),
        interpret=compilation.interpret_mode(),
    )
    return compilation.jit_shard_map(
        call, mesh,
        in_specs=(P(None, axis), P(axis, None)),
        out_specs=P(axis, None),
    )


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2, 3))
def _gemm_rs_core(mesh, axis, cfg, out_dtype, a, b):
    """Differentiable n>1 core.  Adjoint duality with ``ag_gemm``: the
    ReduceScatter's transpose is an AllGather, so d/dA runs the other
    fused op and the backward pass overlaps its wire exactly like the
    forward."""
    n = mesh.shape[axis]
    fn = _build_gemm_rs(
        mesh, axis, a.shape[0] // n, a.shape[1] // n, b.shape[1],
        jnp.dtype(a.dtype), out_dtype, cfg,
    )
    return fn(a, b)


def _gemm_rs_fwd(mesh, axis, cfg, out_dtype, a, b):
    return _gemm_rs_core(mesh, axis, cfg, out_dtype, a, b), (a, b)


def _gemm_rs_bwd(mesh, axis, cfg, out_dtype, res, dout):
    from ..comm.allgather import all_gather
    from .ag_gemm import ag_gemm

    a, b = res
    # dA = dOut @ B^T: dOut is row-scattered, so its adjoint gathers —
    # exactly the fused AllGather-GEMM
    da = ag_gemm(dout, b.T, mesh, axis, out_dtype=a.dtype)
    # dB = A^T @ dOut: gather the scattered rows once, local K-shard GEMM
    ag_dout = all_gather(dout, mesh, axis)

    def local(ar, ag):
        return jnp.dot(ar.T, ag,
                       preferred_element_type=jnp.float32).astype(b.dtype)

    db = compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(None, axis), P(None, None)),
        out_specs=P(axis, None),
    )(a, ag_dout)
    return da, db


_gemm_rs_core.defvjp(_gemm_rs_fwd, _gemm_rs_bwd)


def gemm_rs(
    a: jax.Array,
    b: jax.Array,
    mesh: Mesh,
    axis: str = TP_AXIS,
    *,
    config: GemmRsConfig | None = None,
    out_dtype=None,
    wire_dtype: str = "bf16",
) -> jax.Array:
    """Overlapped ``ReduceScatter(a @ b)`` (reference host entry
    ``gemm_rs:576``).

    ``a``: (M, K) sharded on dim 1 over ``axis`` (activations, K-parallel).
    ``b``: (K, N) sharded on dim 0 over ``axis`` (row-parallel weight).
    Returns (M, N) sharded on dim 0: the reduced sum, row-chunk r on rank r.

    ``wire_dtype``: "int8"/"fp8" computes the local partial and reduces
    it through the quantized exchange (``comm.quantized`` — packed
    payload + scale sidecar, f32 consumer reduce) at half the wire
    bytes; "auto" lets the contextual tuner pick per shape/ranks/wire
    class.
    """
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(a.dtype)
    n = mesh.shape[axis]

    m_tot, k_dim = a.shape
    k2, n_dim = b.shape
    if k2 != k_dim:
        raise ValueError(f"inner dims mismatch: {a.shape} @ {b.shape}")
    if n == 1:
        return jnp.dot(a, b, preferred_element_type=jnp.float32).astype(out_dtype)
    if m_tot % n or k_dim % n:
        raise ValueError(
            f"M={m_tot} and K={k_dim} must be divisible by {axis}={n}"
        )
    if wire_dtype != "bf16":
        from ..comm import quantized as _q
        from ..tune.autotuner import is_tracer as _q_is_tracer

        if wire_dtype == "auto":
            wire_dtype = _q.resolve_wire_dtype(
                "gemm_rs_wire", (m_tot, k_dim, n_dim, str(a.dtype)),
                mesh, axis,
                lambda wd: (lambda: gemm_rs(
                    a, b, mesh, axis, config=config, out_dtype=out_dtype,
                    wire_dtype=wd)),
                tracing=_q_is_tracer(a),
            )
        if wire_dtype != "bf16":
            parts = _q.stacked_partial_gemm(a, b, mesh, axis, out_dtype)
            return _q.quantized_reduce_scatter(
                parts, mesh, axis, wire_dtype=wire_dtype,
                out_dtype=out_dtype)

    if config is None:
        # transparent contextual tuning (see ops/ag_gemm.py)
        from ..tune import autotuner as _tune

        config = _tune.resolve_gemm_like(
            "gemm_rs", gemm_rs, GemmRsConfig, _tune.GEMM_RS_CAND_DIMS,
            GemmRsConfig(), a, b, mesh, axis, dict(out_dtype=out_dtype), {},
        )
    cfg = config

    m_loc, k_loc = m_tot // n, k_dim // n
    cfg = cfg.clip(m_loc, k_loc, n_dim)
    from .. import resilience
    from ..tune.autotuner import is_tracer

    core = lambda: _gemm_rs_core(mesh, axis, cfg, out_dtype, a, b)  # noqa: E731
    eager = not is_tracer(a)
    if eager and resilience.integrity.enabled():
        # consumer-side Freivalds verification (TDT_INTEGRITY=1)
        core = resilience.integrity.checked(
            "gemm_rs", core, ranks=n,
            verify=lambda out: resilience.integrity.verify_gemm(
                "gemm_rs", a, b, out))
    if eager and resilience.enabled():
        # eager calls only (see comm/allgather.py): watchdog + ladder,
        # degraded fallback = local partial GEMM + XLA ReduceScatter
        return resilience.guarded(
            "gemm_rs", core,
            family="gemm_rs", ranks=n,
            payload_bytes=m_loc * n_dim * jnp.dtype(out_dtype).itemsize * n,
            fallback=lambda: resilience.fallbacks.xla_gemm_rs(
                a, b, mesh, axis, out_dtype),
        )()
    return core()
