"""Shared inner-pipeline building blocks for overlapped ops.

One home for the MXU accumulate/flush matmul body and elementwise bodies
used by ``ag_gemm``, ``gemm_rs``, ``reduce_scatter`` and the MoE ops — the
TPU analogue of the reference's shared tile loops (the `tl.dot` hot loop in
``allgather_gemm.py:216-260`` replicated per op there; we keep one copy).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu


def _protocol_stub(kind: str):
    """Under tdt.analysis record mode the emit_pipeline bodies must not be
    built (they touch Mosaic pipeline internals and real refs); the stub
    records one compute event — reads of every input ref, a write of the
    output ref — which is all the protocol checks need from local compute.
    Returns None in normal operation."""
    from ..lang import primitives as dl

    if dl.active_recorder() is None:
        return None

    def stub(*refs, scratches=None, allocations=None):
        rec = dl.active_recorder()
        if rec is None:
            raise RuntimeError(
                "protocol-stub pipeline called outside record mode"
            )
        fl = dl._flight()
        if fl is not None:
            # flight cost attribution: the flop/byte counts of this
            # pipeline invocation, derived from the recorded regions
            fl.on_compute(kind, refs)
        rec.on_compute(kind, refs[:-1], refs[-1])

    return stub


def matmul_body(nk: int, out_dtype, a_ref, b_ref, c_ref, acc_ref):
    """Blocked matmul step with f32 accumulation.

    Grid must be (m, n, k) with k innermost so the accumulator block stays
    resident per (m, n) tile; ``acc_ref`` is a (bm, bn) f32 VMEM scratch.
    """
    k = pl.program_id(2)

    @pl.when(k == 0)
    def _():
        acc_ref[...] = jnp.zeros_like(acc_ref)

    acc_ref[...] += jnp.dot(
        a_ref[...], b_ref[...], preferred_element_type=jnp.float32
    )

    @pl.when(k == nk - 1)
    def _():
        c_ref[...] = acc_ref[...].astype(out_dtype)


def add_body(a_ref, b_ref, o_ref):
    o_ref[...] = a_ref[...] + b_ref[...]


def make_matmul_pipeline(m: int, n: int, k: int, bm: int, bn: int, bk: int,
                         out_dtype):
    """An ``emit_pipeline`` computing C[m,n] = A[m,k] @ B[k,n] blockwise.

    Call as ``pipe(a_ref, b_ref, c_ref, scratches=[acc_ref])`` with an
    (bm, bn) f32 VMEM accumulator.
    """
    stub = _protocol_stub("matmul")
    if stub is not None:
        return stub
    grid = (m // bm, n // bn, k // bk)
    return pltpu.emit_pipeline(
        functools.partial(matmul_body, grid[2], out_dtype),
        grid=grid,
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j))],
    )


def sum_body(out_dtype, *refs):
    *in_refs, o_ref = refs
    acc = in_refs[0][...].astype(jnp.float32)
    for r in in_refs[1:]:
        acc += r[...].astype(jnp.float32)
    o_ref[...] = acc.astype(out_dtype)


def make_sum_pipeline(num_in: int, m: int, n: int, bm: int, bn: int, out_dtype):
    """An ``emit_pipeline`` computing O[m,n] = sum of ``num_in`` same-shaped
    inputs with f32 accumulation (the one-shot AllReduce local reduction).

    Call as ``pipe(in0, in1, ..., out_ref)``.
    """
    stub = _protocol_stub("sum")
    if stub is not None:
        return stub
    spec = pl.BlockSpec((bm, bn), lambda i, j: (i, j))
    return pltpu.emit_pipeline(
        functools.partial(sum_body, out_dtype),
        grid=(m // bm, n // bn),
        in_specs=[spec] * num_in,
        out_specs=[spec],
    )


def swiglu_body(out_dtype, g_ref, u_ref, o_ref):
    g = g_ref[...].astype(jnp.float32)
    u = u_ref[...].astype(jnp.float32)
    o_ref[...] = (jax.nn.silu(g) * u).astype(out_dtype)


def make_swiglu_pipeline(m: int, n: int, bm: int, bn: int, out_dtype):
    """An ``emit_pipeline`` computing O[m,n] = silu(G) * U blockwise in f32
    (the gate activation between the up- and down-projections of the fused
    decode MLP megakernel, ``ops.fused_decode``).

    Call as ``pipe(g_ref, u_ref, o_ref)``.
    """
    stub = _protocol_stub("swiglu")
    if stub is not None:
        return stub
    spec = pl.BlockSpec((bm, bn), lambda i, j: (i, j))
    return pltpu.emit_pipeline(
        functools.partial(swiglu_body, out_dtype),
        grid=(m // bm, n // bn),
        in_specs=[spec, spec],
        out_specs=[spec],
    )


def make_add_pipeline(m: int, n: int, bm: int, bn: int):
    """An ``emit_pipeline`` computing O[m,n] = A + B blockwise."""
    stub = _protocol_stub("add")
    if stub is not None:
        return stub
    return pltpu.emit_pipeline(
        add_body,
        grid=(m // bm, n // bn),
        in_specs=[
            pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
            pl.BlockSpec((bm, bn), lambda i, j: (i, j)),
        ],
        out_specs=[pl.BlockSpec((bm, bn), lambda i, j: (i, j))],
    )


def copy_body(a_ref, o_ref):
    o_ref[...] = a_ref[...]


def make_copy_pipeline(m: int, n: int, bm: int, bn: int):
    """An ``emit_pipeline`` computing O[m,n] = A blockwise (the persistent
    decode loop's final hidden-state writeback, ``ops.persistent_decode``)."""
    stub = _protocol_stub("copy")
    if stub is not None:
        return stub
    spec = pl.BlockSpec((bm, bn), lambda i, j: (i, j))
    return pltpu.emit_pipeline(
        copy_body, grid=(m // bm, n // bn),
        in_specs=[spec], out_specs=[spec],
    )


def rmsnorm_body(eps: float, out_dtype, x_ref, w_ref, o_ref):
    xf = x_ref[...].astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + eps)
    o_ref[...] = (out * w_ref[...].astype(jnp.float32)).astype(out_dtype)


def make_rmsnorm_pipeline(m: int, n: int, bm: int, eps: float, out_dtype):
    """An ``emit_pipeline`` computing O[m,n] = rms_norm(X) * W blockwise
    over WHOLE rows (the norm reduces the full feature axis, so blocks
    are (bm, n) — fine at decode widths), mirroring
    ``layers.norm.rms_norm`` numerics (f32 math, cast back).

    Call as ``pipe(x_ref, w_ref, o_ref)`` with ``w_ref`` a (1, n) view
    (e.g. one layer's slice of a stacked (L, n) norm-weight array) —
    the residual/norm glue fused between the persistent decode loop's
    chained stages (``ops.persistent_decode``).
    """
    stub = _protocol_stub("rmsnorm")
    if stub is not None:
        return stub
    return pltpu.emit_pipeline(
        functools.partial(rmsnorm_body, eps, out_dtype),
        grid=(m // bm,),
        in_specs=[
            pl.BlockSpec((bm, n), lambda i: (i, 0)),
            pl.BlockSpec((1, n), lambda i: (0, 0)),
        ],
        out_specs=[pl.BlockSpec((bm, n), lambda i: (i, 0))],
    )
