"""Distributed (sequence-parallel) flash-decode.

Reference: ``python/triton_dist/kernels/nvidia/flash_decode.py`` — per-rank
GQA split-KV decode stage (``:130``), inter-rank softmax-state combine
(``kernel_inter_rank_flash_decode:482``), consumed by
``layers/nvidia/sp_flash_decode_layer.py:44``.  Each rank owns a slice of
the KV cache along the sequence axis, computes partial attention over its
slice, and the partials are combined exactly via the associative
(numerator, max, denominator) merge.

TPU design split:

- the heavy, bandwidth-bound work — streaming the local KV slice — is the
  Pallas split-KV kernel (``ops/attention.decode_attention_state``);
- the cross-rank combine exchanges only the tiny state pytree
  ((B, H, D) numerator + two (B, H) scalars per rank, a few KB), which is
  latency-bound: that is XLA-collective territory (``lax.all_gather`` over
  the mesh axis), not hand-rolled DMA — the reference needs a custom
  inter-rank kernel only because NVSHMEM symmetric staging is its one
  cross-GPU path (SURVEY.md section 7).

Ranks whose slice is entirely beyond ``kv_len`` contribute a zero
denominator and drop out of the merge (see the masked-tile guard in
``_decode_kernel``).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from ..core import compilation
from ..core.mesh import SP_AXIS
from .attention import (
    decode_attention,
    decode_attention_state,
    merge_decode_states,
    paged_decode_attention,
    paged_decode_attention_state,
    safe_normalize_decode,
)


@functools.lru_cache(maxsize=None)
def _build_sp_flash_decode(
    mesh: Mesh,
    axis: str,
    shapes_key,   # (b, h, hk, s_loc, d, n_split, sm_scale, soft_cap, dtype)
):
    b, h, hk, s_loc, d, n_split, sm_scale, soft_cap, dtype = shapes_key

    def local_fn(q, k_loc, v_loc, kv_len):
        r = jax.lax.axis_index(axis)
        # this rank covers absolute kv positions [r*s_loc, (r+1)*s_loc);
        # kv_len is (B,) — ragged per-sequence lengths clip per rank
        len_loc = jnp.clip(kv_len - r * s_loc, 0, s_loc)
        num, m, l = decode_attention_state(
            q, k_loc, v_loc, len_loc,
            n_split=n_split, sm_scale=sm_scale, soft_cap=soft_cap,
        )
        num, m, l = merge_decode_states(num, m, l)     # splits -> one state
        # tiny state exchange: (n, B, H, D) + 2x (n, B, H)
        nums = jax.lax.all_gather(num[..., 0, :], axis)
        ms = jax.lax.all_gather(m[..., 0], axis)
        ls = jax.lax.all_gather(l[..., 0], axis)
        num, _, l = merge_decode_states(
            jnp.moveaxis(nums, 0, -2), jnp.moveaxis(ms, 0, -1),
            jnp.moveaxis(ls, 0, -1),
        )
        return safe_normalize_decode(
            num[..., 0, :], l[..., 0][..., None], dtype
        )

    return compilation.jit_shard_map(
        local_fn, mesh,
        in_specs=(
            P(None, None, None),        # q replicated
            P(None, None, axis, None),  # K cache: sequence-sharded
            P(None, None, axis, None),  # V cache
            P(None),                    # kv_len replicated
        ),
        out_specs=P(None, None, None),
    )


def sp_flash_decode(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    kv_len: jax.Array | int,
    mesh: Mesh,
    axis: str = SP_AXIS,
    *,
    n_split: int | None = None,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
) -> jax.Array:
    """Decode attention over a sequence-sharded KV cache (reference host
    entry ``flash_decode.py:537-587`` + ``sp_flash_decode_layer.py:44``).

    ``q``: (B, H, D) replicated decode token; ``k``/``v``: (B, Hkv, S, D)
    global cache sharded on the sequence dim over ``axis``; ``kv_len``: the
    GLOBAL number of valid cache positions — a scalar, or a (B,) int32
    array of RAGGED per-sequence lengths.  Returns (B, H, D) replicated.
    Golden: full-cache ``decode_attention`` on one device.
    """
    n = mesh.shape[axis]
    b, h, d = q.shape
    _, hk, s_tot, _ = k.shape
    if v.shape != k.shape:
        raise ValueError(f"shape mismatch: k={k.shape} v={v.shape}")
    if n == 1:
        return decode_attention(
            q, k, v, kv_len, n_split=n_split, sm_scale=sm_scale,
            soft_cap=soft_cap,
        )
    if s_tot % n:
        raise ValueError(f"cache seq {s_tot} not divisible by {axis}={n}")
    s_loc = s_tot // n
    if n_split is None:
        from .attention import auto_n_split

        n_split = auto_n_split(s_loc)
    if n_split > 1 and s_loc % n_split:
        raise ValueError(
            f"local cache {s_loc} not divisible by n_split={n_split}"
        )
    sm_scale = float(sm_scale) if sm_scale is not None else d ** -0.5
    fn = _build_sp_flash_decode(
        mesh, axis,
        (b, h, hk, s_loc, d, n_split, sm_scale, float(soft_cap),
         jnp.dtype(q.dtype)),
    )
    kv_len = jnp.broadcast_to(jnp.asarray(kv_len, jnp.int32), (b,))
    return fn(q, k, v, kv_len)


@functools.lru_cache(maxsize=None)
def _build_sp_paged_flash_decode(
    mesh: Mesh,
    axis: str,
    shapes_key,   # (b, h, hk, ps, mp_loc, d, sm_scale, soft_cap, dtype,
                  #  quantized)
):
    (b, h, hk, ps, mp_loc, d, sm_scale, soft_cap, dtype,
     quantized) = shapes_key
    s_loc = mp_loc * ps

    def local_fn(q, pool_k_loc, pool_v_loc, table_loc, seq_lens,
                 *scales):
        # ``scales``: (kscale_loc, vscale_loc) on the quantized build
        # only — the bf16 hot path ships no scale operands at all
        r = jax.lax.axis_index(axis)
        # this rank's pages cover absolute positions [r*s_loc, (r+1)*s_loc);
        # seq_lens is RAGGED per sequence — clip per rank per sequence
        len_loc = jnp.clip(seq_lens - r * s_loc, 0, s_loc)
        num, m, l = paged_decode_attention_state(
            q, pool_k_loc, pool_v_loc, table_loc[0], len_loc,
            sm_scale=sm_scale, soft_cap=soft_cap,
            k_scale=scales[0] if quantized else None,
            v_scale=scales[1] if quantized else None,
        )
        num, m, l = merge_decode_states(num, m, l)     # pages -> one state
        nums = jax.lax.all_gather(num[..., 0, :], axis)
        ms = jax.lax.all_gather(m[..., 0], axis)
        ls = jax.lax.all_gather(l[..., 0], axis)
        num, _, l = merge_decode_states(
            jnp.moveaxis(nums, 0, -2), jnp.moveaxis(ms, 0, -1),
            jnp.moveaxis(ls, 0, -1),
        )
        return safe_normalize_decode(
            num[..., 0, :], l[..., 0][..., None], dtype
        )

    in_specs = [
        P(None, None, None),                  # q replicated
        P(axis, None, None, None),            # page pool: rank-owned pages
        P(axis, None, None, None),
        P(axis, None, None),                  # per-rank local block tables
        P(None),                              # global ragged lengths
    ]
    if quantized:
        in_specs += [P(axis, None), P(axis, None)]  # (page, head) scales
    return compilation.jit_shard_map(
        local_fn, mesh, in_specs=tuple(in_specs),
        out_specs=P(None, None, None),
    )


def sp_paged_flash_decode(
    q: jax.Array,
    pool_k: jax.Array,
    pool_v: jax.Array,
    block_table: jax.Array,
    seq_lens: jax.Array,
    mesh: Mesh,
    axis: str = SP_AXIS,
    *,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
) -> jax.Array:
    """Decode attention over a sequence-sharded PAGED cache (the reference's
    production decode layer: ``sp_flash_decode_layer.py:83-108`` threads
    ``block_table`` into ``gqa_fwd_batch_decode``).

    ``k_scale``/``v_scale``: (P_total, Hkv) f32 per-(page, head) scales
    of an int8 pool — the fused-dequant path of the quantized KV cache;
    the cross-rank state merge is unchanged (softmax states are f32
    regardless of the pool dtype).

    Each rank owns a page pool holding its slice of the sequence axis and a
    LOCAL block table; the cross-rank softmax-state merge is identical to
    :func:`sp_flash_decode`.

    ``q``: (B, H, D) replicated; ``pool_k``/``pool_v``: global
    (n * P_loc, Hkv, page_size, D) sharded on the page dim over ``axis``;
    ``block_table``: global (n, B, max_pages_loc) — rank r's (B, mp) table
    in its LOCAL pool page ids, rank r covering absolute positions
    ``[r * mp * page_size, (r+1) * mp * page_size)``; ``seq_lens``: (B,)
    int32 GLOBAL ragged lengths, replicated.  Returns (B, H, D) replicated.
    Golden: per-sequence contiguous materialization + ``decode_attention``.
    """
    n = mesh.shape[axis]
    b, h, d = q.shape
    p_tot, hk, ps, dk = pool_k.shape
    if pool_v.shape != pool_k.shape or dk != d:
        raise ValueError(
            f"shape mismatch: q={q.shape} pool_k={pool_k.shape} "
            f"pool_v={pool_v.shape}"
        )
    if n == 1:
        table = block_table[0] if block_table.ndim == 3 else block_table
        return paged_decode_attention(
            q, pool_k, pool_v, table, seq_lens,
            sm_scale=sm_scale, soft_cap=soft_cap,
            k_scale=k_scale, v_scale=v_scale,
        )
    if block_table.shape[0] != n or block_table.shape[1] != b:
        raise ValueError(
            f"block_table {block_table.shape} must be (n, B, max_pages_loc)"
            f" with n={n}, B={b}"
        )
    if p_tot % n:
        raise ValueError(f"page pool {p_tot} not divisible by {axis}={n}")
    mp_loc = block_table.shape[2]
    sm_scale = float(sm_scale) if sm_scale is not None else d ** -0.5
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("pass both k_scale and v_scale or neither")
    fn = _build_sp_paged_flash_decode(
        mesh, axis,
        (b, h, hk, ps, mp_loc, d, sm_scale, float(soft_cap),
         jnp.dtype(q.dtype), quantized),
    )
    args = [q, pool_k, pool_v, block_table.astype(jnp.int32),
            seq_lens.astype(jnp.int32)]
    if quantized:
        args += [k_scale.astype(jnp.float32), v_scale.astype(jnp.float32)]
    return fn(*args)
