"""Decode megakernel: the per-layer decode chain collapsed into persistent,
semaphore-chained Pallas kernels (ROADMAP item 2).

A decode step through the per-kernel path issues, per layer, a long chain
of separate dispatches — qkv projection, qk-norm, rope, the ragged
KV-append scatter, ``paged_decode_attention``, a row-parallel o-proj
reduce, the MLP up-projection, and another reduce (``models/qwen.py``) —
and every kernel boundary is a host-visible launch where communication
cannot overlap the next kernel's compute.  That is exactly the "hidden
serialization" megakernel communication compilation (arXiv:2605.00686)
and T3's fused transmit-on-produce (arXiv:2401.16677) eliminate.  This
module is the TPU answer, in two fusions wired as ``decode_mode="fused"``
(:class:`~..models.qwen.Qwen3`):

- **Stage 1 — :func:`fused_attn_decode`** (local, one kernel per layer):
  qkv GEMM + qk-norm + rope + the ragged paged KV-append + block-table
  flash decode in ONE ``pallas_call``.  The page pool rides through
  ``input_output_aliases`` so the token append is an in-place DMA into
  the aliased pool instead of an XLA scatter materializing a new pool;
  pages stream through a double-buffered in-kernel DMA pipeline, and the
  freshly projected token's K/V are folded into the online softmax from
  registers — the append and the attention share one launch.

- **Stage 2 — :func:`fused_mlp_ar` / :func:`fused_linear_ar`**
  (collective, family ``fused_mlp_ar``): the MLP block (gate/up GEMM +
  SwiGLU + down-projection) chained straight into a two-shot AllReduce
  ring through device-side semaphores (``lang/primitives``) — the
  down-proj partial of ring step s computes while step s-1's chunk is on
  the wire, and control never returns to the host between the GEMM and
  the reduction.  Unlike ``ops.gemm_ar`` (which chunks M over ranks and
  therefore needs ``B % tp == 0``), the ring here chunks the OUTPUT
  column axis, so any decode batch size rides the fused path.  The
  ``linear`` variant (no SwiGLU prologue) serves the attention o-proj.

The per-kernel paths remain as the other ``decode_mode``s — the parity
reference (``tests/test_fused_decode.py``) and the fallback where the
fused constraints do not hold.  Protocol coverage: the collective kernel
is registered in ``analysis.registry`` (family ``fused_mlp_ar``,
verified at ranks {2, 4, 8} and covered by the fault matrix); tile/block
configs resolve through the contextual autotuner like the other fused
ops; ``obs.costs`` carries both families' flop/byte models so watchdog
deadlines, Mosaic cost estimates and the flight timeline agree.  See
docs/perf.md "Decode megakernel".
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..comm import ring
from ..core import compilation
from ..core.mesh import TP_AXIS
from ..core.utils import clip_block
from ..lang import primitives as dl
from ..lang.primitives import Team
from . import blocks
from .attention import _init_carry, _tile_update, safe_normalize_decode

# ---------------------------------------------------------------------------
# Stage 1: fused attention-side decode (local per rank, one kernel per layer)


@dataclasses.dataclass(frozen=True)
class FusedAttnConfig:
    """Knobs of the attention megakernel.  ``vmem_limit``: scoped VMEM
    budget (None = Mosaic default) — the per-cell working set is the
    head's qkv weight columns plus two KV page buffers, which can exceed
    the 16 MiB default at large hidden sizes."""

    vmem_limit: int | None = None


_FUSED_ATTN_VL = 100 * 2**20


def fused_attn_candidates() -> list:
    return [FusedAttnConfig(None), FusedAttnConfig(_FUSED_ATTN_VL)]


def _rms(x, w, eps: float):
    """In-kernel RMSNorm over the last axis, mirroring
    ``layers.norm.rms_norm`` (f32 math, scale in f32, cast back)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + eps)
    return (out * w.astype(jnp.float32)).astype(x.dtype)


def _rope1(x, pos, theta: float):
    """In-kernel rotate-half RoPE of ``x`` (rows, d) at one absolute
    position, mirroring ``ops.rope.apply_rope_at`` numerics."""
    d = x.shape[-1]
    half = d // 2
    inv = 1.0 / (theta ** (
        jax.lax.broadcasted_iota(jnp.float32, (1, half), 1) / half))
    ang = pos.astype(jnp.float32) * inv            # (1, half)
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1 = x[:, :half].astype(jnp.float32)
    x2 = x[:, half:].astype(jnp.float32)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1)
    return out.astype(x.dtype)


def _fused_attn_kernel(
    hk: int,
    g: int,
    d: int,
    ps: int,
    mp: int,
    theta: float,
    qk_eps,        # float | None — qk-norm epsilon (None = no norm)
    sm_scale: float,
    soft_cap: float,
    quantized: bool,
    *refs,
    # inputs: table (B*mp,) SMEM (flattened row-major); lens (B,) SMEM;
    # [kscale/vscale (rows,) f32 SMEM when quantized — per-(page, head)
    # dequant factors in pool-row order]; x (1, K) blocked per
    # batch row; wq (K, g*d) / wk (K, d) / wv (K, d) blocked per kv head
    # (three column views of the SAME wqkv array); [qn (1, d), kn (1, d)
    # when qk_eps]; pool_k/pool_v (rows, ps, d) ANY (aliased outputs).
    # outputs: out (1, 1, g, d) blocked; pool_k/pool_v aliased ANY;
    # [ktok_out/vtok_out (1, 1, d) blocked when quantized — the
    # projected token per (head, sequence), appended by the caller's
    # exact quantized scatter].
    # scratch: kbuf/vbuf (2, ps, d); ktok/vtok (1, d) pool-dtype;
    # pg_sems DMA (2, 2); tok_sems DMA (2,)
):
    refs = list(refs)
    table_ref, lens_ref = refs[:2]
    del refs[:2]
    if quantized:
        kscale_ref, vscale_ref = refs[:2]
        del refs[:2]
    else:
        kscale_ref = vscale_ref = None
    x_ref, wq_ref, wk_ref, wv_ref = refs[:4]
    del refs[:4]
    if qk_eps is not None:
        qn_ref, kn_ref = refs[:2]
        del refs[:2]
    else:
        qn_ref = kn_ref = None
    _pk_in, _pv_in, out_ref, pool_k, pool_v = refs[:5]
    del refs[:5]
    if quantized:
        ktok_out, vtok_out = refs[:2]
        del refs[:2]
    else:
        ktok_out = vtok_out = None
    kbuf, vbuf, ktok, vtok, pg_sems, tok_sems = refs
    h_i = pl.program_id(0)          # local kv head (outer: weight blocks
    b_i = pl.program_id(1)          # stay resident across the batch loop)
    pos = lens_ref[b_i]
    x = x_ref[...]                                   # (1, K) storage dtype

    # --- qkv projection for this (sequence, kv head) cell ---------------
    q = jax.lax.dot(x, wq_ref[...],
                    preferred_element_type=jnp.float32).astype(x.dtype)
    k_new = jax.lax.dot(x, wk_ref[...],
                        preferred_element_type=jnp.float32).astype(x.dtype)
    v_new = jax.lax.dot(x, wv_ref[...],
                        preferred_element_type=jnp.float32).astype(x.dtype)
    q = q.reshape(g, d)
    if qk_eps is not None:
        q = _rms(q, qn_ref[...], qk_eps)
        k_new = _rms(k_new, kn_ref[...], qk_eps)
    q = _rope1(q, pos, theta)
    k_new = _rope1(k_new, pos, theta)

    if quantized:
        # int8 pool: the kernel cannot grow the target page's (page,
        # head) scale in place without re-encoding its residents, so the
        # token travels OUT full-precision and the caller's exact
        # dequant-merge-requant scatter appends it (one page per
        # sequence; ``kv_cache.append_layer_quantized``).  THIS step's
        # attention still folds the token from registers below —
        # numerics identical to append-then-attend.
        ktok_out[0] = k_new
        vtok_out[0] = v_new
    else:
        # --- ragged append: DMA the token into its page slot in place ---
        # (the pool is ALIASED in/out, so only this (1, d) slot moves —
        # the per-kernel path's XLA scatter rewrites pool rows instead).
        # The write is drained before the page reads below so the read
        # DMAs can never race it; the slot itself is masked out of the
        # attention (kpos < pos), matching append-then-attend-at-pos+1
        # numerics.
        pg = jnp.minimum(pos // ps, mp - 1)   # clamped like the jit scatter
        row = table_ref[b_i * mp + pg] * hk + h_i
        off = pos % ps
        ktok[...] = k_new.astype(ktok.dtype)
        vtok[...] = v_new.astype(vtok.dtype)
        wk_copy = pltpu.make_async_copy(
            ktok, pool_k.at[row, pl.ds(off, 1)], tok_sems.at[0])
        wv_copy = pltpu.make_async_copy(
            vtok, pool_v.at[row, pl.ds(off, 1)], tok_sems.at[1])
        wk_copy.start()
        wv_copy.start()
        wk_copy.wait()
        wv_copy.wait()

    # --- block-table flash decode over the cached prefix [0, pos) -------
    # (int8 pages stream at HALF the HBM bytes; their per-(page, head)
    # scale dequantizes inside the tile update — two scalar multiplies,
    # no full-precision pool ever materialized)
    q_s = (q.astype(jnp.float32) * sm_scale).astype(q.dtype)
    npages = jnp.minimum((pos + ps - 1) // ps, mp)

    def page_dma(slot, j):
        r = table_ref[b_i * mp + j] * hk + h_i
        return (
            pltpu.make_async_copy(pool_k.at[r], kbuf.at[slot],
                                  pg_sems.at[slot, 0]),
            pltpu.make_async_copy(pool_v.at[r], vbuf.at[slot],
                                  pg_sems.at[slot, 1]),
        )

    @pl.when(npages > 0)
    def _():
        ck, cv = page_dma(0, 0)
        ck.start()
        cv.start()

    def body(j, carry):
        @pl.when(j + 1 < npages)
        def _():
            ck, cv = page_dma((j + 1) % 2, j + 1)
            ck.start()
            cv.start()

        ck, cv = page_dma(j % 2, j)
        ck.wait()
        cv.wait()
        k_t = kbuf[j % 2]
        v_t = vbuf[j % 2]
        ks = vs = None
        if quantized:
            r_j = table_ref[b_i * mp + j] * hk + h_i
            ks = kscale_ref[r_j]
            vs = vscale_ref[r_j]
        kpos = j * ps + jax.lax.broadcasted_iota(jnp.int32, (g, ps), 1)
        return _tile_update(q_s, k_t, v_t, kpos < pos, soft_cap, carry,
                            k_scale=ks, v_scale=vs)

    carry = jax.lax.fori_loop(0, npages, body, _init_carry(g, d))

    # --- fold the just-projected token from registers -------------------
    # (an 8-row tile keeps the score matmul sublane-aligned; rows past
    # the first are masked)
    kt8 = jnp.concatenate([k_new, jnp.zeros((7, d), k_new.dtype)], axis=0)
    vt8 = jnp.concatenate([v_new, jnp.zeros((7, d), v_new.dtype)], axis=0)
    tok_mask = jax.lax.broadcasted_iota(jnp.int32, (g, 8), 1) == 0
    m1, l1, acc1 = _tile_update(q_s, kt8, vt8, tok_mask, soft_cap, carry)
    out_ref[0, 0] = safe_normalize_decode(acc1, l1, out_ref.dtype)


@functools.lru_cache(maxsize=None)
def _build_fused_attn(b, k_dim, hk, g, d, pool_rows, ps, mp, theta, qk_eps,
                      sm_scale, soft_cap, dtype, pool_dtype, cfg,
                      quantized=False):
    kernel = functools.partial(
        _fused_attn_kernel, hk, g, d, ps, mp, theta, qk_eps, sm_scale,
        soft_cap, quantized,
    )
    # three column views of the ONE (K, qkv_cols) wqkv array: q columns
    # [h*g*d, (h+1)*g*d), k at (h_loc + h)*d, v at (h_loc + hk + h)*d —
    # block indices address multiples of the block width, so the k/v maps
    # offset by whole q-section widths expressed in d-wide blocks
    h_loc = hk * g
    in_specs = [
        pl.BlockSpec(memory_space=pltpu.SMEM),            # table
        pl.BlockSpec(memory_space=pltpu.SMEM),            # lens
    ]
    if quantized:
        in_specs += [
            pl.BlockSpec(memory_space=pltpu.SMEM),        # k_scale rows
            pl.BlockSpec(memory_space=pltpu.SMEM),        # v_scale rows
        ]
    in_specs += [
        pl.BlockSpec((1, k_dim), lambda h, bi: (bi, 0)),  # x row
        pl.BlockSpec((k_dim, g * d), lambda h, bi: (0, h)),
        pl.BlockSpec((k_dim, d), lambda h, bi: (0, h_loc + h)),
        pl.BlockSpec((k_dim, d), lambda h, bi: (0, h_loc + hk + h)),
    ]
    if qk_eps is not None:
        in_specs += [
            pl.BlockSpec((1, d), lambda h, bi: (0, 0)),   # q_norm
            pl.BlockSpec((1, d), lambda h, bi: (0, 0)),   # k_norm
        ]
    pool_spec = pl.BlockSpec(memory_space=pl.ANY)
    in_specs += [pool_spec, pool_spec]
    n_in = len(in_specs)
    out_specs = [
        pl.BlockSpec((1, 1, g, d), lambda h, bi: (h, bi, 0, 0)),
        pool_spec,
        pool_spec,
    ]
    out_shape = [
        jax.ShapeDtypeStruct((hk, b, g, d), dtype),
        jax.ShapeDtypeStruct((pool_rows, ps, d), pool_dtype),
        jax.ShapeDtypeStruct((pool_rows, ps, d), pool_dtype),
    ]
    if quantized:
        # the projected token per (head, sequence) — the caller appends
        # it through the exact quantized scatter (see kernel docstring)
        tok_spec = pl.BlockSpec((1, 1, d), lambda h, bi: (h, bi, 0))
        out_specs += [tok_spec, tok_spec]
        out_shape += [jax.ShapeDtypeStruct((hk, b, d), dtype),
                      jax.ShapeDtypeStruct((hk, b, d), dtype)]
    from ..obs import costs

    call = pl.pallas_call(
        kernel,
        grid=(hk, b),
        in_specs=in_specs,
        out_specs=out_specs,
        out_shape=out_shape,
        # the pool travels in place: the token append touches one (1, d)
        # slot of the aliased buffer instead of rewriting the pool
        # (quantized: the aliased pools pass through untouched)
        input_output_aliases={n_in - 2: 1, n_in - 1: 2},
        scratch_shapes=[
            pltpu.VMEM((2, ps, d), pool_dtype),
            pltpu.VMEM((2, ps, d), pool_dtype),
            pltpu.VMEM((1, d), pool_dtype),
            pltpu.VMEM((1, d), pool_dtype),
            pltpu.SemaphoreType.DMA((2, 2)),
            pltpu.SemaphoreType.DMA((2,)),
        ],
        cost_estimate=costs.pallas_cost(
            costs.fused_attn_decode(b, k_dim, h_loc, hk, mp * ps, d,
                                    pool_dtype)),
        compiler_params=compilation.compiler_params(
            collective=False,
            dimension_semantics=("arbitrary", "arbitrary"),
            vmem_limit_bytes=cfg.vmem_limit,
        ),
        interpret=compilation.interpret_mode(),
    )
    return jax.jit(call)


def fused_attn_decode(
    x: jax.Array,
    wqkv: jax.Array,
    q_norm: jax.Array | None,
    k_norm: jax.Array | None,
    pool_k: jax.Array,
    pool_v: jax.Array,
    block_table: jax.Array,
    seq_lens: jax.Array,
    *,
    rope_theta: float = 10_000.0,
    qk_eps: float | None = None,
    sm_scale: float | None = None,
    soft_cap: float = 0.0,
    config: FusedAttnConfig | None = None,
    k_scale: jax.Array | None = None,
    v_scale: jax.Array | None = None,
):
    """One layer's fused attention-side decode step (LOCAL per rank — call
    inside the TP ``shard_map`` like ``paged_decode_attention``).

    ``x``: (B, K) replicated activations; ``wqkv``: (K, (Hq+2Hkv)·D) this
    rank's column shard (layout ``[q | k | v]``); ``pool_k``/``pool_v``:
    (P, Hkv, page_size, D) page pools; ``block_table``: (B, max_pages);
    ``seq_lens``: (B,) ragged lengths.  Returns ``(out, pool_k, pool_v)``
    with ``out``: (B, Hq·D) attention outputs (pre o-proj) and the pools
    updated IN PLACE (aliased) with the new token at each sequence's
    position.  Golden: the per-kernel chain in
    ``Qwen3._attn_decode_paged`` (qkv → norm → rope → ``append_paged``
    scatter → ``paged_decode_attention``).

    **Quantized pools** (``k_scale``/``v_scale`` (P, Hkv) f32, int8
    pools): pages stream with dequantization fused into the flash loop
    (half the cache bandwidth), the in-kernel append is SKIPPED (the
    kernel cannot re-encode a page whose scale grows), and the return
    becomes ``(out, pool_k, pool_v, k_tok, v_tok)`` with the projected
    token (B, Hkv, D) full-precision — append it with
    ``kv_cache.append_layer_quantized`` after the kernel (one page per
    sequence; this step's attention already folded the token from
    registers, so numerics match append-then-attend).
    """
    b, k_dim = x.shape
    p, hk, ps, d = pool_k.shape
    if pool_v.shape != pool_k.shape:
        raise ValueError(
            f"pool shape mismatch: {pool_k.shape} vs {pool_v.shape}")
    qkv_cols = wqkv.shape[1]
    if wqkv.shape[0] != k_dim or qkv_cols % d:
        raise ValueError(f"wqkv {wqkv.shape} inconsistent with x {x.shape} "
                         f"/ head_dim {d}")
    h_loc = qkv_cols // d - 2 * hk
    if h_loc < hk or h_loc % hk:
        raise ValueError(
            f"wqkv {wqkv.shape} does not hold [q|k|v] for {hk} kv heads "
            f"at head_dim {d}")
    mp = block_table.shape[1]
    if block_table.shape[0] != b or seq_lens.shape != (b,):
        raise ValueError(
            f"block_table {block_table.shape} / seq_lens {seq_lens.shape} "
            f"inconsistent with B={b}")
    sm_scale = float(sm_scale) if sm_scale is not None else d ** -0.5
    eps = None if qk_eps is None else float(qk_eps)
    quantized = k_scale is not None
    if quantized != (v_scale is not None):
        raise ValueError("pass both k_scale and v_scale or neither")
    if config is None:
        from ..tune import autotuner as _tune

        from ..core import platform

        def thunk(c):
            return lambda: fused_attn_decode(
                x, wqkv, q_norm, k_norm, pool_k, pool_v, block_table,
                seq_lens, rope_theta=rope_theta, qk_eps=qk_eps,
                sm_scale=sm_scale, soft_cap=soft_cap, config=c,
                k_scale=k_scale, v_scale=v_scale)

        config = _tune.resolve_config(
            "fused_attn_decode",
            (b, k_dim, h_loc, hk, ps, mp, d, str(x.dtype),
             str(pool_k.dtype), platform.device_kind()),
            fused_attn_candidates(), FusedAttnConfig(), thunk,
            tracing=any(map(_tune.is_tracer, (x, pool_k, seq_lens))),
        )
    fn = _build_fused_attn(
        b, k_dim, hk, h_loc // hk, d, p * hk, ps, mp, float(rope_theta),
        eps, sm_scale, float(soft_cap), jnp.dtype(x.dtype),
        jnp.dtype(pool_k.dtype), config, quantized,
    )
    args = [
        block_table.astype(jnp.int32).reshape(b * mp),
        seq_lens.astype(jnp.int32),
    ]
    if quantized:
        args += [k_scale.reshape(p * hk).astype(jnp.float32),
                 v_scale.reshape(p * hk).astype(jnp.float32)]
    args += [
        x,
        wqkv, wqkv, wqkv,
    ]
    if eps is not None:
        args += [q_norm.reshape(1, d), k_norm.reshape(1, d)]
    args += [
        pool_k.reshape(p * hk, ps, d),
        pool_v.reshape(p * hk, ps, d),
    ]
    if quantized:
        out, pk, pv, ktok, vtok = fn(*args)
        out = out.transpose(1, 0, 2, 3).reshape(b, h_loc * d)
        return (out, pk.reshape(p, hk, ps, d), pv.reshape(p, hk, ps, d),
                ktok.transpose(1, 0, 2), vtok.transpose(1, 0, 2))
    out, pk, pv = fn(*args)
    out = out.transpose(1, 0, 2, 3).reshape(b, h_loc * d)
    return out, pk.reshape(p, hk, ps, d), pv.reshape(p, hk, ps, d)


# ---------------------------------------------------------------------------
# Stage 2: fused MLP / linear + two-shot AllReduce (collective)


@dataclasses.dataclass(frozen=True)
class FusedMlpConfig:
    """Tile config of the semaphore-chained MLP/o-proj AllReduce kernel:
    ``bm`` rows (clipped to B — decode batches are small), ``bn`` output
    columns per matmul block, ``bk`` contraction depth, ``bf`` the
    up-projection/SwiGLU feature tile."""

    bm: int = 1024
    bn: int = 512
    bk: int = 512
    bf: int = 512

    def clip(self, b: int, k_loc: int, cn: int) -> "FusedMlpConfig":
        return FusedMlpConfig(
            bm=clip_block(self.bm, b), bn=clip_block(self.bn, cn),
            bk=clip_block(self.bk, k_loc), bf=clip_block(self.bf, k_loc),
        )


def fused_mlp_candidates(b: int, k_loc: int, cn: int) -> list:
    """(bm, bn, bk, bf) sweep for the ``config=None`` path, default-first
    (the baseline the autotuner margins protect), clipped to the problem
    and deduped — at decode shapes most tilings collapse onto the
    default and the one-candidate sweep short-circuits."""
    dims = [(1024, 512, 512, 512), (1024, 1024, 512, 512),
            (1024, 512, 1024, 1024), (1024, 256, 512, 512)]
    out, seen = [], set()
    for bm, bn, bk, bf in dims:
        c = FusedMlpConfig(bm, bn, bk, bf).clip(b, k_loc, cn)
        if c not in seen:
            seen.add(c)
            out.append(c)
    return out


def _fused_mlp_ar_kernel(
    team: Team,
    b: int,
    k_in: int,
    k_loc: int,
    n_dim: int,
    cfg: FusedMlpConfig,
    swiglu: bool,
    out_dtype,
    *refs,
    # inputs: x (B, k_in) [ANY]; [gate_up (k_in, 2*k_loc) ANY when
    # swiglu]; w_dn (k_loc, n_dim) [ANY].
    # output: out (n*B, cn) [ANY] — column chunk c of AllReduce(act@w_dn)
    # lands at rows [c*B, (c+1)*B).
    # scratch: [g_buf/u_buf/act_buf (B, k_loc) HBM when swiglu];
    # mm/recv/send (2, B, cn) HBM; send/recv/ack sems (2,);
    # ag_send_sem; ag_recv_sems (n,); [acc_up (bm, bf) when swiglu];
    # acc (bm, bn) f32 VMEM
):
    if swiglu:
        (x_ref, gu_ref, dn_ref, out_ref, g_buf, u_buf, act_buf,
         mm_buf, recv_buf, send_buf, send_sems, recv_sems, ack_sems,
         ag_send_sem, ag_recv_sems, acc_up, acc_ref) = refs
    else:
        (x_ref, dn_ref, out_ref,
         mm_buf, recv_buf, send_buf, send_sems, recv_sems, ack_sems,
         ag_send_sem, ag_recv_sems, acc_ref) = refs
    n = team.size
    left, right = team.neighbor_ranks()
    left_id, right_id = team.device_id(left), team.device_id(right)
    cn = n_dim // n

    # --- prologue: gate/up GEMM + SwiGLU, chained in-kernel -------------
    if swiglu:
        mmu = blocks.make_matmul_pipeline(
            b, k_loc, k_in, cfg.bm, cfg.bf, cfg.bk, out_dtype)
        mmu(x_ref, gu_ref.at[:, pl.ds(0, k_loc)], g_buf,
            scratches=[acc_up])
        mmu(x_ref, gu_ref.at[:, pl.ds(k_loc, k_loc)], u_buf,
            scratches=[acc_up])
        sw = blocks.make_swiglu_pipeline(b, k_loc, cfg.bm, cfg.bf,
                                         out_dtype)
        sw(g_buf, u_buf, act_buf)
        a_ref = act_buf
    else:
        a_ref = x_ref

    mm = blocks.make_matmul_pipeline(
        b, cn, k_loc, cfg.bm, cfg.bn, cfg.bk, out_dtype)
    add = blocks.make_add_pipeline(b, cn, cfg.bm, cfg.bn)

    def dn_chunk(c):
        return dn_ref.at[:, pl.ds(c * cn, cn)]

    dl.collective_prologue(team, neighbors_only=True)

    # --- phase 1: down-proj GEMM + ring ReduceScatter over OUTPUT column
    # chunks (the ops/gemm_rs.py flow with N-chunking, so any B rides) —
    # the partial of ring step s computes while step s-1's chunk is on
    # the wire, chained through the DMA/ack semaphores, never the host.
    # The slot/ack accounting lives ONCE in ring.gemm_rs_chunk_phase
    # (shared with the persistent chain, ops/persistent_decode).
    ring.gemm_rs_chunk_phase(team, b, mm, add, a_ref, dn_chunk, out_ref,
                             mm_buf, recv_buf, send_buf, send_sems,
                             recv_sems, ack_sems, acc_ref, right_id,
                             left_id)

    # --- phase 2: AG ring of reduced chunks + drains (gemm_ar accounting)
    ring.ag_ring_phase(team, out_ref, b, ag_send_sem, ag_recv_sems,
                       right_id)
    ring.gemm_rs_send_drain(n, send_buf, send_sems)
    ring.rs_ack_drain(ack_sems, n)
    ring.ag_ring_drain(team, out_ref, b, ag_send_sem)


@functools.lru_cache(maxsize=None)
def _build_fused_mlp_ar(
    mesh: Mesh,
    axis: str,
    b: int,
    k_in: int,
    k_loc: int,
    n_dim: int,
    swiglu: bool,
    dtype: jnp.dtype,
    out_dtype: jnp.dtype,
    cfg: FusedMlpConfig,
):
    team = Team.of(mesh, axis)
    n = team.size
    compilation.verify_protocol("fused_mlp_ar", n)
    cn = n_dim // n

    from ..obs import costs

    kernel = functools.partial(
        _fused_mlp_ar_kernel, team, b, k_in, k_loc, n_dim, cfg, swiglu,
        out_dtype,
    )
    in_specs = [pl.BlockSpec(memory_space=pl.ANY)] * (3 if swiglu else 2)
    scratch = []
    if swiglu:
        scratch += [pltpu.HBM((b, k_loc), out_dtype)] * 3
    scratch += [
        pltpu.HBM((2, b, cn), out_dtype),
        pltpu.HBM((2, b, cn), out_dtype),
        pltpu.HBM((2, b, cn), out_dtype),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.DMA((2,)),
        pltpu.SemaphoreType.REGULAR((2,)),
        pltpu.SemaphoreType.DMA(()),
        pltpu.SemaphoreType.DMA((n,)),
    ]
    if swiglu:
        scratch += [pltpu.VMEM((cfg.bm, cfg.bf), jnp.float32)]
    scratch += [pltpu.VMEM((cfg.bm, cfg.bn), jnp.float32)]
    call = pl.pallas_call(
        kernel,
        cost_estimate=costs.pallas_cost(
            costs.fused_mlp_ar(b, k_in, k_loc, n_dim, n, dtype, out_dtype,
                               swiglu=swiglu)),
        out_shape=jax.ShapeDtypeStruct((n * b, cn), out_dtype),
        in_specs=in_specs,
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=scratch,
        compiler_params=compilation.compiler_params(
            collective=True,
            collective_id=compilation.collective_id("fused_mlp_ar"),
        ),
        interpret=compilation.interpret_mode(),
    )
    if swiglu:
        in_p = (P(None, None), P(None, axis), P(axis, None))
    else:
        in_p = (P(None, axis), P(axis, None))
    return compilation.jit_shard_map(
        call, mesh, in_specs=in_p, out_specs=P(None, None),
    )


def _ar_chunks_to_rows(out: jax.Array, n: int, b: int) -> jax.Array:
    """(n*B, cn) chunk-major kernel output -> (B, n_dim) replicated."""
    cn = out.shape[1]
    return out.reshape(n, b, cn).transpose(1, 0, 2).reshape(b, n * cn)


def _resolve_fused_mlp(name, b, k_in, k_loc, n_dim, n, dtype, run, *,
                       tracing: bool):
    from ..core import platform
    from ..tune import autotuner as _tune

    return _tune.resolve_config(
        name,
        (b, k_in, k_loc, n_dim, n, str(dtype), platform.device_kind()),
        # the SHARED pruned sweep (tune.autotuner) — the candidates
        # digest keys the winner cache, so this transparent path and
        # fresh_tune_fused_mlp must consume the identical list
        _tune.fused_mlp_candidates_pruned(b, k_in, k_loc, n_dim, n,
                                          dtype),
        FusedMlpConfig().clip(b, k_loc, n_dim // n),
        lambda c: (lambda: run(c)),
        tracing=tracing,
    )


def _mlp_act_host(x: jax.Array, gate_up: jax.Array, n: int,
                  out_dtype) -> jax.Array:
    """The (B, F) SwiGLU activation the kernel feeds its down-proj,
    recomputed on the host for the integrity check: per-rank
    ``[gate_r | up_r]`` column blocks of the global (K, 2F) weight, with
    the same quantization points as the in-kernel pipelines (g/u GEMMs
    f32-accumulated then cast to ``out_dtype``, silu·mul in f32, the act
    cast back) — so a clean kernel run sits well inside the Freivalds
    tolerance even at bf16.  Columns land rank-major, matching ``down``'s
    row-parallel layout, so ``act @ down`` is the verified product."""
    f = gate_up.shape[1] // (2 * n)
    acts = []
    for r in range(n):
        blk = gate_up[:, r * 2 * f:(r + 1) * 2 * f]
        g = jnp.dot(x, blk[:, :f],
                    preferred_element_type=jnp.float32).astype(out_dtype)
        u = jnp.dot(x, blk[:, f:],
                    preferred_element_type=jnp.float32).astype(out_dtype)
        acts.append((jax.nn.silu(g.astype(jnp.float32))
                     * u.astype(jnp.float32)).astype(out_dtype))
    return jnp.concatenate(acts, axis=1)


@functools.lru_cache(maxsize=None)
def _build_mlp_partials(mesh: Mesh, axis: str, b: int, k_in: int,
                        f_loc: int, n_dim: int, dtype, out_dtype):
    """Per-rank SwiGLU-MLP down-proj partials, stacked (n*B, N): the
    producer half of the quantized-wire composition (the consumer is
    ``comm.quantized.quantized_all_reduce``)."""
    from jax.sharding import PartitionSpec as P

    def local(x_rep, gu_loc, dn_loc):
        fused = jnp.dot(x_rep, gu_loc,
                        preferred_element_type=jnp.float32).astype(x_rep.dtype)
        wg, w1 = jnp.split(fused, 2, axis=-1)
        act = jax.nn.silu(wg) * w1
        return jnp.dot(act, dn_loc,
                       preferred_element_type=jnp.float32).astype(out_dtype)

    return compilation.jit_shard_map(
        local, mesh,
        in_specs=(P(None, None), P(None, axis), P(axis, None)),
        out_specs=P(axis, None))


def fused_mlp_ar(
    x: jax.Array,
    gate_up: jax.Array,
    down: jax.Array,
    mesh: Mesh,
    axis: str = TP_AXIS,
    *,
    config: FusedMlpConfig | None = None,
    out_dtype=None,
    wire_dtype: str = "bf16",
) -> jax.Array:
    """Fused decode-MLP block: ``AllReduce(swiglu(x @ gate_up) @ down)``
    in ONE semaphore-chained kernel per rank.

    ``x``: (B, K) replicated; ``gate_up``: (K, 2F) sharded on dim 1 in
    the rank-blocked ``[gate_r | up_r]`` layout (``layers.tp_mlp``);
    ``down``: (F, K) row-parallel.  Returns (B, K) replicated.  Requires
    ``F % tp == 0`` (the weight sharding) and ``K % tp == 0`` (the output
    column chunking); B is unconstrained — the ring chunks columns, not
    rows (cf. ``ops.gemm_ar``).  Golden: ``Qwen3._mlp_decode``'s psum
    path.

    ``wire_dtype``: "int8"/"fp8" keeps the MLP local and reduces the
    down-proj partial through the quantized two-hop exchange
    (``comm.quantized`` — half the reduction's wire bytes, traded
    against this kernel's semaphore-chained overlap; "auto" lets the
    contextual tuner decide per shape/ranks/wire class).  Needs
    ``B % tp == 0`` (the exchange chunks rows) — other shapes keep the
    bf16 kernel.
    """
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(x.dtype)
    n = mesh.shape[axis]
    b, k_in = x.shape
    f_dim = down.shape[0]
    n_dim = down.shape[1]
    if gate_up.shape != (k_in, 2 * f_dim):
        raise ValueError(
            f"gate_up {gate_up.shape} inconsistent with x {x.shape} / "
            f"down {down.shape}")
    if n == 1:
        fused = jnp.dot(x, gate_up, preferred_element_type=jnp.float32
                        ).astype(x.dtype)
        wg, w1 = jnp.split(fused, 2, axis=-1)
        act = jax.nn.silu(wg) * w1
        return jnp.dot(act, down,
                       preferred_element_type=jnp.float32).astype(out_dtype)
    if f_dim % n or n_dim % n:
        raise ValueError(
            f"F={f_dim} and N={n_dim} must be divisible by {axis}={n}")
    if wire_dtype != "bf16" and b % n == 0:
        from ..comm import quantized as _q
        from ..tune.autotuner import is_tracer as _q_is_tracer

        if wire_dtype == "auto":
            wire_dtype = _q.resolve_wire_dtype(
                "fused_mlp_ar_wire", (b, k_in, f_dim, n_dim, str(x.dtype)),
                mesh, axis,
                lambda wd: (lambda: fused_mlp_ar(
                    x, gate_up, down, mesh, axis, config=config,
                    out_dtype=out_dtype, wire_dtype=wd)),
                tracing=_q_is_tracer(x),
            )
        if wire_dtype != "bf16":
            parts = _build_mlp_partials(
                mesh, axis, b, k_in, f_dim // n, n_dim,
                jnp.dtype(x.dtype), out_dtype)(x, gate_up, down)
            return _q.quantized_all_reduce(
                parts, mesh, axis, wire_dtype=wire_dtype,
                out_dtype=out_dtype)
    k_loc = f_dim // n

    def run(cfg):
        fn = _build_fused_mlp_ar(
            mesh, axis, b, k_in, k_loc, n_dim, True, jnp.dtype(x.dtype),
            out_dtype, cfg.clip(b, k_loc, n_dim // n),
        )
        return _ar_chunks_to_rows(fn(x, gate_up, down), n, b)

    from .. import resilience
    from ..tune.autotuner import is_tracer

    eager = not is_tracer(x)
    if config is None:
        # resolve under tracing too: the jitted decode step consults the
        # winner cache (resolve_config's contract) so a bench/warmup
        # crown reaches the serving path — measurement stays eager-only
        config = _resolve_fused_mlp(
            "fused_mlp_ar", b, k_in, k_loc, n_dim, n, x.dtype, run,
            tracing=not eager)
    cfg = config
    core = lambda: run(cfg)  # noqa: E731
    if eager and resilience.integrity.enabled():
        # consumer-side verification (TDT_INTEGRITY=1): mirror the
        # in-kernel act quantization on the host, then Freivalds-check
        # the down-proj + AllReduce like the other fused GEMM entries
        core = resilience.integrity.checked(
            "fused_mlp_ar", core, ranks=n,
            verify=lambda out: resilience.integrity.verify_gemm(
                "fused_mlp_ar",
                _mlp_act_host(x, gate_up, n, out_dtype), down, out))
    if eager and resilience.enabled():
        return resilience.guarded(
            "fused_mlp_ar", core,
            family="fused_mlp_ar", ranks=n,
            payload_bytes=b * n_dim * jnp.dtype(out_dtype).itemsize,
            fallback=lambda: resilience.fallbacks.xla_fused_mlp_ar(
                x, gate_up, down, mesh, axis, out_dtype),
        )()
    return core()


def fused_linear_ar(
    h: jax.Array,
    w: jax.Array,
    mesh: Mesh,
    axis: str = TP_AXIS,
    *,
    config: FusedMlpConfig | None = None,
    out_dtype=None,
) -> jax.Array:
    """Fused row-parallel projection: ``AllReduce(h @ w)`` through the
    same semaphore-chained column-ring kernel, without the SwiGLU
    prologue — the decode o-proj reduction.

    ``h``: (B, F) sharded on dim 1; ``w``: (F, N) row-parallel.  Returns
    (B, N) replicated.  Unlike ``ops.gemm_ar`` this needs no ``B % tp``
    (columns are chunked), only ``N % tp == 0``.
    """
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(h.dtype)
    n = mesh.shape[axis]
    b, f_dim = h.shape
    if w.shape[0] != f_dim:
        raise ValueError(f"inner dims mismatch: {h.shape} @ {w.shape}")
    n_dim = w.shape[1]
    if n == 1:
        return jnp.dot(h, w,
                       preferred_element_type=jnp.float32).astype(out_dtype)
    if f_dim % n or n_dim % n:
        raise ValueError(
            f"F={f_dim} and N={n_dim} must be divisible by {axis}={n}")
    k_loc = f_dim // n

    def run(cfg):
        fn = _build_fused_mlp_ar(
            mesh, axis, b, k_loc, k_loc, n_dim, False, jnp.dtype(h.dtype),
            out_dtype, cfg.clip(b, k_loc, n_dim // n),
        )
        return _ar_chunks_to_rows(fn(h, w), n, b)

    from .. import resilience
    from ..tune.autotuner import is_tracer

    eager = not is_tracer(h)
    if config is None:
        # winner-cache consult under tracing, like fused_mlp_ar above
        config = _resolve_fused_mlp(
            "fused_linear_ar", b, k_loc, k_loc, n_dim, n, h.dtype, run,
            tracing=not eager)
    cfg = config
    core = lambda: run(cfg)  # noqa: E731
    if eager and resilience.integrity.enabled():
        # plain AllReduce(h @ w): the gemm_ar Freivalds check applies as-is
        core = resilience.integrity.checked(
            "fused_linear_ar", core, ranks=n,
            verify=lambda out: resilience.integrity.verify_gemm(
                "fused_linear_ar", h, w, out))
    if eager and resilience.enabled():
        return resilience.guarded(
            "fused_linear_ar", core,
            family="fused_mlp_ar", ranks=n,
            payload_bytes=b * n_dim * jnp.dtype(out_dtype).itemsize,
            fallback=lambda: resilience.fallbacks.xla_gemm_ar(
                h, w, mesh, axis, out_dtype),
        )()
    return core()


# ---------------------------------------------------------------------------
# dispatch accounting: the number the megakernel exists to shrink


# primitives that survive XLA fusion as separate dispatches (or fusion
# barriers) on the decode path: Pallas launches, MXU GEMMs, cache
# scatters/updates, and cross-rank reductions.  Elementwise chains
# (norms, rope, residuals) fuse into their neighbours and are not
# counted — this is a conservative static proxy, identical for both
# modes, so the fused/unfused RATIO is meaningful wherever tracing runs.
DISPATCH_PRIMS = frozenset((
    "pallas_call",
    "dot_general",
    "scatter",
    "scatter-add",
    "dynamic_update_slice",
    "psum",
    "psum_invariant",
    "all_reduce",
    "all_gather",
    "reduce_scatter",
    "ppermute",
))


def count_jaxpr_dispatches(fn, *args, **kw) -> int:
    """Count kernel-dispatch-shaped equations in ``fn``'s jaxpr,
    descending into pjit/shard_map/loop/custom-vjp sub-jaxprs (a loop
    body's dispatches count once — the decode layer loop is unrolled in
    the model, so per-layer work is fully visible)."""
    jaxpr = jax.make_jaxpr(fn)(*args, **kw)

    def walk(jx) -> int:
        total = 0
        for eqn in jx.eqns:
            if eqn.primitive.name in DISPATCH_PRIMS:
                total += 1
            for v in eqn.params.values():
                for sub in _sub_jaxprs(v):
                    total += walk(sub)
        return total

    return walk(jaxpr.jaxpr)


def _sub_jaxprs(v):
    import jax.core as jcore

    if isinstance(v, jcore.ClosedJaxpr):
        yield v.jaxpr
    elif isinstance(v, jcore.Jaxpr):
        yield v
    elif isinstance(v, (list, tuple)):
        for item in v:
            yield from _sub_jaxprs(item)


def count_decode_dispatches(model, params, cache, tokens) -> int:
    """Static dispatch count of one ``model.decode`` step (the metric
    ``bench.py decode`` records as ``decode_step_dispatches``)."""
    return count_jaxpr_dispatches(
        lambda p, c, t: model.decode(p, c, t), params, cache, tokens)
