"""Local blocked matmul as a standalone Pallas kernel.

The single-chip building block under every fused op: the same
``blocks.make_matmul_pipeline`` MXU loop that ``ag_gemm``/``gemm_rs`` run
per chunk, exposed as a plain op.  Reference analogue: the non-distributed
persistent GEMM the consumer kernels are built around
(``python/triton_dist/kernels/nvidia/allgather_gemm.py:216-260``); on TPU it
doubles as the single-chip benchmark kernel (``bench.py``) and the n=1
fallback of the distributed ops.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core import compilation
from ..core.utils import clip_block
from . import blocks


def _matmul_kernel(m, n, k, bm, bn, bk, out_dtype, a_ref, b_ref, c_ref, acc_ref):
    pipe = blocks.make_matmul_pipeline(m, n, k, bm, bn, bk, out_dtype)
    pipe(a_ref, b_ref, c_ref, scratches=[acc_ref])


@functools.lru_cache(maxsize=None)
def _build_matmul(m, n, k, bm, bn, bk, dtype, out_dtype):
    kernel = functools.partial(_matmul_kernel, m, n, k, bm, bn, bk, out_dtype)
    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=compilation.compiler_params(collective=False),
        interpret=compilation.interpret_mode(),
    )
    return jax.jit(call)


def matmul(
    a: jax.Array,
    b: jax.Array,
    *,
    bm: int = 512,
    bn: int = 512,
    bk: int = 512,
    out_dtype=None,
) -> jax.Array:
    """C = A @ B with f32 accumulation, blocked for the MXU."""
    (m, k), (k2, n) = a.shape, b.shape
    if k2 != k:
        raise ValueError(f"inner dims mismatch: {a.shape} @ {b.shape}")
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(a.dtype)
    bm, bn, bk = clip_block(bm, m), clip_block(bn, n), clip_block(bk, k)
    fn = _build_matmul(m, n, k, bm, bn, bk, jnp.dtype(a.dtype), out_dtype)
    return fn(a, b)
