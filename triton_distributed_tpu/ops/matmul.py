"""Local blocked matmul as a standalone Pallas kernel.

The single-chip building block under every fused op: the same
``blocks.make_matmul_pipeline`` MXU loop that ``ag_gemm``/``gemm_rs`` run
per chunk, exposed as a plain op.  Reference analogue: the non-distributed
persistent GEMM the consumer kernels are built around
(``python/triton_dist/kernels/nvidia/allgather_gemm.py:216-260``); on TPU it
doubles as the single-chip benchmark kernel (``bench.py``) and the n=1
fallback of the distributed ops.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core import compilation
from ..core.utils import clip_block
from ..tune.autotuner import MATMUL_DEFAULT_TILES
from . import blocks


@functools.lru_cache(maxsize=None)
def _build_matmul(m, n, k, bm, bn, bk, dtype, out_dtype):
    # Grid form (not emit_pipeline): Mosaic schedules the (m, n, k) grid
    # itself, and dimension_semantics lets it reorder/parallelize the two
    # output dims — measured ~4% faster than the in-kernel emit_pipeline
    # form at 7168^3 bf16.  The fused ops keep emit_pipeline (they need the
    # manual loop to interleave DMA waits); this op is the pure-MXU path.
    nk = k // bk
    call = pl.pallas_call(
        functools.partial(blocks.matmul_body, nk, out_dtype),
        grid=(m // bm, n // bn, nk),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=compilation.compiler_params(
            collective=False,
            dimension_semantics=("parallel", "parallel", "arbitrary"),
        ),
        interpret=compilation.interpret_mode(),
    )
    return jax.jit(call)


def matmul(
    a: jax.Array,
    b: jax.Array,
    *,
    bm: int | None = None,
    bn: int | None = None,
    bk: int | None = None,
    out_dtype=None,
) -> jax.Array:
    """C = A @ B with f32 accumulation, blocked for the MXU.

    With no explicit tiles, the contextual autotuner resolves them per
    shape class: a cached per-(m, n, k, dtype, device) winner if one
    exists, a measurement sweep on the first eager real-hardware call,
    else the static default (512, 1792, 512) — which measured 1.03x of
    XLA's own GEMM at 7168^3 bf16 (median per-round interleaved ratio over
    14 rounds; the wide 14-lane-tile N block keeps the MXU fed while
    halving the accumulator footprint vs 1024x1024, which measured 0.99x).
    For shapes 1792 does not divide, ``clip_block`` degrades bn to the
    largest sublane-aligned divisor (1024/512/...).
    """
    (m, k), (k2, n) = a.shape, b.shape
    if k2 != k:
        raise ValueError(f"inner dims mismatch: {a.shape} @ {b.shape}")
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(a.dtype)
    if bm is None and bn is None and bk is None:
        from ..tune import autotuner as _tune

        bm, bn, bk = _tune.resolve_config(
            "matmul", _tune.matmul_resolve_key(m, n, k, a.dtype),
            _tune.matmul_tile_candidates(m, n, k),
            _tune.MATMUL_DEFAULT_TILES,
            lambda c: (lambda: matmul(a, b, bm=c[0], bn=c[1], bk=c[2],
                                      out_dtype=out_dtype)),
            tracing=_tune.is_tracer(a) or _tune.is_tracer(b),
        )
    else:
        dbm, dbn, dbk = MATMUL_DEFAULT_TILES
        bm, bn, bk = bm or dbm, bn or dbn, bk or dbk
    bm, bn, bk = clip_block(bm, m), clip_block(bn, n), clip_block(bk, k)
    fn = _build_matmul(m, n, k, bm, bn, bk, jnp.dtype(a.dtype), out_dtype)
    return fn(a, b)
