"""Local blocked matmul: best-backend dispatch over Pallas tiles and XLA.

The single-chip building block under every fused op: the same
``blocks.make_matmul_pipeline`` MXU loop that ``ag_gemm``/``gemm_rs`` run
per chunk, exposed as a plain op.  Reference analogue: the non-distributed
persistent GEMM the consumer kernels are built around
(``python/triton_dist/kernels/nvidia/allgather_gemm.py:216-260``) — which
competes with and falls back to cuBLAS where the hand-written kernel
loses.  The TPU analogue of that dispatch is this op's ``config=None``
path: the contextual autotuner measures Pallas grid tilings AND XLA's own
MXU GEMM under tuned compile options (``tune.autotuner.XlaBackend``,
``core.compilation.xla_gemm_options``) and crowns the per-shape winner.
On the benched v5e the crowned backend is shape- and chip-state-
dependent: XLA + raised scoped VMEM wins large skewed shapes by 1.6-2.1x
over default-flag XLA; at 7168^3 everything ties within noise.

Explicit ``bm``/``bn``/``bk`` always run the Pallas grid kernel (the form
the fused collective ops build on, and what the CPU-mesh tests exercise).
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from ..core import compilation
from ..core.utils import clip_block
from ..tune.autotuner import MATMUL_DEFAULT_TILES, XlaBackend
from . import blocks


@functools.lru_cache(maxsize=None)
def _build_matmul(m, n, k, bm, bn, bk, dtype, out_dtype, vmem_limit=None):
    # Grid form (not emit_pipeline): Mosaic schedules the (m, n, k) grid
    # itself, and dimension_semantics lets it reorder/parallelize the two
    # output dims — measured ~4% faster than the in-kernel emit_pipeline
    # form at 7168^3 bf16.  The fused ops keep emit_pipeline (they need the
    # manual loop to interleave DMA waits); this op is the pure-MXU path.
    # ``vmem_limit`` raises Mosaic's scoped-VMEM budget above the 16 MiB
    # default for big-accumulator tiles (the v5e has 128 MiB of VMEM; a
    # >=4 MB f32 accumulator plus double-buffered operands fails to
    # compile under the default budget).
    from ..obs import costs

    nk = k // bk
    call = pl.pallas_call(
        functools.partial(blocks.matmul_body, nk, out_dtype),
        grid=(m // bm, n // bn, nk),
        cost_estimate=costs.pallas_cost(
            costs.matmul(m, n, k, dtype, out_dtype)),
        in_specs=[
            pl.BlockSpec((bm, bk), lambda i, j, kk: (i, kk)),
            pl.BlockSpec((bk, bn), lambda i, j, kk: (kk, j)),
        ],
        out_specs=pl.BlockSpec((bm, bn), lambda i, j, kk: (i, j)),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        scratch_shapes=[pltpu.VMEM((bm, bn), jnp.float32)],
        compiler_params=compilation.compiler_params(
            collective=False,
            dimension_semantics=("parallel", "parallel", "arbitrary"),
            vmem_limit_bytes=vmem_limit,
        ),
        interpret=compilation.interpret_mode(),
    )
    return jax.jit(call)


def _xla_dot(a, b, out_dtype):
    if jnp.result_type(a, b) == jnp.float32:
        # the op's contract is true f32 accumulation; TPU DEFAULT
        # precision would silently run bf16 passes over f32 operands
        return jnp.matmul(
            a, b, precision=jax.lax.Precision.HIGHEST
        ).astype(out_dtype)
    # the natural-out-dtype case emits EXACTLY ``jnp.matmul(a, b)`` — the
    # measured-ratio reference program — so an XlaBackend(0) crown means
    # "identical to XLA", not "close to XLA" (an explicit
    # preferred_element_type changes XLA's strategy choice at some shapes,
    # which measured anywhere from 0.6x to 1.9x of the plain dot on the
    # v5e depending on chip state — not a stable substitute)
    if out_dtype == jnp.result_type(a, b):
        return jnp.matmul(a, b)
    return jnp.matmul(
        a, b, preferred_element_type=jnp.float32
    ).astype(out_dtype)


@functools.lru_cache(maxsize=None)
def _xla_matmul_fn(scoped_vmem_kib: int, out_dtype):
    """Jitted XLA GEMM carrying the backend's compile options — the
    executable an eagerly-called ``matmul`` dispatches to when an
    ``XlaBackend`` config is crowned."""
    return jax.jit(
        functools.partial(_xla_dot, out_dtype=out_dtype),
        compiler_options=compilation.xla_gemm_options(scoped_vmem_kib)
        or None,
    )


def _xla_matmul(a, b, out_dtype, cfg: XlaBackend):
    if isinstance(a, jax.core.Tracer) or isinstance(b, jax.core.Tracer):
        # inside someone else's jit: compile options cannot attach to an
        # inlined op — emit the plain dot and let the outer computation's
        # options govern
        return _xla_dot(a, b, out_dtype)
    return _xla_matmul_fn(cfg.scoped_vmem_kib, out_dtype)(a, b)


def matmul(
    a: jax.Array,
    b: jax.Array,
    *,
    bm: int | None = None,
    bn: int | None = None,
    bk: int | None = None,
    out_dtype=None,
    config=None,
) -> jax.Array:
    """C = A @ B with f32 accumulation, blocked for the MXU.

    With no explicit tiles, the contextual autotuner resolves the BACKEND
    per shape class: a cached per-(m, n, k, dtype, device) winner if one
    exists, a measurement sweep over Pallas tilings + XLA dispatch
    variants on the first eager real-hardware call, else the XLA default.
    ``config`` accepts an explicit resolution (a tile tuple or
    :class:`~..tune.autotuner.XlaBackend`) — the form the autotuner's
    thunks use.  Explicit ``bm``/``bn``/``bk`` force the Pallas grid
    kernel with those tiles.
    """
    (m, k), (k2, n) = a.shape, b.shape
    if k2 != k:
        raise ValueError(f"inner dims mismatch: {a.shape} @ {b.shape}")
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(a.dtype)
    if config is None and bm is None and bn is None and bk is None:
        from ..tune import autotuner as _tune

        config = _tune.resolve_config(
            "matmul", _tune.matmul_resolve_key(m, n, k, a.dtype),
            _tune.matmul_candidates_pruned(m, n, k, a.dtype),
            XlaBackend(),
            lambda c: (lambda: matmul(a, b, config=c, out_dtype=out_dtype)),
            tracing=_tune.is_tracer(a) or _tune.is_tracer(b),
        )
    if isinstance(config, XlaBackend):
        return _xla_matmul(a, b, out_dtype, config)
    vl = None
    if config is not None:
        # tile tuples are (bm, bn, bk) or (bm, bn, bk, vmem_limit)
        bm, bn, bk, *rest = config
        vl = rest[0] if rest else None
    else:
        dbm, dbn, dbk = MATMUL_DEFAULT_TILES
        bm, bn, bk = bm or dbm, bn or dbn, bk or dbk
    bm, bn, bk = clip_block(bm, m), clip_block(bn, n), clip_block(bk, k)
    fn = _build_matmul(m, n, k, bm, bn, bk, jnp.dtype(a.dtype), out_dtype,
                       vl)
    return fn(a, b)


def matmul_callable(a: jax.Array, b: jax.Array, *, out_dtype=None):
    """Resolve the tuned backend for this shape ONCE and return the
    underlying jitted callable ``(a, b) -> C``.

    The zero-dispatch-overhead form a hot serving loop (and ``bench.py``'s
    timed engines) should hold: the eager ``matmul()`` wrapper costs
    ~100 us of Python per call (resolution memo, lru hops), which is
    enough to skew sub-millisecond timed windows — measured as a phantom
    15% loss on an IDENTICAL executable at 4096^3.  Eager-only (resolution
    measures on first call if this shape was never tuned)."""
    if isinstance(a, jax.core.Tracer) or isinstance(b, jax.core.Tracer):
        raise TypeError(
            "matmul_callable is eager-only (it measures/resolves on real "
            "arrays); call matmul() inside jit instead"
        )
    (m, k), (k2, n) = a.shape, b.shape
    if k2 != k:
        raise ValueError(f"inner dims mismatch: {a.shape} @ {b.shape}")
    out_dtype = jnp.dtype(out_dtype) if out_dtype else jnp.dtype(a.dtype)
    from ..tune import autotuner as _tune

    config = _tune.resolve_config(
        "matmul", _tune.matmul_resolve_key(m, n, k, a.dtype),
        _tune.matmul_candidates_pruned(m, n, k, a.dtype),
        XlaBackend(),
        lambda c: (lambda: matmul(a, b, config=c, out_dtype=out_dtype)),
        tracing=False,
    )
    if isinstance(config, XlaBackend):
        return _xla_matmul_fn(config.scoped_vmem_kib, out_dtype)
    bm, bn, bk = (clip_block(config[0], m), clip_block(config[1], n),
                  clip_block(config[2], k))
    vl = config[3] if len(config) > 3 else None
    return _build_matmul(m, n, k, bm, bn, bk, jnp.dtype(a.dtype), out_dtype,
                         vl)
