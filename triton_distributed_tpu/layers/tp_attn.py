"""Tensor-parallel attention (QKV column-parallel over heads, O row-parallel).

Reference: ``python/triton_dist/layers/nvidia/tp_attn.py:78-274`` — fused
wqkv per rank ([q_r | k_r | v_r], ``:99-104``), ``dist_triton_fwd`` =
AG-GEMM -> QK-norm -> RoPE -> flash-attn -> GEMM-RS (``:203-237``),
``dist_triton_AR_fwd`` = local GEMM -> attention -> GEMM+AllReduce
(``:239-273``).

TPU design mirrors ``layers/tp_mlp.py``: the two fused collective GEMMs
bracket a per-rank block (QKV split, optional QK RMSNorm, RoPE, local
flash-attention over this rank's heads) that runs under ``shard_map`` —
head-parallelism means attention never needs communication, exactly the
property the reference exploits.

Prefill only; the decode path (KV cache append + ``decode_attention``)
lives in ``models/`` where the cache is owned.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.mesh import TP_AXIS
from ..ops import ag_gemm, gemm_ar, gemm_rs
from ..ops.attention import flash_attention
from ..ops.rope import apply_rope_at
from .norm import rms_norm
from .tp_mlp import fuse_column_shards, replicated_column_gemm


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TPAttnParams:
    """wqkv: (K, (H + 2*Hkv) * D) rank-blocked [q_r | k_r | v_r];
    wo: (H*D, K) row-sharded; q_norm/k_norm: (D,) or None."""

    wqkv: jax.Array
    wo: jax.Array
    q_norm: jax.Array | None
    k_norm: jax.Array | None


@dataclasses.dataclass(frozen=True)
class TPAttn:
    mesh: Mesh
    num_heads: int
    num_kv_heads: int
    head_dim: int
    axis: str = TP_AXIS
    rope_theta: float = 10_000.0
    qk_norm_eps: float | None = None   # set to enable Qwen3-style QK norm

    @property
    def tp(self) -> int:
        return self.mesh.shape[self.axis]

    def __post_init__(self):
        n = self.tp
        if self.num_heads % n or self.num_kv_heads % n:
            raise ValueError(
                f"heads ({self.num_heads}, kv {self.num_kv_heads}) must be "
                f"divisible by {self.axis}={n}"
            )

    # -- parameter construction ------------------------------------------

    def shard_params(self, wq, wk, wv, wo, q_norm=None, k_norm=None
                     ) -> TPAttnParams:
        """Full weights: wq (K, H*D), wk/wv (K, Hkv*D), wo (H*D, K)."""
        n = self.tp
        wqkv = fuse_column_shards([wq, wk, wv], n)
        return TPAttnParams(
            wqkv=jax.device_put(
                wqkv, NamedSharding(self.mesh, P(None, self.axis))
            ),
            wo=jax.device_put(
                wo, NamedSharding(self.mesh, P(self.axis, None))
            ),
            q_norm=q_norm, k_norm=k_norm,
        )

    def init(self, key: jax.Array, hidden: int, dtype=jnp.bfloat16,
             scale: float = 0.02) -> TPAttnParams:
        kq, kk, kv, ko = jax.random.split(key, 4)
        h, hk, d = self.num_heads, self.num_kv_heads, self.head_dim
        wq = jax.random.normal(kq, (hidden, h * d), dtype) * scale
        wk = jax.random.normal(kk, (hidden, hk * d), dtype) * scale
        wv = jax.random.normal(kv, (hidden, hk * d), dtype) * scale
        wo = jax.random.normal(ko, (h * d, hidden), dtype) * scale
        qn = kn = None
        if self.qk_norm_eps is not None:
            qn = jnp.ones((d,), dtype)
            kn = jnp.ones((d,), dtype)
        return self.shard_params(wq, wk, wv, wo, qn, kn)

    # -- forward ----------------------------------------------------------

    def _local_attention(self, qkv, q_norm, k_norm, batch: int, seq: int,
                         segment_ids=None):
        """Per-rank: split rank-local [q_r | k_r | v_r] columns, QK-norm,
        RoPE, causal flash-attention over this rank's heads.  With
        ``segment_ids`` (B, S), the batch is a PACKED varlen batch: RoPE
        positions restart at each segment boundary and attention is
        confined to the segment (the reference's cu_seqlens path)."""
        n = self.tp
        h_loc = self.num_heads // n
        hk_loc = self.num_kv_heads // n
        d = self.head_dim

        def body(qkv_loc, segs):
            q, k, v = jnp.split(
                qkv_loc, [h_loc * d, (h_loc + hk_loc) * d], axis=-1
            )
            # (M, h*d) -> (B, heads, S, d)
            def to_heads(x, nh):
                return x.reshape(batch, seq, nh, d).transpose(0, 2, 1, 3)

            q, k, v = to_heads(q, h_loc), to_heads(k, hk_loc), to_heads(v, hk_loc)
            if self.qk_norm_eps is not None:
                q = rms_norm(q, q_norm, self.qk_norm_eps)
                k = rms_norm(k, k_norm, self.qk_norm_eps)
            if segs is None:
                pos = jnp.arange(seq)
            else:
                # positions restart per segment: index - running seg start
                idx = jnp.arange(seq)
                is_start = jnp.concatenate(
                    [jnp.ones((batch, 1), bool),
                     segs[:, 1:] != segs[:, :-1]], axis=1,
                )
                seg_start = jax.lax.cummax(
                    jnp.where(is_start, idx[None], 0), axis=1
                )
                pos = (idx[None] - seg_start)[:, None, :]   # (B, 1, S)
            q = apply_rope_at(q, pos, theta=self.rope_theta)
            k = apply_rope_at(k, pos, theta=self.rope_theta)
            out = flash_attention(q, k, v, causal=True, segment_ids=segs)
            return out.transpose(0, 2, 1, 3).reshape(batch * seq, h_loc * d)

        # check_vma off: the Pallas flash kernel's outputs carry no vma
        if segment_ids is None:
            return jax.shard_map(
                lambda qkv_loc: body(qkv_loc, None), mesh=self.mesh,
                in_specs=P(None, self.axis), out_specs=P(None, self.axis),
                check_vma=False,
            )(qkv)
        return jax.shard_map(
            body, mesh=self.mesh,
            in_specs=(P(None, self.axis), P(None, None)),
            out_specs=P(None, self.axis),
            check_vma=False,
        )(qkv, segment_ids.astype(jnp.int32))

    def forward(self, params: TPAttnParams, x: jax.Array,
                batch: int = 1, *,
                segment_ids: jax.Array | None = None) -> jax.Array:
        """AG-GEMM -> local attention -> GEMM-RS (reference
        ``dist_triton_fwd``).

        ``x``: (M, K) sharded on dim 0, M = batch * seq flattened tokens.
        ``segment_ids``: optional (batch, seq) for packed varlen batches.
        Returns (M, K) sharded on dim 0.
        """
        m, _ = x.shape
        seq = m // batch
        qkv = ag_gemm(x, params.wqkv, self.mesh, self.axis)
        attn = self._local_attention(qkv, params.q_norm, params.k_norm,
                                     batch, seq, segment_ids)
        return gemm_rs(attn, params.wo, self.mesh, self.axis)

    def forward_ar(self, params: TPAttnParams, x: jax.Array,
                   batch: int = 1, *,
                   segment_ids: jax.Array | None = None) -> jax.Array:
        """Local GEMM -> local attention -> fused GEMM+AllReduce (reference
        ``dist_triton_AR_fwd``; small-M path).

        ``x``: (M, K) replicated.  ``segment_ids``: optional (batch, seq)
        for packed varlen batches.  Returns (M, K) replicated.
        """
        m, _ = x.shape
        seq = m // batch
        qkv = replicated_column_gemm(self.mesh, self.axis, x, params.wqkv)
        attn = self._local_attention(qkv, params.q_norm, params.k_norm,
                                     batch, seq, segment_ids)
        return gemm_ar(attn, params.wo, self.mesh, self.axis)
