"""Tensor-parallel MLP (gate/up column-parallel, down row-parallel).

Reference: ``python/triton_dist/layers/nvidia/tp_mlp.py:51-241`` — fused
gate_up weight per rank, ``dist_triton_fwd`` = AG-GEMM -> act -> GEMM-RS
(``:143-167``), ``dist_triton_AR_fwd`` = local GEMMs -> AllReduce
(``:168-191``, the small-M path).

TPU design: a functional pytree of sharded arrays + a static config.  The
two fused collective GEMMs are the framework's overlapped Pallas ops; the
per-rank split/activation between them runs under ``shard_map`` so the
rank-blocked fused gate_up layout ([gate_r | up_r] per rank, exactly the
reference's ``torch.cat`` layout) never needs a global relayout.

Sharding map (M = flattened tokens, K = hidden, I = intermediate):

- ``forward``    x: (M, K) M-sharded  ->  (M, K) M-sharded   (SP in/out)
- ``forward_ar`` x: (M, K) replicated ->  (M, K) replicated  (AR out)
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core import compilation
from ..core.mesh import TP_AXIS
from ..ops import ag_gemm, gemm_ar, gemm_rs


def fuse_column_shards(parts, n: int) -> jax.Array:
    """Fuse column-parallel weights into the per-rank-blocked layout.

    ``parts``: list of (K, I_j) arrays, each to be column-sharded n ways.
    Returns (K, sum_j I_j) whose global column order is
    [p0_r0 | p1_r0 | ... | p0_r1 | p1_r1 | ...] — rank r's shard holds its
    slice of every part contiguously (reference ``tp_mlp.py:77-80``).
    """
    for p in parts:
        if p.shape[1] % n:
            raise ValueError(
                f"column count {p.shape[1]} not divisible by {n} shards"
            )
    blocks = []
    for r in range(n):
        for p in parts:
            i = p.shape[1] // n
            blocks.append(p[:, r * i:(r + 1) * i])
    return jnp.concatenate(blocks, axis=1)


def replicated_column_gemm(mesh: Mesh, axis: str, x: jax.Array,
                           w: jax.Array) -> jax.Array:
    """Local GEMM of replicated activations against a column-sharded weight:
    (M, K) replicated @ (K, N) P(None, axis) -> (M, N) P(None, axis).  The
    no-communication first half of the AR forward paths (MLP and Attn)."""
    def local_gemm(x_loc, w_loc):
        return jnp.dot(
            x_loc, w_loc, preferred_element_type=jnp.float32
        ).astype(x_loc.dtype)

    return compilation.jit_shard_map(
        local_gemm, mesh,
        in_specs=(P(None, None), P(None, axis)),
        out_specs=P(None, axis),
    )(x, w)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class TPMLPParams:
    """gate_up: (K, 2I) rank-blocked [gate_r | up_r]; down: (I, K)."""

    gate_up: jax.Array
    down: jax.Array


@dataclasses.dataclass(frozen=True)
class TPMLP:
    """Static layer config; params travel separately (functional style)."""

    mesh: Mesh
    axis: str = TP_AXIS
    act: str = "silu"

    @property
    def tp(self) -> int:
        return self.mesh.shape[self.axis]

    # -- parameter construction ------------------------------------------

    def shard_params(self, gate, up, down) -> TPMLPParams:
        """Build sharded params from full (replicated) weights:
        gate/up (K, I), down (I, K)."""
        n = self.tp
        gate_up = fuse_column_shards([gate, up], n)
        return TPMLPParams(
            gate_up=jax.device_put(
                gate_up, NamedSharding(self.mesh, P(None, self.axis))
            ),
            down=jax.device_put(
                down, NamedSharding(self.mesh, P(self.axis, None))
            ),
        )

    def init(self, key: jax.Array, hidden: int, intermediate: int,
             dtype=jnp.bfloat16, scale: float = 0.02) -> TPMLPParams:
        kg, ku, kd = jax.random.split(key, 3)
        g = jax.random.normal(kg, (hidden, intermediate), dtype) * scale
        u = jax.random.normal(ku, (hidden, intermediate), dtype) * scale
        d = jax.random.normal(kd, (intermediate, hidden), dtype) * scale
        return self.shard_params(g, u, d)

    # -- forward passes ---------------------------------------------------

    def _act_combine(self, fused: jax.Array) -> jax.Array:
        """Per-rank split of the rank-blocked [gate_r | up_r] columns and
        gated activation; local columns only, so it runs under shard_map."""
        act = dict(silu=jax.nn.silu, gelu=jax.nn.gelu, relu=jax.nn.relu)[self.act]

        def local(o_loc):
            wg, w1 = jnp.split(o_loc, 2, axis=-1)
            return act(wg) * w1

        return jax.shard_map(
            local, mesh=self.mesh,
            in_specs=P(None, self.axis), out_specs=P(None, self.axis),
        )(fused)

    def forward(self, params: TPMLPParams, x: jax.Array) -> jax.Array:
        """AG-GEMM -> act -> GEMM-RS (reference ``dist_triton_fwd``).

        ``x``: (M, K) sharded on dim 0 (sequence-parallel activations).
        Returns (M, K) sharded on dim 0.
        """
        fused = ag_gemm(x, params.gate_up, self.mesh, self.axis)
        h = self._act_combine(fused)
        return gemm_rs(h, params.down, self.mesh, self.axis)

    def forward_ar(self, params: TPMLPParams, x: jax.Array) -> jax.Array:
        """Local GEMM -> act -> fused GEMM+AllReduce (reference
        ``dist_triton_AR_fwd``; preferred at small M, BASELINE.md).

        ``x``: (M, K) replicated.  Returns (M, K) replicated.
        """
        fused = replicated_column_gemm(self.mesh, self.axis, x, params.gate_up)
        h = self._act_combine(fused)
        return gemm_ar(h, params.down, self.mesh, self.axis)
