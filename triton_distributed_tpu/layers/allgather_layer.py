"""Persistent-workspace AllGather layer (double-buffered).

Reference: ``python/triton_dist/layers/nvidia/low_latency_allgather_layer.py:30``
— a layer owning a persistent symmetric workspace and parity signal sets so
back-to-back AllGathers never reallocate and a consumer may keep reading
call k's output while call k+1 runs.

TPU translation: the workspace is a :class:`core.symm.SymmetricBuffer` pair
(parity slots); each call writes its parity's buffer IN PLACE via Pallas
``input_output_aliases`` + jit donation — the XLA-world equivalent of the
reference's preallocated symmetric heap tensors.  The LL flag-in-data
protocol collapses: Pallas semaphores are kernel-scoped and the entry
barrier is 2 hops, so flags woven into payloads buy nothing on TPU
(SURVEY.md section 7); what the layer keeps is the allocation-free steady
state and the one-call-back read guarantee.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu
from jax.sharding import Mesh, PartitionSpec as P

from ..comm import allgather as ag
from ..core import compilation
from ..core.mesh import TP_AXIS
from ..lang.primitives import Team


@functools.lru_cache(maxsize=None)
def _build_ws_all_gather(
    mesh: Mesh,
    axis: str,
    method: ag.AllGatherMethod,
    shard_shape: tuple[int, ...],
    dtype: jnp.dtype,
):
    """AG call writing into a caller-owned workspace (aliased in/out)."""
    team = Team.of(mesh, axis)
    n = team.size
    m_local = shard_shape[0]
    kern, two_send_sems = ag._KERNELS[method]
    inner = functools.partial(kern, team, m_local)

    def kernel(x_ref, ws_ref, out_ref, *scratch):
        del ws_ref  # same memory as out_ref (aliased)
        inner(x_ref, out_ref, *scratch)

    call = pl.pallas_call(
        kernel,
        out_shape=jax.ShapeDtypeStruct(
            (n * m_local, *shard_shape[1:]), dtype
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pl.ANY),
            pl.BlockSpec(memory_space=pl.ANY),
        ],
        out_specs=pl.BlockSpec(memory_space=pl.ANY),
        input_output_aliases={1: 0},
        scratch_shapes=[
            pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((2,)) if two_send_sems
            else pltpu.SemaphoreType.DMA(()),
            pltpu.SemaphoreType.DMA((n,)),
        ],
        compiler_params=compilation.compiler_params(
            collective=True,
            collective_id=compilation.collective_id("allgather"),
        ),
        interpret=compilation.interpret_mode(),
    )

    ndim = len(shard_shape)
    return compilation.jit_shard_map(
        call, mesh,
        in_specs=(P(axis, *([None] * (ndim - 1))), P(*([None] * ndim))),
        out_specs=P(*([None] * ndim)),
        donate_argnums=(1,),
    )


@dataclasses.dataclass
class AllGatherLayer:
    """Double-buffered persistent AG: ``layer(x)`` gathers dim 0 of the
    ``axis``-sharded ``x`` into the current parity's workspace; the
    PREVIOUS call's result stays intact until the call after next."""

    mesh: Mesh
    local_rows: int
    trailing: tuple[int, ...]
    dtype: jnp.dtype = jnp.bfloat16
    axis: str = TP_AXIS
    method: ag.AllGatherMethod = ag.AllGatherMethod.AUTO

    def __post_init__(self):
        n = self.mesh.shape[self.axis]
        shape = (n * self.local_rows, *self.trailing)
        method = ag.resolve_method(
            self.method, (self.local_rows, *self.trailing), self.dtype, n
        )
        self._fn = _build_ws_all_gather(
            self.mesh, self.axis, method,
            (self.local_rows, *self.trailing), jnp.dtype(self.dtype),
        )
        from jax.sharding import NamedSharding

        rep = NamedSharding(self.mesh, P(*([None] * (1 + len(self.trailing)))))
        self._ws = [
            jax.device_put(jnp.zeros(shape, self.dtype), rep)
            for _ in range(2)
        ]
        self._calls = 0

    def __call__(self, x: jax.Array) -> jax.Array:
        slot = self._calls % 2
        out = self._fn(x, self._ws[slot])
        self._ws[slot] = out
        self._calls += 1
        return out
