"""MoE MLP layer: top-k routed experts under TP or EP parallelism.

Reference: the MoE stack of ``python/triton_dist`` — TP strategy =
AG + group-GEMM then group-GEMM + RS (``allgather_group_gemm.py:398-605``,
``moe_reduce_rs.py:486-816``); EP strategy = A2A dispatch -> local experts
-> A2A combine (``ep_a2a.py:37-310``, ``layers/nvidia/ep_a2a_layer.py:40``);
routing/index prep = ``moe_utils.py:94-360``.

TPU design: routing and sorting are per-rank jnp (XLA sorts); the
communication rides the framework's collectives (``ag_group_gemm`` /
``moe_reduce_rs`` for TP, ``ep_dispatch``/``ep_combine`` for EP); the
ragged expert GEMM is ``lax.ragged_dot`` everywhere.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..comm.all_to_all import AllToAllConfig, ep_combine, ep_dispatch
from ..comm.quantized import quantized_ep_combine, quantized_ep_dispatch
from ..core import mesh as mesh_lib
from ..core.mesh import TP_AXIS
from ..lang import quant
from ..ops.group_gemm import ag_group_gemm, moe_reduce_rs
from ..ops.moe_utils import (
    flatten_topk,
    global_presort_index,
    sort_by_expert,
    topk_route,
    unsort_combine,
)

# The fp8 pack/unpack machinery this layer pioneered (one-pass Pallas
# pack at ~255 GB/s vs 100-166 GB/s for the materialized XLA path,
# measured at the bench shape — BENCH r04) was promoted into the SHARED
# quant module (``lang.quant``, ISSUE 9), together with the
# straight-through custom-vjp transports (now ``comm.quantized``) — one
# home for every quantized wire.  The aliases below keep the historic
# names importable (bench.py, tests).
_FP8_SIDECAR = quant.SIDECAR
_build_pack_fp8 = functools.partial(quant._build_pack, wire_dtype="fp8")


def _pack_fp8(x: jax.Array) -> jax.Array:
    return quant.pack_rows(x, "fp8")


def _pack_fp8_xla(x: jax.Array) -> jax.Array:
    return quant._pack_rows_xla(x, "fp8")


def _unpack_fp8(u8: jax.Array, h: int, out_dtype) -> jax.Array:
    return quant.unpack_rows(u8, h, "fp8", out_dtype)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class MoEParams:
    """router: (K, E) replicated; w_up: (E, K, F) — or the fused
    (E, K, 2F) ``[gate | up]`` layout when the layer runs ``swiglu=True``
    (rank-blocked ``[gate_r | up_r]`` under TP; build it with
    ``MoEMLP.fuse_expert_gate_up``); w_dn: (E, F, K).  Expert weights are
    sharded on F (TP) or on E (EP)."""

    router: jax.Array
    w_up: jax.Array
    w_dn: jax.Array


@dataclasses.dataclass(frozen=True)
class MoEMLP:
    """``swiglu=False``: experts are single up-projections with ``act``
    applied to the output (the reference's group-GEMM data flow).
    ``swiglu=True``: experts are gated MLPs — ``w_up`` carries fused
    [gate | up] columns, (E, K, 2F); under TP the 2F columns are
    rank-blocked ``[gate_r | up_r]`` per rank (same layout as
    ``TPMLP.gate_up``) so the gating stays rank-local.  Qwen3-MoE experts
    are SwiGLU."""

    mesh: Mesh
    num_experts: int
    top_k: int = 2
    axis: str = TP_AXIS
    act: str = "silu"
    swiglu: bool = False
    renormalize: bool = True
    # EP A2A ships e4m3 payloads + f32 scale sidecars instead of the model
    # dtype (the reference's production low-latency A2A configuration);
    # experts still compute in the model dtype after dequantization.
    # ``"auto"`` enables the codec only when the A2A axis rides DCN
    # (cross-slice) hops: the measured economics (BENCH r04
    # ``net_us_per_token_hop_ici`` = -0.03 us vs ``_dcn`` = +1.06 us)
    # say the halved payload pays for the codec on the slow wire class
    # and not on the ICI torus.  True/False force it either way.
    fp8_wire: bool | str = False
    # Multi-slice EP (ISSUE 10): when set, the EP axis is the 2D
    # (dcn_axis x axis) mesh and dispatch/combine ride the hierarchical
    # TOPOLOGY-SCHEDULED all-to-all (``comm.hierarchical`` — DCN phase
    # launched first, farthest-first ICI emission order underneath);
    # the DCN hop's payload quantizes per ``fp8_wire`` (forward-only on
    # that hop — the straight-through transports cover the flat path).
    dcn_axis: str | None = None

    def __post_init__(self):
        if self.fp8_wire not in (True, False, "auto"):
            raise ValueError(
                f"fp8_wire must be True, False, or 'auto'; "
                f"got {self.fp8_wire!r}"
            )

    def fp8_wire_enabled(self, hdim: int | None = None) -> bool:
        """The resolved wire-codec decision for THIS layer's A2A axis:
        the codec ships when its NET time win is positive on the axis's
        wire class at the layer's ROW WIDTH (``tools.calibrate
        .codec_pays`` — measured link calibration when one exists, the
        documented cold-start numbers otherwise; with cold-start values
        this reproduces the old DCN-only rule exactly).  ``hdim``: the
        activation width the wire actually ships — narrow rows amortize
        the scale sidecar worse and can flip the economics.  With
        ``dcn_axis`` set, the decision keys on the DCN wire class — the
        hop the hierarchical path would actually quantize."""
        if self.fp8_wire == "auto":
            from ..tools import calibrate

            kwargs = {} if hdim is None else {"h": int(hdim)}
            axis = self.dcn_axis if self.dcn_axis is not None else self.axis
            return calibrate.codec_pays(
                mesh_lib.wire_class(self.mesh, axis), **kwargs)
        return bool(self.fp8_wire)

    @property
    def _ep_spec(self):
        """The PartitionSpec axis entry of EP-sharded dims: the combined
        (dcn, tp) tuple on a multi-slice layout, the flat axis
        otherwise."""
        return (self.dcn_axis, self.axis) if self.dcn_axis is not None \
            else self.axis

    @property
    def n(self) -> int:
        n = self.mesh.shape[self.axis]
        if self.dcn_axis is not None:
            n *= self.mesh.shape[self.dcn_axis]
        return n

    def _act(self):
        return dict(silu=jax.nn.silu, gelu=jax.nn.gelu, relu=jax.nn.relu)[self.act]

    def _combine(self, h: jax.Array) -> jax.Array:
        """Post-up-projection nonlinearity on a LOCAL column block: plain
        activation, or the gated split when ``swiglu`` (the local block is
        [gate_r | up_r], so the split is down the middle)."""
        if not self.swiglu:
            return self._act()(h)
        g, u = jnp.split(h, 2, axis=-1)
        return self._act()(g) * u

    # -- parameter construction ------------------------------------------

    def shard_params_tp(self, router, w_up, w_dn) -> MoEParams:
        """TP layout: every rank holds all experts, F-sharded."""
        return MoEParams(
            router=jax.device_put(
                router, NamedSharding(self.mesh, P(None, None))
            ),
            w_up=jax.device_put(
                w_up, NamedSharding(self.mesh, P(None, None, self.axis))
            ),
            w_dn=jax.device_put(
                w_dn, NamedSharding(self.mesh, P(None, self.axis, None))
            ),
        )

    def shard_params_ep(self, router, w_up, w_dn) -> MoEParams:
        """EP layout: experts partitioned across ranks (rank r owns the
        contiguous expert block [r*E/n, (r+1)*E/n); under ``dcn_axis``
        the ranks enumerate outer-major over (dcn, tp) — slice-blocked
        experts, the hierarchical A2A's global order)."""
        spec = self._ep_spec
        return MoEParams(
            router=jax.device_put(
                router, NamedSharding(self.mesh, P(None, None))
            ),
            w_up=jax.device_put(
                w_up, NamedSharding(self.mesh, P(spec, None, None))
            ),
            w_dn=jax.device_put(
                w_dn, NamedSharding(self.mesh, P(spec, None, None))
            ),
        )

    def fuse_expert_gate_up(self, gate: jax.Array, up: jax.Array,
                            *, ep: bool = False) -> jax.Array:
        """Fuse per-expert (E, K, F) gate/up into the (E, K, 2F) layout
        ``swiglu`` mode consumes: rank-blocked ``[gate_r | up_r]`` under TP
        (F columns sharded), plain ``[gate | up]`` under EP (experts
        sharded, F local)."""
        from .tp_mlp import fuse_column_shards

        n = 1 if ep else self.n
        return jax.vmap(lambda g, u: fuse_column_shards([g, u], n))(gate, up)

    def init(self, key: jax.Array, hidden: int, ffn: int, *,
             ep: bool = False, dtype=jnp.float32,
             scale: float = 0.02) -> MoEParams:
        kr, ku, kd = jax.random.split(key, 3)
        e = self.num_experts
        router = jax.random.normal(kr, (hidden, e), dtype) * scale
        if self.swiglu:
            kg = jax.random.fold_in(ku, 1)
            gate = jax.random.normal(kg, (e, hidden, ffn), dtype) * scale
            up = jax.random.normal(ku, (e, hidden, ffn), dtype) * scale
            w_up = self.fuse_expert_gate_up(gate, up, ep=ep)
        else:
            w_up = jax.random.normal(ku, (e, hidden, ffn), dtype) * scale
        w_dn = jax.random.normal(kd, (e, ffn, hidden), dtype) * scale
        return (self.shard_params_ep if ep else self.shard_params_tp)(
            router, w_up, w_dn
        )

    # -- routing prep (shared) -------------------------------------------

    def _route_and_sort(self, x, router):
        """Per-rank: route own tokens, flatten top-k, sort by expert.
        Returns globally stacked (x_sorted, splits, wflat, unsort)."""
        e, k = self.num_experts, self.top_k

        def local(x_loc, router_rep):
            logits = x_loc @ router_rep
            eid, wts = topk_route(logits, k, renormalize=self.renormalize)
            xr, eflat, wflat = flatten_topk(x_loc, eid, wts)
            xs, splits, unsort = sort_by_expert(xr, eflat, e)
            return xs, splits, wflat, unsort

        spec = self._ep_spec
        return jax.shard_map(
            local, mesh=self.mesh,
            in_specs=(P(spec, None), P(None, None)),
            out_specs=(P(spec, None), P(spec), P(spec), P(spec)),
        )(x, router)

    # -- TP forward -------------------------------------------------------

    def forward_tp(self, params: MoEParams, x: jax.Array) -> jax.Array:
        """Route -> AG + group-GEMM (up) -> act -> group-GEMM + RS (down).

        ``x``: (M, K) sharded on dim 0 over ``axis``.  Returns the same.
        """
        if self.dcn_axis is not None:
            raise ValueError(
                "forward_tp is single-slice; multi-slice MoE runs the EP "
                "strategy (forward_ep with dcn_axis)"
            )
        n = self.n
        x_sorted, splits, wflat, unsort = self._route_and_sort(
            x, params.router
        )
        h, total_splits, perm = ag_group_gemm(
            x_sorted, params.w_up, splits, self.mesh, self.axis
        )
        # the nonlinearity reads only this rank's column block (under
        # swiglu the block is [gate_r | up_r]) — keep it rank-local
        h = jax.shard_map(
            self._combine, mesh=self.mesh,
            in_specs=P(None, self.axis), out_specs=P(None, self.axis),
        )(h)
        t_per_rank = x_sorted.shape[0] // n
        presort = global_presort_index(
            perm, unsort.reshape(n, t_per_rank)
        )
        return moe_reduce_rs(
            h, params.w_dn, total_splits, presort, wflat, self.top_k,
            self.mesh, self.axis,
        )

    def _replicated_local_step(self, ep: bool):
        """Shared body of the small-M decode paths: route all tokens,
        ragged expert GEMMs against this rank's weight slice, weighted
        fold, one psum.  Under ``ep`` each rank additionally keeps only
        the rows routed to experts it owns (foreign rows park on local
        slot 0 with weight 0 — computed then discarded; B is tiny)."""
        e, k = self.num_experts, self.top_k
        epr = e // self.n

        def local(x_rep, router_rep, w_up_loc, w_dn_loc):
            eid, wts = topk_route(x_rep @ router_rep, k,
                                  renormalize=self.renormalize)
            xr, eflat, wflat = flatten_topk(x_rep, eid, wts)
            num_local = e
            if ep:
                r = jax.lax.axis_index(self.axis)
                local_eid = eflat - r * epr
                owned = (local_eid >= 0) & (local_eid < epr)
                wflat = jnp.where(owned, wflat, 0.0)
                eflat = jnp.where(owned, local_eid, 0).astype(jnp.int32)
                num_local = epr
            xs, splits, unsort = sort_by_expert(xr, eflat, num_local)
            h = self._combine(jax.lax.ragged_dot(xs, w_up_loc, splits))
            y = jax.lax.ragged_dot(h, w_dn_loc, splits)
            y = unsort_combine(y, unsort, wflat, k)
            return jax.lax.psum(y, self.axis).astype(x_rep.dtype)

        return local

    def forward_replicated(self, params: MoEParams, x: jax.Array) -> jax.Array:
        """Small-M decode path: replicated tokens against the TP (F-sharded)
        expert layout — local routed ragged GEMMs, then one psum; the MoE
        analogue of the dense layer's AR decode path (``Qwen3._mlp_decode``).

        ``x``: (B, K) replicated.  Returns (B, K) replicated.
        """
        return jax.shard_map(
            self._replicated_local_step(ep=False), mesh=self.mesh,
            in_specs=(P(None, None), P(None, None),
                      P(None, None, self.axis), P(None, self.axis, None)),
            out_specs=P(None, None),
            check_vma=False,
        )(x, params.router, params.w_up, params.w_dn)

    def forward_replicated_ep(self, params: MoEParams,
                              x: jax.Array) -> jax.Array:
        """Replicated small-batch decode against the EP (expert-partitioned)
        layout: every rank routes all tokens identically, computes only the
        contributions of the experts it owns, and one psum folds the routed
        sum — the latency-path analogue of the reference's low-latency EP
        decode (dispatching one-token batches over A2A would put two wire
        hops on the critical path for a sub-tile payload).

        ``x``: (B, K) replicated.  Returns (B, K) replicated.
        """
        return jax.shard_map(
            self._replicated_local_step(ep=True), mesh=self.mesh,
            in_specs=(P(None, None), P(None, None),
                      P(self.axis, None, None), P(self.axis, None, None)),
            out_specs=P(None, None),
            check_vma=False,
        )(x, params.router, params.w_up, params.w_dn)

    # -- EP forward -------------------------------------------------------

    def forward_ep(self, params: MoEParams, x: jax.Array,
                   *, a2a_config: AllToAllConfig | None = None) -> jax.Array:
        """Route -> A2A dispatch -> local expert MLP -> A2A combine ->
        weighted top-k fold (reference ``ep_a2a_layer.py:40``).

        With ``dcn_axis`` set the exchange is the hierarchical
        topology-SCHEDULED all-to-all (``comm.hierarchical``): the DCN
        phase launches first, the ICI phase pipelines underneath with
        the farthest-first emission order, and the DCN payload quantizes
        per the layer's wire policy.

        ``x``: (M, K) sharded on dim 0 over the EP axis (both axes when
        hierarchical).  Returns the same.
        """
        n = self.n
        e, k = self.num_experts, self.top_k
        epr = e // n
        hdim = x.shape[-1]
        x_dtype = x.dtype
        spec = self._ep_spec
        hier = self.dcn_axis is not None and \
            self.mesh.shape[self.dcn_axis] > 1
        x_sorted, splits, wflat, unsort = self._route_and_sort(
            x, params.router
        )
        fp8 = self.fp8_wire_enabled(hdim) and n > 1
        cfg = a2a_config or AllToAllConfig()
        if hier:
            from ..comm.hierarchical import (
                scheduled_ep_combine, scheduled_ep_dispatch,
            )

            wire = "fp8" if fp8 else "bf16"
            recv, recv_splits = scheduled_ep_dispatch(
                x_sorted, splits, self.mesh, self.axis, self.dcn_axis,
                config=cfg, wire_dtype=wire,
            )
        elif fp8:
            # quantized wire with a straight-through backward
            # (comm.quantized); zones come back dequantized to the model
            # dtype
            recv, recv_splits = quantized_ep_dispatch(
                self.mesh, self.axis, cfg, hdim, "fp8", x_sorted, splits
            )
        else:
            recv, recv_splits = ep_dispatch(
                x_sorted, splits, self.mesh, self.axis, config=cfg
            )
        z = recv.shape[1]
        # zones per rank: the flat A2A lands one zone per GLOBAL peer;
        # the hierarchical one lands one per INNER (merged) source
        n_src = recv.shape[0] // n
        combine = self._combine

        def local_experts(zones, rsplits, w_up_loc, w_dn_loc):
            # zones: (n_src, Z, K); rsplits: (n_src, epr).  Compact zone
            # rows into one expert-major run for a single ragged_dot,
            # then scatter back to zone layout for the combine.
            kdim = zones.shape[-1]
            flat = zones.reshape(n_src * z, kdim)
            # owned-expert index of each zone row; padding rows map to epr
            # (one past the last expert) and stable-sort to the tail
            j = jnp.arange(z)
            cum = jnp.cumsum(rsplits, axis=1)                   # (n_src, epr)
            eid = jax.vmap(
                lambda c: jnp.searchsorted(c, j, side="right")
            )(cum)                                              # (n_src, z)
            order = jnp.argsort(eid.reshape(n_src * z), stable=True)
            compact = jnp.take(flat, order, axis=0)
            gsz = rsplits.sum(axis=0).astype(jnp.int32)              # (epr,)
            h_loc = combine(jax.lax.ragged_dot(compact, w_up_loc, gsz))
            y = jax.lax.ragged_dot(h_loc, w_dn_loc, gsz)
            # rows past sum(gsz) belong to no expert; zero them before the
            # scatter so padding rows stay inert through the combine
            valid = jnp.arange(n_src * z) < gsz.sum()
            y = jnp.where(valid[:, None], y, 0)
            y = y.astype(x_dtype)
            out = jnp.zeros((n_src * z, y.shape[-1]), y.dtype)
            return out.at[order].set(y).reshape(n_src, z, -1)

        processed = jax.shard_map(
            local_experts, mesh=self.mesh,
            in_specs=(P(spec, None, None), P(spec, None),
                      P(spec, None, None), P(spec, None, None)),
            out_specs=P(spec, None, None),
        )(
            recv.reshape(n, n_src, z, -1).reshape(n * n_src, z, -1),
            recv_splits.reshape(n * n_src, epr),
            params.w_up, params.w_dn,
        )
        t_loc = x_sorted.shape[0] // n
        if hier:
            back = scheduled_ep_combine(
                processed, splits, self.mesh, self.axis, self.dcn_axis,
                token_dim=t_loc, config=cfg,
                wire_dtype="fp8" if fp8 else "bf16",
            )
        elif fp8:
            # quantized return hop, straight-through backward
            back = quantized_ep_combine(self.mesh, self.axis, cfg, hdim,
                                        "fp8", t_loc, processed, splits)
        else:
            back = ep_combine(
                processed, splits, self.mesh, self.axis,
                token_dim=t_loc, config=cfg,
            )

        # per-rank: unsort and weighted fold
        def fold(y_loc, unsort_loc, w_loc):
            return unsort_combine(y_loc, unsort_loc, w_loc, k)

        return jax.shard_map(
            fold, mesh=self.mesh,
            in_specs=(P(spec, None), P(spec), P(spec)),
            out_specs=P(spec, None),
        )(back, unsort, wflat)
