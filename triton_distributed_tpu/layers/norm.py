"""Normalization layers (RMSNorm) as jnp expressions.

The reference carries a Triton ``layer_norm`` kernel for the QK-norm path
(``python/triton_dist/layers/nvidia/tp_attn.py:219-226``); on TPU a
reduction+elementwise chain is exactly what XLA fuses into neighbouring
matmuls, so the native form is the expression below (SURVEY.md section 7).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rms_norm(x: jax.Array, weight: jax.Array | None = None,
             eps: float = 1e-6) -> jax.Array:
    """RMSNorm over the last axis, computed in f32 (Qwen/LLaMA convention:
    the scale multiplies the normalized value in f32, result cast back)."""
    xf = x.astype(jnp.float32)
    var = jnp.mean(xf * xf, axis=-1, keepdims=True)
    out = xf * jax.lax.rsqrt(var + eps)
    if weight is not None:
        out = out * weight.astype(jnp.float32)
    return out.astype(x.dtype)
