"""TP/EP/SP layers as functional pytree modules (reference:
``python/triton_dist/layers/nvidia/`` — TP_MLP, TP_Attn, EP A2A,
SP flash-decode, low-latency AG layers)."""

from .moe import MoEMLP, MoEParams
from .norm import rms_norm
from .tp_attn import TPAttn, TPAttnParams
from .tp_mlp import TPMLP, TPMLPParams, fuse_column_shards
