"""Qwen3-style TP decoder model on the fused collective ops.

Reference: ``python/triton_dist/models/qwen.py:54-300`` — ``Qwen3Layer``
(TP_Attn + TP_MLP + the two RMSNorms) and ``Qwen3`` (embedding, layer
stack, lm_head) with per-mode forwards (torch / triton_dist / AR).

TPU translation of the mode split, by arithmetic intensity (the same
criterion the reference's engine applies):

- **prefill** (M = B*S tokens, MXU-bound): sequence-sharded activations
  through the fused AG-GEMM -> local flash-attn -> GEMM-RS layer path,
  the ``dist_triton_fwd`` analogue.  K/V heads computed per rank land
  directly in the head-sharded cache.
- **decode** (M = B rows): replicated activations, local column GEMMs,
  and a row-parallel reduction whose implementation is switched by
  ``decode_mode`` — the reference's ``set_fwd('torch'|'triton_dist'|
  'triton_dist_AR')`` (``models/qwen.py:85,143``):

  * ``"psum"`` — ``lax.psum`` after a local GEMM: XLA's fused latency
    path, the right default at B=1 where the payload is sub-tile;
  * ``"ar"`` — local GEMM then the Pallas fast-AllReduce family
    (one-shot/two-shot by size), the reference's GEMM + fast-AR decode
    configuration that wins 1.27-1.37x at B=128-4096
    (``docs/getting-started/e2e/e2e_dense.md`` "GEMM + AllReduce");
  * ``"gemm_ar"`` — the fully fused GEMM+AllReduce ring kernel
    (compute hides the wire) when B divides by tp, else the "ar" path.

  The decode attention itself is the split-KV Pallas kernel against the
  head-sharded cache.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.mesh import TP_AXIS
from ..layers.moe import MoEMLP, MoEParams
from ..layers.norm import rms_norm
from ..layers.tp_attn import TPAttn, TPAttnParams
from ..layers.tp_mlp import TPMLP, TPMLPParams
from ..comm.allreduce import all_reduce
from ..ops import ag_gemm, gemm_ar, gemm_rs
from ..ops.attention import (
    decode_attention,
    flash_attention,
    paged_decode_attention,
)
from ..ops import persistent_decode as pd
from ..ops.fused_decode import (
    fused_attn_decode,
    fused_linear_ar,
    fused_mlp_ar,
)
from ..ops.rope import apply_rope_at
from .config import ModelConfig
from .kv_cache import (
    KVCache,
    PagedKVCache,
    advance,
    append_layer_quantized,
    layer_pool,
    replace_layer_slices,
    with_length,
    write_chunk_paged,
    write_prefill,
    write_prefill_paged,
)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QwenLayerParams:
    ln1: jax.Array
    attn: TPAttnParams
    ln2: jax.Array
    mlp: "TPMLPParams | MoEParams"   # MoEParams when config.is_moe


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class QwenParams:
    embed: jax.Array          # (V, K) replicated
    layers: list[QwenLayerParams]
    final_norm: jax.Array     # (K,)
    lm_head: jax.Array        # (K, V) replicated


DECODE_MODES = ("psum", "ar", "gemm_ar", "fused", "persistent")


def stack_decode_params(params: QwenParams) -> pd.StackedDecodeParams:
    """Stack the per-layer decode weights on a leading (L,) axis — the
    persistent megakernel's weight layout (``ops.persistent_decode``).
    Runs under jit (one concatenate per array per traced bundle, hoisted
    outside the step scan by ``Qwen3.decode_multi``); layouts pass
    through unchanged (``wqkv`` rank-blocked ``[q_r|k_r|v_r]``,
    ``gate_up`` rank-blocked ``[gate_r|up_r]``)."""
    layers = params.layers
    qk = layers[0].attn.q_norm is not None
    return pd.StackedDecodeParams(
        ln1=jnp.stack([lp.ln1 for lp in layers]),
        wqkv=jnp.stack([lp.attn.wqkv for lp in layers]),
        q_norm=jnp.stack([lp.attn.q_norm for lp in layers]) if qk else None,
        k_norm=jnp.stack([lp.attn.k_norm for lp in layers]) if qk else None,
        wo=jnp.stack([lp.attn.wo for lp in layers]),
        ln2=jnp.stack([lp.ln2 for lp in layers]),
        gate_up=jnp.stack([lp.mlp.gate_up for lp in layers]),
        down=jnp.stack([lp.mlp.down for lp in layers]),
    )


@dataclasses.dataclass(frozen=True)
class Qwen3:
    """Static model definition; params/cache travel separately.

    ``decode_mode`` switches the decode-step row-parallel reductions
    (o-proj and MLP down-proj) between ``lax.psum`` and the Pallas
    AllReduce kernels — the reference's ``set_fwd`` mode switch
    (``models/qwen.py:85,143``).  Static: changing it retriggers jit.

    ``"fused"`` is the decode MEGAKERNEL mode (``ops.fused_decode``,
    docs/perf.md "Decode megakernel"): on a paged cache each layer's
    attention side (qkv + qk-norm + rope + ragged KV-append + block-table
    flash decode) collapses into one kernel with the pool updated in
    place, and both row-parallel reductions run the semaphore-chained
    SwiGLU/linear + two-shot-AllReduce column-ring kernel instead of
    returning to the host between the GEMM and the reduction.  Shapes
    the fused kernels cannot serve (hidden or intermediate not divisible
    by tp) fall back per-site to the ``psum`` path — the per-kernel
    paths stay the parity reference.
    """

    config: ModelConfig
    mesh: Mesh
    axis: str = TP_AXIS
    decode_mode: str = "psum"

    def __post_init__(self):
        if self.decode_mode not in DECODE_MODES:
            raise ValueError(
                f"decode_mode {self.decode_mode!r} not in {DECODE_MODES}"
            )

    @property
    def tp(self) -> int:
        return self.mesh.shape[self.axis]

    def _row_parallel_reduce(self, h: jax.Array, w: jax.Array) -> jax.Array:
        """Decode-step ``AllReduce(h @ w)``: ``h`` (B, F) sharded on dim 1
        over ``axis``, ``w`` (F, H) row-parallel, result (B, H) replicated.
        Dispatches on ``decode_mode`` (see class docstring)."""
        n = self.tp
        if (self.decode_mode in ("fused", "persistent")
                and h.shape[1] % n == 0 and w.shape[1] % n == 0):
            # megakernel mode: semaphore-chained GEMM + two-shot AR ring
            # over output-column chunks — any B rides (ops.fused_decode);
            # n == 1 degenerates to the plain local GEMM without the
            # shard_map/psum wrappers
            return fused_linear_ar(h, w, self.mesh, self.axis)
        if (self.decode_mode == "gemm_ar" and n > 1
                and h.shape[0] % n == 0 and h.shape[1] % n == 0):
            # fused ring kernel: chunks M and the K dim n ways in-kernel
            return gemm_ar(h, w, self.mesh, self.axis)
        if self.decode_mode in ("ar", "gemm_ar") and n > 1:
            def local(h_loc, w_loc):
                return jnp.dot(
                    h_loc, w_loc, preferred_element_type=jnp.float32
                ).astype(h_loc.dtype)

            partials = jax.shard_map(
                local, mesh=self.mesh,
                in_specs=(P(None, self.axis), P(self.axis, None)),
                out_specs=P(self.axis, None),
                check_vma=False,
            )(h, w)   # (n*B, H) stacked partials
            return all_reduce(partials, self.mesh, self.axis)

        def local_psum(h_loc, w_loc):
            part = jnp.dot(h_loc, w_loc, preferred_element_type=jnp.float32)
            return jax.lax.psum(part, self.axis).astype(h_loc.dtype)

        return jax.shard_map(
            local_psum, mesh=self.mesh,
            in_specs=(P(None, self.axis), P(self.axis, None)),
            out_specs=P(None, None),
        )(h, w)

    def _attn_layer(self) -> TPAttn:
        c = self.config
        return TPAttn(
            self.mesh, num_heads=c.num_heads, num_kv_heads=c.num_kv_heads,
            head_dim=c.head_dim, axis=self.axis, rope_theta=c.rope_theta,
            qk_norm_eps=c.rms_eps if c.qk_norm else None,
        )

    def _mlp_layer(self) -> TPMLP:
        return TPMLP(self.mesh, axis=self.axis)

    def _moe_layer(self) -> MoEMLP:
        c = self.config
        return MoEMLP(
            self.mesh, num_experts=c.num_experts, top_k=c.top_k,
            axis=self.axis, swiglu=True, renormalize=c.norm_topk,
            fp8_wire=c.moe_fp8_wire,
        )

    def _mlp_forward(self, p, x: jax.Array) -> jax.Array:
        """Prefill MLP: dense fused path or routed MoE (config strategy:
        TP = experts F-sharded, AG + group-GEMM + RS; EP = experts
        partitioned, A2A dispatch/combine)."""
        c = self.config
        if c.is_moe:
            moe = self._moe_layer()
            if c.moe_strategy == "ep":
                return moe.forward_ep(p, x)
            return moe.forward_tp(p, x)
        return self._mlp_layer().forward(p, x)

    def _mlp_decode_step(self, p, x: jax.Array) -> jax.Array:
        c = self.config
        if c.is_moe:
            moe = self._moe_layer()
            if c.moe_strategy == "ep":
                return moe.forward_replicated_ep(p, x)
            return moe.forward_replicated(p, x)
        return self._mlp_decode(p, x)

    # -- parameters -------------------------------------------------------

    def init(self, key: jax.Array, scale: float = 0.02) -> QwenParams:
        c = self.config
        attn_l = self._attn_layer()
        keys = jax.random.split(key, 2 * c.num_layers + 3)
        layers = []
        for li in range(c.num_layers):
            if c.is_moe:
                mlp = self._moe_layer().init(
                    keys[2 * li + 1], c.hidden, c.moe_intermediate,
                    ep=c.moe_strategy == "ep", dtype=c.dtype, scale=scale,
                )
            else:
                mlp = self._mlp_layer().init(
                    keys[2 * li + 1], c.hidden, c.intermediate,
                    dtype=c.dtype, scale=scale,
                )
            layers.append(QwenLayerParams(
                ln1=jnp.ones((c.hidden,), c.dtype),
                attn=attn_l.init(keys[2 * li], c.hidden, dtype=c.dtype,
                                 scale=scale),
                ln2=jnp.ones((c.hidden,), c.dtype),
                mlp=mlp,
            ))
        rep = NamedSharding(self.mesh, P(None, None))
        embed = jax.device_put(
            jax.random.normal(keys[-2], (c.vocab, c.hidden), c.dtype) * scale,
            rep,
        )
        lm_head = jax.device_put(
            jax.random.normal(keys[-1], (c.hidden, c.vocab), c.dtype) * scale,
            rep,
        )
        return QwenParams(
            embed=embed, layers=layers,
            final_norm=jnp.ones((c.hidden,), c.dtype), lm_head=lm_head,
        )

    # -- prefill ----------------------------------------------------------

    def _attn_prefill(self, p: TPAttnParams, x: jax.Array, batch: int,
                      seq: int):
        """AG-GEMM -> per-rank (QK-norm, RoPE, flash) -> GEMM-RS; also
        emits this layer's K/V heads for the cache."""
        c = self.config
        n = self.tp
        h_loc, hk_loc, d = c.num_heads // n, c.num_kv_heads // n, c.head_dim
        qkv = ag_gemm(x, p.wqkv, self.mesh, self.axis)

        def local(qkv_loc, qn, kn):
            q, k, v = jnp.split(
                qkv_loc, [h_loc * d, (h_loc + hk_loc) * d], axis=-1
            )

            def to_heads(t, nh):
                return t.reshape(batch, seq, nh, d).transpose(0, 2, 1, 3)

            q, k, v = to_heads(q, h_loc), to_heads(k, hk_loc), to_heads(v, hk_loc)
            if c.qk_norm:
                q = rms_norm(q, qn, c.rms_eps)
                k = rms_norm(k, kn, c.rms_eps)
            pos = jnp.arange(seq)
            q = apply_rope_at(q, pos, theta=c.rope_theta)
            k = apply_rope_at(k, pos, theta=c.rope_theta)
            out = flash_attention(q, k, v, causal=True)
            out = out.transpose(0, 2, 1, 3).reshape(batch * seq, h_loc * d)
            return out, k, v

        out, k_new, v_new = jax.shard_map(
            local, mesh=self.mesh,
            in_specs=(P(None, self.axis), P(None), P(None)),
            out_specs=(P(None, self.axis),
                       P(None, self.axis, None, None),
                       P(None, self.axis, None, None)),
            check_vma=False,
        )(qkv, p.q_norm, p.k_norm)
        return gemm_rs(out, p.wo, self.mesh, self.axis), k_new, v_new

    def prefill(self, params: QwenParams, cache: KVCache,
                input_ids: jax.Array, true_len: jax.Array | int | None = None):
        """Forward all prompt tokens; fills the cache.  ``input_ids``:
        (B, S).  Returns (logits (B, S, V), cache).

        ``true_len`` (scalar, traceable) marks the REAL prompt length when
        ``input_ids`` is right-padded to a bucketed shape (the AOT serving
        path, ``Engine.precompile``): attention is causal, so pad
        positions never influence logits at positions < true_len, and
        setting the cache length to ``true_len`` masks the garbage K/V
        the pads wrote — the next decode step overwrites position
        true_len and proceeds as if the pads never ran.  One compiled
        bucket executable therefore serves every prompt length <= its
        shape exactly.
        """
        c = self.config
        b, s = input_ids.shape
        x = params.embed[input_ids.reshape(-1)]
        x = jax.lax.with_sharding_constraint(
            x, NamedSharding(self.mesh, P(self.axis, None))
        )
        for li, lp in enumerate(params.layers):
            attn_out, k_new, v_new = self._attn_prefill(
                lp.attn, rms_norm(x, lp.ln1, c.rms_eps), b, s
            )
            if isinstance(cache, PagedKVCache):
                cache = write_prefill_paged(cache, li, k_new, v_new)
            else:
                cache = write_prefill(cache, li, k_new, v_new)
            x = x + attn_out
            x = x + self._mlp_forward(lp.mlp, rms_norm(x, lp.ln2, c.rms_eps))
        x = rms_norm(x, params.final_norm, c.rms_eps)
        logits = jnp.dot(x, params.lm_head,
                         preferred_element_type=jnp.float32)
        # prefill always writes positions [0, s): SET the length rather than
        # advancing it, so a stale cache cannot desynchronize from the data
        # (true_len < s = the bucketed-pad case, see the docstring)
        return (
            logits.reshape(b, s, c.vocab),
            with_length(cache, s if true_len is None else true_len),
        )

    # -- chunked prefill (serving scheduler path) --------------------------

    def _heads_from_qkv(self, qkv: jax.Array, b: int, s: int):
        """Split a (B, S, (H+2Hk)*D) qkv projection whose feature dim is
        RANK-BLOCKED ``[q_r | k_r | v_r]`` per TP rank (the layout
        ``ag_gemm`` produces and the head-sharded cache consumes) into
        (B, S, H, D) / (B, S, Hk, D) / (B, S, Hk, D) with rank-major
        global head order — the same order the cache's sharded head axis
        holds, so chunk-written K/V and fused-prefill K/V interleave
        correctly."""
        c = self.config
        n = self.tp
        hl, hkl, d = c.num_heads // n, c.num_kv_heads // n, c.head_dim
        t = qkv.reshape(b, s, n, (hl + 2 * hkl) * d)
        q = t[..., :hl * d].reshape(b, s, n * hl, d)
        k = t[..., hl * d:(hl + hkl) * d].reshape(b, s, n * hkl, d)
        v = t[..., (hl + hkl) * d:].reshape(b, s, n * hkl, d)
        return q, k, v

    def prefill_chunk(self, params: QwenParams, cache: PagedKVCache,
                      input_ids: jax.Array, start: jax.Array | int,
                      true_len: jax.Array | int | None = None):
        """Prefill ONE chunk of a prompt against the paged pool: write
        this chunk's K/V at positions [start, start+S) through the block
        table, attend each chunk query over the CACHED PREFIX plus the
        chunk (causal), and return (logits (B, S, V), cache) with
        ``seq_lens`` set to ``start + true_len``.

        This is the serving scheduler's admission path
        (``serve.EngineBackend``): a long prompt is fed in fixed-size
        chunks interleaved with in-flight decode steps, so one arrival
        cannot stall cohabitants for its whole prompt.  ``start`` and
        ``true_len`` are traceable scalars — ONE jitted executable
        serves every (chunk position, pad amount), the same
        pad-and-mask contract bucketed AOT prefill uses.  Pad positions
        write garbage K/V beyond ``start + true_len``; the next chunk
        (or the first decode append) overwrites them and ``seq_lens``
        masks them meanwhile — and any position past the mapped pages
        lands in the slot view's scrap page, never in a neighbor.

        Implementation note: plain jnp (GSPMD inserts the TP
        reductions) rather than the fused AG-GEMM/flash path — the
        chunk path optimizes for retrace-freedom and prefix attention
        through the block table, not peak prefill flops; whole-prompt
        admission still uses the fused :meth:`prefill`.  Dense MLP
        only (MoE prompts prefill whole)."""
        c = self.config
        if c.is_moe:
            raise NotImplementedError(
                "prefill_chunk supports the dense MLP path; MoE prompts "
                "prefill whole via Qwen3.prefill")
        b, s = input_ids.shape
        n = self.tp
        d = c.head_dim
        start = jnp.asarray(start, jnp.int32)
        tl = jnp.asarray(s if true_len is None else true_len, jnp.int32)
        pos = start + jnp.arange(s, dtype=jnp.int32)          # (S,)
        x = params.embed[input_ids]                           # (B, S, K)
        max_len = cache.max_pages * cache.page_size

        for li, lp in enumerate(params.layers):
            h = rms_norm(x, lp.ln1, c.rms_eps)
            qkv = jnp.dot(h, lp.attn.wqkv,
                          preferred_element_type=jnp.float32).astype(x.dtype)
            q, k, v = self._heads_from_qkv(qkv, b, s)
            if c.qk_norm:
                q = rms_norm(q, lp.attn.q_norm, c.rms_eps)
                k = rms_norm(k, lp.attn.k_norm, c.rms_eps)
            # (B, H, S, D) for rope-at-positions, then the pool write
            q = apply_rope_at(q.transpose(0, 2, 1, 3), pos,
                              theta=c.rope_theta)
            k = apply_rope_at(k.transpose(0, 2, 1, 3), pos,
                              theta=c.rope_theta)
            v = v.transpose(0, 2, 1, 3)
            cache = write_chunk_paged(cache, li, k, v, start)
            # prefix attention through the block table: materialize the
            # slot's logical [0, max_len) K/V (chunk included — it was
            # just written; an int8 cache dequantizes here — the chunk
            # path trades pool materialization for retrace-freedom, see
            # kv_cache.layer_pool) and mask causally at absolute positions
            k_pool_l, v_pool_l = layer_pool(cache, li, x.dtype)
            kc = k_pool_l[cache.block_table]        # (B, mp, Hk, ps, D)
            vc = v_pool_l[cache.block_table]
            kc = kc.transpose(0, 2, 1, 3, 4).reshape(
                b, c.num_kv_heads, max_len, d)
            vc = vc.transpose(0, 2, 1, 3, 4).reshape(
                b, c.num_kv_heads, max_len, d)
            rep = c.num_heads // c.num_kv_heads
            kc = jnp.repeat(kc, rep, axis=1)
            vc = jnp.repeat(vc, rep, axis=1)
            scores = jnp.einsum(
                "bhqd,bhkd->bhqk", q.astype(jnp.float32),
                kc.astype(jnp.float32)) * (d ** -0.5)
            causal = (jnp.arange(max_len, dtype=jnp.int32)[None, :]
                      <= pos[:, None])                       # (S, L)
            scores = jnp.where(causal[None, None], scores, -jnp.inf)
            probs = jax.nn.softmax(scores, axis=-1).astype(x.dtype)
            attn = jnp.einsum("bhqk,bhkd->bhqd", probs, vc)
            attn = attn.transpose(0, 2, 1, 3).reshape(
                b, s, c.num_heads * d)
            x = x + jnp.dot(attn, lp.attn.wo,
                            preferred_element_type=jnp.float32
                            ).astype(x.dtype)
            # dense MLP, rank-blocked [gate_r | up_r] feature layout
            h2 = rms_norm(x, lp.ln2, c.rms_eps)
            fused = jnp.dot(h2, lp.mlp.gate_up,
                            preferred_element_type=jnp.float32
                            ).astype(x.dtype)
            t = fused.reshape(b, s, n, 2, c.intermediate // n)
            act = (jax.nn.silu(t[..., 0, :]) * t[..., 1, :]).reshape(
                b, s, c.intermediate)
            x = x + jnp.dot(act, lp.mlp.down,
                            preferred_element_type=jnp.float32
                            ).astype(x.dtype)
        x = rms_norm(x, params.final_norm, c.rms_eps)
        logits = jnp.dot(x, params.lm_head,
                         preferred_element_type=jnp.float32)
        return logits, with_length(cache, start + tl)

    # -- decode -----------------------------------------------------------

    def _attn_decode(self, p: TPAttnParams, x: jax.Array, cache: KVCache,
                     layer: int):
        """Replicated-activation decode step against the sharded cache."""
        c = self.config
        n = self.tp
        h_loc, hk_loc, d = c.num_heads // n, c.num_kv_heads // n, c.head_dim
        b = x.shape[0]
        pos = cache.kv_len

        def local(x_rep, wqkv_loc, qn, kn, k_cache_l, v_cache_l, pos):
            qkv = jnp.dot(x_rep, wqkv_loc,
                          preferred_element_type=jnp.float32).astype(x_rep.dtype)
            q, k, v = jnp.split(
                qkv, [h_loc * d, (h_loc + hk_loc) * d], axis=-1
            )
            q = q.reshape(b, h_loc, 1, d)
            k = k.reshape(b, hk_loc, 1, d)
            v = v.reshape(b, hk_loc, 1, d)
            if c.qk_norm:
                q = rms_norm(q, qn, c.rms_eps)
                k = rms_norm(k, kn, c.rms_eps)
            q = apply_rope_at(q, pos[None], theta=c.rope_theta)
            k = apply_rope_at(k, pos[None], theta=c.rope_theta)
            # cache append is LOCAL per rank (head-sharded slices)
            k_cache_l = jax.lax.dynamic_update_slice(
                k_cache_l, k, (0, 0, pos, 0)
            )
            v_cache_l = jax.lax.dynamic_update_slice(
                v_cache_l, v, (0, 0, pos, 0)
            )
            out = decode_attention(
                q[:, :, 0], k_cache_l, v_cache_l, pos + 1
            )  # (b, h_loc, d)
            return out.reshape(b, h_loc * d), k_cache_l, v_cache_l

        out, k_l, v_l = jax.shard_map(
            local, mesh=self.mesh,
            in_specs=(P(None, None), P(None, self.axis), P(None), P(None),
                      P(None, self.axis, None, None),
                      P(None, self.axis, None, None), P()),
            out_specs=(P(None, self.axis),
                       P(None, self.axis, None, None),
                       P(None, self.axis, None, None)),
            check_vma=False,
        )(x, p.wqkv, p.q_norm, p.k_norm, cache.k[layer], cache.v[layer], pos)

        # out-projection: row-parallel reduce by decode_mode (psum at B=1
        # sub-tile payloads; fast-AR kernels at batch).  The layer's
        # updated K/V slices travel back to the decode loop, which
        # rebuilds the stacked pool ONCE after all layers
        # (kv_cache.replace_layer_slices) instead of rewriting the whole
        # (L, ...) pool per layer.
        return self._row_parallel_reduce(out, p.wo), k_l, v_l

    def _attn_decode_paged(self, p: TPAttnParams, x: jax.Array,
                           cache: PagedKVCache, layer: int):
        """Decode step against the paged pool: per-sequence RAGGED
        positions, token append as a pool scatter, attention through the
        block-table kernel (reference ``gqa_fwd_batch_decode`` +
        ``block_table``, ``flash_decode.py:587-720``).

        On an int8-quantized cache (ISSUE 9) the append goes through the
        exact dequant-merge-requant scatter
        (``kv_cache.append_layer_quantized``) and the attention kernel
        dequantizes in its page-streaming loop (``k_scale``/``v_scale``)
        — the pool stays int8 end to end."""
        c = self.config
        n = self.tp
        h_loc, hk_loc, d = c.num_heads // n, c.num_kv_heads // n, c.head_dim
        b = x.shape[0]
        quantized = cache.quantized

        def project(x_rep, wqkv_loc, qn, kn, lens):
            qkv = jnp.dot(x_rep, wqkv_loc,
                          preferred_element_type=jnp.float32).astype(x_rep.dtype)
            q, k, v = jnp.split(
                qkv, [h_loc * d, (h_loc + hk_loc) * d], axis=-1
            )
            q = q.reshape(b, h_loc, 1, d)
            k = k.reshape(b, hk_loc, 1, d)
            v = v.reshape(b, hk_loc, 1, d)
            if c.qk_norm:
                q = rms_norm(q, qn, c.rms_eps)
                k = rms_norm(k, kn, c.rms_eps)
            pos = lens[:, None, None]        # (B, 1, 1): per-seq positions
            q = apply_rope_at(q, pos, theta=c.rope_theta)
            k = apply_rope_at(k, pos, theta=c.rope_theta)
            return q, k, v

        if quantized:
            def local_q(x_rep, wqkv_loc, qn, kn, pool_k_l, pool_v_l,
                        ksc_l, vsc_l, table, lens):
                q, k, v = project(x_rep, wqkv_loc, qn, kn, lens)
                pk, pv, ksc, vsc = append_layer_quantized(
                    pool_k_l, pool_v_l, ksc_l, vsc_l, table, lens,
                    k[:, :, 0], v[:, :, 0])
                out = paged_decode_attention(
                    q[:, :, 0], pk, pv, table, lens + 1,
                    k_scale=ksc, v_scale=vsc,
                )  # (b, h_loc, d)
                return out.reshape(b, h_loc * d), pk, pv, ksc, vsc

            out, k_l, v_l, ksc_l, vsc_l = jax.shard_map(
                local_q, mesh=self.mesh,
                in_specs=(P(None, None), P(None, self.axis), P(None),
                          P(None),
                          P(None, self.axis, None, None),
                          P(None, self.axis, None, None),
                          P(None, self.axis), P(None, self.axis),
                          P(None, None), P(None)),
                out_specs=(P(None, self.axis),
                           P(None, self.axis, None, None),
                           P(None, self.axis, None, None),
                           P(None, self.axis), P(None, self.axis)),
                check_vma=False,
            )(x, p.wqkv, p.q_norm, p.k_norm, cache.k[layer],
              cache.v[layer], cache.k_scale[layer], cache.v_scale[layer],
              cache.block_table, cache.seq_lens)
            return (self._row_parallel_reduce(out, p.wo), k_l, v_l,
                    ksc_l, vsc_l)

        def local(x_rep, wqkv_loc, qn, kn, pool_k_l, pool_v_l, table, lens):
            q, k, v = project(x_rep, wqkv_loc, qn, kn, lens)
            # ragged append: each sequence's token into its own page slot
            ps = pool_k_l.shape[2]
            pages = jnp.take_along_axis(
                table, (lens // ps)[:, None], axis=1
            )[:, 0]
            offs = lens % ps
            pool_k_l = pool_k_l.at[pages, :, offs].set(
                k[:, :, 0].astype(pool_k_l.dtype)
            )
            pool_v_l = pool_v_l.at[pages, :, offs].set(
                v[:, :, 0].astype(pool_v_l.dtype)
            )
            out = paged_decode_attention(
                q[:, :, 0], pool_k_l, pool_v_l, table, lens + 1
            )  # (b, h_loc, d)
            return out.reshape(b, h_loc * d), pool_k_l, pool_v_l

        out, k_l, v_l = jax.shard_map(
            local, mesh=self.mesh,
            in_specs=(P(None, None), P(None, self.axis), P(None), P(None),
                      P(None, self.axis, None, None),
                      P(None, self.axis, None, None),
                      P(None, None), P(None)),
            out_specs=(P(None, self.axis),
                       P(None, self.axis, None, None),
                       P(None, self.axis, None, None)),
            check_vma=False,
        )(x, p.wqkv, p.q_norm, p.k_norm, cache.k[layer], cache.v[layer],
          cache.block_table, cache.seq_lens)
        return self._row_parallel_reduce(out, p.wo), k_l, v_l

    def _attn_decode_paged_fused(self, p: TPAttnParams, x: jax.Array,
                                 cache: PagedKVCache, layer: int):
        """The attention megakernel step (``decode_mode="fused"``): qkv
        GEMM, qk-norm, rope, the ragged paged append and the block-table
        flash decode run as ONE ``pallas_call`` per rank
        (``ops.fused_decode.fused_attn_decode``), with the page pool
        updated in place through ``input_output_aliases`` — the four
        dispatches plus the ``.at[].set`` pool scatter of
        :meth:`_attn_decode_paged` collapse into a single launch."""
        c = self.config

        if cache.quantized:
            # megakernel with fused page-stream dequant; the projected
            # token comes back full-precision and appends through the
            # exact quantized scatter (see ops.fused_decode)
            def local_q(x_rep, wqkv_loc, qn, kn, pool_k_l, pool_v_l,
                        ksc_l, vsc_l, table, lens):
                out, pk, pv, ktok, vtok = fused_attn_decode(
                    x_rep, wqkv_loc, qn, kn, pool_k_l, pool_v_l, table,
                    lens, rope_theta=c.rope_theta,
                    qk_eps=c.rms_eps if c.qk_norm else None,
                    k_scale=ksc_l, v_scale=vsc_l,
                )
                pk, pv, ksc, vsc = append_layer_quantized(
                    pk, pv, ksc_l, vsc_l, table, lens, ktok, vtok)
                return out, pk, pv, ksc, vsc

            out, k_l, v_l, ksc_l, vsc_l = jax.shard_map(
                local_q, mesh=self.mesh,
                in_specs=(P(None, None), P(None, self.axis), P(None),
                          P(None),
                          P(None, self.axis, None, None),
                          P(None, self.axis, None, None),
                          P(None, self.axis), P(None, self.axis),
                          P(None, None), P(None)),
                out_specs=(P(None, self.axis),
                           P(None, self.axis, None, None),
                           P(None, self.axis, None, None),
                           P(None, self.axis), P(None, self.axis)),
                check_vma=False,
            )(x, p.wqkv, p.q_norm, p.k_norm, cache.k[layer],
              cache.v[layer], cache.k_scale[layer], cache.v_scale[layer],
              cache.block_table, cache.seq_lens)
            return (self._row_parallel_reduce(out, p.wo), k_l, v_l,
                    ksc_l, vsc_l)

        def local(x_rep, wqkv_loc, qn, kn, pool_k_l, pool_v_l, table, lens):
            return fused_attn_decode(
                x_rep, wqkv_loc, qn, kn, pool_k_l, pool_v_l, table, lens,
                rope_theta=c.rope_theta,
                qk_eps=c.rms_eps if c.qk_norm else None,
            )

        out, k_l, v_l = jax.shard_map(
            local, mesh=self.mesh,
            in_specs=(P(None, None), P(None, self.axis), P(None), P(None),
                      P(None, self.axis, None, None),
                      P(None, self.axis, None, None),
                      P(None, None), P(None)),
            out_specs=(P(None, self.axis),
                       P(None, self.axis, None, None),
                       P(None, self.axis, None, None)),
            check_vma=False,
        )(x, p.wqkv, p.q_norm, p.k_norm, cache.k[layer], cache.v[layer],
          cache.block_table, cache.seq_lens)
        return self._row_parallel_reduce(out, p.wo), k_l, v_l

    def _mlp_decode(self, p: TPMLPParams, x: jax.Array) -> jax.Array:
        n = self.tp
        if (self.decode_mode in ("fused", "persistent")
                and p.down.shape[0] % n == 0 and p.down.shape[1] % n == 0):
            # megakernel mode: gate/up GEMM + SwiGLU + down-proj chained
            # into the AR ring inside ONE kernel (ops.fused_decode) —
            # the host never sits between the GEMM and the reduction
            return fused_mlp_ar(x, p.gate_up, p.down, self.mesh, self.axis)
        if self.decode_mode in ("psum", "fused", "persistent") \
                or self.tp == 1:
            def local(x_rep, gu_loc, dn_loc):
                fused = jnp.dot(x_rep, gu_loc,
                                preferred_element_type=jnp.float32).astype(x_rep.dtype)
                wg, w1 = jnp.split(fused, 2, axis=-1)
                h = jax.nn.silu(wg) * w1
                part = jnp.dot(h, dn_loc, preferred_element_type=jnp.float32)
                return jax.lax.psum(part, self.axis).astype(x_rep.dtype)

            return jax.shard_map(
                local, mesh=self.mesh,
                in_specs=(P(None, None), P(None, self.axis),
                          P(self.axis, None)),
                out_specs=P(None, None),
            )(x, p.gate_up, p.down)

        # AR modes: the gate/up GEMM + SwiGLU stays local, the down-proj
        # reduction goes through the Pallas AllReduce path
        def up_local(x_rep, gu_loc):
            fused = jnp.dot(x_rep, gu_loc,
                            preferred_element_type=jnp.float32).astype(x_rep.dtype)
            wg, w1 = jnp.split(fused, 2, axis=-1)
            return jax.nn.silu(wg) * w1

        h = jax.shard_map(
            up_local, mesh=self.mesh,
            in_specs=(P(None, None), P(None, self.axis)),
            out_specs=P(None, self.axis),
            check_vma=False,
        )(x, p.gate_up)
        return self._row_parallel_reduce(h, p.down)

    def decode(self, params: QwenParams, cache: KVCache,
               tokens: jax.Array):
        """One decode step.  ``tokens``: (B,) int32.  Returns
        (logits (B, V), cache).

        Each layer's attention step returns its updated K/V slices; the
        stacked (L, ...) pool is rebuilt ONCE after the layer loop
        (``kv_cache.replace_layer_slices``) — the old per-layer
        ``dynamic_update_slice`` against the full pool was a whole-pool
        copy per layer on any path XLA does not fuse in place.
        ``decode_mode="fused"`` additionally runs the paged attention
        side as one megakernel per layer (``_attn_decode_paged_fused``);
        on a contiguous cache the fused mode keeps the per-kernel
        attention and fuses the reductions only."""
        if self.decode_mode == "persistent" and self._persistent_ok(cache):
            return self._decode_persistent(params, cache, tokens)
        c = self.config
        x = params.embed[tokens]
        if isinstance(cache, PagedKVCache):
            attn_step = (self._attn_decode_paged_fused
                         if self.decode_mode in ("fused", "persistent")
                         else self._attn_decode_paged)
        else:
            attn_step = self._attn_decode
        ks, vs, ksc, vsc = [], [], [], []
        for li, lp in enumerate(params.layers):
            res = attn_step(
                lp.attn, rms_norm(x, lp.ln1, c.rms_eps), cache, li
            )
            attn_out, k_l, v_l = res[:3]
            ks.append(k_l)
            vs.append(v_l)
            if len(res) == 5:      # quantized paged cache: scale slices
                ksc.append(res[3])
                vsc.append(res[4])
            x = x + attn_out
            x = x + self._mlp_decode_step(
                lp.mlp, rms_norm(x, lp.ln2, c.rms_eps)
            )
        cache = replace_layer_slices(cache, ks, vs,
                                     ks_scale=ksc or None,
                                     vs_scale=vsc or None)
        x = rms_norm(x, params.final_norm, c.rms_eps)
        logits = jnp.dot(x, params.lm_head,
                         preferred_element_type=jnp.float32)
        return logits, advance(cache, 1)

    # -- persistent decode (the device-resident multi-layer loop) ----------

    def _persistent_ok(self, cache) -> bool:
        """Whether the persistent megakernel serves this (model, cache):
        paged full-precision pools, dense MLP, every sharded dim
        divisible by tp.  Anything else falls back to the per-layer
        ``fused`` chain (docs/perf.md "Persistent decode loop")."""
        c = self.config
        n = self.tp
        return (isinstance(cache, PagedKVCache)
                and not cache.quantized
                and not c.is_moe
                and c.hidden % n == 0 and c.intermediate % n == 0
                and c.num_kv_heads % n == 0 and c.num_heads % n == 0)

    def _persistent_step(self, params: QwenParams,
                         sp: "pd.StackedDecodeParams", cache: PagedKVCache,
                         tokens: jax.Array, config=None):
        """One token through ALL L layers as one persistent launch, plus
        the (out-of-kernel) final norm + lm_head: the step the bundle
        scans.  The page pools ride the kernel's aliased in/outs — no
        ``replace_layer_slices`` rebuild exists on this path."""
        c = self.config
        x = params.embed[tokens]
        x, pk, pv = pd.persistent_decode_step(
            x, sp, cache.k, cache.v, cache.block_table, cache.seq_lens,
            self.mesh, self.axis,
            rope_theta=c.rope_theta, rms_eps=c.rms_eps,
            qk_eps=c.rms_eps if c.qk_norm else None, config=config,
        )
        cache = dataclasses.replace(cache, k=pk, v=pv)
        x = rms_norm(x, params.final_norm, c.rms_eps)
        logits = jnp.dot(x, params.lm_head,
                         preferred_element_type=jnp.float32)
        return logits, advance(cache, 1)

    def _decode_persistent(self, params: QwenParams, cache: PagedKVCache,
                           tokens: jax.Array, config=None):
        return self._persistent_step(params, stack_decode_params(params),
                                     cache, tokens, config)

    def decode_multi(self, params: QwenParams, cache: KVCache,
                     tokens: jax.Array, steps: int, *,
                     persistent_config=None, stacked=None):
        """``steps`` greedy decode steps in ONE dispatch
        (``ops.persistent_decode.decode_bundle``): the argmax token
        feeds back on device, so the host-visible seam between steps
        disappears — batch-membership changes apply only BETWEEN
        bundles (``serve.EngineBackend.steps_per_dispatch`` /
        ``docs/serving.md``).  ``steps`` is static (one executable per
        steps bucket).  Returns ``(tokens (steps, B), cache)``.

        ``decode_mode="persistent"`` scans the megakernel step (the
        weight stack and the tile config are hoisted OUTSIDE the scan —
        ``persistent_config`` threads a construction-time-resolved
        config so the hot loop never consults the autotuner winner
        cache, and ``stacked`` threads a pre-built
        :class:`~..ops.persistent_decode.StackedDecodeParams` so the
        traced bundle does not re-materialize the full weight stack per
        dispatch — ``serve.EngineBackend`` builds it once at
        construction); every other mode scans its :meth:`decode` chain —
        same one-dispatch bundle, per-layer launches still inside."""
        steps = int(steps)
        if self.decode_mode == "persistent" and self._persistent_ok(cache):
            sp = stacked if stacked is not None \
                else stack_decode_params(params)

            def step(cache, tok):
                return self._persistent_step(params, sp, cache, tok,
                                             persistent_config)

            return pd.decode_bundle(step, cache, tokens, steps)
        return pd.decode_bundle(
            lambda cache, tok: self.decode(params, cache, tok),
            cache, tokens, steps)
