"""Model configs, KV cache, Qwen3 decoder, and the inference engine
(reference: ``python/triton_dist/models/`` — config, kv_cache, qwen,
engine)."""

from .checkpoint import load_checkpoint, save_checkpoint
from .config import ModelConfig
from .engine import Engine, sample_token
from .kv_cache import (
    KVCache,
    PagedKVCache,
    PagePoolExhausted,
    advance,
    append_paged,
    init_cache,
    init_paged_cache,
    init_serving_cache,
    reset,
    with_length,
    write_chunk_paged,
    write_prefill,
    write_prefill_paged,
)
from .loader import load_qwen_from_safetensors, load_qwen_state_dict
from .qwen import Qwen3, QwenLayerParams, QwenParams
from .safetensors_io import SafetensorsFile, load_state_dict, save_safetensors
