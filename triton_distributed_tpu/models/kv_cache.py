"""KV cache as a functional pytree, head-sharded over TP.

Reference: ``python/triton_dist/models/kv_cache.py:29`` — preallocated
(L, B, max_len, Hkv/world, D) tensors plus a device offset, mutated in
place.  TPU translation: the same preallocated layout as immutable arrays
sharded ``P(None, None, tp, None, None)`` on the head axis; updates are
``lax.dynamic_update_slice`` (head-sharded update against head-sharded
cache — XLA keeps the write local to each rank), and in-place semantics
come from buffer donation at the jit boundary (``Engine``), the TPU
analogue of the reference's static CUDA-graph buffers.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.mesh import TP_AXIS


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """k/v: (L, B, Hkv, max_len, D) head-sharded; kv_len: () int32 valid
    positions (shared across layers, like the reference's kv_offset)."""

    k: jax.Array
    v: jax.Array
    kv_len: jax.Array


def init_cache(mesh: Mesh, num_layers: int, batch: int, kv_heads: int,
               max_length: int, head_dim: int, dtype=jnp.bfloat16,
               axis: str = TP_AXIS) -> KVCache:
    shape = (num_layers, batch, kv_heads, max_length, head_dim)
    sharding = NamedSharding(mesh, P(None, None, axis, None, None))
    return KVCache(
        k=jax.device_put(jnp.zeros(shape, dtype), sharding),
        v=jax.device_put(jnp.zeros(shape, dtype), sharding),
        kv_len=jnp.zeros((), jnp.int32),
    )


def write_prefill(cache: KVCache, layer: int, k_new: jax.Array,
                  v_new: jax.Array) -> KVCache:
    """Write a full prefill's (B, Hkv, S, D) at positions [0, S)."""
    idx = (layer, 0, 0, 0, 0)
    return dataclasses.replace(
        cache,
        k=jax.lax.dynamic_update_slice(cache.k, k_new[None], idx),
        v=jax.lax.dynamic_update_slice(cache.v, v_new[None], idx),
    )


def advance(cache: KVCache, steps: jax.Array | int) -> KVCache:
    return dataclasses.replace(
        cache, kv_len=cache.kv_len + jnp.asarray(steps, jnp.int32)
    )


def with_length(cache: KVCache, length: jax.Array | int) -> KVCache:
    return dataclasses.replace(
        cache, kv_len=jnp.asarray(length, jnp.int32)
    )


def reset(cache: KVCache) -> KVCache:
    return dataclasses.replace(cache, kv_len=jnp.zeros((), jnp.int32))
