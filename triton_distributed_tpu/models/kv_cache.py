"""KV caches as functional pytrees, head-sharded over TP.

Reference: ``python/triton_dist/models/kv_cache.py:29`` — preallocated
(L, B, max_len, Hkv/world, D) tensors plus a device offset, mutated in
place.  TPU translation: the same preallocated layout as immutable arrays
sharded ``P(None, None, tp, None, None)`` on the head axis; updates are
``lax.dynamic_update_slice`` (head-sharded update against head-sharded
cache — XLA keeps the write local to each rank), and in-place semantics
come from buffer donation at the jit boundary (``Engine``), the TPU
analogue of the reference's static CUDA-graph buffers.

Two layouts:

- :class:`KVCache` — contiguous (L, B, Hkv, max_len, D) blocks, one shared
  length (every sequence the same age).  Simple, fastest for lockstep
  batches.
- :class:`PagedKVCache` — a physical page POOL (L, P, Hkv, page_size, D)
  plus a per-sequence ``block_table`` and RAGGED ``seq_lens`` — the
  reference's production decode layout (``flash_decode.py:587-720``
  ``block_table`` through ``gqa_fwd_batch_decode``;
  ``sp_flash_decode_layer.py:83-108``), which is what realistic serving
  (per-sequence lengths, cache reuse) needs.  Reads go through the
  scalar-prefetch paged kernel (``ops.attention.paged_decode_attention``);
  writes are XLA scatters into the pool.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.mesh import TP_AXIS
from ..lang import quant as _quant


class PagePoolExhausted(RuntimeError):
    """A sequence needs a KV page the pool cannot provide.

    Raised by :func:`append_paged` when a sequence's write position has
    outgrown its allocated pages (the write would otherwise scatter out
    of range silently — JAX drops out-of-bounds scatter indices under
    jit, which corrupts nothing but LOSES the token), and by the serving
    page allocator (``serve.budget.PagePool``) when a free-list
    allocation fails.  The continuous-batching scheduler catches it as
    its PREEMPTION trigger: evict the lowest-priority sequence's pages
    and park that request instead of failing the step.
    """

    def __init__(self, msg: str, *, sequences: tuple[int, ...] = (),
                 needed: int = 0, available: int = 0):
        self.sequences = tuple(sequences)
        self.needed = int(needed)
        self.available = int(available)
        super().__init__(msg)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """k/v: (L, B, Hkv, max_len, D) head-sharded; kv_len: () int32 valid
    positions (shared across layers, like the reference's kv_offset)."""

    k: jax.Array
    v: jax.Array
    kv_len: jax.Array


def init_cache(mesh: Mesh, num_layers: int, batch: int, kv_heads: int,
               max_length: int, head_dim: int, dtype=jnp.bfloat16,
               axis: str = TP_AXIS) -> KVCache:
    shape = (num_layers, batch, kv_heads, max_length, head_dim)
    sharding = NamedSharding(mesh, P(None, None, axis, None, None))
    return KVCache(
        k=jax.device_put(jnp.zeros(shape, dtype), sharding),
        v=jax.device_put(jnp.zeros(shape, dtype), sharding),
        kv_len=jnp.zeros((), jnp.int32),
    )


def write_prefill(cache: KVCache, layer: int, k_new: jax.Array,
                  v_new: jax.Array) -> KVCache:
    """Write a full prefill's (B, Hkv, S, D) at positions [0, S)."""
    idx = (layer, 0, 0, 0, 0)
    return dataclasses.replace(
        cache,
        k=jax.lax.dynamic_update_slice(cache.k, k_new[None], idx),
        v=jax.lax.dynamic_update_slice(cache.v, v_new[None], idx),
    )


def advance(cache, steps: jax.Array | int):
    if isinstance(cache, PagedKVCache):
        return dataclasses.replace(
            cache, seq_lens=cache.seq_lens + jnp.asarray(steps, jnp.int32)
        )
    return dataclasses.replace(
        cache, kv_len=cache.kv_len + jnp.asarray(steps, jnp.int32)
    )


def with_length(cache, length: jax.Array | int):
    """Set the valid length(s).  For a paged cache a scalar broadcasts to
    every sequence and a (B,) array sets ragged lengths."""
    if isinstance(cache, PagedKVCache):
        lens = jnp.broadcast_to(
            jnp.asarray(length, jnp.int32), cache.seq_lens.shape
        )
        return dataclasses.replace(cache, seq_lens=lens)
    return dataclasses.replace(
        cache, kv_len=jnp.asarray(length, jnp.int32)
    )


def reset(cache):
    if isinstance(cache, PagedKVCache):
        return dataclasses.replace(
            cache, seq_lens=jnp.zeros_like(cache.seq_lens)
        )
    return dataclasses.replace(cache, kv_len=jnp.zeros((), jnp.int32))


# ---------------------------------------------------------------------------
# paged layout


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKVCache:
    """k/v: (L, P, Hkv, page_size, D) physical page pools, head-sharded;
    block_table: (B, max_pages) int32 — logical page j of sequence b lives
    in pool page ``block_table[b, j]``; seq_lens: (B,) int32 ragged valid
    lengths.  The table is a device array (it travels through jit), but its
    values are expected to be stable across a generation — the engine
    allocates the static worst case up front like the reference's
    preallocated cache.

    **Quantized layout** (``kv_dtype="int8"``, ISSUE 9): the pools store
    int8 with a PER-(page, head) f32 scale sidecar ``k_scale``/``v_scale``
    of shape (L, P, Hkv) — one scale per (layer, physical page, kv head),
    chosen so the page-head's absmax maps to 127 (``lang.quant``'s
    recipe at page granularity).  Writes quantize fused into the scatter
    (:func:`append_paged` / :func:`write_chunk_paged` dequant-merge-
    requant the touched pages only); reads dequantize fused into the
    decode kernels' page-streaming loops (``ops.attention`` /
    ``ops.fused_decode`` take the scales) — no full-precision pool is
    ever materialized on the decode path.  Halved page bytes double the
    pool's sequence capacity at the same byte budget, which the
    continuous-batching scheduler converts directly into concurrent
    sequences.  ``k_scale``/``v_scale`` are None for full-precision
    pools (the layout is byte-identical to the pre-ISSUE-9 cache)."""

    k: jax.Array
    v: jax.Array
    block_table: jax.Array
    seq_lens: jax.Array
    k_scale: jax.Array | None = None
    v_scale: jax.Array | None = None

    @property
    def page_size(self) -> int:
        return self.k.shape[3]

    @property
    def max_pages(self) -> int:
        return self.block_table.shape[1]

    @property
    def quantized(self) -> bool:
        return self.k_scale is not None


def _resolve_kv_dtype(dtype, kv_dtype):
    """(pool dtype, quantized?) from the ``kv_dtype`` knob: ``None``
    keeps ``dtype`` (full precision); ``"int8"`` selects the quantized
    per-(page, head)-scale layout; any other jnp dtype stores as-is."""
    if kv_dtype is None:
        return jnp.dtype(dtype), False
    if kv_dtype == "int8" or jnp.dtype(kv_dtype) == jnp.int8:
        return jnp.dtype(jnp.int8), True
    return jnp.dtype(kv_dtype), False


def kv_page_bytes(num_layers: int, kv_heads: int, page_size: int,
                  head_dim: int, dtype=jnp.bfloat16,
                  kv_dtype=None) -> int:
    """Bytes ONE physical page costs across all layers, k + v, scale
    sidecars included — the capacity-math unit ``bench.py serve`` and
    the docs use (int8 halves the pool bytes per page, so the same byte
    budget holds ~2x the pages -> ~2x the concurrent sequences)."""
    pd, quantized = _resolve_kv_dtype(dtype, kv_dtype)
    per = 2 * num_layers * kv_heads * page_size * head_dim * pd.itemsize
    if quantized:
        per += 2 * num_layers * kv_heads * 4          # f32 scale sidecars
    return per


def _init_scales(num_layers: int, pool_pages: int, kv_heads: int,
                 mesh: Mesh, axis: str):
    sharding = NamedSharding(mesh, P(None, None, axis))
    z = jnp.full((num_layers, pool_pages, kv_heads), _quant.SCALE_EPS,
                 jnp.float32)
    return jax.device_put(z, sharding)


def init_paged_cache(mesh: Mesh, num_layers: int, batch: int, kv_heads: int,
                     max_length: int, head_dim: int, dtype=jnp.bfloat16,
                     axis: str = TP_AXIS, *, page_size: int = 64,
                     key: jax.Array | None = None,
                     kv_dtype=None) -> PagedKVCache:
    """Preallocate ``batch * (max_length // page_size)`` pages and a full
    block table.  ``key``: when given, the (sequence, logical page) ->
    physical page map is a random bijection instead of the identity — the
    fragmented layout a real page allocator produces, useful for tests and
    as honest serving behavior.  ``kv_dtype="int8"`` selects the
    quantized layout (see :class:`PagedKVCache`)."""
    if max_length % page_size:
        raise ValueError(
            f"max_length {max_length} not divisible by page_size {page_size}"
        )
    mp = max_length // page_size
    p = batch * mp
    pool_dtype, quantized = _resolve_kv_dtype(dtype, kv_dtype)
    pool_shape = (num_layers, p, kv_heads, page_size, head_dim)
    sharding = NamedSharding(mesh, P(None, None, axis, None, None))
    ids = jnp.arange(p, dtype=jnp.int32)
    if key is not None:
        ids = jax.random.permutation(key, ids)
    return PagedKVCache(
        k=jax.device_put(jnp.zeros(pool_shape, pool_dtype), sharding),
        v=jax.device_put(jnp.zeros(pool_shape, pool_dtype), sharding),
        block_table=ids.reshape(batch, mp),
        seq_lens=jnp.zeros((batch,), jnp.int32),
        k_scale=_init_scales(num_layers, p, kv_heads, mesh, axis)
        if quantized else None,
        v_scale=_init_scales(num_layers, p, kv_heads, mesh, axis)
        if quantized else None,
    )


def _quantize_pages(vals: jax.Array):
    """Quantize page-major values ``(..., Hkv, ps, D)`` to int8 with one
    f32 scale per leading-(page, head) cell — ``lang.quant``'s recipe at
    (page, head) granularity.  Returns ``(q, scale)`` with ``scale``
    shaped like ``vals`` minus the last two axes."""
    xf = vals.astype(jnp.float32)
    absmax = jnp.max(jnp.abs(xf), axis=(-2, -1))
    scale = absmax / _quant.INT8_MAX + _quant.SCALE_EPS
    q = jnp.clip(jnp.round(xf / scale[..., None, None]),
                 -_quant.INT8_MAX, _quant.INT8_MAX).astype(jnp.int8)
    return q, scale


def _dequantize_pages(q: jax.Array, scale: jax.Array,
                      dtype=jnp.float32) -> jax.Array:
    """Inverse of :func:`_quantize_pages`."""
    return (q.astype(jnp.float32) * scale[..., None, None]).astype(dtype)


def _merge_token_page(q_pages: jax.Array, scales: jax.Array,
                      tok: jax.Array, offs: jax.Array):
    """The quantized token-append merge core, shared by
    :func:`append_paged` and :func:`append_layer_quantized` (one home —
    the two must stay bit-identical): dequantize each sequence's ONE
    touched page, set the token at its in-page offset, zero slots PAST
    it (a recycled page carries the previous tenant's bytes —
    ``PagePool.free`` does not scrub — and a stale large value would
    inflate the absmax; zeroing also keeps the page bytes a
    deterministic function of the sequence's own content, the
    checksum-on-evict restore contract), and requantize.  ``q_pages``:
    (B, Hkv, ps, D) int8; ``scales``: (B, Hkv); ``tok``: (B, Hkv, D);
    ``offs``: (B,) in-page slots.  Returns ``(q, scale)``."""
    ps = q_pages.shape[-2]
    rows = jnp.arange(offs.shape[0])
    old = _dequantize_pages(q_pages, scales)
    keep = (jnp.arange(ps)[None, None, :, None]
            <= offs[:, None, None, None])              # (B, 1, ps, 1)
    merged = jnp.where(
        keep, old.at[rows, :, offs].set(tok.astype(jnp.float32)), 0.0)
    return _quantize_pages(merged)


def dequantize_pool(cache: PagedKVCache, dtype=jnp.bfloat16) -> PagedKVCache:
    """A full-precision copy of a quantized cache (golden/test path and
    the XLA fallbacks; the decode kernels stream-dequantize instead —
    this MATERIALIZES the pool and must stay off hot paths)."""
    if not cache.quantized:
        return cache
    return dataclasses.replace(
        cache,
        k=_dequantize_pages(cache.k, cache.k_scale, dtype),
        v=_dequantize_pages(cache.v, cache.v_scale, dtype),
        k_scale=None, v_scale=None,
    )


def layer_pool(cache: PagedKVCache, layer: int, dtype=None) -> tuple:
    """One layer's (k, v) pools in compute precision: the pools
    themselves for a full-precision cache, dequantized views for int8
    (the chunk-prefill prefix-attention path; decode uses the
    scale-aware kernels instead)."""
    k_l, v_l = cache.k[layer], cache.v[layer]
    if not cache.quantized:
        return (k_l, v_l) if dtype is None \
            else (k_l.astype(dtype), v_l.astype(dtype))
    dt = dtype if dtype is not None else jnp.bfloat16
    return (_dequantize_pages(k_l, cache.k_scale[layer], dt),
            _dequantize_pages(v_l, cache.v_scale[layer], dt))


def write_prefill_paged(cache: PagedKVCache, layer: int, k_new: jax.Array,
                        v_new: jax.Array) -> PagedKVCache:
    """Scatter a full prefill's (B, Hkv, S, D) into the page pool at
    positions [0, S).  A partial trailing page is zero-padded; those slots
    are masked by ``seq_lens`` and overwritten by later appends.  On a
    quantized cache the quantization is FUSED into the scatter: pages
    are written int8 with their (page, head) scales in one pass."""
    b, hk, s, d = k_new.shape
    ps = cache.page_size
    npg = (s + ps - 1) // ps
    pad = npg * ps - s

    def paged_vals(vals):
        vals = jnp.pad(vals, ((0, 0), (0, 0), (0, pad), (0, 0)))
        # (B, Hkv, npg*ps, D) -> (B, npg, Hkv, ps, D) page-major updates
        return vals.reshape(b, hk, npg, ps, d).transpose(0, 2, 1, 3, 4)

    if cache.quantized:
        qk, sk = _quantize_pages(paged_vals(k_new))
        qv, sv = _quantize_pages(paged_vals(v_new))
        pages = cache.block_table[:, :npg]
        return dataclasses.replace(
            cache,
            k=cache.k.at[layer, pages].set(qk),
            v=cache.v.at[layer, pages].set(qv),
            k_scale=cache.k_scale.at[layer, pages].set(sk),
            v_scale=cache.v_scale.at[layer, pages].set(sv),
        )

    def scatter(pool, vals):
        return pool.at[layer, cache.block_table[:, :npg]].set(
            paged_vals(vals).astype(pool.dtype)
        )

    return dataclasses.replace(
        cache, k=scatter(cache.k, k_new), v=scatter(cache.v, v_new)
    )


def append_paged(cache: PagedKVCache, layer: int, k_tok: jax.Array,
                 v_tok: jax.Array) -> PagedKVCache:
    """Write one decode token per sequence at its own (ragged) position
    ``seq_lens[b]``.  ``k_tok``/``v_tok``: (B, Hkv, D).  Does NOT advance
    ``seq_lens`` (mirror of the contiguous path: the model advances once
    per step, after all layers).

    Bounds: a sequence whose position has outgrown its block table
    (``seq_lens[b] >= max_pages * page_size``) has nowhere to put the
    token — ``take_along_axis`` would clamp the page lookup and the
    scatter would land in the WRONG page silently.  On the eager path
    (concrete ``seq_lens``) this raises :class:`PagePoolExhausted`
    naming the offending sequences instead; under jit the caller (the
    serving scheduler's page-budget admission) must guarantee capacity
    before dispatching the step — that invariant is exactly what
    ``serve.budget.PagePool`` + preemption exist to maintain.
    """
    ps = cache.page_size
    pos = cache.seq_lens
    if not isinstance(pos, jax.core.Tracer):
        limit = cache.max_pages * ps
        over = [int(b) for b in
                jnp.nonzero(pos >= limit)[0].tolist()]
        if over:
            raise PagePoolExhausted(
                f"append_paged: sequence(s) {over} at position(s) "
                f"{[int(pos[b]) for b in over]} have outgrown their "
                f"block table ({cache.max_pages} pages x page_size {ps} "
                f"= {limit} positions); the scatter would silently land "
                f"out of range — allocate pages (or preempt) first",
                sequences=tuple(over), needed=1, available=0,
            )
    pages = jnp.take_along_axis(
        cache.block_table, (pos // ps)[:, None], axis=1
    )[:, 0]                                            # (B,)
    offs = pos % ps

    if cache.quantized:
        # dequant-merge-requant of the ONE touched page per sequence
        # (:func:`_merge_token_page`): the (page, head) scale may grow
        # with the new token, so the page's residents re-quantize
        # against the merged absmax — bounded at one int8 ulp per
        # scale-growth event, and a no-growth append round-trips
        # bit-exact (int grid points are fixed points of the codec).
        # Touches B pages, not the pool.
        qk, sk = _merge_token_page(cache.k[layer, pages],
                                   cache.k_scale[layer, pages],
                                   k_tok, offs)
        qv, sv = _merge_token_page(cache.v[layer, pages],
                                   cache.v_scale[layer, pages],
                                   v_tok, offs)
        return dataclasses.replace(
            cache,
            k=cache.k.at[layer, pages].set(qk),
            v=cache.v.at[layer, pages].set(qv),
            k_scale=cache.k_scale.at[layer, pages].set(sk),
            v_scale=cache.v_scale.at[layer, pages].set(sv),
        )

    def scatter(pool, tok):
        # advanced indices (pages, offs) separated by the head slice put
        # the batch axis first: target slots (B, Hkv, D)
        return pool.at[layer, pages, :, offs].set(tok.astype(pool.dtype))

    return dataclasses.replace(
        cache, k=scatter(cache.k, k_tok), v=scatter(cache.v, v_tok)
    )


def append_layer_quantized(pool_k_l: jax.Array, pool_v_l: jax.Array,
                           ksc_l: jax.Array, vsc_l: jax.Array,
                           block_table: jax.Array, seq_lens: jax.Array,
                           k_tok: jax.Array, v_tok: jax.Array):
    """The quantized ragged append on ONE layer's pool slices (the form
    the decode step's shard_map locals need — :func:`append_paged` works
    on the stacked cache).  ``pool_*_l``: (P, Hkv, ps, D) int8;
    ``*sc_l``: (P, Hkv) f32; ``k_tok``/``v_tok``: (B, Hkv, D) the new
    token per sequence at position ``seq_lens[b]``.  Returns the four
    updated arrays; same dequant-merge-requant semantics as
    :func:`append_paged` (one touched page per sequence)."""
    ps = pool_k_l.shape[2]
    pos = seq_lens
    pages = jnp.take_along_axis(
        block_table, (pos // ps)[:, None], axis=1)[:, 0]
    offs = pos % ps

    def merge(pool, scale, tok):
        q, sc = _merge_token_page(pool[pages], scale[pages], tok, offs)
        return pool.at[pages].set(q), scale.at[pages].set(sc)

    pk, ksc = merge(pool_k_l, ksc_l, k_tok)
    pv, vsc = merge(pool_v_l, vsc_l, v_tok)
    return pk, pv, ksc, vsc


def write_chunk_paged(cache: PagedKVCache, layer: int, k_new: jax.Array,
                      v_new: jax.Array, start: jax.Array | int
                      ) -> PagedKVCache:
    """Scatter a prefill CHUNK's (B, Hkv, S, D) into the page pool at
    positions [start, start+S) of every sequence — the chunked-prefill
    generalization of :func:`write_prefill_paged` (which is the
    ``start == 0`` whole-prompt case but needs page-aligned geometry).
    ``start`` may be traced (one jitted chunk executable serves every
    chunk position).  Positions are looked up per token through the
    block table, so chunk boundaries need NOT be page-aligned.  Writes
    whose position lands at or beyond ``max_pages * page_size`` are
    DROPPED (JAX scatter out-of-bounds semantics) — the scheduler pads
    the final chunk and masks the pads via ``seq_lens``."""
    b, hk, s, d = k_new.shape
    ps = cache.page_size
    pos = jnp.asarray(start, jnp.int32) + jnp.arange(s, dtype=jnp.int32)
    pages = jnp.take(cache.block_table, pos // ps, axis=1)   # (B, S)
    offs = jnp.broadcast_to(pos % ps, (b, s))                # (B, S)
    # out-of-range positions: redirect the page index out of the pool so
    # the scatter drops them instead of clamping into a wrong page
    npages = cache.k.shape[1]
    pages = jnp.where(pos[None, :] < cache.max_pages * ps, pages, npages)

    if cache.quantized:
        return _write_chunk_quantized(cache, layer, k_new, v_new,
                                      jnp.asarray(start, jnp.int32))

    def scatter(pool, vals):
        # advanced indices (pages, offs) around the head slice: target
        # slots (B, S, Hkv, D)
        return pool.at[layer, pages, :, offs].set(
            vals.transpose(0, 2, 1, 3).astype(pool.dtype), mode="drop"
        )

    return dataclasses.replace(
        cache, k=scatter(cache.k, k_new), v=scatter(cache.v, v_new)
    )


def _write_chunk_quantized(cache: PagedKVCache, layer: int,
                           k_new: jax.Array, v_new: jax.Array,
                           start: jax.Array) -> PagedKVCache:
    """The quantized body of :func:`write_chunk_paged`: gather the
    pages the chunk touches (a STATIC count — ceil(S/ps) + 1 covers any
    alignment of a traced ``start``), dequantize, overlay the chunk's
    values at their in-page offsets, requantize the merged pages, and
    scatter pages + scales back.  Out-of-range logical pages redirect to
    the out-of-pool sentinel so their scatter drops, matching the
    full-precision path's pad semantics; only the touched pages move —
    never the pool."""
    b, hk, s, d = k_new.shape
    ps = cache.page_size
    mp = cache.max_pages
    npages = cache.k.shape[1]
    npg_t = s // ps + (2 if s % ps else 1)   # worst-case touched pages
    npg_t = min(npg_t, mp)
    lo = start // ps                          # first touched logical page
    logical = lo + jnp.arange(npg_t, dtype=jnp.int32)           # (npg_t,)
    in_range = logical < mp
    gather_idx = jnp.clip(logical, 0, mp - 1)
    pages = jnp.take(cache.block_table, gather_idx, axis=1)     # (B, npg_t)
    # positions of the chunk rows RELATIVE to the gathered window
    rel = (start % ps) + jnp.arange(s, dtype=jnp.int32)         # (S,)
    rel = jnp.where(rel < npg_t * ps, rel, npg_t * ps)  # oob rows -> drop
    scatter_pages = jnp.where(in_range[None, :], pages, npages)

    # window slots past the chunk's end hold either zero-init or a
    # recycled page's stale tenant bytes (PagePool.free does not scrub)
    # — zero them before the absmax so a stale large value cannot
    # inflate the (page, head) scale; slots BEFORE the chunk are the
    # sequence's own earlier chunks and stay.  Also keeps the page
    # bytes a deterministic function of the sequence's content (the
    # checksum-on-evict restore contract).
    keep = (jnp.arange(npg_t * ps, dtype=jnp.int32)
            < (start % ps) + s)[None, None, :, None]

    def merge(pool, scale, vals):
        old = _dequantize_pages(pool[layer, pages], scale[layer, pages])
        # (B, npg_t, Hkv, ps, D) -> (B, Hkv, npg_t*ps, D) window view
        win = old.transpose(0, 2, 1, 3, 4).reshape(b, hk, npg_t * ps, d)
        win = win.at[:, :, rel, :].set(vals.astype(jnp.float32),
                                       mode="drop")
        win = jnp.where(keep, win, 0.0)
        merged = win.reshape(b, hk, npg_t, ps, d).transpose(0, 2, 1, 3, 4)
        q, sc = _quantize_pages(merged)
        return (pool.at[layer, scatter_pages].set(q, mode="drop"),
                scale.at[layer, scatter_pages].set(sc, mode="drop"))

    k_pool, k_sc = merge(cache.k, cache.k_scale, k_new)
    v_pool, v_sc = merge(cache.v, cache.v_scale, v_new)
    return dataclasses.replace(cache, k=k_pool, v=v_pool,
                               k_scale=k_sc, v_scale=v_sc)


def replace_layer_slices(cache, ks: list, vs: list,
                         ks_scale: list | None = None,
                         vs_scale: list | None = None):
    """Rebuild the stacked (L, ...) pools from per-layer slices in ONE
    materialization per pool.

    The decode loop used to fold each layer's updated slice back with
    ``dynamic_update_slice(cache.k, k_l[None], (layer, 0, ...))`` — L
    sequential writes against the FULL stacked pool, each of which is a
    whole-pool copy on any path where XLA does not prove in-place
    fusion (eager dispatch, a donation-less jit boundary, the AOT
    executables' input resharding).  Decode updates EVERY layer's slice
    exactly once per step, so the loop threads the per-layer slices and
    this helper stacks them once: 2 pool materializations per step (k
    and v) instead of 2·L.  Pinned by
    ``tests/test_fused_decode.py::test_decode_writeback_copy_count``.
    """
    if len(ks) != cache.k.shape[0] or len(vs) != cache.v.shape[0]:
        raise ValueError(
            f"need one slice per layer: got {len(ks)}/{len(vs)} for "
            f"{cache.k.shape[0]} layers")
    kw = {}
    if ks_scale is not None:
        kw = dict(k_scale=jnp.stack(ks_scale).astype(jnp.float32),
                  v_scale=jnp.stack(vs_scale).astype(jnp.float32))
    return dataclasses.replace(
        cache,
        k=jnp.stack(ks).astype(cache.k.dtype),
        v=jnp.stack(vs).astype(cache.v.dtype),
        **kw,
    )


def init_serving_cache(mesh: Mesh, num_layers: int, slots: int,
                       kv_heads: int, max_length: int, head_dim: int,
                       dtype=jnp.bfloat16, axis: str = TP_AXIS, *,
                       page_size: int = 64, pool_pages: int | None = None,
                       kv_dtype=None) -> PagedKVCache:
    """A :class:`PagedKVCache` for the continuous-batching scheduler:
    the physical pool holds ``pool_pages`` pages (the serving KV-page
    BUDGET — decoupled from ``slots * max_pages``, so the scheduler can
    overcommit logical capacity and preempt under pressure), and the
    block table starts all-zero: page 0 is the scheduler's reserved
    SCRAP page (inactive slots write their garbage token there and read
    it back masked), pages [1, pool_pages) belong to the free list
    (``serve.budget.PagePool``).

    ``kv_dtype="int8"`` selects the quantized page layout
    (:class:`PagedKVCache`): at the same POOL BYTES a budget holds ~2x
    the pages (:func:`kv_page_bytes`), which the scheduler converts
    directly into concurrent sequences (``bench.py serve``)."""
    if max_length % page_size:
        raise ValueError(
            f"max_length {max_length} not divisible by page_size {page_size}"
        )
    mp = max_length // page_size
    if pool_pages is None:
        pool_pages = slots * mp + 1
    if pool_pages < 2:
        raise ValueError(f"pool_pages {pool_pages} < 2 (page 0 is the "
                         f"reserved scrap page)")
    pool_dtype, quantized = _resolve_kv_dtype(dtype, kv_dtype)
    pool_shape = (num_layers, pool_pages, kv_heads, page_size, head_dim)
    sharding = NamedSharding(mesh, P(None, None, axis, None, None))
    return PagedKVCache(
        k=jax.device_put(jnp.zeros(pool_shape, pool_dtype), sharding),
        v=jax.device_put(jnp.zeros(pool_shape, pool_dtype), sharding),
        block_table=jnp.zeros((slots, mp), jnp.int32),
        seq_lens=jnp.zeros((slots,), jnp.int32),
        k_scale=_init_scales(num_layers, pool_pages, kv_heads, mesh, axis)
        if quantized else None,
        v_scale=_init_scales(num_layers, pool_pages, kv_heads, mesh, axis)
        if quantized else None,
    )
