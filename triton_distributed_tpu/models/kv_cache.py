"""KV caches as functional pytrees, head-sharded over TP.

Reference: ``python/triton_dist/models/kv_cache.py:29`` — preallocated
(L, B, max_len, Hkv/world, D) tensors plus a device offset, mutated in
place.  TPU translation: the same preallocated layout as immutable arrays
sharded ``P(None, None, tp, None, None)`` on the head axis; updates are
``lax.dynamic_update_slice`` (head-sharded update against head-sharded
cache — XLA keeps the write local to each rank), and in-place semantics
come from buffer donation at the jit boundary (``Engine``), the TPU
analogue of the reference's static CUDA-graph buffers.

Two layouts:

- :class:`KVCache` — contiguous (L, B, Hkv, max_len, D) blocks, one shared
  length (every sequence the same age).  Simple, fastest for lockstep
  batches.
- :class:`PagedKVCache` — a physical page POOL (L, P, Hkv, page_size, D)
  plus a per-sequence ``block_table`` and RAGGED ``seq_lens`` — the
  reference's production decode layout (``flash_decode.py:587-720``
  ``block_table`` through ``gqa_fwd_batch_decode``;
  ``sp_flash_decode_layer.py:83-108``), which is what realistic serving
  (per-sequence lengths, cache reuse) needs.  Reads go through the
  scalar-prefetch paged kernel (``ops.attention.paged_decode_attention``);
  writes are XLA scatters into the pool.
"""

from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..core.mesh import TP_AXIS


class PagePoolExhausted(RuntimeError):
    """A sequence needs a KV page the pool cannot provide.

    Raised by :func:`append_paged` when a sequence's write position has
    outgrown its allocated pages (the write would otherwise scatter out
    of range silently — JAX drops out-of-bounds scatter indices under
    jit, which corrupts nothing but LOSES the token), and by the serving
    page allocator (``serve.budget.PagePool``) when a free-list
    allocation fails.  The continuous-batching scheduler catches it as
    its PREEMPTION trigger: evict the lowest-priority sequence's pages
    and park that request instead of failing the step.
    """

    def __init__(self, msg: str, *, sequences: tuple[int, ...] = (),
                 needed: int = 0, available: int = 0):
        self.sequences = tuple(sequences)
        self.needed = int(needed)
        self.available = int(available)
        super().__init__(msg)


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class KVCache:
    """k/v: (L, B, Hkv, max_len, D) head-sharded; kv_len: () int32 valid
    positions (shared across layers, like the reference's kv_offset)."""

    k: jax.Array
    v: jax.Array
    kv_len: jax.Array


def init_cache(mesh: Mesh, num_layers: int, batch: int, kv_heads: int,
               max_length: int, head_dim: int, dtype=jnp.bfloat16,
               axis: str = TP_AXIS) -> KVCache:
    shape = (num_layers, batch, kv_heads, max_length, head_dim)
    sharding = NamedSharding(mesh, P(None, None, axis, None, None))
    return KVCache(
        k=jax.device_put(jnp.zeros(shape, dtype), sharding),
        v=jax.device_put(jnp.zeros(shape, dtype), sharding),
        kv_len=jnp.zeros((), jnp.int32),
    )


def write_prefill(cache: KVCache, layer: int, k_new: jax.Array,
                  v_new: jax.Array) -> KVCache:
    """Write a full prefill's (B, Hkv, S, D) at positions [0, S)."""
    idx = (layer, 0, 0, 0, 0)
    return dataclasses.replace(
        cache,
        k=jax.lax.dynamic_update_slice(cache.k, k_new[None], idx),
        v=jax.lax.dynamic_update_slice(cache.v, v_new[None], idx),
    )


def advance(cache, steps: jax.Array | int):
    if isinstance(cache, PagedKVCache):
        return dataclasses.replace(
            cache, seq_lens=cache.seq_lens + jnp.asarray(steps, jnp.int32)
        )
    return dataclasses.replace(
        cache, kv_len=cache.kv_len + jnp.asarray(steps, jnp.int32)
    )


def with_length(cache, length: jax.Array | int):
    """Set the valid length(s).  For a paged cache a scalar broadcasts to
    every sequence and a (B,) array sets ragged lengths."""
    if isinstance(cache, PagedKVCache):
        lens = jnp.broadcast_to(
            jnp.asarray(length, jnp.int32), cache.seq_lens.shape
        )
        return dataclasses.replace(cache, seq_lens=lens)
    return dataclasses.replace(
        cache, kv_len=jnp.asarray(length, jnp.int32)
    )


def reset(cache):
    if isinstance(cache, PagedKVCache):
        return dataclasses.replace(
            cache, seq_lens=jnp.zeros_like(cache.seq_lens)
        )
    return dataclasses.replace(cache, kv_len=jnp.zeros((), jnp.int32))


# ---------------------------------------------------------------------------
# paged layout


@jax.tree_util.register_dataclass
@dataclasses.dataclass
class PagedKVCache:
    """k/v: (L, P, Hkv, page_size, D) physical page pools, head-sharded;
    block_table: (B, max_pages) int32 — logical page j of sequence b lives
    in pool page ``block_table[b, j]``; seq_lens: (B,) int32 ragged valid
    lengths.  The table is a device array (it travels through jit), but its
    values are expected to be stable across a generation — the engine
    allocates the static worst case up front like the reference's
    preallocated cache."""

    k: jax.Array
    v: jax.Array
    block_table: jax.Array
    seq_lens: jax.Array

    @property
    def page_size(self) -> int:
        return self.k.shape[3]

    @property
    def max_pages(self) -> int:
        return self.block_table.shape[1]


def init_paged_cache(mesh: Mesh, num_layers: int, batch: int, kv_heads: int,
                     max_length: int, head_dim: int, dtype=jnp.bfloat16,
                     axis: str = TP_AXIS, *, page_size: int = 64,
                     key: jax.Array | None = None) -> PagedKVCache:
    """Preallocate ``batch * (max_length // page_size)`` pages and a full
    block table.  ``key``: when given, the (sequence, logical page) ->
    physical page map is a random bijection instead of the identity — the
    fragmented layout a real page allocator produces, useful for tests and
    as honest serving behavior."""
    if max_length % page_size:
        raise ValueError(
            f"max_length {max_length} not divisible by page_size {page_size}"
        )
    mp = max_length // page_size
    p = batch * mp
    pool_shape = (num_layers, p, kv_heads, page_size, head_dim)
    sharding = NamedSharding(mesh, P(None, None, axis, None, None))
    ids = jnp.arange(p, dtype=jnp.int32)
    if key is not None:
        ids = jax.random.permutation(key, ids)
    return PagedKVCache(
        k=jax.device_put(jnp.zeros(pool_shape, dtype), sharding),
        v=jax.device_put(jnp.zeros(pool_shape, dtype), sharding),
        block_table=ids.reshape(batch, mp),
        seq_lens=jnp.zeros((batch,), jnp.int32),
    )


def write_prefill_paged(cache: PagedKVCache, layer: int, k_new: jax.Array,
                        v_new: jax.Array) -> PagedKVCache:
    """Scatter a full prefill's (B, Hkv, S, D) into the page pool at
    positions [0, S).  A partial trailing page is zero-padded; those slots
    are masked by ``seq_lens`` and overwritten by later appends."""
    b, hk, s, d = k_new.shape
    ps = cache.page_size
    npg = (s + ps - 1) // ps
    pad = npg * ps - s

    def scatter(pool, vals):
        vals = jnp.pad(vals, ((0, 0), (0, 0), (0, pad), (0, 0)))
        # (B, Hkv, npg*ps, D) -> (B, npg, Hkv, ps, D) page-major updates
        vals = vals.reshape(b, hk, npg, ps, d).transpose(0, 2, 1, 3, 4)
        return pool.at[layer, cache.block_table[:, :npg]].set(
            vals.astype(pool.dtype)
        )

    return dataclasses.replace(
        cache, k=scatter(cache.k, k_new), v=scatter(cache.v, v_new)
    )


def append_paged(cache: PagedKVCache, layer: int, k_tok: jax.Array,
                 v_tok: jax.Array) -> PagedKVCache:
    """Write one decode token per sequence at its own (ragged) position
    ``seq_lens[b]``.  ``k_tok``/``v_tok``: (B, Hkv, D).  Does NOT advance
    ``seq_lens`` (mirror of the contiguous path: the model advances once
    per step, after all layers).

    Bounds: a sequence whose position has outgrown its block table
    (``seq_lens[b] >= max_pages * page_size``) has nowhere to put the
    token — ``take_along_axis`` would clamp the page lookup and the
    scatter would land in the WRONG page silently.  On the eager path
    (concrete ``seq_lens``) this raises :class:`PagePoolExhausted`
    naming the offending sequences instead; under jit the caller (the
    serving scheduler's page-budget admission) must guarantee capacity
    before dispatching the step — that invariant is exactly what
    ``serve.budget.PagePool`` + preemption exist to maintain.
    """
    ps = cache.page_size
    pos = cache.seq_lens
    if not isinstance(pos, jax.core.Tracer):
        limit = cache.max_pages * ps
        over = [int(b) for b in
                jnp.nonzero(pos >= limit)[0].tolist()]
        if over:
            raise PagePoolExhausted(
                f"append_paged: sequence(s) {over} at position(s) "
                f"{[int(pos[b]) for b in over]} have outgrown their "
                f"block table ({cache.max_pages} pages x page_size {ps} "
                f"= {limit} positions); the scatter would silently land "
                f"out of range — allocate pages (or preempt) first",
                sequences=tuple(over), needed=1, available=0,
            )
    pages = jnp.take_along_axis(
        cache.block_table, (pos // ps)[:, None], axis=1
    )[:, 0]                                            # (B,)
    offs = pos % ps

    def scatter(pool, tok):
        # advanced indices (pages, offs) separated by the head slice put
        # the batch axis first: target slots (B, Hkv, D)
        return pool.at[layer, pages, :, offs].set(tok.astype(pool.dtype))

    return dataclasses.replace(
        cache, k=scatter(cache.k, k_tok), v=scatter(cache.v, v_tok)
    )


def write_chunk_paged(cache: PagedKVCache, layer: int, k_new: jax.Array,
                      v_new: jax.Array, start: jax.Array | int
                      ) -> PagedKVCache:
    """Scatter a prefill CHUNK's (B, Hkv, S, D) into the page pool at
    positions [start, start+S) of every sequence — the chunked-prefill
    generalization of :func:`write_prefill_paged` (which is the
    ``start == 0`` whole-prompt case but needs page-aligned geometry).
    ``start`` may be traced (one jitted chunk executable serves every
    chunk position).  Positions are looked up per token through the
    block table, so chunk boundaries need NOT be page-aligned.  Writes
    whose position lands at or beyond ``max_pages * page_size`` are
    DROPPED (JAX scatter out-of-bounds semantics) — the scheduler pads
    the final chunk and masks the pads via ``seq_lens``."""
    b, hk, s, d = k_new.shape
    ps = cache.page_size
    pos = jnp.asarray(start, jnp.int32) + jnp.arange(s, dtype=jnp.int32)
    pages = jnp.take(cache.block_table, pos // ps, axis=1)   # (B, S)
    offs = jnp.broadcast_to(pos % ps, (b, s))                # (B, S)
    # out-of-range positions: redirect the page index out of the pool so
    # the scatter drops them instead of clamping into a wrong page
    npages = cache.k.shape[1]
    pages = jnp.where(pos[None, :] < cache.max_pages * ps, pages, npages)

    def scatter(pool, vals):
        # advanced indices (pages, offs) around the head slice: target
        # slots (B, S, Hkv, D)
        return pool.at[layer, pages, :, offs].set(
            vals.transpose(0, 2, 1, 3).astype(pool.dtype), mode="drop"
        )

    return dataclasses.replace(
        cache, k=scatter(cache.k, k_new), v=scatter(cache.v, v_new)
    )


def replace_layer_slices(cache, ks: list, vs: list):
    """Rebuild the stacked (L, ...) pools from per-layer slices in ONE
    materialization per pool.

    The decode loop used to fold each layer's updated slice back with
    ``dynamic_update_slice(cache.k, k_l[None], (layer, 0, ...))`` — L
    sequential writes against the FULL stacked pool, each of which is a
    whole-pool copy on any path where XLA does not prove in-place
    fusion (eager dispatch, a donation-less jit boundary, the AOT
    executables' input resharding).  Decode updates EVERY layer's slice
    exactly once per step, so the loop threads the per-layer slices and
    this helper stacks them once: 2 pool materializations per step (k
    and v) instead of 2·L.  Pinned by
    ``tests/test_fused_decode.py::test_decode_writeback_copy_count``.
    """
    if len(ks) != cache.k.shape[0] or len(vs) != cache.v.shape[0]:
        raise ValueError(
            f"need one slice per layer: got {len(ks)}/{len(vs)} for "
            f"{cache.k.shape[0]} layers")
    return dataclasses.replace(
        cache,
        k=jnp.stack(ks).astype(cache.k.dtype),
        v=jnp.stack(vs).astype(cache.v.dtype),
    )


def init_serving_cache(mesh: Mesh, num_layers: int, slots: int,
                       kv_heads: int, max_length: int, head_dim: int,
                       dtype=jnp.bfloat16, axis: str = TP_AXIS, *,
                       page_size: int = 64, pool_pages: int | None = None
                       ) -> PagedKVCache:
    """A :class:`PagedKVCache` for the continuous-batching scheduler:
    the physical pool holds ``pool_pages`` pages (the serving KV-page
    BUDGET — decoupled from ``slots * max_pages``, so the scheduler can
    overcommit logical capacity and preempt under pressure), and the
    block table starts all-zero: page 0 is the scheduler's reserved
    SCRAP page (inactive slots write their garbage token there and read
    it back masked), pages [1, pool_pages) belong to the free list
    (``serve.budget.PagePool``)."""
    if max_length % page_size:
        raise ValueError(
            f"max_length {max_length} not divisible by page_size {page_size}"
        )
    mp = max_length // page_size
    if pool_pages is None:
        pool_pages = slots * mp + 1
    if pool_pages < 2:
        raise ValueError(f"pool_pages {pool_pages} < 2 (page 0 is the "
                         f"reserved scrap page)")
    pool_shape = (num_layers, pool_pages, kv_heads, page_size, head_dim)
    sharding = NamedSharding(mesh, P(None, None, axis, None, None))
    return PagedKVCache(
        k=jax.device_put(jnp.zeros(pool_shape, dtype), sharding),
        v=jax.device_put(jnp.zeros(pool_shape, dtype), sharding),
        block_table=jnp.zeros((slots, mp), jnp.int32),
        seq_lens=jnp.zeros((slots,), jnp.int32),
    )
