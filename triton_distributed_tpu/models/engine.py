"""Inference engine: jitted prefill/decode loop with donated cache buffers.

Reference: ``python/triton_dist/models/engine.py:37-136`` — KV-cache init,
CUDA-graph capture of the decode step, and the ``serve`` loop (prefill,
then token-by-token decode with sampling).

TPU translation: CUDA-graph capture becomes ``jax.jit`` with the KV cache
DONATED (``donate_argnums``) — the compiled executable reuses the cache
buffers in place, which is exactly what the reference's static graph
buffers achieve; the first call compiles (the capture), subsequent calls
replay.  Sampling (temperature / top-p) is jnp, reference
``utils.py sample_token``.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Any

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from .. import obs
from ..core.mesh import TP_AXIS
from .config import ModelConfig
from .kv_cache import KVCache, init_cache, init_paged_cache, reset
from .qwen import Qwen3, QwenParams


def sample_token(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature: float = 0.0,
    top_p: float = 1.0,
) -> jax.Array:
    """Greedy / temperature / nucleus sampling over (B, V) f32 logits
    (reference ``sample_token``)."""
    if temperature == 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)
    logits = logits / temperature
    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # smallest set with cumulative prob >= top_p; mask the rest
        cutoff_idx = jnp.argmax(cum >= top_p, axis=-1)
        cutoff = jnp.take_along_axis(
            sorted_logits, cutoff_idx[:, None], axis=-1
        )
        logits = jnp.where(logits < cutoff, -jnp.inf, logits)
    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)


def arch_fingerprint(config: ModelConfig, mesh: Mesh, axis: str) -> dict:
    """JSON-safe identity of (model architecture, mesh topology): the full
    ModelConfig field dict plus the mesh axis sizes and the TP axis.  Saved
    in the AOT manifest and compared at load, so a bundle compiled for a
    DIFFERENT model or topology fails with a clear error instead of an
    opaque call-time sharding/shape error — or, worse, running when shapes
    coincide (ADVICE r5 low #4)."""
    cfg = {}
    for f in dataclasses.fields(config):
        v = getattr(config, f.name)
        cfg[f.name] = str(jnp.dtype(v)) if f.name == "dtype" else v
    return {
        "model_config": cfg,
        "mesh": {str(name): int(mesh.shape[name])
                 for name in mesh.axis_names},
        "axis": str(axis),
    }


def check_arch(manifest: dict, have: dict) -> None:
    """Raise ValueError naming every differing fingerprint field.  Bundles
    from before the fingerprint was recorded (no ``arch`` key) pass — the
    coarse batch/vocab/max_length checks still apply to them."""
    want = manifest.get("arch")
    if want is None or want == have:
        return
    diffs = []
    w_cfg, h_cfg = want.get("model_config", {}), have.get("model_config", {})
    for k in sorted(set(w_cfg) | set(h_cfg)):
        if w_cfg.get(k) != h_cfg.get(k):
            diffs.append(f"model.{k}: bundle={w_cfg.get(k)!r} "
                         f"engine={h_cfg.get(k)!r}")
    for k in ("mesh", "axis"):
        if want.get(k) != have.get(k):
            diffs.append(f"{k}: bundle={want.get(k)!r} engine={have.get(k)!r}")
    raise ValueError(
        "AOT bundle was compiled for a different model architecture / mesh "
        "topology: " + "; ".join(diffs or ["<unstructured fingerprint>"])
    )


@dataclasses.dataclass
class Engine:
    """Owns model definition, params, cache, and the compiled step fns.

    ``cache_layout``: "contiguous" (one shared length) or "paged" (page
    pool + block table + ragged per-sequence lengths — the reference's
    production decode layout, ``sp_flash_decode_layer.py:83-108``).

    Two serving shapes (ISSUE 6 split the engine into stateless step
    functions x a Python loop):

    - :meth:`serve` — ONE fixed-shape batch end to end (prefill, then
      lockstep decode); the engine-internal loop, donated buffers.
    - :meth:`scheduler` — continuous batching: the engine hands its
      stateless jit step functions to a ``serve.Scheduler`` whose loop
      re-decides batch membership every iteration against an explicit
      KV-page budget (admission control, chunked prefill, preemption,
      per-sequence isolation — ``docs/serving.md``)."""

    model: Qwen3
    params: QwenParams
    batch: int = 1
    temperature: float = 0.0
    top_p: float = 1.0
    cache_layout: str = "contiguous"
    page_size: int = 64
    # KV storage dtype knob (ISSUE 9): None keeps the model dtype;
    # "int8" selects the quantized paged layout (per-(page, head) scale
    # sidecars, dequant fused into the decode kernels) — halved pool
    # bytes the scheduler converts into concurrent sequences.  Paged
    # layout only.
    kv_dtype: str | None = None
    # default per-request wall budget for :meth:`serve` when resilience
    # is enabled (TDT_RESILIENCE=1); None = unbounded unless the call
    # passes ``deadline_ms`` explicitly
    request_deadline_ms: float | None = None

    def __post_init__(self):
        import threading

        self._failed_requests = 0
        self._last_failure: str | None = None
        # flight-recorder step ordinal (TDT_FLIGHT=1): monotone across
        # requests so the ring's last-N-steps retention spans request
        # boundaries; ``_last_flight`` holds the dump of the most recent
        # failed step for Engine.health()
        self._flight_step = 0
        self._last_flight: tuple[str, ...] = ()
        # watchdog dispatch threads abandoned by a deadline breach: their
        # in-flight steps must not clobber the engine's (reset) cache —
        # thread OBJECTS, not idents (idents recycle after thread death).
        # The lock orders the membership checks against _mark_failed's
        # add-then-reset (no check-then-assign window on a timeout).
        self._abandoned_threads: set = set()
        self._fence_lock = threading.Lock()
        c = self.model.config
        if self.cache_layout == "paged":
            self.cache = init_paged_cache(
                self.model.mesh, c.num_layers, self.batch, c.num_kv_heads,
                c.max_length, c.head_dim, c.dtype, self.model.axis,
                page_size=self.page_size, kv_dtype=self.kv_dtype,
            )
        elif self.cache_layout == "contiguous":
            if self.kv_dtype is not None:
                raise ValueError(
                    "kv_dtype quantization needs cache_layout='paged' "
                    "(the per-(page, head) scale layout)")
            self.cache = init_cache(
                self.model.mesh, c.num_layers, self.batch, c.num_kv_heads,
                c.max_length, c.head_dim, c.dtype, self.model.axis,
            )
        else:
            raise ValueError(
                f"cache_layout {self.cache_layout!r} not in "
                "('contiguous', 'paged')"
            )
        # the CUDA-graph analogue: jit with the cache donated so decode
        # steps update the cache buffers in place
        self._prefill = jax.jit(self.model.prefill, donate_argnums=(1,))
        self._decode = jax.jit(self.model.decode, donate_argnums=(1,))
        # bucketed AOT executables (Engine.precompile / load_precompiled):
        # {bucket_len: Compiled}; when present, prefill dispatches by
        # bucket and never retraces
        self._prefill_exec: dict[int, Any] = {}
        self._decode_exec = None
        self._exec_params_put: dict = {}
        # live telemetry plane (TDT_OBS_HTTP=<port>): /metrics, /healthz
        # (this engine's health()), /debug/flight|timeline.  The env
        # check here keeps the unset path to ONE dict lookup — touching
        # obs.server would pay its lazy http.server import chain
        # (docs/observability.md "Live telemetry")
        import os

        if os.environ.get("TDT_OBS_HTTP", "").strip():
            obs.server.maybe_start(self)

    @classmethod
    def build(cls, config: ModelConfig, mesh: Mesh, *, key=None,
              batch: int = 1, axis: str = TP_AXIS,
              decode_mode: str = "psum", **kw) -> "Engine":
        """``decode_mode``: "psum" | "ar" | "gemm_ar" | "fused" — the
        decode-step kernel chain (reference ``set_fwd``); "fused" is the
        decode megakernel (``ops.fused_decode``, docs/perf.md "Decode
        megakernel"); see :class:`Qwen3`."""
        model = Qwen3(config, mesh, axis, decode_mode=decode_mode)
        params = model.init(key if key is not None else jax.random.key(0))
        return cls(model, params, batch=batch, **kw)

    def scheduler(self, *, pool_pages: int | None = None,
                  chunk_tokens: int = 64, steps_per_dispatch: int = 1,
                  config=None, **cfg_kw):
        """The continuous-batching serving loop over this engine
        (ROADMAP item 1; ``docs/serving.md``): the engine contributes
        STATELESS, non-donated jit step functions (``Qwen3.decode`` /
        ``Qwen3.prefill_chunk`` — shapes fixed, so batch-membership
        changes never retrace), the returned ``serve.Scheduler`` owns
        everything stateful: the bounded request queue, the KV-page
        free list sized by ``pool_pages`` (the serving memory budget —
        may deliberately UNDERsize ``batch * max_length`` to overcommit,
        relying on preemption), chunked prefill at ``chunk_tokens``
        per step, per-request deadlines, per-sequence failure
        isolation, and degradation.  Requires ``cache_layout='paged'``.
        ``steps_per_dispatch`` > 1 batches membership-stable windows of
        decode steps into one device dispatch (the ISSUE-13 persistent
        serving loop; docs/serving.md "steps_per_dispatch") — pair with
        ``decode_mode="persistent"`` for the full device-resident path.

        ``config``: a full ``serve.SchedulerConfig``; or pass its
        fields as ``**cfg_kw``.  ``Engine.serve`` remains the
        single-batch path (one fixed-shape request end to end)."""
        from ..serve import EngineBackend, Scheduler, SchedulerConfig

        backend = EngineBackend(self, pool_pages=pool_pages,
                                chunk_tokens=chunk_tokens,
                                steps_per_dispatch=steps_per_dispatch)
        if config is None:
            cfg_kw.setdefault("prefill_chunk_tokens", chunk_tokens)
            config = SchedulerConfig(**cfg_kw)
        return Scheduler(backend, config)

    def set_decode_mode(self, mode: str) -> None:
        """Swap the decode-step reduction implementation in place (the
        reference's ``set_fwd`` switch, ``models/qwen.py:85``).  Params and
        cache are kept; the decode step re-jits on next call.  Any AOT
        decode executable is DROPPED (it bakes in the old mode) — re-run
        :meth:`precompile` to restore zero-compile serving."""
        self.model = dataclasses.replace(self.model, decode_mode=mode)
        self._decode = jax.jit(self.model.decode, donate_argnums=(1,))
        self._decode_exec = None

    def prefill(self, input_ids: jax.Array) -> jax.Array:
        """Run the prompt; returns last-position logits (B, V).
        With ``TDT_OBS=1`` the call is recorded as a ``prefill`` step span
        (host wall time of the dispatch; device time is async).

        With precompiled buckets (:meth:`precompile` /
        :meth:`load_precompiled`) the prompt is right-padded to the
        smallest bucket >= its length and dispatched to that AOT
        executable — no tracing happens on this path (reference: the
        signature-space dispatch its AOT linker emits,
        ``tools/compile_aot.py:61-130`` + ``link_all:470``)."""
        max_len = self.model.config.max_length
        b, plen = input_ids.shape
        # fail loudly BEFORE tracing: a batch mismatch used to surface
        # as an opaque shape error deep in the jitted step (or, on the
        # AOT path, a bucket sharding rejection)
        if b != self.batch:
            raise ValueError(
                f"input_ids batch {b} does not match engine batch "
                f"{self.batch} — the cache and compiled steps are shaped "
                f"for batch={self.batch}; rebuild the engine or reshape "
                f"the prompt batch"
            )
        if plen > max_len:
            raise ValueError(
                f"prompt length {plen} exceeds max_length={max_len}"
            )
        self._flight_tick()
        with obs.span("prefill", cat="step", batch=b, prompt_len=plen):
            return self._prefill_dispatch(input_ids, b, plen)

    def _flight_tick(self) -> None:
        """One serving-step boundary on the flight ring (≈0 when
        TDT_FLIGHT is off — one cached-bool check)."""
        if obs.flight.enabled():
            self._flight_step += 1
            obs.flight.mark_step(self._flight_step)

    def _set_cache(self, cache) -> None:
        """Adopt a step's updated cache UNLESS this thread was abandoned
        by a watchdog deadline breach — a stale dispatch completing
        after :meth:`_mark_failed` reset the cache must not clobber the
        next request's clean state (failed-step isolation).  Check and
        assignment share ``_fence_lock`` with ``_mark_failed``'s
        add-then-reset, so a timeout firing between them cannot slip a
        stale cache past the fence.  Refusal RAISES (same abort as
        ``_check_abandoned``): falling through would let the stale step
        keep running and read — with donation, consume — the fresh
        cache on its next use of ``self.cache``."""
        import threading

        with self._fence_lock:
            if threading.current_thread() not in self._abandoned_threads:
                self.cache = cache
                return
        self._raise_abandoned()

    def _check_abandoned(self) -> None:
        """Kill an abandoned serving thread at its next step: letting it
        continue would READ (and, with donation, consume) the reset
        cache the next request owns.  The raise lands in the watchdog's
        result box, which nobody reads."""
        import threading

        with self._fence_lock:
            abandoned = threading.current_thread() in \
                self._abandoned_threads
        if abandoned:
            self._raise_abandoned()

    def _raise_abandoned(self) -> None:
        import threading

        # this thread is about to unwind out of the engine for good:
        # drop its fence entry so the set stays bounded by in-flight
        # breaches, not by the engine's lifetime breach count
        with self._fence_lock:
            self._abandoned_threads.discard(threading.current_thread())
        raise RuntimeError(
            "serving thread abandoned after a deadline breach; "
            "aborting stale dispatch"
        )

    def _prefill_dispatch(self, input_ids, b: int, plen: int) -> jax.Array:
        self._check_abandoned()
        self._set_cache(reset(self.cache))
        if self._prefill_exec:
            bucket = min(
                (L for L in self._prefill_exec if L >= plen), default=None
            )
            if bucket is not None:
                ids = input_ids if bucket == plen else jnp.concatenate(
                    [input_ids,
                     jnp.zeros((b, bucket - plen), input_ids.dtype)], axis=1
                )
                logits, cache = self._call_exec(
                    self._prefill_exec[bucket],
                    self.params, self.cache, ids, jnp.int32(plen),
                )
                self._set_cache(cache)
                return logits[:, plen - 1]
            # longer than every bucket: fall through to the jit path
        logits, cache = self._prefill(self.params, self.cache, input_ids)
        self._set_cache(cache)
        return logits[:, -1]

    def _call_exec(self, ex, params, *rest):
        """Invoke an AOT executable, resharding inputs to its compiled
        input shardings first.  A Compiled object (unlike jit) REJECTS
        semantically-equal-but-differently-expressed shardings — e.g. the
        GSPMD shardings a jit-path output carries vs the NamedShardings
        the executable was lowered with — so arguments are device_put to
        the exact expected shardings (a no-op for already-matching
        placements).  The PARAMS subtree (hundreds of leaves on a real
        model, shardings fixed after build) is resharded once per
        (executable, params) pair and memoized; only the small
        cache/tokens/length trees pay the per-call traversal on the
        per-token decode path."""
        arg_sh = tuple(ex.input_shardings[0])
        # keyed by the EXECUTABLE OBJECT (strong ref; a handful exist) and
        # validated by params IDENTITY — an id()-keyed memo without a
        # retained reference could match a recycled id after a weight
        # swap and silently serve stale weights
        hit = self._exec_params_put.get(ex)
        if hit is None or hit[0] is not params:
            hit = (params, jax.tree.map(jax.device_put, params, arg_sh[0]))
            self._exec_params_put[ex] = hit
        rest = tuple(
            jax.tree.map(jax.device_put, r, s)
            for r, s in zip(rest, arg_sh[1:])
        )
        return ex(hit[1], *rest)

    def decode_step(self, tokens: jax.Array) -> jax.Array:
        self._check_abandoned()
        with obs.span("decode_dispatch", cat="compute"):
            if self._decode_exec is not None:
                logits, cache = self._call_exec(
                    self._decode_exec, self.params, self.cache, tokens
                )
                self._set_cache(cache)
                return logits
            logits, cache = self._decode(self.params, self.cache, tokens)
            self._set_cache(cache)
            return logits

    # -- bucketed AOT serving ---------------------------------------------

    _MANIFEST = "aot_manifest.json"

    def precompile(self, prompt_buckets, save_dir: str | None = None) -> dict:
        """AOT-compile prefill for each prompt-length bucket plus the
        decode step; optionally serialize next to the weights.

        Reference: ``compile_aot.py:61-130`` declares signature/grid
        spaces per kernel and links a dispatcher so serving launches
        graph-safely with zero JIT work; here each bucket is one XLA
        executable taking (params, cache, padded_ids, true_len) — the
        traced ``true_len`` makes a single bucket exact for every prompt
        length <= its shape (see ``Qwen3.prefill``).  Returns the
        manifest dict; ``load_precompiled`` restores the executables in
        another process with zero retraces.
        """
        import json
        import os

        from ..tools import aot

        if self.cache_layout != "contiguous":
            raise ValueError("bucketed AOT serving supports the contiguous "
                             "cache layout")
        c = self.model.config
        buckets = sorted(set(int(x) for x in prompt_buckets))
        if not buckets or buckets[0] < 1 or buckets[-1] > c.max_length:
            raise ValueError(
                f"buckets must be within [1, max_length={c.max_length}]; "
                f"got {buckets}"
            )
        from ..core import compilation

        cache0 = reset(self.cache)
        # a fresh bucket set REPLACES any previous one: accumulating would
        # desynchronize the in-memory dispatch from the saved manifest
        self._prefill_exec = {}
        self._exec_params_put = {}
        for L in buckets:
            ids = jnp.zeros((self.batch, L), jnp.int32)
            self._prefill_exec[L] = self._prefill.lower(
                self.params, cache0, ids, jnp.int32(L)
            ).compile()
        self._decode_exec = self._decode.lower(
            self.params, cache0, jnp.zeros((self.batch,), jnp.int32)
        ).compile()
        manifest = {
            "buckets": buckets,
            "batch": self.batch,
            "max_length": c.max_length,
            "vocab": c.vocab,
            "decode_mode": self.model.decode_mode,
            "cache_layout": self.cache_layout,
            "arch": arch_fingerprint(c, self.model.mesh, self.model.axis),
        }
        if save_dir is not None:
            if compilation.interpret_mode():
                raise RuntimeError(
                    "serializing AOT bundles requires real-TPU lowering "
                    "(interpret kernels embed python callbacks XLA cannot "
                    "serialize)"
                )
            os.makedirs(save_dir, exist_ok=True)
            for L, ex in self._prefill_exec.items():
                aot.save(ex, os.path.join(save_dir, f"prefill_{L}.xla"))
            aot.save(self._decode_exec, os.path.join(save_dir, "decode.xla"))
            with open(os.path.join(save_dir, self._MANIFEST), "w") as f:
                json.dump(manifest, f, indent=1, sort_keys=True)
        return manifest

    def load_precompiled(self, save_dir: str) -> dict:
        """Restore :meth:`precompile`'s serialized executables — the
        second-process serving path: after this, prefill (for lengths
        within the buckets) and decode never trace or compile."""
        import json
        import os

        from ..tools import aot

        with open(os.path.join(save_dir, self._MANIFEST)) as f:
            manifest = json.load(f)
        c = self.model.config
        mine = {"batch": self.batch, "max_length": c.max_length,
                "vocab": c.vocab, "decode_mode": self.model.decode_mode,
                "cache_layout": self.cache_layout}
        for field, have in mine.items():
            want = manifest.get(field)
            if want != have:
                raise ValueError(
                    f"AOT bundle was compiled for {field}={want!r}; this "
                    f"engine has {field}={have!r}"
                )
        check_arch(manifest,
                   arch_fingerprint(c, self.model.mesh, self.model.axis))
        self._prefill_exec = {
            int(L): aot.load(os.path.join(save_dir, f"prefill_{L}.xla"))
            for L in manifest["buckets"]
        }
        self._decode_exec = aot.load(os.path.join(save_dir, "decode.xla"))
        return manifest

    def _check_length(self, prompt_len: int, gen_len: int) -> None:
        # dynamic_update_slice CLAMPS out-of-range writes: past max_length
        # the cache would silently corrupt, so refuse up front
        max_len = self.model.config.max_length
        if prompt_len + gen_len > max_len:
            raise ValueError(
                f"prompt {prompt_len} + gen_len {gen_len} exceeds "
                f"max_length={max_len}"
            )

    def generate(self, input_ids: jax.Array, gen_len: int,
                 key: jax.Array | None = None) -> jax.Array:
        """Prefill + ``gen_len - 1`` decode steps (reference
        ``Engine.serve``).  Returns (B, gen_len) generated token ids."""
        self._check_length(input_ids.shape[1], gen_len)
        logits = self.prefill(input_ids)
        return self.generate_from_logits(logits, gen_len, key)

    def serve(self, input_ids: jax.Array, gen_len: int,
              key: jax.Array | None = None, *,
              deadline_ms: float | None = None):
        """Timed generate with a throughput report (reference
        ``Engine.serve:113``: prefill then graph-replayed decode, printing
        tokens/s).  Returns ``(tokens, stats)`` where stats has
        ``prefill_ms``, ``decode_ms_per_token``, ``decode_tokens_per_s``
        (wall-clock, compile excluded by a 1-token warmup).

        ``deadline_ms`` (or, with ``TDT_RESILIENCE=1``, the engine's
        ``request_deadline_ms``) bounds the REQUEST: the prefill block
        and the decode block each run under the remaining budget and a
        breach raises ``CollectiveTimeoutError`` instead of hanging the
        serve loop.  Failed-step isolation: any failure inside the timed
        region resets the KV cache (the donated buffers are in an
        unknown state after an abandoned dispatch) and lands in
        :meth:`health` before re-raising — the engine object stays
        serviceable for the next request."""
        b, prompt_len = input_ids.shape
        self._check_length(prompt_len, gen_len)
        # live telemetry: the queue-depth gauge spans the whole request
        # (warmup included — the operator sees compile stalls as queued
        # requests); the latency sketches get only the timed stats below.
        # Balanced by the request_end in the finally below — ANY exit,
        # including a failure in the metrics recording itself, must not
        # leak the depth gauge.
        live = obs.enabled()
        if live:
            obs.serve_stats.STATS.request_begin()
        ok = False
        try:
            tokens, stats = self._serve_inner(input_ids, gen_len, key,
                                              deadline_ms, b, prompt_len)
            ok = True
            return tokens, stats
        finally:
            if live:
                obs.serve_stats.STATS.request_end(failed=not ok)

    def _serve_inner(self, input_ids, gen_len, key, deadline_ms,
                     b: int, prompt_len: int):
        import time

        if deadline_ms is None:
            from .. import resilience

            if resilience.enabled():
                deadline_ms = self.request_deadline_ms
        # warmup/compile both steps outside the timed region (the
        # reference's graph capture happens before its timed replay too);
        # run through the stateful path — the donated cache buffers are
        # consumed and replaced, and the timed prefill resets the length.
        # Span recording is suppressed: a compile-time warmup is not a
        # serving step and would land a multi-second outlier in the
        # overlap report's per-step table.  The warmup is also outside
        # the request deadline: a first-call compile is not request work.
        with obs.suppress():
            jax.block_until_ready(self.prefill(input_ids))
            jax.block_until_ready(
                self.decode_step(jnp.zeros((b,), jnp.int32)))

        t0 = time.perf_counter()
        try:
            logits = self._step_bounded(
                "engine_prefill",
                lambda: jax.block_until_ready(self.prefill(input_ids)),
                deadline_ms, t0)
            t1 = time.perf_counter()
            tokens = self._step_bounded(
                "engine_decode",
                lambda: jax.block_until_ready(
                    self.generate_from_logits(logits, gen_len, key)),
                deadline_ms, t0)
            t2 = time.perf_counter()
        except Exception as e:
            self._mark_failed(e)
            raise
        decode_steps = max(gen_len - 1, 1)
        stats = {
            "prefill_ms": (t1 - t0) * 1e3,
            "decode_ms_per_token": (t2 - t1) * 1e3 / decode_steps,
            "decode_tokens_per_s": b * decode_steps / max(t2 - t1, 1e-9),
        }
        if obs.enabled():
            self._record_serve_metrics(prompt_len, gen_len, stats)
        return tokens, stats

    def _step_bounded(self, op: str, thunk, deadline_ms: float | None,
                      t0: float):
        """Run one serving step under what remains of the request budget
        (None = unbounded)."""
        if deadline_ms is None:
            return thunk()
        import time

        from .. import resilience

        remaining = deadline_ms - (time.perf_counter() - t0) * 1e3
        if remaining <= 0:
            raise resilience.CollectiveTimeoutError(
                op, deadline_ms, resilience.TimeoutDiagnosis(
                    op, 0, note="request budget exhausted before this "
                                "step started"))
        return resilience.call_with_deadline(op, thunk, remaining)

    def _mark_failed(self, err: BaseException) -> None:
        """Failed-step isolation: record the failure, fence the
        abandoned dispatch thread (its in-flight step must neither write
        its stale cache over ours nor read/donate the fresh one — see
        ``_set_cache`` / ``_check_abandoned``), and reset the KV cache
        so the NEXT request starts from clean state."""
        self._failed_requests += 1
        self._last_failure = f"{type(err).__name__}: {err}"
        if obs.flight.enabled():
            # dump the ring at failure time: the last-N-steps protocol
            # history behind this request's death, kept for health() and
            # attached to the error (docs/observability.md)
            self._last_flight = obs.flight.recent_lines(32)
            if hasattr(err, "add_note"):
                err.add_note("flight recorder (last events): "
                             + " | ".join(self._last_flight[-8:]))
        abandoned = getattr(err, "abandoned_thread", None)
        with self._fence_lock:
            # prune threads that already exited (their identity can
            # never re-enter the engine) so the set stays bounded
            self._abandoned_threads = {
                t for t in self._abandoned_threads if t.is_alive()
            }
            if abandoned is not None:
                self._abandoned_threads.add(abandoned)
            try:
                self.cache = reset(self.cache)
            except Exception:
                pass  # best effort: health still records the failure
        if obs.enabled():
            obs.counter("engine_failed_requests",
                        kind=type(err).__name__).inc()

    def health(self) -> dict:
        """Serving-health snapshot: resilience breaker/counter state
        (``resilience.health_snapshot``) plus this engine's request
        failure history and configuration — the ``/health`` payload of a
        serving wrapper."""
        from .. import resilience

        snap = resilience.health_snapshot()
        # live-serving percentiles and windowed rates (obs.serve_stats):
        # populated when TDT_OBS=1, zeroed sketches otherwise
        snap["serve_stats"] = obs.serve_stats.STATS.snapshot()
        snap["engine"] = {
            "failed_requests": self._failed_requests,
            "last_failure": self._last_failure,
            "batch": self.batch,
            "cache_layout": self.cache_layout,
            "decode_mode": self.model.decode_mode,
            "request_deadline_ms": self.request_deadline_ms,
            "aot_prefill_buckets": sorted(self._prefill_exec),
            "last_flight": list(self._last_flight),
        }
        return snap

    def close(self) -> None:
        """Release engine-owned telemetry: stop the ``TDT_OBS_HTTP``
        endpoint iff this engine is its registered health source
        (another engine's plane is left running)."""
        obs.server.release(self)

    def _record_serve_metrics(self, prompt_len: int, gen_len: int,
                              stats: dict) -> None:
        """Serve-loop telemetry (``TDT_OBS=1``): latency histograms,
        throughput gauge, tokens counter, and KV-cache / device-memory
        occupancy gauges (``docs/observability.md``)."""
        obs.histogram("engine_prefill_ms").observe(stats["prefill_ms"])
        obs.histogram("engine_decode_ms_per_token").observe(
            stats["decode_ms_per_token"])
        obs.gauge("engine_decode_tokens_per_s").set(
            stats["decode_tokens_per_s"])
        obs.counter("engine_tokens_generated").inc(self.batch * gen_len)
        # live telemetry plane: latency sketches + windowed tokens/s
        # (obs.serve_stats, scraped via /metrics and Engine.health())
        obs.serve_stats.STATS.observe_request(
            prompt_len=prompt_len, gen_len=gen_len, stats=stats,
            batch=self.batch)
        c = self.model.config
        # sequence occupancy: how full the (contiguous or paged) cache's
        # length budget is after this request
        occupancy = (prompt_len + gen_len) / c.max_length
        obs.gauge("kv_cache_seq_occupancy").set(occupancy)
        obs.serve_stats.STATS.set_gauge("kv_cache_seq_occupancy", occupancy)
        from ..tools.profile import memory_stats

        for dev, st in memory_stats().items():
            in_use = st.get("bytes_in_use")
            limit = st.get("bytes_limit")
            if in_use is not None:
                obs.gauge("device_bytes_in_use", device=dev).set(in_use)
            if in_use and limit:
                obs.gauge("device_memory_occupancy", device=dev).set(
                    in_use / limit)
                obs.serve_stats.STATS.set_gauge(
                    f"device_memory_occupancy_{dev}", in_use / limit)

    def generate_from_logits(self, logits: jax.Array, gen_len: int,
                             key: jax.Array | None = None) -> jax.Array:
        """Decode loop given the prefill's last-position logits (the decode
        half of :meth:`generate`; cache state must match)."""
        key = key if key is not None else jax.random.key(0)
        outs = []
        tok = sample_token(logits, key, temperature=self.temperature,
                           top_p=self.top_p)
        outs.append(tok)
        for i in range(gen_len - 1):
            # one "step" span per generated token: the unit the overlap
            # report (scripts/obs_report.py) groups comm/compute spans by
            self._flight_tick()
            with obs.span("decode_step", cat="step", idx=i):
                step_logits = self.decode_step(tok)
                key = jax.random.fold_in(key, i)
                tok = sample_token(step_logits, key,
                                   temperature=self.temperature,
                                   top_p=self.top_p)
            outs.append(tok)
        return jnp.stack(outs, axis=1)
