"""Model configuration (reference: ``python/triton_dist/models/config.py`` /
the HF config fields ``models/qwen.py`` consumes)."""

from __future__ import annotations

import dataclasses

import jax.numpy as jnp


@dataclasses.dataclass(frozen=True)
class ModelConfig:
    """Qwen3-family decoder hyperparameters (defaults: a tiny test model;
    Qwen3-8B-sized values in the docstrings)."""

    num_layers: int = 2            # 36
    hidden: int = 128              # 4096
    intermediate: int = 256        # 12288
    num_heads: int = 8             # 32
    num_kv_heads: int = 4          # 8
    head_dim: int = 64             # 128
    vocab: int = 512               # 151936
    max_length: int = 512          # 32k
    rope_theta: float = 1e6
    rms_eps: float = 1e-6
    qk_norm: bool = True           # Qwen3 normalizes Q/K per head
    dtype: jnp.dtype = jnp.bfloat16

    # MoE (Qwen3-MoE family): num_experts == 0 means dense layers
    num_experts: int = 0           # 128 (Qwen3-30B-A3B)
    top_k: int = 8
    moe_intermediate: int = 0      # 768; per-expert SwiGLU width
    norm_topk: bool = True         # renormalize routing weights over top-k
    moe_strategy: str = "tp"       # "tp" (experts F-sharded) | "ep"
                                   # (experts partitioned; A2A dispatch)
    moe_fp8_wire: bool | str = False  # EP A2A e4m3 wire; "auto" = DCN hops only
                                   # (reference low-latency A2A production
                                   # config); compute stays in `dtype`

    @property
    def is_moe(self) -> bool:
        return self.num_experts > 0
