"""Checkpoint save/restore for model params (and any pytree).

The reference has NO checkpoint path (SURVEY.md section 5: weights stream
from the HF hub per run).  On TPU, serving restarts are routine (preemption)
and re-sharding a large model from host weights is minutes of wall clock,
so the framework ships the orbax-based path: sharded arrays are written
per-shard and restored DIRECTLY into their target shardings — no host
staging of the full model.
"""

from __future__ import annotations

import jax


def save_checkpoint(path: str, pytree) -> None:
    """Write ``pytree`` (e.g. ``QwenParams``) to ``path`` (a directory)."""
    import orbax.checkpoint as ocp

    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, pytree, force=True)


def load_checkpoint(path: str, like):
    """Restore a checkpoint into the structure/shardings of ``like``
    (an abstract or concrete pytree with the target shardings)."""
    import orbax.checkpoint as ocp

    target = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding)
        if hasattr(x, "sharding") else x,
        like,
    )
    with ocp.StandardCheckpointer() as ckptr:
        return ckptr.restore(path, target)
