"""Safetensors weight files: native mmap reader, numpy fallback, writer.

The file-level half of weight ingest (reference: weights stream from the
HF hub through torch's loader, ``models/qwen.py:147-165``; its host-side
native code lives in ``csrc/``).  Here the reader is native C++
(``csrc/safetensors_reader.cc``: one mmap, header parsed without a JSON
DOM, zero-copy tensor views served straight from the mapping), compiled
on demand via ``tools.native`` with a pure-numpy fallback producing the
same views through ``np.memmap``.  ``load_state_dict`` accepts a single
``.safetensors`` file, an HF ``*.index.json``, or a directory of shards,
and feeds ``loader.load_qwen_state_dict`` without materializing more
than one device copy.

Arrays returned by the readers are read-only views into the mapped file;
the mapping lives as long as some returned array (or the
:class:`SafetensorsFile`) is referenced.
"""

from __future__ import annotations

import ctypes
import json
import os
import struct
from typing import Iterator, Mapping

import numpy as np

_DTYPES: dict[str, np.dtype] = {}


def _dtype_table() -> dict[str, np.dtype]:
    if not _DTYPES:
        import ml_dtypes

        _DTYPES.update({
            "F64": np.dtype(np.float64),
            "F32": np.dtype(np.float32),
            "F16": np.dtype(np.float16),
            "BF16": np.dtype(ml_dtypes.bfloat16),
            "F8_E4M3": np.dtype(ml_dtypes.float8_e4m3fn),
            "F8_E5M2": np.dtype(ml_dtypes.float8_e5m2),
            "I64": np.dtype(np.int64),
            "I32": np.dtype(np.int32),
            "I16": np.dtype(np.int16),
            "I8": np.dtype(np.int8),
            "U64": np.dtype(np.uint64),
            "U32": np.dtype(np.uint32),
            "U16": np.dtype(np.uint16),
            "U8": np.dtype(np.uint8),
            "BOOL": np.dtype(np.bool_),
        })
    return _DTYPES


def _to_tag(dt: np.dtype) -> str:
    for tag, d in _dtype_table().items():
        if d == dt:
            return tag
    raise ValueError(f"dtype {dt} has no safetensors tag")


def _load_lib():
    from ..tools.native import load_native

    lib = load_native("safetensors_reader.cc")
    if lib and not getattr(lib, "_st_typed", False):
        lib.st_open.restype = ctypes.c_void_p
        lib.st_open.argtypes = [ctypes.c_char_p]
        lib.st_last_error.restype = ctypes.c_char_p
        lib.st_num_tensors.restype = ctypes.c_long
        lib.st_num_tensors.argtypes = [ctypes.c_void_p]
        lib.st_name.restype = ctypes.c_char_p
        lib.st_name.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.st_dtype.restype = ctypes.c_char_p
        lib.st_dtype.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.st_ndim.restype = ctypes.c_long
        lib.st_ndim.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.st_shape.restype = None
        lib.st_shape.argtypes = [
            ctypes.c_void_p, ctypes.c_long, ctypes.POINTER(ctypes.c_longlong)
        ]
        lib.st_data.restype = ctypes.c_void_p
        lib.st_data.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.st_nbytes.restype = ctypes.c_longlong
        lib.st_nbytes.argtypes = [ctypes.c_void_p, ctypes.c_long]
        lib.st_close.restype = None
        lib.st_close.argtypes = [ctypes.c_void_p]
        lib._st_typed = True
    return lib


class SafetensorsFile(Mapping):
    """Dict-like zero-copy view of one ``.safetensors`` file.

    ``native=None`` (default) uses the C++ reader when the toolchain is
    available, else the numpy fallback; both produce identical read-only
    arrays.  Close explicitly or let GC unmap; arrays handed out keep the
    mapping alive through their ``base`` chain (numpy path) or a handle
    reference (native path).
    """

    def __init__(self, path: str, *, native: bool | None = None):
        self.path = path
        self._arrays: dict[str, np.ndarray] = {}
        self._handle = None
        self._lib = None
        lib = _load_lib() if native in (None, True) else False
        if native is True and not lib:
            raise RuntimeError("native safetensors reader unavailable")
        if lib:
            handle = lib.st_open(path.encode())
            if not handle:
                raise ValueError(
                    f"{path}: {lib.st_last_error().decode(errors='replace')}"
                )
            self._lib, self._handle = lib, handle
            self._read_native(lib, handle)
        else:
            self._read_numpy(path)

    def _read_native(self, lib, handle) -> None:
        table = _dtype_table()
        for i in range(lib.st_num_tensors(handle)):
            name = lib.st_name(handle, i).decode()
            tag = lib.st_dtype(handle, i).decode()
            if tag not in table:
                raise ValueError(f"{self.path}: unsupported dtype {tag!r}")
            ndim = lib.st_ndim(handle, i)
            shape = (ctypes.c_longlong * max(ndim, 1))()
            lib.st_shape(handle, i, shape)
            nbytes = lib.st_nbytes(handle, i)
            ptr = lib.st_data(handle, i)
            if nbytes:
                buf = (ctypes.c_ubyte * nbytes).from_address(ptr)
                arr = np.frombuffer(buf, dtype=table[tag])
            else:
                arr = np.empty(0, dtype=table[tag])
            arr = arr.reshape(tuple(shape[:ndim]))
            arr.flags.writeable = False
            # keep the mapping alive as long as any view is
            arr = arr.view(_OwnedView)
            arr._owner = self
            self._arrays[name] = arr

    def _read_numpy(self, path: str) -> None:
        table = _dtype_table()
        with open(path, "rb") as f:
            (hlen,) = struct.unpack("<Q", f.read(8))
            header = json.loads(f.read(hlen))
        raw = np.memmap(path, dtype=np.uint8, mode="r", offset=8 + hlen)
        for name, info in header.items():
            if name == "__metadata__":
                continue
            tag = info["dtype"]
            if tag not in table:
                raise ValueError(f"{path}: unsupported dtype {tag!r}")
            a, b = info["data_offsets"]
            shape = tuple(info["shape"])
            # mirror the native reader's validation: out-of-range offsets
            # would otherwise clamp through slicing and surface as an opaque
            # reshape error; overlaps/mismatches would be silently accepted
            count = 1
            for d in shape:
                if d < 0:
                    raise ValueError(
                        f"{path}: negative dimension in tensor {name!r}"
                    )
                count *= d
            itemsize = np.dtype(table[tag]).itemsize
            if not (0 <= a <= b <= raw.size) or b - a != count * itemsize:
                raise ValueError(
                    f"{path}: inconsistent tensor entry {name!r} "
                    f"(offsets [{a}, {b}), shape {shape})"
                )
            arr = raw[a:b].view(table[tag]).reshape(shape)
            self._arrays[name] = arr

    def __getitem__(self, name: str) -> np.ndarray:
        return self._arrays[name]

    def __iter__(self) -> Iterator[str]:
        return iter(self._arrays)

    def __len__(self) -> int:
        return len(self._arrays)

    def close(self) -> None:
        """Unmap.  Only safe once no returned array is referenced."""
        self._arrays.clear()
        if self._handle is not None:
            self._lib.st_close(self._handle)
            self._handle = None

    def __del__(self):
        try:
            if self._handle is not None and self._lib is not None:
                self._lib.st_close(self._handle)
        except Exception:
            pass


class _OwnedView(np.ndarray):
    """ndarray subclass carrying a reference to the mapping owner."""

    _owner = None

    def __array_finalize__(self, obj):
        if obj is not None:
            self._owner = getattr(obj, "_owner", None)


class _ShardedDict(Mapping):
    """Lazy union of per-shard :class:`SafetensorsFile` mappings."""

    def __init__(self, files: dict[str, SafetensorsFile],
                 weight_map: dict[str, str]):
        self._files = files
        self._map = weight_map

    def __getitem__(self, name: str) -> np.ndarray:
        return self._files[self._map[name]][name]

    def __iter__(self) -> Iterator[str]:
        return iter(self._map)

    def __len__(self) -> int:
        return len(self._map)


def load_state_dict(path: str, *, native: bool | None = None) -> Mapping:
    """Open safetensors weights as a lazy name -> ndarray mapping.

    ``path`` may be a ``.safetensors`` file, an HF ``*.index.json`` shard
    index, or a directory containing either.
    """
    if os.path.isdir(path):
        index = [f for f in sorted(os.listdir(path))
                 if f.endswith(".index.json")]
        if index:
            path = os.path.join(path, index[0])
        else:
            shards = [f for f in sorted(os.listdir(path))
                      if f.endswith(".safetensors")]
            if not shards:
                raise FileNotFoundError(f"no safetensors files under {path}")
            files = {
                f: SafetensorsFile(os.path.join(path, f), native=native)
                for f in shards
            }
            wmap = {name: f for f, sf in files.items() for name in sf}
            return _ShardedDict(files, wmap)
    if path.endswith(".index.json"):
        with open(path) as f:
            wmap = json.load(f)["weight_map"]
        base = os.path.dirname(path)
        files = {
            f: SafetensorsFile(os.path.join(base, f), native=native)
            for f in sorted(set(wmap.values()))
        }
        return _ShardedDict(files, wmap)
    return SafetensorsFile(path, native=native)


def save_safetensors(arrays: Mapping[str, np.ndarray], path: str,
                     *, metadata: dict[str, str] | None = None) -> None:
    """Write a safetensors file (pure Python; the export direction is
    cold).  Header is padded with spaces to 8-byte alignment like the
    format's reference implementation."""
    header: dict = {}
    if metadata:
        header["__metadata__"] = metadata
    off = 0
    items = [(name, np.asarray(arr)) for name, arr in arrays.items()]
    for name, arr in items:
        # np.asarray, NOT ascontiguousarray: the latter silently promotes
        # 0-d to 1-d, and tobytes() below emits C order for any layout
        header[name] = {
            "dtype": _to_tag(arr.dtype),
            "shape": list(arr.shape),
            "data_offsets": [off, off + arr.nbytes],
        }
        off += arr.nbytes
    # ensure_ascii=False: escaped non-BMP names would become surrogate
    # pairs, which the native reader rejects; raw UTF-8 parses everywhere
    hjson = json.dumps(header, separators=(",", ":"),
                       ensure_ascii=False).encode()
    pad = (8 - (len(hjson) % 8)) % 8
    hjson += b" " * pad
    tmp = path + f".tmp.{os.getpid()}"
    with open(tmp, "wb") as f:
        f.write(struct.pack("<Q", len(hjson)))
        f.write(hjson)
        for _, arr in items:
            # stream per tensor: peak RSS stays one tensor, not the model
            f.write(arr.tobytes())
    os.replace(tmp, path)
