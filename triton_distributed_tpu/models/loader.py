"""Weight loading: HF-style state dicts -> sharded TP params.

Reference: ``python/triton_dist/models/qwen.py:147-165`` — weights stream
from the HF hub, and each layer's ``_init_parameters`` shards
q/k/v/o/gate/up/down into the fused per-rank layouts.

Here the same mapping runs on host numpy/torch tensors and lands directly
in the framework's layouts: wqkv fused rank-blocked [q_r | k_r | v_r],
gate_up fused [gate_r | up_r], row-sharded wo/down — one ``device_put``
per parameter, sharded placement included (no full-model replication on
any single device beyond the host staging copy).

HF linear weights are stored as (out_features, in_features); this
framework right-multiplies activations, so every matrix is transposed on
ingest.
"""

from __future__ import annotations

from typing import Mapping

import jax
import jax.numpy as jnp
import numpy as np

from .config import ModelConfig
from .qwen import Qwen3, QwenLayerParams, QwenParams


def _as_np(t) -> np.ndarray:
    """Accept torch tensors or arrays without importing torch eagerly."""
    if hasattr(t, "detach"):
        t = t.detach().cpu().numpy()
    return np.asarray(t)


def _w(sd: Mapping, key: str, dtype) -> jnp.ndarray:
    """Fetch an HF linear weight and transpose to (in, out)."""
    return jnp.asarray(_as_np(sd[key]).T, dtype=dtype)


def _vec(sd: Mapping, key: str, dtype) -> jnp.ndarray:
    return jnp.asarray(_as_np(sd[key]), dtype=dtype)


def _np_fuse_gate_up(gate: np.ndarray, up: np.ndarray, n: int) -> np.ndarray:
    """Host-side mirror of ``MoEMLP.fuse_expert_gate_up``: (E, K, F) pairs
    -> (E, K, 2F) rank-blocked ``[gate_r | up_r]`` columns (plain
    ``[gate | up]`` at n=1, the EP layout)."""
    f = gate.shape[2]
    if f % n:
        raise ValueError(f"expert width {f} not divisible by {n} shards")
    i = f // n
    blocks = []
    for r in range(n):
        blocks.append(gate[:, :, r * i:(r + 1) * i])
        blocks.append(up[:, :, r * i:(r + 1) * i])
    return np.concatenate(blocks, axis=2)


def load_qwen_state_dict(
    model: Qwen3,
    state_dict: Mapping,
    *,
    prefix: str = "model.",
) -> QwenParams:
    """Build sharded :class:`QwenParams` from a HF Qwen3-style state dict
    (torch tensors or numpy arrays).

    Expected keys (HF Qwen3 naming): ``model.embed_tokens.weight``,
    per layer ``model.layers.{i}.input_layernorm.weight``,
    ``...self_attn.{q,k,v,o}_proj.weight`` (+ optional ``q_norm``/
    ``k_norm``), ``...post_attention_layernorm.weight``,
    ``...mlp.{gate,up,down}_proj.weight``, ``model.norm.weight``, and
    ``lm_head.weight`` (falls back to tied embeddings when absent).
    """
    c: ModelConfig = model.config
    dt = c.dtype
    attn_l = model._attn_layer()
    mlp_l = model._mlp_layer()
    from ..core.mesh import replicated

    def rep(x):
        # explicit replicated placement: a later checkpoint restore commits
        # shardings, so uncommitted single-device arrays must not mix in
        return jax.device_put(x, replicated(model.mesh))

    layers = []
    for i in range(c.num_layers):
        lp = f"{prefix}layers.{i}."
        qn = kn = None
        if c.qk_norm:
            qn = rep(_vec(state_dict, lp + "self_attn.q_norm.weight", dt))
            kn = rep(_vec(state_dict, lp + "self_attn.k_norm.weight", dt))
        attn = attn_l.shard_params(
            _w(state_dict, lp + "self_attn.q_proj.weight", dt),
            _w(state_dict, lp + "self_attn.k_proj.weight", dt),
            _w(state_dict, lp + "self_attn.v_proj.weight", dt),
            _w(state_dict, lp + "self_attn.o_proj.weight", dt),
            qn, kn,
        )
        if c.is_moe:
            # HF Qwen3-MoE: mlp.gate (router, (E, K)) + per-expert
            # gate/up/down projections.  Stack + fuse on HOST numpy: the
            # expert stacks are the big tensors, and a device-side fuse
            # would stage full unsharded (E, K, 2F) copies on one chip —
            # device_put of the host array straight into the sharded
            # layout keeps the no-single-device-replication guarantee.
            moe_l = model._moe_layer()
            is_ep = c.moe_strategy == "ep"
            router = _w(state_dict, lp + "mlp.gate.weight", dt)
            gates, ups, downs = [], [], []
            for j in range(c.num_experts):
                ep = lp + f"mlp.experts.{j}."
                gates.append(_as_np(state_dict[ep + "gate_proj.weight"]).T)
                ups.append(_as_np(state_dict[ep + "up_proj.weight"]).T)
                downs.append(_as_np(state_dict[ep + "down_proj.weight"]).T)
            w_up = _np_fuse_gate_up(
                np.stack(gates), np.stack(ups), 1 if is_ep else model.tp
            ).astype(jnp.dtype(dt))
            shard_fn = (moe_l.shard_params_ep if is_ep
                        else moe_l.shard_params_tp)
            # numpy in: device_put shards straight from host memory
            mlp = shard_fn(
                router, w_up, np.stack(downs).astype(jnp.dtype(dt))
            )
        else:
            mlp = mlp_l.shard_params(
                _w(state_dict, lp + "mlp.gate_proj.weight", dt),
                _w(state_dict, lp + "mlp.up_proj.weight", dt),
                _w(state_dict, lp + "mlp.down_proj.weight", dt),
            )
        layers.append(QwenLayerParams(
            ln1=rep(_vec(state_dict, lp + "input_layernorm.weight", dt)),
            attn=attn,
            ln2=rep(_vec(state_dict, lp + "post_attention_layernorm.weight", dt)),
            mlp=mlp,
        ))

    embed = jnp.asarray(_as_np(state_dict[prefix + "embed_tokens.weight"]),
                        dtype=dt)
    if "lm_head.weight" in state_dict:
        lm_head = _w(state_dict, "lm_head.weight", dt)
    else:  # tied embeddings
        lm_head = embed.T
    return QwenParams(
        embed=rep(embed),
        layers=layers,
        final_norm=rep(_vec(state_dict, prefix + "norm.weight", dt)),
        lm_head=rep(lm_head),
    )


def load_qwen_from_safetensors(
    model: Qwen3,
    path: str,
    *,
    prefix: str = "model.",
    native: bool | None = None,
) -> QwenParams:
    """Load sharded :class:`QwenParams` straight from safetensors weights
    on disk (a file, an HF ``*.index.json``, or a checkpoint directory).

    Tensors stream zero-copy from the mmap'd file(s) through
    :mod:`models.safetensors_io` (native C++ reader when the toolchain is
    available) into their sharded device layouts — host RSS stays at one
    tensor, not one model.
    """
    from .safetensors_io import load_state_dict

    return load_qwen_state_dict(
        model, load_state_dict(path, native=native), prefix=prefix
    )
