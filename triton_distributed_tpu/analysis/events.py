"""The event model of the static protocol verifier.

A distributed Pallas kernel in this framework is, protocol-wise, a
per-rank sequence of a SMALL vocabulary of effects (``lang/primitives``):

- ``notify``       +inc on a (possibly remote) REGULAR semaphore
- ``wait``         blocking -value on a local REGULAR semaphore
- ``remote_copy``  async RDMA: credits the send DMA semaphore locally and
                   the recv DMA semaphore on the target, and writes a
                   destination region of a named symmetric buffer there
- ``local_copy``   async local DMA: credits a local DMA semaphore, writes
                   a local region
- ``wait_recv`` /
  ``wait_send``    blocking consumption of DMA credits, denominated in
                   ELEMENTS of the shaped ref they are constructed from
                   (the static analogue of byte-counting DMA semaphores)
- ``compute``      an emit_pipeline body: reads input regions, writes one
                   output region (recorded via the ``ops.blocks`` stubs)
- ``barrier_all`` / ``barrier_neighbors``  expanded to their constituent
                   signal/wait events against the global barrier semaphore

Record mode (``lang.primitives.active_recorder``) captures these without
touching jax arrays: refs and semaphores are the symbolic stand-ins below,
identified by NAME (the symmetric-memory property: every rank owns an
instance of each named buffer/semaphore, and remote ops address the
peer's same-named instance by device id).
"""

from __future__ import annotations

import dataclasses
from typing import Any


def _as_int(x) -> int:
    """Concretize an index that may be a Python int or an eager jax scalar
    (kernels do ring arithmetic through ``jax.lax.rem``, which returns
    0-d arrays even for concrete operands)."""
    return int(x)


# ---------------------------------------------------------------------------
# regions


@dataclasses.dataclass(frozen=True)
class Region:
    """A rectangular slice of a named buffer: per-dimension [lo, hi) bounds
    (every dimension materialized, unindexed dims span the full extent)."""

    buffer: str
    shape: tuple[int, ...]
    bounds: tuple[tuple[int, int], ...]

    def elements(self) -> int:
        n = 1
        for lo, hi in self.bounds:
            n *= max(hi - lo, 0)
        return n

    def overlaps(self, other: "Region") -> bool:
        if self.buffer != other.buffer:
            return False
        return all(
            a_lo < b_hi and b_lo < a_hi
            for (a_lo, a_hi), (b_lo, b_hi) in zip(self.bounds, other.bounds)
        )

    def label(self) -> str:
        idx = ", ".join(
            f"{lo}:{hi}" if (lo, hi) != (0, s) else ":"
            for (lo, hi), s in zip(self.bounds, self.shape)
        )
        return f"{self.buffer}[{idx}]"


def _interval(idx: Any, size: int) -> tuple[int, int]:
    """One dimension's [lo, hi) from an index expression: an int (or eager
    jax scalar), a ``pl.ds``/``pl.Slice`` (duck-typed on .start/.size), or
    a Python slice."""
    if isinstance(idx, slice):
        lo = 0 if idx.start is None else _as_int(idx.start)
        hi = size if idx.stop is None else _as_int(idx.stop)
        return lo, hi
    start = getattr(idx, "start", None)
    if start is not None and hasattr(idx, "size"):
        lo = _as_int(start)
        return lo, lo + _as_int(idx.size)
    i = _as_int(idx)
    return i, i + 1


# ---------------------------------------------------------------------------
# symbolic refs / semaphores


class _RefIndexer:
    def __init__(self, ref: "FakeRef"):
        self._ref = ref

    def __getitem__(self, idx) -> "FakeRef":
        items = idx if isinstance(idx, tuple) else (idx,)
        r = self._ref
        depth = len(r.ivals)
        if depth + len(items) > len(r.shape):
            raise IndexError(
                f"{r.name}: {depth + len(items)} indices on rank-"
                f"{len(r.shape)} buffer"
            )
        new = r.ivals + tuple(
            _interval(it, r.shape[depth + k]) for k, it in enumerate(items)
        )
        return FakeRef(r.name, r.shape, new)


class FakeRef:
    """Symbolic stand-in for a (HBM/ANY) ref inside a recorded kernel:
    carries only a buffer name, shape, and the interval stack built by
    ``.at[...]`` indexing.  No data, no jax."""

    def __init__(self, name: str, shape: tuple[int, ...],
                 ivals: tuple[tuple[int, int], ...] = ()):
        self.name = name
        self.shape = tuple(int(s) for s in shape)
        self.ivals = ivals

    @property
    def at(self) -> _RefIndexer:
        return _RefIndexer(self)

    def region(self) -> Region:
        bounds = self.ivals + tuple(
            (0, s) for s in self.shape[len(self.ivals):]
        )
        return Region(self.name, self.shape, bounds)

    def __repr__(self):
        return f"FakeRef({self.region().label()})"


class FakeSmem(FakeRef):
    """Scalar-memory ref with concrete example values (the per-peer counts
    an all-to-all kernel reads to size its chunk loops)."""

    def __init__(self, name: str, values):
        super().__init__(name, (len(values),))
        self.values = [int(v) for v in values]

    def __getitem__(self, idx) -> int:
        return self.values[_as_int(idx)]


class _SemIndexer:
    def __init__(self, sem: "FakeSem"):
        self._sem = sem

    def __getitem__(self, idx) -> "FakeSem":
        if self._sem.index is not None:
            raise IndexError(f"{self._sem.label()}: already indexed")
        return FakeSem(self._sem.name, self._sem.kind, _as_int(idx))


class FakeSem:
    """Symbolic semaphore (scalar or 1-D array): identity is (name, index).
    ``kind``: "dma" (credits in elements) or "regular" (credits in counts).
    """

    def __init__(self, name: str, kind: str = "dma",
                 index: int | None = None):
        if kind not in ("dma", "regular"):
            raise ValueError(f"semaphore kind {kind!r}")
        self.name = name
        self.kind = kind
        self.index = index

    @property
    def at(self) -> _SemIndexer:
        return _SemIndexer(self)

    def key(self) -> tuple[str, int | None]:
        return (self.name, self.index)

    def label(self) -> str:
        return self.name if self.index is None else \
            f"{self.name}[{self.index}]"


BARRIER_SEM = "<collective_barrier>"


def sem_label(key: tuple[str, int | None]) -> str:
    name, index = key
    return name if index is None else f"{name}[{index}]"


# ---------------------------------------------------------------------------
# events (one rank's recorded trace is a list of these)


@dataclasses.dataclass(frozen=True)
class NotifyEv:
    sem: tuple[str, int | None]
    target: int            # device id whose semaphore instance is credited
    amount: int
    kind: str = "regular"  # credit unit: "regular" counts


@dataclasses.dataclass(frozen=True)
class WaitEv:
    sem: tuple[str, int | None]
    amount: int
    unit: str              # "count" (regular) | "elem" (DMA)


@dataclasses.dataclass(frozen=True)
class CopyEv:
    src: Region
    dst: Region
    dst_rank: int          # owner of the destination buffer instance
    send_sem: tuple[str, int | None] | None   # credited locally (elements of src)
    recv_sem: tuple[str, int | None]          # credited on dst_rank (elements of dst)


@dataclasses.dataclass(frozen=True)
class ComputeEv:
    kind: str
    reads: tuple[Region, ...]
    write: Region
