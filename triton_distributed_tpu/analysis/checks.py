"""The four protocol checks over composed N-rank traces.

Given one recorded trace per rank (``analysis.record``), the verifier
proves — for THIS rank count and THIS set of example shapes/counts, which
for the shipped kernels covers all control flow since their loops are
static in (rank, n) — four properties the reference framework only ever
probed dynamically with racecheck runs (SURVEY.md §5):

1. **signal balance** — for every (rank, semaphore): the credits produced
   by matching notifies / DMA completions targeting that instance equal
   the credits its waits consume.  A deficit starves a wait (deadlock on
   hardware); a surplus leaks into the NEXT invocation of the kernel and
   satisfies a future wait early — the mismatched-signal-count failure
   class of T3 (arXiv:2401.16677).

2. **deadlock freedom** — the cross-rank wait-for structure admits an
   execution: a round-robin scheduler advances every rank past its waits;
   a stall is reported with the blocked waits and the wait-for cycle.
   Credit monotonicity makes THIS check schedule-insensitive: sends are
   asynchronous (credits appear at issue), each pool is consumed only by
   its owner in program order, so availability at any wait is monotone in
   schedule progress — the simulation is a canonical maximal execution,
   and it stalls iff every interleaving stalls.

   Soundness scope (corrected in ISSUE 15 — the claim used to be stated
   for the whole verifier): monotonicity covers ENABLEDNESS only.  The
   happens-before structure check 3 consumes is built from the FIFO
   credit->wait MATCHING, and when a pool is fed by two CONCURRENT
   producers that matching is schedule-dependent — one schedule's safe
   settle assignment is another schedule's un-ACKed slot reuse.  Exactly
   the protocols shipped since: the persistent megakernel's chained ring
   instances re-arm one shared semaphore set in-kernel, and the
   quantized/hierarchical/handoff families layer sidecars and multi-axis
   credits on shared pools.  For those, run ``analysis.explore`` (DPOR
   over all schedule classes; ``tdt_lint --dpor``, ``TDT_VERIFY_EXPLORE``)
   — the seeded ``fixtures.dpor_fixture_cases`` pass every check below on
   the canonical schedule yet race under reordering, pinning the gap.

3. **write-overlap** — the static analogue of interpret-mode
   ``detect_races``: no two writes (remote DMA landings, local DMA, or
   compute outputs) touch overlapping regions of the same rank's buffer
   without a happens-before edge.  Ordering is tracked with vector
   clocks; crucially a DMA write is NOT ordered by its issuer's program
   order — it is "settled" only when a wait consumes its recv credit, so
   two back-to-back sends into the same remote slot are flagged unless an
   ACK chain (the ring-RS credit protocol) interposes.

4. **collective divergence** — all ranks must run the same collective
   program: same kernel variant (the hazard per-host autotune/calibration
   thresholds can create, ``tools/calibrate.py``) and the same collapsed
   op-kind signature.
"""

from __future__ import annotations

import dataclasses
from collections import deque

from .events import ComputeEv, CopyEv, NotifyEv, WaitEv, sem_label


@dataclasses.dataclass(frozen=True)
class Violation:
    check: str      # signal_balance | deadlock | write_overlap | collective_divergence
    kernel: str
    ranks: int
    message: str

    def __str__(self):
        return f"[{self.check}] {self.kernel} @ ranks={self.ranks}: " \
               f"{self.message}"


class ProtocolViolationError(RuntimeError):
    """Raised by the build-time hook (TDT_VERIFY=1) when a kernel's
    protocol fails static verification."""

    def __init__(self, violations: list[Violation]):
        self.violations = violations
        super().__init__(
            "static protocol verification failed:\n" +
            "\n".join(f"  {v}" for v in violations)
        )


# ---------------------------------------------------------------------------
# vector clocks


def _leq(a: tuple[int, ...], b: tuple[int, ...]) -> bool:
    return all(x <= y for x, y in zip(a, b))


def _join(a: tuple[int, ...], b: tuple[int, ...]) -> tuple[int, ...]:
    return tuple(max(x, y) for x, y in zip(a, b))


@dataclasses.dataclass
class _Credit:
    amount: int
    clock: tuple[int, ...]
    settle_tid: int | None   # transfer settled when this credit is consumed


@dataclasses.dataclass
class _Write:
    owner: int
    region: object
    start: tuple[int, ...]
    tid: int | None          # None: synchronous (compute) write
    writer: int
    what: str


def _static_balance(kernel, n, traces) -> list[Violation]:
    produced: dict[tuple[int, tuple], int] = {}
    consumed: dict[tuple[int, tuple], int] = {}
    for r, events in enumerate(traces):
        for ev in events:
            if isinstance(ev, NotifyEv):
                key = (ev.target, ev.sem)
                produced[key] = produced.get(key, 0) + ev.amount
            elif isinstance(ev, CopyEv):
                if ev.send_sem is not None:
                    key = (r, ev.send_sem)
                    produced[key] = produced.get(key, 0) + \
                        ev.src.elements()
                key = (ev.dst_rank, ev.recv_sem)
                produced[key] = produced.get(key, 0) + ev.dst.elements()
            elif isinstance(ev, WaitEv):
                key = (r, ev.sem)
                consumed[key] = consumed.get(key, 0) + ev.amount
    out = []
    for key in sorted(set(produced) | set(consumed)):
        p, c = produced.get(key, 0), consumed.get(key, 0)
        if p != c:
            rank, sem = key
            surplus = "leaks into the next invocation" if p > c else \
                "starves the wait (deadlock on hardware)"
            out.append(Violation(
                "signal_balance", kernel, n,
                f"semaphore {sem_label(sem)} on rank {rank}: signals "
                f"produced {p} != waited {c} — the surplus/deficit of "
                f"{abs(p - c)} {surplus}",
            ))
    return out


def _simulate(kernel, n, traces):
    """Run the canonical maximal execution.  Returns
    (violations, writes, settle) — violations non-empty iff deadlocked."""
    credits: dict[tuple[int, tuple], deque[_Credit]] = {}
    clocks = [tuple(0 for _ in range(n)) for _ in range(n)]
    pcs = [0] * n
    writes: list[_Write] = []
    settle: dict[int, tuple[int, ...]] = {}
    next_tid = 0

    def bump(r):
        c = list(clocks[r])
        c[r] += 1
        clocks[r] = tuple(c)

    def add_credit(rank, sem, amount, clock, tid=None):
        credits.setdefault((rank, sem), deque()).append(
            _Credit(amount, clock, tid)
        )

    def available(rank, sem) -> int:
        return sum(c.amount for c in credits.get((rank, sem), ()))

    def step(r) -> bool:
        """Try to execute rank r's next event; True on progress."""
        nonlocal next_tid
        if pcs[r] >= len(traces[r]):
            return False
        ev = traces[r][pcs[r]]
        if isinstance(ev, WaitEv):
            if available(r, ev.sem) < ev.amount:
                return False
            need = ev.amount
            q = credits.setdefault((r, ev.sem), deque())
            while need > 0:
                c = q[0]
                take = min(need, c.amount)
                c.amount -= take
                need -= take
                clocks[r] = _join(clocks[r], c.clock)
                if c.settle_tid is not None:
                    # the consumer has OBSERVED this transfer's landing:
                    # anything causally after this wait is ordered after
                    # the transfer's write
                    prev = settle.get(c.settle_tid)
                    settle[c.settle_tid] = clocks[r] if prev is None \
                        else _join(prev, clocks[r])
                if c.amount == 0:
                    q.popleft()
        elif isinstance(ev, NotifyEv):
            add_credit(ev.target, ev.sem, ev.amount, clocks[r])
        elif isinstance(ev, CopyEv):
            tid = next_tid
            next_tid += 1
            if ev.send_sem is not None:
                add_credit(r, ev.send_sem, ev.src.elements(), clocks[r])
            add_credit(ev.dst_rank, ev.recv_sem, ev.dst.elements(),
                       clocks[r], tid=tid)
            writes.append(_Write(
                ev.dst_rank, ev.dst, clocks[r], tid, r,
                "remote_copy" if ev.dst_rank != r else "local_copy",
            ))
        elif isinstance(ev, ComputeEv):
            writes.append(_Write(r, ev.write, clocks[r], None, r,
                                 f"compute:{ev.kind}"))
        pcs[r] += 1
        bump(r)
        return True

    progress = True
    while progress:
        progress = False
        for r in range(n):
            while step(r):
                progress = True

    if all(pcs[r] >= len(traces[r]) for r in range(n)):
        return [], writes, settle, clocks

    # deadlock: describe each blocked rank and find a wait-for cycle
    blocked = {}
    for r in range(n):
        if pcs[r] < len(traces[r]):
            ev = traces[r][pcs[r]]
            blocked[r] = ev
    def producers_of(rank, sem):
        """Blocked ranks whose REMAINING events could credit (rank, sem)."""
        out = set()
        for p, evp in blocked.items():
            for ev in traces[p][pcs[p]:]:
                if isinstance(ev, NotifyEv) and ev.target == rank \
                        and ev.sem == sem:
                    out.add(p)
                elif isinstance(ev, CopyEv) and (
                    (ev.dst_rank == rank and ev.recv_sem == sem)
                    or (p == rank and ev.send_sem == sem)
                ):
                    out.add(p)
        return out

    lines = []
    edges = {}
    for r, ev in sorted(blocked.items()):
        if isinstance(ev, WaitEv):
            lines.append(
                f"rank {r} blocked at event #{pcs[r]} "
                f"wait({sem_label(ev.sem)}, need {ev.amount}, "
                f"have {available(r, ev.sem)})"
            )
            edges[r] = producers_of(r, ev.sem)
        else:  # pragma: no cover - only waits block
            lines.append(f"rank {r} stuck at event #{pcs[r]}: {ev}")
            edges[r] = set()
    cycle = _find_cycle(edges)
    if cycle:
        lines.append(
            "wait-for cycle: " + " -> ".join(f"rank {r}" for r in cycle)
        )
    return (
        [Violation("deadlock", kernel, n, "; ".join(lines))],
        writes, settle, clocks,
    )


def _find_cycle(edges: dict[int, set[int]]) -> list[int] | None:
    """A wait-for cycle among blocked ranks (greedy lowest-successor walk;
    advisory — the deadlock itself is already established)."""
    for start in sorted(edges):
        path, node = [start], start
        for _ in range(len(edges) + 1):
            nxts = sorted(edges.get(node, ()))
            if not nxts:
                break
            node = nxts[0]
            if node in path:
                return path[path.index(node):] + [node]
            path.append(node)
    return None


def _write_overlap(kernel, n, writes: list[_Write],
                   settle: dict[int, tuple[int, ...]]) -> list[Violation]:
    def settled(w: _Write) -> tuple[int, ...] | None:
        if w.tid is None:
            # synchronous write: complete at its start clock (program order
            # on its own rank orders it against later same-rank events)
            return w.start
        return settle.get(w.tid)

    out = []
    by_owner: dict[tuple[int, str], list[_Write]] = {}
    for w in writes:
        by_owner.setdefault((w.owner, w.region.buffer), []).append(w)
    for (owner, _buf), ws in sorted(by_owner.items()):
        for i in range(len(ws)):
            for j in range(i + 1, len(ws)):
                a, b = ws[i], ws[j]
                if not a.region.overlaps(b.region):
                    continue
                sa, sb = settled(a), settled(b)
                ordered = (sa is not None and _leq(sa, b.start)) or \
                          (sb is not None and _leq(sb, a.start))
                if not ordered:
                    out.append(Violation(
                        "write_overlap", kernel, n,
                        f"unordered writes to rank {owner}'s "
                        f"{a.region.label()} ({a.what} from rank "
                        f"{a.writer}) and {b.region.label()} ({b.what} "
                        f"from rank {b.writer}) — no happens-before edge "
                        f"orders the landings (the static analogue of an "
                        f"interpret-mode race report)",
                    ))
    return out


def _divergence(kernel, n, sigs, variants) -> list[Violation]:
    out = []
    if len(set(variants)) > 1:
        out.append(Violation(
            "collective_divergence", kernel, n,
            "ranks selected different collective variants: " + ", ".join(
                f"rank {r}={v}" for r, v in enumerate(variants)
            ) + " — per-host thresholds (tools/calibrate.py) must resolve "
            "identically on every process",
        ))
        return out
    base = sigs[0]
    for r, s in enumerate(sigs[1:], start=1):
        if s != base:
            k = next(
                (i for i, (x, y) in enumerate(zip(base, s)) if x != y),
                min(len(base), len(s)),
            )
            out.append(Violation(
                "collective_divergence", kernel, n,
                f"rank 0 and rank {r} issue different collective-op "
                f"sequences (first divergence at step {k}: "
                f"{base[k] if k < len(base) else '<end>'} vs "
                f"{s[k] if k < len(s) else '<end>'})",
            ))
            break
    return out


def analyze(kernel: str, n: int, traces, sigs, variants) -> list[Violation]:
    """Run all four checks over per-rank (events, collapsed signature,
    variant label) and return every violation found."""
    out = []
    out.extend(_divergence(kernel, n, sigs, variants))
    out.extend(_static_balance(kernel, n, traces))
    dead, writes, settle, _clocks = _simulate(kernel, n, traces)
    out.extend(dead)
    if not dead:
        out.extend(_write_overlap(kernel, n, writes, settle))
    return out


CHECKS = ("collective_divergence", "signal_balance", "deadlock",
          "write_overlap")
