"""Registry of verifiable kernel builders: every collective kernel body in
``comm/`` and ``ops/``, bound to symbolic refs/semaphores shaped exactly
like the real builders' ``scratch_shapes``, across rank counts.

Each :class:`KernelCase` knows how to run ONE rank of one kernel variant
under record mode; ``verify_case`` records all N ranks, composes the
traces, and runs the four checks (``analysis.checks``).  Example dims are
tiny (protocol structure is invariant in them — the kernels' loops are
static in ``(rank, n)``; the all-to-all chunk counts are data-dependent
and get a deliberately asymmetric example matrix).

``maybe_verify_build`` is the opt-in build-time hook (``TDT_VERIFY=1``)
the op builders call before constructing their pallas_call: the family is
verified once per (family, n) per process and a violation raises
:class:`~analysis.checks.ProtocolViolationError` instead of building a
kernel with a broken protocol.
"""

from __future__ import annotations

import dataclasses
import threading
from typing import Callable

from .checks import ProtocolViolationError, Violation, analyze
from .events import FakeRef, FakeSem, FakeSmem
from .record import record_kernel

DEFAULT_RANKS = (2, 4, 8)

# families the CLI and the build hook know; collective_id families of the
# a2a builders map onto the one shared kernel body
FAMILIES = (
    "allgather", "reduce_scatter", "allreduce", "all_to_all",
    "ag_gemm", "gemm_rs", "gemm_ar", "fused_mlp_ar",
    "quantized_wire", "hierarchical", "persistent_decode",
)

_FAMILY_ALIASES = {"ep_dispatch": "all_to_all", "ep_combine": "all_to_all",
                   "sched_ep_dispatch": "all_to_all",
                   "sched_ep_combine": "all_to_all"}

# slice layouts the hierarchical family's ACCEPTANCE matrix pins
# (ISSUE 10): (num_slices, chips_per_slice).  The DEFAULT_RANKS sweep
# covers all three — n=4 verifies 2x2 and n=8 verifies 2x4 AND 4x2.
HIER_LAYOUTS = ((2, 2), (2, 4), (4, 2))


def hier_layouts_for(n: int) -> list[tuple[int, int]]:
    """EVERY (n_out >= 2, n_in >= 2) factorization of ``n`` — not just
    the pinned acceptance layouts: the build-time verify gate
    (``verify_protocol("hierarchical", n)``) must exercise whatever rank
    count a live 2D mesh presents (a 2x8 mesh verifies at (2,8), (4,4),
    (8,2)), never memoize an empty run as verified.  Rank counts with no
    such factorization (primes; or n_in==1 meshes, where the inner ring
    is degenerate and the DCN hop is a bare XLA collective) have no
    two-level protocol to check."""
    out = []
    for o in range(2, n // 2 + 1):
        if n % o == 0 and n // o >= 2:
            out.append((o, n // o))
    return out


@dataclasses.dataclass(frozen=True)
class KernelCase:
    """One verifiable (kernel variant, rank count): ``make(rank)`` returns
    ``(variant_label, thunk)`` where the thunk runs the kernel body for
    that rank with fresh symbolic args.  ``axes`` selects a multi-axis
    harness mesh (outermost first; the hierarchical two-level cases run on
    ``(("dcn", n_out), ("tp", n_in))``) with ranks enumerated row-major so
    device id == rank index; None = the single-axis ``(("tp", n),)``."""

    name: str
    family: str
    n: int
    make: Callable[[int], tuple[str, Callable[[], None]]]
    axes: tuple[tuple[str, int], ...] | None = None


def _team(n: int):
    from ..lang.primitives import Team

    return Team((("tp", n),), "tp")


# ---------------------------------------------------------------------------
# per-family case builders (arg layouts mirror the real scratch_shapes)


def _ag_cases(n: int) -> list[KernelCase]:
    from ..comm.allgather import _KERNELS as _AG_KERNELS

    m, r = 4, 8
    team = _team(n)

    def make(kern, two_send):
        def _make(rank, kern=kern, two_send=two_send):
            x = FakeRef("x", (m, r))
            out = FakeRef("out", (n * m, r))
            local_sem = FakeSem("local_sem")
            send = FakeSem("send_sems") if two_send else FakeSem("send_sem")
            recv = FakeSem("recv_sems")
            return "default", lambda: kern(
                team, m, x, out, local_sem, send, recv
            )
        return _make

    return [
        KernelCase(f"allgather/{meth.value}", "allgather", n,
                   make(kern, two_send))
        for meth, (kern, two_send) in _AG_KERNELS.items()
    ]


def _rs_cases(n: int) -> list[KernelCase]:
    from ..comm.reduce_scatter import ReduceScatterConfig, _rs_ring_kernel

    m_loc, r = 4, 8
    team = _team(n)
    cfg = ReduceScatterConfig()

    def make(rank):
        x = FakeRef("x", (n * m_loc, r))
        out = FakeRef("out", (m_loc, r))
        recv_buf = FakeRef("recv_buf", (2, m_loc, r))
        send_buf = FakeRef("send_buf", (2, m_loc, r))
        send_sems = FakeSem("send_sems")
        recv_sems = FakeSem("recv_sems")
        ack_sems = FakeSem("ack_sems", kind="regular")
        return "ring", lambda: _rs_ring_kernel(
            team, m_loc, r, cfg, x, out, recv_buf, send_buf,
            send_sems, recv_sems, ack_sems,
        )

    return [KernelCase("reduce_scatter/ring", "reduce_scatter", n, make)]


def _ar_cases(n: int) -> list[KernelCase]:
    import jax.numpy as jnp

    from ..comm.allreduce import (
        AllReduceConfig,
        _ar_one_shot_kernel,
        _ar_two_shot_kernel,
    )

    r = 8
    team = _team(n)
    cfg = AllReduceConfig()

    def make_one(rank):
        m = 4
        x = FakeRef("x", (m, r))
        out = FakeRef("out", (m, r))
        slots = FakeRef("slots", (n, m, r))
        return "one_shot", lambda: _ar_one_shot_kernel(
            team, m, r, cfg, jnp.float32, x, out, slots,
            FakeSem("local_sem"), FakeSem("send_sem"), FakeSem("recv_sems"),
        )

    def make_two(rank):
        m_chunk = 2
        x = FakeRef("x", (n * m_chunk, r))
        out = FakeRef("out", (n * m_chunk, r))
        return "two_shot", lambda: _ar_two_shot_kernel(
            team, m_chunk, r, cfg, jnp.float32, x, out,
            FakeRef("recv_buf", (2, m_chunk, r)),
            FakeRef("send_buf", (2, m_chunk, r)),
            FakeSem("rs_send_sems"), FakeSem("rs_recv_sems"),
            FakeSem("ack_sems", kind="regular"),
            FakeSem("ag_send_sem"), FakeSem("ag_recv_sems"),
        )

    return [
        KernelCase("allreduce/one_shot", "allreduce", n, make_one),
        KernelCase("allreduce/two_shot", "allreduce", n, make_two),
    ]


def _a2a_counts(n: int) -> list[list[int]]:
    """Deliberately asymmetric example split matrix: counts[src][dst] rows
    from src to dst (includes the self-zone copy the kernel issues)."""
    return [[(src + 2 * dst) % 3 + 1 for dst in range(n)] for src in range(n)]


def _a2a_cases(n: int) -> list[KernelCase]:
    from ..comm.all_to_all import _a2a_push_kernel

    chunk, h, z = 2, 4, 8
    team = _team(n)
    counts = _a2a_counts(n)

    def _offsets(row):
        offs, acc = [], 0
        for c in row:
            offs.append(acc)
            acc += c
        return offs

    def make_dispatch(rank):
        row = counts[rank]
        expected = [counts[p][rank] for p in range(n)]
        x = FakeRef("x", (4 * n + chunk, h))
        out = FakeRef("zones", (n, z, h))
        return "push", lambda: _a2a_push_kernel(
            team, chunk, z, h,
            FakeSmem("counts", row), FakeSmem("offs", _offsets(row)),
            FakeSmem("expected", expected), x, out,
            FakeSem("send_sem"), FakeSem("recv_sems"),
        )

    def make_combine(rank):
        # roles reversed (comm.all_to_all._build_combine): send each zone
        # back to its source; zone p's rows start at p*z in the flattened y
        back = [counts[p][rank] for p in range(n)]     # rows back to p
        expected = counts[rank]                        # rows p returns me
        y = FakeRef("y", (n * z, h))
        out = FakeRef("zones", (n, z, h))
        return "push", lambda: _a2a_push_kernel(
            team, chunk, z, h,
            FakeSmem("counts", back),
            FakeSmem("offs", [p * z for p in range(n)]),
            FakeSmem("expected", expected), y, out,
            FakeSem("send_sem"), FakeSem("recv_sems"),
        )

    def make_scheduled(rank):
        # the topology-scheduled emission order (ISSUE 10): same push
        # protocol, peer offsets emitted farthest-first (the FAST-style
        # order the hierarchical A2A launches ICI chunks in); the
        # verifier proves reordering the static loop preserves the
        # protocol at every rank count
        from ..comm.hierarchical import ici_schedule

        row = counts[rank]
        expected = [counts[p][rank] for p in range(n)]
        x = FakeRef("x", (4 * n + chunk, h))
        out = FakeRef("zones", (n, z, h))
        return "scheduled", lambda: _a2a_push_kernel(
            team, chunk, z, h,
            FakeSmem("counts", row), FakeSmem("offs", _offsets(row)),
            FakeSmem("expected", expected), x, out,
            FakeSem("send_sem"), FakeSem("recv_sems"),
            schedule=ici_schedule(n),
        )

    return [
        KernelCase("all_to_all/dispatch", "all_to_all", n, make_dispatch),
        KernelCase("all_to_all/combine", "all_to_all", n, make_combine),
        KernelCase("all_to_all/scheduled", "all_to_all", n, make_scheduled),
    ]


def _ag_gemm_cases(n: int) -> list[KernelCase]:
    import jax.numpy as jnp

    from ..ops.ag_gemm import (
        AgGemmConfig,
        _ag_gemm_bidir_kernel,
        _ag_gemm_kernel,
    )

    m_loc, k, n_loc = 4, 8, 4
    team = _team(n)
    cfg = AgGemmConfig()

    def make(kern, label, two_send):
        def _make(rank, kern=kern, label=label, two_send=two_send):
            a = FakeRef("a", (m_loc, k))
            b = FakeRef("b", (k, n_loc))
            ag_ref = FakeRef("ag", (n * m_loc, k))
            c = FakeRef("c", (n * m_loc, n_loc))
            acc = FakeRef("acc", (1, 1))
            send = FakeSem("send_sems") if two_send else FakeSem("send_sem")
            return label, lambda: kern(
                team, m_loc, k, n_loc, cfg, jnp.float32, a, b, ag_ref, c,
                FakeSem("local_sem"), send, FakeSem("recv_sems"), acc,
            )
        return _make

    return [
        KernelCase("ag_gemm/unidir", "ag_gemm", n,
                   make(_ag_gemm_kernel, "unidir", False)),
        KernelCase("ag_gemm/bidir", "ag_gemm", n,
                   make(_ag_gemm_bidir_kernel, "bidir", True)),
    ]


def _gemm_rs_cases(n: int) -> list[KernelCase]:
    import jax.numpy as jnp

    from ..ops.gemm_rs import GemmRsConfig, _gemm_rs_kernel

    m_loc, k_loc, n_dim = 4, 8, 4
    team = _team(n)
    cfg = GemmRsConfig()

    def make(rank):
        a = FakeRef("a", (n * m_loc, k_loc))
        b = FakeRef("b", (k_loc, n_dim))
        out = FakeRef("out", (m_loc, n_dim))
        return "ring", lambda: _gemm_rs_kernel(
            team, m_loc, k_loc, n_dim, cfg, jnp.float32, a, b, out,
            FakeRef("mm_buf", (2, m_loc, n_dim)),
            FakeRef("recv_buf", (2, m_loc, n_dim)),
            FakeRef("send_buf", (2, m_loc, n_dim)),
            FakeSem("send_sems"), FakeSem("recv_sems"),
            FakeSem("ack_sems", kind="regular"), FakeRef("acc", (1, 1)),
        )

    return [KernelCase("gemm_rs/ring", "gemm_rs", n, make)]


def _gemm_ar_cases(n: int) -> list[KernelCase]:
    import jax.numpy as jnp

    from ..ops.gemm_ar import GemmArConfig, _gemm_ar_kernel

    m_loc, k_loc, n_dim = 4, 8, 4
    team = _team(n)
    cfg = GemmArConfig()

    def make(rank):
        a = FakeRef("a", (n * m_loc, k_loc))
        b = FakeRef("b", (k_loc, n_dim))
        out = FakeRef("out", (n * m_loc, n_dim))
        return "ring", lambda: _gemm_ar_kernel(
            team, m_loc, k_loc, n_dim, cfg, jnp.float32, a, b, out,
            FakeRef("mm_buf", (2, m_loc, n_dim)),
            FakeRef("recv_buf", (2, m_loc, n_dim)),
            FakeRef("send_buf", (2, m_loc, n_dim)),
            FakeSem("send_sems"), FakeSem("recv_sems"),
            FakeSem("ack_sems", kind="regular"),
            FakeSem("ag_send_sem"), FakeSem("ag_recv_sems"),
            FakeRef("acc", (1, 1)),
        )

    return [KernelCase("gemm_ar/ring", "gemm_ar", n, make)]


def _fused_mlp_ar_cases(n: int) -> list[KernelCase]:
    import jax.numpy as jnp

    from ..ops.fused_decode import FusedMlpConfig, _fused_mlp_ar_kernel

    b, k_in, k_loc = 2, 8, 8
    n_dim = 4 * n            # cn = 4 per chunk
    team = _team(n)
    cfg = FusedMlpConfig()

    def make_common(rank, swiglu: bool):
        args = [FakeRef("x", (b, k_in))]
        if swiglu:
            args.append(FakeRef("gate_up", (k_in, 2 * k_loc)))
        args.append(FakeRef("w_dn", (k_loc, n_dim)))
        args.append(FakeRef("out", (n * b, n_dim // n)))
        if swiglu:
            args += [FakeRef("g_buf", (b, k_loc)),
                     FakeRef("u_buf", (b, k_loc)),
                     FakeRef("act_buf", (b, k_loc))]
        cn = n_dim // n
        args += [
            FakeRef("mm_buf", (2, b, cn)),
            FakeRef("recv_buf", (2, b, cn)),
            FakeRef("send_buf", (2, b, cn)),
            FakeSem("send_sems"), FakeSem("recv_sems"),
            FakeSem("ack_sems", kind="regular"),
            FakeSem("ag_send_sem"), FakeSem("ag_recv_sems"),
        ]
        if swiglu:
            args.append(FakeRef("acc_up", (1, 1)))
        args.append(FakeRef("acc", (1, 1)))
        label = "swiglu" if swiglu else "linear"
        return label, lambda: _fused_mlp_ar_kernel(
            team, b, k_in, k_loc, n_dim, cfg, swiglu, jnp.float32, *args,
        )

    return [
        KernelCase("fused_mlp_ar/swiglu", "fused_mlp_ar", n,
                   lambda rank: make_common(rank, True)),
        KernelCase("fused_mlp_ar/linear", "fused_mlp_ar", n,
                   lambda rank: make_common(rank, False)),
    ]


def _persistent_cases(n: int) -> list[KernelCase]:
    """The persistent multi-layer decode loop (ISSUE 13,
    ``ops.persistent_decode``): the WHOLE chained body — L layers, each
    an attention cell plus TWO column-ring AllReduce instances on one
    shared semaphore/buffer set, the inter-instance dependency carried
    by deferred ACK credits ("semaphores re-armed in-kernel") — recorded
    as one kernel.  Two layers suffice to exercise every chaining state:
    the unarmed first instance, armed same-layer and armed cross-layer
    reuse, and the single exit drain."""
    import jax.numpy as jnp

    from ..ops.persistent_decode import (
        PersistentDecodeConfig,
        _persistent_decode_kernel,
    )

    layers, b, k_dim, hk, g, d = 2, 2, 8, 1, 1, 4
    ps, mp, pool_pages, f_loc = 4, 2, 4, 8
    h_loc = hk * g
    qkv_cols = (h_loc + 2 * hk) * d
    pool_rows = layers * pool_pages * hk
    team = _team(n)
    cfg = PersistentDecodeConfig()

    def make(rank):
        cn = k_dim // n
        args = [
            FakeRef("table", (b * mp,)),
            FakeRef("lens", (b,)),
            FakeRef("x", (b, k_dim)),
            FakeRef("ln1_s", (layers, k_dim)),
            FakeRef("wqkv_s", (layers, k_dim, qkv_cols)),
            FakeRef("qn_s", (layers, d)),
            FakeRef("kn_s", (layers, d)),
            FakeRef("wo_s", (layers, h_loc * d, k_dim)),
            FakeRef("ln2_s", (layers, k_dim)),
            FakeRef("gate_up_s", (layers, k_dim, 2 * f_loc)),
            FakeRef("down_s", (layers, f_loc, k_dim)),
            FakeRef("pool_k", (pool_rows, ps, d)),
            FakeRef("pool_v", (pool_rows, ps, d)),
            FakeRef("x_out", (b, k_dim)),
            FakeRef("pool_k", (pool_rows, ps, d)),
            FakeRef("pool_v", (pool_rows, ps, d)),
            FakeRef("xa", (b, k_dim)),
            FakeRef("xb", (b, k_dim)),
            FakeRef("h_buf", (b, k_dim)),
            FakeRef("qkv_buf", (b, qkv_cols)),
            FakeRef("attn_vm", (b, h_loc * d)),
            FakeRef("attn_buf", (b, h_loc * d)),
            FakeRef("g_buf", (b, f_loc)),
            FakeRef("u_buf", (b, f_loc)),
            FakeRef("act_buf", (b, f_loc)),
            FakeRef("red_buf", (n * b, cn)),
            FakeRef("mm_buf", (2, b, cn)),
            FakeRef("recv_buf", (2, b, cn)),
            FakeRef("send_buf", (2, b, cn)),
            FakeRef("qrow", (1, qkv_cols)),
            FakeRef("qn_vm", (1, d)),
            FakeRef("kn_vm", (1, d)),
            FakeRef("ktok", (1, d)),
            FakeRef("vtok", (1, d)),
            FakeRef("kbuf", (2, ps, d)),
            FakeRef("vbuf", (2, ps, d)),
            FakeSem("stage_sems"),
            FakeSem("pg_sems"),
            FakeSem("tok_sems"),
            FakeSem("send_sems"),
            FakeSem("recv_sems"),
            FakeSem("ack_sems", kind="regular"),
            FakeSem("ag_send_sem"),
            FakeSem("ag_recv_sems"),
            FakeRef("acc_qkv", (1, 1)),
            FakeRef("acc_ar", (1, 1)),
            FakeRef("acc_up", (1, 1)),
        ]
        return "chain", lambda: _persistent_decode_kernel(
            team, layers, b, k_dim, hk, g, d, ps, mp, pool_pages, f_loc,
            10_000.0, 1e-6, 1e-6, d ** -0.5, 0.0, cfg, jnp.float32,
            *args,
        )

    return [KernelCase("persistent_decode/chain", "persistent_decode", n,
                       make)]


def _quant_cases(n: int) -> list[KernelCase]:
    """The quantized collective variants (ISSUE 9) at their WIRE shapes:
    a quantized payload rides the same kernel protocols on the packed u8
    message (H payload bytes + the 128-lane scale sidecar in ONE chunk),
    so the verifiable object is each protocol at the packed geometry —
    the scale sidecar travelling with its payload rows is exactly what
    these shapes encode.

    - ``quant_allgather/*``: the u8 AG the quantized gather ships
      (``comm.quantized.quantized_all_gather`` routes the packed array
      through the real Pallas AG entries).
    - ``quant_exchange/oneshot``: the one-shot packed chunk exchange of
      the quantized RS/AR (every rank sends chunk j to rank j) —
      expressed on the A2A push kernel body with the equal-split count
      matrix that exchange induces.
    """
    from ..comm.allgather import _KERNELS as _AG_KERNELS, AllGatherMethod
    from ..comm.all_to_all import _a2a_push_kernel
    from ..lang.quant import SIDECAR

    h = 8
    w = h + SIDECAR                 # packed row width (u8 bytes)
    m = 4                           # rows per shard/chunk
    team = _team(n)

    def make_ag(kern, two_send):
        def _make(rank, kern=kern, two_send=two_send):
            x = FakeRef("x_u8", (m, w))
            out = FakeRef("out_u8", (n * m, w))
            local_sem = FakeSem("local_sem")
            send = FakeSem("send_sems") if two_send else FakeSem("send_sem")
            recv = FakeSem("recv_sems")
            return "packed_u8", lambda: kern(
                team, m, x, out, local_sem, send, recv
            )
        return _make

    cases = [
        KernelCase(f"quant_allgather/{meth.value}", "quantized_wire", n,
                   make_ag(kern, two_send))
        for meth, (kern, two_send) in _AG_KERNELS.items()
        if meth in (AllGatherMethod.PUSH_1SHOT, AllGatherMethod.RING_BIDIR)
    ]

    chunk, z = 2, m + 2             # zone rows (chunk multiple + slack)

    def make_exchange(rank):
        # equal splits: m rows to every peer (the one-shot RS exchange)
        counts = [m] * n
        offs = [p * m for p in range(n)]
        expected = [m] * n
        x = FakeRef("packed_chunks", (n * m + chunk, w))
        out = FakeRef("zones_u8", (n, z, w))
        return "oneshot", lambda: _a2a_push_kernel(
            team, chunk, z, w,
            FakeSmem("counts", counts), FakeSmem("offs", offs),
            FakeSmem("expected", expected), x, out,
            FakeSem("send_sem"), FakeSem("recv_sems"),
        )

    cases.append(KernelCase("quant_exchange/oneshot", "quantized_wire", n,
                            make_exchange))
    return cases


def _hier_cases(n: int) -> list[KernelCase]:
    """The two-level (ICI x DCN) collective protocols (ISSUE 10) at every
    slice layout whose total rank count is ``n`` (``hier_layouts_for`` —
    the {2x2, 2x4, 4x2} acceptance matrix).  Each case composes the REAL
    shipped inner kernel body (per-slice Pallas ring, addressed through a
    two-axis ``Team`` so peer ids resolve within the slice) with the
    record-mode protocol model of the DCN hop
    (``comm.hierarchical.dcn_broadcast_model`` / ``dcn_reduce_model`` —
    in production that hop is an XLA collective, SURVEY.md section 7; the
    model pins the credit/ordering contract the composition relies on,
    which is what the dropped-inter-slice-credit fault class injects
    against)."""
    import jax.numpy as jnp

    from ..lang.primitives import Team

    cases: list[KernelCase] = []
    m, r = 4, 8
    for n_out, n_in in hier_layouts_for(n):
        axes = (("dcn", n_out), ("tp", n_in))
        team = Team(axes, "tp")
        label = f"{n_out}x{n_in}"

        def make_ag(rank, team=team, n_out=n_out, n_in=n_in):
            from ..comm.allgather import _ag_ring_kernel
            from ..comm.hierarchical import dcn_broadcast_model

            x = FakeRef("x", (m, r))
            inner = FakeRef("inner_gather", (n_in * m, r))
            zones = FakeRef("dcn_zones", (n_out, n_in * m, r))

            def body():
                _ag_ring_kernel(team, m, x, inner, FakeSem("local_sem"),
                                FakeSem("send_sem"), FakeSem("recv_sems"))
                dcn_broadcast_model(n_out, n_in, inner, zones,
                                    FakeSem("dcn_send_sem"),
                                    FakeSem("dcn_recv_sems"))
            return "ring+dcn_bcast", body

        def make_rs(rank, team=team, n_out=n_out, n_in=n_in):
            from ..comm.hierarchical import dcn_reduce_model
            from ..comm.reduce_scatter import (
                ReduceScatterConfig, _rs_ring_kernel,
            )

            cfg = ReduceScatterConfig()
            x = FakeRef("x", (n_in * m, r))
            part = FakeRef("part", (m, r))
            zones = FakeRef("dcn_zones", (n_out, m, r))
            out = FakeRef("out", (m, r))

            def body():
                _rs_ring_kernel(team, m, r, cfg, x, part,
                                FakeRef("recv_buf", (2, m, r)),
                                FakeRef("send_buf", (2, m, r)),
                                FakeSem("send_sems"), FakeSem("recv_sems"),
                                FakeSem("ack_sems", kind="regular"))
                dcn_reduce_model(n_out, n_in, part, zones, out,
                                 FakeSem("dcn_send_sem"),
                                 FakeSem("dcn_recv_sems"),
                                 jnp.float32, m, r)
            return "ring+dcn_reduce", body

        def make_ar(rank, team=team, n_out=n_out, n_in=n_in):
            from ..comm.allgather import _ag_ring_kernel
            from ..comm.hierarchical import dcn_reduce_model
            from ..comm.reduce_scatter import (
                ReduceScatterConfig, _rs_ring_kernel,
            )

            cfg = ReduceScatterConfig()
            x = FakeRef("x", (n_in * m, r))
            part = FakeRef("part", (m, r))
            zones = FakeRef("dcn_zones", (n_out, m, r))
            red = FakeRef("reduced", (m, r))
            out = FakeRef("out", (n_in * m, r))

            def body():
                # RS ring on ICI, reduce across DCN, AG ring on ICI — the
                # RS∘AG composition whose DCN hop carries 1/n_in of the
                # payload (the bench.py hier claims-gate bound)
                _rs_ring_kernel(team, m, r, cfg, x, part,
                                FakeRef("rs_recv_buf", (2, m, r)),
                                FakeRef("rs_send_buf", (2, m, r)),
                                FakeSem("rs_send_sems"),
                                FakeSem("rs_recv_sems"),
                                FakeSem("rs_ack_sems", kind="regular"))
                dcn_reduce_model(n_out, n_in, part, zones, red,
                                 FakeSem("dcn_send_sem"),
                                 FakeSem("dcn_recv_sems"),
                                 jnp.float32, m, r)
                _ag_ring_kernel(team, m, red, out, FakeSem("ag_local_sem"),
                                FakeSem("ag_send_sem"),
                                FakeSem("ag_recv_sems"))
            return "rs+dcn_reduce+ag", body

        def make_a2a(rank, team=team, n_out=n_out, n_in=n_in):
            from ..comm.all_to_all import _a2a_push_kernel
            from ..comm.hierarchical import dcn_broadcast_model, ici_schedule

            chunk, h, z = 2, 4, 8
            i = rank % n_in
            counts = _a2a_counts(n_in)
            row = counts[i]
            expected = [counts[p][i] for p in range(n_in)]
            offs, acc = [], 0
            for c in row:
                offs.append(acc)
                acc += c
            tokens = FakeRef("tokens", (n_out, 4 * n_in + chunk, h))
            zones = FakeRef("dcn_zones", (n_out, 4 * n_in + chunk, h))
            x = FakeRef("merged", (4 * n_in + chunk, h))
            out = FakeRef("ici_zones", (n_in, z, h))

            def body():
                # phase 1 FIRST: the DCN-bound token blocks launch onto
                # the slow wire, then the ICI kernel pipelines underneath
                # with the farthest-first schedule (FAST, arXiv:2505.09764)
                dcn_broadcast_model(n_out, n_in, tokens.at[0], zones,
                                    FakeSem("dcn_send_sem"),
                                    FakeSem("dcn_recv_sems"))
                _a2a_push_kernel(
                    team, chunk, z, h,
                    FakeSmem("counts", row), FakeSmem("offs", offs),
                    FakeSmem("expected", expected), x, out,
                    FakeSem("send_sem"), FakeSem("recv_sems"),
                    schedule=ici_schedule(n_in),
                )
            return "dcn+sched_push", body

        cases += [
            KernelCase(f"hier_allgather/{label}", "hierarchical", n,
                       make_ag, axes=axes),
            KernelCase(f"hier_reduce_scatter/{label}", "hierarchical", n,
                       make_rs, axes=axes),
            KernelCase(f"hier_allreduce/{label}", "hierarchical", n,
                       make_ar, axes=axes),
            KernelCase(f"hier_a2a/{label}", "hierarchical", n,
                       make_a2a, axes=axes),
        ]
    return cases


_FAMILY_CASES = {
    "allgather": _ag_cases,
    "reduce_scatter": _rs_cases,
    "allreduce": _ar_cases,
    "all_to_all": _a2a_cases,
    "ag_gemm": _ag_gemm_cases,
    "gemm_rs": _gemm_rs_cases,
    "gemm_ar": _gemm_ar_cases,
    "fused_mlp_ar": _fused_mlp_ar_cases,
    "quantized_wire": _quant_cases,
    "hierarchical": _hier_cases,
    "persistent_decode": _persistent_cases,
}


def cases_for(family: str, n: int) -> list[KernelCase]:
    family = _FAMILY_ALIASES.get(family, family)
    try:
        builder = _FAMILY_CASES[family]
    except KeyError:
        raise KeyError(
            f"unknown kernel family {family!r}; register it in "
            "analysis.registry._FAMILY_CASES"
        ) from None
    return builder(n)


def all_cases(ranks=DEFAULT_RANKS) -> list[KernelCase]:
    out = []
    for n in ranks:
        for family in FAMILIES:
            out.extend(cases_for(family, n))
    return out


# ---------------------------------------------------------------------------
# verification entry points


def record_case(case: KernelCase) -> tuple[list, list, list]:
    """Record all N ranks of one case ONCE: (traces, signatures,
    variants).  Shareable between the canonical checks and the DPOR
    explorer — a build-time verification with the explore knob armed
    must not pay the trace recording twice per case."""
    traces, sigs, variants = [], [], []
    for rank in range(case.n):
        label, thunk = case.make(rank)
        rec = record_kernel(thunk, n=case.n, rank=rank, axes=case.axes)
        traces.append(rec.events)
        sigs.append(rec.collapsed_signature())
        variants.append(label)
    return traces, sigs, variants


def verify_case(case: KernelCase, *, recorded=None) -> list[Violation]:
    """Record all N ranks of one case (or reuse ``recorded`` from
    :func:`record_case`) and run the four checks.  Check and violation
    totals land in the obs registry when observability is on."""
    traces, sigs, variants = recorded if recorded is not None \
        else record_case(case)
    violations = analyze(case.name, case.n, traces, sigs, variants)
    from .. import obs

    if obs.enabled():
        from .checks import CHECKS

        for check in CHECKS:
            obs.counter("verify_checks", kernel=case.family,
                        check=check).inc()
        for v in violations:
            obs.counter("verify_violations", kernel=case.family,
                        check=v.check).inc()
    return violations


def verify_all(ranks=DEFAULT_RANKS, *, kernel_filter: str | None = None):
    """Run the full matrix; returns ``[(case, violations), ...]``."""
    out = []
    for case in all_cases(ranks):
        if kernel_filter and kernel_filter not in case.name:
            continue
        out.append((case, verify_case(case)))
    return out


# one verification per (family, n, explore depth) per process: builders
# are themselves cached, but the flat entry points re-invoke them per
# shape class
_VERIFIED: set[tuple[str, int, int | None]] = set()
_VERIFIED_LOCK = threading.Lock()


def maybe_verify_build(family: str, n: int, *,
                       explore: int | None = None) -> None:
    """Statically verify ``family`` at ``n`` ranks before the kernel is
    built; raises :class:`ProtocolViolationError` on any violation — a
    kernel with a broken wait/notify protocol must not reach the compiler.

    ``explore`` (the ``TDT_VERIFY_EXPLORE`` knob via
    ``core.compilation.verify_protocol``) additionally model-checks every
    schedule class with the DPOR explorer: an integer is the preemption
    bound, -1 the exact mode, None canonical-only.  The ``TDT_VERIFY``
    env gate is owned by the compilation hook (a direct call here
    verifies unconditionally); degenerate meshes have no protocol to
    check."""
    if n < 2:
        return
    family = _FAMILY_ALIASES.get(family, family)
    key = (family, int(n), explore)
    with _VERIFIED_LOCK:
        if key in _VERIFIED:
            return
    violations = []
    capped = False
    for case in cases_for(family, n):
        recorded = record_case(case)           # ONE recording pass
        violations.extend(verify_case(case, recorded=recorded))
        if explore is not None and not violations:
            from .explore import explore_case

            if explore < 0:
                # the operator asked for EXACT: no preemption bound and
                # no resource caps — truncating here and memoizing the
                # result as verified would silently weaken the gate
                res = explore_case(case, recorded=recorded,
                                   preemption_bound=None,
                                   max_schedules=2**62, budget_ms=None)
            else:
                res = explore_case(case, recorded=recorded,
                                   preemption_bound=explore)
            violations.extend(res.violations)
            if res.pruned:
                import warnings

                capped = True
                warnings.warn(
                    f"TDT_VERIFY_EXPLORE: {case.name}@{n} hit a "
                    f"schedule/time cap after {res.schedules} clean "
                    f"classes — bounded verification only; the result "
                    f"is NOT memoized as verified")
    if violations:
        raise ProtocolViolationError(violations)
    if not capped:
        with _VERIFIED_LOCK:
            _VERIFIED.add(key)
