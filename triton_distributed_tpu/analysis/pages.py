"""Page-lifetime ownership model checking for the serving KV pool.

The analysis plane proves device-side collective protocols
schedule-exhaustively (``analysis.checks`` + the ``analysis.explore``
DPOR explorer), but the HOST-side page protocol — the paged KV pool
crossed by two tiers, handoff, preemption, eviction, scrub and audit —
was guarded only by dynamic "zero leaked pages" assertions in the fault
matrix, which witness ONE interleaving per seed.  "Demystifying
NVSHMEM" (PAPERS.md) shows the order-dependent slot-reuse/ABA hazard
class is exactly what single-schedule testing provably misses; this
module closes that gap for pages the same way PR 2/PR 14 closed it for
semaphores.

Three layers:

1. **Record mode** — :func:`record` arms a :class:`PageRecorder` via
   ``serve.budget.set_lifecycle_recorder``; every page operation at its
   real call site (``PagePool`` alloc/share/release/free/scrub, the
   scheduler's prefill-write / decode-append / audit-stamp /
   restore-verify / colocate-retain, ``serve.handoff``'s extract and
   the adopt-side implant) funnels through ``budget.page_event`` into
   one per-actor event stream.  Unarmed, the call sites pay a single
   module-global load.

2. **Ownership state machine** — :func:`check_events` walks a stream
   and tracks each page through

   ``FREE -> RESERVED -> FILLING -> STAMPED -> READABLE ->
   {SHARED, IN_FLIGHT, SCRUB_PENDING} -> FREE``

   (SHARED is the refcount>1 face of a sealed page, not a stored
   state), flagging leak-on-terminal-path, use-after-free,
   read-before-stamp, double-free/alloc, refcount underflow,
   write-under-share, adopt-before-stamp-verify, ABA reuse-before-
   scrub, and scrub-under-live-reader — each violation names the page
   id and the violating transition.

3. **Schedule exhaustion** — :func:`explore_pages` mirrors the PR-14
   DPOR reduction stack (sleep sets, singleton persistent sets via
   eager advancement, optional preemption bound, resource caps ->
   ``pruned``) over per-actor :class:`PageOp` scenarios: page-footprint
   overlap is the dependence relation and guard tokens encode the
   happens-before edges reality enforces (the router only extracts a
   PARKED handoff; release waits for adoption).  Every complete
   schedule class runs the full state machine, so an order-dependent
   lifecycle race is caught exhaustively, not per-seed.

Wired as ``tdt_lint --pages`` (fixture selftest + fault-matrix static
replay + the DPOR sweep over :func:`two_tier_scenarios`), the opt-in
``TDT_VERIFY_PAGES=1`` gate on ``serve.trace.replay``, and the
``page_lifecycle_checks`` / ``page_lifecycle_violations`` obs
counters.  The refcounted ``PagePool.share``/``release`` substrate this
module certifies is the exact primitive the radix prefix cache
(ROADMAP item 3) needs — shipped here verified-before-used.
"""

from __future__ import annotations

import contextlib
import dataclasses
import threading
import time

from .checks import ProtocolViolationError, Violation

# ---------------------------------------------------------------------------
# events + recorder

#: ops the state machine understands (call sites emit these via
#: ``serve.budget.page_event``)
OPS = frozenset({
    "alloc", "write", "implant", "seal", "stamp", "verify", "read",
    "share", "release", "free", "scrub", "extract", "retain",
})


@dataclasses.dataclass(frozen=True)
class PageEvent:
    """One page operation: ``actor`` (tier / pump / audit), ``op``
    (member of :data:`OPS`), ``key`` (a hashable page identity —
    ``(pool_idx, page_id)`` for recorded pools, a plain string in
    synthetic scenarios) and frozen ``meta`` pairs."""

    actor: str
    op: str
    key: object
    meta: tuple = ()

    def get(self, name, default=None):
        for k, v in self.meta:
            if k == name:
                return v
        return default


class PageRecorder:
    """Accumulates :class:`PageEvent` streams from the live call sites.

    Pools are keyed by identity (two tiers legitimately use the same
    physical page ids); an actor defaults to the owning scheduler's
    ``trace_tier`` so recorded traces read ``prefill``/``decode``
    exactly like request traces do.  Thread-safe: the straggler
    watchdog's abandoned dispatches and the pool's own lock discipline
    mean emits can arrive from more than one thread."""

    def __init__(self):
        self._lock = threading.Lock()
        self.events: list[PageEvent] = []
        self._pools: dict[int, tuple[int, object]] = {}

    def _pool_idx(self, pool) -> int:
        if pool is None:
            return 0
        ent = self._pools.get(id(pool))
        if ent is None:
            ent = (len(self._pools) + 1, pool)
            self._pools[id(pool)] = ent
        return ent[0]

    def pool_name(self, idx: int) -> str:
        for i, pool in self._pools.values():
            if i == idx:
                tier = getattr(getattr(pool, "owner", None),
                               "trace_tier", None)
                return tier if tier else f"pool{idx}"
        return "pool" if idx == 0 else f"pool{idx}"

    def emit(self, op: str, pages, *, pool=None, actor=None,
             **meta) -> None:
        if isinstance(pages, int):
            pages = (pages,)
        frozen = tuple(sorted(meta.items()))
        with self._lock:
            idx = self._pool_idx(pool)
            if actor is None:
                actor = getattr(getattr(pool, "owner", None),
                                "trace_tier", None) or "pool"
            for p in pages:
                self.events.append(
                    PageEvent(str(actor), op, (idx, int(p)), frozen))

    def page_label(self, key) -> str:
        if isinstance(key, tuple) and len(key) == 2:
            return f"{key[1]} ({self.pool_name(key[0])} pool)"
        return str(key)

    def __len__(self):
        return len(self.events)


@contextlib.contextmanager
def record():
    """Arm a fresh :class:`PageRecorder` on ``serve.budget`` for the
    duration of the block (restoring whatever was armed before)."""
    from ..serve import budget

    rec = PageRecorder()
    prev = budget.set_lifecycle_recorder(rec)
    try:
        yield rec
    finally:
        budget.set_lifecycle_recorder(prev)


# ---------------------------------------------------------------------------
# the ownership state machine

FREE = "FREE"
RESERVED = "RESERVED"
FILLING = "FILLING"
STAMPED = "STAMPED"
READABLE = "READABLE"
IN_FLIGHT = "IN_FLIGHT"
SCRUB_PENDING = "SCRUB_PENDING"

#: readable states a ``read`` is legal in (FILLING included: decode
#: legitimately attends over the partially-filled tail page)
_READ_OK = frozenset({FILLING, STAMPED, READABLE, IN_FLIGHT})
_DEAD = frozenset({FREE, SCRUB_PENDING})


class _Page:
    __slots__ = ("state", "refs", "adopted", "verified")

    def __init__(self):
        self.state = FREE
        self.refs = 0
        self.adopted = False
        self.verified = False

    def face(self) -> str:
        """The display state: SHARED is the refs>1 face of a sealed
        page, derived rather than stored so share/release never lose
        the underlying STAMPED/READABLE."""
        if self.refs > 1 and self.state not in _DEAD:
            return "SHARED"
        return self.state


class _Machine:
    """One pass of the ownership state machine over an event stream."""

    def __init__(self, label: str, page_label=None):
        self.label = label
        self.page_label = page_label or str
        self.pages: dict[object, _Page] = {}
        self.violations: list[Violation] = []

    # -- helpers ------------------------------------------------------------

    def _flag(self, check: str, ev: PageEvent, pg: _Page,
              why: str) -> None:
        self.violations.append(Violation(
            check, self.label, 0,
            f"page {self.page_label(ev.key)}: illegal transition "
            f"{pg.face()}->{ev.op} by actor {ev.actor} — {why}"))

    def _page(self, key) -> _Page:
        pg = self.pages.get(key)
        if pg is None:
            pg = self.pages[key] = _Page()
        return pg

    # -- the transition table ----------------------------------------------

    def step(self, ev: PageEvent) -> None:
        pg = self._page(ev.key)
        op = ev.op
        if op == "alloc":
            if pg.state == SCRUB_PENDING:
                self._flag(
                    "reuse_before_scrub", ev, pg,
                    "re-allocated before the pending poison-fill "
                    "landed — the ABA window where the new tenant can "
                    "read the previous tenant's bytes OR the late "
                    "scrub can poison the new tenant's writes")
            elif pg.state != FREE:
                self._flag(
                    "double_alloc", ev, pg,
                    "allocated while live — two sequences would share "
                    "it and corrupt each other's KV")
            pg.state, pg.refs = RESERVED, 1
            pg.adopted = pg.verified = False
        elif op in ("write", "implant"):
            if pg.state in _DEAD:
                self._flag("use_after_free", ev, pg,
                           "write lands in recycled (or scrub-pending) "
                           "storage")
                return
            if pg.refs > 1:
                self._flag(
                    "write_under_share", ev, pg,
                    "a shared page must be copied before mutation "
                    "(copy-on-write) — every other reference sees the "
                    "edit")
                return
            if pg.state == STAMPED and op == "write":
                self._flag(
                    "write_after_stamp", ev, pg,
                    "stamped bytes may not change — the next audit "
                    "fold would quarantine a legal write as "
                    "corruption")
                return
            if pg.state == IN_FLIGHT:
                self._flag(
                    "write_in_flight", ev, pg,
                    "the extracted payload and the pool bytes would "
                    "diverge mid-transfer")
                return
            if op == "implant":
                pg.adopted, pg.verified = True, False
            pg.state = FILLING if pg.state in (
                RESERVED, FILLING) else pg.state
        elif op == "seal":
            if pg.state in _DEAD:
                self._flag("use_after_free", ev, pg,
                           "sealing recycled storage")
                return
            if pg.adopted and not pg.verified:
                self._flag(
                    "adopt_before_stamp_verify", ev, pg,
                    "an implanted page must pass stamp verification "
                    "before it is declared readable — adopting "
                    "unverified wire bytes is how a corrupt transfer "
                    "becomes silent KV corruption")
            if pg.state == IN_FLIGHT:
                self._flag("seal_in_flight", ev, pg,
                           "cannot seal mid-transfer")
                return
            pg.state = READABLE if pg.state != STAMPED else STAMPED
        elif op == "stamp":
            if pg.state in _DEAD:
                self._flag("use_after_free", ev, pg,
                           "stamping recycled storage")
                return
            if pg.state == RESERVED:
                self._flag(
                    "stamp_unwritten", ev, pg,
                    "folding a never-written page pins garbage as the "
                    "golden stamp")
                return
            # audit may re-fold a page parked IN_FLIGHT (HANDOFF slots
            # stay in slots[] until released) — state unchanged there
            if pg.state in (FILLING, READABLE):
                pg.state = STAMPED
        elif op == "verify":
            if pg.state in _DEAD:
                self._flag("use_after_free", ev, pg,
                           "verifying recycled storage")
                return
            pg.verified = True
        elif op == "read":
            if pg.state in _DEAD:
                self._flag("use_after_free", ev, pg,
                           "attention would read recycled (or poison-"
                           "filled) KV")
                return
            if pg.state == RESERVED:
                self._flag(
                    "read_before_stamp", ev, pg,
                    "reading a reserved, never-written page returns "
                    "whatever the previous tenant left")
                return
            if pg.adopted and not pg.verified:
                self._flag(
                    "adopt_before_stamp_verify", ev, pg,
                    "reading implanted wire bytes before stamp "
                    "verification")
        elif op == "share":
            if pg.state in _DEAD or pg.refs == 0:
                self._flag("use_after_free", ev, pg,
                           "a reference to recycled storage reads the "
                           "next tenant's KV")
                return
            if pg.state in (RESERVED, FILLING):
                self._flag(
                    "share_unsealed", ev, pg,
                    "only sealed content may be shared — a prefix "
                    "cache handing out a still-filling page serves a "
                    "torn read")
                return
            if pg.state == IN_FLIGHT:
                self._flag("share_in_flight", ev, pg,
                           "cannot take references mid-transfer")
                return
            pg.refs += 1
        elif op in ("free", "release"):
            if pg.refs == 0:
                if op == "release":
                    self._flag(
                        "refcount_underflow", ev, pg,
                        "more releases than references — some earlier "
                        "release already recycled the page under a "
                        "holder that still believes it owns one")
                else:
                    self._flag(
                        "double_free", ev, pg,
                        "two sequences would share it and corrupt "
                        "each other's KV")
                return
            pg.refs -= 1
            if pg.refs == 0:
                pg.state = SCRUB_PENDING if ev.get("scrub_pending") \
                    else FREE
                pg.adopted = pg.verified = False
        elif op == "scrub":
            if pg.refs > 0:
                self._flag(
                    "scrub_under_live_reader", ev, pg,
                    f"poison-fill with {pg.refs} live reference(s) — "
                    f"the reader's next attention step returns the "
                    f"poison pattern")
                return
            pg.state = FREE
        elif op == "extract":
            if pg.state in _DEAD:
                self._flag("use_after_free", ev, pg,
                           "extracting recycled storage ships garbage")
                return
            if pg.state in (RESERVED, FILLING):
                self._flag(
                    "extract_unsealed", ev, pg,
                    "the handoff payload must cover sealed content — "
                    "extracting mid-fill ships a torn prefix")
                return
            if pg.state in (STAMPED, READABLE):
                pg.state = IN_FLIGHT
        elif op == "retain":
            if pg.state in _DEAD:
                self._flag("use_after_free", ev, pg,
                           "colocating onto recycled storage")
                return
            if pg.state == IN_FLIGHT:
                pg.state = READABLE
        else:   # pragma: no cover - call sites only emit OPS members
            raise ValueError(f"unknown page op {op!r}")

    def finish(self) -> None:
        """Terminal-path leak check: every page must be back to FREE
        (SCRUB_PENDING counts — the free committed, only the poison
        fill is outstanding) with zero references."""
        for key in sorted(self.pages, key=str):
            pg = self.pages[key]
            if pg.state not in _DEAD or pg.refs > 0:
                self.violations.append(Violation(
                    "page_leak", self.label, 0,
                    f"page {self.page_label(key)}: still {pg.face()} "
                    f"with {pg.refs} reference(s) at end of trace — a "
                    f"terminal path (complete/abort/shed/preempt/"
                    f"re-prefill/drain) failed to return it (missing "
                    f"{pg.face()}->free)"))


def check_events(events, *, label: str = "pages",
                 page_label=None) -> list[Violation]:
    """Run the ownership state machine over one merged event stream;
    returns the violations (empty = leak-free and lifetime-safe).
    Bumps the ``page_lifecycle_checks`` / ``page_lifecycle_violations``
    counters when observability is on."""
    m = _Machine(label, page_label)
    for ev in events:
        m.step(ev)
    m.finish()
    from .. import obs

    if obs.enabled():
        obs.counter("page_lifecycle_checks").inc()
        if m.violations:
            obs.counter("page_lifecycle_violations").inc(
                len(m.violations))
    return m.violations


def check_recorder(rec: PageRecorder, *,
                   label: str = "pages") -> list[Violation]:
    """:func:`check_events` over a live recording, with page labels
    resolved through the recorder's pool table (``3 (prefill pool)``)."""
    return check_events(rec.events, label=label,
                        page_label=rec.page_label)


# ---------------------------------------------------------------------------
# the TDT_VERIFY_PAGES gate


def verify_pages_enabled() -> bool:
    """``TDT_VERIFY_PAGES=1``: serve-trace replays record every page
    op and raise :class:`ProtocolViolationError` on any lifecycle
    violation (docs/static_analysis.md flag matrix)."""
    from ..core.utils import env_flag

    return env_flag("TDT_VERIFY_PAGES")


@contextlib.contextmanager
def maybe_record(label: str = "serve_replay"):
    """The replay hook: arm + check when ``TDT_VERIFY_PAGES=1`` (and
    no outer recorder is already armed), a no-op otherwise.  Raises on
    violations only when the guarded block exits cleanly — a replay
    that already raised keeps its own error."""
    from ..serve import budget

    if not verify_pages_enabled() \
            or budget.lifecycle_recorder() is not None:
        yield None
        return
    with record() as rec:
        yield rec
    vs = check_recorder(rec, label=label)
    if vs:
        raise ProtocolViolationError(vs)


# ---------------------------------------------------------------------------
# the page-footprint DPOR explorer


@dataclasses.dataclass(frozen=True)
class PageOp:
    """One static scenario event.  ``guard``: tokens that must ALL be
    produced before this op is enabled (the happens-before edges
    reality enforces — e.g. the router only extracts a PARKED
    handoff); ``token``: produced when the op executes.  ``meta``:
    frozen ``(k, v)`` pairs forwarded to the state machine (e.g.
    ``(("scrub_pending", True),)``)."""

    op: str
    page: object
    guard: tuple = ()
    token: str | None = None
    meta: tuple = ()


@dataclasses.dataclass
class PageExploreResult:
    name: str
    actors: tuple
    schedules: int                 # complete equivalence classes
    violations: list[Violation]
    pruned: bool = False
    preemption_bound: int | None = None
    witness: str | None = None     # schedule label of first violation


DEFAULT_MAX_SCHEDULES = 2048
DEFAULT_BUDGET_MS = 2_000.0


class _PageExplorer:
    """The PR-14 reduction stack over per-actor PageOp traces: sleep
    sets, singleton persistent sets via eager advancement of
    non-branching events, optional preemption bound, resource caps ->
    ``pruned``.  Dependence is page-footprint overlap; guard tokens
    never make co-enabled events dependent (tokens are produced, never
    consumed, so an enabled op stays enabled)."""

    def __init__(self, name, scenario, *, preemption_bound,
                 max_schedules, budget_ms, stop_on_violation):
        self.name = name
        self.actors = tuple(scenario)
        self.traces = [tuple(scenario[a]) for a in self.actors]
        self.n = len(self.actors)
        self.bound = preemption_bound
        self.max_schedules = max_schedules
        self.deadline = None if budget_ms is None else \
            time.monotonic() + budget_ms / 1e3
        self.stop_on_violation = stop_on_violation
        self.pcs = [0] * self.n
        self.produced: set[str] = set()
        self.schedule: list[int] = []
        self.schedules = 0
        self.pruned = False
        self.violations: list[Violation] = []
        self._seen: set[tuple[str, str]] = set()
        self.witness: str | None = None

    # -- state --------------------------------------------------------------

    def next_op(self, i: int) -> PageOp | None:
        t = self.traces[i]
        return t[self.pcs[i]] if self.pcs[i] < len(t) else None

    def enabled(self, i: int) -> bool:
        op = self.next_op(i)
        return op is not None and all(
            g in self.produced for g in op.guard)

    def execute(self, i: int):
        op = self.traces[i][self.pcs[i]]
        self.pcs[i] += 1
        self.schedule.append(i)
        new_token = op.token is not None and op.token not in self.produced
        if new_token:
            self.produced.add(op.token)
        return (i, op.token if new_token else None)

    def undo(self, undo) -> None:
        i, token = undo
        self.pcs[i] -= 1
        self.schedule.pop()
        if token is not None:
            self.produced.discard(token)

    def done(self) -> bool:
        return all(self.pcs[i] >= len(self.traces[i])
                   for i in range(self.n))

    # -- dependence ---------------------------------------------------------

    def _independent(self, a: int, b: int) -> bool:
        """Co-enabled branch choices commute iff their page footprints
        are disjoint (token production only ever ENABLES more — it
        cannot disable, so it is not a dependence between co-enabled
        ops)."""
        oa, ob = self.next_op(a), self.next_op(b)
        if oa is None or ob is None:
            return True
        return oa.page != ob.page

    def branches(self, i: int) -> bool:
        """Is actor ``i``'s next op a branch point?  Conservative:
        it branches if ANY other actor still has an op on the same
        page anywhere in its remaining trace — a conflicting op that
        is merely not-yet-enabled can become enabled after other
        steps, so only pages no one else will ever touch again are
        safe to advance eagerly (singleton persistent set)."""
        oi = self.next_op(i)
        if oi is None:
            return False
        for j in range(self.n):
            if j == i:
                continue
            t = self.traces[j]
            if any(t[k].page == oi.page
                   for k in range(self.pcs[j], len(t))):
                return True
        return False

    # -- per-class check ----------------------------------------------------

    def _label(self, cap: int = 48) -> str:
        runs: list[list[int]] = []
        for r in self.schedule:
            if runs and runs[-1][0] == r:
                runs[-1][1] += 1
            else:
                runs.append([r, 1])
        parts = [self.actors[r] if k == 1 else f"{self.actors[r]}*{k}"
                 for r, k in runs]
        if len(parts) > cap:
            parts = parts[:cap] + ["..."]
        return " ".join(parts)

    def _record(self, v: Violation, sched: str) -> None:
        key = (v.check, v.message)
        if key in self._seen:
            return
        self._seen.add(key)
        self.violations.append(Violation(
            v.check, v.kernel, v.ranks,
            f"under schedule [{sched}]: {v.message}"))
        if self.witness is None:
            self.witness = sched

    def _check_complete(self) -> None:
        self.schedules += 1
        sched = self._label()
        events = []
        pcs = [0] * self.n
        for r in self.schedule:
            op = self.traces[r][pcs[r]]
            pcs[r] += 1
            events.append(PageEvent(self.actors[r], op.op, op.page,
                                    op.meta))
        for v in check_events(events, label=self.name):
            self._record(v, sched)

    def _deadlock(self) -> None:
        self.schedules += 1
        sched = self._label()
        blocked = []
        for i in range(self.n):
            op = self.next_op(i)
            if op is not None:
                missing = [g for g in op.guard
                           if g not in self.produced]
                blocked.append(
                    f"{self.actors[i]} stuck at {op.op}({op.page}) "
                    f"awaiting {missing}")
        self._record(Violation(
            "deadlock", self.name, 0,
            "no actor can advance (guard tokens never produced): "
            + "; ".join(blocked)), sched)

    # -- search -------------------------------------------------------------

    def _stop(self) -> bool:
        if self.stop_on_violation and self.violations:
            return True
        if self.schedules >= self.max_schedules or (
                self.deadline is not None
                and time.monotonic() > self.deadline):
            self.pruned = True
            return True
        return False

    def run(self) -> None:
        self._explore(frozenset(), None, 0)

    def _advance_eager(self, sleep: frozenset) -> list:
        undos = []
        progress = True
        while progress:
            progress = False
            for i in range(self.n):
                if i in sleep:
                    continue
                while self.enabled(i) and not self.branches(i):
                    undos.append(self.execute(i))
                    progress = True
        return undos

    def _explore(self, sleep: frozenset, last, preemptions) -> None:
        if self._stop():
            return
        undos = self._advance_eager(sleep)
        try:
            enabled = [i for i in range(self.n) if self.enabled(i)]
            live = [i for i in enabled if i not in sleep]
            if not enabled:
                if self.done():
                    self._check_complete()
                elif not sleep:
                    self._deadlock()
                # else: a slept sibling covers this continuation
                return
            if not live:
                return
            if self.bound is not None and preemptions >= self.bound \
                    and last is not None and last in live:
                live = [last]
            done: list[int] = []
            for i in live:
                if self._stop():
                    return
                cost = preemptions
                if last is not None and i != last \
                        and self.enabled(last):
                    cost += 1
                    if self.bound is not None and cost > self.bound:
                        continue
                child_sleep = frozenset(
                    u for u in (*sleep, *done)
                    if self.enabled(u) and self._independent(u, i))
                undo = self.execute(i)
                self._explore(child_sleep, i, cost)
                self.undo(undo)
                done.append(i)
        finally:
            for u in reversed(undos):
                self.undo(u)


def explore_pages(name: str, scenario: dict, *,
                  preemption_bound: int | None = None,
                  max_schedules: int = DEFAULT_MAX_SCHEDULES,
                  budget_ms: float | None = DEFAULT_BUDGET_MS,
                  stop_on_violation: bool = False) -> PageExploreResult:
    """Explore all schedule classes of ``scenario`` (actor name ->
    list of :class:`PageOp`) and run the ownership state machine on
    every complete class.  ``preemption_bound=None`` is the exact
    mode — scenario traces are short enough that the sweep defaults to
    it, unlike the semaphore explorer."""
    ex = _PageExplorer(name, scenario,
                       preemption_bound=preemption_bound,
                       max_schedules=max_schedules,
                       budget_ms=budget_ms,
                       stop_on_violation=stop_on_violation)
    ex.run()
    return PageExploreResult(name, ex.actors, ex.schedules,
                             ex.violations, pruned=ex.pruned,
                             preemption_bound=preemption_bound,
                             witness=ex.witness)


# ---------------------------------------------------------------------------
# the clean two-tier scenarios (the sweep `tdt_lint --pages` walks)


def two_tier_scenarios() -> list[tuple[str, dict]]:
    """The router-pump x prefill-tier x decode-tier x audit-cadence
    interleaving, modeled per terminal path.  Guard tokens encode
    exactly the happens-before edges the protocol enforces (extract
    only after parked, release only after adoption, scrub only after
    the LAST release); everything else — audit cadence against the
    other tier's progress, decode stepping against the pump — is left
    free for the explorer to permute.  All must verify clean; the
    seeded-bad twins live in ``fixtures.page_fixture_cases``."""
    P, D = "P1", "D1"    # prefill-pool / decode-pool page ids
    w = lambda **kw: tuple(sorted(kw.items()))

    handoff_clean = {
        "prefill": [
            PageOp("alloc", P), PageOp("write", P),
            PageOp("seal", P, token="parked"),
        ],
        "audit": [
            # audit cadence: the re-fold + re-read race the pump and
            # the decode tier freely, INCLUDING mid-transfer (HANDOFF
            # slots stay in slots[] until released) — but the release
            # waits for the tick, because audit and release share the
            # prefill scheduler's single thread and audit only ever
            # touches slots still present
            PageOp("stamp", P, guard=("parked",)),
            PageOp("read", P, guard=("parked",), token="audited"),
        ],
        "router": [
            PageOp("extract", P, guard=("parked",), token="shipped"),
            PageOp("free", P, guard=("adopted", "audited"),
                   meta=w(scrub_pending=True)),
            PageOp("scrub", P),
        ],
        "decode": [
            PageOp("alloc", D, guard=("shipped",)),
            PageOp("implant", D), PageOp("verify", D),
            PageOp("seal", D, token="adopted"),
            PageOp("read", D), PageOp("write", D), PageOp("seal", D),
            PageOp("free", D, meta=w(scrub_pending=True)),
            PageOp("scrub", D),
        ],
    }

    reprefill_drop = {
        # transfer ladder exhausted (TRANSFER_DROP / open breaker):
        # producer pages come home from IN_FLIGHT, the decode tier
        # recomputes from the prompt with carried stamps
        "prefill": [
            PageOp("alloc", P), PageOp("write", P),
            PageOp("seal", P, token="parked"),
        ],
        "router": [
            PageOp("extract", P, guard=("parked",)),
            PageOp("free", P, token="reprefilled",
                   meta=w(scrub_pending=True)),
            PageOp("scrub", P),
        ],
        "decode": [
            PageOp("alloc", D, guard=("reprefilled",)),
            PageOp("write", D), PageOp("verify", D),
            PageOp("seal", D), PageOp("read", D),
            PageOp("free", D, meta=w(scrub_pending=True)),
            PageOp("scrub", D),
        ],
    }

    preempt_restore = {
        # preemption returns pages mid-decode; the restore re-allocs
        # (possibly the SAME id — the ABA shape the scrub ordering
        # must survive) and re-verifies against carried stamps
        "serve": [
            PageOp("alloc", P), PageOp("write", P), PageOp("seal", P),
            PageOp("stamp", P, token="stamped"), PageOp("read", P),
            # preempt frees only after the audit tick — audit and the
            # scheduling loop share one thread, so audit never holds a
            # reference across a free
            PageOp("free", P, guard=("audited",),
                   meta=w(scrub_pending=True)),
            PageOp("scrub", P),
            # restore: the pool's free-list commit + same-thread
            # scrubber put the scrub strictly before any re-alloc of
            # the same id (program order above); the fixtures' ABA
            # seed is exactly this ordering dropped
            PageOp("alloc", P),
            PageOp("write", P), PageOp("verify", P), PageOp("seal", P),
            PageOp("read", P),
            PageOp("free", P, meta=w(scrub_pending=True)),
            PageOp("scrub", P),
        ],
        "audit": [
            # the audit re-fold floats between the stamp and the
            # preempt — the explorer permutes it against the owner's
            # read
            PageOp("read", P, guard=("stamped",), token="audited"),
        ],
    }

    colocate_drain = {
        # decode tier saturated: the request finishes decode on the
        # prefill tier, where its pages already live (retain from
        # park, never extracted)
        "prefill": [
            PageOp("alloc", P), PageOp("write", P),
            PageOp("seal", P, token="parked"),
        ],
        "router": [
            PageOp("retain", P, guard=("parked",), token="colocated"),
        ],
        "serve": [
            PageOp("read", P, guard=("colocated",)),
            PageOp("write", P), PageOp("seal", P),
            PageOp("free", P, guard=("colocated",),
                   meta=w(scrub_pending=True)),
            PageOp("scrub", P),
        ],
    }

    shared_release = {
        # the refcount substrate (radix prefix cache): owner seals, a
        # sharer takes a reference, BOTH release — whichever order the
        # explorer picks, the scrub must come only after the LAST
        # release.  The owner's free is guarded on the share having
        # happened (references are taken synchronously during the
        # owner's lifetime); the scrub waits on both release tokens —
        # exactly what PagePool's refcounts enforce structurally.
        "decode": [
            PageOp("alloc", D), PageOp("write", D),
            PageOp("seal", D, token="sealed"),
            PageOp("read", D),
            PageOp("free", D, guard=("cached",),
                   token="owner_released"),
        ],
        "radix": [
            PageOp("share", D, guard=("sealed",), token="cached"),
            PageOp("read", D),
            PageOp("release", D, token="cache_released",
                   meta=w(scrub_pending=True)),
        ],
        "scrubber": [
            PageOp("scrub", D,
                   guard=("owner_released", "cache_released")),
        ],
    }

    return [
        ("pages/handoff_clean", handoff_clean),
        ("pages/reprefill_drop", reprefill_drop),
        ("pages/preempt_restore", preempt_restore),
        ("pages/colocate_drain", colocate_drain),
        ("pages/shared_release", shared_release),
    ]


# ---------------------------------------------------------------------------
# lifecycle coverage (the completeness golden reads this)

#: every RequestState member and every HandoffFault class -> the
#: harness that discharges its page-lifetime claim statically.
#: ``analysis.completeness`` diffs this against the live enums BOTH
#: ways, so adding a state or a fault class without lifecycle coverage
#: fails the lint.
LIFECYCLE_COVERAGE = {
    "request_states": {
        "QUEUED": "matrix:scheduler (admission holds no pages; the "
                  "pop_if race-path alloc/free is recorded)",
        "PREFILL": "matrix:scheduler + scenario pages/handoff_clean",
        "DECODE": "matrix:scheduler + scenario pages/handoff_clean",
        "HANDOFF": "matrix:handoff + scenarios pages/handoff_clean, "
                   "pages/colocate_drain",
        "PREEMPTED": "matrix:scheduler preempt cells + scenario "
                     "pages/preempt_restore",
        "DONE": "every matrix cell drains to DONE; terminal leak "
                "check on all recorded replays",
        "FAILED": "matrix:scheduler poison cells (fail_slot frees; "
                  "fixture pagefix/leak_on_abort pins the omission)",
        "SHED": "matrix:scheduler shed cells (shed before alloc / "
                "release on shed both recorded)",
    },
    "handoff_faults": {
        "transfer_drop": "matrix:handoff drop cell + scenario "
                         "pages/reprefill_drop (producer pages freed "
                         "from IN_FLIGHT on the exhausted ladder)",
        "corrupt_page_in_flight": "matrix:handoff corrupt cell (clean "
                                  "retry re-extracts; stamp-verify "
                                  "before the adopted seal)",
        "stale_stamp": "matrix:handoff stale cell (same retry path — "
                       "a stale sidecar is a corrupt payload to the "
                       "verify step)",
        "prefill_rank_abort": "matrix:handoff abort cell + scenario "
                              "pages/reprefill_drop (aborted "
                              "producer's pages freed, victim "
                              "re-prefills on the decode tier)",
        "decode_saturated": "matrix:handoff saturation cell + "
                            "scenario pages/colocate_drain (colocated "
                            "slot retains IN_FLIGHT pages in place)",
    },
}
