"""tdt.analysis: static protocol verification for distributed Pallas kernels.

The reference framework validates its device-side wait/notify/putmem_signal
protocols only dynamically (compute-sanitizer racecheck, SURVEY.md §5), and
our interpret-mode stand-in (``core.compilation.enable_race_detection``)
needs a jax able to run Pallas interpret mode at all.  This package proves
the protocol properties STATICALLY, on any CPU, by symbolically executing
each collective kernel's primitive vocabulary per rank (record mode in
``lang.primitives``) and composing the N-rank traces:

1. signal balance        every wait's expected count is produced
2. deadlock freedom      the cross-rank wait-for structure is acyclic
3. write-overlap         no unordered overlapping destination writes
4. collective divergence all ranks run the same collective program

Entry points:

- ``verify_all()`` / ``verify_case``   the registry matrix (CLI:
  ``scripts/tdt_lint.py``)
- ``maybe_verify_build(family, n)``    build-time gate, ``TDT_VERIFY=1``
- ``fixtures.run_selftest()``          seeded-bad kernels battery

See docs/static_analysis.md for the event model and check semantics.
"""

from .checks import CHECKS, ProtocolViolationError, Violation, analyze
from .events import FakeRef, FakeSem, FakeSmem, Region
from .record import KernelRecorder, record_kernel, recording
from .registry import (
    DEFAULT_RANKS,
    FAMILIES,
    KernelCase,
    all_cases,
    cases_for,
    maybe_verify_build,
    verify_all,
    verify_case,
)

__all__ = [
    "CHECKS", "DEFAULT_RANKS", "FAMILIES", "FakeRef", "FakeSem", "FakeSmem",
    "KernelCase", "KernelRecorder", "ProtocolViolationError", "Region",
    "Violation", "all_cases", "analyze", "cases_for", "maybe_verify_build",
    "record_kernel", "recording", "verify_all", "verify_case",
]
