"""tdt.analysis: static protocol verification for distributed Pallas kernels.

The reference framework validates its device-side wait/notify/putmem_signal
protocols only dynamically (compute-sanitizer racecheck, SURVEY.md §5), and
our interpret-mode stand-in (``core.compilation.enable_race_detection``)
needs a jax able to run Pallas interpret mode at all.  This package proves
the protocol properties STATICALLY, on any CPU, by symbolically executing
each collective kernel's primitive vocabulary per rank (record mode in
``lang.primitives``) and composing the N-rank traces:

1. signal balance        every wait's expected count is produced
2. deadlock freedom      the cross-rank wait-for structure is acyclic
3. write-overlap         no unordered overlapping destination writes
4. collective divergence all ranks run the same collective program

The canonical checks are schedule-sound for deadlock (credit
monotonicity) but NOT for the credit->wait matching on multi-producer
pools; ``explore`` closes that gap by model-checking every schedule
class up to equivalence (DPOR: sleep sets + singleton persistent sets
over the credit-FIFO independence relation), and ``footprint`` adds the
static resource leg — symbolic VMEM/SMEM/semaphore footprints per
(family x tile config) that the autotuner prunes against before
measuring.

Entry points:

- ``verify_all()`` / ``verify_case``   the registry matrix (CLI:
  ``scripts/tdt_lint.py``)
- ``explore_all()`` / ``explore_case`` schedule-exhaustive DPOR sweep
  (CLI: ``tdt_lint --dpor``)
- ``maybe_verify_build(family, n)``    build-time gate, ``TDT_VERIFY=1``
  (+ ``TDT_VERIFY_EXPLORE`` for bounded/exact exploration)
- ``fixtures.run_selftest()``          seeded-bad kernels battery
- ``fixtures.run_dpor_selftest()``     canonical-pass / DPOR-fail pins
- ``fixtures.run_page_selftest()``     seeded-bad page lifecycles
- ``footprint.check_defaults()``       default-config feasibility
- ``completeness.check()``             cross-subsystem wiring lint
- ``pages.check_events`` / ``pages.explore_pages``  page-lifetime
  ownership model checking for the serving KV pool (CLI:
  ``tdt_lint --pages``; gate: ``TDT_VERIFY_PAGES=1``)

See docs/static_analysis.md for the event model and check semantics.
"""

from .checks import CHECKS, ProtocolViolationError, Violation, analyze
from .events import FakeRef, FakeSem, FakeSmem, Region
# NOTE: the raw-traces ``explore(kernel, n, traces)`` entry stays on the
# submodule (``analysis.explore.explore``) — re-exporting it here would
# shadow the submodule name itself
from .explore import ExploreResult, explore_all, explore_case
from .footprint import Footprint
# NOTE: same treatment for ``pages`` — the checker's entry points stay
# importable as names here while the submodule keeps its own name
from .pages import (
    PageEvent,
    PageExploreResult,
    PageOp,
    PageRecorder,
    check_events,
    explore_pages,
    two_tier_scenarios,
)
from .record import KernelRecorder, record_kernel, recording
from .registry import (
    DEFAULT_RANKS,
    FAMILIES,
    KernelCase,
    all_cases,
    cases_for,
    maybe_verify_build,
    record_case,
    verify_all,
    verify_case,
)

__all__ = [
    "CHECKS", "DEFAULT_RANKS", "ExploreResult", "FAMILIES", "FakeRef",
    "FakeSem", "FakeSmem", "Footprint", "KernelCase", "KernelRecorder",
    "PageEvent", "PageExploreResult", "PageOp", "PageRecorder",
    "ProtocolViolationError", "Region", "Violation", "all_cases",
    "analyze", "cases_for", "check_events", "explore_all",
    "explore_case", "explore_pages", "maybe_verify_build",
    "record_case", "record_kernel", "recording", "two_tier_scenarios",
    "verify_all",
    "verify_case",
]
