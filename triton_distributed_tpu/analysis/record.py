"""Record mode: symbolically execute one rank of a kernel body.

``record_kernel`` installs a :class:`KernelRecorder` as the thread's active
recorder (``lang.primitives.active_recorder``) and runs the kernel body as
PLAIN PYTHON with :mod:`analysis.events` fakes in place of refs and
semaphores.  Every rank identity (``Team.rank``, ``dl.rank``,
``jax.lax.axis_index``) resolves to the concrete rank being recorded, so
``pl.when``-free kernel control flow — the entire collective vocabulary of
``comm/`` and ``ops/`` — executes concretely; ring arithmetic through
``jax.lax.rem`` on concrete ints runs eagerly and is concretized with
``int()`` at event boundaries.  ``jax.lax.fori_loop`` is patched to a
Python loop for the duration (the all-to-all kernels drive chunk DMAs
through it with counts read from SMEM example values; tracing the body
would destroy concreteness).

The recorded artifacts per rank:

- ``events``     the flat effect list (:mod:`analysis.events` dataclasses)
- ``signature``  the high-level op-kind sequence (``barrier_all``,
  ``remote_copy``, ``wait_recv``, ...) used by the collective-divergence
  check; barriers record ONE signature entry even though they expand to
  several signal/wait events.
"""

from __future__ import annotations

import contextlib
import threading

from ..lang import primitives as dl
from .events import (
    BARRIER_SEM,
    CopyEv,
    FakeRef,
    FakeSem,
    NotifyEv,
    WaitEv,
    ComputeEv,
    _as_int,
)


class _LocalCopyDesc:
    """The descriptor ``dl.local_copy`` returns under record mode; its
    ``.wait()`` is the local-DMA completion consumption."""

    def __init__(self, rec: "KernelRecorder", dst: FakeRef, sem: FakeSem):
        self._rec, self._dst, self._sem = rec, dst, sem

    def start(self) -> None:
        pass

    def wait(self) -> None:
        self._rec.signature.append("local_wait")
        self._rec.events.append(
            WaitEv(self._sem.key(), self._dst.region().elements(), "elem")
        )


class _RemoteCopyDesc:
    def start(self) -> None:
        pass

    def wait(self) -> None:
        raise NotImplementedError(
            "record mode: wait a remote_copy through wait_send/wait_recv "
            "(the two sides complete independently)"
        )


class KernelRecorder:
    """One rank's event recorder.  ``axes``: the mesh as ((name, size), ...)
    outermost first; ``coords``: this device's coordinate per axis.  Device
    ids are the linearized logical ids over ``axes`` (for the single-axis
    harness meshes, device id == team rank)."""

    def __init__(self, axes: tuple[tuple[str, int], ...],
                 coords: dict[str, int]):
        self.axes = tuple((str(n), int(s)) for n, s in axes)
        self.coords = {str(k): int(v) for k, v in coords.items()}
        for name, size in self.axes:
            if not 0 <= self.coords.get(name, -1) < size:
                raise ValueError(
                    f"coords[{name!r}] must be in [0, {size})"
                )
        self.events: list = []
        self.signature: list[str] = []

    # -- identity -----------------------------------------------------------

    def axis_rank(self, axis: str) -> int:
        return self.coords[axis]

    def axis_size(self, axis: str) -> int:
        return dict(self.axes)[axis]

    @property
    def device_id(self) -> int:
        lid = 0
        for name, size in self.axes:
            lid = lid * size + self.coords[name]
        return lid

    def _target(self, device_id) -> int:
        return self.device_id if device_id is None else _as_int(device_id)

    # -- primitive hooks (called from lang.primitives) ----------------------

    def on_notify(self, sem: FakeSem, device_id, inc) -> None:
        self.signature.append("notify")
        self.events.append(
            NotifyEv(sem.key(), self._target(device_id), _as_int(inc))
        )

    def on_wait(self, sem: FakeSem, value) -> None:
        self.signature.append("wait")
        self.events.append(WaitEv(sem.key(), _as_int(value), "count"))

    def on_remote_copy(self, src: FakeRef, dst: FakeRef, send_sem: FakeSem,
                       recv_sem: FakeSem, device_id, *,
                       start: bool = True) -> _RemoteCopyDesc:
        if not start:
            # silently modeling an unstarted descriptor would credit
            # semaphores for a copy that may never run — a false CLEAN
            raise NotImplementedError(
                "record mode cannot model start=False descriptors: the "
                "verifier has no static issue point for a deferred start"
            )
        self.signature.append("remote_copy")
        self.events.append(CopyEv(
            src.region(), dst.region(), self._target(device_id),
            None if send_sem is None else send_sem.key(), recv_sem.key(),
        ))
        return _RemoteCopyDesc()

    def on_local_copy(self, src: FakeRef, dst: FakeRef, sem: FakeSem, *,
                      start: bool = True) -> _LocalCopyDesc:
        if not start:
            raise NotImplementedError(
                "record mode cannot model start=False descriptors: the "
                "verifier has no static issue point for a deferred start"
            )
        self.signature.append("local_copy")
        self.events.append(CopyEv(
            src.region(), dst.region(), self.device_id, None, sem.key(),
        ))
        return _LocalCopyDesc(self, dst, sem)

    def on_wait_recv(self, dst_ref: FakeRef, sem: FakeSem) -> None:
        self.signature.append("wait_recv")
        self.events.append(
            WaitEv(sem.key(), dst_ref.region().elements(), "elem")
        )

    def on_wait_send(self, src_ref: FakeRef, sem: FakeSem) -> None:
        self.signature.append("wait_send")
        self.events.append(
            WaitEv(sem.key(), src_ref.region().elements(), "elem")
        )

    def on_compute(self, kind: str, reads, write: FakeRef) -> None:
        self.signature.append(f"compute:{kind}")
        self.events.append(ComputeEv(
            kind,
            tuple(r.region() for r in reads if isinstance(r, FakeRef)),
            write.region(),
        ))

    # -- barriers (expanded concretely per rank) ----------------------------

    def _barrier_sem_key(self, sem) -> tuple[str, int | None]:
        return (BARRIER_SEM, None) if sem is None else sem.key()

    def on_barrier_all(self, team, sem) -> None:
        """The hub barrier of ``primitives.barrier_all``, expanded for this
        rank (the ``pl.when`` branches become a Python if)."""
        self.signature.append("barrier_all")
        key = self._barrier_sem_key(sem)
        me, n = team.rank(), team.size
        if n == 1:
            return
        if me != 0:
            self.events.append(NotifyEv(key, _as_int(team.device_id(0)), 1))
            self.events.append(WaitEv(key, 1, "count"))
        else:
            self.events.append(WaitEv(key, n - 1, "count"))
            for i in range(n - 1):
                self.events.append(
                    NotifyEv(key, _as_int(team.device_id(i + 1)), 1)
                )

    def on_barrier_neighbors(self, team, sem) -> None:
        self.signature.append("barrier_neighbors")
        key = self._barrier_sem_key(sem)
        if team.size == 1:
            return
        left, right = team.neighbor_ranks()
        self.events.append(NotifyEv(key, _as_int(team.device_id(left)), 1))
        self.events.append(NotifyEv(key, _as_int(team.device_id(right)), 1))
        self.events.append(WaitEv(key, 2, "count"))

    def collapsed_signature(self) -> tuple[str, ...]:
        """Adjacent-duplicate-collapsed op sequence: data-dependent REPEAT
        counts (an all-to-all rank sending more chunks than its neighbor)
        are not divergence; a different op STRUCTURE is."""
        out: list[str] = []
        for s in self.signature:
            if not out or out[-1] != s:
                out.append(s)
        return tuple(out)


def _py_fori_loop(lower, upper, body, init):
    val = init
    for i in range(_as_int(lower), _as_int(upper)):
        val = body(i, val)
    return val


# jax.lax.fori_loop is module state, not thread state, so the patch is
# refcounted under a lock and DISPATCHES per thread: only a thread with an
# active recorder gets the concrete Python loop — a concurrent thread
# tracing real jax (e.g. another builder while TDT_VERIFY verification
# runs) still reaches the original implementation.
_FORI_PATCH_LOCK = threading.Lock()
_FORI_PATCH = {"depth": 0, "orig": None}


def _fori_loop_dispatch(lower, upper, body, init):
    if dl.active_recorder() is None:
        return _FORI_PATCH["orig"](lower, upper, body, init)
    return _py_fori_loop(lower, upper, body, init)


@contextlib.contextmanager
def recording(axes: tuple[tuple[str, int], ...], coords: dict[str, int]):
    """Install a fresh recorder for one rank; yields it.  For the duration,
    ``jax.lax.fori_loop`` routes recorder-active threads to a concrete
    Python loop (see ``_fori_loop_dispatch``)."""
    import jax

    if dl.active_recorder() is not None:
        raise RuntimeError("record mode does not nest")
    rec = KernelRecorder(axes, coords)
    with _FORI_PATCH_LOCK:
        if _FORI_PATCH["depth"] == 0:
            _FORI_PATCH["orig"] = jax.lax.fori_loop
            jax.lax.fori_loop = _fori_loop_dispatch
        _FORI_PATCH["depth"] += 1
    dl._set_recorder(rec)
    try:
        yield rec
    finally:
        dl._set_recorder(None)
        with _FORI_PATCH_LOCK:
            _FORI_PATCH["depth"] -= 1
            if _FORI_PATCH["depth"] == 0:
                jax.lax.fori_loop = _FORI_PATCH["orig"]
                _FORI_PATCH["orig"] = None


def coords_of(axes: tuple[tuple[str, int], ...], rank: int) -> dict[str, int]:
    """Row-major (outermost-first) decomposition of a flat rank index into
    per-axis coordinates — the convention under which the linearized
    logical device id of rank ``r`` equals ``r`` itself, which the
    composed-trace checks and the bounded simulator rely on (they index
    traces by rank and compare against recorded device ids)."""
    coords: dict[str, int] = {}
    rem = int(rank)
    for name, size in reversed(axes):
        coords[name] = rem % size
        rem //= size
    return coords


def record_kernel(thunk, *, n: int, rank: int, axis: str = "tp",
                  axes: tuple[tuple[str, int], ...] | None = None):
    """Record one rank of a collective kernel.  ``thunk`` runs the kernel
    body (fakes already bound); returns the recorder.  ``axes`` selects a
    multi-axis harness mesh (outermost first; e.g. the hierarchical
    two-level cases record over ``(("dcn", n_out), ("tp", n_in))``) with
    ``rank`` decomposed row-major (``coords_of``); the default is the
    single-axis ``(("tp", n),)`` mesh."""
    if axes is None:
        axes = ((axis, n),)
    with recording(axes, coords_of(axes, rank)) as rec:
        thunk()
    return rec
