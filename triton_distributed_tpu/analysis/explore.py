"""Schedule-exhaustive protocol exploration: DPOR over recorded traces.

The canonical maximal execution (``checks._simulate``) is sound for
ENABLEDNESS: credits only accumulate, every pool is consumed by exactly
one rank in program order, so availability at any wait is monotone in
schedule progress and the canonical run stalls iff every interleaving
stalls.  It is NOT sound for the happens-before structure the
write-overlap check consumes: a wait consumes credits in FIFO *arrival*
order, and when a pool is fed by two concurrent producers the arrival
order — hence which transfer each wait SETTLES — depends on the
schedule.  The chained protocols (ISSUE 13's in-kernel re-armed ring
instances, the quantized sidecar messages, the hierarchical DCN credit
models) are exactly the family where per-credit identity carries the
ordering, i.e. where one schedule can witness a safe matching while
another witnesses an un-ACKed slot reuse ("Demystifying NVSHMEM"'s
order-dependent slot reuse / premature credit consumption / ABA class).

This module explores ALL schedules up to Mazurkiewicz-trace equivalence
and re-runs the hazard checks on every explored class.  The reduction
stack (each step proved in terms of the credit-FIFO semantics):

- **independence relation** (the vector-clock model's, made explicit):
  two cross-rank events are dependent iff they PRODUCE into a common
  non-*bulk* pool, or one produces into a pool whose consume is not yet
  enabled.  A *bulk* pool (consumed by at most one balanced wait, or
  never consumed) joins every credit regardless of arrival order.  An
  ALREADY-ENABLED consume commutes with any produce: FIFO hands it the
  same credit prefix either way — and it commutes leftward past any
  prefix of other-rank events, because an executed consume was
  necessarily enabled without any later-arriving credit.  Overlapping
  writes need no dependence edge: the per-schedule vector-clock race
  check is symmetric in the order of unordered writes.
- **persistent-set reduction**: by the above, an enabled event is a
  singleton persistent set — executed eagerly, never a branch point —
  unless it produces into a non-bulk pool into which another rank still
  has produces outstanding (tracked with per-pool suffix counts).  The
  exploration therefore branches ONLY at multi-producer credit races:
  the exact class the canonical schedule cannot decide.
- **sleep sets**: after a branch explores transition ``t``, ``t`` sleeps
  in the subtrees of its later siblings while independent, so each
  equivalence class is counted exactly once.

``preemption_bound`` (the context-switch-bounded mode) caps the number
of *preemptive* switches among branch choices per schedule — switching
away from a rank whose next event could still run.  Eager and forced
switches are free.  Bounded exploration is CHESS-style best-effort
below the bound; ``bound=None`` is the exact mode.  ``max_schedules`` /
``budget_ms`` are hard resource caps; hitting one marks the result
``pruned`` (surfaced by the ``explore_pruned`` obs counter and the
``--dpor`` lint column, never silently).
"""

from __future__ import annotations

import dataclasses
import time
from collections import deque

from .checks import Violation, _Credit, _join, _write_overlap, _Write, \
    sem_label
from .events import ComputeEv, CopyEv, NotifyEv, WaitEv


# ---------------------------------------------------------------------------
# static pool analysis


def _pools_of(ev, rank: int):
    """((pool, mode), ...) for one event: pool = (owner_rank, sem_key),
    mode = "p" (produce) | "c" (consume)."""
    if isinstance(ev, NotifyEv):
        return (((ev.target, ev.sem), "p"),)
    if isinstance(ev, WaitEv):
        return (((rank, ev.sem), "c"),)
    if isinstance(ev, CopyEv):
        out = [((ev.dst_rank, ev.recv_sem), "p")]
        if ev.send_sem is not None:
            out.append(((rank, ev.send_sem), "p"))
        return tuple(out)
    return ()


@dataclasses.dataclass(frozen=True)
class _PoolInfo:
    producers: frozenset      # ranks producing into it
    waits: int                # number of WaitEv consuming it
    produced: int
    consumed: int

    @property
    def bulk(self) -> bool:
        """Arrival order into this pool is unobservable: no wait ever
        consumes it, or a SINGLE balanced wait consumes every credit
        (joining every clock regardless of order) — produces into such
        a pool commute."""
        return self.waits == 0 or \
            (self.waits == 1 and self.produced == self.consumed)


def _pool_table(n: int, traces) -> dict:
    t: dict[tuple, dict] = {}
    for r in range(n):
        for ev in traces[r]:
            for pool, mode in _pools_of(ev, r):
                d = t.setdefault(pool, {"prod": set(), "waits": 0,
                                        "p": 0, "c": 0})
                if mode == "p":
                    d["prod"].add(r)
                    if isinstance(ev, NotifyEv):
                        d["p"] += ev.amount
                    else:  # CopyEv: src elements on send, dst on recv
                        d["p"] += ev.src.elements() \
                            if (pool[0] == r and pool[1] == ev.send_sem) \
                            else ev.dst.elements()
                else:
                    d["waits"] += 1
                    d["c"] += ev.amount
    return {
        pool: _PoolInfo(frozenset(d["prod"]), d["waits"], d["p"], d["c"])
        for pool, d in t.items()
    }


# ---------------------------------------------------------------------------
# the exploration state (with O(1)-amortized undo)

_MISS = object()


class _State:
    def __init__(self, n: int, traces, pools: dict):
        self.n = n
        self.traces = traces
        self.pools = pools
        self.pcs = [0] * n
        self.credits: dict[tuple, deque] = {}
        self.avail: dict[tuple, int] = {}
        self.clocks = [tuple(0 for _ in range(n)) for _ in range(n)]
        self.writes: list[_Write] = []
        self.settle: dict[int, tuple] = {}
        self.next_tid = 0
        self.schedule: list[int] = []
        # suffix produce counts: rem_prod[pool][rank] = produces rank
        # still has outstanding into pool (drives the branch-point test)
        self.rem_prod: dict[tuple, list[int]] = {}
        for r in range(n):
            for ev in traces[r]:
                for pool, mode in _pools_of(ev, r):
                    if mode == "p":
                        self.rem_prod.setdefault(pool, [0] * n)[r] += 1

    def next_ev(self, r: int):
        return self.traces[r][self.pcs[r]] if self.pcs[r] < \
            len(self.traces[r]) else None

    def enabled(self, r: int) -> bool:
        ev = self.next_ev(r)
        if ev is None:
            return False
        if isinstance(ev, WaitEv):
            return self.avail.get((r, ev.sem), 0) >= ev.amount
        return True

    def branches(self, r: int) -> bool:
        """True when rank ``r``'s next (enabled) event is a real branch
        point: it produces into a non-bulk pool into which another rank
        still has produces outstanding — the multi-producer credit race
        whose arrival order the schedule decides.  Everything else is a
        singleton persistent set (see the module docstring)."""
        ev = self.traces[r][self.pcs[r]]
        for pool, mode in _pools_of(ev, r):
            if mode != "p" or self.pools[pool].bulk:
                continue
            rem = self.rem_prod[pool]
            if sum(rem) - rem[r] > 0:
                return True
        return False

    def done(self) -> bool:
        return all(self.pcs[r] >= len(self.traces[r])
                   for r in range(self.n))

    # -- execute/undo -------------------------------------------------------

    def execute(self, r: int):
        """Run rank ``r``'s next event; returns an opaque undo record.

        SEMANTICS CONTRACT: this is the same credit-FIFO execution
        ``checks._simulate`` implements (FIFO consumption, vector-clock
        joins, settle-on-consume), restated with an undo journal so the
        DFS can backtrack.  The one textual difference — ``_simulate``
        settles each credit at the consumer's MID-LOOP clock while this
        settles every consumed credit at the POST-join clock — is
        observationally equivalent whenever no single wait spans
        settle-carrying credits from multiple transfers, which holds
        for every shipped protocol and is pinned byte-for-byte over the
        whole registry by
        ``test_explorer_state_agrees_with_canonical_simulator``; a
        change to either implementation must keep that test green."""
        ev = self.traces[r][self.pcs[r]]
        undo = {"r": r, "clock": self.clocks[r], "tid": self.next_tid,
                "writes": len(self.writes), "cons": None, "adds": []}
        if isinstance(ev, WaitEv):
            need = ev.amount
            pool = (r, ev.sem)
            self.avail[pool] = self.avail.get(pool, 0) - need
            q = self.credits.setdefault(pool, deque())
            consumed = []   # [credit, taken, popped]
            clock = self.clocks[r]
            while need > 0:
                c = q[0]
                take = min(need, c.amount)
                c.amount -= take
                need -= take
                clock = _join(clock, c.clock)
                popped = c.amount == 0
                if popped:
                    q.popleft()
                consumed.append((c, take, popped))
            # settle joins use the POST-join clock (the consumer has
            # observed every landing this wait consumed)
            prev_settles = []
            for c, _take, _popped in consumed:
                if c.settle_tid is not None:
                    prev = self.settle.get(c.settle_tid, _MISS)
                    prev_settles.append((c.settle_tid, prev))
                    self.settle[c.settle_tid] = clock if prev is _MISS \
                        else _join(prev, clock)
            self.clocks[r] = clock
            undo["cons"] = (pool, consumed, prev_settles)
        elif isinstance(ev, NotifyEv):
            self._add(undo, r, (ev.target, ev.sem),
                      _Credit(ev.amount, self.clocks[r], None))
        elif isinstance(ev, CopyEv):
            tid = self.next_tid
            self.next_tid += 1
            if ev.send_sem is not None:
                self._add(undo, r, (r, ev.send_sem),
                          _Credit(ev.src.elements(), self.clocks[r], None))
            self._add(undo, r, (ev.dst_rank, ev.recv_sem),
                      _Credit(ev.dst.elements(), self.clocks[r], tid))
            self.writes.append(_Write(
                ev.dst_rank, ev.dst, self.clocks[r], tid, r,
                "remote_copy" if ev.dst_rank != r else "local_copy",
            ))
        elif isinstance(ev, ComputeEv):
            self.writes.append(_Write(r, ev.write, self.clocks[r], None, r,
                                      f"compute:{ev.kind}"))
        self.pcs[r] += 1
        c = list(self.clocks[r])
        c[r] += 1
        self.clocks[r] = tuple(c)
        self.schedule.append(r)
        return undo

    def _add(self, undo, r, pool, credit):
        self.credits.setdefault(pool, deque()).append(credit)
        self.avail[pool] = self.avail.get(pool, 0) + credit.amount
        self.rem_prod[pool][r] -= 1
        undo["adds"].append((pool, credit.amount))

    def undo(self, undo) -> None:
        r = undo["r"]
        self.schedule.pop()
        self.pcs[r] -= 1
        self.clocks[r] = undo["clock"]
        self.next_tid = undo["tid"]
        del self.writes[undo["writes"]:]
        for pool, amount in reversed(undo["adds"]):
            self.credits[pool].pop()
            self.avail[pool] -= amount
            self.rem_prod[pool][r] += 1
        if undo["cons"] is not None:
            pool, consumed, prev_settles = undo["cons"]
            for tid, prev in prev_settles:
                if prev is _MISS:
                    del self.settle[tid]
                else:
                    self.settle[tid] = prev
            q = self.credits[pool]
            for c, take, popped in reversed(consumed):
                c.amount += take
                self.avail[pool] += take
                if popped:
                    q.appendleft(c)


# ---------------------------------------------------------------------------
# the explorer


@dataclasses.dataclass
class ExploreResult:
    kernel: str
    n: int
    schedules: int                 # complete equivalence classes explored
    violations: list[Violation]
    pruned: bool = False           # a resource cap cut the exploration
    preemption_bound: int | None = None
    witness: tuple[int, ...] | None = None   # rank order of the first
    #                                          violating schedule


class _Explorer:
    def __init__(self, kernel: str, n: int, traces, *,
                 preemption_bound: int | None, max_schedules: int,
                 budget_ms: float | None, stop_on_violation: bool):
        self.kernel, self.n, self.traces = kernel, n, traces
        self.bound = preemption_bound
        self.max_schedules = max_schedules
        self.deadline = None if budget_ms is None else \
            time.monotonic() + budget_ms / 1e3
        self.stop_on_violation = stop_on_violation
        self.pools = _pool_table(n, traces)
        self.state = _State(n, traces, self.pools)
        self.schedules = 0
        self.pruned = False
        self.violations: list[Violation] = []
        self._seen_msgs: set[str] = set()
        self.witness: tuple[int, ...] | None = None

    # -- independence (for sleep-set filtering at branch points) ------------

    def _independent(self, a: int, b: int) -> bool:
        """Are ranks ``a``/``b``'s NEXT events independent in the CURRENT
        state?  Both are enabled branch choices when consulted."""
        eva, evb = self.state.next_ev(a), self.state.next_ev(b)
        pa = dict(_pools_of(eva, a)) if eva is not None else {}
        pb = dict(_pools_of(evb, b)) if evb is not None else {}
        for pool in pa.keys() & pb.keys():
            ma, mb = pa[pool], pb[pool]
            if ma == "p" and mb == "p":
                if not self.pools[pool].bulk:
                    return False
                continue
            # produce vs consume: an ALREADY-ENABLED consume commutes
            # with any produce (FIFO hands it the same credit prefix
            # either way); only the enabling produce is a dependence
            ev_c = eva if ma == "c" else evb
            if self.state.avail.get(pool, 0) >= ev_c.amount:
                continue
            return False
        return True

    # -- per-schedule checks ------------------------------------------------

    def _record_violation(self, v: Violation) -> None:
        if v.message not in self._seen_msgs:
            self._seen_msgs.add(v.message)
            self.violations.append(v)
            if self.witness is None:
                self.witness = tuple(self.state.schedule)

    def _check_complete(self) -> None:
        self.schedules += 1
        st = self.state
        sched = _schedule_label(st.schedule, self.n)
        if not st.done():
            blocked = []
            for r in range(self.n):
                ev = st.next_ev(r)
                if isinstance(ev, WaitEv):
                    blocked.append(
                        f"rank {r} wait({sem_label(ev.sem)}, need "
                        f"{ev.amount}, have "
                        f"{st.avail.get((r, ev.sem), 0)})")
                elif ev is not None:   # pragma: no cover - waits block
                    blocked.append(f"rank {r} stuck at {ev}")
            self._record_violation(Violation(
                "deadlock", self.kernel, self.n,
                f"schedule {sched} deadlocks (a reordering the canonical "
                f"maximal execution does not witness): "
                + "; ".join(blocked)))
            return
        for v in _write_overlap(self.kernel, self.n, st.writes, st.settle):
            self._record_violation(Violation(
                v.check, v.kernel, v.ranks,
                f"under schedule {sched}: {v.message}"))

    # -- search -------------------------------------------------------------

    def _stop(self) -> bool:
        if self.stop_on_violation and self.violations:
            return True
        if self.schedules >= self.max_schedules or (
                self.deadline is not None
                and time.monotonic() > self.deadline):
            self.pruned = True
            return True
        return False

    def run(self) -> None:
        self._explore(frozenset(), None, 0)

    def _advance_eager(self, sleep: frozenset) -> list:
        """Execute every enabled non-branching event (singleton
        persistent sets) until only branch points or blocked ranks
        remain; returns the undo stack.  Slept ranks are never advanced
        (their subtrees are covered by an explored sibling), and eager
        events are provably independent of every enabled sleep member,
        so the sleep set passes through unchanged."""
        st = self.state
        undos = []
        progress = True
        while progress:
            progress = False
            for r in range(self.n):
                if r in sleep:
                    continue
                while st.enabled(r) and not st.branches(r):
                    undos.append(st.execute(r))
                    progress = True
        return undos

    def _explore(self, sleep: frozenset, last: int | None,
                 preemptions: int) -> None:
        if self._stop():
            return
        undos = self._advance_eager(sleep)
        try:
            enabled = [r for r in range(self.n) if self.state.enabled(r)]
            live = [r for r in enabled if r not in sleep]
            if not enabled:
                self._check_complete()
                return
            if not live:
                # every continuation is covered by an explored sibling
                return
            # context-bound: past the budget, stay on the current rank
            # when it can still run (eager/forced switches are free)
            if self.bound is not None and preemptions >= self.bound \
                    and last is not None and last in live:
                live = [last]
            done: list[int] = []
            for r in live:
                if self._stop():
                    return
                cost = preemptions
                if last is not None and r != last and \
                        self.state.enabled(last):
                    cost += 1
                    if self.bound is not None and cost > self.bound:
                        continue
                child_sleep = frozenset(
                    u for u in (*sleep, *done)
                    if self.state.enabled(u) and self._independent(u, r)
                )
                undo = self.state.execute(r)
                self._explore(child_sleep, r, cost)
                self.state.undo(undo)
                done.append(r)
        finally:
            for u in reversed(undos):
                self.state.undo(u)


def _schedule_label(schedule: list[int], n: int, cap: int = 48) -> str:
    """Run-length-compressed rank order, e.g. ``r0*3 r1*2 r0``."""
    runs: list[list[int]] = []
    for r in schedule:
        if runs and runs[-1][0] == r:
            runs[-1][1] += 1
        else:
            runs.append([r, 1])
    parts = [f"r{r}" if k == 1 else f"r{r}*{k}" for r, k in runs]
    if len(parts) > cap:
        parts = parts[:cap] + ["..."]
    return " ".join(parts)


# ---------------------------------------------------------------------------
# entry points


# resource caps for the registry sweep: generous enough that every
# shipped kernel (branch points exist only at multi-producer credit
# races, so most cases explore exhaustively in ONE class) completes,
# tight enough that a pathological case cannot eat the lint budget
DEFAULT_MAX_SCHEDULES = 512
DEFAULT_BUDGET_MS = 2_000.0
DEFAULT_BOUND = 2


def explore(kernel: str, n: int, traces, *,
            preemption_bound: int | None = DEFAULT_BOUND,
            max_schedules: int = DEFAULT_MAX_SCHEDULES,
            budget_ms: float | None = DEFAULT_BUDGET_MS,
            stop_on_violation: bool = True) -> ExploreResult:
    """Explore all schedules of the composed per-rank ``traces`` up to
    equivalence; run deadlock + write-overlap on every explored class.
    ``preemption_bound=None`` is the exact mode."""
    ex = _Explorer(kernel, n, traces,
                   preemption_bound=preemption_bound,
                   max_schedules=max_schedules, budget_ms=budget_ms,
                   stop_on_violation=stop_on_violation)
    ex.run()
    return ExploreResult(kernel, n, ex.schedules, ex.violations,
                         pruned=ex.pruned,
                         preemption_bound=preemption_bound,
                         witness=ex.witness)


def explore_case(case, *, recorded=None, **kw) -> ExploreResult:
    """Record all N ranks of a registry :class:`KernelCase` (or reuse
    ``recorded`` from ``registry.record_case`` — callers that already
    ran the canonical checks share one recording pass) and explore.
    Counters ``explore_schedules`` / ``explore_pruned`` land in the obs
    registry when observability is on."""
    if recorded is not None:
        traces = recorded[0]
    else:
        from .registry import record_case

        traces = record_case(case)[0]
    res = explore(case.name, case.n, traces, **kw)
    from .. import obs

    if obs.enabled():
        obs.counter("explore_schedules",
                    kernel=case.family).inc(res.schedules)
        if res.pruned:
            obs.counter("explore_pruned", kernel=case.family).inc()
    return res


def explore_all(ranks=None, *, kernel_filter: str | None = None,
                **kw) -> list[ExploreResult]:
    """The registry sweep: every kernel case at every rank count, under
    the bounded defaults (``tdt_lint --dpor``)."""
    from .registry import DEFAULT_RANKS, all_cases

    out = []
    for case in all_cases(ranks if ranks is not None else DEFAULT_RANKS):
        if kernel_filter and kernel_filter not in case.name:
            continue
        out.append(explore_case(case, **kw))
    return out
