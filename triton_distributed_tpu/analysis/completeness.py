"""Cross-subsystem completeness lint: every kernel family, fully wired.

A collective family in this codebase is not DONE when its kernel works:
it must be priced (``obs.costs.FAMILY_COSTS`` — the one flop/byte source
the watchdog deadline, Mosaic cost estimate and timeline read), it must
have a degradation story (an ``resilience.fallbacks`` XLA fallback on
the retry ladder, or a DOCUMENTED watchdog-only / rides-the-base-family
status), it must appear in the fault-injection matrix, and its
``collective_id`` must be registered and unique (two in-flight
collectives sharing an id share a Mosaic barrier semaphore).  Until
ISSUE 15 each of those was enforced only by convention — and the
convention had already broken: ``ag_gemm`` shipped five PRs of features
with NO fault-matrix coverage (found by this lint, fixed in the same
PR).

:data:`GOLDEN` pins the full wiring table.  :func:`check` recomputes
the ACTUAL wiring from the live modules and reports every divergence
with the diff as the message — adding a family without full wiring (or
wiring without a golden row) fails loudly in ``tdt_lint
--completeness``.
"""

from __future__ import annotations


# fallback / collective_id values starting with "via:" or
# "watchdog-only:" are DOCUMENTED statuses, verified textually against
# this table rather than against module attributes
GOLDEN: dict[str, dict] = {
    "allgather": {
        "costs": ("allgather",),
        "fallback": "xla_all_gather",
        "faults": ("allgather/push_1shot",),
        "collective_id": 1,
    },
    "reduce_scatter": {
        "costs": ("reduce_scatter",),
        "fallback": "xla_reduce_scatter",
        "faults": ("reduce_scatter/ring",),
        "collective_id": 2,
    },
    "allreduce": {
        "costs": ("allreduce",),
        "fallback": "xla_all_reduce",
        "faults": ("allreduce/two_shot",),
        "collective_id": 3,
    },
    "all_to_all": {
        "costs": ("all_to_all",),
        "fallback": "xla_ep_dispatch",
        "faults": ("all_to_all/dispatch",),
        "collective_id": 4,
    },
    "ag_gemm": {
        "costs": ("ag_gemm",),
        "fallback": "xla_ag_gemm",
        "faults": ("ag_gemm/unidir",),
        "collective_id": 5,
    },
    "gemm_rs": {
        "costs": ("gemm_rs",),
        "fallback": "xla_gemm_rs",
        "faults": ("gemm_rs/ring",),
        "collective_id": 6,
    },
    "gemm_ar": {
        "costs": ("gemm_ar",),
        "fallback": "xla_gemm_ar",
        "faults": ("gemm_ar/ring",),
        "collective_id": 14,
    },
    "fused_mlp_ar": {
        "costs": ("fused_mlp_ar",),
        "fallback": "xla_fused_mlp_ar",
        "faults": ("fused_mlp_ar/swiglu",),
        "collective_id": 16,
    },
    "quantized_wire": {
        "costs": ("quantized_wire",),
        # the quantized variants degrade through the BASE family's XLA
        # fallback with the codec bypassed (comm.quantized rides the
        # eager entries' resilient_call; docs/robustness.md)
        "fallback": "via:base-family XLA fallbacks, codec bypassed",
        "faults": ("quant_allgather/push_1shot", "quant_exchange/oneshot"),
        # packed payloads ride the underlying kernels' collective ids
        "collective_id": "via:underlying families",
    },
    "hierarchical": {
        "costs": ("hier_all_gather", "hier_reduce_scatter",
                  "hier_all_reduce", "hier_all_to_all"),
        # hier entries wrap their cores in resilience.guarded with
        # flat-entry fallbacks; the DCN hop is an XLA collective already
        "fallback": "via:guarded flat-entry fallbacks (DCN hop is XLA)",
        "faults": ("hier_allreduce/2x2", "hier_a2a/2x2"),
        "collective_id": "via:inner-ring families",
    },
    "persistent_decode": {
        "costs": ("persistent_decode",),
        "fallback": "xla_persistent_decode",
        "faults": ("persistent_decode/chain",),
        "collective_id": 17,
    },
}


def _fault_kernel_axis() -> set[str]:
    """Every kernel-case name any fault-matrix slice injects into."""
    from ..resilience import matrix as rmat

    return (set(rmat.DEFAULT_KERNELS) | set(rmat.QUANT_KERNELS)
            | set(rmat.HIER_KERNELS_4) | set(rmat.HIER_KERNELS_8)
            | set(rmat.PERSISTENT_KERNELS))


def check() -> list[str]:
    """Recompute the wiring from the live modules and diff against
    :data:`GOLDEN`; every problem line names the family and the missing
    or drifted piece."""
    from ..core.compilation import _COLLECTIVE_IDS
    from ..obs.costs import FAMILY_COSTS
    from ..resilience import fallbacks
    from .registry import FAMILIES, cases_for

    problems: list[str] = []

    if set(GOLDEN) != set(FAMILIES):
        extra = sorted(set(GOLDEN) - set(FAMILIES))
        missing = sorted(set(FAMILIES) - set(GOLDEN))
        problems.append(
            f"family axis drifted: registry families without a golden "
            f"wiring row {missing}, golden rows without a registry "
            f"family {extra} — new families must land FULLY wired "
            f"(costs + fallback + fault cells + collective_id) and "
            f"pinned here")

    fault_axis = _fault_kernel_axis()
    case_family: dict[str, str] = {}
    for fam in FAMILIES:
        try:
            for n in (2, 4, 8):
                for c in cases_for(fam, n):
                    case_family[c.name] = c.family
        except KeyError:
            problems.append(
                f"{fam}: listed in registry.FAMILIES but has no case "
                f"builder in _FAMILY_CASES — nothing verifies it")
    covered_families = {case_family[k] for k in fault_axis
                        if k in case_family}

    ids_seen: dict[int, str] = {}
    for fam, spec in sorted(GOLDEN.items()):
        # 1) cost calculators
        for key in spec["costs"]:
            if key not in FAMILY_COSTS:
                problems.append(
                    f"{fam}: cost calculator {key!r} missing from "
                    f"obs.costs.FAMILY_COSTS — the watchdog deadline and "
                    f"timeline cannot price this family")
        # 2) degradation story
        fb = spec["fallback"]
        if fb.startswith(("via:", "watchdog-only:")):
            pass   # documented status; the text IS the contract
        elif not hasattr(fallbacks, fb):
            problems.append(
                f"{fam}: resilience fallback {fb!r} not found in "
                f"resilience.fallbacks — the retry ladder has no bottom "
                f"for this family")
        # 3) fault-matrix coverage
        missing_cases = [k for k in spec["faults"] if k not in fault_axis]
        if missing_cases:
            problems.append(
                f"{fam}: golden fault case(s) {missing_cases} not on any "
                f"fault-matrix kernel axis (resilience.matrix)")
        if fam not in covered_families:
            problems.append(
                f"{fam}: NO fault-matrix kernel case covers this family "
                f"— injection coverage is part of shipping a collective")
        # 4) collective id
        cid = spec["collective_id"]
        if isinstance(cid, int):
            actual = _COLLECTIVE_IDS.get(fam)
            if actual != cid:
                problems.append(
                    f"{fam}: collective_id drifted — golden {cid}, "
                    f"core.compilation registers {actual}")
            if cid in ids_seen:
                problems.append(
                    f"{fam}: collective_id {cid} collides with "
                    f"{ids_seen[cid]} — two in-flight collectives would "
                    f"share a Mosaic barrier semaphore")
            ids_seen[cid] = fam
        elif not str(cid).startswith("via:"):
            problems.append(
                f"{fam}: collective_id must be an int or a documented "
                f"'via:' status, got {cid!r}")

    # global id uniqueness (beyond the golden families: the registry in
    # core.compilation must never alias two names onto one id)
    all_ids: dict[int, list[str]] = {}
    for name, cid in _COLLECTIVE_IDS.items():
        all_ids.setdefault(cid, []).append(name)
    for cid, names in sorted(all_ids.items()):
        if len(names) > 1:
            problems.append(
                f"collective_id {cid} registered for multiple families: "
                f"{sorted(names)}")
    problems.extend(check_lifecycle_coverage())
    problems.extend(check_fleet_coverage())
    problems.extend(check_decision_coverage())
    return problems


def check_lifecycle_coverage() -> list[str]:
    """The page-lifetime wiring row: every live ``RequestState`` and
    every ``HandoffFault`` class must have a documented lifecycle-
    coverage entry in ``pages.LIFECYCLE_COVERAGE`` (how the page checker
    exercises that state's alloc/free path), and no coverage entry may
    name a state or fault class that no longer exists.  A new request
    state or handoff fault landing without a page-ownership story is
    exactly the leak-on-abort shape the checker exists to rule out."""
    from ..serve.handoff import HandoffFault
    from ..serve.queue import RequestState
    from .pages import LIFECYCLE_COVERAGE

    problems: list[str] = []
    live_states = {s.name for s in RequestState}
    golden_states = set(LIFECYCLE_COVERAGE["request_states"])
    for name in sorted(live_states - golden_states):
        problems.append(
            f"RequestState.{name}: no page-lifecycle coverage entry in "
            f"analysis.pages.LIFECYCLE_COVERAGE — a request state "
            f"without a documented alloc/free story is an unchecked "
            f"leak path")
    for name in sorted(golden_states - live_states):
        problems.append(
            f"lifecycle coverage names RequestState.{name} which no "
            f"longer exists — prune the stale row")
    live_faults = {f.value for f in HandoffFault}
    golden_faults = set(LIFECYCLE_COVERAGE["handoff_faults"])
    for name in sorted(live_faults - golden_faults):
        problems.append(
            f"HandoffFault {name!r}: no page-lifecycle coverage entry "
            f"in analysis.pages.LIFECYCLE_COVERAGE — a wire fault "
            f"class without a both-tier page-return story is an "
            f"unchecked leak path")
    for name in sorted(golden_faults - live_faults):
        problems.append(
            f"lifecycle coverage names handoff fault {name!r} which no "
            f"longer exists — prune the stale row")
    return problems


def check_decision_coverage() -> list[str]:
    """The control-decision wiring row (ISSUE 19): the ledger's typed
    kind axis (``obs.decisions.DECISION_KINDS`` — kind -> the
    ``FleetRouter`` method(s) recording it) diffed BOTH directions
    against the live actuation sites, found by AST over the router's
    source: every ``self._decide("<kind>", ...)`` call, keyed by its
    enclosing method.  An actuation added without a ledger emit (or a
    golden row whose site vanished) fails with the diff as the message
    — the flight recorder must never silently lose a decision class."""
    import ast
    import inspect

    from ..obs.decisions import DECISION_KINDS
    from ..serve.fleet import FleetRouter

    problems: list[str] = []
    try:
        tree = ast.parse(inspect.getsource(FleetRouter))
    except (OSError, TypeError) as e:
        return [f"decision coverage: cannot read FleetRouter source "
                f"({e}) — the actuation-site diff is undischarged"]

    live: set[tuple[str, str]] = set()
    non_literal: list[str] = []
    for method in ast.walk(tree):
        if not isinstance(method, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(method):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "_decide"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                live.add((node.args[0].value, method.name))
            else:
                non_literal.append(method.name)
    for m in non_literal:
        problems.append(
            f"decision coverage: FleetRouter.{m} calls _decide with a "
            f"non-literal kind — the static diff cannot type it; use a "
            f"string literal from DECISION_KINDS")

    golden: set[tuple[str, str]] = {
        (kind, m) for kind, methods in DECISION_KINDS.items()
        for m in methods
    }
    for kind, m in sorted(live - golden):
        problems.append(
            f"decision coverage: FleetRouter.{m} records decision kind "
            f"{kind!r} with no DECISION_KINDS golden row — a new "
            f"actuation class must land typed (obs.decisions)")
    for kind, m in sorted(golden - live):
        problems.append(
            f"decision coverage: DECISION_KINDS pins {kind!r} emitted "
            f"from FleetRouter.{m}, but no such actuation site exists "
            f"— the controller changed without its flight recorder")
    return problems


def check_fleet_coverage() -> list[str]:
    """The fleet-tier wiring row: every live ``serve.fleet.FleetFault``
    class must have a golden matrix cell in
    ``resilience.matrix.FLEET_GOLDEN`` (which leg exercises it and the
    pinned detected/survived outcome), and no golden row may name a
    fault class that no longer exists.  A new fleet fault landing
    without a matrix cell is a membership-change path the fault drills
    never rehearse."""
    from ..resilience.matrix import FLEET_GOLDEN
    from ..serve.fleet import FleetFault

    problems: list[str] = []
    live = {f.value for f in FleetFault}
    golden = set(FLEET_GOLDEN)
    for name in sorted(live - golden):
        problems.append(
            f"FleetFault {name!r}: no FLEET_GOLDEN matrix row in "
            f"resilience.matrix — a fleet fault class without a "
            f"rehearsed cell is an undrilled membership change")
    for name in sorted(golden - live):
        problems.append(
            f"FLEET_GOLDEN names fleet fault {name!r} which no longer "
            f"exists — prune the stale row")
    for name, row in sorted(FLEET_GOLDEN.items()):
        if row.get("outcome") not in ("detected", "survived"):
            problems.append(
                f"FLEET_GOLDEN[{name!r}]: outcome must be "
                f"'detected' or 'survived', got {row.get('outcome')!r}")
    return problems
