"""Cross-subsystem completeness lint: every kernel family, fully wired.

A collective family in this codebase is not DONE when its kernel works:
it must be priced (``obs.costs.FAMILY_COSTS`` — the one flop/byte source
the watchdog deadline, Mosaic cost estimate and timeline read), it must
have a degradation story (an ``resilience.fallbacks`` XLA fallback on
the retry ladder, or a DOCUMENTED watchdog-only / rides-the-base-family
status), it must appear in the fault-injection matrix, and its
``collective_id`` must be registered and unique (two in-flight
collectives sharing an id share a Mosaic barrier semaphore).  Until
ISSUE 15 each of those was enforced only by convention — and the
convention had already broken: ``ag_gemm`` shipped five PRs of features
with NO fault-matrix coverage (found by this lint, fixed in the same
PR).

:data:`GOLDEN` pins the full wiring table.  :func:`check` recomputes
the ACTUAL wiring from the live modules and reports every divergence
with the diff as the message — adding a family without full wiring (or
wiring without a golden row) fails loudly in ``tdt_lint
--completeness``.
"""

from __future__ import annotations


# fallback / collective_id values starting with "via:" or
# "watchdog-only:" are DOCUMENTED statuses, verified textually against
# this table rather than against module attributes
GOLDEN: dict[str, dict] = {
    "allgather": {
        "costs": ("allgather",),
        "fallback": "xla_all_gather",
        "faults": ("allgather/push_1shot",),
        "collective_id": 1,
    },
    "reduce_scatter": {
        "costs": ("reduce_scatter",),
        "fallback": "xla_reduce_scatter",
        "faults": ("reduce_scatter/ring",),
        "collective_id": 2,
    },
    "allreduce": {
        "costs": ("allreduce",),
        "fallback": "xla_all_reduce",
        "faults": ("allreduce/two_shot",),
        "collective_id": 3,
    },
    "all_to_all": {
        "costs": ("all_to_all",),
        "fallback": "xla_ep_dispatch",
        "faults": ("all_to_all/dispatch",),
        "collective_id": 4,
    },
    "ag_gemm": {
        "costs": ("ag_gemm",),
        "fallback": "xla_ag_gemm",
        "faults": ("ag_gemm/unidir",),
        "collective_id": 5,
    },
    "gemm_rs": {
        "costs": ("gemm_rs",),
        "fallback": "xla_gemm_rs",
        "faults": ("gemm_rs/ring",),
        "collective_id": 6,
    },
    "gemm_ar": {
        "costs": ("gemm_ar",),
        "fallback": "xla_gemm_ar",
        "faults": ("gemm_ar/ring",),
        "collective_id": 14,
    },
    "fused_mlp_ar": {
        "costs": ("fused_mlp_ar",),
        "fallback": "xla_fused_mlp_ar",
        "faults": ("fused_mlp_ar/swiglu",),
        "collective_id": 16,
    },
    "quantized_wire": {
        "costs": ("quantized_wire",),
        # the quantized variants degrade through the BASE family's XLA
        # fallback with the codec bypassed (comm.quantized rides the
        # eager entries' resilient_call; docs/robustness.md)
        "fallback": "via:base-family XLA fallbacks, codec bypassed",
        "faults": ("quant_allgather/push_1shot", "quant_exchange/oneshot"),
        # packed payloads ride the underlying kernels' collective ids
        "collective_id": "via:underlying families",
    },
    "hierarchical": {
        "costs": ("hier_all_gather", "hier_reduce_scatter",
                  "hier_all_reduce", "hier_all_to_all"),
        # hier entries wrap their cores in resilience.guarded with
        # flat-entry fallbacks; the DCN hop is an XLA collective already
        "fallback": "via:guarded flat-entry fallbacks (DCN hop is XLA)",
        "faults": ("hier_allreduce/2x2", "hier_a2a/2x2"),
        "collective_id": "via:inner-ring families",
    },
    "persistent_decode": {
        "costs": ("persistent_decode",),
        "fallback": "xla_persistent_decode",
        "faults": ("persistent_decode/chain",),
        "collective_id": 17,
    },
}


# -- direction-coverage golden (ISSUE 20) -----------------------------------
#
# Every metric that falls to obs.history's deliberate throughput-default
# catch-all ("higher is better") must be listed here BY INTENT (exact
# name or prefix).  A new bench metric landing on the catch-all without
# a row fails `tdt_lint --regress`: either it really is a
# higher-is-better rate (add the row) or it needed a named rule
# (latency / overhead / failure-pressure / ...) and silently got the
# wrong trend direction — the exact drift class the sentinel exists to
# catch.  Dead rows (matching no live metric) fail too.
DEFAULT_HIGHER_OK: tuple = (
    "single_chip_gemm",            # TFLOP/s
    "ag_gemm_",                    # TFLOP/s/chip
    "flash_attn_",                 # TFLOP/s
    "tp_mlp_",                     # TFLOP/s/chip
    "group_gemm_",                 # TFLOP/s
    "decode_attn_",                # GB/s
    "decode_step_dispatches",      # "x fewer dispatches" ratio
    "serve_kv_quant_concurrency",  # "x concurrent sequences" ratio
    "serve_tokens_per_s_saturated",
    "handoff_pages_per_s",
    "overlap_hidden_pct",          # fraction of smaller phase hidden
    "wire_bytes_ratio_bf16_over_quant",   # "x fewer wire bytes"
    # the two vs-bound ratios below ride the catch-all since their
    # first commit; pinned here as-is — re-pointing them at a
    # lower-is-better rule is a deliberate trend-direction change, not
    # a side effect of adding a metric
    "wire_dequant_parity_err_ratio",
    "hier_ar_dcn_bytes_ratio",
)

# Live fleet window-total gauges that classify under the
# control-plane-pressure rule (obs.history.DIRECTION_RULES names them
# in its comment; they carry no unit).  Diffed both directions against
# the fleet_stats source in check_direction_coverage.
WINDOW_METRICS: tuple = (
    "fleet_decision_rate",
    "fleet_role_skew",
    "fleet_occupancy_spread",
)


def _fault_kernel_axis() -> set[str]:
    """Every kernel-case name any fault-matrix slice injects into."""
    from ..resilience import matrix as rmat

    return (set(rmat.DEFAULT_KERNELS) | set(rmat.QUANT_KERNELS)
            | set(rmat.HIER_KERNELS_4) | set(rmat.HIER_KERNELS_8)
            | set(rmat.PERSISTENT_KERNELS))


def check() -> list[str]:
    """Recompute the wiring from the live modules and diff against
    :data:`GOLDEN`; every problem line names the family and the missing
    or drifted piece."""
    from ..core.compilation import _COLLECTIVE_IDS
    from ..obs.costs import FAMILY_COSTS
    from ..resilience import fallbacks
    from .registry import FAMILIES, cases_for

    problems: list[str] = []

    if set(GOLDEN) != set(FAMILIES):
        extra = sorted(set(GOLDEN) - set(FAMILIES))
        missing = sorted(set(FAMILIES) - set(GOLDEN))
        problems.append(
            f"family axis drifted: registry families without a golden "
            f"wiring row {missing}, golden rows without a registry "
            f"family {extra} — new families must land FULLY wired "
            f"(costs + fallback + fault cells + collective_id) and "
            f"pinned here")

    fault_axis = _fault_kernel_axis()
    case_family: dict[str, str] = {}
    for fam in FAMILIES:
        try:
            for n in (2, 4, 8):
                for c in cases_for(fam, n):
                    case_family[c.name] = c.family
        except KeyError:
            problems.append(
                f"{fam}: listed in registry.FAMILIES but has no case "
                f"builder in _FAMILY_CASES — nothing verifies it")
    covered_families = {case_family[k] for k in fault_axis
                        if k in case_family}

    ids_seen: dict[int, str] = {}
    for fam, spec in sorted(GOLDEN.items()):
        # 1) cost calculators
        for key in spec["costs"]:
            if key not in FAMILY_COSTS:
                problems.append(
                    f"{fam}: cost calculator {key!r} missing from "
                    f"obs.costs.FAMILY_COSTS — the watchdog deadline and "
                    f"timeline cannot price this family")
        # 2) degradation story
        fb = spec["fallback"]
        if fb.startswith(("via:", "watchdog-only:")):
            pass   # documented status; the text IS the contract
        elif not hasattr(fallbacks, fb):
            problems.append(
                f"{fam}: resilience fallback {fb!r} not found in "
                f"resilience.fallbacks — the retry ladder has no bottom "
                f"for this family")
        # 3) fault-matrix coverage
        missing_cases = [k for k in spec["faults"] if k not in fault_axis]
        if missing_cases:
            problems.append(
                f"{fam}: golden fault case(s) {missing_cases} not on any "
                f"fault-matrix kernel axis (resilience.matrix)")
        if fam not in covered_families:
            problems.append(
                f"{fam}: NO fault-matrix kernel case covers this family "
                f"— injection coverage is part of shipping a collective")
        # 4) collective id
        cid = spec["collective_id"]
        if isinstance(cid, int):
            actual = _COLLECTIVE_IDS.get(fam)
            if actual != cid:
                problems.append(
                    f"{fam}: collective_id drifted — golden {cid}, "
                    f"core.compilation registers {actual}")
            if cid in ids_seen:
                problems.append(
                    f"{fam}: collective_id {cid} collides with "
                    f"{ids_seen[cid]} — two in-flight collectives would "
                    f"share a Mosaic barrier semaphore")
            ids_seen[cid] = fam
        elif not str(cid).startswith("via:"):
            problems.append(
                f"{fam}: collective_id must be an int or a documented "
                f"'via:' status, got {cid!r}")

    # global id uniqueness (beyond the golden families: the registry in
    # core.compilation must never alias two names onto one id)
    all_ids: dict[int, list[str]] = {}
    for name, cid in _COLLECTIVE_IDS.items():
        all_ids.setdefault(cid, []).append(name)
    for cid, names in sorted(all_ids.items()):
        if len(names) > 1:
            problems.append(
                f"collective_id {cid} registered for multiple families: "
                f"{sorted(names)}")
    problems.extend(check_lifecycle_coverage())
    problems.extend(check_fleet_coverage())
    problems.extend(check_decision_coverage())
    problems.extend(check_direction_coverage())
    return problems


def _bench_metric_pairs() -> tuple[set, list]:
    """Statically harvest every ``(metric, unit)`` pair ``bench.py``
    can emit: result-dict literals whose ``metric`` slot is a string or
    f-string constant (format fields become a digit placeholder — the
    direction rules key on unit text and name substrings, never on the
    shape numbers), plus ``*_record("name", ...)`` call sites whose
    helper's result dict carries the unit but a non-literal name."""
    import ast
    import os

    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    with open(os.path.join(root, "bench.py")) as f:
        tree = ast.parse(f.read())

    pairs: set[tuple[str, str]] = set()
    problems: list[str] = []
    helper_units: dict[str, str] = {}   # helper fn -> its literal unit

    def slots(node: "ast.Dict") -> dict:
        return {k.value: v for k, v in zip(node.keys, node.values)
                if isinstance(k, ast.Constant)
                and isinstance(k.value, str)}

    for fn in tree.body:
        if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, ast.Dict):
                continue
            sl = slots(node)
            if "metric" not in sl or "unit" not in sl:
                continue
            uv = sl["unit"]
            if not (isinstance(uv, ast.Constant)
                    and isinstance(uv.value, str)):
                problems.append(
                    f"bench.py:{node.lineno}: result dict has a "
                    f"non-literal unit — the static direction diff "
                    f"cannot type it")
                continue
            mv = sl["metric"]
            if isinstance(mv, ast.Constant) and isinstance(mv.value, str):
                pairs.add((mv.value, uv.value))
            elif isinstance(mv, ast.JoinedStr):
                name = "".join(
                    p.value if isinstance(p, ast.Constant) else "0"
                    for p in mv.values)
                pairs.add((name, uv.value))
            else:
                # "metric": <variable> — a record helper; its call
                # sites supply the literal names (below), committed
                # rounds supply any locally-computed ones
                helper_units[fn.name] = uv.value
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call) and isinstance(node.func, ast.Name)
                and node.func.id in helper_units and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            pairs.add((node.args[0].value, helper_units[node.func.id]))
    return pairs, problems


def check_direction_coverage() -> list[str]:
    """The trend-direction wiring row (ISSUE 20): every metric
    ``bench.py`` can emit (static harvest + committed rounds) must
    classify under a named ``obs.history.DIRECTION_RULES`` row, with
    the deliberate throughput-default catch-all gated by the
    :data:`DEFAULT_HIGHER_OK` golden — and the diff runs BOTH
    directions: a rule no live metric exercises is dead, an allowlist
    row no metric matches is stale, and :data:`WINDOW_METRICS` (the
    unit-less fleet gauges the control-plane-pressure rule names) is
    pinned against the live ``fleet_stats`` source."""
    import ast
    import inspect
    import os

    from ..obs import fleet_stats, history

    pairs, problems = _bench_metric_pairs()

    root = os.path.dirname(os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))))
    try:
        for name, tr in history.trajectories(
                history.load_rounds(root)).items():
            pairs.add((name, tr.unit))
    except Exception as e:
        problems.append(f"direction coverage: committed rounds "
                        f"unreadable ({e})")

    # the fleet window gauges, pinned both directions against source:
    # every live "fleet_*" string constant the control-plane rule would
    # claim must have a WINDOW_METRICS row, and vice versa
    try:
        src = ast.parse(inspect.getsource(fleet_stats.FleetStats))
        live_fleet = {n.value for n in ast.walk(src)
                      if isinstance(n, ast.Constant)
                      and isinstance(n.value, str)
                      and n.value.startswith("fleet_")}
    except (OSError, TypeError) as e:
        live_fleet = set(WINDOW_METRICS)
        problems.append(f"direction coverage: cannot read FleetStats "
                        f"source ({e}) — the gauge pin is undischarged")
    ctl = {n for n in live_fleet
           if any(tok in n for tok in ("decision_rate", "skew",
                                       "spread"))}
    for n in sorted(ctl - set(WINDOW_METRICS)):
        problems.append(
            f"fleet gauge {n!r} classifies under control-plane-pressure "
            f"but has no WINDOW_METRICS row — pin the new gauge")
    for n in sorted(set(WINDOW_METRICS) - ctl):
        problems.append(
            f"WINDOW_METRICS pins {n!r} which no longer exists in "
            f"fleet_stats (or stopped matching the rule) — prune or "
            f"re-point the row")
    pairs |= {(m, "") for m in WINDOW_METRICS}

    used_rules: set[str] = set()
    default_names: set[str] = set()
    for name, unit in sorted(pairs):
        rule_id, _direction = history.classify_direction(name, unit)
        used_rules.add(rule_id)
        if rule_id != "throughput-default":
            continue
        default_names.add(name)
        if not any(name.startswith(p) for p in DEFAULT_HIGHER_OK):
            problems.append(
                f"metric {name!r} (unit {unit!r}) falls to the "
                f"throughput-default catch-all with no "
                f"DEFAULT_HIGHER_OK row — classify its trend "
                f"direction deliberately (a latency/overhead/pressure "
                f"metric here gets 'higher is better' silently)")
    for rule_id, _dir, _pred in history.DIRECTION_RULES:
        if rule_id not in used_rules:
            problems.append(
                f"direction rule {rule_id!r} classifies no live metric "
                f"— dead row in obs.history.DIRECTION_RULES")
    for prefix in DEFAULT_HIGHER_OK:
        if not any(n.startswith(prefix) for n in default_names):
            problems.append(
                f"DEFAULT_HIGHER_OK row {prefix!r} matches no metric "
                f"on the catch-all — stale allowlist entry")
    return problems


def check_lifecycle_coverage() -> list[str]:
    """The page-lifetime wiring row: every live ``RequestState`` and
    every ``HandoffFault`` class must have a documented lifecycle-
    coverage entry in ``pages.LIFECYCLE_COVERAGE`` (how the page checker
    exercises that state's alloc/free path), and no coverage entry may
    name a state or fault class that no longer exists.  A new request
    state or handoff fault landing without a page-ownership story is
    exactly the leak-on-abort shape the checker exists to rule out."""
    from ..serve.handoff import HandoffFault
    from ..serve.queue import RequestState
    from .pages import LIFECYCLE_COVERAGE

    problems: list[str] = []
    live_states = {s.name for s in RequestState}
    golden_states = set(LIFECYCLE_COVERAGE["request_states"])
    for name in sorted(live_states - golden_states):
        problems.append(
            f"RequestState.{name}: no page-lifecycle coverage entry in "
            f"analysis.pages.LIFECYCLE_COVERAGE — a request state "
            f"without a documented alloc/free story is an unchecked "
            f"leak path")
    for name in sorted(golden_states - live_states):
        problems.append(
            f"lifecycle coverage names RequestState.{name} which no "
            f"longer exists — prune the stale row")
    live_faults = {f.value for f in HandoffFault}
    golden_faults = set(LIFECYCLE_COVERAGE["handoff_faults"])
    for name in sorted(live_faults - golden_faults):
        problems.append(
            f"HandoffFault {name!r}: no page-lifecycle coverage entry "
            f"in analysis.pages.LIFECYCLE_COVERAGE — a wire fault "
            f"class without a both-tier page-return story is an "
            f"unchecked leak path")
    for name in sorted(golden_faults - live_faults):
        problems.append(
            f"lifecycle coverage names handoff fault {name!r} which no "
            f"longer exists — prune the stale row")
    return problems


def check_decision_coverage() -> list[str]:
    """The control-decision wiring row (ISSUE 19): the ledger's typed
    kind axis (``obs.decisions.DECISION_KINDS`` — kind -> the
    ``FleetRouter`` method(s) recording it) diffed BOTH directions
    against the live actuation sites, found by AST over the router's
    source: every ``self._decide("<kind>", ...)`` call, keyed by its
    enclosing method.  An actuation added without a ledger emit (or a
    golden row whose site vanished) fails with the diff as the message
    — the flight recorder must never silently lose a decision class."""
    import ast
    import inspect

    from ..obs.decisions import DECISION_KINDS
    from ..serve.fleet import FleetRouter

    problems: list[str] = []
    try:
        tree = ast.parse(inspect.getsource(FleetRouter))
    except (OSError, TypeError) as e:
        return [f"decision coverage: cannot read FleetRouter source "
                f"({e}) — the actuation-site diff is undischarged"]

    live: set[tuple[str, str]] = set()
    non_literal: list[str] = []
    for method in ast.walk(tree):
        if not isinstance(method, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
            continue
        for node in ast.walk(method):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr == "_decide"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self"):
                continue
            if node.args and isinstance(node.args[0], ast.Constant) \
                    and isinstance(node.args[0].value, str):
                live.add((node.args[0].value, method.name))
            else:
                non_literal.append(method.name)
    for m in non_literal:
        problems.append(
            f"decision coverage: FleetRouter.{m} calls _decide with a "
            f"non-literal kind — the static diff cannot type it; use a "
            f"string literal from DECISION_KINDS")

    golden: set[tuple[str, str]] = {
        (kind, m) for kind, methods in DECISION_KINDS.items()
        for m in methods
    }
    for kind, m in sorted(live - golden):
        problems.append(
            f"decision coverage: FleetRouter.{m} records decision kind "
            f"{kind!r} with no DECISION_KINDS golden row — a new "
            f"actuation class must land typed (obs.decisions)")
    for kind, m in sorted(golden - live):
        problems.append(
            f"decision coverage: DECISION_KINDS pins {kind!r} emitted "
            f"from FleetRouter.{m}, but no such actuation site exists "
            f"— the controller changed without its flight recorder")
    return problems


def check_fleet_coverage() -> list[str]:
    """The fleet-tier wiring row: every live ``serve.fleet.FleetFault``
    class must have a golden matrix cell in
    ``resilience.matrix.FLEET_GOLDEN`` (which leg exercises it and the
    pinned detected/survived outcome), and no golden row may name a
    fault class that no longer exists.  A new fleet fault landing
    without a matrix cell is a membership-change path the fault drills
    never rehearse."""
    from ..resilience.matrix import FLEET_GOLDEN
    from ..serve.fleet import FleetFault

    problems: list[str] = []
    live = {f.value for f in FleetFault}
    golden = set(FLEET_GOLDEN)
    for name in sorted(live - golden):
        problems.append(
            f"FleetFault {name!r}: no FLEET_GOLDEN matrix row in "
            f"resilience.matrix — a fleet fault class without a "
            f"rehearsed cell is an undrilled membership change")
    for name in sorted(golden - live):
        problems.append(
            f"FLEET_GOLDEN names fleet fault {name!r} which no longer "
            f"exists — prune the stale row")
    for name, row in sorted(FLEET_GOLDEN.items()):
        if row.get("outcome") not in ("detected", "survived"):
            problems.append(
                f"FLEET_GOLDEN[{name!r}]: outcome must be "
                f"'detected' or 'survived', got {row.get('outcome')!r}")
    return problems
