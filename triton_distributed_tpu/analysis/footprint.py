"""Symbolic resource-footprint calculator per (kernel family x tile config).

Second leg of the ISSUE-15 verification upgrade, same layer as the DPOR
explorer: a kernel whose protocol verifies clean can still be
UNBUILDABLE — its tile config oversubscribes VMEM (Mosaic refuses or
spills), or its semaphore array count silently grows past what the
scratch shapes allocate.  This module computes, from the same block
shapes the builders use, a static :class:`Footprint` per (family x
config):

- ``vmem_bytes``: the explicit VMEM scratch (f32 accumulators, KV page
  double buffers) plus the ``emit_pipeline`` double-buffered block
  working set — two live copies of every in/out block, the pipeline's
  overlap invariant (``ops.blocks``);
- ``hbm_scratch_bytes``: HBM/ANY scratch buffers (ring slot/staging
  arrays);
- ``smem_bytes``: scalar-prefetch operands (SMEM);
- ``dma_sems`` / ``regular_sems``: semaphore counts — derivable
  independently from a RECORDED trace (:func:`sems_of_case`), so the
  calculator and the protocol recorder cross-check each other.

Validation compares ``vmem_bytes`` against the budget the config
requests (``config.vmem_limit`` when the family has the knob, else
Mosaic's default scoped budget, ``core.compilation``); the requested
budget must itself fit the physical VMEM.  Consumers:

- the autotuner prunes statically-infeasible candidates BEFORE
  measuring (``tune.autotuner.prune_infeasible`` — an infeasible
  candidate costs a compile attempt + an interleaved timing slot, and
  on multi-process sweeps a per-rank build failure is fatal by
  contract), counted by ``footprint_rejections``;
- ``tdt_lint --completeness`` flags any family whose DEFAULT config
  oversubscribes at its representative serving shape
  (:func:`check_defaults`).
"""

from __future__ import annotations

import dataclasses


def _ib(dtype) -> int:
    import jax.numpy as jnp

    return int(jnp.dtype(dtype).itemsize)


@dataclasses.dataclass(frozen=True)
class Footprint:
    """Static per-device resource footprint of one kernel invocation."""

    vmem_bytes: int
    hbm_scratch_bytes: int = 0
    smem_bytes: int = 0
    dma_sems: int = 0
    regular_sems: int = 0

    @property
    def sems(self) -> int:
        return self.dma_sems + self.regular_sems

    def __add__(self, other: "Footprint") -> "Footprint":
        return Footprint(
            self.vmem_bytes + other.vmem_bytes,
            self.hbm_scratch_bytes + other.hbm_scratch_bytes,
            self.smem_bytes + other.smem_bytes,
            self.dma_sems + other.dma_sems,
            self.regular_sems + other.regular_sems,
        )


# ---------------------------------------------------------------------------
# pipeline working sets (ops.blocks factories: every in/out block is
# double-buffered so the next block's DMA rides under the current
# block's compute)


def matmul_pipeline_bytes(bm: int, bn: int, bk: int, dtype,
                          out_dtype=None) -> int:
    ib, ob = _ib(dtype), _ib(out_dtype if out_dtype is not None else dtype)
    return 2 * (bm * bk + bk * bn) * ib + 2 * bm * bn * ob


def add_pipeline_bytes(bm: int, bn: int, dtype) -> int:
    """a + b -> out blockwise (the travelling-partial add)."""
    return 2 * 3 * bm * bn * _ib(dtype)


def sum_pipeline_bytes(n_in: int, bm: int, bn: int, dtype) -> int:
    """n_in slots summed into one output (one-shot AllReduce)."""
    return 2 * (n_in + 1) * bm * bn * _ib(dtype)


def _acc(bm: int, bn: int) -> int:
    return bm * bn * 4     # (bm, bn) f32 accumulator scratch


# ---------------------------------------------------------------------------
# per-family calculators (dims = the builders' per-device shapes)


def matmul(cfg, m: int, n: int, k: int, dtype, out_dtype=None) -> Footprint:
    """Plain blocked matmul.  ``cfg``: (bm, bn, bk[, vmem_limit]) tile
    tuple or an object with .bm/.bn/.bk."""
    bm, bn, bk = _tile3(cfg)
    return Footprint(
        vmem_bytes=_acc(bm, bn)
        + matmul_pipeline_bytes(bm, bn, bk, dtype, out_dtype),
    )


def ag_gemm(cfg, m_loc: int, k: int, n_loc: int, num_ranks: int, dtype,
            out_dtype=None, *, bidir: bool = True) -> Footprint:
    return Footprint(
        vmem_bytes=_acc(cfg.bm, cfg.bn)
        + matmul_pipeline_bytes(cfg.bm, cfg.bn, cfg.bk, dtype, out_dtype),
        dma_sems=1 + (2 if bidir else 1) + num_ranks,
    )


def gemm_rs(cfg, m_loc: int, k_loc: int, n_dim: int, num_ranks: int,
            dtype, out_dtype=None) -> Footprint:
    ob = _ib(out_dtype if out_dtype is not None else dtype)
    return Footprint(
        vmem_bytes=_acc(cfg.bm, cfg.bn)
        + matmul_pipeline_bytes(cfg.bm, cfg.bn, cfg.bk, dtype, out_dtype)
        + add_pipeline_bytes(cfg.bm, cfg.bn, out_dtype or dtype),
        hbm_scratch_bytes=3 * 2 * m_loc * n_dim * ob,   # mm/recv/send slots
        dma_sems=2 + 2,
        regular_sems=2,
    )


def gemm_ar(cfg, m_loc: int, k_loc: int, n_dim: int, num_ranks: int,
            dtype, out_dtype=None) -> Footprint:
    base = gemm_rs(cfg, m_loc, k_loc, n_dim, num_ranks, dtype, out_dtype)
    return base + Footprint(vmem_bytes=0, dma_sems=1 + num_ranks)


def allreduce(cfg, m: int, r: int, num_ranks: int, dtype, *,
              method: str = "two_shot") -> Footprint:
    ib = _ib(dtype)
    if method == "one_shot":
        return Footprint(
            vmem_bytes=sum_pipeline_bytes(num_ranks, cfg.bm, cfg.bn, dtype),
            hbm_scratch_bytes=num_ranks * m * r * ib,
            dma_sems=1 + 1 + num_ranks,
        )
    m_chunk = max(m // max(num_ranks, 1), 1)
    return Footprint(
        vmem_bytes=add_pipeline_bytes(cfg.bm, cfg.bn, dtype),
        hbm_scratch_bytes=2 * 2 * m_chunk * r * ib,     # recv + send parity
        dma_sems=2 + 2 + 1 + num_ranks,                 # rs pair + ag pair
        regular_sems=2,
    )


def reduce_scatter(cfg, m: int, r: int, num_ranks: int, dtype) -> Footprint:
    ib = _ib(dtype)
    m_loc = max(m // max(num_ranks, 1), 1)
    return Footprint(
        vmem_bytes=add_pipeline_bytes(cfg.bm, cfg.bn, dtype),
        hbm_scratch_bytes=2 * 2 * m_loc * r * ib,
        dma_sems=2 + 2,
        regular_sems=2,
    )


def all_to_all(cfg, t: int, h: int, num_ranks: int, dtype) -> Footprint:
    """Pure-DMA push kernel: no pipeline working set; three (n,) int32
    scalar-prefetch rows (counts/offs/expected) ride SMEM."""
    return Footprint(
        vmem_bytes=0,
        smem_bytes=3 * num_ranks * 4,
        dma_sems=1 + num_ranks,
    )


def fused_mlp_ar(cfg, b: int, k_in: int, k_loc: int, n_dim: int,
                 num_ranks: int, dtype, out_dtype=None, *,
                 swiglu: bool = True) -> Footprint:
    ob = _ib(out_dtype if out_dtype is not None else dtype)
    cn = max(n_dim // max(num_ranks, 1), 1)
    vmem = _acc(cfg.bm, cfg.bn) \
        + matmul_pipeline_bytes(cfg.bm, cfg.bn, cfg.bk, dtype, out_dtype) \
        + add_pipeline_bytes(cfg.bm, cfg.bn, out_dtype or dtype)
    hbm = 3 * 2 * b * cn * ob
    if swiglu:
        vmem += cfg.bm * cfg.bf * 4 \
            + matmul_pipeline_bytes(cfg.bm, cfg.bf, cfg.bk, dtype,
                                    out_dtype) \
            + add_pipeline_bytes(cfg.bm, cfg.bf, out_dtype or dtype)
        hbm += 3 * b * k_loc * ob                        # g/u/act staging
    return Footprint(
        vmem_bytes=vmem, hbm_scratch_bytes=hbm,
        dma_sems=2 + 2 + 1 + num_ranks,
        regular_sems=2,
    )


def fused_attn_decode(cfg, b: int, k_dim: int, h: int, hk: int, d: int,
                      page_size: int, dtype) -> Footprint:
    """Attention megakernel cell: one kv-head group's qkv weight columns
    stay VMEM-resident across the batch loop, plus double-buffered KV
    page streams and the token-fold registers."""
    ib = _ib(dtype)
    g = max(h // max(hk, 1), 1)
    qkv_cols = (g + 2) * d       # per kv-head group: g query heads + k + v
    vmem = k_dim * qkv_cols * ib \
        + 2 * 2 * page_size * d * ib \
        + 2 * d * ib + (2 + g) * d * 4
    return Footprint(vmem_bytes=vmem, dma_sems=4)


def persistent_decode(cfg, layers: int, b: int, k_dim: int, hk: int,
                      g: int, d: int, page_size: int, f_loc: int,
                      num_ranks: int, dtype) -> Footprint:
    """The persistent chain: per-layer streamed weights ride
    double-buffered pipelines (two layers' weights live while layer j
    computes and j+1 prefetches), plus the residual/activation staging
    and the shared ring buffers."""
    ib = _ib(dtype)
    h_loc = hk * g
    qkv_cols = (h_loc + 2 * hk) * d
    cn = max(k_dim // max(num_ranks, 1), 1)
    layer_weights = (k_dim * qkv_cols + h_loc * d * k_dim
                     + k_dim * 2 * f_loc + f_loc * k_dim + 3 * k_dim)
    vmem = (
        2 * layer_weights * ib                     # double-buffered stream
        + 3 * b * k_dim * ib                       # xa/xb/h_buf residuals
        + b * qkv_cols * ib
        + 2 * b * h_loc * d * ib                   # attn_vm/attn_buf
        + 3 * b * f_loc * ib                       # g/u/act
        + num_ranks * b * cn * ib                  # red_buf
        + 3 * 2 * b * cn * ib                      # mm/recv/send
        + 2 * 2 * page_size * d * ib               # kbuf/vbuf
        + (qkv_cols + 4 * d) * ib                  # qrow + token regs
        + _acc(cfg.bm, cfg.bn) + cfg.bm * cfg.bf * 4
    )
    return Footprint(
        vmem_bytes=vmem,
        smem_bytes=b * (1 + cfg_mp(cfg)) * 4,
        dma_sems=3 + 2 + 1 + num_ranks,
        regular_sems=2,
    )


def cfg_mp(cfg) -> int:
    """Block-table pages-per-row the persistent kernel prefetches into
    SMEM; not a tile knob — a serving-geometry input with a modest
    default for footprint purposes."""
    return int(getattr(cfg, "max_pages", 8))


def _tile3(cfg) -> tuple[int, int, int]:
    if isinstance(cfg, (tuple, list)):
        return int(cfg[0]), int(cfg[1]), int(cfg[2])
    return int(cfg.bm), int(cfg.bn), int(cfg.bk)


FAMILY_FOOTPRINTS = {
    "matmul": matmul,
    "ag_gemm": ag_gemm,
    "gemm_rs": gemm_rs,
    "gemm_ar": gemm_ar,
    "allreduce": allreduce,
    "reduce_scatter": reduce_scatter,
    "all_to_all": all_to_all,
    "fused_mlp_ar": fused_mlp_ar,
    "fused_attn_decode": fused_attn_decode,
    "persistent_decode": persistent_decode,
}


# ---------------------------------------------------------------------------
# semaphore counts from RECORDED traces (the independent cross-check)


def sems_of_case(case) -> tuple[int, int]:
    """(dma, regular) distinct semaphore instances rank 0 of a registry
    :class:`KernelCase` touches — derived from the recorded trace, so a
    kernel growing a semaphore its scratch_shapes (and this module's
    calculator) do not account for shows up as a count mismatch."""
    from .events import CopyEv, NotifyEv, WaitEv
    from .record import record_kernel

    _label, thunk = case.make(0)
    rec = record_kernel(thunk, n=case.n, rank=0, axes=case.axes)
    dma, regular = set(), set()
    for ev in rec.events:
        if isinstance(ev, CopyEv):
            if ev.send_sem is not None:
                dma.add(ev.send_sem)
            dma.add(ev.recv_sem)
        elif isinstance(ev, WaitEv):
            (dma if ev.unit == "elem" else regular).add(ev.sem)
        elif isinstance(ev, NotifyEv):
            regular.add(ev.sem)
    return len(dma), len(regular)


# ---------------------------------------------------------------------------
# validation


def budget_for(cfg) -> int:
    """The VMEM budget a config REQUESTS: its ``vmem_limit`` knob (tile
    tuples: the optional 4th element) when set, else Mosaic's default
    scoped budget."""
    from ..core import compilation

    limit = None
    if isinstance(cfg, (tuple, list)):
        limit = cfg[3] if len(cfg) > 3 else None
    else:
        limit = getattr(cfg, "vmem_limit", None)
    return int(limit) if limit else compilation.MOSAIC_DEFAULT_VMEM_BYTES


def validate(fp: Footprint, cfg=None, *, budget: int | None = None,
             physical: int | None = None, label: str = "") -> list[str]:
    """Problems (empty = feasible): the working set must fit the
    requested budget, and the requested budget the physical VMEM.
    ``physical`` pins the physical bound explicitly — the autotuner's
    pruning passes the compile-time constant so a per-host
    ``TDT_VMEM_BUDGET`` divergence cannot desynchronize multi-process
    candidate lists; the lint (default None) honors the env override."""
    from ..core import compilation

    if budget is None:
        budget = budget_for(cfg)
    phys = compilation.vmem_budget_bytes() if physical is None \
        else int(physical)
    out = []
    tag = f"{label}: " if label else ""
    if budget > phys:
        out.append(
            f"{tag}requested VMEM budget {budget / 2**20:.1f} MiB exceeds "
            f"the physical {phys / 2**20:.0f} MiB")
    if fp.vmem_bytes > min(budget, phys):
        out.append(
            f"{tag}static VMEM working set {fp.vmem_bytes / 2**20:.1f} MiB "
            f"oversubscribes the {min(budget, phys) / 2**20:.1f} MiB "
            f"budget — Mosaic will refuse or spill; prune before "
            f"measuring")
    return out


def config_feasible(family: str, cfg, dims: dict, *,
                    physical: int | None = None) -> list[str]:
    """Problems for (family, config) at ``dims`` (keyword args of the
    family's calculator); unknown families are feasible by definition —
    pruning must never have false positives.  ``physical`` as in
    :func:`validate`."""
    calc = FAMILY_FOOTPRINTS.get(family)
    if calc is None:
        return []
    fp = calc(cfg, **dims)
    return validate(fp, cfg, physical=physical,
                    label=f"{family}{_tile_label(cfg)}")


def _tile_label(cfg) -> str:
    if isinstance(cfg, (tuple, list)):
        return str(tuple(cfg))
    bm = getattr(cfg, "bm", None)
    return f"(bm={bm}, bn={getattr(cfg, 'bn', None)})" if bm else ""


# representative serving shapes per family for the default-config lint
# (the bench.py / serve defaults: qwen-class hidden sizes on an 8-way
# ring) — the completeness leg flags any DEFAULT that cannot build there
def default_checks() -> list[tuple[str, object, dict]]:
    import jax.numpy as jnp

    from ..comm.all_to_all import AllToAllConfig
    from ..comm.allreduce import AllReduceConfig
    from ..comm.reduce_scatter import ReduceScatterConfig
    from ..ops.ag_gemm import AgGemmConfig
    from ..ops.fused_decode import FusedMlpConfig
    from ..ops.gemm_ar import GemmArConfig
    from ..ops.gemm_rs import GemmRsConfig
    from ..ops.persistent_decode import PersistentDecodeConfig
    from ..tune.autotuner import MATMUL_DEFAULT_TILES

    bf16 = jnp.bfloat16
    return [
        ("matmul", MATMUL_DEFAULT_TILES,
         dict(m=4096, n=4096, k=4096, dtype=bf16)),
        ("ag_gemm", AgGemmConfig().clip(512, 2048, 512),
         dict(m_loc=512, k=2048, n_loc=512, num_ranks=8, dtype=bf16)),
        ("gemm_rs", GemmRsConfig().clip(512, 256, 2048),
         dict(m_loc=512, k_loc=256, n_dim=2048, num_ranks=8, dtype=bf16)),
        ("gemm_ar", GemmArConfig().clip(512, 256, 2048),
         dict(m_loc=512, k_loc=256, n_dim=2048, num_ranks=8, dtype=bf16)),
        ("allreduce", AllReduceConfig().clip(4096, 2048),
         dict(m=4096, r=2048, num_ranks=8, dtype=bf16)),
        ("reduce_scatter", ReduceScatterConfig().clip(512, 2048),
         dict(m=4096, r=2048, num_ranks=8, dtype=bf16)),
        ("all_to_all", AllToAllConfig(),
         dict(t=4096, h=2048, num_ranks=8, dtype=bf16)),
        ("fused_mlp_ar", FusedMlpConfig().clip(8, 768, 256),
         dict(b=8, k_in=2048, k_loc=768, n_dim=2048, num_ranks=8,
              dtype=bf16)),
        ("persistent_decode", PersistentDecodeConfig(),
         dict(layers=24, b=8, k_dim=2048, hk=1, g=2, d=128, page_size=16,
              f_loc=768, num_ranks=8, dtype=bf16)),
    ]


def check_defaults() -> list[str]:
    """The ``tdt_lint --completeness`` footprint leg: every family's
    DEFAULT config must be statically buildable at its representative
    serving shape — a default that oversubscribes means the op fails
    exactly when the autotuner is disabled or cold, the worst time."""
    out = []
    for family, cfg, dims in default_checks():
        out.extend(config_feasible(family, cfg, dims))
    return out
