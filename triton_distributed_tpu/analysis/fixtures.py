"""Seeded-bad protocol fixtures: known-broken kernels each check must flag.

These are the verifier's own regression battery (``scripts/tdt_lint.py
--selftest`` and ``tests/test_static_analysis.py``): one minimal kernel
per defect class, written against the same ``lang.primitives`` vocabulary
as the shipped collectives, so a verifier change that stops catching a
class fails loudly.
"""

from __future__ import annotations

from jax.experimental import pallas as pl

from ..lang import primitives as dl
from .events import FakeRef, FakeSem
from .registry import KernelCase, verify_case


def _team(n: int):
    from ..lang.primitives import Team

    return Team((("tp", n),), "tp")


# ---------------------------------------------------------------------------
# the bad kernels


def bad_missing_notify_kernel(team, ready):
    """Signal balance: every rank credits its right neighbor ONCE but waits
    for TWO arrivals — one notify per semaphore is missing."""
    me, n = team.rank(), team.size
    _, right = team.neighbor_ranks()
    dl.notify(ready, team.device_id(right))
    dl.wait(ready, 2)


def bad_crossed_wait_kernel(team, flag):
    """Deadlock: every rank WAITS for its right neighbor's signal before
    SENDING its own — signal counts balance perfectly, but the wait-for
    graph is one big cycle."""
    me, n = team.rank(), team.size
    _, right = team.neighbor_ranks()
    dl.wait(flag, 1)
    dl.notify(flag, team.device_id(right))


def bad_overlapping_writes_kernel(team, m, x_ref, out_ref, send_sem,
                                  recv_sem):
    """Write overlap: every rank pushes its shard into rows [0, m) of BOTH
    neighbors' output — two unordered remote writes land on the same
    destination chunk (a miscomputed ring offset would look like this)."""
    me, n = team.rank(), team.size
    left, right = team.neighbor_ranks()
    dst = out_ref.at[pl.ds(0, m)]
    dl.remote_copy(x_ref, dst, send_sem, recv_sem, team.device_id(left))
    dl.remote_copy(x_ref, dst, send_sem, recv_sem, team.device_id(right))
    dl.wait_recv(dst, recv_sem)
    dl.wait_recv(dst, recv_sem)
    dl.wait_send(x_ref, send_sem)
    dl.wait_send(x_ref, send_sem)


def bad_hier_dropped_dcn_credit_kernel(n_out, n_in, src, zones, send_sem,
                                       recv_sems):
    """Dropped inter-slice credit (the ISSUE-10 two-level defect class):
    the DCN broadcast pushes one block per peer slice but consumes one
    FEWER arrival credit than the slices deliver — the surplus credit on
    ``dcn_recv_sems`` leaks into the next invocation and satisfies a
    future wait before its block has landed (stale-data consumption on
    hardware).  Signal balance must flag the inter-slice semaphore."""
    o = dl.rank("dcn")
    i = dl.rank("tp")
    for off in range(1, n_out):
        dst_o = (o + off) % n_out
        dl.remote_copy(src, zones.at[o], send_sem, recv_sems.at[o],
                       dst_o * n_in + i)
    # BUG: one source slice's arrival is never consumed
    for off in range(1, n_out - 1):
        src_o = (o + n_out - off) % n_out
        dl.wait_recv(zones.at[src_o], recv_sems.at[src_o])
    for _ in range(n_out - 1):
        dl.wait_send(src, send_sem)


def _compute_reuse(ref) -> None:
    """Record a compute event that reads AND rewrites ``ref`` — the
    static shape of "the consumer reuses the slot it believes just
    landed" (what the ``ops.blocks`` pipeline stubs record for a real
    kernel's in-place stage)."""
    dl.active_recorder().on_compute("reuse", (ref,), ref)


def bad_chained_early_credit_kernel(team, m, r_cols, x_ref, slot_a, slot_b,
                                    send_sem, inst_recv):
    """DPOR-only defect #1 — chained instances on ONE shared arrival
    semaphore, the consumer's per-instance credit armed one instance too
    early (the ISSUE-13 chained-AR hazard class): every rank feeds ring
    instance j's chunk to its -1 neighbor and instance j+1's chunk to
    its -2 neighbor, BOTH crediting the consumer's single unindexed
    ``inst_recv`` semaphore.  The consumer consumes one credit per
    instance and immediately reuses the slot it BELIEVES that credit
    acknowledged — slot identity keyed to arrival order, which nothing
    orders.  The wait order below follows the canonical round-robin
    arrival order (the lower-ranked producer's send lands first), so the
    canonical maximal execution witnesses only the safe matching and
    ALL FOUR canonical checks pass; swapping the two producers' sends
    (one context switch) makes the first wait consume the OTHER
    instance's credit and the reuse overwrites a slot whose landing is
    still unsettled — un-ACKed slot reuse only DPOR can witness."""
    me, n = team.rank(), team.size
    # producer role: instance-1 chunk to (me-1), instance-2 to (me-2)
    dl.remote_copy(x_ref, slot_a, send_sem, inst_recv,
                   team.device_id((me - 1) % n))
    dl.remote_copy(x_ref, slot_b, send_sem, inst_recv,
                   team.device_id((me - 2) % n))
    # consumer role: my slot_a is fed by (me+1), slot_b by (me+2); the
    # canonical sweep delivers the LOWER-ranked producer's credit first
    a_src, b_src = (me + 1) % n, (me + 2) % n
    order = (slot_a, slot_b) if a_src < b_src else (slot_b, slot_a)
    for slot in order:
        dl.wait_recv(slot, inst_recv)   # BUG: shared sem — which landing?
        _compute_reuse(slot)
    dl.wait_send(x_ref, send_sem)
    dl.wait_send(x_ref, send_sem)


def bad_reorderable_slot_reuse_kernel(team, m, r_cols, x_ref, staging,
                                      scratch, slot, send_sem, io_sem):
    """DPOR-only defect #2 — ACK-balanced but reorderable slot reuse:
    every rank prefetches a staging block into ``scratch`` through a
    local DMA and receives its right neighbor's shard into ``slot``, the
    local completion and the remote arrival sharing one ``io_sem``.
    Credits balance EXACTLY, yet the consumer reuses whichever buffer it
    believes each credit acknowledged.  The wait order follows the
    canonical arrival order (a producer ranked below me lands before my
    own prefetch issue; one ranked above lands after), so the canonical
    execution is clean at every rank; executing the other producer's
    DMA first flips the credit matching and the reuse races the
    still-unsettled landing."""
    me, n = team.rank(), team.size
    src = (me + 1) % n           # my slot is fed by my +1 neighbor
    dl.local_copy(staging, scratch, io_sem)
    dl.remote_copy(x_ref, slot, send_sem, io_sem,
                   team.device_id((me - 1) % n))
    order = (scratch, slot) if src > me else (slot, scratch)
    for buf in order:
        dl.wait_recv(buf, io_sem)    # BUG: shared sem — local or remote?
        _compute_reuse(buf)
    dl.wait_send(x_ref, send_sem)


def diverged_method_kernel(team, sem, *, one_shot: bool):
    """Collective divergence: the op sequence depends on which method this
    HOST resolved (the ``tools/calibrate.py`` per-host-threshold hazard) —
    here rank 0 runs the short protocol and everyone else the long one."""
    dl.barrier_all(team)
    if not one_shot:
        dl.notify(sem)          # local self-credit
        dl.wait(sem, 1)


# ---------------------------------------------------------------------------
# cases


def fixture_cases(n: int = 4) -> list[KernelCase]:
    team = _team(n)
    m, r = 4, 8

    def make_missing_notify(rank):
        return "default", lambda: bad_missing_notify_kernel(
            team, FakeSem("ready", kind="regular")
        )

    def make_crossed_wait(rank):
        return "default", lambda: bad_crossed_wait_kernel(
            team, FakeSem("flag", kind="regular")
        )

    def make_overlap(rank):
        return "default", lambda: bad_overlapping_writes_kernel(
            team, m, FakeRef("x", (m, r)), FakeRef("out", (n * m, r)),
            FakeSem("send_sem"), FakeSem("recv_sem"),
        )

    def make_diverged(rank):
        method = "one_shot" if rank == 0 else "two_shot"
        return method, lambda: diverged_method_kernel(
            team, FakeSem("sem", kind="regular"), one_shot=(rank == 0)
        )

    # the two-level fixture runs on a (dcn x tp) harness mesh: n ranks as
    # 2 slices of n//2 chips (n must be even — the selftest's n=4 gives
    # the 2x2 layout)
    n_out, n_in = 2, max(n // 2, 1)

    def make_hier_dropped(rank):
        return "dcn_bcast", lambda: bad_hier_dropped_dcn_credit_kernel(
            n_out, n_in, FakeRef("block", (m, r)),
            FakeRef("dcn_zones", (n_out, m, r)),
            FakeSem("dcn_send_sem"), FakeSem("dcn_recv_sems"),
        )

    return [
        KernelCase("fixture/missing_notify", "fixture", n,
                   make_missing_notify),
        KernelCase("fixture/crossed_wait", "fixture", n, make_crossed_wait),
        KernelCase("fixture/overlapping_writes", "fixture", n, make_overlap),
        KernelCase("fixture/diverged_method", "fixture", n, make_diverged),
        KernelCase("fixture/hier_dropped_dcn_credit", "fixture", n,
                   make_hier_dropped,
                   axes=(("dcn", n_out), ("tp", n_in))),
    ]


def dpor_fixture_cases(n: int = 4) -> list[KernelCase]:
    """Seeded-bad kernels that PASS every canonical check but fail under
    reordering — the soundness gap ``analysis.explore`` exists to close
    (see the kernel docstrings).  Kept OUT of :func:`fixture_cases`: the
    canonical selftest asserts those are flagged, while
    :func:`run_dpor_selftest` asserts these are canonical-clean AND
    DPOR-caught, pinning the gap in both directions."""
    if n < 3:
        raise ValueError("the chained fixture needs n >= 3 (two distinct "
                         "producer ranks per consumer pool)")
    team = _team(n)
    m, r = 4, 8

    def make_chained(rank):
        return "default", lambda: bad_chained_early_credit_kernel(
            team, m, r, FakeRef("x", (m, r)),
            FakeRef("slot_a", (m, r)), FakeRef("slot_b", (m, r)),
            FakeSem("send_sem"), FakeSem("inst_recv"),
        )

    def make_reorder(rank):
        return "default", lambda: bad_reorderable_slot_reuse_kernel(
            team, m, r, FakeRef("x", (m, r)), FakeRef("staging", (m, r)),
            FakeRef("scratch", (m, r)), FakeRef("slot", (m, r)),
            FakeSem("send_sem"), FakeSem("io_sem"),
        )

    return [
        KernelCase("fixture/chained_early_credit", "fixture", n,
                   make_chained),
        KernelCase("fixture/reorderable_slot_reuse", "fixture", n,
                   make_reorder),
    ]


# DPOR-fixture contract: (check the explorer must report, token the
# violation message must name)
DPOR_EXPECTED = {
    "fixture/chained_early_credit": ("write_overlap", "slot_"),
    "fixture/reorderable_slot_reuse": ("write_overlap", "scratch"),
}


def run_dpor_selftest(n: int = 4) -> list[str]:
    """Both directions of the ISSUE-15 soundness pin, per DPOR fixture:
    (1) the canonical verifier reports NOTHING (the defect provably
    passes the single maximal execution), and (2) the explorer flags the
    expected check with the reused slot named.  Returns failure lines;
    empty means the gap stays pinned."""
    from .explore import explore_case
    from .registry import record_case

    problems = []
    for case in dpor_fixture_cases(n):
        want_check, token = DPOR_EXPECTED[case.name]
        recorded = record_case(case)       # one pass feeds both checks
        canonical = verify_case(case, recorded=recorded)
        if canonical:
            problems.append(
                f"{case.name}: must PASS the canonical schedule, got "
                f"{[str(v) for v in canonical]}")
        res = explore_case(case, recorded=recorded)
        hits = [v for v in res.violations if v.check == want_check]
        if not hits:
            problems.append(
                f"{case.name}: DPOR must report a {want_check} violation "
                f"(explored {res.schedules} classes), got "
                f"{[v.check for v in res.violations]}")
        elif not any(token in v.message for v in hits):
            problems.append(
                f"{case.name}: {want_check} message does not name the "
                f"reused slot ({token!r}): {hits[0].message}")
    return problems


# which check each fixture MUST trip (selftest contract); extra findings
# (a missing notify also deadlocks) are allowed
EXPECTED = {
    "fixture/missing_notify": "signal_balance",
    "fixture/crossed_wait": "deadlock",
    "fixture/overlapping_writes": "write_overlap",
    "fixture/diverged_method": "collective_divergence",
    "fixture/hier_dropped_dcn_credit": "signal_balance",
}


def page_fixture_cases() -> list[tuple[str, dict]]:
    """Seeded-bad page-lifetime scenarios for ``analysis.pages`` — each
    is a clean two-tier scenario with ONE ordering edge or release
    dropped, reproducing a real bug class the ownership state machine
    (plus the page-footprint DPOR) must flag.  Kept beside the kernel
    fixtures so one module owns every seeded-bad battery."""
    from .pages import PageOp

    w = lambda **kw: tuple(sorted(kw.items()))

    # the owner frees twice: the bookkeeping bug PagePool's typed
    # PageLifecycleError rejects dynamically, flagged here statically
    double_free = {
        "serve": [
            PageOp("alloc", "F1"), PageOp("write", "F1"),
            PageOp("seal", "F1"), PageOp("read", "F1"),
            PageOp("free", "F1"), PageOp("free", "F1"),
        ],
    }

    # pre-refcount TDT_SCRUB_PAGES: the scrubber poison-fills as soon
    # as the OWNER departs, with the radix cache's reference still live
    scrub_under_live_reader = {
        "decode": [
            PageOp("alloc", "S1"), PageOp("write", "S1"),
            PageOp("seal", "S1", token="sealed"),
            PageOp("free", "S1", token="owner_gone",
                   meta=w(scrub_pending=True)),
        ],
        "radix": [
            PageOp("share", "S1", guard=("sealed",)),
            PageOp("read", "S1"),
            PageOp("release", "S1"),
        ],
        "scrubber": [
            # BUG: guarded only on the owner's release, not the LAST
            PageOp("scrub", "S1", guard=("owner_gone",)),
        ],
    }

    # an abort path returns the first page but forgets the growth page
    leak_on_abort = {
        "serve": [
            PageOp("alloc", "L1"), PageOp("alloc", "L2"),
            PageOp("write", "L1"),
            PageOp("free", "L1"),     # BUG: L2 never comes home
        ],
    }

    # the decode tier seals (and reads) implanted wire bytes without
    # the stamp verification the handoff plane exists to run
    adopt_before_stamp_verify = {
        "decode": [
            PageOp("alloc", "A1"), PageOp("implant", "A1"),
            PageOp("seal", "A1"),     # BUG: no verify before the seal
            PageOp("read", "A1"), PageOp("free", "A1"),
        ],
    }

    # more releases than references: a holder releases a page it
    # already gave up, recycling it under the remaining owner
    refcount_underflow = {
        "decode": [
            PageOp("alloc", "R1"), PageOp("write", "R1"),
            PageOp("seal", "R1", token="sealed"),
            PageOp("release", "R1"),
        ],
        "radix": [
            PageOp("share", "R1", guard=("sealed",)),
            PageOp("release", "R1", token="done"),
            PageOp("release", "R1", guard=("done",)),   # BUG: twice
        ],
    }

    return [
        ("pagefix/double_free", double_free),
        ("pagefix/scrub_under_live_reader", scrub_under_live_reader),
        ("pagefix/leak_on_abort", leak_on_abort),
        ("pagefix/adopt_before_stamp_verify", adopt_before_stamp_verify),
        ("pagefix/refcount_underflow", refcount_underflow),
    ]


# page-fixture contract: (check the state machine must report, page id
# the violation message must name — the transition is asserted by the
# selftest via the "->" the message format always carries)
PAGE_EXPECTED = {
    "pagefix/double_free": ("double_free", "F1"),
    "pagefix/scrub_under_live_reader": ("scrub_under_live_reader", "S1"),
    "pagefix/leak_on_abort": ("page_leak", "L2"),
    "pagefix/adopt_before_stamp_verify": ("adopt_before_stamp_verify",
                                          "A1"),
    "pagefix/refcount_underflow": ("refcount_underflow", "R1"),
}


def run_page_selftest() -> list[str]:
    """Both directions of the page-lifetime pin, mirroring
    :func:`run_dpor_selftest`: (1) every CLEAN two-tier scenario
    (``pages.two_tier_scenarios``) verifies quiet across ALL its
    schedule classes, and (2) every seeded-bad fixture is flagged with
    the expected check, the page id, and the violating transition
    named.  Returns failure lines; empty means the pin holds."""
    from .pages import explore_pages, two_tier_scenarios

    problems = []
    for name, scenario in two_tier_scenarios():
        res = explore_pages(name, scenario)
        if res.violations:
            problems.append(
                f"{name}: clean scenario must verify quiet across all "
                f"{res.schedules} classes, got "
                f"{[str(v) for v in res.violations]}")
        if res.pruned:
            problems.append(
                f"{name}: exploration was pruned — the clean sweep "
                f"must be exhaustive")
    for name, scenario in page_fixture_cases():
        want_check, page = PAGE_EXPECTED[name]
        res = explore_pages(name, scenario)
        hits = [v for v in res.violations if v.check == want_check]
        if not hits:
            problems.append(
                f"{name}: expected a {want_check} violation (explored "
                f"{res.schedules} classes), got "
                f"{[v.check for v in res.violations]}")
            continue
        if not any(f"page {page}" in v.message for v in hits):
            problems.append(
                f"{name}: {want_check} message does not name page "
                f"{page!r}: {hits[0].message}")
        elif not any("->" in v.message for v in hits):
            problems.append(
                f"{name}: {want_check} message does not name the "
                f"violating transition: {hits[0].message}")
    return problems


def run_selftest(n: int = 4) -> list[str]:
    """Verify every fixture trips its expected check (and that the flagged
    message names the offending semaphore/chunk).  Returns failure lines;
    empty means the selftest passed."""
    problems = []
    named = {
        "fixture/missing_notify": "ready",
        "fixture/crossed_wait": "flag",
        "fixture/overlapping_writes": "out[0:4",
        "fixture/hier_dropped_dcn_credit": "dcn_recv_sems",
    }
    for case in fixture_cases(n):
        violations = verify_case(case)
        want = EXPECTED[case.name]
        hits = [v for v in violations if v.check == want]
        if not hits:
            problems.append(
                f"{case.name}: expected a {want} violation, got "
                f"{[v.check for v in violations]}"
            )
            continue
        token = named.get(case.name)
        if token and not any(token in v.message for v in hits):
            problems.append(
                f"{case.name}: {want} message does not name the violating "
                f"semaphore/chunk ({token!r}): {hits[0].message}"
            )
    return problems
